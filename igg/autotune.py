"""igg.autotune — ledger-driven dispatch autotuning with a persistent
tuning cache.

PR 8 built the perf ledger as an autotuner's prior on purpose —
`igg.perf.query()/best()` answer "fastest known (tier, config) for this
(family, shape, dtype, dims, device_kind)" — yet the dispatch parameters
stayed hand-derived constants (K=8 at 128^3, fixed slab heights, a fixed
32→110 MB VMEM budget), and the stencil-tuning literature puts auto-tuned
parameters at 1.5-2x over hand-picked ones (PAPERS: 2406.08923,
2309.04671).  This module closes the loop:

- **The search** (:func:`search`): per compiled-cache signature
  `(family, local_shape, dtype, dims, backend, device_kind)` — the same
  axes the perf ledger keys on — candidate configs over
  `(tier, K, bx, vmem budget)` are measured with warm timed dispatches
  on scratch copies of family-default fields (`igg.time_steps` slope
  timing, donation-safe).  The ledger's :func:`igg.perf.best` is the
  PRIOR: its tier's candidates are measured first, and a candidate whose
  first warm sample exceeds ``IGG_TUNE_CUTOFF`` x the best-so-far is cut
  off without paying the full slope measurement.  Every sample lands in
  the perf ledger (source ``"autotune"``), so the search itself enriches
  the prior.

- **The tuning cache**: winners persist in a versioned JSON file
  (``IGG_TUNE_CACHE``; format ``igg-tune-cache-v1``), keyed like the
  compiled-program cache, with atomic merge-on-write saves (tmp +
  rename, newest ``updated_wall`` wins per key — the perf-ledger
  convention) and rank-tagging on multi-controller runs.  A second
  process pointing at the same cache serves the winner with ZERO search
  dispatches (:func:`search_dispatches` counts them for the contract
  test).

- **The application** (:func:`applied`): the model factories
  (`make_multi_step` / `make_iteration` / `make_step`) accept
  ``tune="auto"/True/False`` (default: the ``IGG_TUNE`` knob).  "auto"
  consults the cache and applies a hit's (tier pin, K, bx, vmem budget)
  wherever the caller left the defaults — a pure host-side dict lookup
  at FACTORY time, zero hot-loop cost (the PR-7 zero-host-syncs sentinel
  runs with tuning enabled); True additionally runs the search on a
  cache miss; False ignores the cache.  Explicit caller arguments always
  win over a cached winner.

- **Staleness** (:func:`invalidate`): :func:`igg.perf.invalidate` — the
  `igg.heal` re-calibration loop's first step on ``cost_model_drift`` —
  also evicts the family's tuning-cache entries (memory AND disk), so a
  drifted machine re-tunes instead of serving a stale winner.  The
  eviction emits a ``tune_invalidated`` bus record.

The VMEM-budget axis rides the shared budget authority
(`igg.ops._vmem.set_cap_override`): the search sweeps the cap for
kernels that consult `vmem_limit`/`chunk_budget`, and an applied winner
re-installs its cap process-wide (budgets are a per-chip property, not a
per-family one).
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import _env
from . import telemetry as _telemetry
from .shared import GridError

__all__ = ["TUNE_FORMAT", "applied", "cache_path", "candidates_for", "get",
           "invalidate", "load", "record_winner", "register_family",
           "registered_families", "reset", "resolve", "save", "search",
           "search_dispatches"]

TUNE_FORMAT = "igg-tune-cache-v1"

_env.register("IGG_TUNE",
              "autotune default: 0 off, 1 search-on-miss, auto (default) "
              "cached winners only")
_env.register("IGG_TUNE_CACHE",
              "path of the on-disk tuning-cache JSON (unset: in-memory "
              "only; rank-tagged on multi-controller runs)")
_env.register("IGG_TUNE_NT",
              "slope-timing batch size per tuning candidate (default 2)")
_env.register("IGG_TUNE_CUTOFF",
              "early-cutoff factor: a candidate whose first warm sample "
              "exceeds this multiple of the best-so-far skips the full "
              "measurement (default 2.0)")

_lock = threading.RLock()
_CACHE: Dict[Tuple, Dict] = {}
_LOADED: set = set()           # cache files already lazily loaded
_SEARCH_DISPATCHES = 0         # timed search dispatches this process

# Round 17: the search's hard-coded family tables (candidates_for /
# _build_candidate) became a registration hook so spec-defined families
# (igg.stencil) are searchable without editing this module.  An entry
# supplies `candidates(grid, *, n_inner, interpret) -> [cand dicts]` and
# `build(cand, *, n_inner, params, interpret) -> (state_fn, args)`; the
# four built-ins stay in the static dispatch as the fallback.
_FAMILY_REGISTRY: Dict[str, Dict] = {}


def register_family(name: str, *, candidates, build) -> None:
    """Register a family's autotune providers (idempotent;
    `igg.stencil.compile` calls it for every compiled spec)."""
    with _lock:
        _FAMILY_REGISTRY[str(name)] = {"candidates": candidates,
                                       "build": build}


def registered_families() -> Dict[str, Dict]:
    with _lock:
        return dict(_FAMILY_REGISTRY)


# ---------------------------------------------------------------------------
# Configuration / keys
# ---------------------------------------------------------------------------

def resolve(tune):
    """The factories' ``tune=`` contract: None defers to ``IGG_TUNE``
    ("0" -> False, "1" -> True, unset/"auto" -> "auto"); otherwise must
    be False, True, or "auto"."""
    if tune is None:
        raw = (_env.text("IGG_TUNE") or "auto").strip().lower()
        if raw in ("0", "false", "off", "no"):
            return False
        if raw in ("1", "true", "on", "yes"):
            return True
        if raw == "auto":
            return "auto"
        raise GridError(f"IGG_TUNE={raw!r}: expected 0, 1, or auto.")
    if tune in (False, True, "auto"):
        return tune
    raise GridError(f"tune={tune!r}: expected None, False, True, or "
                    f"'auto'.")


def cache_path() -> Optional[pathlib.Path]:
    """The configured on-disk tuning cache (``IGG_TUNE_CACHE``),
    rank-tagged on multi-controller runs (the perf-ledger convention).
    None when unset — the cache then lives in memory only."""
    raw = _env.text("IGG_TUNE_CACHE")
    if not raw:
        return None
    p = pathlib.Path(raw)
    rank = _telemetry._process()
    if rank:
        p = p.with_name(f"{p.stem}_r{rank}{p.suffix or '.json'}")
    return p


def search_dispatches() -> int:
    """Timed search dispatches performed by this process — the
    cache-round-trip contract's counter (a second process serving a
    cached winner must keep it at zero)."""
    return _SEARCH_DISPATCHES


def _key(family, local_shape, dtype, dims, backend, device_kind) -> Tuple:
    return (str(family),
            tuple(int(s) for s in (local_shape or ())),
            str(dtype),
            tuple(int(d) for d in dims) if dims else None,
            str(backend) if backend else None,
            str(device_kind) if device_kind else None)


def _key_str(k: Tuple) -> str:
    family, shape, dtype, dims, backend, device_kind = k
    return "|".join([
        family, "x".join(map(str, shape)) or "-", dtype,
        "x".join(map(str, dims)) if dims else "-",
        backend or "-", device_kind or "-"])


def _entry_key(e: Dict) -> Tuple:
    return _key(e["family"], e.get("local_shape") or (),
                e.get("dtype", "float32"), e.get("dims"),
                e.get("backend"), e.get("device_kind"))


def _context(family: str, local_shape=None) -> Dict:
    """Signature axes from the live grid/device — the compiled-cache key
    minus the tier."""
    from . import perf, shared

    ctx = perf.device_context()
    ctx["dims"] = (tuple(shared.global_grid().dims)
                   if shared.grid_is_initialized() else None)
    if local_shape is None and shared.grid_is_initialized():
        grid = shared.global_grid()
        local_shape = (tuple(grid.nxyz[:2]) if family == "wave2d"
                       else tuple(grid.nxyz))
    ctx["local_shape"] = tuple(local_shape or ())
    return ctx


# ---------------------------------------------------------------------------
# The cache
# ---------------------------------------------------------------------------

def _lazy_load() -> None:
    """Merge the configured cache file into memory, once per path (the
    second process's zero-search read path)."""
    target = cache_path()
    if target is None:
        return
    pkey = str(target)
    with _lock:
        if pkey in _LOADED:
            return
        _LOADED.add(pkey)
    if target.exists():
        try:
            load(target)
        except GridError:
            pass   # a corrupt cache is re-tuned, never fatal


def get(family: str, local_shape=None, dtype="float32") -> Optional[Dict]:
    """The cached winner for this signature on the live grid/device, or
    None.  Host-side dict lookup (plus a one-time lazy file load) — no
    device work."""
    _lazy_load()
    ctx = _context(family, local_shape)
    k = _key(family, ctx["local_shape"], dtype, ctx["dims"],
             ctx["backend"], ctx["device_kind"])
    with _lock:
        e = _CACHE.get(k)
        return dict(e) if e else None


def record_winner(family: str, winner: Dict, *, local_shape=None,
                  dtype="float32", source: str = "search",
                  persist: bool = True) -> Dict:
    """Install a winner for this signature (and persist it when a cache
    path is configured)."""
    ctx = _context(family, local_shape)
    k = _key(family, ctx["local_shape"], dtype, ctx["dims"],
             ctx["backend"], ctx["device_kind"])
    e = {"family": family, "local_shape": list(k[1]), "dtype": k[2],
         "dims": list(k[3]) if k[3] else None, "backend": k[4],
         "device_kind": k[5],
         "tier": winner.get("tier"), "K": winner.get("K"),
         "bx": winner.get("bx"), "band": winner.get("band"),
         "vmem_mb": winner.get("vmem_mb"),
         "overlap": bool(winner.get("overlap", False)),
         "ms": winner.get("ms"), "source": source,
         "updated_wall": time.time()}
    with _lock:
        _CACHE[k] = e
    _telemetry.emit("autotune_winner", **{kk: vv for kk, vv in e.items()
                                          if kk != "updated_wall"})
    if persist:
        save()
    return dict(e)


def reset() -> None:
    """Clear the in-memory cache and the lazy-load/search-count state
    (the on-disk file is untouched; tests call this for isolation)."""
    global _SEARCH_DISPATCHES
    with _lock:
        _CACHE.clear()
        _LOADED.clear()
        _FAMILY_REGISTRY.clear()
        _SEARCH_DISPATCHES = 0


def invalidate(family: str, tier: Optional[str] = None) -> int:
    """Evict `family`'s tuning-cache entries (optionally only winners
    serving `tier`) from memory AND the on-disk cache — the staleness
    half of the heal loop: :func:`igg.perf.invalidate` calls this, so a
    ``cost_model_drift``-driven invalidation re-tunes instead of serving
    a stale winner.  Returns the number of entries evicted."""
    with _lock:
        keys = [k for k, e in _CACHE.items()
                if k[0] == family and (tier is None or e.get("tier") == tier)]
        for k in keys:
            del _CACHE[k]
    n = len(keys)
    # Durable eviction: merge-on-write would resurrect the entry from
    # disk at the next save, so the file is rewritten without it.
    target = cache_path()
    if target is not None and target.exists():
        try:
            doc = json.loads(target.read_text())
            entries = doc.get("entries", {}) if isinstance(doc, dict) else {}
            kept = {ks: e for ks, e in entries.items()
                    if not (isinstance(e, dict) and e.get("family") == family
                            and (tier is None or e.get("tier") == tier))}
            n = max(n, len(entries) - len(kept))
            doc = {"format": TUNE_FORMAT, "saved_wall": time.time(),
                   "process": _telemetry._process(), "entries": kept}
            tmp = target.with_name(target.name + ".tmp")
            tmp.write_text(json.dumps(doc, indent=1, sort_keys=True))
            os.replace(tmp, target)
        except (OSError, json.JSONDecodeError):
            pass
    if n:
        _telemetry.emit("tune_invalidated", family=family, tier=tier,
                        entries=n)
    return n


def _read_cache_file(path) -> List[Dict]:
    path = pathlib.Path(path)
    try:
        doc = json.loads(path.read_text())
    except OSError as e:
        raise GridError(f"igg.autotune: cannot read cache {path}: {e}")
    except json.JSONDecodeError as e:
        raise GridError(f"igg.autotune: {path} is not valid JSON ({e}).")
    if not isinstance(doc, dict) or doc.get("format") != TUNE_FORMAT:
        raise GridError(
            f"igg.autotune: {path} is not an {TUNE_FORMAT} cache "
            f"(format="
            f"{doc.get('format') if isinstance(doc, dict) else '?'!r}).")
    return [e for e in doc.get("entries", {}).values()
            if isinstance(e, dict)]


def save(path=None) -> Optional[pathlib.Path]:
    """Persist the in-memory cache: read whatever is on disk, merge
    (newest ``updated_wall`` wins per key), atomically replace (tmp +
    rename) — concurrent runs lose nothing.  `path` defaults to the
    ``IGG_TUNE_CACHE`` rank path; with neither, a no-op returning
    None."""
    target = pathlib.Path(path) if path is not None else cache_path()
    if target is None:
        return None
    on_disk: List[Dict] = []
    if target.exists():
        try:
            on_disk = _read_cache_file(target)
        except GridError:
            on_disk = []   # a corrupt cache is replaced, not fatal
    merged: Dict[Tuple, Dict] = {}
    for e in on_disk:
        merged[_entry_key(e)] = e
    with _lock:
        for k, e in _CACHE.items():
            have = merged.get(k)
            if (have is None or e.get("updated_wall", 0)
                    >= have.get("updated_wall", 0)):
                merged[k] = dict(e)
    doc = {"format": TUNE_FORMAT, "saved_wall": time.time(),
           "process": _telemetry._process(),
           "entries": {_key_str(k): e for k, e in sorted(merged.items())}}
    try:
        target.parent.mkdir(parents=True, exist_ok=True)
        tmp = target.with_name(target.name + ".tmp")
        tmp.write_text(json.dumps(doc, indent=1, sort_keys=True))
        os.replace(tmp, target)
    except OSError:
        return None   # a full/readonly disk must never kill the run
    return target


def load(path=None, *, replace: bool = False) -> int:
    """Load a cache file into memory (merging, newest wins;
    ``replace=True`` clears first).  Returns the number of entries now
    in memory."""
    target = pathlib.Path(path) if path is not None else cache_path()
    if target is None:
        raise GridError("igg.autotune.load: no path given and "
                        "IGG_TUNE_CACHE is unset.")
    entries = _read_cache_file(target)
    with _lock:
        if replace:
            _CACHE.clear()
        for e in entries:
            k = _entry_key(e)
            have = _CACHE.get(k)
            if (have is None or e.get("updated_wall", 0)
                    >= have.get("updated_wall", 0)):
                _CACHE[k] = e
        return len(_CACHE)


# ---------------------------------------------------------------------------
# The application (factory-time, zero hot-loop cost)
# ---------------------------------------------------------------------------

def applied(family: str, tune, *, n_inner: int = 8, params=None,
            interpret: bool = False) -> Optional[Dict]:
    """The factories' entry point: resolve the ``tune=`` knob, look up
    the cached winner for this signature, search on miss when
    ``tune=True``, install the winner's VMEM cap, and return the winner
    (None when tuning is off, the grid is uninitialized, or there is no
    winner).  Pure host work at factory-build time.

    The VMEM-cap override is process-global (a chip property), so this
    call NORMALIZES it for the factory being built: a winner carrying a
    cap installs it, and every other outcome — a miss, a vmem-less
    winner, or an explicitly-untuned factory (``tune=False``) — CLEARS
    it back to the hand-derived default, so one family's tuned cap can
    never silently re-budget another family's admission."""
    from . import shared
    from .ops import _vmem

    mode = resolve(tune)
    if mode is False:
        _vmem.set_cap_override(None)
        return None
    if not shared.grid_is_initialized():
        return None
    w = get(family)
    if w is None and mode is True:
        w = search(family, n_inner=n_inner, params=params,
                   interpret=interpret)
    _vmem.set_cap_override(int(w["vmem_mb"]) * 1024 * 1024
                           if w and w.get("vmem_mb") else None)
    return w


# ---------------------------------------------------------------------------
# The search
# ---------------------------------------------------------------------------

# (hide_communication read radius, decomposition rank) per built-in —
# the admission geometry of each family's overlapped XLA candidate.
_OVERLAP_GEOMETRY = {"diffusion3d": (1, 3), "stokes3d": (2, 3),
                     "hm3d": (1, 3), "wave2d": (2, 2)}


def candidates_for(family: str, *, n_inner: int = 8,
                   interpret: bool = False) -> List[Dict]:
    """The (tier, K, bx, vmem) candidate set admissible for `family` on
    the live grid: the truth rung, the per-step fused tier (with a bx
    sweep for diffusion and a VMEM-cap sweep on compiled TPU mode), and
    every admissible chunk depth of the family's K-step tier.  Candidate
    dicts carry the factory kwargs the search applies."""
    from . import perf, shared

    grid = shared.global_grid()
    reg = _FAMILY_REGISTRY.get(family)
    if reg is not None:
        return reg["candidates"](grid, n_inner=n_inner,
                                 interpret=interpret)
    shape = (tuple(grid.nxyz[:2]) if family == "wave2d"
             else tuple(grid.nxyz))
    dtype = np.float32
    tpu = perf.device_context()["backend"] == "tpu"
    vmems = [None] if (interpret or not tpu) else [None, 64]
    out: List[Dict] = [{"tier": f"{family}.xla", "K": None, "bx": None,
                        "vmem_mb": None}]

    def chunk_ks(supported, ks=(4, 8)):
        return [K for K in ks
                if supported(grid, shape, K, n_inner - 1, dtype,
                             interpret=interpret)]

    def banded_cands(supported, tier, ks=(4, 8), bands=(8, 16)):
        # The streaming banded tier joins the search space on its own
        # (K, band) axes — admission-gated host-side so a refused config
        # never costs a search dispatch.
        return [{"tier": tier, "K": K, "bx": None, "band": B,
                 "vmem_mb": None}
                for K in ks for B in bands
                if supported(grid, shape, K, n_inner - 1, dtype, B=B,
                             interpret=interpret)]

    if family == "diffusion3d":
        from .ops import diffusion_banded_supported, pallas_supported

        if pallas_supported(grid, type("S", (), {
                "ndim": 3, "shape": shape, "dtype": dtype})()):
            for bx in (4, 8, 16):
                if shape[0] % bx == 0:
                    out.append({"tier": "diffusion3d.mosaic", "K": bx,
                                "bx": bx, "vmem_mb": None})
        out.extend(banded_cands(diffusion_banded_supported,
                                "diffusion3d.banded"))
    elif family == "stokes3d":
        from .ops import (stokes_banded_supported,
                          stokes_trapezoid_supported)

        for v in vmems:
            out.append({"tier": "stokes3d.mosaic", "K": None, "bx": None,
                        "vmem_mb": v})
        for K in chunk_ks(stokes_trapezoid_supported):
            out.append({"tier": "stokes3d.trapezoid", "K": K, "bx": None,
                        "vmem_mb": None})
        out.extend(banded_cands(stokes_banded_supported,
                                "stokes3d.banded"))
    elif family == "hm3d":
        from .ops.hm3d_trapezoid import (hm3d_banded_supported,
                                         hm3d_trapezoid_supported)

        for v in vmems:
            out.append({"tier": "hm3d.mosaic", "K": None, "bx": None,
                        "vmem_mb": v})
        for K in chunk_ks(hm3d_trapezoid_supported):
            out.append({"tier": "hm3d.trapezoid", "K": K, "bx": None,
                        "vmem_mb": None})
        out.extend(banded_cands(hm3d_banded_supported, "hm3d.banded"))
    elif family == "wave2d":
        from .ops.wave2d_pallas import (wave2d_banded_supported,
                                        wave2d_chunk_supported)

        out.append({"tier": "wave2d.mosaic", "K": None, "bx": None,
                    "vmem_mb": None})
        for K in chunk_ks(wave2d_chunk_supported):
            out.append({"tier": "wave2d.chunk", "K": K, "bx": None,
                        "vmem_mb": None})
        out.extend(banded_cands(wave2d_banded_supported, "wave2d.banded"))
    else:
        raise GridError(
            f"igg.autotune: unknown family {family!r} (built-ins: "
            f"diffusion3d, stokes3d, hm3d, wave2d; registered: "
            f"{sorted(_FAMILY_REGISTRY) or 'none'} — "
            f"igg.autotune.register_family hooks new ones in).")
    # The overlapped XLA composition (igg.hide_communication) is a
    # first-class candidate on the same axes: admission-gated host-side
    # (radius vs ol-1, single-device mesh — igg.overlap.overlap_admission)
    # so a refused variant never costs a search dispatch.  Chunk/mosaic
    # tiers carry their own overlap semantics and get no variant.
    from .overlap import overlap_admission

    radius, nd = _OVERLAP_GEOMETRY[family]
    if overlap_admission(radius, grid=grid, ndim=nd):
        out.insert(1, {"tier": f"{family}.xla", "K": None, "bx": None,
                       "vmem_mb": None, "overlap": True})
    return out


def _build_candidate(family: str, cand: Dict, n_inner: int, params,
                     interpret: bool):
    """(state_fn, args) for one candidate config: the family factory
    pinned to the candidate's tier/K/bx (``tune=False`` so the search
    never recurses into itself), on family-default f32 fields."""
    reg = _FAMILY_REGISTRY.get(family)
    if reg is not None:
        return reg["build"](cand, n_inner=n_inner, params=params,
                            interpret=interpret)
    tier = cand["tier"]
    fast = not tier.endswith(".xla")
    ov = bool(cand.get("overlap"))
    bdd = tier.endswith(".banded")
    if family == "diffusion3d":
        from .models import diffusion3d as m

        p = params or m.Params()
        T, Cp = m.init_fields(p, dtype=np.float32)
        step = m.make_multi_step(
            n_inner, p, donate=False, overlap=ov,
            use_pallas=(True if fast else False),
            pallas_interpret=interpret, bx=cand.get("bx"),
            banded=(True if bdd else False),
            K=cand.get("K") if bdd else None, band=cand.get("band"),
            tune=False)
        return (lambda T, Cp: (step(T, Cp), Cp)), (T, Cp)
    if family == "stokes3d":
        from .models import stokes3d as m

        p = params or m.Params()
        fields = m.init_fields(p, dtype=np.float32)
        it = m.make_iteration(
            p, donate=False, n_inner=n_inner, overlap=ov,
            use_pallas=(True if fast else False), pallas_interpret=interpret,
            trapezoid=(tier.endswith(".trapezoid")), K=cand.get("K"),
            banded=(True if bdd else False), band=cand.get("band"),
            tune=False)
        return (lambda P, Vx, Vy, Vz, Rho:
                it(P, Vx, Vy, Vz, Rho) + (Rho,)), tuple(fields)
    if family == "hm3d":
        from .models import hm3d as m

        p = params or m.Params()
        fields = m.init_fields(p, dtype=np.float32)
        step = m.make_step(
            p, donate=False, n_inner=n_inner, overlap=ov,
            use_pallas=(True if fast else False), pallas_interpret=interpret,
            trapezoid=(tier.endswith(".trapezoid")), K=cand.get("K"),
            banded=(True if bdd else False), band=cand.get("band"),
            tune=False)
        return (lambda Pe, phi: step(Pe, phi)), tuple(fields)
    if family == "wave2d":
        from .models import wave2d as m

        p = params or m.Params()
        fields = m.init_fields(p, dtype=np.float32)
        step = m.make_step(
            p, donate=False, n_inner=n_inner, overlap=ov,
            use_pallas=(True if fast else False), pallas_interpret=interpret,
            chunk=(tier == "wave2d.chunk"), K=cand.get("K"),
            banded=(True if bdd else False), band=cand.get("band"),
            tune=False)
        return (lambda P, Vx, Vy: step(P, Vx, Vy)), tuple(fields)
    raise GridError(f"igg.autotune: unknown family {family!r}.")


def _cand_label(cand: Dict) -> str:
    bits = [cand["tier"]]
    if cand.get("overlap"):
        bits.append("overlap")
    if cand.get("K"):
        bits.append(f"K={cand['K']}")
    if cand.get("bx"):
        bits.append(f"bx={cand['bx']}")
    if cand.get("band"):
        bits.append(f"band={cand['band']}")
    if cand.get("vmem_mb"):
        bits.append(f"vmem={cand['vmem_mb']}MB")
    return "[" + ",".join(bits) + "]"


def search(family: str, *, n_inner: int = 8, params=None,
           interpret: bool = False, nt: Optional[int] = None,
           candidates: Optional[Sequence[Dict]] = None,
           cutoff: Optional[float] = None,
           source: str = "autotune") -> Optional[Dict]:
    """Measure the candidate set for `family`'s current signature and
    install the winner in the tuning cache.

    Measurement protocol per candidate: one untimed warm-up dispatch
    (pays the compile), one quick timed dispatch — if that already
    exceeds ``cutoff`` x the best quick sample so far, the candidate is
    CUT OFF (its quick sample still lands in the ledger) — otherwise
    `igg.time_steps` slope timing (nt and 3*nt batches; constant
    dispatch latency cancels).  The ledger prior (:func:`igg.perf.best`)
    orders the candidates so the cutoff threshold is set by the likely
    winner first.  All samples are recorded into the perf ledger
    (source ``"autotune"``); the winner is persisted to the tuning
    cache.  Returns the winner entry (None when nothing is
    measurable)."""
    global _SEARCH_DISPATCHES
    import jax

    import igg
    from . import perf, shared

    shared.check_initialized()
    nt = int(nt if nt is not None else _env.number("IGG_TUNE_NT", 2))
    cutoff = float(cutoff if cutoff is not None
                   else _env.number("IGG_TUNE_CUTOFF", 2.0))
    cands = list(candidates if candidates is not None
                 else candidates_for(family, n_inner=n_inner,
                                     interpret=interpret))
    if not cands:
        return None

    ctx = _context(family)
    # The ledger prior orders the walk: best-known tier's candidates
    # first, so the cutoff threshold is set by the likely winner.
    prior = perf.best(family, local_shape=ctx["local_shape"] or None)
    if prior is not None:
        cands.sort(key=lambda c: 0 if c["tier"] == prior["tier"] else 1)

    from .ops import _vmem

    results = []
    best_quick = None
    entry_cap = _vmem._CAP_OVERRIDE      # restored on exit
    try:
        for cand in cands:
            label = _cand_label(cand)
            # vmem_mb=None candidates measure at the TRUE hand-derived
            # default (override cleared), never at a previously-applied
            # winner's cap — the baseline must not be biased by state.
            _vmem.set_cap_override(int(cand["vmem_mb"]) * 1024 * 1024
                                   if cand.get("vmem_mb") else None)
            try:
                state_fn, args = _build_candidate(family, cand, n_inner,
                                                  params, interpret)
                scratch = tuple(a + 0 for a in args)  # donation-safe
                # Warm-up (compile) + one quick timed dispatch.
                out = state_fn(*scratch)
                jax.block_until_ready(out)
                t0 = time.monotonic()
                out = state_fn(*out)
                jax.block_until_ready(out)
                quick = (time.monotonic() - t0) / n_inner * 1e3
                _SEARCH_DISPATCHES += 1
                cut = (best_quick is not None
                       and quick > cutoff * best_quick)
                if not cut:
                    _, sec = igg.time_steps(state_fn, out, n1=nt,
                                            n2=3 * nt, warmup=0)
                    _SEARCH_DISPATCHES += 4 * nt
                    ms = sec / n_inner * 1e3
                else:
                    ms = quick
                best_quick = (quick if best_quick is None
                              else min(best_quick, quick))
            except Exception as e:  # an inadmissible/failing candidate
                _telemetry.emit("autotune_candidate_failed",
                                family=family, candidate=label,
                                error=f"{type(e).__name__}: {e}")
                continue
            perf.record(family, cand["tier"], ms, source=source,
                        local_shape=ctx["local_shape"],
                        dtype="float32", dims=ctx["dims"],
                        backend=ctx["backend"],
                        device_kind=ctx["device_kind"])
            _telemetry.emit("autotune_sample", family=family,
                            candidate=label, ms_per_step=ms,
                            cutoff=bool(cut))
            results.append((ms, cand))
    finally:
        _vmem.set_cap_override(entry_cap)
    if not results:
        return None
    results.sort(key=lambda r: (r[0] if math.isfinite(r[0]) else
                                float("inf")))
    ms, best = results[0]
    if best.get("overlap") and not _overlap_confirmed(family, params,
                                                      n_inner):
        # The overlapped composition won the slope timing but the
        # measured step-time decomposition shows no exposed-comm drop
        # (hidden >= exchange): the timing win is noise or slab-recompute
        # luck, not hidden communication — demote to the best
        # non-overlapped candidate.  The decomposition samples are in the
        # perf ledger (family "comm", tier "overlap.<family>.xla+overlap.*",
        # source "calibrate"), so `igg.perf compare` gates the decision.
        seq = next((r for r in results if not r[1].get("overlap")), None)
        _telemetry.emit("overlap_demoted", family=family,
                        overlapped_ms=ms,
                        demoted_to=_cand_label(seq[1]) if seq else None)
        if seq is not None:
            ms, best = seq
    winner = dict(best, ms=ms)
    return record_winner(family, winner, local_shape=ctx["local_shape"])


def _overlap_confirmed(family: str, params, n_inner: int) -> bool:
    """Exposed-comm-driven selection: an overlapped candidate that wins
    the slope timing is recorded ONLY when an in-search
    :func:`igg.comm.decompose` window shows the hidden variant actually
    beating the plain exchange (measured exposed communication drops) —
    attributed to the ``"<family>.xla+overlap"`` serving config in the
    comm ledger.  Families without a step-variant recipe (spec-compiled
    ones measure through their own registered builders) pass on the
    timing evidence alone."""
    from . import comm

    try:
        mv = comm.model_step_variants(family, params)
    except GridError:
        return True
    try:
        fields = mv["init"](np.float32)
        d = comm.decompose(mv["compute"], fields[:mv["nf"]],
                           aux=fields[mv["nf"]:], radius=mv["radius"],
                           nt=2, n_inner=max(2, int(n_inner) // 2),
                           config=f"{family}.xla+overlap")
    except Exception as e:   # a failed probe must not kill the search
        _telemetry.emit("overlap_confirm_failed", family=family,
                        error=f"{type(e).__name__}: {e}")
        return True
    return d["hidden_ms"] < d["exchange_ms"]
