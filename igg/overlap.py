"""Communication/computation overlap: the TPU-native `hide_communication`.

The reference delegates comm/compute overlap to the caller: it provides
max-priority copy streams so an application layer (ParallelStencil's
`@hide_communication`) can compute the domain interior while `update_halo!`
messages are in flight (`/root/reference/README.md:9`,
`/root/reference/src/update_halo.jl:337,365`).

On TPU the equivalent is *structural*: inside one XLA program, a
collective-permute can overlap with compute only if there is no data
dependency between them.  In the naive step

    A' = compute(A); A' = update_halo_local(A')

the ppermutes consume planes of `A'`, so the whole stencil update must finish
before the first flit leaves the chip.  :func:`hide_communication`
restructures the step: the send planes are produced by thin, redundant *slab*
computations (two `(1+2r)`-plane stencil applications per dimension), the
dimension-sequential plane-level exchange runs on those — corner/edge
propagation intact — and the full-domain `compute(A)` is data-independent of
the entire exchange chain, so XLA's latency-hiding scheduler can run it while
the collectives ride the ICI links.  Cost: recomputing ~6 boundary planes,
O(s²) work against the O(s³) interior — the same trade ParallelStencil makes.

Semantics vs the sequential composition:
  - fully periodic or interior ranks: identical (the exchanged planes are the
    same arithmetic on the same values);
  - open-boundary edge ranks: halo planes keep their *pre-compute* values
    (the reference's no-write semantics — its users' stencils never write
    halo planes, `/root/reference/test/test_update_halo.jl:727-732`) except
    the corner/edge cells shared with an exchanged dimension, which carry
    that dimension's received values (as in the reference, where the later
    exchange overwrites them); the plain composition instead leaves whatever
    `compute` put there.  Halo cells at an open boundary are not meaningful
    in either model.

Requirements on `compute`: a shift-invariant local stencil of radius
`<= ol-1` per participating dimension (it is applied to thin slabs, so it
must accept any extent along the grid dimensions — `jnp.roll`/shift-based
stencils do).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from . import shared
from .halo import _plane, active_dims, assemble_planes, exchange_all_dims
from .shared import NDIMS, GridError


def hide_communication(A, compute: Callable, *aux, radius: int = 1):
    """`update_halo_local(compute(A, *aux))`, restructured so the halo
    exchange is data-independent of the full-domain compute (see module
    docstring).

    For use *inside* SPMD code (`igg.sharded` functions / shard_map), exactly
    like :func:`igg.update_halo_local`; `A` is the per-device local block.
    `aux` are read-only coefficient fields of the stencil (e.g. the heat
    capacity in the diffusion model); they must have the same local shape as
    `A` so they can be sliced into the same boundary slabs.  Returns the
    updated block.
    """
    from jax import lax

    shared.check_initialized()
    grid = shared.global_grid()
    s = A.shape
    for i, B in enumerate(aux):
        if B.shape != s:
            raise GridError(
                f"hide_communication: aux field {i} has shape {B.shape} != "
                f"{s}; aux fields must match the primary field's local shape "
                f"(pre-slice staggered coefficients inside `compute`).")

    dims_active = active_dims(s, grid)
    for d, ol in dims_active:
        if radius > ol - 1:
            raise GridError(
                f"hide_communication: stencil radius {radius} exceeds ol-1="
                f"{ol - 1} along dimension {d}; the send planes cannot be "
                f"computed from in-block data.")

    # 1. Send planes from thin slab computations (independent of the full
    #    compute).  Slab [p-r, p+r] around send plane p; its center plane has
    #    all its stencil inputs inside the slab.
    send: Dict[Tuple[int, int], object] = {}
    for d, ol in dims_active:
        for side, p in ((0, ol - 1), (1, s[d] - ol)):
            cut = lambda B: lax.slice_in_dim(B, p - radius, p + radius + 1,
                                             axis=d)
            send[(d, side)] = _plane(compute(cut(A), *map(cut, aux)),
                                     d, radius)

    # 2. Dimension-sequential plane-level exchange with corner propagation
    #    (shared with the halo engine, :func:`igg.halo.exchange_all_dims`).
    recv = exchange_all_dims(A, send, dims_active, grid)

    # 3. Full-domain compute — no data dependency on any ppermute above.
    out = compute(A, *aux)

    # 4. Assembly, in dimension order (later writes own the corner cells,
    #    like the reference's later exchanges).
    return assemble_planes(out, recv, dims_active)
