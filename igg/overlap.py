"""Communication/computation overlap: the TPU-native `hide_communication`.

The reference delegates comm/compute overlap to the caller: it provides
max-priority copy streams so an application layer (ParallelStencil's
`@hide_communication`) can compute the domain interior while `update_halo!`
messages are in flight (`/root/reference/README.md:9`,
`/root/reference/src/update_halo.jl:337,365`).

On TPU the equivalent is *structural*: inside one XLA program, a
collective-permute can overlap with compute only if there is no data
dependency between them.  In the naive step

    A' = compute(A); A' = update_halo_local(A')

the ppermutes consume planes of `A'`, so the whole stencil update must finish
before the first flit leaves the chip.  :func:`hide_communication`
restructures the step: the send planes are produced by thin, redundant *slab*
computations (two `(1+2r)`-plane stencil applications per dimension), the
dimension-sequential plane-level exchange runs on those — corner/edge
propagation intact — and the full-domain `compute` is data-independent of
the entire exchange chain, so XLA's latency-hiding scheduler can run it while
the collectives ride the ICI links.  Cost: recomputing ~6 boundary planes,
O(s²) work against the O(s³) interior — the same trade ParallelStencil makes.

Multi-field steps (e.g. the Stokes iteration, which updates and exchanges
P/Vx/Vy/Vz together) pass a *tuple* of primary fields and a `compute`
returning the same tuple; each field's send planes come from the same slab
recomputations and each field is exchanged independently, exactly like a
grouped `update_halo_local(P, Vx, Vy, Vz)`
(`/root/reference/src/update_halo.jl:19-20`).  Staggered primaries and aux
fields (local sizes differing from the base grid per dimension, reference
`/root/reference/src/shared.jl:81`) are pre-sliced internally: every array's
slab along `d` spans `[p - r, p + r + 1 + (size_d - base_d))`, so the
overhang of a `(n+1)`-sized face field is preserved on the slab.

Semantics vs the sequential composition:
  - fully periodic or interior ranks: identical (the exchanged planes are the
    same arithmetic on the same values);
  - open-boundary edge ranks: the no-write fallback planes (the reference's
    semantics — nothing is received there,
    `/root/reference/test/test_update_halo.jl:727-732`) are taken from the
    *slab-computed* output, not the pre-compute field, so whatever `compute`
    writes into its outermost planes is preserved exactly as in the plain
    composition.  For slice-based stencils (every model in `igg.models`;
    anything whose outermost-plane values read only cells within the slab)
    the two formulations are therefore identical *everywhere* — including
    full-shape updates like the Stokes pressure, whose open-boundary planes
    evolve.  Only wrap-based computes (e.g. `jnp.roll` stencils), whose edge
    values depend on the far side of the array, differ on those planes —
    and for those the plain composition's edge values are block-size
    artifacts anyway.  The fallback planes stay data-independent of the
    full-domain `compute` (they come from the same thin slabs as the send
    planes), so the overlap property is unaffected.

Requirements on `compute`: a shift-invariant local stencil of radius
`<= ol-1` per participating dimension (it is applied to thin slabs, so it
must accept any extent along the grid dimensions).  `radius` counts the full
dependency chain: a Gauss-Seidel-style step whose later updates read earlier
updates (e.g. Stokes velocities reading the freshly-updated pressure) has
radius 2 and therefore needs grids initialized with overlap >= 3.
"""

from __future__ import annotations

from typing import Callable

from . import shared
from . import telemetry as _telemetry
from .halo import _plane, active_dims, assemble_field, exchange_all_dims
from .shared import GridError


def hide_communication(A, compute: Callable, *aux, radius: int = 1,
                       assembly=None):
    """`update_halo_local(compute(A, *aux))`, restructured so the halo
    exchange is data-independent of the full-domain compute (see module
    docstring).

    For use *inside* SPMD code (`igg.sharded` functions / shard_map), exactly
    like :func:`igg.update_halo_local`; `A` is the per-device local block —
    or a tuple of blocks for multi-field steps, with `compute` returning the
    matching tuple.  `aux` are read-only coefficient fields of the stencil
    (any stagger).  Returns the updated block(s).  `assembly` selects the
    halo-plane write strategy exactly as in :func:`igg.update_halo_local`
    (`"xla"` lets the select chain fuse into `compute`'s output pass —
    measured faster for the radius-1 single-field diffusion step).
    """
    from jax import lax

    shared.check_initialized()
    grid = shared.global_grid()

    single = not isinstance(A, (tuple, list))
    fields = (A,) if single else tuple(A)
    if not fields:
        raise GridError("hide_communication: no fields given.")
    base = fields[0]
    s0 = base.shape

    dims_base = active_dims(s0, grid)
    base_dims = [d for d, _ in dims_base]
    per_field_dims = []
    for i, F in enumerate(fields):
        dims_f = active_dims(F.shape, grid)
        if [d for d, _ in dims_f] != base_dims:
            raise GridError(
                f"hide_communication: field {i} (local shape {F.shape}) has "
                f"halos in dims {[d for d, _ in dims_f]} but the base field "
                f"has {base_dims}; all primary fields must share the same "
                f"exchanged dimensions.")
        for d, ol in dims_f:
            if radius > ol - 1:
                raise GridError(
                    f"hide_communication: stencil radius {radius} exceeds "
                    f"ol-1={ol - 1} for field {i} along dimension {d}; the "
                    f"send planes cannot be computed from in-block data "
                    f"(initialize the grid with a larger overlap).")
        per_field_dims.append(dims_f)

    # Observability (igg.comm / igg.telemetry): hide_communication runs at
    # TRACE time inside the caller's SPMD program, so per-call host
    # accounting is impossible — instead every trace emits one
    # `hide_communication` bus record + counter (which compiled programs
    # carry the overlap restructuring), and the restructuring itself is a
    # trace-time span, so its construction cost shows in the span trace.
    _telemetry.counter("igg_hide_communication_traces_total").inc()
    _telemetry.emit("hide_communication", n_fields=len(fields),
                    radius=radius, dims=base_dims, assembly=assembly)
    with _telemetry.span("overlap.hide_communication",
                         n_fields=len(fields), radius=radius):
        return _hide_impl(fields, aux, compute, radius, assembly, grid,
                          single, s0, dims_base, per_field_dims)


def _hide_impl(fields, aux, compute, radius, assembly, grid, single, s0,
               dims_base, per_field_dims):
    """The restructured step (see :func:`hide_communication`)."""
    from jax import lax

    # 1. Send planes from thin slab computations (independent of the full
    #    compute).  All arrays are cut with a COMMON start `lo` along `d`
    #    (index alignment is what makes a shift-invariant stencil see the
    #    slabs as a consistent window of the global arrays), each keeping
    #    its stagger overhang `df = size_d - base_d` at the far end
    #    (extent `E + df`).
    #
    #    Window algebra.  With aligned indexing, producing field g's value
    #    at index i reads array f within
    #        [i - r + min(0, df_f - df_g), i + r + max(0, df_f - df_g)]
    #    (radius from the base lattice plus the relative stagger between
    #    f's and g's lattices).  Send planes sit at q_g = p + df_g on side
    #    0 and q_g = p on side 1 (since s_f - ol_f == s0 - ol).  Solving
    #    "the window covers every primary's plane's reads in every array"
    #    for the common start and extent gives the `lo`/`E` below; the
    #    old `[p-r, p+r+1+df)` rule under-covered any field staggered
    #    *smaller* than the base (its side-0 plane sits below the base's)
    #    and side-1 reads reaching above a smaller field's overhang.
    sends = [dict() for _ in fields]
    stales = [dict() for _ in fields]
    for (d, ol) in dims_base:
        dfs_all = [B.shape[d] - s0[d] for B in (*fields, *aux)]
        dgs = [F.shape[d] - s0[d] for F in fields]
        dgmin, dgmax = min(dgs), max(dgs)     # over primaries (incl. base 0)
        dmin_all = min(dfs_all)               # over primaries and aux
        for side, p in ((0, ol - 1), (1, s0[d] - ol)):
            if side == 0:
                lo = p - radius + min(dmin_all, dgmin)
                E = 2 * radius + 1 - min(dmin_all, dgmin) \
                    + max(0, dgmax - dmin_all)
            else:
                lo = p - radius + min(0, dmin_all - dgmax)
                E = (p - lo) + radius + 1 - min(dmin_all, dgmin)
            # Validate the radius-derived window BEFORE clamping: this is
            # the overlap-insufficiency diagnostic (the clamped window
            # always fits by construction).
            for B in (*fields, *aux):
                df = B.shape[d] - s0[d]
                if lo < 0 or lo + E + df > B.shape[d]:
                    raise GridError(
                        f"hide_communication: the send-plane window "
                        f"[{lo}, {lo + E + df}) along dimension {d} exceeds "
                        f"an array of local size {B.shape[d]}; increase the "
                        f"grid overlap to accommodate radius {radius} with "
                        f"staggers {sorted(set(dfs_all))}.")
            # Extend the window to the block end so the outermost plane is
            # in-slab: it is the open-boundary no-write fallback (see module
            # docstring) — a few extra rows of O(s²) work.
            if side == 0:
                E += lo
                lo = 0
            else:
                E = s0[d] - lo

            def cut(B):
                df = B.shape[d] - s0[d]
                return lax.slice_in_dim(B, lo, lo + E + df, axis=d)

            outs = compute(*(cut(F) for F in fields),
                           *(cut(B) for B in aux))
            outs = (outs,) if single else tuple(outs)
            for i, out in enumerate(outs):
                local_p = (p + dgs[i] if side == 0 else p) - lo
                sends[i][(d, side)] = _plane(out, d, local_p)
                stales[i][(d, side)] = _plane(
                    out, d, 0 if side == 0 else out.shape[d] - 1)

    # 2. Dimension-sequential plane-level exchange with corner propagation,
    #    per field (shared with the halo engine).
    recvs = [exchange_all_dims(F, sends[i], per_field_dims[i], grid,
                               stale=stales[i])
             for i, F in enumerate(fields)]

    # 3. Full-domain compute — no data dependency on any ppermute above.
    outs = compute(*fields, *aux)
    outs = (outs,) if single else tuple(outs)

    # 4. Assembly, in dimension order (later writes own the corner cells,
    #    like the reference's later exchanges) — through the in-place Pallas
    #    writers on TPU, the XLA plans elsewhere.
    result = tuple(assemble_field(out, recvs[i], per_field_dims[i], grid,
                                  assembly=assembly)
                   for i, out in enumerate(outs))
    return result[0] if single else result


# ---------------------------------------------------------------------------
# Overlap as a SERVING configuration (round 16): the factories' overlap=
# "auto"/True/False contract plus the structured admission the autotuner
# and igg.degrade consult before the overlapped variant may serve traffic.
# ---------------------------------------------------------------------------

def overlap_admission(radius: int = 1, *, grid=None, ndim: int = 3,
                      chunk_active: bool = False):
    """Whether the overlapped step variant is ADMISSIBLE as a serving
    configuration on the live grid — an :class:`igg.degrade.Admission`
    carrying the structured refusal reason:

    - ``radius > ol-1``: the send planes cannot be slab-computed from
      in-block data (:func:`hide_communication` would raise at trace
      time — initialize the grid with a larger overlap);
    - single-device mesh: every exchange is a local plane copy, there is
      no wire latency to hide behind the interior compute;
    - an active chunk/trapezoid tier: the K-step kernels already
      amortize one halo update over K interior steps, so restructuring
      the per-step exchange buys nothing.

    `ndim` bounds the participating grid dimensions (2 for the 2-D
    families).  Pure host arithmetic; never raises."""
    from .degrade import Admission

    if grid is None:
        if not shared.grid_is_initialized():
            return Admission.no("no grid initialized")
        grid = shared.global_grid()
    r = int(radius)
    for d in range(min(int(ndim), len(grid.overlaps))):
        ol = int(grid.overlaps[d])
        if r > ol - 1:
            return Admission.no(
                f"stencil radius {r} exceeds ol-1={ol - 1} along dimension "
                f"{d}: the send planes cannot be computed from in-block "
                f"data (initialize the grid with overlap >= {r + 1})")
    if all(int(dm) == 1 for dm in grid.dims[:int(ndim)]):
        return Admission.no(
            "single-device mesh: every exchange is a local plane copy, "
            "there is no wire to hide")
    if chunk_active:
        return Admission.no(
            "chunk tier already amortizes the exchange (one halo update "
            "per K interior steps)")
    return Admission.yes()


def resolve_overlap(overlap, *, family: str, tuned=None, radius: int = 1,
                    ndim: int = 3, chunk_active: bool = False) -> bool:
    """The factories' ``overlap=`` contract: ``True``/``False`` are
    explicit caller pins (True still trace-time-validates inside
    :func:`hide_communication`); ``"auto"`` resolves, in order:

    1. the ``IGG_OVERLAP`` knob — a set value forces on (1/true/on) or
       pins off (0/false/off) every auto knob in the process;
    2. the autotuner's cached winner for this signature (its persisted
       ``overlap`` axis, `igg.autotune`);
    3. off — the sequential composition stays the default with no
       winner.

    A resolved True is admission-gated by :func:`overlap_admission`: a
    refusal DEGRADES to the sequential composition (recorded in
    `igg.degrade.admission_log()` under ``{family}.overlap`` and emitted
    as an ``overlap_refused`` bus record) rather than raising — auto
    mode must never crash a serving path."""
    from . import _env, degrade

    if overlap in (True, False):
        return bool(overlap)
    if overlap != "auto":
        raise GridError(
            f"overlap={overlap!r}: expected True, False, or 'auto'.")
    forced = _env.text("IGG_OVERLAP")
    if forced is not None:
        want = _env.flag("IGG_OVERLAP")
    elif tuned is not None and tuned.get("overlap") is not None:
        want = bool(tuned["overlap"])
    else:
        want = False
    if not want:
        return False
    adm = overlap_admission(radius, ndim=ndim, chunk_active=chunk_active)
    if not adm:
        degrade._ADMISSION_LOG[f"{family}.overlap"] = adm.reason
        _telemetry.emit("overlap_refused", family=family, radius=radius,
                        reason=adm.reason)
        return False
    return True
