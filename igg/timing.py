"""Step timing that is robust to remote-dispatch transports.

The reference times loops with barrier-synchronized `tic()/toc()`
(`/root/reference/src/tools.jl:228-234`), which is accurate when a barrier
costs microseconds.  On remotely-attached TPU runtimes (device tunnels) a
device->host read carries a large *constant* latency (observed ~100-160 ms)
and `block_until_ready` may return at enqueue-acknowledgement rather than
completion — so any timed region that ends in a single sync is inflated by a
constant that dwarfs small step times.

:func:`time_steps` instead measures seconds/step by the **slope method**:
time a batch of N1 dispatches and a batch of N2 dispatches, each ended by the
same scalar device->host read; the constant dispatch/read latency cancels in
`(T2 - T1) / (N2 - N1)`.  Validated against the known v5e matmul roofline
(measures ~190 TFLOP/s bf16 against the 197 peak).
"""

from __future__ import annotations

import time
from typing import Callable, Tuple

__all__ = ["time_steps"]


def _sync_read(state) -> None:
    """Force completion of everything enqueued: read one scalar back."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaf = jax.tree.leaves(state)[0]
    if hasattr(leaf, "ndim") and leaf.ndim > 0:
        leaf = jnp.ravel(leaf)[0]
    np.asarray(jax.device_get(leaf))


def time_steps(step: Callable, state: Tuple, *, n1: int = 10, n2: int = 50,
               warmup: int = 3) -> Tuple[Tuple, float]:
    """Seconds per call of `state = step(*state)`, slope-measured.

    `step` takes the state tuple's elements and returns the new state (tuple,
    or a single array for 1-element states).  Returns `(state, sec_per_call)`.
    """
    if n2 <= n1:
        raise ValueError(f"need n2 > n1, got n1={n1} n2={n2}")

    def advance(n: int) -> float:
        nonlocal state
        t0 = time.monotonic()
        for _ in range(n):
            out = step(*state)
            state = out if isinstance(out, tuple) else (out,)
        _sync_read(state)
        return time.monotonic() - t0

    state = tuple(state) if isinstance(state, tuple) else (state,)
    advance(warmup)
    t1 = advance(n1)
    t2 = advance(n2)
    # The number of executed calls is deterministic — exactly
    # `warmup + n1 + n2` — so physics driven through this timer is
    # reproducible run to run.
    if t2 > t1:
        return state, (t2 - t1) / (n2 - n1)
    # Noise swamped the slope (t2 <= t1, e.g. a lingering recompile in the
    # first batch): fall back to the batch-2 average — an overestimate (it
    # includes the constant readback latency) but never zero/negative.
    return state, t2 / n2
