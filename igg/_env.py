"""Centralized parsing of the `IGG_*` environment knobs.

Every environment variable the library reads goes through the typed
accessors here, against a registry of the known names.  Two robustness
holes this closes (round 10):

- a typo'd knob (`IGG_ASSEMLBY`, `IGG_VERIFY_KERNEL`) used to be silently
  ignored — the user believes the override is active and it is not.  The
  first accessor call scans the process environment for `IGG_`-prefixed
  names outside the registry and warns ONCE, listing them next to the
  knobs that exist;
- an unparsable value (`IGG_CKPT_COMMIT_TIMEOUT=ten`) used to surface as a
  bare `ValueError` from some call stack deep in a save; the accessors
  raise `GridError` naming the variable and the expected type instead.

Extensions register their knobs with :func:`register` before first use so
the unknown-name sweep stays accurate.
"""

from __future__ import annotations

import os
import warnings
from typing import Dict, Optional

from .shared import GridError

# The registry: every IGG_* knob the library understands, with the one-line
# meaning shown when an unrecognized sibling is found.
_KNOWN: Dict[str, str] = {
    "IGG_ASSEMBLY": "pin the measured halo-assembly election (xla|writer)",
    "IGG_CKPT_COMMIT_TIMEOUT":
        "seconds to wait for sharded-checkpoint commit coordination",
    "IGG_COMM_STALL_TIMEOUT":
        "seconds before an unfetched async probe is reported as a "
        "collective stall (default 120; 0 disables the stall heartbeat)",
    "IGG_DIST_INIT_BACKOFF":
        "initial sleep between jax.distributed.initialize retries (s)",
    "IGG_DIST_INIT_TIMEOUT":
        "total seconds to keep retrying jax.distributed.initialize",
    "IGG_ENSEMBLE_MAX_PENDING_PROBES":
        "outstanding per-member watchdog probes before a forced fetch",
    "IGG_ENSEMBLE_RETRIES":
        "per-member rollback budget before a member is quarantined",
    "IGG_FLEET_BACKOFF":
        "initial sleep between fleet job-launch retries (s, doubling)",
    "IGG_FLEET_RETRIES":
        "launcher-fault retries per fleet job before it is marked failed",
    "IGG_HEAL":
        "1 enables the igg.heal self-healing engine on every run loop "
        "(default off; heal= on the run loops overrides)",
    "IGG_HEAL_COOLDOWN":
        "minimum seconds between consecutive heal actions (hysteresis; "
        "default 60)",
    "IGG_HEAL_MAX_ACTIONS":
        "heal-action budget per run before the escalation ladder "
        "(default 3)",
    "IGG_HEAL_SKEW_TOL":
        "straggler threshold: a watchdog window (or rank skew) beyond "
        "this factor of the healthy baseline plans a re-tile "
        "(default 4.0)",
    "IGG_HEAL_SUSTAIN":
        "consecutive observations a soft heal signal must persist "
        "before an action is planned (default 2)",
    "IGG_HEAL_THROUGHPUT_TOL":
        "lagging-job threshold: measured member_steps_per_s below this "
        "fraction of the expectation plans a repack (default 0.5)",
    "IGG_INTEGRITY":
        "1 enables the igg.integrity numeric-integrity layer on the run "
        "loops (default off; integrity= on the run loops overrides)",
    "IGG_INTEGRITY_CHECK_EVERY":
        "shadow re-execution cadence in watch windows (default 4; 0 "
        "disables the shadow spot checks)",
    "IGG_INTEGRITY_TOL":
        "relative invariant-drift tolerance of the integrity probes and "
        "the deep checkpoint verify (default 1e-3)",
    "IGG_INTEGRITY_DEEP_VERIFY":
        "0 stops integrity-enabled rollback/resume scans from preferring "
        "deep-verified generations (stamps are always written; default 1)",
    "IGG_NATIVE": "0 disables the native (C++) host-side runtime",
    "IGG_OVERLAP":
        "force (1/on) or pin off (0/off) the overlap='auto' knobs of the "
        "model factories and igg.stencil.compile; unset defers to the "
        "autotuner's cached winner (igg.overlap.resolve_overlap)",
    "IGG_NATIVE_THREADS": "thread count for the native re-tile/memcopy",
    "IGG_PERF": "0 disables perf-ledger recording (igg.perf)",
    "IGG_PERF_DRIFT_TOL":
        "relative cost-model error beyond which a cost_model_drift bus "
        "event fires (default 0.5)",
    "IGG_PERF_LEDGER":
        "path of the on-disk perf-ledger JSON (unset: in-memory only; "
        "rank-tagged automatically on multi-controller runs)",
    "IGG_PERF_SAVE_EVERY":
        "minimum seconds between perf-ledger autosaves (default 60)",
    "IGG_SERVE_MAX_CONCURRENT":
        "concurrent jobs the serve scheduler runs on disjoint device "
        "subsets (default 2; the bin-packer partitions the live devices)",
    "IGG_SERVE_QUEUE_BOUND":
        "global admission-queue bound of igg.serve — submissions past it "
        "shed with 429/job_shed and readiness pins queue_saturated "
        "(default 16)",
    "IGG_SERVE_TENANT_QUEUE_BOUND":
        "per-tenant admission-queue bound of igg.serve (default 8)",
    "IGG_SERVE_TENANT_RETRIES":
        "per-tenant retry budget: strikes a tenant's failing jobs may "
        "burn before its submissions shed and its jobs fail fast "
        "(default 8)",
    "IGG_SERVE_POLL":
        "serve scheduler tick interval in seconds (default 0.05)",
    "IGG_SERVE_MAX_BODY":
        "largest accepted submission body in bytes — bigger is rejected "
        "oversized (default 65536)",
    "IGG_STATUSD_PORT":
        "TCP port of the igg.statusd live ops endpoint (0/unset: off; "
        "the serve= knob on the run loops overrides)",
    "IGG_STATUSD_HOST":
        "bind address of the igg.statusd endpoint (default 127.0.0.1)",
    "IGG_STATUSD_HBM_EVERY":
        "minimum seconds between device memory_stats polls behind the "
        "igg_hbm_* gauges (default 10)",
    "IGG_STATUSD_MAX_FETCH_LAG":
        "watchdog fetch-lag (steps) beyond which /healthz readiness "
        "flips false (default 1000; 0 disables the lag check)",
    "IGG_STATUSD_PUBLISH_EVERY":
        "seconds between the non-zero-rank statusd snapshot files that "
        "rank 0's endpoint merges (default 5)",
    "IGG_TELEMETRY_DEVICE":
        "0 disables mirroring trace spans onto the device timeline "
        "(jax.profiler.TraceAnnotation)",
    "IGG_TELEMETRY_DIR":
        "default igg.telemetry session directory (setting it attaches "
        "telemetry to every run loop)",
    "IGG_TELEMETRY_FLIGHT_RECORDER":
        "flight-recorder ring size (events kept for post-mortem dumps)",
    "IGG_TELEMETRY_METRICS_EVERY":
        "seconds between periodic metrics exports (0: at detach only)",
    "IGG_TELEMETRY_SPANS": "0 disables host-side trace-span capture",
    "IGG_TPU_TESTS": "1 runs the TPU-only test files on the real backend",
    "IGG_VERIFY_KERNELS":
        "1 verifies every kernel tier against the XLA truth on first use",
}

_warned_unknown = False


def register(name: str, doc: str) -> None:
    """Add an extension's `IGG_*` knob to the known-name registry (call
    before the first accessor use so the unknown-name sweep stays
    accurate)."""
    if not name.startswith("IGG_"):
        raise GridError(f"_env.register: {name!r} is not an IGG_* name.")
    _KNOWN[name] = doc


def _sweep_unknown() -> None:
    """One-time warning for `IGG_`-prefixed environment variables the
    library does not understand — a typo'd knob silently ignored is its
    own robustness hole."""
    global _warned_unknown
    if _warned_unknown:
        return
    _warned_unknown = True
    unknown = sorted(n for n in os.environ
                     if n.startswith("IGG_") and n not in _KNOWN)
    if unknown:
        known = ", ".join(sorted(_KNOWN))
        warnings.warn(
            f"igg: unrecognized IGG_* environment variable(s) "
            f"{', '.join(unknown)} — they have no effect (known knobs: "
            f"{known}).", stacklevel=3)


def text(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw string accessor (registry-checked)."""
    _sweep_unknown()
    assert name in _KNOWN, f"unregistered IGG knob {name!r} (add to _env)"
    return os.environ.get(name, default)


def number(name: str, default: float) -> float:
    val = text(name)
    if val is None:
        return default
    try:
        return float(val)
    except ValueError:
        raise GridError(f"{name}={val!r} is not a number.") from None


def integer(name: str, default: int) -> int:
    val = text(name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError:
        raise GridError(f"{name}={val!r} is not an integer.") from None


def flag(name: str, default: bool = False) -> bool:
    """Boolean knob: "1"/"true"/"yes"/"on" (case-insensitive) are true,
    "0"/"false"/"no"/"off"/"" are false; anything else raises."""
    val = text(name)
    if val is None:
        return default
    low = val.strip().lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off", ""):
        return False
    raise GridError(f"{name}={val!r} is not a boolean "
                    f"(use 1/0, true/false, yes/no, on/off).")
