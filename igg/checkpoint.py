"""Checkpoint / resume for grid fields — a TPU-native extension.

The reference has NO checkpoint facility: `gather!` is its only state
export, and its examples only visualize the gathered array
(`/root/reference/src/gather.jl`, SURVEY §5 "Checkpoint / resume: none").
Long-running pod jobs need one, so this module adds the minimal faithful
version: save every field's full block-stacked global array (halo cells
included — on open boundaries they are user-owned data, e.g. physical
boundary values, and must survive a resume bit-for-bit) plus the grid
geometry, and restore into an identically-decomposed grid.

Format: one `numpy` `.npz` per checkpoint with a `__igg_meta__` JSON entry
recording `(nxyz, dims, overlaps, periods, nprocs)`.  Restore validates
the geometry against the live grid and fails loudly on any mismatch — a
checkpoint is tied to its decomposition because the stacked array's shape
is `dims * local` and halo cells are decomposition-dependent.  To move a
run to a DIFFERENT decomposition, pass `redistribute=True` to
:func:`load_checkpoint`: overlaps are stripped, the global interior is
re-tiled onto the current grid, and every block's halo cells are
reconstructed bit-exactly by global indexing (periodic wrap included).

Multi-controller runs: every process computes the full global array (the
same `process_allgather` path `gather` uses); only process 0 writes.  On
restore every process reads the file (shared filesystem, the standard pod
setup) and `device_put`s its own shards.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict

import numpy as np

from . import shared
from .shared import GridError

__all__ = ["save_checkpoint", "load_checkpoint"]

_META_KEY = "__igg_meta__"

# One-time memory-cliff warning flag (multi-controller checkpoint
# materializes every field's global array on every process).
_warned_ckpt_cliff = False


def _meta(grid) -> dict:
    return {
        "nxyz": list(grid.nxyz),
        "dims": list(grid.dims),
        "overlaps": list(grid.overlaps),
        "periods": list(grid.periods),
        "nprocs": grid.nprocs,
    }


def _write_npz(path, arrays: Dict[str, np.ndarray]) -> None:
    """`np.savez` without its two footguns: the `file=` keyword collides
    with a field named "file", and a missing `.npz` suffix makes savez
    write to a DIFFERENT path than given (breaking the load round-trip).
    This writes the same uncompressed npy-zip format np.load reads, to the
    exact path given."""
    import io
    import os
    import zipfile

    # Atomic: a crash mid-write must not destroy the previous checkpoint at
    # `path` (the overwrite-in-place pattern is the module's whole purpose).
    tmp = path.with_name(path.name + ".tmp")
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED) as zf:
        for name, arr in arrays.items():
            buf = io.BytesIO()
            np.lib.format.write_array(buf, arr, allow_pickle=False)
            zf.writestr(name + ".npy", buf.getvalue())
    os.replace(tmp, path)


def save_checkpoint(path, /, **fields) -> None:
    """Write the named grid fields and the grid geometry to `path` (.npz).

    Fields are full block-stacked global arrays (any stagger, any dtype);
    every process participates (multi-controller shards are exchanged over
    the runtime), process 0 writes.
    """
    import jax

    from .gather import _fetch_global

    shared.check_initialized()
    grid = shared.global_grid()
    if not fields:
        raise GridError("save_checkpoint: no fields given.")

    global _warned_ckpt_cliff
    if jax.process_count() > 1 and not _warned_ckpt_cliff:
        import warnings

        _warned_ckpt_cliff = True
        total = sum(int(getattr(A, "nbytes", 0)) for A in fields.values())
        warnings.warn(
            f"igg.save_checkpoint: on a multi-controller run every "
            f"process materializes the full global array of every field "
            f"(~{total / 2**20:.0f} MiB total here) in host memory "
            f"simultaneously — the allgather memory cliff documented in "
            f"docs/multihost.md.  Checkpoint fewer fields per call, or "
            f"space out the cadence, if hosts are memory-tight.  (Warned "
            f"once per process.)", stacklevel=2)

    host: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for name, A in fields.items():
        if name == _META_KEY:
            raise GridError(f"save_checkpoint: field name {_META_KEY!r} is "
                            f"reserved.")
        arr = np.ascontiguousarray(_fetch_global(A))
        dtypes[name] = str(arr.dtype)
        if arr.dtype.kind == "V" or arr.dtype.str.startswith("|V"):
            # Extension dtypes (bfloat16, float8_*) have no portable npy
            # descr; store the raw bytes and the true dtype name in meta.
            arr = arr.view(np.uint8)
        host[name] = arr

    if jax.process_index() == 0:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        meta = {**_meta(grid), "dtypes": dtypes}
        _write_npz(path, {**host, _META_KEY: np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)})
    if jax.process_count() > 1:
        # Multi-controller: no process may return (and possibly reload the
        # file) before process 0 finished writing it.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_save_checkpoint")


def load_checkpoint(path, /, *, redistribute: bool = False) -> Dict:
    """Read a checkpoint written by :func:`save_checkpoint` and return
    `{name: sharded jax.Array}` on the CURRENT grid.

    By default the current grid must have the geometry the checkpoint was
    written under (validated; `GridError` on mismatch).  With
    `redistribute=True` a checkpoint from a DIFFERENT decomposition is
    re-tiled onto the current grid (VERDICT r3 item 8): the saved blocks'
    overlaps are stripped (the `gather_interior` contract, via
    `numpy_retile`), the de-duplicated global interior is validated
    against the current grid's global sizes, and every target block —
    halo cells included — is reconstructed by global indexing with
    periodic wrap, which reproduces exactly what an `update_halo` on
    globally-consistent data would give, bit for bit.  Periodicity and
    per-array stagger must match; `dims`, local sizes, and overlaps may
    all differ."""
    import jax

    from .fields import sharding_for

    shared.check_initialized()
    grid = shared.global_grid()
    with np.load(pathlib.Path(path)) as z:
        meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        arrays = {k: z[k] for k in z.files if k != _META_KEY}

    mine = _meta(grid)
    same_geometry = {k: meta.get(k) for k in mine} == mine
    if not same_geometry and not redistribute:
        diffs = {k: (meta.get(k), mine[k]) for k in mine
                 if meta.get(k) != mine[k]}
        raise GridError(
            f"load_checkpoint: grid geometry mismatch {diffs} "
            f"(checkpoint vs current).  Pass redistribute=True to re-tile "
            f"the checkpoint onto the current decomposition.")
    if not same_geometry and list(meta["periods"]) != mine["periods"]:
        raise GridError(
            f"load_checkpoint(redistribute=True): periodicity mismatch "
            f"{meta['periods']} vs {mine['periods']} — redistribution "
            f"changes the decomposition, not the physics.")

    dtypes = meta.get("dtypes", {})
    out = {}
    for name, arr in arrays.items():
        want = np.dtype(dtypes.get(name, str(arr.dtype)))
        if arr.dtype != want:
            arr = arr.view(want)   # extension dtypes stored as raw bytes
        if not same_geometry:
            arr = _redistribute(name, arr, meta, grid)
        out[name] = jax.device_put(arr, sharding_for(arr.ndim))
    return out


def _redistribute(name: str, arr: np.ndarray, meta: dict, grid) -> np.ndarray:
    """Re-tile one saved stacked array from the checkpoint's decomposition
    onto `grid` (see :func:`load_checkpoint`)."""
    from .gather import numpy_retile
    from .shared import NDIMS

    ndim = min(arr.ndim, NDIMS)
    dims_s = list(meta["dims"][:ndim])
    nxyz_s = list(meta["nxyz"][:ndim])
    over_s = list(meta["overlaps"][:ndim])
    periods = list(meta["periods"][:ndim])

    local_s, ol_s = [], []
    for d in range(ndim):
        if arr.shape[d] % dims_s[d] != 0:
            raise GridError(
                f"load_checkpoint: field '{name}' dim {d} of size "
                f"{arr.shape[d]} is not divisible by the checkpoint's "
                f"dims[{d}]={dims_s[d]}.")
        local_s.append(arr.shape[d] // dims_s[d])
        ol_s.append(over_s[d] + (local_s[d] - nxyz_s[d]))

    interior = numpy_retile(
        arr, dims_s, local_s,
        [local_s[d] - max(ol_s[d], 0) for d in range(ndim)],
        [not periods[d] for d in range(ndim)])

    # Target geometry: the stagger (local - base) is decomposition-
    # independent; validate the de-duplicated global sizes agree.
    out = interior
    for d in range(ndim):
        df = local_s[d] - nxyz_s[d]
        s_b = grid.nxyz[d] + df
        ol_b = grid.overlaps[d] + df
        n_b = grid.dims[d]
        size = interior.shape[d]
        want = n_b * (s_b - ol_b) + (0 if periods[d] else ol_b)
        if size != want:
            raise GridError(
                f"load_checkpoint(redistribute=True): field '{name}' has "
                f"{size} unique cells along dim {d} but the current grid "
                f"needs {want}; the global physical domain must match.")
        # Stacked index j = c*s_b + i -> global interior index
        # c*(s_b - ol_b) + i (wrapped for periodic dims).
        idx = np.concatenate([
            (c * (s_b - ol_b) + np.arange(s_b)) % size if periods[d]
            else c * (s_b - ol_b) + np.arange(s_b)
            for c in range(n_b)])
        out = np.take(out, idx, axis=d)
    return out
