"""Checkpoint / resume for grid fields — a TPU-native extension.

The reference has NO checkpoint facility: `gather!` is its only state
export, and its examples only visualize the gathered array
(`/root/reference/src/gather.jl`, SURVEY §5 "Checkpoint / resume: none").
Long-running pod jobs need one, so this module adds the minimal faithful
version: save every field's full block-stacked global array (halo cells
included — on open boundaries they are user-owned data, e.g. physical
boundary values, and must survive a resume bit-for-bit) plus the grid
geometry, and restore into an identically-decomposed grid.

Format: one `numpy` `.npz` per checkpoint with a `__igg_meta__` JSON entry
recording `(nxyz, dims, overlaps, periods, nprocs)` plus a per-array CRC32
manifest (`crc32`, round 8) computed over each array's stored bytes and
verified on load — a truncated or bit-flipped checkpoint raises `GridError`
naming the path instead of surfacing a raw `zipfile.BadZipFile`, and
:func:`latest_checkpoint` scans a directory's generation files newest-first
skipping anything that fails verification (the rollback contract of
:mod:`igg.resilience`).  Restore validates
the geometry against the live grid and fails loudly on any mismatch — a
checkpoint is tied to its decomposition because the stacked array's shape
is `dims * local` and halo cells are decomposition-dependent.  To move a
run to a DIFFERENT decomposition, pass `redistribute=True` to
:func:`load_checkpoint`: overlaps are stripped, the global interior is
re-tiled onto the current grid, and every block's halo cells are
reconstructed bit-exactly by global indexing (periodic wrap included).

Multi-controller runs: every process computes the full global array (the
same `process_allgather` path `gather` uses); only process 0 writes.  On
restore every process reads the file (shared filesystem, the standard pod
setup) and `device_put`s its own shards.
"""

from __future__ import annotations

import json
import pathlib
import re
import zlib
from typing import Dict, Optional

import numpy as np

from . import shared
from .shared import GridError

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint",
           "verify_checkpoint", "checkpoint_step", "list_generations"]

_META_KEY = "__igg_meta__"

# One-time memory-cliff warning flag (multi-controller checkpoint
# materializes every field's global array on every process).
_warned_ckpt_cliff = False

# One-time warning flag for sweeping stale `*.tmp` files a crashed run left
# behind mid-`_write_npz`.
_warned_stale_tmp = False


def _crc32(arr: np.ndarray) -> int:
    """CRC32 of an array's raw bytes (C-order).  Cheap relative to the
    device→host fetch the arrays already paid, and independent of the zip
    container's own entry checksums — it lives in the `__igg_meta__`
    manifest, so a rewritten-but-wrong payload is still caught."""
    a = np.ascontiguousarray(arr)
    return int(zlib.crc32(a.reshape(-1).view(np.uint8)))


def _meta(grid) -> dict:
    return {
        "nxyz": list(grid.nxyz),
        "dims": list(grid.dims),
        "overlaps": list(grid.overlaps),
        "periods": list(grid.periods),
        "nprocs": grid.nprocs,
    }


# A .tmp file younger than this is assumed to belong to a LIVE concurrent
# writer (another process checkpointing into the same directory) and is
# left alone; a crashed writer's file only accrues age.
_STALE_TMP_AGE_S = 300.0


def _sweep_stale_tmp(parent: pathlib.Path) -> None:
    """Remove old `*.npz.tmp` files left in the checkpoint directory by a
    crash mid-`_write_npz` (the atomic-rename pattern never publishes them,
    so any that exist are garbage from a dead writer).  Two guards keep the
    sweep from touching files it does not own: only the `*.npz.tmp` shape
    `_write_npz` stages (a suffix-less checkpoint path leaves a `*.tmp`
    unswept — rare and harmless — rather than risk deleting another tool's
    temp file from a shared directory), and only files older than
    `_STALE_TMP_AGE_S` — a young one may be a live concurrent writer
    mid-write, and unlinking it would make its `os.replace` fail.  Warns
    once per process."""
    import time

    global _warned_stale_tmp

    now = time.time()
    stale = []
    for p in sorted(parent.glob("*.npz.tmp")):
        try:
            if now - p.stat().st_mtime >= _STALE_TMP_AGE_S:
                stale.append(p)
        except OSError:
            pass   # vanished under us (its writer finished or swept it)
    if not stale:
        return
    if not _warned_stale_tmp:
        import warnings

        _warned_stale_tmp = True
        warnings.warn(
            f"igg.save_checkpoint: sweeping {len(stale)} stale .tmp file(s) "
            f"left by a crashed writer in {parent} (e.g. {stale[0].name}); "
            f"checkpoints publish atomically, so .tmp files are never valid "
            f"state.  (Warned once per process.)", stacklevel=3)
    for p in stale:
        try:
            p.unlink()
        except OSError:
            pass  # another process swept it first


def _write_npz(path, arrays: Dict[str, np.ndarray]) -> None:
    """`np.savez` without its two footguns: the `file=` keyword collides
    with a field named "file", and a missing `.npz` suffix makes savez
    write to a DIFFERENT path than given (breaking the load round-trip).
    This writes the same uncompressed npy-zip format np.load reads, to the
    exact path given."""
    import io
    import os
    import zipfile

    # Atomic: a crash mid-write must not destroy the previous checkpoint at
    # `path` (the overwrite-in-place pattern is the module's whole purpose).
    tmp = path.with_name(path.name + ".tmp")
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED) as zf:
        for name, arr in arrays.items():
            buf = io.BytesIO()
            np.lib.format.write_array(buf, arr, allow_pickle=False)
            zf.writestr(name + ".npy", buf.getvalue())
    os.replace(tmp, path)


def save_checkpoint(path, /, **fields) -> None:
    """Write the named grid fields and the grid geometry to `path` (.npz).

    Fields are full block-stacked global arrays (any stagger, any dtype);
    every process participates (multi-controller shards are exchanged over
    the runtime), process 0 writes.
    """
    import jax

    from .gather import _fetch_global

    shared.check_initialized()
    grid = shared.global_grid()
    if not fields:
        raise GridError("save_checkpoint: no fields given.")

    global _warned_ckpt_cliff
    if jax.process_count() > 1 and not _warned_ckpt_cliff:
        import warnings

        _warned_ckpt_cliff = True
        total = sum(int(getattr(A, "nbytes", 0)) for A in fields.values())
        warnings.warn(
            f"igg.save_checkpoint: on a multi-controller run every "
            f"process materializes the full global array of every field "
            f"(~{total / 2**20:.0f} MiB total here) in host memory "
            f"simultaneously — the allgather memory cliff documented in "
            f"docs/multihost.md.  Checkpoint fewer fields per call, or "
            f"space out the cadence, if hosts are memory-tight.  (Warned "
            f"once per process.)", stacklevel=2)

    host: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for name, A in fields.items():
        if name == _META_KEY:
            raise GridError(f"save_checkpoint: field name {_META_KEY!r} is "
                            f"reserved.")
        arr = np.ascontiguousarray(_fetch_global(A))
        dtypes[name] = str(arr.dtype)
        if arr.dtype.kind == "V" or arr.dtype.str.startswith("|V"):
            # Extension dtypes (bfloat16, float8_*) have no portable npy
            # descr; store the raw bytes and the true dtype name in meta.
            arr = arr.view(np.uint8)
        host[name] = arr

    if jax.process_index() == 0:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        _sweep_stale_tmp(path.parent)
        meta = {**_meta(grid), "dtypes": dtypes,
                "crc32": {name: _crc32(arr) for name, arr in host.items()}}
        _write_npz(path, {**host, _META_KEY: np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)})
    if jax.process_count() > 1:
        # Multi-controller: no process may return (and possibly reload the
        # file) before process 0 finished writing it.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_save_checkpoint")


def load_checkpoint(path, /, *, redistribute: bool = False) -> Dict:
    """Read a checkpoint written by :func:`save_checkpoint` and return
    `{name: sharded jax.Array}` on the CURRENT grid.

    By default the current grid must have the geometry the checkpoint was
    written under (validated; `GridError` on mismatch).  With
    `redistribute=True` a checkpoint from a DIFFERENT decomposition is
    re-tiled onto the current grid (VERDICT r3 item 8): the saved blocks'
    overlaps are stripped (the `gather_interior` contract, via
    `numpy_retile`), the de-duplicated global interior is validated
    against the current grid's global sizes, and every target block —
    halo cells included — is reconstructed by global indexing with
    periodic wrap, which reproduces exactly what an `update_halo` on
    globally-consistent data would give, bit for bit.  Periodicity and
    per-array stagger must match; `dims`, local sizes, and overlaps may
    all differ."""
    import jax

    from .fields import sharding_for

    shared.check_initialized()
    grid = shared.global_grid()
    meta, arrays = _read_verified(pathlib.Path(path))

    mine = _meta(grid)
    same_geometry = {k: meta.get(k) for k in mine} == mine
    if not same_geometry and not redistribute:
        diffs = {k: (meta.get(k), mine[k]) for k in mine
                 if meta.get(k) != mine[k]}
        raise GridError(
            f"load_checkpoint: grid geometry mismatch {diffs} "
            f"(checkpoint vs current).  Pass redistribute=True to re-tile "
            f"the checkpoint onto the current decomposition.")
    if not same_geometry and list(meta["periods"]) != mine["periods"]:
        raise GridError(
            f"load_checkpoint(redistribute=True): periodicity mismatch "
            f"{meta['periods']} vs {mine['periods']} — redistribution "
            f"changes the decomposition, not the physics.")

    dtypes = meta.get("dtypes", {})
    out = {}
    for name, arr in arrays.items():
        try:
            want = np.dtype(dtypes.get(name, str(arr.dtype)))
            if arr.dtype != want:
                arr = arr.view(want)   # extension dtypes stored as raw bytes
        except (TypeError, ValueError) as e:
            raise GridError(
                f"load_checkpoint: corrupt dtypes manifest for field "
                f"{name!r} in {path} ({e}).") from e
        if not same_geometry:
            arr = _redistribute(name, arr, meta, grid)
        out[name] = jax.device_put(arr, sharding_for(arr.ndim))
    return out


def _read_verified(path: pathlib.Path):
    """Read every entry of a checkpoint file and verify the per-array CRC32
    manifest.  Returns `(meta, arrays)`; raises `GridError` naming the path
    for anything unreadable — a missing file, a zip truncated by a crashed
    or preempted writer, a payload whose container checksum fails, or an
    array whose manifest CRC32 disagrees with its bytes."""
    import zipfile

    try:
        with np.load(path) as z:
            if _META_KEY not in z.files:
                raise GridError(
                    f"load_checkpoint: {path} has no {_META_KEY!r} entry — "
                    f"not an igg checkpoint (or one truncated before the "
                    f"manifest was written).")
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
            arrays = {k: z[k] for k in z.files if k != _META_KEY}
    except GridError:
        raise
    except (zipfile.BadZipFile, OSError, EOFError, ValueError, KeyError,
            json.JSONDecodeError) as e:
        raise GridError(
            f"load_checkpoint: cannot read checkpoint {path}: "
            f"{type(e).__name__}: {e} — the file is missing, truncated, or "
            f"corrupt (a crash mid-write never publishes a partial file; "
            f"this one was damaged after the fact or never completed on a "
            f"non-atomic filesystem).") from e

    crcs = meta.get("crc32", {})   # absent in pre-round-8 checkpoints
    for name, arr in arrays.items():
        want = crcs.get(name)
        if want is not None and _crc32(arr) != want:
            raise GridError(
                f"load_checkpoint: CRC32 mismatch for field {name!r} in "
                f"{path} ({_crc32(arr):#010x} != recorded {want:#010x}) — "
                f"the checkpoint is corrupt.")
    return meta, arrays


def verify_checkpoint(path, *, check_finite: bool = False) -> bool:
    """Whether `path` is a readable, checksum-consistent checkpoint.

    Reads every array and verifies the CRC32 manifest (files written before
    the manifest existed verify structurally only).  With
    `check_finite=True`, additionally require every floating/complex field
    to be entirely finite — the health gate :mod:`igg.resilience` applies
    when choosing a rollback generation, since a checkpoint written between
    a NaN blowup and its detection is structurally perfect but poisoned.
    Purely host-side (no grid needs to be initialized)."""
    try:
        meta, arrays = _read_verified(pathlib.Path(path))
    except GridError:
        return False
    if not check_finite:
        return True
    dtypes = meta.get("dtypes", {})
    for name, arr in arrays.items():
        # A malformed dtypes manifest entry (version-skewed writer, damaged
        # meta — the CRC32 manifest covers arrays, not itself) must read as
        # "not a valid checkpoint", never escape as a raw TypeError/
        # ValueError and kill the skip-corrupt fallback in the callers.
        try:
            want = np.dtype(dtypes.get(name, str(arr.dtype)))
            if arr.dtype != want:
                arr = arr.view(want)   # extension dtypes stored as raw bytes
        except (TypeError, ValueError):
            return False
        if want.kind in "biu":
            continue               # integral: always finite
        # f/c AND the kind-'V' extension floats (bfloat16, float8_* — a
        # kind check of "fc" would wave a NaN-poisoned bf16 field through
        # the health gate); np.isfinite handles them via ml_dtypes.
        try:
            ok = bool(np.isfinite(arr).all())
        except TypeError:          # dtype without isfinite support
            continue
        if not ok:
            return False
    return True


def checkpoint_step(path) -> Optional[int]:
    """Step number encoded in a generation filename (`<prefix>_<step>.npz`,
    the ring layout :mod:`igg.resilience` writes); None for non-generation
    names."""
    m = re.search(r"_(\d+)\.npz$", pathlib.Path(path).name)
    return int(m.group(1)) if m else None


def list_generations(directory, prefix: str = "ckpt"):
    """All generation files `{prefix}_<digits>.npz` in `directory` as a
    `[(step, path), ...]` list sorted by step (strict filename match — a
    sibling ring under a longer prefix like 'ckpt_b' never matches).  The
    single scan shared by :func:`latest_checkpoint` and the resilience
    ring's pruning/rollback, so the two can never disagree on what a
    generation is."""
    directory = pathlib.Path(directory)
    gens = []
    for p in directory.glob(f"{prefix}_*.npz"):
        if re.fullmatch(re.escape(prefix) + r"_\d+\.npz", p.name):
            gens.append((checkpoint_step(p), p))
    return sorted(gens)


def latest_checkpoint(directory, prefix: str = "ckpt", *,
                      check_finite: bool = False) -> Optional[pathlib.Path]:
    """Newest valid checkpoint generation in `directory`.

    Scans `{prefix}_<step>.npz` files newest-first (by the step encoded in
    the filename) and returns the first that passes
    :func:`verify_checkpoint` — a truncated or corrupt newest generation is
    skipped, falling back to the previous one.  Returns None when no valid
    generation exists.  `check_finite` additionally skips generations
    holding non-finite field values (resume-after-blowup safety)."""
    for _, p in reversed(list_generations(directory, prefix)):
        if verify_checkpoint(p, check_finite=check_finite):
            return p
    return None


def _redistribute(name: str, arr: np.ndarray, meta: dict, grid) -> np.ndarray:
    """Re-tile one saved stacked array from the checkpoint's decomposition
    onto `grid` (see :func:`load_checkpoint`)."""
    from .gather import numpy_retile
    from .shared import NDIMS

    ndim = min(arr.ndim, NDIMS)
    dims_s = list(meta["dims"][:ndim])
    nxyz_s = list(meta["nxyz"][:ndim])
    over_s = list(meta["overlaps"][:ndim])
    periods = list(meta["periods"][:ndim])

    local_s, ol_s = [], []
    for d in range(ndim):
        if arr.shape[d] % dims_s[d] != 0:
            raise GridError(
                f"load_checkpoint: field '{name}' dim {d} of size "
                f"{arr.shape[d]} is not divisible by the checkpoint's "
                f"dims[{d}]={dims_s[d]}.")
        local_s.append(arr.shape[d] // dims_s[d])
        ol_s.append(over_s[d] + (local_s[d] - nxyz_s[d]))

    interior = numpy_retile(
        arr, dims_s, local_s,
        [local_s[d] - max(ol_s[d], 0) for d in range(ndim)],
        [not periods[d] for d in range(ndim)])

    # Target geometry: the stagger (local - base) is decomposition-
    # independent; validate the de-duplicated global sizes agree.
    out = interior
    for d in range(ndim):
        df = local_s[d] - nxyz_s[d]
        s_b = grid.nxyz[d] + df
        ol_b = grid.overlaps[d] + df
        n_b = grid.dims[d]
        size = interior.shape[d]
        want = n_b * (s_b - ol_b) + (0 if periods[d] else ol_b)
        if size != want:
            raise GridError(
                f"load_checkpoint(redistribute=True): field '{name}' has "
                f"{size} unique cells along dim {d} but the current grid "
                f"needs {want}; the global physical domain must match.")
        # Stacked index j = c*s_b + i -> global interior index
        # c*(s_b - ol_b) + i (wrapped for periodic dims).
        idx = np.concatenate([
            (c * (s_b - ol_b) + np.arange(s_b)) % size if periods[d]
            else c * (s_b - ol_b) + np.arange(s_b)
            for c in range(n_b)])
        out = np.take(out, idx, axis=d)
    return out
