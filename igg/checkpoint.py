"""Checkpoint / resume for grid fields — a TPU-native extension.

The reference has NO checkpoint facility: `gather!` is its only state
export, and its examples only visualize the gathered array
(`/root/reference/src/gather.jl`, SURVEY §5 "Checkpoint / resume: none").
Long-running pod jobs need one, so this module adds the minimal faithful
version: save every field's full block-stacked global array (halo cells
included — on open boundaries they are user-owned data, e.g. physical
boundary values, and must survive a resume bit-for-bit) plus the grid
geometry, and restore into an identically-decomposed grid.

Format: one `numpy` `.npz` per checkpoint with a `__igg_meta__` JSON entry
recording `(nxyz, dims, overlaps, periods, nprocs)` plus a per-array CRC32
manifest (`crc32`, round 8) computed over each array's stored bytes and
verified on load — a truncated or bit-flipped checkpoint raises `GridError`
naming the path instead of surfacing a raw `zipfile.BadZipFile`, and
:func:`latest_checkpoint` scans a directory's generation files newest-first
skipping anything that fails verification (the rollback contract of
:mod:`igg.resilience`).  Restore validates
the geometry against the live grid and fails loudly on any mismatch — a
checkpoint is tied to its decomposition because the stacked array's shape
is `dims * local` and halo cells are decomposition-dependent.  To move a
run to a DIFFERENT decomposition, pass `redistribute=True` to
:func:`load_checkpoint`: overlaps are stripped, the global interior is
re-tiled onto the current grid, and every block's halo cells are
reconstructed bit-exactly by global indexing (periodic wrap included).

Two on-disk formats coexist:

- **Flat `.npz`** (:func:`save_checkpoint`): one file holding every field's
  full block-stacked global array.  Simple and portable, but the write
  requires the global array assembled on the root process — the legacy
  format for single-host runs and small grids.
- **Sharded generation directory** (:func:`save_checkpoint_sharded`): the
  production-scale format.  A checkpoint is a directory where every grid
  block lands in its own `shard_<rank>.npz` (halo cells included — on open
  boundaries they are user-owned data and must survive a resume
  bit-for-bit), written by the controller process that addresses that
  block, plus a process-0 `manifest.json` carrying the grid geometry,
  per-field dtypes/local shapes, and a per-shard CRC32 summary.  The
  directory is staged as `<name>.tmp/` and the manifest is written LAST,
  then the staging directory is renamed into place (the same atomic
  pattern `_write_npz` uses for single files): a generation without its
  manifest — or still under its `.tmp` staging name — is uncommitted and
  is skipped by :func:`verify_checkpoint`/:func:`latest_checkpoint`
  exactly like a bit-flipped flat file.  **No process ever assembles the
  global array**: save stages one O(local) block at a time, and
  :func:`load_checkpoint` restores shard-by-shard — including the
  *elastic* restore path, which re-tiles a generation written under a
  DIFFERENT `dims`/device count onto the live decomposition
  (`redistribute=True`) by per-target-block global indexing (overlaps
  stripped, halos reconstructed, periodic wrap and open-boundary
  user-owned planes preserved), never holding more than a couple of
  shards in host memory.

Restore validates the geometry against the live grid and fails loudly on
any mismatch; pass `redistribute=True` to :func:`load_checkpoint` to
re-tile either format onto the current decomposition (the flat path
materializes the global interior on each process; the sharded path
streams).  Periodicity and per-array stagger must match — redistribution
changes the decomposition, not the physics.

Multi-controller runs: the sharded format needs a shared filesystem (the
standard pod setup) — each process writes its own shards, process 0 waits
for the full shard set and seals the generation with the manifest; no
cross-process array collectives are involved, so saves can run from a
background writer thread (:mod:`igg.resilience`).  The legacy flat format
assembles the global array on process 0 only (root-biased chunked fetch;
non-root host memory stays O(local) — see `igg.gather._fetch_global`).
"""

from __future__ import annotations

import itertools
import json
import logging
import pathlib
import re
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import shared
from . import telemetry as _telemetry
from .shared import GridError, NDIMS

__all__ = ["save_checkpoint", "save_checkpoint_sharded", "load_checkpoint",
           "latest_checkpoint", "verify_checkpoint",
           "verify_checkpoint_distributed", "checkpoint_step",
           "list_generations", "remove_generation"]

_log = logging.getLogger("igg.checkpoint")

_META_KEY = "__igg_meta__"
# Attempt handshake inside a staging dir (all filesystem, no collectives):
# peer process p publishes `hello_<p>` holding a fresh per-call nonce, and
# process 0 — which cleared any dead attempt's leftovers BEFORE it answers
# anything — replies with `ack_<p>` echoing that nonce plus this attempt's
# token.  A peer trusts only an ack echoing ITS OWN nonce: the nonce did
# not exist before the peer entered the call, so the echoing process 0 is
# provably live and past its clear, and the token in the ack is provably
# this attempt's — a dead attempt's stale staging dir (token, acks, shards
# and all) can never satisfy the handshake, no matter how the relaunch
# interleaves with process 0's cleanup.  The commit wait then matches the
# sealed manifest against the same token, so neither stale shards nor a
# pre-existing committed generation satisfies either side.
_HELLO = "hello_{:05d}"
_ACK = "ack_{:05d}"
# Third leg: the peer confirms it HAS the token (`done_<p>`), and process 0
# seals only after every peer's confirmation (plus the full shard set) —
# without it, a peer owning no shard files (all fields rank < 3) that says
# hello after the shard set completes would never be answered and would
# time out against a staging dir that no longer exists.
_DONE = "done_{:05d}"
# Marker name older igg versions staged (still recognized when sweeping
# their orphaned staging dirs).
_ATTEMPT = "attempt.token"

# Sharded-generation layout constants.
_MANIFEST = "manifest.json"
_FORMAT = "igg-sharded-v1"

# One-shot debug-log guard: a multi-controller run taking the LEGACY flat
# `.npz` path (root still assembles the global array; the sharded format
# doesn't).  The old one-time memory-cliff UserWarning is retired — the
# root-biased fetch keeps non-root host memory at O(local) even here.
_logged_flat_fallback = False

# One-time warning flag for sweeping stale `*.tmp` staging files/dirs a
# crashed writer left behind mid-`_write_npz`/mid-commit.
_warned_stale_tmp = False

# Deep-stamp format version (round 19): per-field owned-cell moment sums
# (+ the active invariants' references) written into the flat meta /
# sharded manifests so `verify_checkpoint(deep=True)` can refuse a
# finite-but-poisoned or silently-corrupted generation that the CRC32
# layer and the all-finite gate both wave through.
_DEEP_V = 1


def _crc32(arr: np.ndarray) -> int:
    """CRC32 of an array's raw bytes (C-order).  Cheap relative to the
    device→host fetch the arrays already paid, and independent of the zip
    container's own entry checksums — it lives in the `__igg_meta__`
    manifest, so a rewritten-but-wrong payload is still caught."""
    a = np.ascontiguousarray(arr)
    return int(zlib.crc32(a.reshape(-1).view(np.uint8)))


def _meta(grid) -> dict:
    return {
        "nxyz": list(grid.nxyz),
        "dims": list(grid.dims),
        "overlaps": list(grid.overlaps),
        "periods": list(grid.periods),
        "nprocs": grid.nprocs,
    }


# A .tmp file younger than this is assumed to belong to a LIVE concurrent
# writer (another process checkpointing into the same directory) and is
# left alone; a crashed writer's file only accrues age.
_STALE_TMP_AGE_S = 300.0


def _is_staging_dir(p: pathlib.Path) -> bool:
    """Whether a `*.tmp` directory has the exact shape
    :func:`save_checkpoint_sharded` stages — only `shard_*.npz` files
    (possibly with their own `.tmp` staging suffix) and the manifest.
    Anything else means the directory is NOT ours and must never be swept
    from a shared checkpoint directory."""
    try:
        entries = list(p.iterdir())
    except OSError:
        return False
    for e in entries:
        if not (re.fullmatch(
                    r"(shard_\d+\.npz|hello_\d+|ack_\d+|done_\d+)(\.tmp)?",
                    e.name)
                or e.name in (_MANIFEST, _MANIFEST + ".tmp",
                              _ATTEMPT, _ATTEMPT + ".tmp")):
            return False
    return True


def _sweep_stale_tmp(parent: pathlib.Path) -> None:
    """Remove old `*.npz.tmp` files AND orphaned `*.tmp` generation
    directories left in the checkpoint directory by a crash mid-write
    (`_write_npz`'s atomic rename and the sharded commit both stage under
    `.tmp` names and never publish them, so any that exist are garbage
    from a dead writer).  Two guards keep the sweep from touching state it
    does not own: only the exact shapes this module stages — `*.npz.tmp`
    files and staging directories holding nothing but `shard_*.npz` /
    manifest entries (another tool's temp file or directory in a shared
    checkpoint dir is never deleted) — and only entries older than
    `_STALE_TMP_AGE_S`, since a young one may belong to a LIVE concurrent
    writer mid-write/mid-commit, and removing it would make that writer's
    `os.replace` fail.  Warns once per process."""
    import shutil
    import time

    global _warned_stale_tmp

    now = time.time()
    stale = []
    for p in sorted(parent.glob("*.tmp")):
        try:
            is_dir = p.is_dir()
            if is_dir and not _is_staging_dir(p):
                continue
            if not is_dir and not p.name.endswith(".npz.tmp"):
                continue
            if now - p.stat().st_mtime >= _STALE_TMP_AGE_S:
                stale.append((p, is_dir))
        except OSError:
            pass   # vanished under us (its writer finished or swept it)
    if not stale:
        return
    if not _warned_stale_tmp:
        import warnings

        _warned_stale_tmp = True
        warnings.warn(
            f"igg.save_checkpoint: sweeping {len(stale)} stale .tmp "
            f"file(s)/staging dir(s) left by a crashed writer in {parent} "
            f"(e.g. {stale[0][0].name}); checkpoints publish atomically, so "
            f".tmp entries are never valid state.  (Warned once per "
            f"process.)", stacklevel=3)
    for p, is_dir in stale:
        try:
            shutil.rmtree(p) if is_dir else p.unlink()
        except OSError:
            pass  # another process swept it first


def _write_npz(path, arrays: Dict[str, np.ndarray]) -> None:
    """`np.savez` without its two footguns: the `file=` keyword collides
    with a field named "file", and a missing `.npz` suffix makes savez
    write to a DIFFERENT path than given (breaking the load round-trip).
    This writes the same uncompressed npy-zip format np.load reads, to the
    exact path given."""
    import io
    import os
    import zipfile

    # Atomic: a crash mid-write must not destroy the previous checkpoint at
    # `path` (the overwrite-in-place pattern is the module's whole purpose).
    tmp = path.with_name(path.name + ".tmp")
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED) as zf:
        for name, arr in arrays.items():
            buf = io.BytesIO()
            np.lib.format.write_array(buf, arr, allow_pickle=False)
            zf.writestr(name + ".npy", buf.getvalue())
    os.replace(tmp, path)


def _encode(arr: np.ndarray) -> np.ndarray:
    """Extension dtypes (bfloat16, float8_*) have no portable npy descr;
    store the raw bytes (the true dtype name travels in the meta/manifest
    `dtypes` entry and is viewed back on load)."""
    if arr.dtype.kind == "V" or arr.dtype.str.startswith("|V"):
        return arr.view(np.uint8)
    return arr


def _decode(arr: np.ndarray, want: Optional[str], path, name: str):
    """View a stored array back to its true dtype per the manifest; a
    malformed dtypes entry reads as a corrupt checkpoint, never a raw
    TypeError/ValueError."""
    try:
        w = np.dtype(want) if want is not None else arr.dtype
        if arr.dtype != w:
            arr = arr.view(w)
    except (TypeError, ValueError) as e:
        raise GridError(
            f"load_checkpoint: corrupt dtypes manifest for field "
            f"{name!r} in {path} ({e}).") from e
    return arr


# ---------------------------------------------------------------------------
# Deep stamps (round 19): owned-cell moment sums + invariant references
# ---------------------------------------------------------------------------

def _real_view(a: np.ndarray) -> np.ndarray:
    """A real-valued view for the deep moment sums: complex splits into
    interleaved re/im floats, bool widens to uint8; everything else is
    summed as-is (in float64).  Purely a deterministic digest basis."""
    if a.dtype.kind == "c":
        return a.view(np.dtype(f"f{a.dtype.itemsize // 2}"))
    if a.dtype.kind == "b":
        return a.astype(np.uint8)
    return a


def _deep_stats(a: np.ndarray) -> np.ndarray:
    """``[sum, abs_sum, sum_sq]`` of an (owned-slice, true-dtype) array
    in float64 — deterministic for a given array (numpy's pairwise
    summation is shape-fixed), so recomputing at verify time reproduces
    the stamp bit-for-bit unless the bytes changed."""
    x = np.asarray(_real_view(np.asarray(a)), dtype=np.float64)
    return np.array([x.sum(), np.abs(x).sum(), (x * x).sum()],
                    dtype=np.float64)


def _owned_slice(shape, coords, meta) -> tuple:
    """Owned-cell slice of one local block (halo cells included in
    `shape`): along each sharded dim the block owns its first
    ``s − ol`` cells, the LAST block of a non-periodic dim owning all
    ``s`` — exactly the :func:`_redistribute` de-duplication, so the
    union over blocks is the global interior, each cell once."""
    sl = []
    for d in range(min(len(shape), NDIMS)):
        s = int(shape[d])
        ol = meta["overlaps"][d] + (s - meta["nxyz"][d])
        keep = s - max(ol, 0)
        last = coords[d] == meta["dims"][d] - 1
        sl.append(slice(0, s if (last and not meta["periods"][d]) else keep))
    return tuple(sl) + (slice(None),) * (len(shape) - len(sl))


def _deep_sums_stacked(arr: np.ndarray, meta: dict) -> np.ndarray:
    """Dedup moment sums of a block-STACKED global array (the flat
    format): per-block owned slices accumulated in block-rank order."""
    nd = min(arr.ndim, NDIMS)
    local = [arr.shape[d] // meta["dims"][d] for d in range(nd)]
    tot = np.zeros(3, dtype=np.float64)
    for coords in itertools.product(
            *[range(meta["dims"][d]) for d in range(nd)]):
        block = arr[tuple(slice(c * local[d], (c + 1) * local[d])
                          for d, c in enumerate(coords)) or (Ellipsis,)]
        tot += _deep_stats(block[_owned_slice(block.shape, coords, meta)])
    return tot


def _stamp_invariants() -> Optional[list]:
    """The active run's invariant stamp entries (igg.integrity's stamp
    context) — None outside an integrity-enabled run.  Lazy import so
    the checkpoint layer never pays for (or cycles with) the integrity
    module."""
    try:
        from . import integrity
    except ImportError:       # pragma: no cover - integrity always ships
        return None
    return integrity.stamp_entries()


def _deep_meta(sums: Dict[str, list]) -> dict:
    deep = {"v": _DEEP_V, "sums": {n: [float(v) for v in s]
                                   for n, s in sums.items()}}
    inv = _stamp_invariants()
    if inv:
        deep["invariants"] = inv
    return deep


def _close(a, b) -> bool:
    return bool(np.isclose(float(a), float(b), rtol=1e-9, atol=1e-12,
                           equal_nan=True))


def _stats_match(got: np.ndarray, want) -> bool:
    want = np.asarray(want, dtype=np.float64)
    return want.shape == (3,) and all(_close(g, w)
                                      for g, w in zip(got, want))


def _derive_invariant(entry: dict, sums: Dict[str, list]):
    """(value, present) of one stamped invariant from per-field moment
    sums: moment 1 reads the plain sums, moment 2 the sums of squares
    (``Σ f^m`` over the invariant's fields)."""
    idx = 0 if int(entry.get("moment", 1)) == 1 else 2
    total = 0.0
    for f in entry.get("fields", ()):
        s = sums.get(f)
        if s is None or len(s) < 3:
            return 0.0, False
        total += float(s[idx])
    return total, True


def _invariants_ok(deep: dict) -> bool:
    """The drift half of deep verification: every stamped invariant
    whose reference is present must sit within its tolerance of that
    reference — the gate that refuses a generation saved from
    finite-but-poisoned state (its content stamps are self-consistent;
    its physics is not)."""
    for entry in deep.get("invariants") or ():
        ref, scale = entry.get("ref"), entry.get("scale")
        if ref is None:
            continue   # stamped before the run anchored its references
        value, present = _derive_invariant(entry, deep.get("sums", {}))
        if not present:
            return False
        tol = float(entry.get("tol", 1e-3))
        bound = tol * max(float(scale or 0.0), 1e-30)
        drift = value - float(ref)
        if entry.get("kind") == "bounded":
            if drift > bound:
                return False
        elif abs(drift) > bound:
            return False
    return True


def save_checkpoint(path, /, **fields) -> None:
    """Write the named grid fields and the grid geometry to `path` (.npz) —
    the legacy FLAT single-file format (see
    :func:`save_checkpoint_sharded` for the O(local) generation-directory
    format the resilience ring uses by default).

    Fields are full block-stacked global arrays (any stagger, any dtype);
    every process participates (multi-controller shards are exchanged over
    the runtime, root-biased — only process 0 assembles), process 0 writes.
    """
    import jax

    from .gather import _fetch_global

    shared.check_initialized()
    grid = shared.global_grid()
    if not fields:
        raise GridError("save_checkpoint: no fields given.")

    global _logged_flat_fallback
    if jax.process_count() > 1 and not _logged_flat_fallback:
        _logged_flat_fallback = True
        _log.debug(
            "igg.save_checkpoint: legacy flat-.npz checkpoint on a "
            "multi-controller run — the global array is assembled on "
            "process 0 only (root-biased chunked fetch; non-root host "
            "memory stays O(local)).  Prefer save_checkpoint_sharded / "
            "run_resilient(sharded=True): per-process shard writes, no "
            "global assembly anywhere.")

    t_start = time.monotonic()
    host: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    deep_sums: Dict[str, list] = {}
    gmeta = _meta(grid)
    for name, A in fields.items():
        if name == _META_KEY:
            raise GridError(f"save_checkpoint: field name {_META_KEY!r} is "
                            f"reserved.")
        dtypes[name] = str(np.dtype(A.dtype))
        arr = _fetch_global(A)   # None on non-root multi-controller ranks
        if arr is not None:
            arr = np.ascontiguousarray(arr)
            # Deep stamp over the TRUE-dtype array before byte-encoding:
            # verification decodes first, so the recompute matches.
            deep_sums[name] = _deep_sums_stacked(arr, gmeta).tolist()
            host[name] = _encode(arr)

    if jax.process_index() == 0:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        _sweep_stale_tmp(path.parent)
        meta = {**gmeta, "dtypes": dtypes,
                "deep": _deep_meta(deep_sums),
                "crc32": {name: _crc32(arr) for name, arr in host.items()}}
        _write_npz(path, {**host, _META_KEY: np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)})
    if jax.process_count() > 1:
        # Multi-controller: no process may return (and possibly reload the
        # file) before process 0 finished writing it.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_save_checkpoint")
    # Observability (igg.telemetry): flat-format write latency + bytes
    # (the assembled global payload — zero on non-root ranks).
    dur = time.monotonic() - t_start
    nbytes = int(sum(a.nbytes for a in host.values()))
    _telemetry.counter("igg_checkpoint_bytes_total").inc(nbytes)
    _telemetry.histogram("igg_checkpoint_write_seconds",
                         format="flat").observe(dur)
    _telemetry.emit("checkpoint_write", path=str(path), bytes=nbytes,
                    seconds=round(dur, 6), format="flat")


def load_checkpoint(path, /, *, redistribute: bool = False) -> Dict:
    """Read a checkpoint written by :func:`save_checkpoint` (flat `.npz`
    file) or :func:`save_checkpoint_sharded` (generation directory — the
    format is auto-detected) and return `{name: sharded jax.Array}` on the
    CURRENT grid.

    By default the current grid must have the geometry the checkpoint was
    written under (validated; `GridError` on mismatch).  With
    `redistribute=True` a checkpoint from a DIFFERENT decomposition is
    re-tiled onto the current grid (VERDICT r3 item 8): the saved blocks'
    overlaps are stripped (the `gather_interior` contract, via
    `numpy_retile`), the de-duplicated global interior is validated
    against the current grid's global sizes, and every target block —
    halo cells included — is reconstructed by global indexing with
    periodic wrap, which reproduces exactly what an `update_halo` on
    globally-consistent data would give, bit for bit.  Periodicity and
    per-array stagger must match; `dims`, local sizes, overlaps, and the
    device count may all differ.  On a sharded generation this ELASTIC
    restore streams shard-by-shard (a bounded cache of O(local) blocks) —
    no process ever materializes the global array; the flat path
    materializes the stacked array per process (legacy behavior)."""
    import jax

    from .fields import sharding_for

    shared.check_initialized()
    grid = shared.global_grid()
    path = pathlib.Path(path)
    if path.is_dir():
        return _load_sharded(path, grid, redistribute)
    meta, arrays = _read_verified(path)

    mine = _meta(grid)
    same_geometry = {k: meta.get(k) for k in mine} == mine
    if not same_geometry and not redistribute:
        diffs = {k: (meta.get(k), mine[k]) for k in mine
                 if meta.get(k) != mine[k]}
        raise GridError(
            f"load_checkpoint: grid geometry mismatch {diffs} "
            f"(checkpoint vs current).  Pass redistribute=True to re-tile "
            f"the checkpoint onto the current decomposition.")
    if not same_geometry and list(meta["periods"]) != mine["periods"]:
        raise GridError(
            f"load_checkpoint(redistribute=True): periodicity mismatch "
            f"{meta['periods']} vs {mine['periods']} — redistribution "
            f"changes the decomposition, not the physics.")

    dtypes = meta.get("dtypes", {})
    out = {}
    for name, arr in arrays.items():
        arr = _decode(arr, dtypes.get(name), path, name)
        if not same_geometry:
            arr = _redistribute(name, arr, meta, grid)
        out[name] = jax.device_put(arr, sharding_for(arr.ndim))
    return out


def _read_verified(path: pathlib.Path):
    """Read every entry of a checkpoint file and verify the per-array CRC32
    manifest.  Returns `(meta, arrays)`; raises `GridError` naming the path
    for anything unreadable — a missing file, a zip truncated by a crashed
    or preempted writer, a payload whose container checksum fails, or an
    array whose manifest CRC32 disagrees with its bytes."""
    import zipfile

    try:
        with np.load(path) as z:
            if _META_KEY not in z.files:
                raise GridError(
                    f"load_checkpoint: {path} has no {_META_KEY!r} entry — "
                    f"not an igg checkpoint (or one truncated before the "
                    f"manifest was written).")
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
            arrays = {k: z[k] for k in z.files if k != _META_KEY}
    except GridError:
        raise
    except (zipfile.BadZipFile, OSError, EOFError, ValueError, KeyError,
            json.JSONDecodeError) as e:
        raise GridError(
            f"load_checkpoint: cannot read checkpoint {path}: "
            f"{type(e).__name__}: {e} — the file is missing, truncated, or "
            f"corrupt (a crash mid-write never publishes a partial file; "
            f"this one was damaged after the fact or never completed on a "
            f"non-atomic filesystem).") from e

    crcs = meta.get("crc32", {})   # absent in pre-round-8 checkpoints
    for name, arr in arrays.items():
        want = crcs.get(name)
        if want is not None and _crc32(arr) != want:
            raise GridError(
                f"load_checkpoint: CRC32 mismatch for field {name!r} in "
                f"{path} ({_crc32(arr):#010x} != recorded {want:#010x}) — "
                f"the checkpoint is corrupt.")
    return meta, arrays


def _all_finite(arrays: Dict[str, np.ndarray]) -> bool:
    """The all-finite health gate over DECODED (true-dtype) arrays.
    Integral dtypes pass trivially; f/c AND the kind-'V' extension floats
    (bfloat16, float8_* — a kind check of "fc" would wave a NaN-poisoned
    bf16 field through) go through np.isfinite via ml_dtypes."""
    for arr in arrays.values():
        if arr.dtype.kind in "biu":
            continue               # integral: always finite
        try:
            ok = bool(np.isfinite(arr).all())
        except TypeError:          # dtype without isfinite support
            continue
        if not ok:
            return False
    return True


def verify_checkpoint(path, *, check_finite: bool = False,
                      part: Optional[Tuple[int, int]] = None,
                      deep: bool = False) -> bool:
    """Whether `path` is a readable, checksum-consistent checkpoint — a
    flat `.npz` file or a sharded generation directory (auto-detected).

    Reads every array and verifies the CRC32 manifest(s) (flat files
    written before the manifest existed verify structurally only; a
    sharded generation additionally requires its commit record — the
    manifest written last — and every listed shard present and
    summary-consistent).  With `check_finite=True`, additionally require
    every floating/complex field to be entirely finite — the health gate
    :mod:`igg.resilience` applies when choosing a rollback generation,
    since a checkpoint written between a NaN blowup and its detection is
    structurally perfect but poisoned.  `part=(i, n)` restricts a sharded
    verification to every n-th shard starting at i (the distributed
    round-robin of :func:`verify_checkpoint_distributed`; ignored for flat
    files, which have no shards to split).  Purely host-side (no grid
    needs to be initialized); peak staging on a sharded generation is one
    shard.

    `deep=True` (round 19) is STRICT numeric-integrity verification: the
    checkpoint must carry the deep stamp (per-field owned-cell moment
    sums — written by every post-round-19 save), every stamped sum must
    match a recompute from the stored arrays (refusing finite-valued
    corruption written consistently through the CRC layer, the
    ``igg.chaos.poison_checkpoint`` shape), and every stamped invariant
    reference must sit within its tolerance (refusing a generation saved
    from finite-but-POISONED state — its stamps are self-consistent, its
    physics drifted).  Pre-round-19 checkpoints have no stamp and verify
    False under `deep=True`; callers that *prefer* deep-verified
    generations scan deep first and fall back (the
    :mod:`igg.resilience` rollback contract)."""
    path = pathlib.Path(path)
    if path.is_dir():
        return _verify_sharded(path, check_finite=check_finite, part=part,
                               deep=deep)
    try:
        meta, arrays = _read_verified(path)
    except GridError:
        return False
    if not (check_finite or deep):
        return True
    dtypes = meta.get("dtypes", {})
    try:
        decoded = {n: _decode(a, dtypes.get(n), path, n)
                   for n, a in arrays.items()}
    except GridError:
        # A malformed dtypes manifest entry (version-skewed writer, damaged
        # meta — the CRC32 manifest covers arrays, not itself) must read as
        # "not a valid checkpoint", never kill the skip-corrupt fallback in
        # the callers.
        return False
    if check_finite and not _all_finite(decoded):
        return False
    if deep:
        dm = meta.get("deep")
        if not isinstance(dm, dict) or not isinstance(dm.get("sums"), dict):
            return False   # unstamped (pre-round-19): deep cannot vouch
        sums = dm["sums"]
        for n, a in decoded.items():
            if n not in sums or not _stats_match(
                    _deep_sums_stacked(a, meta), sums[n]):
                return False
        if not _invariants_ok(dm):
            return False
    return True


def verify_checkpoint_distributed(path, *, check_finite: bool = False,
                                  deep: bool = False) -> bool:
    """Collective variant of :func:`verify_checkpoint` for multi-controller
    runs: each process verifies a round-robin subset of a sharded
    generation's shards and the per-process verdicts are AND-combined, so
    a pod-scale verification reads every shard ONCE globally instead of
    once per process.  Must be called by every process (it is a
    collective) and — unlike the purely host-side
    :func:`verify_checkpoint` — needs the grid initialized on a
    multi-controller run, since the verdict combine is one tiny SPMD
    min-reduce over the grid mesh.  On a single process it is exactly
    :func:`verify_checkpoint`.  A flat-file checkpoint is read whole by
    every process (no shards to round-robin) but the verdict is STILL
    combined: callers treat the result as collective-consistent (all
    processes then load the same generation), and one process's transient
    read failure must make every process skip the generation, not just
    the one that saw it."""
    import jax

    path = pathlib.Path(path)
    nproc = int(jax.process_count())
    if nproc == 1:
        return verify_checkpoint(path, check_finite=check_finite, deep=deep)
    part = ((int(jax.process_index()), nproc) if path.is_dir() else None)
    ok = verify_checkpoint(path, check_finite=check_finite, part=part,
                           deep=deep)
    return _combine_verdicts(ok)


def _combine_min(val: int) -> int:
    """Minimum of a per-process int32 value across every process: each
    device of the grid mesh contributes its process's value and one
    compiled min-reduce replicates the result — an SPMD program over the
    mesh (works on every multi-controller backend), NOT
    `process_allgather` of a host value (unimplemented on some).  The
    combine primitive under both the verdict AND
    (:func:`_combine_verdicts`) and the agreed-step probes of the
    distributed generation scan (int32 so step numbers combine exactly;
    float32 rounds past 2**24)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    shared.check_initialized()
    grid = shared.global_grid()
    from .shared import AXIS_NAMES

    v = np.asarray([val], dtype=np.int32)
    arr = jax.make_array_from_callback(
        (grid.nprocs,),
        NamedSharding(grid.mesh, PartitionSpec(tuple(AXIS_NAMES))),
        lambda idx: v)
    out = shared.replicating_jit(
        jnp.min, NamedSharding(grid.mesh, PartitionSpec()))(arr)
    return int(np.asarray(out.addressable_shards[0].data))


def _combine_max(val: int) -> int:
    """Maximum across processes (min-reduce of the negation)."""
    return -_combine_min(-int(val))


def _combine_verdicts(ok: bool) -> bool:
    """AND a per-process verdict across every process (module comment at
    :func:`_combine_min`)."""
    return _combine_min(1 if ok else 0) > 0


def checkpoint_step(path) -> Optional[int]:
    """Step number encoded in a generation name (`<prefix>_<step>.npz`
    flat file or `<prefix>_<step>` sharded directory, the ring layouts
    :mod:`igg.resilience` writes); None for non-generation names (a
    `.tmp`-staged directory included — it is uncommitted)."""
    m = re.search(r"_(\d+)(?:\.npz)?$", pathlib.Path(path).name)
    return int(m.group(1)) if m else None


def list_generations(directory, prefix: str = "ckpt"):
    """All generations — flat files `{prefix}_<digits>.npz` and sharded
    directories `{prefix}_<digits>` — in `directory` as a `[(step, path),
    ...]` list sorted by step (strict name match: a sibling ring under a
    longer prefix like 'ckpt_b' never matches, and a `.tmp` staging
    directory is not a generation).  The single scan shared by
    :func:`latest_checkpoint` and the resilience ring's pruning/rollback,
    so the two can never disagree on what a generation is."""
    directory = pathlib.Path(directory)
    gens = []
    for p in directory.glob(f"{prefix}_*"):
        if re.fullmatch(re.escape(prefix) + r"_\d+(\.npz)?", p.name):
            gens.append((checkpoint_step(p), p))
    return sorted(gens)


def remove_generation(path) -> None:
    """Delete one generation, flat file or sharded directory (the unlink
    shared by the resilience ring's pruning and its fresh-run clearing).
    Already-gone paths are fine (another process pruned first)."""
    import shutil

    path = pathlib.Path(path)
    try:
        if path.is_dir():
            shutil.rmtree(path, ignore_errors=True)
        else:
            path.unlink()
    except OSError:
        pass


def prune_generations(directory, prefix: str, ring: int,
                      good_until: int) -> None:
    """The checkpoint-ring prune rule shared by :func:`igg.run_resilient`
    and :func:`igg.run_ensemble`: keep the newest `ring` generations PLUS
    the newest one at or below `good_until` — the health-established
    rollback target.  With a checkpoint cadence much shorter than the
    watch cadence, several unconfirmed (possibly poisoned) generations
    can land before the first probe is fetched, and a plain newest-R
    prune would rotate the only healthy target out of the ring."""
    gens = list_generations(directory, prefix)
    keep = {s for s, _ in gens[-ring:]}
    good = [s for s, _ in gens if s <= good_until]
    if good:
        keep.add(max(good))
    for s, p in gens:
        if s not in keep:
            remove_generation(p)


def latest_checkpoint(directory, prefix: str = "ckpt", *,
                      check_finite: bool = False,
                      distributed: bool = False,
                      max_step: Optional[int] = None,
                      deep: bool = False
                      ) -> Optional[pathlib.Path]:
    """Newest valid checkpoint generation in `directory`.

    Scans generations (flat `{prefix}_<step>.npz` files and sharded
    `{prefix}_<step>` directories) newest-first by the step encoded in the
    name and returns the first that passes :func:`verify_checkpoint` — a
    truncated/corrupt/uncommitted newest generation is skipped, falling
    back to the previous one.  Returns None when no valid generation
    exists.  `check_finite` additionally skips generations holding
    non-finite field values (resume-after-blowup safety); `max_step`
    restricts the scan to generations at that step or older (the rollback
    contract of :mod:`igg.resilience` — a generation younger than the
    failing probe is post-failure noise).

    `distributed=True` verifies each candidate through
    :func:`verify_checkpoint_distributed` (each process reads a round-robin
    subset of a sharded generation's shards instead of all of them).  It is
    then a COLLECTIVE: every process must call it, and — because directory
    listings can transiently diverge across hosts (NFS attribute caches) —
    the candidate sequence is NOT each process's own listing: each probed
    step is agreed globally first (a max-combine of the processes' newest
    remaining candidates), so every process executes the same collectives
    in the same order.  A generation one process cannot see verifies False
    there and the AND-combine skips it everywhere — conservative, never
    divergent.

    `deep=True` scans with STRICT deep verification (numeric-integrity
    stamps recomputed, invariant drift gated — see
    :func:`verify_checkpoint`); unstamped pre-round-19 generations are
    then skipped, so callers that merely PREFER deep-verified generations
    run a `deep=True` scan first and fall back to the plain scan — the
    mixed stamped/unstamped ordering contract of :mod:`igg.resilience`."""
    import jax

    gens = [(s, p) for s, p in list_generations(directory, prefix)
            if max_step is None or s <= max_step]
    if not distributed or int(jax.process_count()) == 1:
        # Every generation is a candidate — a step can hold BOTH artifacts
        # (a sharded directory and a stale flat file from a sharded=False
        # run); one of them failing must not mask the other.
        for _, p in reversed(gens):
            if (verify_checkpoint_distributed if distributed
                    else verify_checkpoint)(p, check_finite=check_finite,
                                            deep=deep):
                return p
        return None

    directory = pathlib.Path(directory)
    steps = {s for s, _ in gens}
    probe = None
    while True:
        below = probe if probe is not None else (
            max_step + 1 if max_step is not None else 2**31 - 1)
        mine = max((s for s in steps if s < below), default=-1)
        probe = _combine_max(mine)
        if probe < 0:
            return None
        # Both possible artifacts of the probed step are tried in a FIXED
        # order (directory first, then flat file) so every process
        # executes the same collectives; paths are constructed from the
        # step, not the listing, so an entry a stale listing missed is
        # still read.  An artifact any process cannot verify fails the
        # combine — conservative, never divergent — and a combined pass
        # guarantees every process verified (hence has) the SAME artifact.
        for cand in (directory / f"{prefix}_{probe:09d}",
                     directory / f"{prefix}_{probe:09d}.npz"):
            is_dir = cand.is_dir()
            ok = (cand.exists()
                  and verify_checkpoint(cand, check_finite=check_finite,
                                        deep=deep,
                                        part=((int(jax.process_index()),
                                               int(jax.process_count()))
                                              if is_dir else None)))
            if _combine_verdicts(ok):
                return cand
        steps.discard(probe)


def _redistribute(name: str, arr: np.ndarray, meta: dict, grid) -> np.ndarray:
    """Re-tile one saved stacked array from the checkpoint's decomposition
    onto `grid` (see :func:`load_checkpoint`)."""
    from .gather import numpy_retile
    from .shared import NDIMS

    ndim = min(arr.ndim, NDIMS)
    dims_s = list(meta["dims"][:ndim])
    nxyz_s = list(meta["nxyz"][:ndim])
    over_s = list(meta["overlaps"][:ndim])
    periods = list(meta["periods"][:ndim])

    local_s, ol_s = [], []
    for d in range(ndim):
        if arr.shape[d] % dims_s[d] != 0:
            raise GridError(
                f"load_checkpoint: field '{name}' dim {d} of size "
                f"{arr.shape[d]} is not divisible by the checkpoint's "
                f"dims[{d}]={dims_s[d]}.")
        local_s.append(arr.shape[d] // dims_s[d])
        ol_s.append(over_s[d] + (local_s[d] - nxyz_s[d]))

    interior = numpy_retile(
        arr, dims_s, local_s,
        [local_s[d] - max(ol_s[d], 0) for d in range(ndim)],
        [not periods[d] for d in range(ndim)])

    # Target geometry: the stagger (local - base) is decomposition-
    # independent; validate the de-duplicated global sizes agree.
    out = interior
    for d in range(ndim):
        df = local_s[d] - nxyz_s[d]
        s_b = grid.nxyz[d] + df
        ol_b = grid.overlaps[d] + df
        n_b = grid.dims[d]
        size = interior.shape[d]
        want = n_b * (s_b - ol_b) + (0 if periods[d] else ol_b)
        if size != want:
            raise GridError(
                f"load_checkpoint(redistribute=True): field '{name}' has "
                f"{size} unique cells along dim {d} but the current grid "
                f"needs {want}; the global physical domain must match.")
        # Stacked index j = c*s_b + i -> global interior index
        # c*(s_b - ol_b) + i (wrapped for periodic dims).
        idx = np.concatenate([
            (c * (s_b - ol_b) + np.arange(s_b)) % size if periods[d]
            else c * (s_b - ol_b) + np.arange(s_b)
            for c in range(n_b)])
        out = np.take(out, idx, axis=d)
    return out


# ---------------------------------------------------------------------------
# Sharded generation format (igg-sharded-v1)
# ---------------------------------------------------------------------------
#
# {path}/                      <- committed by renaming {path}.tmp/ into place
#   shard_00000.npz            <- one per grid block (cart rank), written by
#   shard_00001.npz               the process addressing that block; every
#   ...                           field's LOCAL block, halo cells included,
#                                 plus a per-shard __igg_meta__ CRC32 manifest
#   manifest.json              <- process 0, written LAST: the commit record
#                                 (grid geometry, per-field dtypes and local
#                                 shapes, per-shard CRC32 summaries)
#
# Fields of rank < 3 are replicated over the trailing mesh axes; their block
# lives in the shard of the rank with trailing coords 0, so exactly one
# process owns every shard file.


def _shard_name(rank: int) -> str:
    return f"shard_{rank:05d}.npz"


def _summary_crc(crcs: Dict[str, int]) -> int:
    """One CRC32 summarizing a shard's per-field CRC32 map — what the
    top-level manifest records per shard, tying each shard file to the
    generation that wrote it without re-hashing the array bytes."""
    return int(zlib.crc32(json.dumps(
        {k: int(v) for k, v in sorted(crcs.items())}).encode()))


def _ranks_for_field(grid, ndim: int):
    """Shard ranks holding blocks of a rank-`ndim` field: all coordinates
    over the first min(ndim, NDIMS) mesh axes, trailing coords 0."""
    nd = min(ndim, NDIMS)
    for coords in itertools.product(
            *[range(grid.dims[d]) for d in range(nd)]):
        yield grid.cart_rank(tuple(coords) + (0,) * (NDIMS - nd))


def _expected_shards(grid, field_ndims) -> List[int]:
    ranks = set()
    for nd in field_ndims:
        ranks.update(_ranks_for_field(grid, nd))
    return sorted(ranks)


def _local_block_refs(grid, fields) -> Dict[int, Dict[str, object]]:
    """{shard rank: {field: device-resident block}} for every block THIS
    process addresses.  References only — no device→host transfer happens
    here, so a caller (the background checkpoint writer) can poll readiness
    before fetching.  Lower-rank fields are replicated over the trailing
    mesh axes; only the copy on the trailing-coords-0 device is taken, so
    each shard file has exactly one writer."""
    devpos = {dev: pos for pos, dev in np.ndenumerate(grid.mesh.devices)}
    refs: Dict[int, Dict[str, object]] = {}
    for name, A in fields.items():
        local = grid.local_shape(A)
        nd = min(A.ndim, NDIMS)
        for sh in A.addressable_shards:
            pos = devpos.get(sh.device)
            if pos is None or any(pos[k] != 0 for k in range(nd, NDIMS)):
                continue   # a replica off the trailing-0 plane (or a device
                           # outside the grid mesh): not this shard's owner
            coords = tuple((sh.index[d].start or 0) // local[d]
                           for d in range(nd))
            rank = grid.cart_rank(coords + (0,) * (NDIMS - nd))
            refs.setdefault(rank, {})[name] = sh.data
    return refs


def _commit_timeout_s() -> float:
    from . import _env

    return _env.number("IGG_CKPT_COMMIT_TIMEOUT", 600)


def _await_files(base: pathlib.Path, names, what: str,
                 on_poll=None) -> None:
    """Poll a shared filesystem until every `base/name` exists (the
    cross-process coordination of the sharded commit — no device
    collectives, so it is safe from a background writer thread).
    `on_poll` runs once per poll round (process 0 answers late peer
    hellos with it).  Raises `GridError` naming the missing entries after
    `IGG_CKPT_COMMIT_TIMEOUT` seconds (default 600)."""
    import time

    deadline = time.monotonic() + _commit_timeout_s()
    missing = list(names)
    while True:
        if on_poll is not None:
            on_poll()
        missing = [n for n in missing if not (base / n).exists()]
        if not missing:
            return
        if time.monotonic() >= deadline:
            raise GridError(
                f"save_checkpoint_sharded: timed out after "
                f"{_commit_timeout_s():g}s (IGG_CKPT_COMMIT_TIMEOUT) waiting "
                f"for {len(missing)} {what} entr(ies) under {base} "
                f"(e.g. {missing[0]}) — a peer process died mid-write, or "
                f"the checkpoint directory is not a shared filesystem.")
        time.sleep(0.05)


def _write_atomic_text(p: pathlib.Path, text: str,
                       durable: bool = False) -> None:
    import os

    tmp = p.with_name(p.name + ".tmp")
    if durable:
        # Commit records (the fleet journal, the generation manifest
        # seal): fsync the tmp file BEFORE the atomic rename — without
        # it, a power cut can reorder the rename ahead of the data
        # blocks and leave a committed name pointing at torn bytes —
        # then fsync the directory so the rename itself survives.
        with open(tmp, "w") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, p)
        try:
            fd = os.open(p.parent, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass   # directory fsync unsupported here: best effort
        return
    tmp.write_text(text)
    os.replace(tmp, p)


def _ack_hellos(staging: pathlib.Path, token: str) -> None:
    """Process 0's side of the attempt handshake (module comment at
    `_HELLO`): answer every peer hello whose nonce is not yet acked with
    `ack_<p>` = nonce + this attempt's token.  Idempotent and cheap (one
    directory scan plus tiny atomic writes); called right after the
    staging dir is created, between process 0's own shard writes, and from
    every poll of the shard wait, so a peer arriving at any point before
    the seal gets answered."""
    try:
        entries = list(staging.iterdir())
    except OSError:
        return
    for e in entries:
        m = re.fullmatch(r"hello_(\d+)", e.name)
        if not m:
            continue
        try:
            nonce = e.read_text()
        except OSError:
            continue   # mid-replace; the next poll answers it
        ack = staging / _ACK.format(int(m.group(1)))
        try:
            if ack.read_text().split("\n", 1)[0] == nonce:
                continue   # this nonce is already answered
        except (OSError, ValueError):
            pass
        _write_atomic_text(ack, f"{nonce}\n{token}")


def _peer_handshake(staging: pathlib.Path, proc: int) -> str:
    """A non-root process's side of the attempt handshake (module comment
    at `_HELLO`): publish a fresh nonce as `hello_<proc>`, poll for the
    `ack_<proc>` echoing it, and return the attempt token the ack
    carries.  The hello is re-published whenever it is found missing or
    holding another nonce — process 0's stale-attempt clear sweeps any
    copy that landed in a dead attempt's staging dir — and an ack echoing
    any OTHER nonce (a dead attempt's leftover) is ignored, so only a
    process 0 that is live in THIS save can complete the handshake.
    Raises `GridError` after `IGG_CKPT_COMMIT_TIMEOUT` seconds."""
    import time
    import uuid

    nonce = uuid.uuid4().hex
    hello = staging / _HELLO.format(proc)
    ack = staging / _ACK.format(proc)
    deadline = time.monotonic() + _commit_timeout_s()
    while True:
        try:
            published = hello.read_text() == nonce
        except OSError:
            published = False
        if not published:
            try:
                _write_atomic_text(hello, nonce)
            except OSError:
                pass   # staging dir not created yet, or just cleared
        try:
            got, tok = ack.read_text().split("\n", 1)
            if got == nonce:
                # Confirm receipt: process 0 seals only after every peer's
                # `done` file, so no peer is left mid-handshake against a
                # staging dir that gets renamed away (module comment at
                # `_DONE`).  The dir is provably live here — the ack came
                # from a process 0 past its clear.
                _write_atomic_text(staging / _DONE.format(proc), nonce)
                return tok
        except (OSError, ValueError):
            pass
        if time.monotonic() >= deadline:
            raise GridError(
                f"save_checkpoint_sharded: timed out after "
                f"{_commit_timeout_s():g}s (IGG_CKPT_COMMIT_TIMEOUT) "
                f"waiting for process 0 to acknowledge this process's "
                f"attempt handshake under {staging} — process 0 died "
                f"before sealing this save, or the checkpoint directory "
                f"is not a shared filesystem.")
        time.sleep(0.05)


def _await_commit(path: pathlib.Path, token: str) -> None:
    """Poll until the generation at `path` is sealed by THIS attempt — a
    readable manifest whose ``attempt`` entry matches `token`.  Manifest
    presence alone is not enough: a previously committed generation can
    already sit at `path` while process 0 is still sealing the new one."""
    import time

    deadline = time.monotonic() + _commit_timeout_s()
    while True:
        try:
            man = json.loads((path / _MANIFEST).read_text())
            if man.get("attempt") == token:
                return
        except (OSError, json.JSONDecodeError):
            pass   # not committed yet (or mid-replace of the old gen)
        if time.monotonic() >= deadline:
            raise GridError(
                f"save_checkpoint_sharded: timed out after "
                f"{_commit_timeout_s():g}s (IGG_CKPT_COMMIT_TIMEOUT) waiting "
                f"for process 0 to commit {path} (attempt {token[:8]}…) — "
                f"process 0 died mid-seal, or the checkpoint directory is "
                f"not a shared filesystem.")
        time.sleep(0.05)


def save_checkpoint_sharded(path, /, **fields) -> None:
    """Write the named grid fields as a sharded generation DIRECTORY at
    `path` (module docstring for the format).  Every process writes only
    its own local blocks — one `shard_<rank>.npz` per grid block, staged
    one O(local) block at a time — and process 0 seals the generation with
    the manifest (written last) and the atomic `.tmp`-dir rename.  No
    process ever assembles the global array, and no device collectives are
    involved (multi-controller coordination is filesystem-based), so this
    is safe to call from a background writer thread."""
    import jax

    from .gather import _CHUNK_BYTES, _slabbed_get

    shared.check_initialized()
    grid = shared.global_grid()
    if not fields:
        raise GridError("save_checkpoint_sharded: no fields given.")
    for name in fields:
        if name == _META_KEY:
            raise GridError(f"save_checkpoint_sharded: field name "
                            f"{_META_KEY!r} is reserved.")
    path = pathlib.Path(path)
    if path.suffix == ".npz":
        raise GridError(
            "save_checkpoint_sharded: a sharded checkpoint is a DIRECTORY "
            "generation; pass a path without the .npz suffix "
            "(save_checkpoint writes the flat single-file format).")

    import os
    import shutil
    import uuid

    t_start = time.monotonic()
    written_bytes = 0   # this process's staged shard payload (pre-zip)
    proc0 = int(jax.process_index()) == 0
    staging = path.with_name(path.name + ".tmp")
    path.parent.mkdir(parents=True, exist_ok=True)
    if proc0:
        _sweep_stale_tmp(path.parent)
        # A staging dir already at this exact name is a dead attempt's
        # leftover (commits rename it away atomically, and a dead peer
        # process stalls the whole multi-controller job, so no live writer
        # can still own it).  Clear it BEFORE answering any peer hello: a
        # stale shard that survived here could otherwise satisfy the shard
        # wait below and be sealed — CRC-consistent but from the wrong
        # attempt — into the manifest.
        if staging.is_dir():
            # The clear can race a live peer's hello landing in the stale
            # dir (hellos precede any ack); retry-swept, not fatal.
            _rmtree_contended(staging)
        elif staging.exists():
            staging.unlink()
        staging.mkdir()
        token = uuid.uuid4().hex
        # Answer peers that already said hello so their shard writes
        # overlap ours; late arrivals are answered between our own shard
        # writes and from the shard-wait polls below.
        _ack_hellos(staging, token)
    else:
        # Peers write nothing until the handshake proves process 0 has
        # cleared stale attempts and issued THIS attempt's token — writing
        # earlier would race the clear above and lose fresh shards to the
        # rmtree (and a stale token would desynchronize the commit wait).
        token = _peer_handshake(staging, int(jax.process_index()))

    dtypes = {n: str(np.dtype(A.dtype)) for n, A in fields.items()}
    local_shapes = {n: [int(v) for v in grid.local_shape(A)]
                    for n, A in fields.items()}
    refs = _local_block_refs(grid, fields)
    gmeta = _meta(grid)
    my_crcs: Dict[int, Dict[str, int]] = {}
    my_deep: Dict[int, Dict[str, list]] = {}
    for rank in sorted(refs):
        # One shard at a time: fetch (largest-dim slabs above _CHUNK_BYTES),
        # CRC, write, release — peak host staging is one block set.
        host: Dict[str, np.ndarray] = {}
        crcs: Dict[str, int] = {}
        deep: Dict[str, list] = {}
        coords = grid.cart_coords(rank)
        for name in sorted(refs[rank]):
            raw = np.ascontiguousarray(
                _slabbed_get(refs[rank][name], _CHUNK_BYTES))
            # Deep stamp: owned-cell moment sums of the TRUE-dtype block
            # (verification decodes before recomputing, so they match).
            deep[name] = _deep_stats(
                raw[_owned_slice(raw.shape, coords, gmeta)]).tolist()
            arr = _encode(raw)
            crcs[name] = _crc32(arr)
            host[name] = arr
            written_bytes += arr.nbytes
        smeta = {"shard": rank, "coords": list(coords),
                 "dtypes": {n: dtypes[n] for n in host}, "crc32": crcs,
                 "deep": deep}
        my_deep[rank] = deep
        _write_npz(staging / _shard_name(rank), {
            **host, _META_KEY: np.frombuffer(
                json.dumps(smeta).encode(), dtype=np.uint8)})
        my_crcs[rank] = crcs
        if proc0:
            _ack_hellos(staging, token)   # answer peers between our writes

    expected = _expected_shards(grid, [A.ndim for A in fields.values()])
    if proc0:
        # Peers write their shards to the shared filesystem; wait for the
        # full set (each published atomically, so visible == complete;
        # the entry clear above guarantees every file here is THIS
        # attempt's) AND for every peer's handshake confirmation — a peer
        # owning no shard files must still complete its handshake before
        # the staging dir is renamed away.  Then seal: manifest written
        # last, then the commit rename.  The handshake files have done
        # their job and do not belong in the committed format.
        _await_files(staging,
                     [_shard_name(r) for r in expected]
                     + [_DONE.format(p)
                        for p in range(1, int(jax.process_count()))],
                     "shard/handshake",
                     on_poll=lambda: _ack_hellos(staging, token))
        shards = {}
        deep_sums: Dict[str, np.ndarray] = {}
        deep_whole = True
        for r in expected:
            crcs = my_crcs.get(r)
            deep = my_deep.get(r)
            if crcs is None:
                peer = _read_shard_meta(staging / _shard_name(r))
                crcs = peer.get("crc32", {})
                deep = peer.get("deep")
            shards[_shard_name(r)] = _summary_crc(crcs)
            # Manifest deep stamp: element-wise sums of the per-shard
            # owned-cell partials.  A shard without one (a version-skewed
            # peer writer) drops the manifest stamp entirely — a partial
            # stamp would verify against a lie.
            if deep is None:
                deep_whole = False
            elif deep_whole:
                for name, stats in deep.items():
                    acc = deep_sums.setdefault(
                        name, np.zeros(3, dtype=np.float64))
                    acc += np.asarray(stats, dtype=np.float64)
        for e in list(staging.iterdir()):
            if re.fullmatch(r"(hello_\d+|ack_\d+|done_\d+)(\.tmp)?", e.name):
                e.unlink()
        manifest = {"format": _FORMAT, **gmeta, "dtypes": dtypes,
                    "local_shapes": local_shapes, "shards": shards,
                    "attempt": token}
        if deep_whole:
            manifest["deep"] = _deep_meta(
                {n: s.tolist() for n, s in deep_sums.items()})
        # durable=True: the manifest IS the generation's commit record —
        # fsync before the rename, so a power cut mid-seal can never
        # leave a manifest name pointing at torn bytes (the same
        # treatment as the fleet queue journal).
        _write_atomic_text(staging / _MANIFEST, json.dumps(manifest),
                           durable=True)
        # Commit.  `os.replace` cannot atomically replace a non-empty
        # directory, so an existing committed generation at `path` is
        # RENAMED aside (atomic) rather than deleted in place: the crash
        # window in which neither the old nor the new generation sits at
        # `path` is two renames, not an rmtree of a many-GB shard set —
        # and the aside copy (a `.tmp` name, so the stale-staging sweep
        # reclaims it after a crash) still holds the old committed data
        # until the new generation is in place.
        if path.exists():
            aside = path.with_name(path.name + ".old.tmp")
            if aside.is_dir():
                shutil.rmtree(aside)
            elif aside.exists():
                aside.unlink()
            os.replace(path, aside)
            os.replace(staging, path)
            remove_generation(aside)
        else:
            os.replace(staging, path)
    else:
        # No process may return (and possibly reload the generation) before
        # it is committed — and only THIS attempt's commit counts: a
        # committed generation already sitting at `path` (e.g. resuming a
        # replay over an earlier, possibly poisoned, save of the same step)
        # carries a different token and keeps the wait pending.
        _await_commit(path, token)
    # Observability (igg.telemetry): bytes staged by THIS process +
    # end-to-end write latency, commit/handshake waits included.
    dur = time.monotonic() - t_start
    _telemetry.counter("igg_checkpoint_bytes_total").inc(written_bytes)
    _telemetry.histogram("igg_checkpoint_write_seconds",
                         format="sharded").observe(dur)
    _telemetry.emit("checkpoint_write", path=str(path),
                    bytes=int(written_bytes), seconds=round(dur, 6),
                    format="sharded")


def _rmtree_contended(path, attempts: int = 8) -> None:
    """`shutil.rmtree` that survives a CONCURRENT file creation inside the
    tree: clearing a dead attempt's staging directory can race a live
    peer's hello write (peers publish their hello before any ack gates
    them), which surfaces as ENOTEMPTY/EEXIST from the final rmdir.  Each
    retry sweeps the newcomers too; anything else propagates."""
    import errno
    import shutil
    import time as _t

    for i in range(attempts):
        try:
            shutil.rmtree(path)
            return
        except FileNotFoundError:
            return
        except OSError as e:
            if (e.errno not in (errno.ENOTEMPTY, errno.EEXIST)
                    or i == attempts - 1):
                raise
            _t.sleep(0.01)


def _read_shard_meta(p: pathlib.Path) -> dict:
    """Just the `__igg_meta__` entry of one shard file (a central-directory
    seek plus one small member — the array payloads are not read)."""
    import zipfile

    try:
        with np.load(p) as z:
            if _META_KEY not in z.files:
                raise GridError(
                    f"checkpoint shard {p} has no {_META_KEY!r} entry — not "
                    f"an igg shard (or truncated before its manifest).")
            return json.loads(bytes(z[_META_KEY].tobytes()).decode())
    except GridError:
        raise
    except (zipfile.BadZipFile, OSError, EOFError, ValueError, KeyError,
            json.JSONDecodeError) as e:
        raise GridError(
            f"cannot read checkpoint shard {p}: {type(e).__name__}: {e} — "
            f"missing, truncated, or corrupt.") from e


def _read_shard(gen: pathlib.Path, fname: str, man: Optional[dict] = None):
    """Read and fully verify one shard file of a generation: per-field
    CRC32s against the shard's own manifest (REQUIRED in the sharded
    format), the summary CRC against the generation manifest, and — when
    `man` is given — shapes against the recorded local shapes.  Returns
    `(shard_meta, {field: np array in its TRUE dtype})`; raises `GridError`
    naming the path for anything inconsistent."""
    import zipfile

    p = gen / fname
    try:
        with np.load(p) as z:
            if _META_KEY not in z.files:
                raise GridError(
                    f"load_checkpoint: shard {p} has no {_META_KEY!r} entry "
                    f"— not an igg shard (or truncated).")
            smeta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
            arrays = {k: z[k] for k in z.files if k != _META_KEY}
    except GridError:
        raise
    except (zipfile.BadZipFile, OSError, EOFError, ValueError, KeyError,
            json.JSONDecodeError) as e:
        raise GridError(
            f"load_checkpoint: cannot read shard {p}: {type(e).__name__}: "
            f"{e} — missing, truncated, or corrupt (an uncommitted or "
            f"damaged generation).") from e

    crcs = smeta.get("crc32", {})
    for name, arr in arrays.items():
        want = crcs.get(name)
        if want is None or _crc32(arr) != want:
            raise GridError(
                f"load_checkpoint: CRC32 mismatch for field {name!r} in "
                f"shard {p} — the shard is corrupt.")
    if man is not None:
        if _summary_crc(crcs) != man["shards"].get(fname):
            raise GridError(
                f"load_checkpoint: shard {p} disagrees with the generation "
                f"manifest (summary CRC32) — the shard belongs to a "
                f"different write or was swapped.")
    dt = (man or smeta).get("dtypes", {})
    out = {}
    for name, arr in arrays.items():
        arr = _decode(arr, dt.get(name), p, name)
        if man is not None:
            want_shape = tuple(man.get("local_shapes", {}).get(name, arr.shape))
            if tuple(arr.shape) != want_shape:
                raise GridError(
                    f"load_checkpoint: field {name!r} in shard {p} has "
                    f"shape {tuple(arr.shape)}, manifest says {want_shape} "
                    f"— the shard is inconsistent with its generation.")
        out[name] = arr
    return smeta, out


def _read_manifest_verified(path: pathlib.Path) -> dict:
    """The generation manifest — the commit record.  A directory without
    one is an UNCOMMITTED generation (crashed between shard writes and the
    seal) and reads as invalid, exactly like a truncated flat file."""
    mp = path / _MANIFEST
    try:
        man = json.loads(mp.read_text())
    except FileNotFoundError:
        raise GridError(
            f"load_checkpoint: {path} has no {_MANIFEST} — an uncommitted "
            f"(crashed or preempted mid-commit) sharded generation, not a "
            f"valid checkpoint.") from None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        raise GridError(
            f"load_checkpoint: cannot read {mp}: {type(e).__name__}: {e} — "
            f"the generation manifest is corrupt.") from e
    if man.get("format") != _FORMAT:
        raise GridError(
            f"load_checkpoint: {mp} has format {man.get('format')!r}, "
            f"expected {_FORMAT!r}.")
    for key in ("nxyz", "dims", "overlaps", "periods", "nprocs", "dtypes",
                "local_shapes", "shards"):
        if key not in man:
            raise GridError(
                f"load_checkpoint: {mp} is missing the {key!r} entry — the "
                f"generation manifest is corrupt.")
    return man


def _verify_sharded(path: pathlib.Path, *, check_finite: bool,
                    part: Optional[Tuple[int, int]] = None,
                    deep: bool = False) -> bool:
    """Directory branch of :func:`verify_checkpoint`: manifest present and
    well-formed, every (selected) shard present, readable, and CRC- and
    summary-consistent; `check_finite` gates each shard's decoded arrays —
    one shard in memory at a time.

    `deep=True` additionally requires the round-19 integrity stamps:
    every (selected) shard's owned-cell moment sums must match a
    recompute from its decoded blocks, the manifest must carry the
    summed stamp, and every stamped invariant reference must hold
    (:func:`_invariants_ok`).  The invariant check is pure manifest
    arithmetic, so a `part`-restricted distributed verification still
    gates it on every process."""
    try:
        man = _read_manifest_verified(path)
    except GridError:
        return False
    deep_man = man.get("deep") if deep else None
    if deep and (not isinstance(deep_man, dict)
                 or not isinstance(deep_man.get("sums"), dict)):
        return False   # unstamped (pre-round-19 or skewed-writer) gen
    if deep and not _invariants_ok(deep_man):
        return False
    names = sorted(man["shards"])
    if part is not None:
        i, n = part
        names = names[i::n]
    for fname in names:
        try:
            smeta, arrays = _read_shard(path, fname, man)
        except GridError:
            return False
        if check_finite and not _all_finite(arrays):
            return False
        if deep:
            stamped = smeta.get("deep")
            if not isinstance(stamped, dict):
                return False
            coords = smeta.get("coords")
            if coords is None:
                return False
            for n2, a in arrays.items():
                if n2 not in stamped or not _stats_match(
                        _deep_stats(a[_owned_slice(a.shape, coords, man)]),
                        stamped[n2]):
                    return False
    return True


class _ShardCache:
    """Bounded LRU of decoded, verified shard files — the streaming unit of
    the sharded load paths.  Peak host staging is `limit` shards plus the
    one target block being assembled, never the global array."""

    def __init__(self, path: pathlib.Path, man: dict, limit: int = 4):
        import threading

        self._path, self._man, self._limit = path, man, limit
        self._cache: Dict[str, Dict[str, np.ndarray]] = {}
        # The restore callback may be driven concurrently by the runtime;
        # the LRU bookkeeping is not atomic without this.
        self._lock = threading.Lock()

    def get(self, rank: int) -> Dict[str, np.ndarray]:
        fname = _shard_name(rank)
        with self._lock:
            if fname in self._cache:
                self._cache[fname] = self._cache.pop(fname)   # LRU touch
                return self._cache[fname]
        if fname not in self._man["shards"]:
            raise GridError(
                f"load_checkpoint: generation {self._path} has no shard "
                f"{fname} — the manifest does not cover this block.")
        _, arrays = _read_shard(self._path, fname, self._man)
        with self._lock:
            while len(self._cache) >= self._limit:
                self._cache.pop(next(iter(self._cache)))
            self._cache[fname] = arrays
        return arrays


def _elastic_params(name: str, src_local, tgt_local, man: dict, grid):
    """Per-sharded-dim re-tiling parameters from the checkpoint's
    decomposition onto the live grid — the same plane algebra as the flat
    :func:`_redistribute`, validated up front: de-duplicated global sizes
    must agree (the physical domain is decomposition-invariant)."""
    params = []
    for d in range(min(len(tgt_local), NDIMS)):
        df = src_local[d] - man["nxyz"][d]
        s_s, s_b = src_local[d], tgt_local[d]
        ol_s = man["overlaps"][d] + df
        ol_b = grid.overlaps[d] + df
        n_s, n_b = man["dims"][d], grid.dims[d]
        periodic = bool(man["periods"][d])
        keep_s = s_s - max(ol_s, 0)
        size = n_s * keep_s + (0 if periodic else max(ol_s, 0))
        want = n_b * (s_b - ol_b) + (0 if periodic else ol_b)
        if size != want:
            raise GridError(
                f"load_checkpoint(redistribute=True): field '{name}' has "
                f"{size} unique cells along dim {d} but the current grid "
                f"needs {want}; the global physical domain must match.")
        params.append(dict(keep_s=keep_s, size=size, stride_b=s_b - ol_b,
                           n_s=n_s, s_b=s_b, periodic=periodic))
    return params


def _assemble_block(name: str, cache: _ShardCache, man: dict, params,
                    coords, tgt_local, dtype) -> np.ndarray:
    """Reconstruct ONE target block (halo cells included) of a field from
    the source shards, by global indexing.  Target stacked index `i` of
    block `c` is global interior cell `g = c*(s_b - ol_b) + i` (wrapped on
    periodic dims); cell `g` is owned by source block
    `min(g // keep_s, n_s - 1)` at local index `g - c_src*keep_s` — the
    inverse of the `gather_interior` de-duplication.  This reproduces
    exactly what the flat `_redistribute` materializes globally, one
    O(local) block at a time: interior bit-exact, halos as an `update_halo`
    on globally-consistent data would give (periodic wrap included), and
    open-boundary user-owned halo planes preserved (the edge blocks' outer
    planes ARE de-duplicated global cells)."""
    nds = len(params)
    maps = []
    for d, p in enumerate(params):
        g = coords[d] * p["stride_b"] + np.arange(p["s_b"])
        if p["periodic"]:
            g %= p["size"]
        c_src = np.minimum(g // p["keep_s"], p["n_s"] - 1)
        maps.append((c_src, g - c_src * p["keep_s"]))
    out = np.empty(tuple(tgt_local), dtype=dtype)
    dims_s = man["dims"]
    for combo in itertools.product(
            *[np.unique(m[0]).tolist() for m in maps]):
        pos = [np.nonzero(maps[d][0] == combo[d])[0] for d in range(nds)]
        c3 = tuple(int(c) for c in combo) + (0,) * (NDIMS - nds)
        rank_s = c3[0] + c3[1] * dims_s[0] + c3[2] * dims_s[0] * dims_s[1]
        S = cache.get(rank_s)[name]
        sel = tuple(maps[d][1][pos[d]] for d in range(nds))
        out[np.ix_(*pos)] = S[np.ix_(*sel)]
    return out


def _load_sharded(path: pathlib.Path, grid, redistribute: bool) -> Dict:
    """Directory branch of :func:`load_checkpoint`: every process restores
    its own blocks shard-by-shard (same geometry: a 1:1 shard read per
    block; different geometry: the elastic per-block assembly), through
    `jax.make_array_from_callback` so each block lands directly on its
    device — the global array is never materialized."""
    import jax

    from .fields import sharding_for, stacked_shape

    man = _read_manifest_verified(path)
    mine = _meta(grid)
    same_geometry = {k: man.get(k) for k in mine} == mine
    if not same_geometry and not redistribute:
        diffs = {k: (man.get(k), mine[k]) for k in mine
                 if man.get(k) != mine[k]}
        raise GridError(
            f"load_checkpoint: grid geometry mismatch {diffs} "
            f"(checkpoint vs current).  Pass redistribute=True to re-tile "
            f"the sharded generation onto the current decomposition "
            f"(elastic restore).")
    if not same_geometry and list(man["periods"]) != mine["periods"]:
        raise GridError(
            f"load_checkpoint(redistribute=True): periodicity mismatch "
            f"{man['periods']} vs {mine['periods']} — redistribution "
            f"changes the decomposition, not the physics.")

    # Size the LRU to the SOURCE shards this process's blocks touch: the
    # load loop below is field-outer, so each field's callbacks sweep the
    # same source ranks in the same order — a smaller cache would evict
    # every shard right before its next-field reuse and re-read (and
    # re-CRC) the whole set once per field.  On an elastic shrink restore
    # each target block overlaps ~ceil(n_src/n_tgt) source shards, each
    # ~n_tgt/n_src the target block's size, so the bound stays
    # O(this process's blocks) in BYTES even when it exceeds the block
    # count — never the global array.
    nlocal = sum(1 for dev in grid.mesh.devices.flat
                 if dev.process_index == jax.process_index())
    n_src = max(1, len(man["shards"]))
    per_block = -(-n_src // max(1, int(grid.nprocs)))   # ceil
    cache = _ShardCache(path, man, limit=max(4, nlocal * per_block))
    out = {}
    for name in sorted(man["local_shapes"]):
        src_local = [int(v) for v in man["local_shapes"][name]]
        nd = len(src_local)
        nds = min(nd, NDIMS)
        tgt_local = [grid.nxyz[d] + (src_local[d] - man["nxyz"][d])
                     if d < NDIMS else src_local[d] for d in range(nd)]
        if any(s < 1 for s in tgt_local):
            raise GridError(
                f"load_checkpoint: field '{name}' has local shape "
                f"{tgt_local} on the current grid — the stagger recorded "
                f"in {path} does not fit it.")
        dtype = np.dtype(man["dtypes"][name])
        gshape = tuple(stacked_shape(tgt_local, grid))
        params = (None if same_geometry
                  else _elastic_params(name, src_local, tgt_local, man, grid))

        def cb(index, name=name, nds=nds, tgt_local=tgt_local,
               params=params, dtype=dtype):
            coords = tuple((index[d].start or 0) // tgt_local[d]
                           for d in range(nds))
            if params is None:
                rank = grid.cart_rank(coords + (0,) * (NDIMS - nds))
                return cache.get(rank)[name]
            return _assemble_block(name, cache, man, params, coords,
                                   tgt_local, dtype)

        out[name] = jax.make_array_from_callback(
            gshape, sharding_for(nd, grid), cb)
    return out
