"""3-D staggered-grid Stokes solver (pseudo-transient iteration).

BASELINE config 5 ("3-D staggered-grid Stokes solver with comm/compute
overlap").  The classic ParallelStencil-style miniapp the reference is used
with: cell-centered pressure and normal stresses, face-staggered velocities,
edge-staggered shear stresses, iterated to steady state with pseudo-time
damping.  Per iteration the pressure and
velocities are exchanged — grouped into one call (`/root/reference/src/update_halo.jl:19-20`); the whole
iteration is one SPMD program, so XLA overlaps the three ppermute pairs with
the interior stress/velocity updates (the structural analog of
ParallelStencil's `@hide_communication`, `/root/reference/README.md:9`).

Buoyancy-driven setup: a dense spherical inclusion in a periodic box drives
a convection cell; the solver relaxes momentum + continuity residuals.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    mu: float = 1.0          # viscosity
    rho_g: float = 1.0       # buoyancy contrast of the inclusion
    lx: float = 10.0
    ly: float = 10.0
    lz: float = 10.0
    vdamp: float = 4.0       # velocity damping (pseudo-transient accelerator)

    def spacing(self) -> Tuple[float, float, float]:
        return igg.tools.spacing(self.lx, self.ly, self.lz)


def init_fields(params: Params = Params(), dtype=np.float32):
    """Pressure/velocities at rest; buoyancy from a spherical inclusion."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny, nz = grid.nxyz
    dx, dy, dz = params.spacing()

    P = igg.zeros((nx, ny, nz), dtype=dtype)
    X, Y, Z = (a.astype(dtype) for a in igg.coord_fields(dx, dy, dz, P))
    r2 = ((X - params.lx / 2) ** 2 + (Y - params.ly / 2) ** 2
          + (Z - params.lz / 2) ** 2)
    Rho = params.rho_g * jnp.exp(-r2) + 0 * P   # smooth inclusion
    Vx = igg.zeros((nx + 1, ny, nz), dtype=dtype)
    Vy = igg.zeros((nx, ny + 1, nz), dtype=dtype)
    Vz = igg.zeros((nx, ny, nz + 1), dtype=dtype)
    return P, Vx, Vy, Vz, Rho


def iteration_core(P, Vx, Vy, Vz, Rho, *, dx, dy, dz, mu, dtP, dtV,
                   buoy_axis: int = 2):
    """The raw coupled arithmetic shared VERBATIM by the XLA path and the
    fused Pallas kernel (`igg.ops.stokes_pallas`) — one source of truth, so
    the two paths agree to Mosaic-vs-XLA rounding (~1 ulp).  Returns the
    full-shape updated pressure and the *interior* velocity increments
    `(P', rx, ry, rz)`; callers apply the increments with
    :func:`igg.ops.interior_add` (XLA) or interior ref writes (kernel).

    `buoy_axis` names the axis whose velocity the buoyancy term drives
    (physical z by default).  The arithmetic is otherwise symmetric under a
    y<->z swap of axes, fields, and spacings, which the fused kernel's
    transposed z-window send-plane computation exploits with
    `buoy_axis=1`."""
    # Divergence at cell centers
    divV = ((Vx[1:, :, :] - Vx[:-1, :, :]) / dx
            + (Vy[:, 1:, :] - Vy[:, :-1, :]) / dy
            + (Vz[:, :, 1:] - Vz[:, :, :-1]) / dz)
    P = P - dtP * divV

    # Deviatoric normal stresses at centers
    txx = 2.0 * mu * ((Vx[1:, :, :] - Vx[:-1, :, :]) / dx - divV / 3.0)
    tyy = 2.0 * mu * ((Vy[:, 1:, :] - Vy[:, :-1, :]) / dy - divV / 3.0)
    tzz = 2.0 * mu * ((Vz[:, :, 1:] - Vz[:, :, :-1]) / dz - divV / 3.0)

    # Shear stresses on interior edges (no halo needed: computed locally
    # from halo-valid velocities, used only for interior velocity updates)
    txy = mu * ((Vx[1:-1, 1:, :] - Vx[1:-1, :-1, :]) / dy
                + (Vy[1:, 1:-1, :] - Vy[:-1, 1:-1, :]) / dx)
    txz = mu * ((Vx[1:-1, :, 1:] - Vx[1:-1, :, :-1]) / dz
                + (Vz[1:, :, 1:-1] - Vz[:-1, :, 1:-1]) / dx)
    tyz = mu * ((Vy[:, 1:-1, 1:] - Vy[:, 1:-1, :-1]) / dz
                + (Vz[:, 1:, 1:-1] - Vz[:, :-1, 1:-1]) / dy)

    # Momentum residuals on interior faces
    rx = ((txx[1:, 1:-1, 1:-1] - txx[:-1, 1:-1, 1:-1]) / dx
          + (txy[:, 1:, 1:-1] - txy[:, :-1, 1:-1]) / dy
          + (txz[:, 1:-1, 1:] - txz[:, 1:-1, :-1]) / dz
          - (P[1:, 1:-1, 1:-1] - P[:-1, 1:-1, 1:-1]) / dx)
    ry = ((tyy[1:-1, 1:, 1:-1] - tyy[1:-1, :-1, 1:-1]) / dy
          + (txy[1:, :, 1:-1] - txy[:-1, :, 1:-1]) / dx
          + (tyz[1:-1, :, 1:] - tyz[1:-1, :, :-1]) / dz
          - (P[1:-1, 1:, 1:-1] - P[1:-1, :-1, 1:-1]) / dy)
    rz = ((tzz[1:-1, 1:-1, 1:] - tzz[1:-1, 1:-1, :-1]) / dz
          + (txz[1:, 1:-1, :] - txz[:-1, 1:-1, :]) / dx
          + (tyz[1:-1, 1:, :] - tyz[1:-1, :-1, :]) / dy
          - (P[1:-1, 1:-1, 1:] - P[1:-1, 1:-1, :-1]) / dz)
    if buoy_axis == 2:                                   # buoyancy drives Vz
        rz = rz + 0.5 * (Rho[1:-1, 1:-1, 1:] + Rho[1:-1, 1:-1, :-1])
    else:                  # transposed windows: physical z sits on axis 1
        ry = ry + 0.5 * (Rho[1:-1, 1:, 1:-1] + Rho[1:-1, :-1, 1:-1])
    return P, dtV * rx, dtV * ry, dtV * rz


def compute_iteration(P, Vx, Vy, Vz, Rho, *, dx, dy, dz, mu, dtP, dtV,
                      buoy_axis: int = 2):
    """The pure coupled update (no halo exchange): pressure then velocities,
    interior cells only — shift-invariant, so it applies both full-domain
    and to the boundary slabs of :func:`igg.hide_communication`.  Effective
    stencil radius is 2 (Gauss-Seidel flavor: the velocity updates read the
    freshly-updated pressure, which itself reads velocities at +-1)."""
    from igg.ops import interior_add

    P, dVx, dVy, dVz = iteration_core(P, Vx, Vy, Vz, Rho, dx=dx, dy=dy,
                                      dz=dz, mu=mu, dtP=dtP, dtV=dtV,
                                      buoy_axis=buoy_axis)
    Vx = interior_add(Vx, dVx)
    Vy = interior_add(Vy, dVy)
    Vz = interior_add(Vz, dVz)
    return P, Vx, Vy, Vz


def local_iteration(P, Vx, Vy, Vz, Rho, *, dx, dy, dz, mu, dtP, dtV,
                    overlap: bool = False, use_pallas: bool = False,
                    pallas_interpret: bool = False, assembly=None):
    """One pseudo-transient iteration over per-device local arrays.

    With `overlap=False`: compute, then one grouped exchange for everything
    that crosses device boundaries (multi-field pipelining,
    `/root/reference/src/update_halo.jl:19-20`).  With `overlap=True` the
    iteration is restructured by :func:`igg.hide_communication` (multi-field
    form) so the exchanges are data-independent of the full-domain stencils;
    the radius-2 update chain requires a grid initialized with
    overlap >= 3 (BASELINE config 5: "Stokes solver with comm/compute
    overlap").  With `use_pallas=True` the whole iteration (compute + the
    grouped halo update) runs as ONE fused kernel
    (`igg.ops.fused_stokes_iteration`, any mesh); it raises `GridError`
    when the kernel is inapplicable (the auto-fallback lives in
    :func:`make_iteration`)."""
    kw = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
    if use_pallas:
        from igg.ops import fused_stokes_iteration

        if overlap:
            raise igg.GridError(
                "the fused Stokes iteration has overlap "
                "(hide_communication) semantics built in; drop "
                "overlap=True when passing use_pallas.")
        _pallas_applicable(True, P, interpret=pallas_interpret)  # or raises
        return fused_stokes_iteration(P, Vx, Vy, Vz, Rho, **kw,
                                      interpret=pallas_interpret)
    if overlap:
        return igg.hide_communication(
            (P, Vx, Vy, Vz),
            lambda P, Vx, Vy, Vz, Rho: compute_iteration(P, Vx, Vy, Vz, Rho,
                                                         **kw),
            Rho, radius=2, assembly=assembly)
    P, Vx, Vy, Vz = compute_iteration(P, Vx, Vy, Vz, Rho, **kw)
    return igg.update_halo_local(P, Vx, Vy, Vz, assembly=assembly)


_PALLAS_REQ = (
    "the fused Stokes iteration requires TPU devices (or "
    "pallas_interpret=True), an overlap-3 grid, f32 fields with local "
    "shape divisible into x-slabs (x % 8 == 0, x >= 16, y >= 8, z >= 8), "
    "and in compiled mode a y*z area small enough that some slab height's "
    "windows fit the VMEM budget (igg.ops.stokes_pallas._vmem_need); use "
    "the XLA path otherwise.")

_TRAPEZOID_REQ = (
    "the K-iteration Stokes chunk tier requires the fused per-iteration "
    "kernel's prerequisites (TPU devices or pallas_interpret=True, "
    "overlap-3 grid, f32 fields) plus: n_inner >= K+1 (one warm-up "
    "iteration + at least one full chunk), tile-aligned local shape "
    "(x % 8 == 0, y % 8 == 0, z % 128 == 0), 2K-deep send slabs inside "
    "every split dimension's block, and a VMEM-resident working set for "
    "the five 2K-extended fields "
    "(igg.ops.stokes_trapezoid.stokes_trapezoid_supported); use "
    "trapezoid='auto' or the per-iteration kernel otherwise.")

_BANDED_REQ = (
    "the streaming banded Stokes chunk tier requires the fused "
    "per-iteration kernel's prerequisites (TPU devices or "
    "pallas_interpret=True, overlap-3 grid, f32 fields) plus: "
    "n_inner >= K+1, banded geometry (band B >= 8, B % 8 == 0, extended "
    "x span divisible into >= 2 bands), 2K-deep send slabs inside every "
    "split dimension's block, and a rolling band window set within the "
    "VMEM budget (igg.ops.stokes_trapezoid.stokes_banded_supported); "
    "use banded='auto' or the resident tiers otherwise.")


def _pallas_applicable(use_pallas, P, interpret: bool = False) -> bool:
    from igg.ops import stokes_pallas_supported

    from ._dispatch import pallas_applicable

    # `pallas_applicable` threads `interpret` into the gate (no Mosaic,
    # no VMEM budget there), so large-y*z grids stay interpret-runnable.
    return pallas_applicable(use_pallas, P,
                             supported_fn=stokes_pallas_supported,
                             requirement=_PALLAS_REQ, interpret=interpret)


def _pseudo_steps(params: Params):
    dx, dy, dz = params.spacing()
    n_min = min(igg.nx_g(), igg.ny_g(), igg.nz_g())
    dtV = min(dx, dy, dz) ** 2 / params.mu / 8.1 / params.vdamp
    dtP = 4.1 * params.mu / n_min
    return dict(dx=dx, dy=dy, dz=dz, mu=params.mu, dtP=dtP, dtV=dtV)


def make_iteration(params: Params = Params(), *, donate: bool = True,
                   overlap="auto", n_inner: int = 1,
                   use_pallas="auto", pallas_interpret: bool = False,
                   trapezoid="auto", K: int = None, banded="auto",
                   band: int = None, verify=None, tune=None):
    """Compiled `(P, Vx, Vy, Vz, Rho) -> (P, Vx, Vy, Vz)` advancing
    `n_inner` iterations in one SPMD program.  `use_pallas`: "auto"
    (default) uses the fused kernel when it applies — TPU devices,
    overlap-3 grid, f32 fields, any device count/periodicity; False forces
    the portable shard_map/XLA path; True requires the kernel and raises if
    inapplicable.  `overlap` restructures the XLA path with
    `igg.hide_communication` ("auto" follows the `IGG_OVERLAP` knob, then
    the autotuner's cached winner — the Gauss-Seidel iteration has radius
    2, so admission needs an overlap-3 grid); the fused kernel has overlap
    semantics built in, so it satisfies both settings.

    `trapezoid` admits the K-iteration temporal-blocking chunk tier
    (`igg.ops.stokes_trapezoid`) on top of the fused kernel: "auto"
    (default) engages it when `stokes_trapezoid_supported` admits some K
    (one warm-up per-iteration kernel, `(n_inner-1) // K` chunks, the
    remainder through the per-iteration kernel); False pins the
    per-iteration kernel; True requires the chunk tier and raises
    `GridError` when inapplicable.  `K` overrides the auto-fitted chunk
    depth (`fit_stokes_K`).

    The factory's dispatch is the family's degradation ladder
    (`igg.degrade`): `stokes3d.trapezoid` → `stokes3d.mosaic` (the
    per-iteration fused kernel) → `stokes3d.xla` (the composition truth),
    so a quarantined chunk tier falls to the per-iteration kernel and a
    quarantined kernel falls to pure XLA.  `verify="first_use"` (or
    `IGG_VERIFY_KERNELS=1`) numerically checks each fast tier against the
    truth before it serves traffic.  `tune` consults the autotuner's
    cached winner for this signature ("auto"/True/False, default the
    `IGG_TUNE` knob; `igg.autotune`): a hit supplies the chunk depth `K`
    (and band depth `band`) and may pin the tier when the caller left
    the defaults.

    `banded` admits the STREAMING banded chunk tier
    (`igg.ops.stokes_trapezoid.fused_stokes_banded_iters` — rolling VMEM
    window, HBM ping-pong; the ladder rung below the resident
    trapezoid): "auto" (default) engages it only where the resident
    tier's `fit_stokes_K` refuses (the VMEM K-bound at headline
    shapes), True requires it, False pins the resident tiers.  `band`
    overrides the auto-fitted band depth B (`fit_stokes_band`)."""
    from jax import lax

    from igg.overlap import resolve_overlap

    from ._dispatch import apply_tuned

    (K, K_from_cache, band, band_from_cache, trapezoid, banded,
     use_pallas, tuned) = apply_tuned(
        "stokes3d", tune, n_inner=n_inner, interpret=pallas_interpret,
        K=K, chunk_knob=trapezoid, use_pallas=use_pallas, band=band,
        banded_knob=banded)
    overlap = resolve_overlap(overlap, family="stokes3d", tuned=tuned,
                              radius=2,
                              chunk_active=(trapezoid is True
                                            or banded is True))

    kw = _pseudo_steps(params)
    dx, dy, dz = kw["dx"], kw["dy"], kw["dz"]
    mu, dtP, dtV = kw["mu"], kw["dtP"], kw["dtV"]
    # NOTE: the step closures capture only hashable scalars so recreated
    # closures share one compiled program (`igg.parallel._fn_key`).

    def build_xla(assembly):
        def xla_it(P, Vx, Vy, Vz, Rho):
            return lax.fori_loop(
                0, n_inner,
                lambda _, S: local_iteration(*S, Rho, dx=dx, dy=dy, dz=dz,
                                             mu=mu, dtP=dtP, dtV=dtV,
                                             overlap=overlap,
                                             assembly=assembly),
                (P, Vx, Vy, Vz))

        return igg.sharded(xla_it,
                           donate_argnums=(0, 1, 2, 3) if donate else ())

    from ._dispatch import measured_assembly_path

    xla_path = measured_assembly_path(
        build_xla, tag=f"stokes3d:{n_inner}:{overlap}:{donate}",
        wrap=lambda fn: lambda P, Vx, Vy, Vz, Rho: (*fn(P, Vx, Vy, Vz, Rho),
                                                    Rho))

    if trapezoid is True and use_pallas is False:
        raise igg.GridError(_TRAPEZOID_REQ)
    if banded is True and use_pallas is False:
        raise igg.GridError(_BANDED_REQ)
    if trapezoid is True or banded is True:
        use_pallas = True    # the chunk tiers ride the fused kernel

    donate_argnums = (0, 1, 2, 3) if donate else ()

    def _fit_K(grid, lshape, dtype):
        """The chunk depth the trapezoid tier will run (0 when none
        applies) — shared by the tier's admission gate and its traced
        body so the two can never disagree."""
        from igg.ops.stokes_trapezoid import (fit_stokes_K,
                                              stokes_trapezoid_supported)

        from ._dispatch import resolve_chunk_K

        if trapezoid is False or n_inner < 3:
            return 0
        return resolve_chunk_K(
            K, K_from_cache,
            lambda k: stokes_trapezoid_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype,
                interpret=pallas_interpret),
            lambda: fit_stokes_K(grid, tuple(lshape), n_inner - 1, dtype,
                                 interpret=pallas_interpret))

    def _fit_band(grid, lshape, dtype):
        """The `(K, B)` config the streaming banded tier will run (None
        when none applies) — shared by the tier's admission gate and its
        traced body so the two can never disagree."""
        from igg.ops.stokes_trapezoid import (fit_stokes_band,
                                              stokes_banded_supported)

        from ._dispatch import resolve_band

        if banded is False or n_inner < 3:
            return None
        return resolve_band(
            K, band, K_from_cache or band_from_cache,
            lambda k, b: stokes_banded_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype, B=b,
                interpret=pallas_interpret),
            lambda bands: fit_stokes_band(grid, tuple(lshape),
                                          n_inner - 1, dtype,
                                          interpret=pallas_interpret,
                                          bands=bands))

    def admit_trapezoid(args):
        from igg.degrade import Admission
        from igg.ops import stokes_pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            # The chunk tier rides the fused kernel: an explicit XLA pin
            # must reach the truth rung, not a Pallas-backed chunk (the
            # per-step tiers' probe enforces this for them; round 16
            # closed the same hole here).
            return Admission.no("use_pallas=False pins the XLA path")
        if trapezoid is False:
            return Admission.no("trapezoid=False pins the per-iteration "
                                "kernel")
        if banded is True:
            return Admission.no("banded=True pins the streaming banded "
                                "tier")
        # Non-raising base probe ("auto", never the forced form): the
        # chunk tier rides the fused kernel, but a use_pallas=True refusal
        # belongs to the mosaic rung.
        base = pallas_applicable("auto", args[0],
                                 supported_fn=stokes_pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-iteration kernel (the chunk "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        P = args[0]
        if not _fit_K(grid, grid.local_shape_any(P), P.dtype):
            return Admission.no(
                "no chunk depth K admissible "
                "(igg.ops.stokes_trapezoid_supported)")
        return Admission.yes()

    def build_trapezoid():
        from igg.ops import fused_stokes_iteration
        from igg.ops.stokes_trapezoid import fused_stokes_trapezoid_iters

        def trap_it(P, Vx, Vy, Vz, Rho):
            # Built inside the closure: the cells must stay hashable
            # scalars so recreated closures share one compiled program
            # (`igg.parallel._fn_key`, see the NOTE above).
            kw_it = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
            grid = igg.get_global_grid()
            Kf = _fit_K(grid, P.shape, P.dtype)   # local block inside sharded
            if not Kf:    # admission gate and trace share _fit_K
                raise igg.GridError(_TRAPEZOID_REQ)
            # Warm-up per-iteration kernel: consumes (and replaces) the
            # entry halos exactly like every other path — the
            # exchange-fresh window state the chunk's validity argument
            # requires, for ANY input.
            state = fused_stokes_iteration(
                P, Vx, Vy, Vz, Rho, **kw_it, interpret=pallas_interpret)
            *state, done = fused_stokes_trapezoid_iters(
                *state, Rho, n_inner=n_inner - 1, K=Kf, **kw_it,
                interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-iteration kernel
                state = lax.fori_loop(
                    0, n,
                    lambda _, S: fused_stokes_iteration(
                        *S, Rho, **kw_it, interpret=pallas_interpret),
                    tuple(state))
            return tuple(state)

        return igg.sharded(trap_it, donate_argnums=donate_argnums,
                           check_vma=not pallas_interpret)

    def admit_banded(args):
        from igg.degrade import Admission
        from igg.ops import stokes_pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        if banded is False:
            return Admission.no("banded=False pins the resident tiers")
        base = pallas_applicable("auto", args[0],
                                 supported_fn=stokes_pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-iteration kernel (the banded "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        P = args[0]
        lshape = grid.local_shape_any(P)
        if banded == "auto":
            if trapezoid is False:
                return Admission.no("trapezoid=False pins the "
                                    "per-iteration kernel (pass "
                                    "banded=True to require the "
                                    "streaming tier)")
            if _fit_K(grid, lshape, P.dtype):
                return Admission.no(
                    "the resident chunk tier serves this shape (the "
                    "banded rung engages where fit_stokes_K refuses)")
        if not _fit_band(grid, lshape, P.dtype):
            return Admission.no(
                "no banded config (K, B) admissible "
                "(igg.ops.stokes_trapezoid.stokes_banded_supported)")
        return Admission.yes()

    def build_banded():
        from igg.ops import fused_stokes_iteration
        from igg.ops.stokes_trapezoid import fused_stokes_banded_iters

        def banded_it(P, Vx, Vy, Vz, Rho):
            kw_it = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
            grid = igg.get_global_grid()
            kb = _fit_band(grid, P.shape, P.dtype)
            if not kb:    # admission gate and trace share _fit_band
                raise igg.GridError(_BANDED_REQ)
            Kf, Bf = kb
            # Warm-up per-iteration kernel: the exchange-fresh entry
            # state the chunk validity argument requires.
            state = fused_stokes_iteration(
                P, Vx, Vy, Vz, Rho, **kw_it, interpret=pallas_interpret)
            *state, done = fused_stokes_banded_iters(
                *state, Rho, n_inner=n_inner - 1, K=Kf, B=Bf, **kw_it,
                interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-iteration kernel
                state = lax.fori_loop(
                    0, n,
                    lambda _, S: fused_stokes_iteration(
                        *S, Rho, **kw_it, interpret=pallas_interpret),
                    tuple(state))
            return tuple(state)

        return igg.sharded(banded_it, donate_argnums=donate_argnums,
                           check_vma=not pallas_interpret)

    def build_pallas_steps():
        from igg.ops import fused_stokes_iteration

        def pallas_it(P, Vx, Vy, Vz, Rho):
            kw_it = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
            return lax.fori_loop(
                0, n_inner,
                lambda _, S: fused_stokes_iteration(
                    *S, Rho, **kw_it, interpret=pallas_interpret),
                (P, Vx, Vy, Vz))

        return pallas_it

    from igg.degrade import Tier
    from igg.ops import stokes_pallas_supported

    from ._dispatch import auto_dispatch

    trap_tier = Tier(name="stokes3d.trapezoid", rung=0,
                     build=build_trapezoid, admit=admit_trapezoid,
                     required=trapezoid is True, requirement=_TRAPEZOID_REQ)
    banded_tier = Tier(name="stokes3d.banded", rung=0,
                       build=build_banded, admit=admit_banded,
                       required=banded is True, requirement=_BANDED_REQ)
    return auto_dispatch(
        use_pallas=use_pallas, interpret=pallas_interpret,
        supported_fn=stokes_pallas_supported, requirement=_PALLAS_REQ,
        xla_path=xla_path, build_pallas_steps=build_pallas_steps,
        donate_argnums=donate_argnums,
        family="stokes3d", verify=verify,
        extra_tiers=(trap_tier, banded_tier))


def run(n_iters: int, params: Params = Params(), dtype=np.float32,
        overlap="auto", n_inner: int = 1, use_pallas="auto"):
    """Slope-timed relaxation (see :func:`igg.time_steps`); returns fields
    and seconds/iteration."""
    P, Vx, Vy, Vz, Rho = init_fields(params, dtype=dtype)
    it = make_iteration(params, overlap=overlap, n_inner=n_inner,
                        use_pallas=use_pallas)
    n1 = max(1, n_iters // 4)
    state, sec = igg.time_steps(
        lambda P, Vx, Vy, Vz, Rho: it(P, Vx, Vy, Vz, Rho) + (Rho,),
        (P, Vx, Vy, Vz, Rho), n1=n1, n2=max(n_iters - n1, n1 + 1))
    return state, sec / n_inner
