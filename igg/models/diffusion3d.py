"""3-D heat diffusion — the flagship model.

TPU-native re-implementation of the reference's de-facto integration benchmark
(`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl`):
Fourier-law fluxes on a staggered grid, conservative temperature update, halo
exchange each step.  The whole step (fluxes + update + halo ppermutes)
compiles to ONE XLA program per device via `igg.sharded`, with the temperature
buffer donated so the update is in-place in HBM; XLA's latency-hiding
scheduler overlaps the halo collectives with interior compute — the built-in
analog of ParallelStencil's `@hide_communication`
(`/root/reference/README.md:9`).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    lam: float = 1.0        # thermal conductivity
    cp_min: float = 1.0     # minimal heat capacity
    lx: float = 10.0        # domain length in x
    ly: float = 10.0
    lz: float = 10.0

    def spacing(self) -> Tuple[float, float, float]:
        return igg.tools.spacing(self.lx, self.ly, self.lz)

    def timestep(self) -> float:
        dx, dy, dz = self.spacing()
        # CFL-type bound of the reference example
        # (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:40`).
        return min(dx * dx, dy * dy, dz * dz) * self.cp_min / self.lam / 8.1


def init_fields(params: Params = Params(), dtype=np.float32):
    """Heat capacity and temperature with Gaussian anomalies, built from
    global coordinates so every device holds globally-consistent data
    (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:33-37`)."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny, nz = grid.nxyz
    dx, dy, dz = params.spacing()
    lx, ly, lz = params.lx, params.ly, params.lz

    T0 = igg.zeros((nx, ny, nz), dtype=dtype)
    X, Y, Z = igg.coord_fields(dx, dy, dz, T0)
    X, Y, Z = (a.astype(dtype) for a in (X, Y, Z))
    Cp = (params.cp_min
          + 5 * jnp.exp(-(X - lx / 1.5) ** 2 - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
          + 5 * jnp.exp(-(X - lx / 3.0) ** 2 - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
          + 0 * T0)
    T = (100 * jnp.exp(-((X - lx / 2) / 2) ** 2 - ((Y - ly / 2) / 2) ** 2
                       - ((Z - lz / 3.0) / 2) ** 2)
         + 50 * jnp.exp(-((X - lx / 2) / 2) ** 2 - ((Y - ly / 2) / 2) ** 2
                        - ((Z - lz / 1.5) / 2) ** 2)
         + 0 * T0)
    return T, Cp


def compute_step(T, Cp, *, dx, dy, dz, dt, lam):
    """The pure stencil update (no halo exchange): conservative interior
    temperature update; boundary planes keep their stale values (the
    reference's no-write semantics).

    Physics of the reference example — Fourier-law fluxes on staggered inner
    faces plus ∂T/∂t = 1/cp ∇·(λ∇T)
    (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:41-48`)
    — algebraically re-associated for TPU: with constant λ the staggered flux
    divergence telescopes to the 7-point Laplacian, so the whole update is ONE
    fused XLA pass (read T, read Cp, write T).  The flux form as written in
    the reference materializes three face-flux temporaries (measured 2.2 GB of
    HBM traffic per step at 256³ instead of ~0.8 GB — the same reason the
    reference's own CuArray-broadcast version is ">10x" slower than its
    hand-fused kernels, `/root/reference/README.md:161`).

    Shift-invariant and radius-1, so it is usable both full-domain and on the
    boundary slabs of :func:`igg.hide_communication`.  The arithmetic lives in
    :func:`igg.ops.diffusion_compute`, shared with the fused Pallas step."""
    from igg.ops import diffusion_compute

    return diffusion_compute(
        T, float(dt * lam) / Cp, rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
        rdz2=1.0 / (dz * dz))


def local_step(T, Cp, *, dx, dy, dz, dt, lam, overlap: bool = False,
               assembly="xla"):
    """One diffusion step over per-device local arrays (the user-model of the
    reference: physics written for a single device's block).  With
    `overlap=True` the step is restructured by :func:`igg.hide_communication`
    so the halo collectives are data-independent of the full-domain stencil
    and XLA can overlap them (ParallelStencil's `@hide_communication`,
    `/root/reference/README.md:9`).

    `assembly` defaults to "xla" for standalone use (for this radius-1
    single-field step, XLA fuses the halo select chain into the stencil's
    output pass — measured 0.70 ms vs 1.12 ms with the Pallas writer at
    256^3); the compiled paths (:func:`make_multi_step`) override it with
    a per-signature measured choice instead of trusting this hint."""
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, lam=lam)
    if overlap:
        return igg.hide_communication(
            T, lambda Tb, Cpb: compute_step(Tb, Cpb, **kw), Cp,
            assembly=assembly)
    return igg.update_halo_local(compute_step(T, Cp, **kw),
                                 assembly=assembly)


def make_member_step(params: Params = Params()):
    """Per-member LOCAL step over the `{"T", "Cp"}` state dict — the
    :func:`igg.run_ensemble` contract (the step is vmapped over the member
    axis inside one `shard_map` program, so it must be the local-arrays
    form, not an `igg.sharded`-wrapped whole-mesh program).  A member
    state may also carry a per-member scalar `"dt_scale"` field (a swept
    parameter): the timestep becomes `dt * dt_scale` for that member.

    The XLA assembly path is pinned: inside the vmapped ensemble program
    the halo select chain fuses into the stencil output pass exactly like
    the composed single-member step (the measured round-6 choice)."""
    dx, dy, dz = params.spacing()
    dt, lam = params.timestep(), params.lam
    rdx2, rdy2, rdz2 = 1.0 / (dx * dx), 1.0 / (dy * dy), 1.0 / (dz * dz)

    def member_step(st):
        from igg.ops import diffusion_compute

        # The coefficient is formed per member (dt_scale may be a traced
        # per-member scalar, so the composed-step float() shortcut of
        # `compute_step` does not apply here).
        coeff = dt * lam
        if "dt_scale" in st:
            coeff = coeff * st["dt_scale"]
        T = diffusion_compute(st["T"], coeff / st["Cp"], rdx2=rdx2,
                              rdy2=rdy2, rdz2=rdz2)
        out = dict(st)
        out["T"] = igg.update_halo_local(T, assembly="xla")
        return out

    return member_step


_PALLAS_REQ = (
    "the fused Pallas step requires TPU devices (or interpret=True), "
    "an overlap-2 grid, and an f32 unstaggered field with local "
    "shape divisible into x-slabs (x % 4 == 0, y >= 8, z >= 128).")


def _pallas_applicable(use_pallas, T, interpret: bool = False) -> bool:
    from igg.ops import pallas_supported

    from ._dispatch import pallas_applicable

    return pallas_applicable(use_pallas, T, supported_fn=pallas_supported,
                             requirement=_PALLAS_REQ, interpret=interpret)


def _best_bx(S0: int) -> int:
    # 8 measured fastest at 256^3 on v5e for the mega-kernel path (the
    # per-step kernel is flat across 8..32); see
    # benchmarks/results/pallas_sweep.jsonl.
    for b in (8, 16, 4, 2):
        if S0 % b == 0:
            return b
    return 1


_BANDED_REQ = (
    "the streaming banded diffusion chunk tier requires the fused "
    "per-step kernel's prerequisites (TPU devices or "
    "pallas_interpret=True, overlap-2 grid, f32 field) plus: "
    "n_inner >= K+1, banded geometry (band B >= 8, B % 8 == 0, extended "
    "x span divisible into >= 2 bands), K-deep send slabs inside every "
    "split dimension's block, and a rolling band window set within the "
    "VMEM budget (igg.ops.diffusion_trapezoid."
    "diffusion_banded_supported); use banded='auto' or the resident "
    "paths otherwise.")


def make_step(params: Params = Params(), *, donate: bool = True,
              use_pallas="auto", overlap="auto",
              pallas_interpret: bool = False, verify=None, tune=None):
    """Compiled whole-step function `(T, Cp) -> T` over the grid mesh.

    `use_pallas`: "auto" (default) uses the fused Pallas kernel
    (`igg.ops.fused_diffusion_step`) when it applies (TPU devices, overlap-2
    grid, f32 unstaggered field — any device count / periodicity); False
    forces the portable shard_map/XLA path; True requires the kernel and
    raises if inapplicable.
    `overlap`: restructure the XLA step with `igg.hide_communication` (the
    Pallas step has overlap semantics built in — its halo exchange is always
    data-independent of the main kernel).  "auto" (default) follows the
    `IGG_OVERLAP` knob, then the autotuner's cached winner, else off
    (`igg.overlap.resolve_overlap`).
    `pallas_interpret`: run the kernel in interpret mode (testing on CPU).
    `verify`: "first_use" numerically checks the fused tier against the
    XLA composition before it serves traffic (`igg.degrade`; defaults to
    the `IGG_VERIFY_KERNELS` environment knob).
    """
    return make_multi_step(1, params, donate=donate, use_pallas=use_pallas,
                           overlap=overlap, pallas_interpret=pallas_interpret,
                           verify=verify, tune=tune)


def make_multi_step(n_inner: int, params: Params = Params(), *,
                    donate: bool = True, use_pallas="auto",
                    overlap="auto", pallas_interpret: bool = False,
                    bx: int = None, banded="auto", K: int = None,
                    band: int = None, verify=None, tune=None):
    """Compiled `(T, Cp) -> T` advancing `n_inner` steps in ONE XLA program
    (`lax.fori_loop` around the step, halo ppermutes included).  This is the
    TPU-idiomatic time loop: host dispatch overhead amortizes to zero, and
    XLA schedules collectives of step k+1 against compute of step k.  The
    reference instead re-dispatches kernels + MPI calls from the host every
    step (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:41-48`).

    Both paths (fused Pallas kernel / portable XLA) compile through
    :func:`igg.sharded` into one SPMD program over the grid mesh.

    `tune` consults the autotuner's cached winner for this signature
    ("auto"/True/False, default the `IGG_TUNE` knob; `igg.autotune`):
    a hit supplies the slab/chunk depth `bx` and may pin the tier when
    the caller left the defaults — K is then searched, not fixed, and
    the winner's persisted overlap axis resolves `overlap="auto"`.

    `banded` admits the STREAMING banded chunk tier
    (`igg.ops.diffusion_trapezoid.fused_diffusion_banded_steps` —
    rolling VMEM window of band depth B, HBM ping-pong): "auto"
    (default) engages it only where the resident fused realizations
    (the mega kernel and the resident trapezoid chunk) both refuse —
    the VMEM K-bound at headline shapes; True requires it; False pins
    the resident paths.  `K`/`band` override the auto-fitted chunk
    depth and band depth (`fit_diffusion_band`)."""
    from jax import lax

    from igg.overlap import resolve_overlap

    from ._dispatch import apply_tuned

    (K, K_from_cache, band, band_from_cache, _, banded,
     use_pallas, tuned) = apply_tuned(
        "diffusion3d", tune, n_inner=n_inner, interpret=pallas_interpret,
        K=K, chunk_knob="auto", use_pallas=use_pallas, band=band,
        banded_knob=banded)
    if bx is None and tuned and tuned.get("bx"):
        bx = int(tuned["bx"])
    overlap = resolve_overlap(overlap, family="diffusion3d", tuned=tuned,
                              radius=1, chunk_active=banded is True)

    dx, dy, dz = params.spacing()
    dt = params.timestep()
    lam = params.lam
    # NOTE: the step closures capture only hashable scalars so recreated
    # closures share one compiled program (`igg.parallel._fn_key`).

    rdx2, rdy2, rdz2 = 1.0 / (dx * dx), 1.0 / (dy * dy), 1.0 / (dz * dz)
    dt_lam = float(dt * lam)

    def build_xla(assembly):
        def xla_steps(T, Cp):
            from igg.ops import diffusion_compute

            # Loop-invariant coefficient: hoists the per-element divide out
            # of the time loop (same trick as the Pallas path).
            A = dt_lam / Cp
            comp = lambda Tb, Ab: diffusion_compute(Tb, Ab, rdx2=rdx2,
                                                    rdy2=rdy2, rdz2=rdz2)

            def one(T):
                if overlap:
                    return igg.hide_communication(T, comp, A,
                                                  assembly=assembly)
                return igg.update_halo_local(comp(T, A), assembly=assembly)

            return lax.fori_loop(0, n_inner, lambda _, T: one(T), T)

        return igg.sharded(xla_steps, donate_argnums=(0,) if donate else ())

    from ._dispatch import measured_assembly_path

    # assembly strategy: measured once per signature ("xla" historically
    # wins this composed radius-1 single-field step; the writers win
    # standalone/multi-field — no more hard-coded hint).
    xla_path = measured_assembly_path(
        build_xla, tag=f"diffusion3d:{n_inner}:{overlap}:{donate}",
        wrap=lambda fn: lambda T, Cp: (fn(T, Cp), Cp))

    def build_pallas_steps():
        from igg.ops import fused_diffusion_steps
        bx_ = bx or _best_bx(igg.get_global_grid().nxyz[0])

        def pallas_steps(T, Cp):
            return fused_diffusion_steps(
                T, Cp, n_inner=n_inner, dx=dx, dy=dy, dz=dz, dt=dt,
                lam=lam, bx=bx_, interpret=pallas_interpret)

        return pallas_steps

    if banded is True and use_pallas is False:
        raise igg.GridError(_BANDED_REQ)
    if banded is True:
        use_pallas = True    # the streaming tier rides the fused kernel

    def _fit_band(grid, lshape, dtype):
        """The `(K, B)` config the streaming banded tier will run (None
        when none applies) — shared by the tier's admission gate and its
        traced body so the two can never disagree."""
        from igg.ops.diffusion_trapezoid import (
            diffusion_banded_supported, fit_diffusion_band)

        from ._dispatch import resolve_band

        if banded is False or n_inner < 3:
            return None
        return resolve_band(
            K, band, K_from_cache or band_from_cache,
            lambda k, b: diffusion_banded_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype, B=b,
                interpret=pallas_interpret),
            lambda bands: fit_diffusion_band(
                grid, tuple(lshape), n_inner - 1, dtype,
                interpret=pallas_interpret, bands=bands))

    def admit_banded(args):
        from igg.degrade import Admission
        from igg.ops import pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        if banded is False:
            return Admission.no("banded=False pins the resident paths")
        base = pallas_applicable("auto", args[0],
                                 supported_fn=pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-step kernel (the banded "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        T = args[0]
        lshape = grid.local_shape_any(T)
        bx_ = bx or _best_bx(grid.nxyz[0])
        if banded == "auto":
            from igg.ops.diffusion_mega import mega_supported
            from igg.ops.diffusion_pallas import _single_device_modes
            from igg.ops.diffusion_trapezoid import trapezoid_supported

            if _single_device_modes(grid) is not None and mega_supported(
                    tuple(lshape), bx_, n_inner, pallas_interpret,
                    dtype=T.dtype):
                return Admission.no(
                    "the resident mega kernel serves this shape (the "
                    "banded rung engages where the resident fused "
                    "realizations refuse)")
            if trapezoid_supported(grid, tuple(lshape), bx_, n_inner - 1,
                                   T.dtype, allow_open=True):
                return Admission.no(
                    "the resident trapezoid chunk serves this shape "
                    "(the banded rung engages where the resident fused "
                    "realizations refuse)")
        if not _fit_band(grid, lshape, T.dtype):
            return Admission.no(
                "no banded config (K, B) admissible "
                "(igg.ops.diffusion_trapezoid.diffusion_banded_supported)")
        return Admission.yes()

    def build_banded():
        from igg.ops import fused_diffusion_step
        from igg.ops.diffusion_trapezoid import fused_diffusion_banded_steps

        def banded_steps(T, Cp):
            grid = igg.get_global_grid()
            kb = _fit_band(grid, T.shape, T.dtype)
            if not kb:    # admission gate and trace share _fit_band
                raise igg.GridError(_BANDED_REQ)
            Kf, Bf = kb
            bx_ = bx or _best_bx(grid.nxyz[0])
            A = dt_lam / Cp    # loop-invariant coefficient
            # Warm-up per-step kernel: the exchange-fresh entry state the
            # chunk validity argument requires (the trapezoid contract).
            T = fused_diffusion_step(T, Cp, dx=dx, dy=dy, dz=dz, dt=dt,
                                     lam=lam, bx=bx_,
                                     interpret=pallas_interpret)
            T, done = fused_diffusion_banded_steps(
                T, A, n_inner=n_inner - 1, K=Kf, B=Bf, grid=grid,
                rdx2=rdx2, rdy2=rdy2, rdz2=rdz2,
                interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-step kernel
                T = lax.fori_loop(
                    0, n,
                    lambda _, T: fused_diffusion_step(
                        T, Cp, dx=dx, dy=dy, dz=dz, dt=dt, lam=lam,
                        bx=bx_, interpret=pallas_interpret),
                    T)
            return T

        return igg.sharded(banded_steps,
                           donate_argnums=(0,) if donate else (),
                           check_vma=not pallas_interpret)

    from igg.degrade import Tier
    from igg.ops import pallas_supported

    from ._dispatch import auto_dispatch

    banded_tier = Tier(name="diffusion3d.banded", rung=0,
                       build=build_banded, admit=admit_banded,
                       required=banded is True,
                       requirement=_BANDED_REQ)
    return auto_dispatch(
        use_pallas=use_pallas, interpret=pallas_interpret,
        supported_fn=pallas_supported, requirement=_PALLAS_REQ,
        xla_path=xla_path, build_pallas_steps=build_pallas_steps,
        donate_argnums=(0,) if donate else (),
        family="diffusion3d", verify=verify,
        extra_tiers=(banded_tier,))


# Numeric-integrity declaration (igg.integrity, round 19): under fully
# periodic boundaries the conservative flux-divergence update preserves
# the total temperature sum exactly (up to accumulation roundoff) — the
# invariant the silent-data-corruption probes watch for state dicts
# carrying this family's canonical "T" field.
from igg import integrity as _integrity

_integrity.register_invariants("diffusion3d", [
    _integrity.Invariant("total_heat", ("T",), moment=1, kind="conserved",
                         requires_periodic=True),
])


def run(nt: int, params: Params = Params(), dtype=np.float32,
        warmup: int = 1, n_inner: int = 1, use_pallas="auto",
        overlap="auto", pallas_interpret: bool = False,
        bx: int = None):
    """Slope-timed run (see :func:`igg.time_steps`): the `nt` timed
    dispatches are split into slope batches of ~nt/4 and ~3nt/4, each
    dispatch advancing `n_inner` steps inside one compiled program, after
    `warmup` untimed dispatches.  Returns (T, seconds_per_step)."""
    T, Cp = init_fields(params, dtype=dtype)
    step = make_multi_step(n_inner, params, use_pallas=use_pallas,
                           overlap=overlap, pallas_interpret=pallas_interpret,
                           bx=bx)
    n1 = max(1, nt // 4)
    (T, Cp), sec = igg.time_steps(lambda T, Cp: (step(T, Cp), Cp), (T, Cp),
                                  n1=n1, n2=max(nt - n1, n1 + 1),
                                  warmup=max(warmup, 1))
    return T, sec / n_inner
