"""3-D heat diffusion — the flagship model.

TPU-native re-implementation of the reference's de-facto integration benchmark
(`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl`):
Fourier-law fluxes on a staggered grid, conservative temperature update, halo
exchange each step.  The whole step (fluxes + update + halo ppermutes)
compiles to ONE XLA program per device via `igg.sharded`, with the temperature
buffer donated so the update is in-place in HBM; XLA's latency-hiding
scheduler overlaps the halo collectives with interior compute — the built-in
analog of ParallelStencil's `@hide_communication`
(`/root/reference/README.md:9`).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    lam: float = 1.0        # thermal conductivity
    cp_min: float = 1.0     # minimal heat capacity
    lx: float = 10.0        # domain length in x
    ly: float = 10.0
    lz: float = 10.0

    def spacing(self) -> Tuple[float, float, float]:
        return igg.tools.spacing(self.lx, self.ly, self.lz)

    def timestep(self) -> float:
        dx, dy, dz = self.spacing()
        # CFL-type bound of the reference example
        # (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:40`).
        return min(dx * dx, dy * dy, dz * dz) * self.cp_min / self.lam / 8.1


def init_fields(params: Params = Params(), dtype=np.float32):
    """Heat capacity and temperature with Gaussian anomalies, built from
    global coordinates so every device holds globally-consistent data
    (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:33-37`)."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny, nz = grid.nxyz
    dx, dy, dz = params.spacing()
    lx, ly, lz = params.lx, params.ly, params.lz

    T0 = igg.zeros((nx, ny, nz), dtype=dtype)
    X, Y, Z = igg.coord_fields(dx, dy, dz, T0)
    X, Y, Z = (a.astype(dtype) for a in (X, Y, Z))
    Cp = (params.cp_min
          + 5 * jnp.exp(-(X - lx / 1.5) ** 2 - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
          + 5 * jnp.exp(-(X - lx / 3.0) ** 2 - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
          + 0 * T0)
    T = (100 * jnp.exp(-((X - lx / 2) / 2) ** 2 - ((Y - ly / 2) / 2) ** 2
                       - ((Z - lz / 3.0) / 2) ** 2)
         + 50 * jnp.exp(-((X - lx / 2) / 2) ** 2 - ((Y - ly / 2) / 2) ** 2
                        - ((Z - lz / 1.5) / 2) ** 2)
         + 0 * T0)
    return T, Cp


def compute_step(T, Cp, *, dx, dy, dz, dt, lam):
    """The pure stencil update (no halo exchange): conservative interior
    temperature update; boundary planes keep their stale values (the
    reference's no-write semantics).

    Physics of the reference example — Fourier-law fluxes on staggered inner
    faces plus ∂T/∂t = 1/cp ∇·(λ∇T)
    (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:41-48`)
    — algebraically re-associated for TPU: with constant λ the staggered flux
    divergence telescopes to the 7-point Laplacian, so the whole update is ONE
    fused XLA pass (read T, read Cp, write T).  The flux form as written in
    the reference materializes three face-flux temporaries (measured 2.2 GB of
    HBM traffic per step at 256³ instead of ~0.8 GB — the same reason the
    reference's own CuArray-broadcast version is ">10x" slower than its
    hand-fused kernels, `/root/reference/README.md:161`).

    Shift-invariant and radius-1, so it is usable both full-domain and on the
    boundary slabs of :func:`igg.hide_communication`."""
    import jax.numpy as jnp
    from jax import lax

    rdx2, rdy2, rdz2 = 1.0 / (dx * dx), 1.0 / (dy * dy), 1.0 / (dz * dz)
    ctr = T[1:-1, 1:-1, 1:-1]
    lap = ((T[2:, 1:-1, 1:-1] + T[:-2, 1:-1, 1:-1]) * rdx2
           + (T[1:-1, 2:, 1:-1] + T[1:-1, :-2, 1:-1]) * rdy2
           + (T[1:-1, 1:-1, 2:] + T[1:-1, 1:-1, :-2]) * rdz2
           - 2.0 * (rdx2 + rdy2 + rdz2) * ctr)
    U = ctr + (dt * lam) / Cp[1:-1, 1:-1, 1:-1] * lap
    # Full-size assembly as a masked select (fuses into the same output pass;
    # `.at[1:-1,...].add` would be a dynamic-update-slice that XLA turns into
    # an extra full-array copy).
    s = T.shape
    inside = None
    for d in range(3):
        i = lax.broadcasted_iota(jnp.int32, s, d)
        m = (i > 0) & (i < s[d] - 1)
        inside = m if inside is None else inside & m
    return jnp.where(inside, jnp.pad(U, 1), T)


def local_step(T, Cp, *, dx, dy, dz, dt, lam, overlap: bool = False):
    """One diffusion step over per-device local arrays (the user-model of the
    reference: physics written for a single device's block).  With
    `overlap=True` the step is restructured by :func:`igg.hide_communication`
    so the halo collectives are data-independent of the full-domain stencil
    and XLA can overlap them (ParallelStencil's `@hide_communication`,
    `/root/reference/README.md:9`)."""
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, lam=lam)
    if overlap:
        return igg.hide_communication(
            T, lambda Tb, Cpb: compute_step(Tb, Cpb, **kw), Cp)
    return igg.update_halo_local(compute_step(T, Cp, **kw))


def _pallas_applicable(use_pallas, T) -> bool:
    import jax.numpy as jnp

    from igg.ops import pallas_supported
    if use_pallas is False:
        return False
    grid = igg.get_global_grid()
    ok = (pallas_supported(grid, T) and T.dtype == jnp.float32
          and next(iter(grid.mesh.devices.flat)).platform == "tpu")
    if use_pallas is True and not ok:
        raise igg.GridError(
            "the fused Pallas step requires a single TPU device, a fully "
            "periodic overlap-2 grid, and an f32 unstaggered field.")
    return ok


def _best_bx(S0: int) -> int:
    for b in (16, 8, 4, 2):  # 16 measured fastest at 256^3 on v5e
        if S0 % b == 0:
            return b
    return 1


def make_step(params: Params = Params(), *, donate: bool = True,
              use_pallas="auto", overlap: bool = False):
    """Compiled whole-step function `(T, Cp) -> T` over the grid mesh.

    `use_pallas`: "auto" (default) uses the fused Pallas kernel
    (`igg.ops.fused_diffusion_step`) when it applies (single TPU device,
    fully-periodic overlap-2 grid, f32); False forces the portable
    shard_map/XLA path; True requires the kernel and raises if inapplicable.
    `overlap`: restructure each step with `igg.hide_communication`.
    """
    return make_multi_step(1, params, donate=donate, use_pallas=use_pallas,
                           overlap=overlap)


def make_multi_step(n_inner: int, params: Params = Params(), *,
                    donate: bool = True, use_pallas="auto",
                    overlap: bool = False):
    """Compiled `(T, Cp) -> T` advancing `n_inner` steps in ONE XLA program
    (`lax.fori_loop` around the step, halo ppermutes included).  This is the
    TPU-idiomatic time loop: host dispatch overhead amortizes to zero, and
    XLA schedules collectives of step k+1 against compute of step k.  The
    reference instead re-dispatches kernels + MPI calls from the host every
    step (`/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:41-48`)."""
    import jax
    from jax import lax

    dx, dy, dz = params.spacing()
    dt = params.timestep()

    def steps(T, Cp):
        return lax.fori_loop(
            0, n_inner,
            lambda _, T: local_step(T, Cp, dx=dx, dy=dy, dz=dz, dt=dt,
                                    lam=params.lam, overlap=overlap),
            T)

    if overlap and use_pallas is True:
        raise igg.GridError(
            "overlap=True applies to the shard_map/XLA path only (the fused "
            "Pallas kernel is single-device: there is no communication to "
            "hide); pass use_pallas=False or 'auto'.")

    xla_path = igg.sharded(steps, donate_argnums=(0,) if donate else ())
    cache = {}

    def dispatch(T, Cp):
        # overlap=True forces the shard_map/XLA path so the restructured
        # step is what actually runs (the Pallas kernel only applies on a
        # single device, where there are no collectives to overlap anyway).
        if not overlap and _pallas_applicable(use_pallas, T):
            from igg.ops import fused_diffusion_step
            key = (T.shape, str(T.dtype))
            fn = cache.get(key)
            if fn is None:
                bx = _best_bx(T.shape[0])
                fn = jax.jit(
                    lambda T, Cp: lax.fori_loop(
                        0, n_inner,
                        lambda _, T: fused_diffusion_step(
                            T, Cp, dx=dx, dy=dy, dz=dz, dt=dt,
                            lam=params.lam, bx=bx),
                        T),
                    donate_argnums=(0,) if donate else ())
                cache[key] = fn
            return fn(T, Cp)
        return xla_path(T, Cp)

    return dispatch


def run(nt: int, params: Params = Params(), dtype=np.float32,
        warmup: int = 1, n_inner: int = 1, use_pallas="auto",
        overlap: bool = False):
    """Slope-timed run (see :func:`igg.time_steps`): the `nt` timed
    dispatches are split into slope batches of ~nt/4 and ~3nt/4, each
    dispatch advancing `n_inner` steps inside one compiled program, after
    `warmup` untimed dispatches.  Returns (T, seconds_per_step)."""
    T, Cp = init_fields(params, dtype=dtype)
    step = make_multi_step(n_inner, params, use_pallas=use_pallas,
                           overlap=overlap)
    n1 = max(1, nt // 4)
    (T, Cp), sec = igg.time_steps(lambda T, Cp: (step(T, Cp), Cp), (T, Cp),
                                  n1=n1, n2=max(nt - n1, n1 + 1),
                                  warmup=max(warmup, 1))
    return T, sec / n_inner
