"""3-D hydro-mechanical porous flow (nonlinear two-field compaction).

BASELINE config 4 ("3-D hydro-mechanical porous flow (ParallelStencil HM3D),
weak scaling").  A compact HM3D-class miniapp: effective pressure `Pe`
diffusing through a porosity field `phi` with porosity-dependent (cubic)
permeability, coupled back through compaction — the porosity-wave problem.
Two mutually-coupled fields exchanged in one grouped halo update per step;
the nonlinear face permeabilities make the stencil state-dependent, unlike
the constant-coefficient diffusion flagship.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    phi0: float = 0.1        # background porosity
    npow: int = 3            # permeability exponent k ~ (phi/phi0)^n
    eta: float = 1.0         # compaction viscosity
    lx: float = 10.0
    ly: float = 10.0
    lz: float = 10.0

    def spacing(self) -> Tuple[float, float, float]:
        return igg.tools.spacing(self.lx, self.ly, self.lz)

    def timestep(self) -> float:
        dx, dy, dz = self.spacing()
        # Permeability k = (phi/phi0)^n reaches 8 at the initial 2*phi0
        # anomaly and keeps growing while compaction feeds the porosity
        # wave; the divisor bounds k*dt/dx^2 with headroom for that growth
        # (long runs at k up to ~25 stay stable).
        return min(dx * dx, dy * dy, dz * dz) / 8.1 / 32.0


def init_fields(params: Params = Params(), dtype=np.float32):
    """Gaussian porosity anomaly in a uniform background; Pe at rest."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny, nz = grid.nxyz
    dx, dy, dz = params.spacing()

    Pe0 = igg.zeros((nx, ny, nz), dtype=dtype)
    X, Y, Z = (a.astype(dtype) for a in igg.coord_fields(dx, dy, dz, Pe0))
    r2 = ((X - params.lx / 2) ** 2 + (Y - params.ly / 2) ** 2
          + (Z - params.lz / 3) ** 2)
    phi = params.phi0 * (1.0 + 1.0 * jnp.exp(-r2)) + 0 * Pe0
    Pe = -0.5 * jnp.exp(-r2) + 0 * Pe0    # under-pressured anomaly
    return Pe, phi


def step_core(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta):
    """The coupled increments `(dPe, dphi)` on a window's interior cells:
    radius-1 shift-invariant, the single source of arithmetic truth shared
    by the XLA step, the `hide_communication` slabs, and the fused Pallas
    kernel (`igg.ops.hm3d_pallas`)."""
    k = (phi / phi0) ** npow
    # Face permeabilities (arithmetic mean) and Darcy fluxes on inner faces
    kx = 0.5 * (k[1:, 1:-1, 1:-1] + k[:-1, 1:-1, 1:-1])
    ky = 0.5 * (k[1:-1, 1:, 1:-1] + k[1:-1, :-1, 1:-1])
    kz = 0.5 * (k[1:-1, 1:-1, 1:] + k[1:-1, 1:-1, :-1])
    qx = -kx * (Pe[1:, 1:-1, 1:-1] - Pe[:-1, 1:-1, 1:-1]) / dx
    qy = -ky * (Pe[1:-1, 1:, 1:-1] - Pe[1:-1, :-1, 1:-1]) / dy
    qz = -kz * (Pe[1:-1, 1:-1, 1:] - Pe[1:-1, 1:-1, :-1]) / dz
    divq = ((qx[1:, :, :] - qx[:-1, :, :]) / dx
            + (qy[:, 1:, :] - qy[:, :-1, :]) / dy
            + (qz[:, :, 1:] - qz[:, :, :-1]) / dz)
    inner = (slice(1, -1),) * 3
    # Fluid mass balance: Pe relaxes by Darcy flow + compaction closure;
    # compaction: porosity responds to the (updated) effective pressure
    # (Gauss-Seidel coupling).
    dPe = dt * (-divq - Pe[inner] * phi[inner] / eta)
    Pe_new = Pe[inner] + dPe
    dphi = dt * (-phi[inner] * (1.0 - phi[inner]) * Pe_new / eta)
    return dPe, dphi


def compute_step(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta):
    """The pure coupled update (no halo exchange): radius-1 shift-invariant,
    usable full-domain and on :func:`igg.hide_communication` slabs."""
    from igg.ops import interior_add

    dPe, dphi = step_core(Pe, phi, dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0,
                          npow=npow, eta=eta)
    return interior_add(Pe, dPe), interior_add(phi, dphi)


def local_step(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta,
               overlap: bool = False, use_pallas: bool = False,
               pallas_interpret: bool = False, assembly=None):
    """One coupled step over per-device local arrays; two mutually-coupled
    fields in one grouped halo update (multi-field pipelining,
    `/root/reference/src/update_halo.jl:19-20`).  `overlap=True`
    restructures with the multi-field :func:`igg.hide_communication`
    (BASELINE config 4's weak-scaling workload).  `use_pallas=True` runs
    the whole step (compute + grouped halo update) as ONE fused kernel
    (`igg.ops.fused_hm3d_step`, any mesh); it raises `GridError` when the
    kernel is inapplicable (the auto-fallback lives in :func:`make_step`)."""
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)
    if use_pallas:
        from igg.ops import fused_hm3d_step

        if overlap:
            raise igg.GridError(
                "the fused HM3D step has overlap (hide_communication) "
                "semantics built in; drop overlap=True when passing "
                "use_pallas.")
        _pallas_applicable(True, Pe, interpret=pallas_interpret)  # or raises
        return fused_hm3d_step(Pe, phi, **kw, interpret=pallas_interpret)
    if overlap:
        return igg.hide_communication(
            (Pe, phi), lambda Pe, phi: compute_step(Pe, phi, **kw),
            assembly=assembly)
    return igg.update_halo_local(*compute_step(Pe, phi, **kw),
                                 assembly=assembly)


_PALLAS_REQ = (
    "the fused HM3D step requires TPU devices (or pallas_interpret=True), "
    "an overlap-2 grid, and f32 unstaggered fields with local shape "
    "divisible into x-slabs (x % 4 == 0, y >= 8, z >= 8; z >= 128 when z "
    "is exchanged), and in compiled mode a y*z area small enough that some "
    "slab height's windows fit the VMEM budget "
    "(igg.ops.hm3d_pallas._vmem_need); use the XLA path otherwise.")


def _pallas_applicable(use_pallas, Pe, interpret: bool = False) -> bool:
    from igg.ops import hm3d_pallas_supported

    from ._dispatch import pallas_applicable

    # `pallas_applicable` threads `interpret` into the gate (no Mosaic,
    # no VMEM budget there), so large-y*z grids stay interpret-runnable.
    return pallas_applicable(use_pallas, Pe,
                             supported_fn=hm3d_pallas_supported,
                             requirement=_PALLAS_REQ, interpret=interpret)


_TRAPEZOID_REQ = (
    "the K-step HM3D chunk tier requires the fused per-step kernel's "
    "prerequisites (TPU devices or pallas_interpret=True, overlap-2 "
    "grid, f32 fields) plus: n_inner >= K+1 (one warm-up step + at "
    "least one full chunk), band/tile-aligned local shape (x % 8 == 0, "
    "y % 8 == 0, z % 128 == 0), K-deep send slabs inside every split "
    "dimension's block, and a VMEM-resident working set for the two "
    "K-extended fields (igg.ops.hm3d_trapezoid.hm3d_trapezoid_supported)"
    "; use trapezoid='auto' or the per-step kernel otherwise.")


_BANDED_REQ = (
    "the streaming banded HM3D chunk tier requires the fused per-step "
    "kernel's prerequisites (TPU devices or pallas_interpret=True, "
    "overlap-2 grid, f32 fields) plus: n_inner >= K+1, banded geometry "
    "(band B >= 8, B % 8 == 0, extended x span divisible into >= 2 "
    "bands), K-deep send slabs inside every split dimension's block, and "
    "a rolling band window set within the VMEM budget "
    "(igg.ops.hm3d_trapezoid.hm3d_banded_supported); use banded='auto' "
    "or the resident tiers otherwise.")


def make_step(params: Params = Params(), *, donate: bool = True,
              overlap="auto", n_inner: int = 1,
              use_pallas="auto", pallas_interpret: bool = False,
              trapezoid="auto", K: int = None, banded="auto",
              band: int = None, verify=None, tune=None):
    """Compiled `(Pe, phi) -> (Pe, phi)` advancing `n_inner` steps in one
    SPMD program.  `use_pallas`: "auto" (default) uses the fused kernel
    (`igg.ops.fused_hm3d_steps`, with boundary-slab carry) when it applies —
    TPU devices, overlap-2 grid, f32 fields, any device count/periodicity;
    False forces the portable shard_map/XLA path; True requires the kernel
    and raises if inapplicable.  `overlap` restructures the XLA path with
    `igg.hide_communication` ("auto" follows the `IGG_OVERLAP` knob, then
    the autotuner's cached winner, else off); the fused kernel has overlap
    semantics built in (its exchange is always data-independent of the
    main kernel), so it satisfies both settings — exactly like
    diffusion3d.
    `verify`: "first_use" numerically checks the fused tier against the
    XLA composition before it serves traffic (`igg.degrade`; defaults to
    the `IGG_VERIFY_KERNELS` environment knob).

    `trapezoid` admits the K-step temporal-blocking chunk tier
    (`igg.ops.hm3d_trapezoid`, round 16 — generated from the shared
    chunk engine) on top of the fused kernel: "auto" (default) engages
    it when `hm3d_trapezoid_supported` admits some K (one warm-up
    per-step kernel, `(n_inner-1) // K` chunks, the remainder through
    the per-step kernel); False pins the per-step kernel; True requires
    the chunk tier and raises `GridError` when inapplicable.  `K`
    overrides the auto-fitted chunk depth (`fit_hm3d_K`).  `tune`
    consults the autotuner's cached winner for this signature
    ("auto"/True/False; `igg.autotune`).

    `banded` admits the STREAMING banded chunk tier
    (`igg.ops.hm3d_trapezoid.fused_hm3d_banded_steps` — rolling VMEM
    window, HBM ping-pong; the ladder rung below the resident
    trapezoid): "auto" (default) engages it only where the resident
    tier's `fit_hm3d_K` refuses (the VMEM K-bound at headline shapes),
    True requires it, False pins the resident tiers.  `band` overrides
    the auto-fitted band depth B (`fit_hm3d_band`)."""
    from jax import lax

    dx, dy, dz = params.spacing()
    dt = params.timestep()
    phi0, npow, eta = params.phi0, params.npow, params.eta
    # NOTE: the step closures capture only hashable scalars so recreated
    # closures share one compiled program (`igg.parallel._fn_key`).

    from igg.overlap import resolve_overlap

    from ._dispatch import apply_tuned

    (K, K_from_cache, band, band_from_cache, trapezoid, banded,
     use_pallas, tuned) = apply_tuned(
        "hm3d", tune, n_inner=n_inner, interpret=pallas_interpret, K=K,
        chunk_knob=trapezoid, use_pallas=use_pallas, band=band,
        banded_knob=banded)
    overlap = resolve_overlap(overlap, family="hm3d", tuned=tuned,
                              radius=1,
                              chunk_active=(trapezoid is True
                                            or banded is True))

    def build_xla(assembly):
        def xla_steps(Pe, phi):
            return lax.fori_loop(
                0, n_inner,
                lambda _, S: local_step(*S, dx=dx, dy=dy, dz=dz, dt=dt,
                                        phi0=phi0, npow=npow, eta=eta,
                                        overlap=overlap, assembly=assembly),
                (Pe, phi))

        return igg.sharded(xla_steps,
                           donate_argnums=(0, 1) if donate else ())

    from ._dispatch import measured_assembly_path

    xla_path = measured_assembly_path(
        build_xla, tag=f"hm3d:{n_inner}:{overlap}:{donate}",
        wrap=lambda fn: fn)

    def build_pallas_steps():
        from igg.ops import fused_hm3d_steps

        def pallas_steps(Pe, phi):
            return fused_hm3d_steps(
                Pe, phi, n_inner=n_inner, dx=dx, dy=dy, dz=dz, dt=dt,
                phi0=phi0, npow=npow, eta=eta, interpret=pallas_interpret)

        return pallas_steps

    if trapezoid is True and use_pallas is False:
        raise igg.GridError(_TRAPEZOID_REQ)
    if banded is True and use_pallas is False:
        raise igg.GridError(_BANDED_REQ)
    if trapezoid is True or banded is True:
        use_pallas = True    # the chunk tiers ride the fused kernel

    donate_argnums = (0, 1) if donate else ()

    def _fit_K(grid, lshape, dtype):
        """The chunk depth the trapezoid tier will run (0 when none
        applies) — shared by the tier's admission gate and its traced
        body so the two can never disagree."""
        from igg.ops.hm3d_trapezoid import (fit_hm3d_K,
                                            hm3d_trapezoid_supported)

        from ._dispatch import resolve_chunk_K

        if trapezoid is False or n_inner < 3:
            return 0
        return resolve_chunk_K(
            K, K_from_cache,
            lambda k: hm3d_trapezoid_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype,
                interpret=pallas_interpret),
            lambda: fit_hm3d_K(grid, tuple(lshape), n_inner - 1, dtype,
                               interpret=pallas_interpret))

    def _fit_band(grid, lshape, dtype):
        """The `(K, B)` config the streaming banded tier will run (None
        when none applies) — shared by the tier's admission gate and its
        traced body so the two can never disagree."""
        from igg.ops.hm3d_trapezoid import (fit_hm3d_band,
                                            hm3d_banded_supported)

        from ._dispatch import resolve_band

        if banded is False or n_inner < 3:
            return None
        return resolve_band(
            K, band, K_from_cache or band_from_cache,
            lambda k, b: hm3d_banded_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype, B=b,
                interpret=pallas_interpret),
            lambda bands: fit_hm3d_band(grid, tuple(lshape), n_inner - 1,
                                        dtype, interpret=pallas_interpret,
                                        bands=bands))

    def admit_trapezoid(args):
        from igg.degrade import Admission
        from igg.ops import hm3d_pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        if trapezoid is False:
            return Admission.no("trapezoid=False pins the per-step "
                                "kernel")
        if banded is True:
            return Admission.no("banded=True pins the streaming banded "
                                "tier")
        base = pallas_applicable("auto", args[0],
                                 supported_fn=hm3d_pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-step kernel (the chunk "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        Pe = args[0]
        if not _fit_K(grid, grid.local_shape_any(Pe), Pe.dtype):
            return Admission.no(
                "no chunk depth K admissible "
                "(igg.ops.hm3d_trapezoid.hm3d_trapezoid_supported)")
        return Admission.yes()

    def build_trapezoid():
        from igg.ops import fused_hm3d_step
        from igg.ops.hm3d_trapezoid import fused_hm3d_trapezoid_steps

        def trap_steps(Pe, phi):
            kw_it = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0,
                         npow=npow, eta=eta)
            grid = igg.get_global_grid()
            Kf = _fit_K(grid, Pe.shape, Pe.dtype)
            if not Kf:    # admission gate and trace share _fit_K
                raise igg.GridError(_TRAPEZOID_REQ)
            # Warm-up per-step kernel: consumes (and replaces) the entry
            # halos exactly like every other path — the exchange-fresh
            # window state the chunk's validity argument requires, for
            # ANY input.
            Pe, phi = fused_hm3d_step(Pe, phi, **kw_it,
                                      interpret=pallas_interpret)
            Pe, phi, done = fused_hm3d_trapezoid_steps(
                Pe, phi, n_inner=n_inner - 1, K=Kf, **kw_it,
                interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-step kernel
                Pe, phi = lax.fori_loop(
                    0, n,
                    lambda _, S: fused_hm3d_step(
                        *S, **kw_it, interpret=pallas_interpret),
                    (Pe, phi))
            return Pe, phi

        return igg.sharded(trap_steps, donate_argnums=donate_argnums,
                           check_vma=not pallas_interpret)

    def admit_banded(args):
        from igg.degrade import Admission
        from igg.ops import hm3d_pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        if banded is False:
            return Admission.no("banded=False pins the resident tiers")
        base = pallas_applicable("auto", args[0],
                                 supported_fn=hm3d_pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-step kernel (the banded "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        Pe = args[0]
        lshape = grid.local_shape_any(Pe)
        if banded == "auto":
            if trapezoid is False:
                return Admission.no("trapezoid=False pins the per-step "
                                    "kernel (pass banded=True to require "
                                    "the streaming tier)")
            if _fit_K(grid, lshape, Pe.dtype):
                return Admission.no(
                    "the resident chunk tier serves this shape (the "
                    "banded rung engages where fit_hm3d_K refuses)")
        if not _fit_band(grid, lshape, Pe.dtype):
            return Admission.no(
                "no banded config (K, B) admissible "
                "(igg.ops.hm3d_trapezoid.hm3d_banded_supported)")
        return Admission.yes()

    def build_banded():
        from igg.ops import fused_hm3d_step
        from igg.ops.hm3d_trapezoid import fused_hm3d_banded_steps

        def banded_steps(Pe, phi):
            kw_it = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0,
                         npow=npow, eta=eta)
            grid = igg.get_global_grid()
            kb = _fit_band(grid, Pe.shape, Pe.dtype)
            if not kb:    # admission gate and trace share _fit_band
                raise igg.GridError(_BANDED_REQ)
            Kf, Bf = kb
            # Warm-up per-step kernel: the exchange-fresh entry state the
            # chunk validity argument requires (the trapezoid contract).
            Pe, phi = fused_hm3d_step(Pe, phi, **kw_it,
                                      interpret=pallas_interpret)
            Pe, phi, done = fused_hm3d_banded_steps(
                Pe, phi, n_inner=n_inner - 1, K=Kf, B=Bf, **kw_it,
                interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-step kernel
                Pe, phi = lax.fori_loop(
                    0, n,
                    lambda _, S: fused_hm3d_step(
                        *S, **kw_it, interpret=pallas_interpret),
                    (Pe, phi))
            return Pe, phi

        return igg.sharded(banded_steps, donate_argnums=donate_argnums,
                           check_vma=not pallas_interpret)

    from igg.degrade import Tier
    from igg.ops import hm3d_pallas_supported

    from ._dispatch import auto_dispatch

    trap_tier = Tier(name="hm3d.trapezoid", rung=0,
                     build=build_trapezoid, admit=admit_trapezoid,
                     required=trapezoid is True,
                     requirement=_TRAPEZOID_REQ)
    banded_tier = Tier(name="hm3d.banded", rung=0,
                       build=build_banded, admit=admit_banded,
                       required=banded is True,
                       requirement=_BANDED_REQ)
    return auto_dispatch(
        use_pallas=use_pallas, interpret=pallas_interpret,
        supported_fn=hm3d_pallas_supported, requirement=_PALLAS_REQ,
        xla_path=xla_path, build_pallas_steps=build_pallas_steps,
        donate_argnums=donate_argnums,
        family="hm3d", verify=verify,
        extra_tiers=(trap_tier, banded_tier))


def run(nt: int, params: Params = Params(), dtype=np.float32,
        overlap="auto", n_inner: int = 1, use_pallas="auto"):
    """Slope-timed run (see :func:`igg.time_steps`)."""
    Pe, phi = init_fields(params, dtype=dtype)
    step = make_step(params, overlap=overlap, n_inner=n_inner,
                     use_pallas=use_pallas)
    n1 = max(1, nt // 4)
    state, sec = igg.time_steps(step, (Pe, phi),
                                n1=n1, n2=max(nt - n1, n1 + 1))
    return state, sec / n_inner
