"""3-D hydro-mechanical porous flow (nonlinear two-field compaction).

BASELINE config 4 ("3-D hydro-mechanical porous flow (ParallelStencil HM3D),
weak scaling").  A compact HM3D-class miniapp: effective pressure `Pe`
diffusing through a porosity field `phi` with porosity-dependent (cubic)
permeability, coupled back through compaction — the porosity-wave problem.
Two mutually-coupled fields exchanged in one grouped halo update per step;
the nonlinear face permeabilities make the stencil state-dependent, unlike
the constant-coefficient diffusion flagship.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    phi0: float = 0.1        # background porosity
    npow: int = 3            # permeability exponent k ~ (phi/phi0)^n
    eta: float = 1.0         # compaction viscosity
    lx: float = 10.0
    ly: float = 10.0
    lz: float = 10.0

    def spacing(self) -> Tuple[float, float, float]:
        return igg.tools.spacing(self.lx, self.ly, self.lz)

    def timestep(self) -> float:
        dx, dy, dz = self.spacing()
        # Permeability k = (phi/phi0)^n reaches 8 at the initial 2*phi0
        # anomaly and keeps growing while compaction feeds the porosity
        # wave; the divisor bounds k*dt/dx^2 with headroom for that growth
        # (long runs at k up to ~25 stay stable).
        return min(dx * dx, dy * dy, dz * dz) / 8.1 / 32.0


def init_fields(params: Params = Params(), dtype=np.float32):
    """Gaussian porosity anomaly in a uniform background; Pe at rest."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny, nz = grid.nxyz
    dx, dy, dz = params.spacing()

    Pe0 = igg.zeros((nx, ny, nz), dtype=dtype)
    X, Y, Z = (a.astype(dtype) for a in igg.coord_fields(dx, dy, dz, Pe0))
    r2 = ((X - params.lx / 2) ** 2 + (Y - params.ly / 2) ** 2
          + (Z - params.lz / 3) ** 2)
    phi = params.phi0 * (1.0 + 1.0 * jnp.exp(-r2)) + 0 * Pe0
    Pe = -0.5 * jnp.exp(-r2) + 0 * Pe0    # under-pressured anomaly
    return Pe, phi


def step_core(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta):
    """The coupled increments `(dPe, dphi)` on a window's interior cells:
    radius-1 shift-invariant, the single source of arithmetic truth shared
    by the XLA step, the `hide_communication` slabs, and the fused Pallas
    kernel (`igg.ops.hm3d_pallas`)."""
    k = (phi / phi0) ** npow
    # Face permeabilities (arithmetic mean) and Darcy fluxes on inner faces
    kx = 0.5 * (k[1:, 1:-1, 1:-1] + k[:-1, 1:-1, 1:-1])
    ky = 0.5 * (k[1:-1, 1:, 1:-1] + k[1:-1, :-1, 1:-1])
    kz = 0.5 * (k[1:-1, 1:-1, 1:] + k[1:-1, 1:-1, :-1])
    qx = -kx * (Pe[1:, 1:-1, 1:-1] - Pe[:-1, 1:-1, 1:-1]) / dx
    qy = -ky * (Pe[1:-1, 1:, 1:-1] - Pe[1:-1, :-1, 1:-1]) / dy
    qz = -kz * (Pe[1:-1, 1:-1, 1:] - Pe[1:-1, 1:-1, :-1]) / dz
    divq = ((qx[1:, :, :] - qx[:-1, :, :]) / dx
            + (qy[:, 1:, :] - qy[:, :-1, :]) / dy
            + (qz[:, :, 1:] - qz[:, :, :-1]) / dz)
    inner = (slice(1, -1),) * 3
    # Fluid mass balance: Pe relaxes by Darcy flow + compaction closure;
    # compaction: porosity responds to the (updated) effective pressure
    # (Gauss-Seidel coupling).
    dPe = dt * (-divq - Pe[inner] * phi[inner] / eta)
    Pe_new = Pe[inner] + dPe
    dphi = dt * (-phi[inner] * (1.0 - phi[inner]) * Pe_new / eta)
    return dPe, dphi


def compute_step(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta):
    """The pure coupled update (no halo exchange): radius-1 shift-invariant,
    usable full-domain and on :func:`igg.hide_communication` slabs."""
    from igg.ops import interior_add

    dPe, dphi = step_core(Pe, phi, dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0,
                          npow=npow, eta=eta)
    return interior_add(Pe, dPe), interior_add(phi, dphi)


def local_step(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta,
               overlap: bool = False, use_pallas: bool = False,
               pallas_interpret: bool = False, assembly=None):
    """One coupled step over per-device local arrays; two mutually-coupled
    fields in one grouped halo update (multi-field pipelining,
    `/root/reference/src/update_halo.jl:19-20`).  `overlap=True`
    restructures with the multi-field :func:`igg.hide_communication`
    (BASELINE config 4's weak-scaling workload).  `use_pallas=True` runs
    the whole step (compute + grouped halo update) as ONE fused kernel
    (`igg.ops.fused_hm3d_step`, any mesh); it raises `GridError` when the
    kernel is inapplicable (the auto-fallback lives in :func:`make_step`)."""
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)
    if use_pallas:
        from igg.ops import fused_hm3d_step

        if overlap:
            raise igg.GridError(
                "the fused HM3D step has overlap (hide_communication) "
                "semantics built in; drop overlap=True when passing "
                "use_pallas.")
        _pallas_applicable(True, Pe, interpret=pallas_interpret)  # or raises
        return fused_hm3d_step(Pe, phi, **kw, interpret=pallas_interpret)
    if overlap:
        return igg.hide_communication(
            (Pe, phi), lambda Pe, phi: compute_step(Pe, phi, **kw),
            assembly=assembly)
    return igg.update_halo_local(*compute_step(Pe, phi, **kw),
                                 assembly=assembly)


_PALLAS_REQ = (
    "the fused HM3D step requires TPU devices (or pallas_interpret=True), "
    "an overlap-2 grid, and f32 unstaggered fields with local shape "
    "divisible into x-slabs (x % 4 == 0, y >= 8, z >= 8; z >= 128 when z "
    "is exchanged), and in compiled mode a y*z area small enough that some "
    "slab height's windows fit the VMEM budget "
    "(igg.ops.hm3d_pallas._vmem_need); use the XLA path otherwise.")


def _pallas_applicable(use_pallas, Pe, interpret: bool = False) -> bool:
    from igg.ops import hm3d_pallas_supported

    from ._dispatch import pallas_applicable

    # `pallas_applicable` threads `interpret` into the gate (no Mosaic,
    # no VMEM budget there), so large-y*z grids stay interpret-runnable.
    return pallas_applicable(use_pallas, Pe,
                             supported_fn=hm3d_pallas_supported,
                             requirement=_PALLAS_REQ, interpret=interpret)


def make_step(params: Params = Params(), *, donate: bool = True,
              overlap: bool = False, n_inner: int = 1,
              use_pallas="auto", pallas_interpret: bool = False,
              verify=None):
    """Compiled `(Pe, phi) -> (Pe, phi)` advancing `n_inner` steps in one
    SPMD program.  `use_pallas`: "auto" (default) uses the fused kernel
    (`igg.ops.fused_hm3d_steps`, with boundary-slab carry) when it applies —
    TPU devices, overlap-2 grid, f32 fields, any device count/periodicity;
    False forces the portable shard_map/XLA path; True requires the kernel
    and raises if inapplicable.  `overlap` restructures the XLA path with
    `igg.hide_communication`; the fused kernel has overlap semantics built
    in (its exchange is always data-independent of the main kernel), so it
    satisfies both settings — exactly like diffusion3d.
    `verify`: "first_use" numerically checks the fused tier against the
    XLA composition before it serves traffic (`igg.degrade`; defaults to
    the `IGG_VERIFY_KERNELS` environment knob)."""
    from jax import lax

    dx, dy, dz = params.spacing()
    dt = params.timestep()
    phi0, npow, eta = params.phi0, params.npow, params.eta
    # NOTE: the step closures capture only hashable scalars so recreated
    # closures share one compiled program (`igg.parallel._fn_key`).

    def build_xla(assembly):
        def xla_steps(Pe, phi):
            return lax.fori_loop(
                0, n_inner,
                lambda _, S: local_step(*S, dx=dx, dy=dy, dz=dz, dt=dt,
                                        phi0=phi0, npow=npow, eta=eta,
                                        overlap=overlap, assembly=assembly),
                (Pe, phi))

        return igg.sharded(xla_steps,
                           donate_argnums=(0, 1) if donate else ())

    from ._dispatch import measured_assembly_path

    xla_path = measured_assembly_path(
        build_xla, tag=f"hm3d:{n_inner}:{overlap}:{donate}",
        wrap=lambda fn: fn)

    def build_pallas_steps():
        from igg.ops import fused_hm3d_steps

        def pallas_steps(Pe, phi):
            return fused_hm3d_steps(
                Pe, phi, n_inner=n_inner, dx=dx, dy=dy, dz=dz, dt=dt,
                phi0=phi0, npow=npow, eta=eta, interpret=pallas_interpret)

        return pallas_steps

    from igg.ops import hm3d_pallas_supported

    from ._dispatch import auto_dispatch

    return auto_dispatch(
        use_pallas=use_pallas, interpret=pallas_interpret,
        supported_fn=hm3d_pallas_supported, requirement=_PALLAS_REQ,
        xla_path=xla_path, build_pallas_steps=build_pallas_steps,
        donate_argnums=(0, 1) if donate else (),
        family="hm3d", verify=verify)


def run(nt: int, params: Params = Params(), dtype=np.float32,
        overlap: bool = False, n_inner: int = 1, use_pallas="auto"):
    """Slope-timed run (see :func:`igg.time_steps`)."""
    Pe, phi = init_fields(params, dtype=dtype)
    step = make_step(params, overlap=overlap, n_inner=n_inner,
                     use_pallas=use_pallas)
    n1 = max(1, nt // 4)
    state, sec = igg.time_steps(step, (Pe, phi),
                                n1=n1, n2=max(nt - n1, n1 + 1))
    return state, sec / n_inner
