"""Physics models built on the implicit global grid.

The reference ships its models as example scripts
(`/root/reference/docs/examples/diffusion3D_*.jl`); here they are importable
modules so benchmarks, tests and the graft entry points share one
implementation.
"""

from . import diffusion3d, hm3d, shallow_water, stokes3d, wave2d

__all__ = ["diffusion3d", "hm3d", "shallow_water", "stokes3d", "wave2d"]
