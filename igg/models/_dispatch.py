"""Shared `use_pallas` dispatch scaffolding for the model families.

Each model family exposes the same three-valued contract on its compiled
step factory: `use_pallas="auto"` (default) routes to the fused Pallas
kernel when it applies and silently falls back to the portable
shard_map/XLA path otherwise; `False` forces the XLA path; `True` requires
the kernel and raises `GridError` with the family's requirement string.
This module is the single implementation of that contract (applicability
probe + lazily-built sharded pallas path), parameterized by the family's
`supported(grid, field)` gate, requirement message, and fused-step
builder."""

from __future__ import annotations

import igg


def pallas_applicable(use_pallas, field, *, supported_fn, requirement,
                      interpret: bool = False) -> bool:
    """The auto/True/False applicability probe: TPU devices (or interpret
    mode), f32 fields, and the family's `supported_fn` gate.  Raises
    `GridError(requirement)` when `use_pallas is True` but the kernel is
    inapplicable."""
    import jax.numpy as jnp

    if use_pallas is False:
        return False
    grid = igg.get_global_grid()
    platform_ok = (interpret
                   or next(iter(grid.mesh.devices.flat)).platform == "tpu")
    ok = (platform_ok and field.dtype == jnp.float32
          and supported_fn(grid, field))
    if use_pallas is True and not ok:
        raise igg.GridError(requirement)
    return ok


def auto_dispatch(*, use_pallas, interpret, supported_fn, requirement,
                  xla_path, build_pallas_steps, donate_argnums):
    """The compiled-entry dispatcher shared by the model factories:
    per-call applicability probe on the first field argument, lazily
    compiling the fused path through `igg.sharded` on first use.

    `build_pallas_steps()` returns the local (per-device) fused step
    function; `check_vma=not interpret` works around interpret-mode
    pallas_call not propagating shard_map's varying-manual-axes metadata."""
    pallas_path = None

    def dispatch(*args):
        nonlocal pallas_path
        if pallas_applicable(use_pallas, args[0], supported_fn=supported_fn,
                             requirement=requirement, interpret=interpret):
            if pallas_path is None:
                pallas_path = igg.sharded(
                    build_pallas_steps(), donate_argnums=donate_argnums,
                    check_vma=not interpret)
            return pallas_path(*args)
        return xla_path(*args)

    return dispatch
