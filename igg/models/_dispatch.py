"""Shared `use_pallas` dispatch scaffolding for the model families.

Each model family exposes the same three-valued contract on its compiled
step factory: `use_pallas="auto"` (default) routes to the fused Pallas
kernel when it applies and silently falls back to the portable
shard_map/XLA path otherwise; `False` forces the XLA path; `True` requires
the kernel and raises `GridError` with the family's requirement string.
This module is the single implementation of that contract, parameterized
by the family's `supported(grid, field)` gate, requirement message, and
fused-step builder.

Round 10: the contract is realized as an :class:`igg.degrade.Ladder` —
every dispatch walks the family's tier ladder (optional extra rungs like
the Stokes trapezoid chunk tier → the fused Mosaic rung → the pure-XLA
composition truth rung), so compile-failure capture, kernel quarantine,
and numeric verify-on-first-use apply uniformly to every family (see
`igg/degrade.py`)."""

from __future__ import annotations

import igg


def pallas_applicable(use_pallas, field, *, supported_fn, requirement,
                      interpret: bool = False):
    """The auto/True/False applicability probe: TPU devices (or interpret
    mode), f32 fields, and the family's `supported_fn` gate.  Returns an
    :class:`igg.degrade.Admission` (truthy/falsy, with the structured
    refusal reason); raises `GridError(requirement)` when `use_pallas is
    True` but the kernel is inapplicable."""
    import inspect

    import jax.numpy as jnp

    from igg.degrade import Admission

    def probe():
        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        grid = igg.get_global_grid()
        if not (interpret
                or next(iter(grid.mesh.devices.flat)).platform == "tpu"):
            return Admission.no("devices are not TPU (and interpret mode "
                                "is off)")
        if field.dtype != jnp.float32:
            return Admission.no(f"dtype {field.dtype} is not float32")
        # Gates that distinguish interpret mode (no Mosaic, no VMEM budget
        # — stokes/hm3d) receive the flag; older two-arg gates are
        # unchanged.
        kw = ({"interpret": interpret}
              if "interpret" in inspect.signature(supported_fn).parameters
              else {})
        ok = supported_fn(grid, field, **kw)
        if isinstance(ok, Admission):
            return ok
        return Admission.yes() if ok else Admission.no(
            "the family's admission gate refused the field/grid")

    adm = probe()
    if use_pallas is True and not adm:
        raise igg.GridError(requirement)
    return adm


# Measured assembly choices, keyed by (model tag, grid epoch, arg
# signature): the right `assembly` mode for a composed step is
# signature-dependent (`"xla"` fuses the halo select chain into a radius-1
# single-field stencil's output pass; the Pallas writers win standalone and
# multi-field updates — `igg.halo.update_halo_local` docstring), so instead
# of hard-coding per-model hints the compiled paths measure both variants
# once per signature and cache the winner (VERDICT r3 item 7).
_ASSEMBLY_CHOICE: dict = {}


def _elect(measure, names=("xla", "writer"), *, close=0.15, max_rounds=3):
    """Noise-hardened winner election: one slope measurement per variant per
    round, compared by per-variant *median* (a single outlier round — high
    OR low — cannot pin the choice, unlike min-of-k).  Re-measures while
    the medians sit within `close` relative distance of each other, up to
    `max_rounds` rounds; variants separated by more than the noise margin
    are elected after one round, so the well-separated common case still
    pays exactly one measurement per variant."""
    import statistics

    samples = {n: [measure(n)] for n in names}
    for _ in range(max_rounds - 1):
        med = {n: statistics.median(s) for n, s in samples.items()}
        lo = min(med.values())
        if max(med.values()) - lo > close * lo:
            break
        for n in names:
            samples[n].append(measure(n))
    med = {n: statistics.median(s) for n, s in samples.items()}
    return min(med, key=med.get)


def _measurement_would_oom(args) -> bool:
    """The one-time measurement keeps a full scratch copy of every field
    live alongside the originals (plus both variants' executables); when
    the device reports less free memory than ~2x the argument bytes, skip
    it — jobs sized to the donation steady state would OOM at first
    dispatch before finding the `IGG_ASSEMBLY` escape hatch."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        free = stats["bytes_limit"] - stats["bytes_in_use"]
    except Exception:
        return False  # no stats on this runtime: keep the measured path

    def per_device_bytes(a):
        # Compare like with like: free memory is per device, so count the
        # bytes one device holds (a sharded field's `nbytes` is the global
        # size — N-chip meshes would over-count by N and silently disable
        # the election).
        shards = getattr(a, "addressable_shards", None)
        if shards:
            ndev = max(1, len({sh.device for sh in shards}))
            return sum(sh.data.nbytes for sh in shards) // ndev
        return getattr(a, "nbytes", 0)

    return free < 2 * sum(per_device_bytes(a) for a in args)


def measured_assembly_path(build_variant, *, tag: str, wrap):
    """Returns `dispatch(*args)` choosing between the compiled
    `assembly="xla"` and writer (`assembly=None`) variants of the same step
    by a one-time slope-timed measurement per argument signature.

    `build_variant(assembly)` -> compiled step callable (built lazily and
    at most once per variant).  `wrap(fn)` adapts the step to a
    state-preserving `state -> state` function for `igg.time_steps` (the
    measurement runs on scratch copies, so donation in the real path is
    unaffected — note the one-time cost: a full scratch copy of the
    fields is live alongside the originals during measurement, plus both
    variants' executables; jobs sized to the donation steady state can
    pin the choice with `IGG_ASSEMBLY=xla|writer` to skip it).

    The measurement is skipped — with a fixed "writer" default, the
    engine's standalone-optimal strategy — when it cannot run safely or
    meaningfully: non-TPU meshes (the writers never engage; "xla"),
    a quarantined writer tier (igg.degrade), multi-controller runs
    (per-process wall clocks could elect different variants and the
    processes would then execute divergent SPMD programs), or an
    `IGG_ASSEMBLY` override."""
    import igg
    from igg import shared
    from igg.halo import _is_tpu

    built = {}

    # Choices are NAMED ("xla" / "writer") rather than the engine's
    # None-means-writers convention: a None cache entry would be
    # indistinguishable from "not measured yet" and re-measure on every
    # dispatch.
    def variant(choice: str):
        if choice not in built:
            built[choice] = build_variant(
                None if choice == "writer" else choice)
        return built[choice]

    def dispatch(*args):
        import jax

        from igg import _env, degrade, halo

        grid = shared.global_grid()
        if not (_is_tpu(grid) or halo._FORCE_WRITER_INTERPRET):
            return variant("xla")(*args)
        if degrade.is_quarantined(degrade.HALO_WRITER_TIER):
            # The writer tier is quarantined (see igg/halo.py): skip the
            # election — a measured "writer" choice could no longer engage
            # the writers and would just mislead the cache.
            return variant("xla")(*args)
        forced = _env.text("IGG_ASSEMBLY")
        if forced in ("xla", "writer"):
            return variant(forced)(*args)
        if jax.process_count() > 1:
            # No cross-process agreement protocol for a measured choice;
            # a per-process pick could diverge and the SPMD programs with
            # it.  Fixed default instead.
            return variant("writer")(*args)
        key = (tag, shared.grid_epoch(),
               tuple((a.shape, str(a.dtype)) for a in args))
        choice = _ASSEMBLY_CHOICE.get(key)
        if choice is None:
            if _measurement_would_oom(args):
                _ASSEMBLY_CHOICE[key] = choice = "writer"
                return variant(choice)(*args)

            def measure(name):
                fn = variant(name)
                scratch = tuple(a + 0 for a in args)  # donation-safe copies
                _, sec = igg.time_steps(wrap(fn), scratch, n1=2, n2=6,
                                        warmup=1)
                return sec

            _ASSEMBLY_CHOICE[key] = choice = _elect(measure)
        return variant(choice)(*args)

    return dispatch


def auto_dispatch(*, use_pallas, interpret, supported_fn, requirement,
                  xla_path, build_pallas_steps, donate_argnums,
                  family: str = "model", verify=None, extra_tiers=()):
    """The compiled-entry dispatcher shared by the model factories: a
    per-family :class:`igg.degrade.Ladder` whose rungs are `extra_tiers`
    (family-specific fast tiers, e.g. the Stokes trapezoid chunk tier) →
    the fused Mosaic tier (`{family}.mosaic`, admission-probed per call on
    the first field argument, lazily compiled through `igg.sharded` on
    first use) → the pure-XLA composition truth tier (`{family}.xla`).
    Every dispatch gets the ladder's runtime guards: quarantine skip,
    compile-failure capture, and — with `verify="first_use"` (or
    `IGG_VERIFY_KERNELS=1`) — a one-time numeric check of each fast tier
    against the truth rung before it serves real traffic.

    `build_pallas_steps()` returns the local (per-device) fused step
    function; `check_vma=not interpret` works around interpret-mode
    pallas_call not propagating shard_map's varying-manual-axes metadata.
    `extra_tiers` is a sequence of `igg.degrade.Tier` placed above the
    Mosaic rung (their `rung` indices are assigned by position).  The
    returned callable exposes the ladder as `.ladder` for observability
    and benchmarks."""
    from igg.degrade import Ladder, Tier

    def admit_mosaic(args):
        return pallas_applicable(use_pallas, args[0],
                                 supported_fn=supported_fn,
                                 requirement=requirement, interpret=interpret)

    def build_mosaic():
        return igg.sharded(build_pallas_steps(),
                           donate_argnums=donate_argnums,
                           check_vma=not interpret)

    tiers = list(extra_tiers)
    tiers.append(Tier(name=f"{family}.mosaic", rung=0, build=build_mosaic,
                      admit=admit_mosaic, required=use_pallas is True,
                      requirement=requirement))
    tiers.append(Tier(name=f"{family}.xla", rung=0,
                      build=lambda: xla_path, truth=True))
    for i, t in enumerate(tiers):
        t.rung = i
    ladder = Ladder(family, tiers, verify=verify)

    def dispatch(*args):
        return ladder.dispatch(*args)

    dispatch.ladder = ladder
    return dispatch


def apply_tuned(family, tune, *, n_inner, interpret, K, chunk_knob,
                use_pallas, band=None, banded_knob="auto"):
    """The chunk-tier families' shared tuned-config application (one
    implementation of the precedence rules — hm3d/wave2d/stokes3d used
    to carry private copies):

    - a cached winner's `K` fills an unset caller `K` — and is marked
      cache-sourced, so the family's `_fit_K` can FALL BACK to auto-fit
      when that K is inapplicable at this factory's `n_inner` (the cache
      key has no n_inner axis; only a CALLER-pinned K hard-refuses);
      a cached winner's `band` fills an unset caller `band` the same way
      (absent in pre-band cache entries — `.get`, never a KeyError);
    - a `<family>.mosaic` winner turns `chunk_knob` "auto" off, and a
      `<family>.banded` winner turns it off too (the streaming tier
      outranked the resident one on this machine);
    - a `<family>.mosaic` winner turns `banded_knob` "auto" off; a
      `<family>.banded` winner marks it "cached" — the family's banded
      admission then serves the tier WITHOUT requiring the resident
      tiers to refuse first (and without hard-raising if the cached
      config no longer fits this shape, unlike an explicit True);
    - a `<family>.xla` winner pins `use_pallas` off ONLY when the caller
      left ALL the knobs on auto — an explicit chunk/trapezoid/banded
      =True always outranks a cached winner.

    Returns `(K, K_from_cache, band, band_from_cache, chunk_knob,
    banded_knob, use_pallas, tuned)` — `tuned` is the raw winner entry
    (or None), so the factory can resolve its remaining auto knobs (the
    overlap axis, `igg.overlap.resolve_overlap`) from the same lookup."""
    from igg import autotune

    tuned = autotune.applied(family, tune, n_inner=n_inner,
                             interpret=interpret)
    K_from_cache = False
    if K is None and tuned and tuned.get("K"):
        K, K_from_cache = int(tuned["K"]), True
    band_from_cache = False
    if band is None and tuned and tuned.get("band"):
        band, band_from_cache = int(tuned["band"]), True
    winner = tuned.get("tier") if tuned else None
    if chunk_knob == "auto" and winner in (f"{family}.mosaic",
                                           f"{family}.banded"):
        chunk_knob = False
    if banded_knob == "auto":
        if winner == f"{family}.banded":
            banded_knob = "cached"
        elif winner == f"{family}.mosaic":
            banded_knob = False
    if use_pallas == "auto" and chunk_knob == "auto" and \
            banded_knob == "auto" and winner == f"{family}.xla":
        use_pallas = False
    return (K, K_from_cache, band, band_from_cache, chunk_knob,
            banded_knob, use_pallas, tuned)


def resolve_chunk_K(K, K_from_cache, supported, fit):
    """The family `_fit_K` body shared by the chunk tiers: an explicit
    K serves iff admissible (a caller pin hard-refuses on mismatch, a
    cache-sourced K falls back to the auto-fit — see
    :func:`apply_tuned`); otherwise the largest admissible K is
    fitted."""
    if K is not None:
        if supported(K):
            return K
        if not K_from_cache:
            return 0
    return fit()


def resolve_band(K, band, from_cache, supported, fit, bands=(8, 16)):
    """The banded-tier `(K, B)` resolution shared by the chunk families
    (the `resolve_chunk_K` rules lifted to the two-axis search space):
    an explicit/cached `(K, band)` pair serves iff admissible; caller
    pins hard-refuse (return None) on mismatch while cache-sourced
    values fall back to the auto-fit (`_vmem.fit_banded` through the
    family's `fit_*_band`).  `supported(K, B)` is the family admission
    gate; `fit(bands)` runs the family fit over a band tuple.  Returns
    `(K, B)` or None."""
    cand = (int(band),) if band is not None else tuple(bands)
    if K is not None:
        for b in cand:
            if supported(int(K), b):
                return (int(K), b)
        if not from_cache:
            return None
        return fit(tuple(bands))
    got = fit(cand)
    if got is None and band is not None and from_cache:
        got = fit(tuple(bands))
    return got
