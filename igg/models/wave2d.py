"""2-D acoustic wave on a staggered grid (velocity-pressure leapfrog).

The 2-D/1-D-halo counterpart of BASELINE.json config 3 ("2-D shallow-water /
acoustic wave, 1-D periodic halo").  Exercises exactly the staggered-array
machinery the reference is built for: pressure `P (nx, ny)` plus face
velocities `Vx (nx+1, ny)` and `Vy (nx, ny+1)` — `Vx` has overlap
`ol_x = 3` so its halo planes sit one cell deeper, handled by the per-array
`ol(dim, A)` rule (`/root/reference/src/shared.jl:81`).  All three fields are
exchanged in ONE grouped `update_halo` (the multi-field pipelining the
reference recommends, `/root/reference/src/update_halo.jl:19-20`).

Round 16: the family dispatches through the degradation ladder like every
other model — `wave2d.chunk` (K-step temporal blocking over the exchanged
dims, periodic meshes; `igg.ops.wave2d_pallas.fused_wave2d_chunk_steps`)
→ `wave2d.mosaic` (the whole coupled update in ONE fused kernel + the
grouped exchange; `fused_wave2d_step`) → `wave2d.xla` (the composition
truth) — with structured Admission refusals, compile-failure capture,
quarantine, and verify-on-first-use (`igg.degrade`).  The fast tiers are
f32-only; the f64 test configurations ride the truth rung unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    rho: float = 1.0      # density
    K: float = 1.0        # bulk modulus
    lx: float = 10.0
    ly: float = 10.0

    def spacing(self) -> Tuple[float, float]:
        return (self.lx / (igg.nx_g() - 1), self.ly / (igg.ny_g() - 1))

    def timestep(self) -> float:
        dx, dy = self.spacing()
        c = (self.K / self.rho) ** 0.5
        return min(dx, dy) / c / 4.1


def init_fields(params: Params = Params(), dtype=np.float32):
    """Gaussian pressure pulse; velocities at rest."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny = grid.nxyz[0], grid.nxyz[1]
    dx, dy = params.spacing()

    P0 = igg.zeros((nx, ny), dtype=dtype)
    X = igg.x_g_field(dx, P0)[:, None].astype(dtype)
    Y = igg.y_g_field(dy, P0)[None, :].astype(dtype)
    P = jnp.exp(-((X - params.lx / 2) ** 2 + (Y - params.ly / 2) ** 2)) + 0 * P0
    Vx = igg.zeros((nx + 1, ny), dtype=dtype)
    Vy = igg.zeros((nx, ny + 1), dtype=dtype)
    return P, Vx, Vy


def compute_step(P, Vx, Vy, *, dx, dy, dt, rho, K):
    """The pure coupled leapfrog update (no halo exchange): velocities on
    interior faces from the pressure gradient, then the pressure
    FULL-SHAPE from the fresh velocity divergence (Gauss-Seidel flavor —
    effective radius 2 per step through the chain).  The single source of
    arithmetic truth shared by the XLA composition, the fused Mosaic
    step, and the chunk tier's window core
    (`igg.ops.wave2d_pallas`)."""
    from igg.ops import interior_add

    Vx = interior_add(Vx, -dt / rho * (P[1:, :] - P[:-1, :]) / dx,
                      ((1, 1), (0, 0)))
    Vy = interior_add(Vy, -dt / rho * (P[:, 1:] - P[:, :-1]) / dy,
                      ((0, 0), (1, 1)))
    P = P - dt * K * ((Vx[1:, :] - Vx[:-1, :]) / dx
                      + (Vy[:, 1:] - Vy[:, :-1]) / dy)
    return P, Vx, Vy


def local_step(P, Vx, Vy, *, dx, dy, dt, rho, K, overlap: bool = False,
               assembly=None):
    """One leapfrog step over per-device local arrays.  With
    `overlap=True` the step is restructured by
    :func:`igg.hide_communication` (radius 2 — the velocity/pressure
    chain — so the grid needs overlap >= 3 along the exchanged dims)."""
    kw = dict(dx=dx, dy=dy, dt=dt, rho=rho, K=K)
    if overlap:
        return igg.hide_communication(
            (P, Vx, Vy),
            lambda Pb, Vxb, Vyb: compute_step(Pb, Vxb, Vyb, **kw),
            radius=2, assembly=assembly)
    P, Vx, Vy = compute_step(P, Vx, Vy, **kw)
    return igg.update_halo_local(P, Vx, Vy)


_PALLAS_REQ = (
    "the fused wave2d step requires TPU devices (or pallas_interpret="
    "True), a 2-D decomposition (dims[2] == 1) with an overlap-2 grid, "
    "f32 fields, and whole blocks small enough for VMEM "
    "(igg.ops.wave2d_pallas.wave2d_pallas_supported); use the XLA path "
    "otherwise.")

_CHUNK_REQ = (
    "the K-step wave2d chunk tier requires the fused per-step kernel's "
    "prerequisites plus: PERIODIC dims only, n_inner >= K+1 (one warm-up "
    "step + at least one full chunk), 2K-deep send slabs inside every "
    "split dimension's block, and an extended working set within the "
    "VMEM budget (igg.ops.wave2d_pallas.wave2d_chunk_supported); use "
    "chunk='auto' or the per-step tiers otherwise.")

_BANDED_REQ = (
    "the streaming banded wave2d chunk tier requires the fused per-step "
    "kernel's prerequisites plus: PERIODIC dims only, n_inner >= K+1, "
    "banded geometry (band B >= 8, B % 8 == 0, extended x span "
    "divisible into >= 2 bands), 2K-deep send slabs inside every split "
    "dimension's block, and a rolling band window set within the VMEM "
    "budget (igg.ops.wave2d_pallas.wave2d_banded_supported — note the "
    "compiled Mosaic instantiation is 3-D-only, so this tier serves "
    "interpret meshes; compiled TPU runs refuse with a structured "
    "reason); use banded='auto' or the resident tiers otherwise.")


def make_step(params: Params = Params(), *, donate: bool = True,
              overlap="auto", n_inner: int = 1, use_pallas="auto",
              pallas_interpret: bool = False, chunk="auto", K: int = None,
              banded="auto", band: int = None, verify=None, tune=None):
    """Compiled `(P, Vx, Vy) -> (P, Vx, Vy)` advancing `n_inner` steps in
    one SPMD program, dispatched through the family's degradation ladder
    (`wave2d.chunk` → `wave2d.mosaic` → `wave2d.xla`).

    `use_pallas`: "auto" (default) serves the fused Mosaic step when it
    applies (TPU devices or `pallas_interpret=True`, 2-D overlap-2 grid,
    f32 fields); False pins the XLA composition; True requires the kernel
    and raises `GridError` when inapplicable.  `chunk` admits the K-step
    temporal-blocking tier on top ("auto"/False/True, the
    `stokes3d.make_iteration` contract); `K` overrides the auto-fitted
    chunk depth.  `overlap` restructures the XLA composition with
    `igg.hide_communication` ("auto" follows the `IGG_OVERLAP` knob, then
    the autotuner's cached winner — the coupled leapfrog has radius 2, so
    admission needs overlap >= 3; the fused tiers have overlap semantics
    built in).  `verify="first_use"` (or `IGG_VERIFY_KERNELS=1`)
    numerically checks each fast tier against the truth before it serves
    traffic.  `tune` consults the autotuner's cached winner for this
    signature ("auto"/True/False; `igg.autotune` — True searches on a
    cache miss).

    `banded` admits the STREAMING banded chunk tier
    (`igg.ops.wave2d_pallas.fused_wave2d_banded_steps` — rolling VMEM
    window; the ladder rung below the resident chunk): "auto" (default)
    engages it only where the resident tier's `fit_wave2d_K` refuses,
    True requires it, False pins the resident tiers.  `band` overrides
    the auto-fitted band depth B (`fit_wave2d_band`)."""
    from jax import lax

    from igg.overlap import resolve_overlap

    dx, dy = params.spacing()
    dt = params.timestep()
    rho, bulk = params.rho, params.K
    # NOTE: the step closures capture only hashable scalars so recreated
    # closures share one compiled program (`igg.parallel._fn_key`).

    from ._dispatch import apply_tuned

    (K, K_from_cache, band, band_from_cache, chunk, banded,
     use_pallas, tuned) = apply_tuned(
        "wave2d", tune, n_inner=n_inner, interpret=pallas_interpret, K=K,
        chunk_knob=chunk, use_pallas=use_pallas, band=band,
        banded_knob=banded)
    overlap = resolve_overlap(overlap, family="wave2d", tuned=tuned,
                              radius=2, ndim=2,
                              chunk_active=(chunk is True
                                            or banded is True))

    def step_kw():
        return dict(dx=dx, dy=dy, dt=dt, rho=rho, K=bulk)

    def xla_steps(P, Vx, Vy):
        return lax.fori_loop(
            0, n_inner,
            lambda _, S: local_step(*S, **step_kw(), overlap=overlap),
            (P, Vx, Vy))

    donate_argnums = (0, 1, 2) if donate else ()
    xla_path = igg.sharded(xla_steps, donate_argnums=donate_argnums)

    if chunk is True and use_pallas is False:
        raise igg.GridError(_CHUNK_REQ)
    if banded is True and use_pallas is False:
        raise igg.GridError(_BANDED_REQ)
    if chunk is True or banded is True:
        use_pallas = True    # the chunk tiers ride the fused kernel

    def _fit_K(grid, lshape, dtype):
        from igg.ops.wave2d_pallas import (fit_wave2d_K,
                                           wave2d_chunk_supported)

        from ._dispatch import resolve_chunk_K

        if chunk is False or n_inner < 3:
            return 0
        return resolve_chunk_K(
            K, K_from_cache,
            lambda k: wave2d_chunk_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype,
                interpret=pallas_interpret),
            lambda: fit_wave2d_K(grid, tuple(lshape), n_inner - 1, dtype,
                                 interpret=pallas_interpret))

    def admit_chunk(args):
        from igg.degrade import Admission
        from igg.ops.wave2d_pallas import wave2d_pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        if chunk is False:
            return Admission.no("chunk=False pins the per-step tiers")
        if banded is True:
            return Admission.no("banded=True pins the streaming banded "
                                "tier")
        base = pallas_applicable("auto", args[0],
                                 supported_fn=wave2d_pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-step kernel (the chunk "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        P = args[0]
        if not _fit_K(grid, grid.local_shape_any(P), P.dtype):
            return Admission.no(
                "no chunk depth K admissible "
                "(igg.ops.wave2d_pallas.wave2d_chunk_supported)")
        return Admission.yes()

    def build_chunk():
        from igg.ops.wave2d_pallas import (fused_wave2d_chunk_steps,
                                           fused_wave2d_step)

        def chunk_steps(P, Vx, Vy):
            kw = step_kw()
            grid = igg.get_global_grid()
            Kf = _fit_K(grid, P.shape, P.dtype)
            if not Kf:    # admission gate and trace share _fit_K
                raise igg.GridError(_CHUNK_REQ)
            # Warm-up per-step kernel: consumes (and replaces) the entry
            # halos — the exchange-fresh window state the chunk's
            # validity argument requires, for ANY input.
            S = fused_wave2d_step(P, Vx, Vy, **kw,
                                  interpret=pallas_interpret)
            *S, done = fused_wave2d_chunk_steps(
                *S, n_inner=n_inner - 1, K=Kf, dx=dx, dy=dy, dt=dt,
                rho=rho, bulk=bulk, interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-step kernel
                S = lax.fori_loop(
                    0, n,
                    lambda _, T: tuple(fused_wave2d_step(
                        *T, **step_kw(), interpret=pallas_interpret)),
                    tuple(S))
            return tuple(S)

        return igg.sharded(chunk_steps, donate_argnums=donate_argnums,
                           check_vma=not pallas_interpret)

    def _fit_band(grid, lshape, dtype):
        """The `(K, B)` config the streaming banded tier will run (None
        when none applies) — shared by the tier's admission gate and its
        traced body so the two can never disagree."""
        from igg.ops.wave2d_pallas import (fit_wave2d_band,
                                           wave2d_banded_supported)

        from ._dispatch import resolve_band

        if banded is False or n_inner < 3:
            return None
        return resolve_band(
            K, band, K_from_cache or band_from_cache,
            lambda k, b: wave2d_banded_supported(
                grid, tuple(lshape), k, n_inner - 1, dtype, B=b,
                interpret=pallas_interpret),
            lambda bands: fit_wave2d_band(grid, tuple(lshape),
                                          n_inner - 1, dtype,
                                          interpret=pallas_interpret,
                                          bands=bands))

    def admit_banded(args):
        from igg.degrade import Admission
        from igg.ops.wave2d_pallas import wave2d_pallas_supported

        from ._dispatch import pallas_applicable

        if use_pallas is False:
            return Admission.no("use_pallas=False pins the XLA path")
        if banded is False:
            return Admission.no("banded=False pins the resident tiers")
        base = pallas_applicable("auto", args[0],
                                 supported_fn=wave2d_pallas_supported,
                                 requirement=_PALLAS_REQ,
                                 interpret=pallas_interpret)
        if not base:
            return Admission.no(f"fused per-step kernel (the banded "
                                f"tier's carrier) inadmissible: "
                                f"{getattr(base, 'reason', '')}")
        if n_inner < 3:
            return Admission.no(f"n_inner={n_inner} < 3: no warm-up plus "
                                f"full chunk fits")
        grid = igg.get_global_grid()
        P = args[0]
        lshape = grid.local_shape_any(P)
        if banded == "auto":
            if chunk is False:
                return Admission.no("chunk=False pins the per-step tiers "
                                    "(pass banded=True to require the "
                                    "streaming tier)")
            if _fit_K(grid, lshape, P.dtype):
                return Admission.no(
                    "the resident chunk tier serves this shape (the "
                    "banded rung engages where fit_wave2d_K refuses)")
        if not _fit_band(grid, lshape, P.dtype):
            return Admission.no(
                "no banded config (K, B) admissible "
                "(igg.ops.wave2d_pallas.wave2d_banded_supported)")
        return Admission.yes()

    def build_banded():
        from igg.ops.wave2d_pallas import (fused_wave2d_banded_steps,
                                           fused_wave2d_step)

        def banded_steps(P, Vx, Vy):
            kw = step_kw()
            grid = igg.get_global_grid()
            kb = _fit_band(grid, P.shape, P.dtype)
            if not kb:    # admission gate and trace share _fit_band
                raise igg.GridError(_BANDED_REQ)
            Kf, Bf = kb
            # Warm-up per-step kernel: the exchange-fresh entry state
            # the chunk validity argument requires.
            S = fused_wave2d_step(P, Vx, Vy, **kw,
                                  interpret=pallas_interpret)
            *S, done = fused_wave2d_banded_steps(
                *S, n_inner=n_inner - 1, K=Kf, B=Bf, dx=dx, dy=dy, dt=dt,
                rho=rho, bulk=bulk, interpret=pallas_interpret)
            n = n_inner - 1 - done
            if n:    # remainder through the per-step kernel
                S = lax.fori_loop(
                    0, n,
                    lambda _, T: tuple(fused_wave2d_step(
                        *T, **step_kw(), interpret=pallas_interpret)),
                    tuple(S))
            return tuple(S)

        return igg.sharded(banded_steps, donate_argnums=donate_argnums,
                           check_vma=not pallas_interpret)

    def build_pallas_steps():
        from igg.ops.wave2d_pallas import fused_wave2d_steps

        def pallas_steps(P, Vx, Vy):
            return fused_wave2d_steps(
                P, Vx, Vy, n_inner=n_inner, **step_kw(),
                interpret=pallas_interpret)

        return pallas_steps

    from igg.degrade import Tier
    from igg.ops.wave2d_pallas import wave2d_pallas_supported

    from ._dispatch import auto_dispatch

    chunk_tier = Tier(name="wave2d.chunk", rung=0, build=build_chunk,
                      admit=admit_chunk, required=chunk is True,
                      requirement=_CHUNK_REQ)
    banded_tier = Tier(name="wave2d.banded", rung=0, build=build_banded,
                       admit=admit_banded, required=banded is True,
                       requirement=_BANDED_REQ)
    return auto_dispatch(
        use_pallas=use_pallas, interpret=pallas_interpret,
        supported_fn=wave2d_pallas_supported, requirement=_PALLAS_REQ,
        xla_path=xla_path, build_pallas_steps=build_pallas_steps,
        donate_argnums=donate_argnums,
        family="wave2d", verify=verify,
        extra_tiers=(chunk_tier, banded_tier))


def run(nt: int, params: Params = Params(), dtype=np.float32,
        warmup: int = 1, n_inner: int = 1, use_pallas="auto",
        pallas_interpret: bool = False, tune=None):
    """Slope-timed run (see :func:`igg.time_steps`)."""
    P, Vx, Vy = init_fields(params, dtype=dtype)
    step = make_step(params, n_inner=n_inner, use_pallas=use_pallas,
                     pallas_interpret=pallas_interpret, tune=tune)
    n1 = max(1, nt // 4)
    state, sec = igg.time_steps(step, (P, Vx, Vy), n1=n1,
                                n2=max(nt - n1, n1 + 1),
                                warmup=max(warmup, 1))
    return state, sec / n_inner


# Numeric-integrity declaration (igg.integrity, round 19): the leapfrog
# acoustic scheme's discrete energy (Σ P² + Σ V² over owned cells, a
# constant-factor stand-in for P²/2K + ρv²/2) oscillates within a few
# percent on a stable timestep and DECAYS when open boundaries radiate —
# it never grows.  A bounded invariant with a loose tolerance: its job
# is catching large finite corruption, not certifying the scheme.
from igg import integrity as _integrity

_integrity.register_invariants("wave2d", [
    _integrity.Invariant("wave_energy", ("P", "Vx", "Vy"), moment=2,
                         kind="bounded", tol=0.25,
                         requires_periodic=False),
])
