"""2-D acoustic wave on a staggered grid (velocity-pressure leapfrog).

The 2-D/1-D-halo counterpart of BASELINE.json config 3 ("2-D shallow-water /
acoustic wave, 1-D periodic halo").  Exercises exactly the staggered-array
machinery the reference is built for: pressure `P (nx, ny)` plus face
velocities `Vx (nx+1, ny)` and `Vy (nx, ny+1)` — `Vx` has overlap
`ol_x = 3` so its halo planes sit one cell deeper, handled by the per-array
`ol(dim, A)` rule (`/root/reference/src/shared.jl:81`).  All three fields are
exchanged in ONE grouped `update_halo` (the multi-field pipelining the
reference recommends, `/root/reference/src/update_halo.jl:19-20`).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

import igg


@dataclasses.dataclass(frozen=True)
class Params:
    rho: float = 1.0      # density
    K: float = 1.0        # bulk modulus
    lx: float = 10.0
    ly: float = 10.0

    def spacing(self) -> Tuple[float, float]:
        return (self.lx / (igg.nx_g() - 1), self.ly / (igg.ny_g() - 1))

    def timestep(self) -> float:
        dx, dy = self.spacing()
        c = (self.K / self.rho) ** 0.5
        return min(dx, dy) / c / 4.1


def init_fields(params: Params = Params(), dtype=np.float32):
    """Gaussian pressure pulse; velocities at rest."""
    import jax.numpy as jnp

    grid = igg.get_global_grid()
    nx, ny = grid.nxyz[0], grid.nxyz[1]
    dx, dy = params.spacing()

    P0 = igg.zeros((nx, ny), dtype=dtype)
    X = igg.x_g_field(dx, P0)[:, None].astype(dtype)
    Y = igg.y_g_field(dy, P0)[None, :].astype(dtype)
    P = jnp.exp(-((X - params.lx / 2) ** 2 + (Y - params.ly / 2) ** 2)) + 0 * P0
    Vx = igg.zeros((nx + 1, ny), dtype=dtype)
    Vy = igg.zeros((nx, ny + 1), dtype=dtype)
    return P, Vx, Vy


def local_step(P, Vx, Vy, *, dx, dy, dt, rho, K):
    """One leapfrog step over per-device local arrays."""
    from igg.ops import interior_add

    Vx = interior_add(Vx, -dt / rho * (P[1:, :] - P[:-1, :]) / dx,
                      ((1, 1), (0, 0)))
    Vy = interior_add(Vy, -dt / rho * (P[:, 1:] - P[:, :-1]) / dy,
                      ((0, 0), (1, 1)))
    P = P - dt * K * ((Vx[1:, :] - Vx[:-1, :]) / dx
                      + (Vy[:, 1:] - Vy[:, :-1]) / dy)
    return igg.update_halo_local(P, Vx, Vy)


def make_step(params: Params = Params(), *, donate: bool = True,
              n_inner: int = 1):
    from jax import lax

    dx, dy = params.spacing()
    dt = params.timestep()

    def step(P, Vx, Vy):
        return lax.fori_loop(
            0, n_inner,
            lambda _, S: local_step(*S, dx=dx, dy=dy, dt=dt,
                                    rho=params.rho, K=params.K),
            (P, Vx, Vy))

    return igg.sharded(step, donate_argnums=(0, 1, 2) if donate else ())


def run(nt: int, params: Params = Params(), dtype=np.float32,
        warmup: int = 1, n_inner: int = 1):
    """Slope-timed run (see :func:`igg.time_steps`)."""
    P, Vx, Vy = init_fields(params, dtype=dtype)
    step = make_step(params, n_inner=n_inner)
    n1 = max(1, nt // 4)
    state, sec = igg.time_steps(step, (P, Vx, Vy), n1=n1,
                                n2=max(nt - n1, n1 + 1),
                                warmup=max(warmup, 1))
    return state, sec / n_inner
