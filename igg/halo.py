"""Halo-exchange engine — the performance core.

TPU-native re-design of `/root/reference/src/update_halo.jl`.  The reference's
machinery (persistent send/recv buffer pools, pinned host memory, CUDA
pack/unpack kernels, max-priority streams, MPI Isend/Irecv) collapses on TPU
into a single XLA program per call signature:

    pack   = plane slice of the boundary plane         (fused by XLA)
    send   = lax.ppermute shift along a mesh axis      (ICI collective-permute)
    unpack = aligned in-place slab updates, or one fused masked-select pass

Halos never touch the host; buffer management is XLA's job (donated inputs
make the update effectively in-place in HBM, matching the reference's
mutate-in-place semantics with zero extra copies).

**Plane representation (round 3).**  Internally, planes are rank-preserving
lazy slices (size 1 along the exchanged dimension) patched in masked-select
form, so plane algebra (corner propagation, open-boundary fallbacks) stays
in rank- and layout-homogeneous XLA fusions (a materialized keepdims
`(S0,S1,1)` plane is lane-padded up to ~40x).  Planes are squeezed to dense
2-D arrays (the reference's `halosize(dim,A)` shape,
`/root/reference/src/update_halo.jl:80`) only at the collective wire, where
they must materialize anyway — so ppermute traffic and multi-field stacking
move logical bytes, and nothing lane-padded ever reaches HBM or the ICI
links.

**Assembly strategies** (chosen per call signature by a static plan):
  - *in-place Pallas writers* (`igg.ops.halo_write`, TPU compiled mode —
    the default): partial-grid `pallas_call`s with the block aliased
    in-place that touch ONLY the dirty tiles.  When the lane dimension
    participates, that is one full RMW pass (the tile-granularity floor —
    see `igg/ops/halo_write.py` for the roofline argument); otherwise the
    dim-0/dim-1 slab writers touch a few MB.  Deterministic — XLA's layout
    assignment for the equivalent HLO is a compile lottery (171-516 us for
    the identical xyz update across surrounding-code variations, and
    grouped multi-field calls went superlinear); the writers pin it at
    203/102 us (f32/bf16 xyz at 256^3), ~22 us xy, cost strictly linear in
    the field count.  Self-wrap (single-device periodic) y/z sources are
    read from the block inside VMEM, so their planes never materialize.
  - *aligned-DUS* (XLA fallback — CPU meshes, rank != 3, unaligned or
    small shapes): per-dimension in-place updates — full planes along
    untiled (major) dimensions, tile-aligned slab read-modify-writes along
    the sublane/lane dimensions, performed in place on donated buffers.
  - *masked-select* (last resort, same fallbacks): ONE fused pass writing
    the whole block with received planes selected in (`jnp.where` on
    `broadcasted_iota`), in dimension order.

**Pair-emulated dtypes (round 5)** — f64 (the reference's Julia default),
int64, complex: the XLA plans are chosen by op-mix ('select' one-pass for
lane-active halo sets, all-DUS 'dus64' otherwise; `_assembly_plan`), the
received planes are fenced with `optimization_barrier` before assembly
(`_materialize_planes` — without the fence, copy-insertion charges
full-block defensive copies), and non-lane fields take the sequential
per-dim exchange+assemble form (`exchange_assemble_sequential` — the
reference's literal control flow, corner propagation for free).  Measured
at 256³/field: 519 µs xyz (2.49× the f32 writer for 2× the bytes), 53 µs
xy (2.1× the f32 slab writers).

The reference meets the same wall on GPUs — its maximally-strided dim-1
plane gets a dedicated custom kernel (`/root/reference/src/update_halo.jl:
439-462`); on TPU the tiled layout moves that worst case to the lane (minor)
dimension (the writer above), and the pack side of it is handled by a
Pallas one-pass plane extractor (`igg.ops.pack`, used for multi-plane
minor-dim sends where XLA materializes each plane in a separate relayout
pass — measured 491 us vs 92 us for the 4-plane y+z pack at 256^3).

Preserved reference semantics:
  - exactly one boundary plane is exchanged per side per dimension:
    send plane `ol-1` (left) / `s-ol` (right) (0-based; reference
    `/root/reference/src/update_halo.jl:386-394`), receive into plane `0` /
    `s-1` (`:397-405`);
  - per-array staggered overlap `ol(dim, A) = overlaps[dim] + (s_d - n_d)`
    (`/root/reference/src/shared.jl:81`); a dimension participates only when
    `ol >= 2` (`/root/reference/src/update_halo.jl:284`);
  - dimensions are exchanged **sequentially** (x, then y, then z) so corner
    and edge values propagate without diagonal messages
    (`/root/reference/src/update_halo.jl:36,130`);
  - open (non-periodic) boundaries: edge halos are simply not written
    (`/root/reference/test/test_update_halo.jl:727-732`) — realized here with
    `axis_index` masks instead of MPI_PROC_NULL neighbors;
  - periodic with one device along a dimension: a pure local copy, the analog
    of the reference's self-neighbor path
    (`/root/reference/src/update_halo.jl:516-532`).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

from . import shared
from .fields import spec_for
from .shared import AXIS_NAMES, NDIMS, GridError


# Compiled update programs keyed by (grid epoch, per-field (shape, dtype)).
# The analog of the reference's grow-only buffer pool keyed by field count and
# dtype (`/root/reference/src/update_halo.jl:86-255`): it exists so the hot
# loop never re-traces/re-allocates.
_compiled: Dict[tuple, object] = {}

# Test seam: force the Pallas-writer assembly path (interpret mode) on
# non-TPU meshes, so the engine-side spec building (wrap/ext classification,
# squeeze axes, recv wiring) is exercised by the CPU suite.
_FORCE_WRITER_INTERPRET = False

# Test seam: engage the stacked lane-active pair-emulated group update
# (`_stacked_lane64_update`) on non-TPU meshes too — on CPU the dtypes are
# native (no pair emulation) but the stacked program is dtype-agnostic, so
# the CPU suite pins its plane wiring/corner propagation for equivalence.
_FORCE_STACKED64 = False

# Fault-injection seam (igg.chaos.halo_corruption): a callable
# `(d, first, last) -> (first, last)` applied to the planes
# `exchange_planes` returns — the single primitive every wire path (grouped,
# sequential, stacked-64) funnels through — so received-halo corruption is
# injectable deterministically for the resilience test matrix.  Read at
# TRACE time: installers must clear the compiled caches (igg.chaos does).
_CHAOS_PLANE_TAP = None


def _chaos_tap(d: int, first, last):
    tap = _CHAOS_PLANE_TAP
    if tap is None:
        return first, last
    return tap(d, first, last)


def free_update_halo_buffers() -> None:
    """Drop all compiled halo programs (reference
    `/root/reference/src/update_halo.jl:95-107`)."""
    _compiled.clear()


# ---------------------------------------------------------------------------
# Argument checking (`/root/reference/src/update_halo.jl:574-604`)
# ---------------------------------------------------------------------------

def check_fields(grid, fields, local_shapes) -> None:
    no_halo = [
        i for i, (A, s) in enumerate(zip(fields, local_shapes))
        if all(grid.ol_of_local(d, s) < 2 for d in range(min(A.ndim, NDIMS)))
    ]
    if len(no_halo) > 1:
        raise GridError(
            f"The fields at positions {', '.join(map(str, no_halo))} have no "
            f"halo; remove them from the call.")
    if no_halo:
        raise GridError(
            f"The field at position {no_halo[0]} has no halo; remove it from "
            f"the call.")

    dups = [(i, j) for i in range(len(fields)) for j in range(i + 1, len(fields))
            if fields[i] is fields[j]]
    if dups:
        i, j = dups[0]
        raise GridError(
            f"The field at position {j} is a duplicate of the one at the "
            f"position {i}; remove the duplicate from the call.")

    diff = [i for i in range(1, len(fields))
            if fields[i].dtype != fields[0].dtype]
    if diff:
        raise GridError(
            f"The field at position {diff[0]} is of different type than the "
            f"first field; make sure that in a same call all fields are of "
            f"the same type.")


# ---------------------------------------------------------------------------
# Plane primitives
#
# Internally, planes are RANK-PRESERVING lazy slices (size 1 along the
# exchanged dimension): every plane-consuming op is then rank- and
# layout-homogeneous with the block, so XLA keeps the whole update in
# default-layout fusions.  Handing XLA rank-2 (squeezed) plane arrays makes
# its layout assignment pick transposed layouts for the surrounding fusions
# and pay whole-array relayout copies each iteration (measured: 560 us
# instead of 160 us at 256^3 f32).  Planes are squeezed ONLY at the
# collective wire (see `_wire_exchange`) — a keepdims (S,S,1) array is
# lane-padded up to ~40x on TPU, so the padded form must never be
# materialized (and never ride the ICI links).
# ---------------------------------------------------------------------------

def _plane(A, d: int, i: int):
    """Rank-preserving boundary plane (size 1 along `d`); the squeezed shape
    is the reference's `halosize(dim,A)`
    (`/root/reference/src/update_halo.jl:80`)."""
    from jax import lax
    return lax.slice_in_dim(A, i, i + 1, axis=d)


def _put_row(P, row, axis: int, i: int, form: str = "where"):
    """Row substitution in a pending plane.  Default masked-select form
    rather than dynamic-update-slice: the result stays a lazy elementwise
    expression over `P` and `row`, so plane patches fuse into whatever
    consumes the plane.  A DUS here forces the (possibly lazily-sliced)
    plane to materialize, and materializing a minor-dim plane is a
    relayout pass over the source tiles — measured ~90 us per plane pair
    at 256^3 f32, turning a 160 us update into 560 us.

    `form="dus"` is for pair-emulated 8/16-byte dtypes on the all-DUS
    'dus64' assembly plan, where the rule is reversed: ONE select
    anywhere in the x64/complex-rewritten graph drags every in-place
    update into defensive pair-split copies (a 441-vs-134 us engine
    regression at 256^3 f64 x+y), while plane-level DUS is native data
    movement there — and dus64 planes must materialize for the wire and
    the block-level DUS anyway, so nothing is lost to the forced
    materialization.  See `_patch_form`."""
    import jax.numpy as jnp
    from jax import lax

    if form == "dus":
        return lax.dynamic_update_slice_in_dim(P, row, i, axis=axis)
    idx = lax.broadcasted_iota(jnp.int32, P.shape, axis)
    return jnp.where(idx == i, row, P)


def _patch_form(shape, dtype, dims, on_tpu: bool) -> str:
    """Corner-patch form matched to the field's assembly plan, so the
    pair-emulated graphs stay homogeneous (`_assembly_plan` docstring):
    'dus' exactly when the field takes the all-DUS 'dus64' plan."""
    return ("dus" if _assembly_plan(shape, dtype, dims, on_tpu) == "dus64"
            else "where")


def active_dims(shape, grid) -> List[Tuple[int, int]]:
    """The (dim, ol) pairs of a local block's shape that have a halo
    (per-array staggered overlap `ol >= 2`,
    `/root/reference/src/update_halo.jl:284`)."""
    return [(d, grid.ol_of_local(d, shape))
            for d in range(min(len(shape), NDIMS))
            if grid.ol_of_local(d, shape) >= 2]


def moving_dims(dims_active, grid) -> List[Tuple[int, int]]:
    """The subset of `dims_active` along which halo planes actually change:
    a dimension with one device and an open boundary never receives anything
    (both global edges live on the same device — the reference's
    `has_neighbor` returning false on both sides), so the verb-level update
    can skip it entirely: the block already holds the stale planes."""
    return [(d, ol) for d, ol in dims_active
            if grid.dims[d] > 1 or grid.periods[d]]


# ---------------------------------------------------------------------------
# Exchange (operates on per-device squeezed planes)
# ---------------------------------------------------------------------------

def exchange_planes(left_send, right_send, stale_first, stale_last,
                    d: int, n: int, periodic: bool, disp: int = 1):
    """Plane-level neighbor shift along mesh axis `d`: returns the
    (new_first, new_last) halo planes of the local block.

    Open-boundary edge devices receive zeros from the (non-wrapping) permute;
    the stale planes are returned there instead — the reference's no-write
    semantics (`/root/reference/test/test_update_halo.jl:727-732`).  With one
    device along the axis, periodic exchange degenerates to a pure local copy
    (self-neighbor path, `/root/reference/src/update_halo.jl:516-532`).

    `disp` is the Cartesian neighbor displacement: partners are the ranks
    `disp` steps away, the semantics `MPI.Cart_shift` gives the reference's
    neighbor table (`/root/reference/src/init_global_grid.jl:78-81`) —
    realized here as ppermute shift tables with stride `disp` (and, when
    `disp` is a multiple of a periodic axis size, the degenerate self-copy).
    """
    import jax.numpy as jnp
    from jax import lax

    axis = AXIS_NAMES[d]
    if periodic and disp % n == 0:
        # Every rank is its own partner (n == 1, or disp wrapping onto
        # itself): a pure local copy, no collective.
        return _chaos_tap(d, right_send, left_send)
    if not periodic and disp >= n:
        # No rank has a partner `disp` steps away inside an open axis
        # (includes the open n == 1 case).
        return _chaos_tap(d, stale_first, stale_last)

    shift_down = ([(i, i - disp) for i in range(disp, n)]
                  + ([(i, (i - disp) % n) for i in range(min(disp, n))]
                     if periodic else []))
    shift_up = ([(i, i + disp) for i in range(n - disp)]
                + ([(i, (i + disp) % n) for i in range(max(n - disp, 0), n)]
                   if periodic else []))
    from_right = lax.ppermute(left_send, axis, shift_down)   # right nb's inner plane
    from_left = lax.ppermute(right_send, axis, shift_up)     # left nb's inner plane
    if periodic:
        return _chaos_tap(d, from_left, from_right)
    idx = lax.axis_index(axis)
    return _chaos_tap(d,
                      jnp.where(idx >= disp, from_left, stale_first),
                      jnp.where(idx < n - disp, from_right, stale_last))


def _wire_exchange(members, sends, stales, d: int, n: int, periodic: bool,
                   disp: int = 1):
    """Exchange dim `d` for a group of same-plane-shape fields: planes are
    SQUEEZED for the wire (dense logical bytes — the keepdims form is
    lane-padded up to ~40x) and, for several fields, stacked so ONE
    `ppermute` per side serves the whole group; received planes are
    re-expanded to keepdims (a metadata reshape that fuses/cancels).
    With one device along the axis nothing materializes — the lazy keepdims
    planes pass straight through (self-neighbor/no-write paths)."""
    import jax.numpy as jnp

    if n == 1:
        return [exchange_planes(sends[i][(d, 0)], sends[i][(d, 1)],
                                stales[i][(d, 0)], stales[i][(d, 1)],
                                d, n, periodic, disp)
                for i in members]

    def squeeze(P):
        return None if P is None else jnp.squeeze(P, axis=d)

    if len(members) == 1:
        i = members[0]
        nf_, nl_ = exchange_planes(
            squeeze(sends[i][(d, 0)]), squeeze(sends[i][(d, 1)]),
            squeeze(stales[i][(d, 0)]), squeeze(stales[i][(d, 1)]),
            d, n, periodic, disp)
        return [(jnp.expand_dims(nf_, d), jnp.expand_dims(nl_, d))]

    ls = jnp.stack([squeeze(sends[i][(d, 0)]) for i in members])
    rs = jnp.stack([squeeze(sends[i][(d, 1)]) for i in members])
    if periodic:
        sf = sl = None
    else:
        sf = jnp.stack([squeeze(stales[i][(d, 0)]) for i in members])
        sl = jnp.stack([squeeze(stales[i][(d, 1)]) for i in members])
    nf_, nl_ = exchange_planes(ls, rs, sf, sl, d, n, periodic, disp)
    return [(jnp.expand_dims(nf_[k], d), jnp.expand_dims(nl_[k], d))
            for k in range(len(members))]


def _patch_pending(store, key, d: int, s, val_first, val_last, pos: int,
                   form: str = "where"):
    """Overwrite the edge rows along exchanged dimension `d` of a pending
    plane of a *later* dimension `d2 = key[0]` (`d < d2`) with the received
    planes' values at that plane's position `pos` — the sequential
    corner/edge propagation of `/root/reference/src/update_halo.jl:36,130`.
    All keepdims: the patch rows are size 1 along both `d` and `d2`."""
    P = store.get(key)
    if P is None:
        return
    d2 = key[0]
    P = _put_row(P, _plane(val_first, d2, pos), d, 0, form)
    P = _put_row(P, _plane(val_last, d2, pos), d, s[d] - 1, form)
    store[key] = P


def exchange_all_dims(A, send: Dict, dims_active, grid,
                      stale: Dict = None, wrap=()) -> Dict:
    """Dimension-sequential plane-level exchange with corner/edge propagation
    for ONE field.  `send[(d, side)]` are the packed KEEPDIMS send planes
    (size 1 along `d`; squeezing for the collective wire is internal);
    returns `recv[d] = (new_first, new_last)` keepdims halo planes per
    active dimension.  See :func:`exchange_all_dims_grouped` for the
    semantics; this wrapper is the single-field form used by the fused
    kernels and :func:`igg.hide_communication`."""
    recvs = exchange_all_dims_grouped(
        [A.shape], [send], [dims_active], grid,
        stales=[stale], wraps=[wrap], blocks=[A])
    return recvs[0]


def exchange_all_dims_grouped(shapes, sends, dims_actives, grid,
                              stales=None, wraps=None,
                              blocks=None) -> List[Dict]:
    """Dimension-sequential plane exchange for several fields at once, with
    corner/edge propagation.  All planes in and out are KEEPDIMS (size 1
    along their dimension); squeezing happens only on the collective wire.

    Equivalence with the reference's sequential per-dimension update of the
    full array (`/root/reference/src/update_halo.jl:36,130`): what later
    dimensions see of the dimensions already exchanged is the received halo
    values inside their edge rows — so after each dimension's exchange, the
    *pending* send planes AND the pending stale (open-boundary fallback)
    planes of all later dimensions get their edge rows overwritten with the
    received/stale result.  The caller must assemble the returned planes in
    dimension order (later dimensions win the shared corner/edge cells, like
    the reference's later exchanges overwrite them).

    Dims in a field's `wrap` set (single periodic device, halo assembled by
    the caller — e.g. in-VMEM by the fused Pallas kernel) are not exchanged
    and need no send planes; their contribution to the sequential semantics
    is the self-alias patch: later dims' pending planes get the wrapped halo
    rows, which are aliases of the plane's own inner rows.

    Multi-field grouping: fields whose planes share a shape are exchanged
    with ONE `ppermute` per (dim, side) — their planes squeezed for the wire
    and stacked along a new leading axis (dense, so the stack moves logical
    bytes only).  This is the TPU analog of the reference's grouped-call
    pipelining note (`/root/reference/src/update_halo.jl:19-20`) with the
    collective count made independent of the field count.

    `blocks[i]`, when given, supplies the source array for any stale planes
    not already present in `stales[i]` (open-boundary fallbacks).
    """
    nf = len(shapes)
    sends = [dict(s) for s in sends]
    stales = [dict(st) if st else {} for st in (stales or [None] * nf)]
    wraps = [frozenset(w or ()) for w in (wraps or [()] * nf)]

    # Corner-patch form per field, matched to its assembly plan so the
    # pair-emulated 8/16-byte graphs stay homogeneous (`_patch_form`).
    on_tpu = _is_tpu(grid)
    forms = []
    for i in range(nf):
        P = next(iter(sends[i].values()), None)
        dt = P.dtype if P is not None else (
            blocks[i].dtype if blocks is not None else None)
        forms.append("where" if dt is None else _patch_form(
            shapes[i], dt, [d for d, _ in dims_actives[i]], on_tpu))

    # Stale planes: what an open-boundary edge device keeps (the reference's
    # no-write semantics, `/root/reference/test/test_update_halo.jl:727-732`).
    # Extracted lazily from the block only for non-periodic dims — periodic
    # exchanges never read them.
    for i in range(nf):
        s = shapes[i]
        for d, ol in dims_actives[i]:
            if d in wraps[i] or grid.periods[d]:
                stales[i][(d, 0)] = stales[i][(d, 1)] = None
            else:
                for side, pos in ((0, 0), (1, s[d] - 1)):
                    if (d, side) not in stales[i]:
                        stales[i][(d, side)] = _plane(blocks[i], d, pos)

    all_dims = sorted({d for da in dims_actives for d, _ in da})
    recvs: List[Dict] = [{} for _ in range(nf)]
    for d in all_dims:
        fidx = [i for i in range(nf) if d in [x for x, _ in dims_actives[i]]]
        wrap_f = [i for i in fidx if d in wraps[i]]
        exch_f = [i for i in fidx if d not in wraps[i]]

        # Wrap dims (caller-assembled self-alias): patch every later pending
        # plane's edge rows with the plane's own inner (send-position) rows.
        for i in wrap_f:
            s = shapes[i]
            ol = dict(dims_actives[i])[d]
            later = [d2 for d2, _ in dims_actives[i] if d2 > d
                     and d2 not in wraps[i]]
            for d2 in later:
                for side2 in (0, 1):
                    for store in (sends[i], stales[i]):
                        P = store.get((d2, side2))
                        if P is None:
                            continue
                        P = _put_row(P, _plane(P, d, s[d] - ol), d, 0,
                                     forms[i])
                        P = _put_row(P, _plane(P, d, ol - 1), d, s[d] - 1,
                                     forms[i])
                        store[(d2, side2)] = P

        if not exch_f:
            continue

        # One collective per (dim, side) for all same-shaped planes
        # (squeezed + stacked on the wire; see `_wire_exchange`).
        n = grid.dims[d]
        periodic = bool(grid.periods[d])
        groups: Dict[tuple, List[int]] = {}
        for i in exch_f:
            P = sends[i][(d, 0)]
            groups.setdefault((tuple(P.shape), str(P.dtype)), []).append(i)
        for shape_key, members in groups.items():
            per_field = _wire_exchange(members, sends, stales, d, n, periodic,
                                       getattr(grid, "disp", 1))
            for i, (new_first, new_last) in zip(members, per_field):
                recvs[i][d] = (new_first, new_last)
                s = shapes[i]
                for d2, ol2 in dims_actives[i]:
                    if d2 <= d or d2 in wraps[i]:
                        continue
                    for side2, p_send, p_stale in ((0, ol2 - 1, 0),
                                                   (1, s[d2] - ol2,
                                                    s[d2] - 1)):
                        _patch_pending(sends[i], (d2, side2), d, s,
                                       new_first, new_last, p_send,
                                       forms[i])
                        _patch_pending(stales[i], (d2, side2), d, s,
                                       new_first, new_last, p_stale,
                                       forms[i])
    return recvs


def _pair_emulated(dtype) -> bool:
    """8/16-byte dtypes the XLA:TPU x64/complex rewriters emulate as pairs
    of 32-bit arrays (f64, i64/u64, complex64, complex128)."""
    import numpy as np

    return np.dtype(dtype).itemsize >= 8


def plane_bytes_by_mode(local_shapes, dtypes, grid
                        ) -> Dict[Tuple[str, str], int]:
    """The `igg_halo_plane_bytes_total` accounting of one `update_halo`
    call, broken down by ``(dim, mode)``: per field and per moving dim,
    two boundary planes per device (each exchanged plane counted once),
    summed over the mesh.  `mode` is ``{wire|local}_{grouped|stacked}``:

    - *wire* — the dim is split across devices, so the planes ride the
      collective (ICI links on a real slice); *local* — single-device
      periodic self-wrap, pure HBM traffic;
    - *stacked* — the field rides the pair-emulated lane-active group
      program (`_stacked_lane64_update`: >= 2 same-shaped 8/16-byte
      fields through ONE stacked block); *grouped* — every other engine
      path (grouped pre-extracted, sequential per-dim, and the Pallas
      writers — one collective per (dim, side) for same-shaped planes in
      all of them).  The classification mirrors the engine's
      stacked-group election on local shapes (pair-emulated fields never
      take the writer path on hardware, so writer eligibility cannot
      flip it).

    Host arithmetic only — this is also the analytic model
    :func:`igg.comm.plane_bytes_model` exposes, so counter deltas
    reconcile against it exactly."""
    import numpy as np

    local_shapes = [tuple(s) for s in local_shapes]
    movings = [moving_dims(active_dims(ls, grid), grid)
               for ls in local_shapes]
    stack_on = _is_tpu(grid) or _FORCE_STACKED64
    groups: Dict[tuple, List[int]] = {}
    for i, ls in enumerate(local_shapes):
        if (stack_on and len(ls) == 3 and _pair_emulated(dtypes[i])
                and any(d == len(ls) - 1 for d, _ in movings[i])):
            key = (ls, str(np.dtype(dtypes[i])), tuple(movings[i]))
            groups.setdefault(key, []).append(i)
    stacked = {i for g in groups.values() if len(g) >= 2 for i in g}
    out: Dict[Tuple[str, str], int] = {}
    for i, ls in enumerate(local_shapes):
        elems = 1
        for v in ls:
            elems *= int(v)
        itemsize = np.dtype(dtypes[i]).itemsize
        path = "stacked" if i in stacked else "grouped"
        for d, _ in movings[i]:
            transport = "wire" if grid.dims[d] > 1 else "local"
            key = ("xyz"[d] if d < 3 else str(d), f"{transport}_{path}")
            out[key] = out.get(key, 0) + (2 * (elems // int(ls[d]))
                                          * itemsize * grid.nprocs)
    return out


def _materialize_planes(out, planes):
    """`optimization_barrier` fence between a block and the halo planes
    about to be written into it — the KEY unlock for pair-emulated dtypes
    (round-5 on-chip study): without it, the planes are lazy slices of the
    very buffer the in-place updates overwrite, XLA's copy-insertion sees a
    read-after-write hazard against the whole block, and every loop
    iteration pays full-block defensive copies (the f64 x+y update at
    256^3 measured 466 us with 4 full copies; with the fence the SAME
    program is 35 us with zero copies — the fence forces the ~MB of planes
    to materialize first, which the exchange wire needs anyway).  Returns
    `(out, planes)` re-fenced; `planes` is a flat list."""
    from jax import lax

    fenced = lax.optimization_barrier((out, *planes))
    return fenced[0], list(fenced[1:])


def _fence_recv(out, recv: Dict, dims_active, on_tpu: bool):
    """Apply the `_materialize_planes` fence to a block and its received
    planes when the dtype is pair-emulated (no-op otherwise); returns the
    re-fenced `(out, recv)`.  Shared by the engine's grouped XLA assembly
    and `assemble_field` so the fence invariant cannot desynchronize."""
    if not (_pair_emulated(out.dtype) and on_tpu):
        return out, recv
    dd = [d for d, _ in dims_active]
    out, flat = _materialize_planes(out, [p for d in dd for p in recv[d]])
    return out, {d: (flat[2 * j], flat[2 * j + 1])
                 for j, d in enumerate(dd)}


def exchange_assemble_sequential(fields, dims_actives, grid, plans):
    """Sequential per-dimension exchange-and-assemble for XLA-plan fields:
    for each dimension in ascending order, send planes are extracted from
    the current (partially updated) blocks — as LAZY slices, except
    minor-dim planes of 32-bit fields that must materialize for the wire,
    which ride the `pack_planes` one-pass extractor — exchanged, and
    assembled straight back into the blocks with the field's plan form.

    This is the reference's literal control flow
    (`/root/reference/src/update_halo.jl:36,130` — pack/exchange/unpack one
    dimension at a time), and on TPU it is the right shape for the
    pair-emulated 8/16-byte dtypes: corner/edge propagation comes for free
    (later dims' planes are sliced from blocks that already contain the
    earlier dims' received values), so no `_put_row` patches are needed —
    and it was exactly those plane-space patches that broke the
    homogeneous-graph rule of `_assembly_plan` (engine 448 us vs 134 us
    standalone for the f64 x+y update at 256^3; with this path the engine
    matches the standalone number).  With one device along a periodic
    dimension everything stays lazy end-to-end, and the fully fused
    'select' program runs at the byte-proportional floor (f64 xyz 256^3:
    one pass at HBM streaming rate).

    The grouped pre-extracted form (:func:`exchange_all_dims_grouped`)
    remains the engine path for Pallas-writer fields, whose assembly is an
    opaque kernel that needs all planes materialized up front."""
    import jax.numpy as jnp

    from .ops.pack import pack_planes, pack_planes_supported

    nf = len(fields)
    vb = list(fields)
    on_tpu = _is_tpu(grid)
    all_dims = sorted({d for da in dims_actives for d, _ in da})
    for d in all_dims:
        fidx = [i for i in range(nf) if d in dict(dims_actives[i])]
        if not fidx:
            continue
        n = grid.dims[d]
        periodic = bool(grid.periods[d])
        sends: Dict[int, Dict] = {}
        stales: Dict[int, Dict] = {}
        for i in fidx:
            s = vb[i].shape
            ol = dict(dims_actives[i])[d]
            reqs = [(d, ol - 1), (d, s[d] - ol)]       # send lo/hi
            if not periodic:
                reqs += [(d, 0), (d, s[d] - 1)]        # stale lo/hi
            # Minor-dim planes that must materialize for a ppermute ride
            # the pack_planes one-pass extractor, exactly like the grouped
            # path (ADVICE r5 item 1): XLA otherwise pays one relayout per
            # y/z plane (measured 491 vs 92 us for the 4-plane pack at
            # 256^3 f32).  Pair-emulated dtypes keep the lazy slices — the
            # sequential form exists for their homogeneous-graph rule, and
            # the measured win was for 32-bit fields (pack is 32-bit-only
            # in Mosaic anyway).
            if (on_tpu and n > 1 and d >= 1 and vb[i].ndim == 3
                    and not _pair_emulated(vb[i].dtype)
                    and pack_planes_supported(s, vb[i].dtype)):
                planes = [jnp.expand_dims(p, d)
                          for p in pack_planes(vb[i], reqs)]
            else:
                planes = [_plane(vb[i], d, pos) for _, pos in reqs]
            sends[i] = {(d, 0): planes[0], (d, 1): planes[1]}
            stales[i] = ({(d, 0): None, (d, 1): None} if periodic
                         else {(d, 0): planes[2], (d, 1): planes[3]})
        groups: Dict[tuple, List[int]] = {}
        for i in fidx:
            P = sends[i][(d, 0)]
            groups.setdefault((tuple(P.shape), str(P.dtype)), []).append(i)
        for members in groups.values():
            per_field = _wire_exchange(members, sends, stales, d, n,
                                       periodic, getattr(grid, "disp", 1))
            for i, (first, last) in zip(members, per_field):
                ol = dict(dims_actives[i])[d]
                B, rv = _fence_recv(vb[i], {d: (first, last)}, [(d, ol)],
                                    on_tpu)
                vb[i] = assemble_planes(B, rv, [(d, ol)], plan=plans[i])
    return vb


def _stacked_lane64_update(blocks, dims, grid):
    """Grouped update of >= 2 same-shaped lane-active PAIR-EMULATED
    fields (f64 — the reference's Julia default — i64, complex) through
    ONE stacked array: the blocks are stacked along a new leading axis,
    the pre-extracted pending planes (lazy keepdims slices of the stack)
    ride one ppermute per (dim, side) for the whole group, corners
    propagate by where-form pending-plane patches, and assembly is ONE
    fenced select pass over the stacked block.

    Why (VERDICT r5 weak #1): the per-field grouped path gives each f64
    field its own pair-emulated buffer through the composed program, and
    the XLA:TPU buffer assigner charges per-field while-loop carry
    copies — 691/807 us *per field* at 2/4 fields vs the 519 us
    single-field round-4 bar at 256^3.  One stacked block is ONE pair
    buffer: one set of carry copies and one homogeneous select chain
    over all fields (the `_assembly_plan` 'select' op-mix rules), with
    the stack/unstack reshapes fusing into the pass.  Array axes are the
    mesh axes shifted by one (array axis d+1 <-> mesh dim d)."""
    import jax.numpy as jnp
    from jax import lax

    s = blocks[0].shape
    nf = len(blocks)
    B = jnp.stack(blocks)
    dd = sorted(d for d, _ in dims)
    ols = dict(dims)
    disp = getattr(grid, "disp", 1)

    sends: Dict = {}
    stales: Dict = {}
    for d in dd:
        ax = d + 1
        ol = ols[d]
        sends[(d, 0)] = _plane(B, ax, ol - 1)
        sends[(d, 1)] = _plane(B, ax, s[d] - ol)
        if grid.periods[d]:
            stales[(d, 0)] = stales[(d, 1)] = None
        else:
            stales[(d, 0)] = _plane(B, ax, 0)
            stales[(d, 1)] = _plane(B, ax, s[d] - 1)

    recv: Dict = {}
    for d in dd:
        ax = d + 1
        n = grid.dims[d]
        periodic = bool(grid.periods[d])
        if n == 1:
            first, last = exchange_planes(
                sends[(d, 0)], sends[(d, 1)], stales[(d, 0)],
                stales[(d, 1)], d, n, periodic, disp)
        else:
            sq = (lambda P: None if P is None
                  else jnp.squeeze(P, axis=ax))
            first, last = exchange_planes(
                sq(sends[(d, 0)]), sq(sends[(d, 1)]), sq(stales[(d, 0)]),
                sq(stales[(d, 1)]), d, n, periodic, disp)
            first = jnp.expand_dims(first, ax)
            last = jnp.expand_dims(last, ax)
        recv[d] = (first, last)
        # Sequential corner/edge propagation into the later dims' pending
        # planes (where-form — the 'select' plan's homogeneous patch).
        for d2 in dd:
            if d2 <= d:
                continue
            ax2 = d2 + 1
            ol2 = ols[d2]
            for side2, p_send, p_stale in ((0, ol2 - 1, 0),
                                           (1, s[d2] - ol2, s[d2] - 1)):
                for store, pos in ((sends, p_send), (stales, p_stale)):
                    P = store.get((d2, side2))
                    if P is None:
                        continue
                    P = _put_row(P, _plane(first, ax2, pos), ax, 0)
                    P = _put_row(P, _plane(last, ax2, pos), ax, s[d] - 1)
                    store[(d2, side2)] = P

    B, flat = _materialize_planes(B, [p for d in dd for p in recv[d]])
    for j, d in enumerate(dd):
        idx = lax.broadcasted_iota(jnp.int32, B.shape, d + 1)
        B = jnp.where(idx == 0, flat[2 * j],
                      jnp.where(idx == s[d] - 1, flat[2 * j + 1], B))
    return [B[k] for k in range(nf)]


# ---------------------------------------------------------------------------
# Assembly
# ---------------------------------------------------------------------------

# Sublane tile height by itemsize (TPU (8,128)-class tiling; 16-bit packs two
# values per sublane row pair, 8-bit four).
_SUBLANE = {1: 32, 2: 16, 4: 8, 8: 8}
_LANE = 128


def _slab_sizes(shape, dtype) -> Dict[int, int]:
    """Minimal tile-aligned in-place write granularity per dimension: 1 for
    untiled (major) dims, the sublane tile for dim N-2, the lane tile for
    dim N-1."""
    import numpy as np

    nd = len(shape)
    ts = _SUBLANE.get(np.dtype(dtype).itemsize, 8)
    out = {}
    for d in range(nd):
        if d == nd - 1:
            out[d] = _LANE
        elif d == nd - 2:
            out[d] = ts
        else:
            out[d] = 1
    return out


def _assembly_plan(shape, dtype, dims, on_tpu: bool = False) -> str:
    """'dus' when every participating dimension admits a tile-aligned
    in-place slab update (size a multiple of its tile and at least two
    tiles), else 'select'.  Measured at 256^3: the two plans tie for f32
    xyz (~165 us), DUS wins for bf16 xyz (138 vs 211 us) and wins big when
    the lane dim does not participate (xy: 9-20 us vs a full pass), so DUS
    is preferred whenever feasible; select is the fallback for small or
    unaligned local shapes (e.g. the CPU-mesh test grids).

    8-byte dtypes on TPU (f64 — the reference's Julia default — plus
    complex64, and complex128 at 16 bytes) are emulated by the XLA:TPU
    x64/complex rewriter as pairs of f32 arrays.  Under that emulation the
    op mix decides everything (round-5 on-chip study, 256^3 f64):

      - graphs of bare `dynamic_update_slice` ops stay native data
        movement — the whole x+y boundary-slab update compiles to
        in-place plane writes;
      - ONE select/where anywhere drags the entire graph into pair land:
        the block is X64Split into two f32 arrays, every DUS is rewritten
        against the halves, and defensive full-array copies appear around
        the in-place updates (measured: the same x+y DUS chain jumps to
        ~0.9-1.3 ms when a lane select joins the program; 3 chained
        selects never fuse — 1473 us — the round-4 superlinear grouped
        rows).

    The round-5 variant matrix sharpened the rule to two invariants:

      1. Keep the pair graph HOMOGENEOUS.  All-DUS graphs are native
         data movement and an all-select chain compiles to ONE fused
         pass; MIXING the two poisons the program with defensive
         pair-split copies (the xyz update as bare DUS x/y + one lane
         select: 1314 us vs the 508 us all-select engine number; the
         corner-patch form is matched to the plan by `_patch_form`).
      2. Fence the planes (`_materialize_planes`): planes left as lazy
         slices of the block being updated read-after-write-hazard the
         whole buffer, and copy-insertion charges full-block copies per
         loop iteration (x+y at 256^3: 441 us -> 58 us engine-measured
         once fenced).

    With both applied, lane-ACTIVE 8/16-byte sets take the all-'select'
    plan (the only form that can touch the lane dim without a relayout:
    per-lane DUS costs a full relayout pass, 348 us; lane concat 920 us;
    barrier-fenced all-DUS incl. lane planes 930 us) and run at 508 us
    for xyz at 256^3 — 2.50x the f32 writer pass for 2x the bytes, the
    residual being while-loop carry copies the XLA:TPU buffer assigner
    inserts for pair types (single-application compiles are copy-free).
    Sets that DON'T touch the lane dim take the all-DUS 'dus64' plan:
    58 us x+y at 256^3, 2.2x the f32 slab writers."""
    if on_tpu and _pair_emulated(dtype):
        if (len(shape) - 1) in dims:
            return "select"
        return "dus64"
    slabs = _slab_sizes(shape, dtype)
    for d in dims:
        t = slabs[d]
        if t > 1 and (shape[d] % t != 0 or shape[d] < 2 * t):
            return "select"
    return "dus"


def assemble_planes(out, recv: Dict, dims_active, plan: Optional[str] = None):
    """Write the received (keepdims) halo planes into `out` in dimension
    order (later dimensions win the shared corner cells), using the
    aligned-DUS or masked-select strategy (module docstring).

    Why masked-select instead of naive per-plane `dynamic_update_slice` (the
    direct translation of the reference's in-place unpack,
    `/root/reference/src/update_halo.jl:397-405`): an unaligned minor-dim
    plane write makes XLA materialize a full-array copy per dimension —
    measured 3 full copies per update at 256^3 on TPU v5e.  The masked-select
    chain fuses into a single read+write pass; the aligned-DUS path goes
    further and writes only the boundary slabs in place (donated buffers)."""
    import jax.numpy as jnp
    from jax import lax

    s = out.shape
    dims = [d for d, _ in dims_active]
    if plan is None:
        plan = _assembly_plan(s, out.dtype, dims)
    if plan == "select":
        for d in dims:
            idx = lax.broadcasted_iota(jnp.int32, s, d)
            out = jnp.where(idx == 0, recv[d][0],
                            jnp.where(idx == s[d] - 1, recv[d][1], out))
        return out
    if plan == "dus64":
        # Pair-emulated 8/16-byte dtypes, lane dim NOT in the halo set
        # (see `_assembly_plan`): bare plane DUSes only — pure data
        # movement under the x64/complex rewriter, nothing elementwise.
        for d in dims:
            first, last = recv[d]
            out = lax.dynamic_update_slice_in_dim(out, first, 0, axis=d)
            out = lax.dynamic_update_slice_in_dim(out, last, s[d] - 1,
                                                  axis=d)
        return out

    slabs = _slab_sizes(s, out.dtype)
    for d in dims:
        first, last = recv[d]
        t = slabs[d]
        if t == 1:
            out = lax.dynamic_update_slice_in_dim(out, first, 0, axis=d)
            out = lax.dynamic_update_slice_in_dim(out, last, s[d] - 1,
                                                  axis=d)
        else:
            slab = lax.slice_in_dim(out, 0, t, axis=d)
            idx = lax.broadcasted_iota(jnp.int32, slab.shape, d)
            slab = jnp.where(idx == 0, first, slab)
            out = lax.dynamic_update_slice_in_dim(out, slab, 0, axis=d)
            slab = lax.slice_in_dim(out, s[d] - t, s[d], axis=d)
            idx = lax.broadcasted_iota(jnp.int32, slab.shape, d)
            slab = jnp.where(idx == t - 1, last, slab)
            out = lax.dynamic_update_slice_in_dim(out, slab, s[d] - t,
                                                  axis=d)
    return out


# ---------------------------------------------------------------------------
# The update itself
# ---------------------------------------------------------------------------

def _is_tpu(grid) -> bool:
    try:
        return grid.mesh.devices.flat[0].platform == "tpu"
    except Exception:
        return False


_ASSEMBLY_MODES = (None, "xla", "pallas")

_PALLAS_NEEDS_TPU = (
    "assembly='pallas' requires TPU devices (the writers are TPU kernels); "
    "use the default or 'xla' elsewhere.")
_PALLAS_UNSUPPORTED = (
    "assembly='pallas' was forced but the Pallas writers do not support "
    "this field (rank-3 blocks of 16/32-bit elements with tile-compatible "
    "shapes; 64-bit dtypes are toolchain-blocked on TPU — see "
    "igg/ops/halo_write.py); use the default or 'xla'.")


def _raise_pallas_unsupported():
    """The forced-writer (`assembly='pallas'`) refusal: names the
    quarantine when that is why the writers cannot serve, the capability
    contract otherwise."""
    from . import degrade

    q = degrade.status().get(degrade.HALO_WRITER_TIER)
    if q is not None:
        raise GridError(
            f"assembly='pallas' was forced but the writer tier is "
            f"quarantined ({q.reason}): {q.error or '<no capture>'}.  "
            f"igg.degrade.reset({degrade.HALO_WRITER_TIER!r}) re-admits "
            f"it.")
    raise GridError(_PALLAS_UNSUPPORTED)


def _check_assembly(assembly):
    if assembly not in _ASSEMBLY_MODES:
        raise GridError(
            f"assembly={assembly!r}: expected one of None (default: the "
            f"in-place Pallas writers on TPU), 'xla' (masked-select/"
            f"aligned-DUS plans, fusable into a producing stencil), or "
            f"'pallas' (explicitly request the writers).")


def assemble_field(out, recv: Dict, dims_active, grid, assembly=None):
    """Write received (keepdims) halo planes into `out` with the best
    available strategy: the in-place Pallas writers on TPU (deterministic,
    see :mod:`igg.ops.halo_write`), the XLA plans elsewhere — or the plan
    forced by `assembly` ("pallas"/"xla"; see :func:`update_halo` for when
    each wins).  Unlike the engine-internal writer path, every dim's planes
    come from `recv` ("ext" sources) — used by
    :func:`igg.hide_communication`, whose planes are slab-computed arrays
    rather than slices of the block."""
    import jax.numpy as jnp

    from .ops.halo_write import halo_write_slabs, write_lane_active

    _check_assembly(assembly)
    on_tpu = _is_tpu(grid)
    xla_plan = _assembly_plan(out.shape, out.dtype,
                              [d for d, _ in dims_active],
                              on_tpu=on_tpu)

    def xla_assemble(out, recv):
        out, recv = _fence_recv(out, recv, dims_active, on_tpu)
        return assemble_planes(out, recv, dims_active, plan=xla_plan)

    if assembly == "xla" or not (on_tpu or _FORCE_WRITER_INTERPRET):
        if assembly == "pallas":
            raise GridError(_PALLAS_NEEDS_TPU)
        return xla_assemble(out, recv)
    _, use_writer = _writer_dims(out, dims_active, grid, all_ext=True)
    if not use_writer:
        if assembly == "pallas":
            _raise_pallas_unsupported()
        return xla_assemble(out, recv)
    specs = [(d, "ext", jnp.squeeze(recv[d][0], d),
              jnp.squeeze(recv[d][1], d)) for d, _ in dims_active]
    interp = _FORCE_WRITER_INTERPRET
    if any(d == out.ndim - 1 for d, _ in dims_active):
        return write_lane_active(out, specs, frozenset(), interpret=interp)
    return halo_write_slabs(out, specs, interpret=interp)


def _writer_dims(A, dims, grid, all_ext: bool = False):
    """Partition a field's moving dims for the one-pass Pallas writer path:
    returns `(wraps, use_writer)` where `wraps` are the single-device
    periodic dims whose halos the writer assembles from in-VMEM self-wrap
    sources (never materializing a lane-padded plane), and `use_writer` says
    the field's assembly goes through :func:`igg.ops.halo_write.halo_write`
    (TPU, rank-3, supported dtype, lane dim participating — elsewhere the
    XLA aligned-DUS/select plans are faster or required).

    64-bit dtypes: the writers' u32 lane-paired view is implemented and
    tested (interpret seam), but BLOCKED on current XLA:TPU — the x64
    rewriter has no 64-bit `bitcast-convert` and Mosaic rejects f64
    kernels outright (gated in `halo_write_supported`) — so on hardware
    f64 rides the PINNED XLA plan: `_assembly_plan` deterministically
    picks aligned-DUS for tile-aligned shapes (masked-select otherwise),
    the reference-default-Float64 story of VERDICT r3 item 4's fallback
    clause.

    A quarantined writer tier (`igg.degrade.HALO_WRITER_TIER` — a captured
    Mosaic compile failure, or an explicit `igg.degrade.quarantine`) turns
    the writers off here, the single election point every assembly path
    consults, so the XLA plans serve instead; quarantining/resetting the
    tier clears the compiled halo caches because this decision is read at
    TRACE time."""
    from . import degrade
    from .ops.halo_write import (ext_planes_supported, halo_write_supported,
                                 slab_write_supported)

    wraps = frozenset(d for d, _ in dims
                      if grid.dims[d] == 1 and grid.periods[d])
    if degrade.is_quarantined(degrade.HALO_WRITER_TIER):
        return wraps, False
    dd = [d for d, _ in dims]
    lane_active = any(d == A.ndim - 1 for d, _ in dims)
    interp = _FORCE_WRITER_INTERPRET
    if lane_active:
        use_writer = (halo_write_supported(A.shape, A.dtype, interp)
                      and _assembly_plan(A.shape, A.dtype, dd) != "select")
    else:
        use_writer = slab_write_supported(A.shape, A.dtype, dd, interp)
    # Received (ext) planes ride partial-grid BlockSpecs with Mosaic
    # tile-alignment requirements; self-wrap planes never materialize and
    # dim-0 planes are passed whole (`ext_planes_supported`).  With
    # `all_ext` (assemble_field: every plane arrives dense) wrap dims
    # count as ext too.  The gate receives the FULL spec dim list and the
    # wrap set the dispatcher will see, so its col/bx pricing runs the
    # same `lane_dispatch` the writer does.
    ext_dims = [d for d in dd if d != 0 and (all_ext or d not in wraps)]
    if use_writer and not interp:
        use_writer = ext_planes_supported(
            A.shape, A.dtype, ext_dims, dd,
            frozenset() if all_ext else wraps)
    return wraps, use_writer


def _update_halo_impl(fields: List, grid, assembly=None) -> Tuple:
    """Halo update of all fields' local blocks: pack squeezed send planes
    (inner plane `ol-1` / `s-ol`, `/root/reference/src/update_halo.jl:
    386-394`), exchange dimension-sequentially with grouped collectives and
    corner propagation, then assemble — with the one-pass in-place Pallas
    writer when the lane dimension participates (see
    :mod:`igg.ops.halo_write` for why), the XLA plans otherwise.

    (When every active dimension is periodic with a single device and
    overlap 2, the update is algebraically `pad(interior, mode='wrap')`;
    measured on TPU v5e that form does NOT fuse — it regressed both here
    and as a model-level fast path, so the plane machinery below is used
    everywhere.)"""
    import jax.numpy as jnp

    from .ops.pack import pack_planes_supported, pack_planes
    from .ops.halo_write import halo_write_slabs, write_lane_active

    _check_assembly(assembly)
    on_tpu = _is_tpu(grid)
    if assembly == "pallas" and not (on_tpu or _FORCE_WRITER_INTERPRET):
        raise GridError(_PALLAS_NEEDS_TPU)
    shapes, dims_moving, wraps, writer = [], [], [], []
    for A in fields:
        s = A.shape
        dims = moving_dims(active_dims(s, grid), grid)
        w, use_writer = (_writer_dims(A, dims, grid)
                         if (on_tpu or _FORCE_WRITER_INTERPRET)
                         and assembly != "xla"
                         else (frozenset(), False))
        if assembly == "pallas" and dims and not use_writer:
            _raise_pallas_unsupported()
        dims_moving.append(dims)
        writer.append(use_writer)
        wraps.append(w if use_writer else frozenset())
        shapes.append(s)

    # XLA-plan fields whose halo set misses the lane dimension take the
    # sequential per-dim form (free corner propagation, homogeneous
    # pair-emulated graphs).  Lane-ACTIVE XLA fields stay on the grouped
    # pre-extracted form below: their assembly is one fused select pass
    # over the whole block, and sequential re-extraction would split it
    # into one unfusable pass per dimension (measured 1367 vs 545 us for
    # the f64 xyz update at 256^3).  Writer fields are grouped too — their
    # assembly is an opaque kernel needing all planes up front.
    seq_idx = [i for i in range(len(fields))
               if not writer[i]
               and not any(d == fields[i].ndim - 1
                           for d, _ in dims_moving[i])]
    seq_out: Dict[int, object] = {}
    if seq_idx:
        plans = [_assembly_plan(shapes[i], fields[i].dtype,
                                [d for d, _ in dims_moving[i]],
                                on_tpu=on_tpu) for i in seq_idx]
        upd = exchange_assemble_sequential(
            [fields[i] for i in seq_idx], [dims_moving[i] for i in seq_idx],
            grid, plans)
        seq_out = dict(zip(seq_idx, upd))
    widx = [i for i in range(len(fields)) if writer[i] or i not in seq_out]

    # Lane-active pair-emulated fields in groups of >= 2 with identical
    # (shape, dtype, dims): ONE stacked block through exchange + select
    # assembly, so the composed program carries one pair buffer instead
    # of nf — the per-field while-loop carry copies were the 691/807 us
    # per-field cost of the grouped f64 update (VERDICT r5 weak #1).
    stack_groups: Dict[tuple, List[int]] = {}
    if on_tpu or _FORCE_STACKED64:
        for i in widx:
            A = fields[i]
            if (not writer[i] and A.ndim == 3
                    and _pair_emulated(A.dtype)
                    and any(d == A.ndim - 1 for d, _ in dims_moving[i])):
                key = (A.shape, str(A.dtype), tuple(dims_moving[i]))
                stack_groups.setdefault(key, []).append(i)
    stacked = [g for g in stack_groups.values() if len(g) >= 2]
    sidx = {i for g in stacked for i in g}
    widx = [i for i in widx if i not in sidx]
    for members in stacked:
        upd = _stacked_lane64_update([fields[i] for i in members],
                                     dims_moving[members[0]], grid)
        seq_out.update(zip(members, upd))

    if not widx:
        return tuple(seq_out[i] for i in range(len(fields)))

    w_sends = []
    for i in widx:
        A = fields[i]
        s = A.shape
        dims = dims_moving[i]
        w = wraps[i]
        # Send planes are needed for exchanged dims only: the exchange
        # never reads a wrap dim's sends, and the writer sources wrap
        # halos itself (y/z from the block in VMEM, dim 0 from its own
        # lazy slices).
        plane_req = {}
        for d, ol in dims:
            if d in w:
                continue
            plane_req[(d, 0)] = (d, ol - 1)
            plane_req[(d, 1)] = (d, s[d] - ol)
        send = {}
        # Minor-dim planes that must materialize for a ppermute are extracted
        # in ONE Pallas pass (XLA relayouts each separately — measured 491 us
        # vs 92 us for the 4-plane y+z pack at 256^3 f32); everything else
        # stays a lazy slice that fuses into its consumer.
        minor = [k for k, (d, _) in plane_req.items()
                 if grid.dims[d] > 1 and d >= A.ndim - 2 and A.ndim == 3]
        if on_tpu and len(minor) >= 2 and pack_planes_supported(s, A.dtype):
            packed = pack_planes(A, [plane_req[k] for k in minor])
            send.update({k: jnp.expand_dims(p, plane_req[k][0])
                         for k, p in zip(minor, packed)})
        for k, (d, pos) in plane_req.items():
            if k not in send:
                send[k] = _plane(A, d, pos)
        w_sends.append(send)

    recvs = exchange_all_dims_grouped(
        [shapes[i] for i in widx], w_sends, [dims_moving[i] for i in widx],
        grid, wraps=[wraps[i] for i in widx], blocks=[fields[i] for i in widx])

    out = dict(seq_out)
    for k, i in enumerate(widx):
        A = fields[i]
        dims = dims_moving[i]
        if not writer[i]:
            plan = _assembly_plan(A.shape, A.dtype, [d for d, _ in dims],
                                  on_tpu=on_tpu)
            A, rv = _fence_recv(A, recvs[k], dims, on_tpu)
            out[i] = assemble_planes(A, rv, dims, plan=plan)
            continue
        s = A.shape
        lane_active = any(d == A.ndim - 1 for d, _ in dims)
        specs = []
        for d, ol in dims:
            if d in wraps[i]:
                if d == 0:
                    specs.append((0, "ext",
                                  jnp.squeeze(_plane(A, 0, s[0] - ol), 0),
                                  jnp.squeeze(_plane(A, 0, ol - 1), 0)))
                else:
                    specs.append((d, "wrap", ol))
            else:
                first, last = recvs[k][d]
                specs.append((d, "ext", jnp.squeeze(first, d),
                              jnp.squeeze(last, d)))
        interp = _FORCE_WRITER_INTERPRET
        out[i] = (write_lane_active(A, specs, wraps[i], interpret=interp)
                  if lane_active
                  else halo_write_slabs(A, specs, interpret=interp))
    return tuple(out[i] for i in range(len(fields)))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def update_halo_local(*fields, assembly=None):
    """Halo update for use *inside* SPMD code (shard_map / `igg.sharded`),
    where arrays are per-device local blocks.  Returns updated block(s).

    `assembly` selects the halo-plane write strategy:
      - `None` (default) — the in-place Pallas writers on TPU
        (deterministic, linear in the field count; the right choice for
        standalone updates and multi-field steps);
      - `"xla"` — the masked-select/aligned-DUS XLA plans.  When the update
        is composed with a producing stencil in ONE traced step, XLA can
        fuse the select chain into the stencil's output pass, beating the
        writer's extra kernel boundary (measured on the radius-1 single
        field diffusion step: 0.70 ms vs 1.12 ms at 256^3) — but the plan
        is a compile lottery for standalone or multi-field programs;
      - `"pallas"` — force the writers; raises `GridError` when they
        cannot serve the call (non-TPU devices, unsupported rank/dtype/
        shape), so the force is a real contract rather than a silent
        fallback.
    """
    shared.check_initialized()
    grid = shared.global_grid()
    out = _update_halo_impl(list(fields), grid, assembly=assembly)
    return out[0] if len(fields) == 1 else out


def update_halo(*fields, assembly=None):
    """Update the halo of the given grid array(s); returns the updated
    array(s) (functional counterpart of the reference's `update_halo!(A...)`,
    `/root/reference/src/update_halo.jl:23-28`).

    Grouping several fields into one call compiles a single XLA program with
    ONE collective per (dimension, side) for all same-shaped fields — group
    subsequent calls for performance, exactly like the reference's
    performance note (`/root/reference/src/update_halo.jl:19-20`).  Inputs
    are donated, so with `T = igg.update_halo(T)` the update is in-place in
    device HBM (and on tile-aligned grids touches only the dirty tiles).
    See :func:`update_halo_local` for the `assembly` strategies (the default
    in-place Pallas writers are the right choice here: a standalone update
    program has no producer to fuse into).
    """
    import jax

    shared.check_initialized()
    grid = shared.global_grid()
    local_shapes = [grid.local_shape(A) for A in fields]
    check_fields(grid, fields, local_shapes)

    key = (shared.grid_epoch(), assembly,
           tuple((A.shape, str(A.dtype)) for A in fields))

    def build():
        specs = tuple(spec_for(A.ndim) for A in fields)
        sm = jax.shard_map(
            lambda *fs: _update_halo_impl(list(fs), grid, assembly=assembly),
            mesh=grid.mesh, in_specs=specs, out_specs=specs)
        return jax.jit(sm, donate_argnums=tuple(range(len(fields))))

    from . import degrade
    from . import telemetry as _telemetry

    fn = _compiled.get(key)
    first = fn is None
    if first:
        fn = _compiled[key] = build()
    writer_possible = (
        assembly is None and (_is_tpu(grid) or _FORCE_WRITER_INTERPRET)
        and not degrade.is_quarantined(degrade.HALO_WRITER_TIER))
    if first:
        # Observability (igg.telemetry): one writer-election record per
        # compiled program — which assembly tier this program was traced
        # against (quarantine flips re-trace, emitting a fresh record).
        _telemetry.emit(
            "halo_writer_election", assembly=assembly,
            writer_possible=bool(writer_possible), n_fields=len(fields),
            quarantined=degrade.is_quarantined(degrade.HALO_WRITER_TIER))
    # Halo traffic: every exchanged boundary plane of this call — per
    # DEVICE, two sides per moving dim of a local-block cross-section,
    # summed over the mesh (the dim classification and plane sizes are
    # local-shape questions: `active_dims`/`ol_of_local` are defined on
    # per-device blocks, not the stacked global array).  Pure host
    # arithmetic, counted once per call.  The unlabeled total is kept
    # for dashboard continuity; the (dim, mode) breakdown (wire vs
    # local, grouped vs stacked — `plane_bytes_by_mode`) lets byte
    # accounting reconcile against the analytic plane-bytes model per
    # exchange path (igg.comm.plane_bytes_model is this same function).
    by_mode = plane_bytes_by_mode(local_shapes,
                                  [A.dtype for A in fields], grid)
    _telemetry.counter("igg_halo_plane_bytes_total").inc(
        sum(by_mode.values()))
    for (dim, mode), nbytes in sorted(by_mode.items()):
        _telemetry.counter("igg_halo_plane_bytes_total",
                           dim=dim, mode=mode).inc(nbytes)
    try:
        if first and writer_possible:
            # Chaos seam (igg.chaos.kernel_compile_fail("halo.writer")).
            degrade._chaos_compile_check(degrade.HALO_WRITER_TIER)
        out = fn(*fields)
    except Exception as e:
        # Compile-failure capture for the writer tier (igg.degrade): a
        # Mosaic/XLA lowering error on the FIRST build of this program,
        # while the writers could have been elected, quarantines the tier
        # and re-traces with the XLA plans — the fast tier is an
        # optimization, never a correctness dependency.  (Errors on an
        # already-serving program, forced assemblies, and programs the
        # writers never entered propagate: they are real.)  The program
        # donates its inputs, so only pre-execution failures — which leave
        # the arguments alive — are capturable; a post-donation runtime
        # error has consumed the buffers, cannot be retried, and says
        # nothing about the writer kernels, so it propagates unclaimed.
        if not (first and writer_possible):
            raise
        if any(getattr(a, "is_deleted", lambda: False)() for a in fields):
            raise
        _compiled.pop(key, None)
        degrade.quarantine(degrade.HALO_WRITER_TIER, 0, "compile_failed", e)
        fn = _compiled[key] = build()   # re-trace: _writer_dims now refuses
        out = fn(*fields)
    if grid.needs_cpu_sync:
        jax.block_until_ready(out)
    return out[0] if len(fields) == 1 else out
