"""Halo-exchange engine — the performance core.

TPU-native re-design of `/root/reference/src/update_halo.jl`.  The reference's
machinery (persistent send/recv buffer pools, pinned host memory, CUDA
pack/unpack kernels, max-priority streams, MPI Isend/Irecv) collapses on TPU
into a single XLA program per call signature:

    pack   = lax.slice of the boundary plane          (fused by XLA)
    send   = lax.ppermute shift along a mesh axis     (ICI collective-permute)
    unpack = lax.dynamic_update_slice                 (fused by XLA)

Halos never touch the host; buffer management is XLA's job (donated inputs
make the update effectively in-place in HBM, matching the reference's
mutate-in-place semantics with zero extra copies).

Preserved reference semantics:
  - exactly one boundary plane is exchanged per side per dimension:
    send plane `ol-1` (left) / `s-ol` (right) (0-based; reference
    `/root/reference/src/update_halo.jl:386-394`), receive into plane `0` /
    `s-1` (`:397-405`);
  - per-array staggered overlap `ol(dim, A) = overlaps[dim] + (s_d - n_d)`
    (`/root/reference/src/shared.jl:81`); a dimension participates only when
    `ol >= 2` (`/root/reference/src/update_halo.jl:284`);
  - dimensions are exchanged **sequentially** (x, then y, then z) so corner
    and edge values propagate without diagonal messages
    (`/root/reference/src/update_halo.jl:36,130`);
  - open (non-periodic) boundaries: edge halos are simply not written
    (`/root/reference/test/test_update_halo.jl:727-732`) — realized here with
    `axis_index` masks instead of MPI_PROC_NULL neighbors;
  - periodic with one device along a dimension: a pure local copy, the analog
    of the reference's self-neighbor path
    (`/root/reference/src/update_halo.jl:516-532`).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Sequence, Tuple

from . import shared
from .fields import spec_for
from .shared import AXIS_NAMES, NDIMS, GridError


# Compiled update programs keyed by (grid epoch, per-field (shape, dtype)).
# The analog of the reference's grow-only buffer pool keyed by field count and
# dtype (`/root/reference/src/update_halo.jl:86-255`): it exists so the hot
# loop never re-traces/re-allocates.
_compiled: Dict[tuple, object] = {}


def free_update_halo_buffers() -> None:
    """Drop all compiled halo programs (reference
    `/root/reference/src/update_halo.jl:95-107`)."""
    _compiled.clear()


# ---------------------------------------------------------------------------
# Argument checking (`/root/reference/src/update_halo.jl:574-604`)
# ---------------------------------------------------------------------------

def check_fields(grid, fields, local_shapes) -> None:
    no_halo = [
        i for i, (A, s) in enumerate(zip(fields, local_shapes))
        if all(grid.ol_of_local(d, s) < 2 for d in range(min(A.ndim, NDIMS)))
    ]
    if len(no_halo) > 1:
        raise GridError(
            f"The fields at positions {', '.join(map(str, no_halo))} have no "
            f"halo; remove them from the call.")
    if no_halo:
        raise GridError(
            f"The field at position {no_halo[0]} has no halo; remove it from "
            f"the call.")

    dups = [(i, j) for i in range(len(fields)) for j in range(i + 1, len(fields))
            if fields[i] is fields[j]]
    if dups:
        i, j = dups[0]
        raise GridError(
            f"The field at position {j} is a duplicate of the one at the "
            f"position {i}; remove the duplicate from the call.")

    diff = [i for i in range(1, len(fields))
            if fields[i].dtype != fields[0].dtype]
    if diff:
        raise GridError(
            f"The field at position {diff[0]} is of different type than the "
            f"first field; make sure that in a same call all fields are of "
            f"the same type.")


# ---------------------------------------------------------------------------
# The exchange itself (operates on per-device local blocks)
# ---------------------------------------------------------------------------

def exchange_planes(left_send, right_send, stale_first, stale_last,
                    d: int, n: int, periodic: bool):
    """Plane-level neighbor shift along mesh axis `d`: returns the
    (new_first, new_last) halo planes of the local block.

    Open-boundary edge devices receive zeros from the (non-wrapping) permute;
    the stale planes are returned there instead — the reference's no-write
    semantics (`/root/reference/test/test_update_halo.jl:727-732`).  With one
    device along the axis, periodic exchange degenerates to a pure local copy
    (self-neighbor path, `/root/reference/src/update_halo.jl:516-532`).
    """
    import jax.numpy as jnp
    from jax import lax

    axis = AXIS_NAMES[d]
    if n == 1:
        if not periodic:
            return stale_first, stale_last
        return right_send, left_send

    shift_down = [(i, i - 1) for i in range(1, n)] + ([(0, n - 1)] if periodic else [])
    shift_up = [(i, i + 1) for i in range(n - 1)] + ([(n - 1, 0)] if periodic else [])
    from_right = lax.ppermute(left_send, axis, shift_down)   # right nb's inner plane
    from_left = lax.ppermute(right_send, axis, shift_up)     # left nb's inner plane
    if periodic:
        return from_left, from_right
    idx = lax.axis_index(axis)
    return (jnp.where(idx > 0, from_left, stale_first),
            jnp.where(idx < n - 1, from_right, stale_last))


def _plane(A, d: int, i: int):
    from jax import lax
    return lax.slice_in_dim(A, i, i + 1, axis=d)


def _put_plane(A, P, d: int, i: int):
    from jax import lax
    return lax.dynamic_update_slice_in_dim(A, P, i, axis=d)


def active_dims(shape, grid) -> List[Tuple[int, int]]:
    """The (dim, ol) pairs of a local block's shape that have a halo
    (per-array staggered overlap `ol >= 2`,
    `/root/reference/src/update_halo.jl:284`)."""
    return [(d, grid.ol_of_local(d, shape))
            for d in range(min(len(shape), NDIMS))
            if grid.ol_of_local(d, shape) >= 2]


def exchange_all_dims(A, send: Dict, dims_active, grid,
                      stale: Dict = None, wrap=()) -> Dict:
    """Dimension-sequential plane-level exchange with corner/edge propagation.

    `send[(d, side)]` are the packed send planes (already containing whatever
    values the caller's semantics require at pack time).  Returns
    `recv[d] = (new_first_plane, new_last_plane)` per active dimension.

    Equivalence with the reference's sequential per-dimension update of the
    full array (`/root/reference/src/update_halo.jl:36,130`): what later
    dimensions see of the dimensions already exchanged is the received halo
    values inside their edge rows — so after each dimension's exchange, the
    *pending* send planes AND the pending stale (open-boundary fallback)
    planes of all later dimensions get their edge rows overwritten with the
    received/stale result.  The caller must assemble the returned planes in
    dimension order (later dimensions win the shared corner/edge cells, like
    the reference's later exchanges overwrite them).

    Dims in `wrap` (single periodic device, halo assembled by the caller —
    e.g. in-VMEM by the fused Pallas kernel) are not exchanged and need no
    send planes; their contribution to the sequential semantics is the
    self-alias patch: later dims' pending planes get the wrapped halo rows,
    which are aliases of the plane's own inner rows.

    Shared by :func:`igg.update_halo` / :func:`igg.update_halo_local` (send
    planes sliced from the block), :func:`igg.hide_communication` (send
    planes from thin slab recomputations), and the fused Pallas path (send
    planes from carried boundary slabs, wrap dims in-kernel).
    """
    s = A.shape
    send = dict(send)
    wrap = frozenset(wrap)
    # Stale planes: what an open-boundary edge device keeps (the reference's
    # no-write semantics, `/root/reference/test/test_update_halo.jl:727-732`).
    # Extracted only for non-periodic dims — periodic exchanges never read
    # them, and a minor-dim plane slice costs nearly a full array pass on TPU
    # (strided reads still transfer whole (8,128) tiles).  Callers holding
    # the boundary planes in compact form already (e.g. the slab-carried
    # Pallas path) pass them via `stale` to skip the slicing cost.
    stale = dict(stale) if stale else {}
    for d, ol in dims_active:
        if d in wrap or grid.periods[d]:
            stale[(d, 0)] = stale[(d, 1)] = None
        else:
            for side, i in ((0, 0), (1, s[d] - 1)):
                if (d, side) not in stale:
                    stale[(d, side)] = _plane(A, d, i)

    recv: Dict[int, Tuple] = {}
    for i, (d, ol) in enumerate(dims_active):
        if d in wrap:
            # Self-alias patch of every later pending plane: the wrapped
            # halo rows along `d` are the plane's own inner (send-position)
            # rows `ol-1` / `s-ol`.
            for d2, ol2 in dims_active[i + 1:]:
                if d2 in wrap:
                    continue
                for side2 in (0, 1):
                    for store in (send, stale):
                        P = store.get((d2, side2))
                        if P is None:
                            continue
                        P = _put_plane(P, _plane(P, d, s[d] - ol), d, 0)
                        P = _put_plane(P, _plane(P, d, ol - 1), d, s[d] - 1)
                        store[(d2, side2)] = P
            continue
        new_first, new_last = exchange_planes(
            send[(d, 0)], send[(d, 1)], stale[(d, 0)], stale[(d, 1)],
            d, grid.dims[d], bool(grid.periods[d]))
        recv[d] = (new_first, new_last)
        for d2, ol2 in dims_active[i + 1:]:
            if d2 in wrap:
                continue
            for side2, p_send, p_stale in ((0, ol2 - 1, 0),
                                           (1, s[d2] - ol2, s[d2] - 1)):
                P = send[(d2, side2)]
                P = _put_plane(P, _plane(new_first, d2, p_send), d, 0)
                P = _put_plane(P, _plane(new_last, d2, p_send), d, s[d] - 1)
                send[(d2, side2)] = P
                if stale[(d2, side2)] is not None:
                    Q = stale[(d2, side2)]
                    Q = _put_plane(Q, _plane(new_first, d2, p_stale), d, 0)
                    Q = _put_plane(Q, _plane(new_last, d2, p_stale), d, s[d] - 1)
                    stale[(d2, side2)] = Q
    return recv


def assemble_planes(out, recv: Dict, dims_active):
    """Write the received halo planes into `out` in ONE fused masked-select
    pass, in dimension order (later dimensions win the shared corner cells).

    Why not per-dimension `dynamic_update_slice` on the block (the direct
    translation of the reference's in-place unpack,
    `/root/reference/src/update_halo.jl:397-405`): XLA cannot prove the plane
    reads and writes disjoint and materializes a full-array copy per
    dimension — measured 3 full copies per update at 256^3 on TPU v5e.  The
    masked-select chain fuses into a single read+write pass over the block;
    all plane traffic on top is O(s^2)."""
    import jax.numpy as jnp
    from jax import lax

    s = out.shape
    for d, _ in dims_active:
        idx = lax.broadcasted_iota(jnp.int32, s, d)
        out = jnp.where(idx == 0, recv[d][0],
                        jnp.where(idx == s[d] - 1, recv[d][1], out))
    return out


def _update_halo_field(A, grid):
    """Halo update of one field's local block: pack send planes (inner plane
    `ol-1` / `s-ol`, `/root/reference/src/update_halo.jl:386-394`), exchange
    dimension-sequentially with corner propagation, assemble in one pass.

    (When every active dimension is periodic with a single device and
    overlap 2, the update is algebraically `pad(interior, mode='wrap')`;
    measured on TPU v5e that form does NOT fuse — it regressed both here
    and as a model-level fast path, so the plane machinery below is used
    everywhere.)"""
    s = A.shape
    dims = active_dims(s, grid)
    send = {}
    for d, ol in dims:
        send[(d, 0)] = _plane(A, d, ol - 1)
        send[(d, 1)] = _plane(A, d, s[d] - ol)
    recv = exchange_all_dims(A, send, dims, grid)
    return assemble_planes(A, recv, dims)


def _update_halo_impl(fields: List, grid) -> Tuple:
    """Halo update of all fields' local blocks.  Different fields are
    independent, so XLA's scheduler can overlap their plane collectives — the
    analog of the reference's grouped-call pipelining note
    (`/root/reference/src/update_halo.jl:19-20`)."""
    return tuple(_update_halo_field(A, grid) for A in fields)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def update_halo_local(*fields):
    """Halo update for use *inside* SPMD code (shard_map / `igg.sharded`),
    where arrays are per-device local blocks.  Returns updated block(s)."""
    shared.check_initialized()
    grid = shared.global_grid()
    out = _update_halo_impl(list(fields), grid)
    return out[0] if len(fields) == 1 else out


def update_halo(*fields):
    """Update the halo of the given grid array(s); returns the updated
    array(s) (functional counterpart of the reference's `update_halo!(A...)`,
    `/root/reference/src/update_halo.jl:23-28`).

    Grouping several fields into one call compiles a single XLA program whose
    collectives can be overlapped — group subsequent calls for performance,
    exactly like the reference's performance note
    (`/root/reference/src/update_halo.jl:19-20`).  Inputs are donated, so with
    `T = igg.update_halo(T)` the update is in-place in device HBM.
    """
    import jax

    shared.check_initialized()
    grid = shared.global_grid()
    local_shapes = [grid.local_shape(A) for A in fields]
    check_fields(grid, fields, local_shapes)

    key = (shared.grid_epoch(),
           tuple((A.shape, str(A.dtype)) for A in fields))
    fn = _compiled.get(key)
    if fn is None:
        specs = tuple(spec_for(A.ndim) for A in fields)
        sm = jax.shard_map(lambda *fs: _update_halo_impl(list(fs), grid),
                           mesh=grid.mesh, in_specs=specs, out_specs=specs)
        fn = jax.jit(sm, donate_argnums=tuple(range(len(fields))))
        _compiled[key] = fn
    out = fn(*fields)
    if grid.needs_cpu_sync:
        jax.block_until_ready(out)
    return out[0] if len(fields) == 1 else out
