"""Halo-exchange engine — the performance core.

TPU-native re-design of `/root/reference/src/update_halo.jl`.  The reference's
machinery (persistent send/recv buffer pools, pinned host memory, CUDA
pack/unpack kernels, max-priority streams, MPI Isend/Irecv) collapses on TPU
into a single XLA program per call signature:

    pack   = lax.slice of the boundary plane          (fused by XLA)
    send   = lax.ppermute shift along a mesh axis     (ICI collective-permute)
    unpack = lax.dynamic_update_slice                 (fused by XLA)

Halos never touch the host; buffer management is XLA's job (donated inputs
make the update effectively in-place in HBM, matching the reference's
mutate-in-place semantics with zero extra copies).

Preserved reference semantics:
  - exactly one boundary plane is exchanged per side per dimension:
    send plane `ol-1` (left) / `s-ol` (right) (0-based; reference
    `/root/reference/src/update_halo.jl:386-394`), receive into plane `0` /
    `s-1` (`:397-405`);
  - per-array staggered overlap `ol(dim, A) = overlaps[dim] + (s_d - n_d)`
    (`/root/reference/src/shared.jl:81`); a dimension participates only when
    `ol >= 2` (`/root/reference/src/update_halo.jl:284`);
  - dimensions are exchanged **sequentially** (x, then y, then z) so corner
    and edge values propagate without diagonal messages
    (`/root/reference/src/update_halo.jl:36,130`);
  - open (non-periodic) boundaries: edge halos are simply not written
    (`/root/reference/test/test_update_halo.jl:727-732`) — realized here with
    `axis_index` masks instead of MPI_PROC_NULL neighbors;
  - periodic with one device along a dimension: a pure local copy, the analog
    of the reference's self-neighbor path
    (`/root/reference/src/update_halo.jl:516-532`).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Sequence, Tuple

from . import shared
from .fields import spec_for
from .shared import AXIS_NAMES, NDIMS, GridError


# Compiled update programs keyed by (grid epoch, per-field (shape, dtype)).
# The analog of the reference's grow-only buffer pool keyed by field count and
# dtype (`/root/reference/src/update_halo.jl:86-255`): it exists so the hot
# loop never re-traces/re-allocates.
_compiled: Dict[tuple, object] = {}


def free_update_halo_buffers() -> None:
    """Drop all compiled halo programs (reference
    `/root/reference/src/update_halo.jl:95-107`)."""
    _compiled.clear()


# ---------------------------------------------------------------------------
# Argument checking (`/root/reference/src/update_halo.jl:574-604`)
# ---------------------------------------------------------------------------

def check_fields(grid, fields, local_shapes) -> None:
    no_halo = [
        i for i, (A, s) in enumerate(zip(fields, local_shapes))
        if all(grid.ol_of_local(d, s) < 2 for d in range(min(A.ndim, NDIMS)))
    ]
    if len(no_halo) > 1:
        raise GridError(
            f"The fields at positions {', '.join(map(str, no_halo))} have no "
            f"halo; remove them from the call.")
    if no_halo:
        raise GridError(
            f"The field at position {no_halo[0]} has no halo; remove it from "
            f"the call.")

    dups = [(i, j) for i in range(len(fields)) for j in range(i + 1, len(fields))
            if fields[i] is fields[j]]
    if dups:
        i, j = dups[0]
        raise GridError(
            f"The field at position {j} is a duplicate of the one at the "
            f"position {i}; remove the duplicate from the call.")

    diff = [i for i in range(1, len(fields))
            if fields[i].dtype != fields[0].dtype]
    if diff:
        raise GridError(
            f"The field at position {diff[0]} is of different type than the "
            f"first field; make sure that in a same call all fields are of "
            f"the same type.")


# ---------------------------------------------------------------------------
# The exchange itself (operates on per-device local blocks)
# ---------------------------------------------------------------------------

def exchange_planes(left_send, right_send, stale_first, stale_last,
                    d: int, n: int, periodic: bool):
    """Plane-level neighbor shift along mesh axis `d`: returns the
    (new_first, new_last) halo planes of the local block.

    Open-boundary edge devices receive zeros from the (non-wrapping) permute;
    the stale planes are returned there instead — the reference's no-write
    semantics (`/root/reference/test/test_update_halo.jl:727-732`).  With one
    device along the axis, periodic exchange degenerates to a pure local copy
    (self-neighbor path, `/root/reference/src/update_halo.jl:516-532`).
    """
    import jax.numpy as jnp
    from jax import lax

    axis = AXIS_NAMES[d]
    if n == 1:
        if not periodic:
            return stale_first, stale_last
        return right_send, left_send

    shift_down = [(i, i - 1) for i in range(1, n)] + ([(0, n - 1)] if periodic else [])
    shift_up = [(i, i + 1) for i in range(n - 1)] + ([(n - 1, 0)] if periodic else [])
    from_right = lax.ppermute(left_send, axis, shift_down)   # right nb's inner plane
    from_left = lax.ppermute(right_send, axis, shift_up)     # left nb's inner plane
    if periodic:
        return from_left, from_right
    idx = lax.axis_index(axis)
    return (jnp.where(idx > 0, from_left, stale_first),
            jnp.where(idx < n - 1, from_right, stale_last))


def _exchange_dim(A, d: int, ol: int, n: int, periodic: bool):
    """Exchange the two boundary planes of local block `A` along array/grid
    dimension `d` with the neighboring devices on mesh axis AXIS_NAMES[d]."""
    from jax import lax

    s = A.shape[d]
    # Packed planes (always from the pre-exchange A, like the reference packs
    # all sendbufs before any receive, `/root/reference/src/update_halo.jl:37-39`).
    left_send = lax.slice_in_dim(A, ol - 1, ol, axis=d)        # to left nb's last plane
    right_send = lax.slice_in_dim(A, s - ol, s - ol + 1, axis=d)  # to right nb's first plane

    new_first, new_last = exchange_planes(
        left_send, right_send,
        lax.slice_in_dim(A, 0, 1, axis=d), lax.slice_in_dim(A, s - 1, s, axis=d),
        d, n, periodic)
    A = lax.dynamic_update_slice_in_dim(A, new_last, s - 1, axis=d)
    A = lax.dynamic_update_slice_in_dim(A, new_first, 0, axis=d)
    return A


def _update_halo_impl(fields: List, grid) -> Tuple:
    """Dimension-sequential halo update of all fields' local blocks.

    The x-exchange of *all* fields is emitted before the y-exchange of any
    (matching the reference's orchestrator loop,
    `/root/reference/src/update_halo.jl:36-39`); the ppermutes of different
    fields within one dimension are independent, so XLA's scheduler can
    overlap them — the analog of the reference's grouped-call pipelining note
    (`/root/reference/src/update_halo.jl:19-20`).
    """
    fields = list(fields)
    for d in range(NDIMS):
        for i, A in enumerate(fields):
            if d >= A.ndim:
                continue
            ol = grid.ol_of_local(d, A.shape)  # A is a local block here
            if ol < 2:
                continue  # no halo in this dimension for this (staggered) field
            fields[i] = _exchange_dim(A, d, ol, grid.dims[d],
                                      bool(grid.periods[d]))
    return tuple(fields)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def update_halo_local(*fields):
    """Halo update for use *inside* SPMD code (shard_map / `igg.sharded`),
    where arrays are per-device local blocks.  Returns updated block(s)."""
    shared.check_initialized()
    grid = shared.global_grid()
    out = _update_halo_impl(list(fields), grid)
    return out[0] if len(fields) == 1 else out


def update_halo(*fields):
    """Update the halo of the given grid array(s); returns the updated
    array(s) (functional counterpart of the reference's `update_halo!(A...)`,
    `/root/reference/src/update_halo.jl:23-28`).

    Grouping several fields into one call compiles a single XLA program whose
    collectives can be overlapped — group subsequent calls for performance,
    exactly like the reference's performance note
    (`/root/reference/src/update_halo.jl:19-20`).  Inputs are donated, so with
    `T = igg.update_halo(T)` the update is in-place in device HBM.
    """
    import jax

    shared.check_initialized()
    grid = shared.global_grid()
    local_shapes = [grid.local_shape(A) for A in fields]
    check_fields(grid, fields, local_shapes)

    key = (shared.grid_epoch(),
           tuple((A.shape, str(A.dtype)) for A in fields))
    fn = _compiled.get(key)
    if fn is None:
        specs = tuple(spec_for(A.ndim) for A in fields)
        sm = jax.shard_map(lambda *fs: _update_halo_impl(list(fs), grid),
                           mesh=grid.mesh, in_specs=specs, out_specs=specs)
        fn = jax.jit(sm, donate_argnums=tuple(range(len(fields))))
        _compiled[key] = fn
    out = fn(*fields)
    if grid.needs_cpu_sync:
        jax.block_until_ready(out)
    return out[0] if len(fields) == 1 else out
