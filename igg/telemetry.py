"""igg.telemetry — the unified observability subsystem: one event bus,
one metrics registry, device-side step stats, and trace spans for the
whole stack.

The reference's entire observability story is the barrier-synchronized
`tic()/toc()` pair (`/root/reference/src/tools.jl:228-234`); igg's
resilience/degradation/ensemble/fleet tiers (PRs 3-6) outgrew that and
each grew its own event shape (`RunResult.events`,
`igg.degrade.events()`, the fleet journal, ensemble sidecars) — four
schemas, no timestamps, no rank tags, no metrics, no way to answer "why
was job 14 slow" after the fact.  This module is the single layer they
all emit into (the TPU-CFD exemplar of arXiv:2108.11076 treats on-device
diagnostics-without-host-sync as a first-class design axis):

- **Event bus.**  :func:`emit` stamps every incident as a typed
  :class:`Record` `(t, wall, process, kind, step, payload)` — `t` is
  `time.monotonic()` (ordering within a process), `wall` is epoch
  seconds (merging across processes), `process` the controller rank.
  Every record lands in the bounded in-memory **flight recorder** ring
  (always on — a deque append, no I/O) and, when a :class:`Telemetry`
  session is attached, in that session's rank-tagged
  `events_r<rank>.jsonl` sink.  The run loops
  (:func:`igg.run_resilient`, :func:`igg.run_ensemble`,
  :func:`igg.run_fleet`), the degradation ladder, the checkpoint layer,
  and the halo engine all emit here; `RunResult.events` /
  `igg.degrade.events()` remain as filtered per-run views for API
  compatibility.

- **Flight recorder.**  The ring keeps the last N records
  (`IGG_TELEMETRY_FLIGHT_RECORDER`, default 512) so a post-mortem always
  has the tail of the story.  It is auto-dumped
  (`flight_r<rank>.json`) on :class:`igg.ResilienceError`, on
  SIGTERM/preemption, and on any exception escaping a run loop —
  :func:`dump_flight_recorder` dumps it on demand.

- **Metrics registry.**  :func:`counter` / :func:`gauge` /
  :func:`histogram` get-or-create named instruments (optional labels);
  :func:`snapshot` returns the registry as a plain dict,
  :func:`prometheus_text` renders the Prometheus text exposition.  A
  session exports both periodically (`metrics_r<rank>.jsonl`,
  `metrics_r<rank>.prom`; cadence `IGG_TELEMETRY_METRICS_EVERY` seconds,
  checked at the run loops' watch cadence) and once at detach.  The
  stack maintains: steps run, rollbacks, checkpoint bytes + write
  latency, halo plane bytes, per-tier dispatch counts, quarantines,
  fleet queue depth, watchdog fetch lag (docs/observability.md for the
  full name list).

- **Device-side step stats, zero hot-loop host syncs.**  The watchdog
  probes of `run_resilient`/`run_ensemble` are already fetched
  asynchronously (`is_ready()` polling); the bus piggybacks on that
  channel: each healthy probe fetch is host-timestamped, and the delta
  between consecutive fetches yields per-window `step_stats` records
  (steps/s, ms/step, watchdog fetch lag; per-member aggregate rates
  under `run_ensemble`) — live rate telemetry that costs NO additional
  device→host synchronization (asserted by the sentinel test in
  `tests/test_telemetry.py` and the `telemetry_overhead` row of
  `benchmarks/resilience_overhead.py`, < 1% contract).

- **Trace spans.**  :func:`span` records a named host-side region
  (checkpoint write/drain, rollback, halo compile, verify-first-use,
  fleet job lifecycle) as a `span` record and mirrors it onto the
  device timeline via `jax.profiler.TraceAnnotation` (so spans line up
  with the XLA profiler trace of :func:`igg.profiling.trace`);
  :func:`export_chrome_trace` renders spans as Chrome-trace/Perfetto
  JSON (a session writes `trace_r<rank>.json` at detach).

- **Multihost merge.**  `python -m igg.telemetry merge out.jsonl
  dir-or-files...` merge-sorts rank-tagged JSONL streams by wall time
  into one stream for cross-rank post-mortems.

A session is a directory::

    with igg.telemetry.Telemetry("/tmp/run1") as tel:
        igg.run_resilient(step, state, nt, telemetry=tel, ...)

or just ``run_resilient(..., telemetry="/tmp/run1")`` (the run owns the
session), or ``IGG_TELEMETRY_DIR=/tmp/run1`` (every run auto-attaches).
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import os
import pathlib
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .shared import GridError

__all__ = [
    "Record", "Telemetry", "emit", "span", "counter", "gauge", "histogram",
    "snapshot", "metric_samples", "prometheus_text", "reset_metrics",
    "flight_recorder", "dump_flight_recorder", "flight_dumps", "run_id",
    "export_chrome_trace", "as_session", "merge_streams", "subscribe",
    "unsubscribe",
]


# ---------------------------------------------------------------------------
# Records and the process-global bus
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Record:
    """One bus record: `t` monotonic seconds (in-process ordering), `wall`
    epoch seconds (cross-process merging), `process` the controller rank,
    `kind` the event name (the union of every tier's kinds —
    docs/observability.md), `step` the step count it is anchored to (None
    for step-less events), `payload` the kind-specific detail."""
    t: float
    wall: float
    process: int
    kind: str
    step: Optional[int] = None
    payload: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"t": self.t, "wall": self.wall, "process": self.process,
                "kind": self.kind, "step": self.step,
                "payload": self.payload}


_lock = threading.RLock()
_RING: Optional[deque] = None        # created lazily (size is an env knob)
_SESSIONS: List["Telemetry"] = []    # attached sinks
_SUBSCRIBERS: List = []              # live bus consumers (igg.heal engines)
_process_cached: Optional[int] = None
# Per-run dump identity (round 18): two runs sharing one telemetry
# directory used to both write `flight_r<rank>.json`, the second
# clobbering the first.  Dumps are now suffixed `flight_r<rank>.<id>.json`
# where the id is a process-unique token plus a run sequence number
# (rotated on every `run_started` record), so each run's post-mortem
# survives; :func:`flight_dumps` globs BOTH filename forms.
_RUN_BASE = f"{os.getpid():x}{int(time.time()) & 0xFFFF:04x}"
_RUN_SEQ = 0


def _env():
    from . import _env as env_mod

    return env_mod


def _process() -> int:
    """Controller rank for stamping records.  Lazy and failure-tolerant:
    telemetry must never be the thing that initializes a JAX backend (or
    crashes because none exists yet), so before the backend is up records
    are stamped rank 0 and the real rank is cached on first success."""
    global _process_cached
    if _process_cached is not None:
        return _process_cached
    try:
        import jax

        _process_cached = int(jax.process_index())
    except Exception:
        return 0
    return _process_cached


def _ring() -> deque:
    global _RING
    if _RING is None:
        with _lock:
            if _RING is None:
                size = max(1, int(_env().integer(
                    "IGG_TELEMETRY_FLIGHT_RECORDER", 512)))
                _RING = deque(maxlen=size)
    return _RING


def run_id() -> str:
    """The current flight-dump identity: a process-unique token plus the
    run sequence number (rotated on every ``run_started`` record), the
    suffix of `flight_r<rank>.<id>.json` dumps."""
    return f"{_RUN_BASE}-{_RUN_SEQ}"


def emit(kind: str, step: Optional[int] = None, **payload) -> Record:
    """Stamp and publish one record: append it to the flight-recorder ring
    (always — a deque append) and hand it to every attached session sink.
    Pure host bookkeeping: no device work, no synchronization."""
    if kind == "run_started":
        # Rotate the flight-dump identity: each run's dumps land in their
        # own `flight_r<rank>.<id>.json` (a second run sharing the
        # telemetry dir must never clobber the first run's post-mortem).
        global _RUN_SEQ
        with _lock:
            _RUN_SEQ += 1
    rec = Record(t=time.monotonic(), wall=time.time(), process=_process(),
                 kind=kind, step=None if step is None else int(step),
                 payload=payload)
    # Appends and snapshots share the lock: the checkpoint layer emits
    # from the async-writer THREAD, and an unsynchronized deque snapshot
    # racing that append raises "deque mutated during iteration" — in
    # exactly the fault path (_auto_dump) that must never mask the real
    # error.  Uncontended acquire, still no I/O.
    with _lock:
        _ring().append(rec)
    if _SESSIONS:
        with _lock:
            sessions = list(_SESSIONS)
        for s in sessions:
            s._write_record(rec)
    if _SUBSCRIBERS:
        with _lock:
            subs = list(_SUBSCRIBERS)
        for fn in subs:
            try:
                fn(rec)
            except Exception:
                # A broken consumer (a heal-engine detector mid-teardown)
                # must never kill the run that is being observed.
                pass
    return rec


def subscribe(fn) -> None:
    """Register a live bus consumer: `fn(record)` is called for EVERY
    subsequent :func:`emit`, on the emitting thread (which may be a
    background thread — the stall heartbeat, the async checkpoint writer).
    Consumers must be fast and non-blocking (the hot loops emit here) and
    must never raise (exceptions are swallowed).  This is the
    detection half of the :mod:`igg.heal` control loops."""
    with _lock:
        if fn not in _SUBSCRIBERS:
            _SUBSCRIBERS.append(fn)


def unsubscribe(fn) -> None:
    """Remove a consumer registered with :func:`subscribe` (idempotent)."""
    with _lock:
        if fn in _SUBSCRIBERS:
            _SUBSCRIBERS.remove(fn)


def flight_recorder() -> List[Record]:
    """The flight-recorder ring's current contents, oldest first (a
    consistent snapshot — see the locking note in :func:`emit`)."""
    ring = _ring()
    with _lock:
        return list(ring)


def _flight_name() -> str:
    """Rank- and run-tagged dump filename: repeated dumps within one run
    overwrite (latest wins — the ring carries the full tail anyway), but
    two runs sharing a telemetry directory never clobber each other."""
    return f"flight_r{_process()}.{run_id()}.json"


def flight_dumps(directory, rank: Optional[int] = None) -> List[pathlib.Path]:
    """Every flight-recorder dump under `directory`, newest first — BOTH
    filename forms: the pre-round-18 `flight_r<rank>.json` and the
    run-id-suffixed `flight_r<rank>.<id>.json` (the merge tool and any
    post-mortem reader should glob through here rather than hard-coding
    a name)."""
    d = pathlib.Path(directory)
    try:
        if rank is None:
            found = list(d.glob("flight_r*.json"))
        else:
            # Two exact-rank patterns, NOT a prefix glob: on a pod,
            # `flight_r1*` would also swallow ranks 10-19's dumps.
            found = list(d.glob(f"flight_r{rank}.*.json"))
            legacy = d / f"flight_r{rank}.json"
            if legacy.exists():
                found.append(legacy)
    except OSError:
        return []
    return sorted(found, key=lambda p: p.stat().st_mtime, reverse=True)


def dump_flight_recorder(reason: str = "requested",
                         path=None) -> List[pathlib.Path]:
    """Dump the ring as JSON: to every attached session's
    `flight_r<rank>.<run-id>.json`, to `path` when given, and — with
    neither — to `IGG_TELEMETRY_DIR` when set.  Returns the paths written
    (empty when there is nowhere to write — the ring itself always remains
    readable via :func:`flight_recorder`)."""
    recs = [r.as_dict() for r in flight_recorder()]
    doc = {"reason": reason, "wall": time.time(),
           "process": _process(), "run_id": run_id(), "events": recs}
    out: List[pathlib.Path] = []
    targets: List[pathlib.Path] = []
    if path is not None:
        targets.append(pathlib.Path(path))
    with _lock:
        sessions = list(_SESSIONS)
    for s in sessions:
        targets.append(s.dir / _flight_name())
    if not targets:
        envdir = _env().text("IGG_TELEMETRY_DIR")
        if envdir:
            targets.append(pathlib.Path(envdir) / _flight_name())
    for t in targets:
        try:
            t.parent.mkdir(parents=True, exist_ok=True)
            tmp = t.with_name(t.name + ".tmp")
            tmp.write_text(json.dumps(doc, default=str))
            os.replace(tmp, t)
            out.append(t)
        except OSError:
            continue   # a full/readonly disk must not mask the real fault
    return out


def _auto_dump(reason: str) -> List[pathlib.Path]:
    """The run loops' fault hook: dump the flight recorder wherever a sink
    is configured (attached session or IGG_TELEMETRY_DIR); silently a no-op
    when telemetry is entirely unconfigured.  Returns the dump paths
    written (empty when unconfigured) so a :class:`igg.ResilienceError`
    can NAME the operator's first postmortem artifact."""
    with _lock:
        have_session = bool(_SESSIONS)
    if have_session or _env().text("IGG_TELEMETRY_DIR"):
        return dump_flight_recorder(reason)
    return []


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

_METRICS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "_Metric"] = {}
# Name-level kind map: "one name, one type" must hold ACROSS label sets
# too (a counter `x{a="1"}` next to a gauge `x{b="2"}` would render an
# unparsable exposition — one `# TYPE x` line cannot cover both).
_KIND_BY_NAME: Dict[str, type] = {}
# Name-level help strings (`# HELP` lines in the exposition).  The
# registration functions take an optional `help=`; the stack's built-in
# metrics get theirs from this table so every call site stays a
# one-liner (a name registered with an explicit help= overrides it).
_HELP_BY_NAME: Dict[str, str] = {}
_BUILTIN_HELP: Dict[str, str] = {
    "igg_steps_total": "Steps completed by a run loop.",
    "igg_member_steps_total": "Member-steps completed by run_ensemble "
                              "(steps times active members).",
    "igg_rollbacks_total": "Checkpoint rollbacks taken by a run loop.",
    "igg_steps_per_s": "Live step rate of the last watchdog window.",
    "igg_member_steps_per_s": "Live aggregate member-step rate of the "
                              "last ensemble watchdog window.",
    "igg_watchdog_fetch_lag_steps": "Steps between the last fetched "
                                    "watchdog probe and the loop's "
                                    "current step.",
    "igg_rank_window_ms": "This rank's last watchdog-window ms/step "
                          "(the live straggler signal).",
    "igg_rank_skew_ms": "Worst-vs-median window time across ranks "
                        "(igg.comm.rank_skew).",
    "igg_checkpoint_bytes_total": "Bytes written into checkpoint "
                                  "generations.",
    "igg_checkpoint_write_seconds": "Checkpoint generation write "
                                    "latency.",
    "igg_halo_plane_bytes_total": "Halo plane bytes moved by "
                                  "update_halo (per dim and wire/local "
                                  "mode when labelled).",
    "igg_halo_gbps": "Measured halo-exchange bandwidth over the logical "
                     "halo bytes.",
    "igg_pct_link_peak": "Measured wire-crossing halo bandwidth as a "
                         "percentage of the chip's published ICI peak.",
    "igg_achieved_gbps": "Achieved HBM bandwidth of the serving kernel "
                         "tier (igg.perf).",
    "igg_pct_hbm_peak": "Achieved HBM bandwidth as a percentage of the "
                        "chip's published peak (igg.perf).",
    "igg_cost_model_rel_error": "Relative error of the registered "
                                "cost-model prediction vs measured "
                                "step time.",
    "igg_exposed_comm_fraction": "Exposed communication fraction "
                                 "(exchange - compute) / exchange of "
                                 "the last decomposition window.",
    "igg_overlap_efficiency": "Overlap efficiency (exchange - hidden) /"
                              " (exchange - compute) of the last "
                              "decomposition window.",
    "igg_hide_communication_traces_total": "hide_communication overlap "
                                           "schedules traced.",
    "igg_tier_dispatch_total": "Dispatches served per (family, tier) by "
                               "the degradation ladder.",
    "igg_tier_quarantined_total": "Kernel tiers quarantined by the "
                                  "degradation ladder.",
    "igg_member_quarantined_total": "Ensemble members quarantined after "
                                    "retry-budget exhaustion.",
    "igg_fleet_queue_depth": "Fleet jobs not yet terminal this drain.",
    "igg_fleet_jobs_total": "Fleet jobs finished, by outcome status.",
    "igg_hbm_bytes_in_use": "Device memory currently allocated "
                            "(device.memory_stats; absent when the "
                            "backend exposes no allocator stats).",
    "igg_hbm_bytes_limit": "Device memory capacity visible to the "
                           "allocator (absent when the backend exposes "
                           "no allocator stats).",
    "igg_hbm_watermark_bytes": "Peak device memory allocated since "
                               "process start (absent when the backend "
                               "exposes no allocator stats).",
    "igg_statusd_requests_total": "HTTP requests served by igg.statusd, "
                                  "by route.",
    "igg_integrity_checks_total": "Clean integrity verdicts decoded from "
                                  "fetched watchdog probes "
                                  "(igg.integrity).",
    "igg_integrity_shadow_checks_total": "Shadow re-execution comparisons "
                                         "completed (igg.integrity).",
    "igg_integrity_violations_total": "Silent-data-corruption verdicts "
                                      "raised (invariant drift or shadow "
                                      "mismatch; igg.integrity).",
}


class _Metric:
    kind = "untyped"

    __slots__ = ("name", "labels", "_lock")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()

    def key(self) -> str:
        if not self.labels:
            return self.name
        inner = ",".join(f'{k}="{v}"' for k, v in self.labels)
        return f"{self.name}{{{inner}}}"

    def as_dict(self) -> dict:   # pragma: no cover - overridden
        raise NotImplementedError


class Counter(_Metric):
    """Monotone counter (`.inc(v)`); `.value` reads it."""
    kind = "counter"
    __slots__ = ("value",)

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise GridError(f"Counter {self.name}: negative increment {v}.")
        with self._lock:
            self.value += v

    def as_dict(self) -> dict:
        return {"type": self.kind, "value": self.value}


class Gauge(_Metric):
    """Last-write-wins instantaneous value (`.set(v)`)."""
    kind = "gauge"
    __slots__ = ("value",)

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def as_dict(self) -> dict:
        return {"type": self.kind, "value": self.value}


class Histogram(_Metric):
    """Streaming summary (`.observe(v)`): count, sum, min, max — enough
    for latency/size distributions without bucket configuration."""
    kind = "histogram"
    __slots__ = ("count", "sum", "min", "max")

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def as_dict(self) -> dict:
        return {"type": self.kind, "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}


def _get_metric(cls, name: str, labels: dict, help: Optional[str]) -> _Metric:
    lab = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    key = (name, lab)
    if help is not None:
        with _lock:
            _HELP_BY_NAME[name] = str(help)
    m = _METRICS.get(key)
    if m is None:
        with _lock:
            m = _METRICS.get(key)
            if m is None:
                have = _KIND_BY_NAME.get(name)
                if have is not None and have is not cls:
                    raise GridError(
                        f"metric {name!r} is a {have.kind}, not a "
                        f"{cls.kind} — one name, one type (across every "
                        f"label set).")
                _KIND_BY_NAME[name] = cls
                m = _METRICS[key] = cls(name, lab)
    if not isinstance(m, cls):
        raise GridError(f"metric {name!r} is a {m.kind}, not a "
                        f"{cls.kind} — one name, one type.")
    return m


def metric_help(name: str) -> Optional[str]:
    """The registered `# HELP` string for a metric name (explicit
    `help=` registration first, the built-in table second, None when
    neither knows the name)."""
    return _HELP_BY_NAME.get(name, _BUILTIN_HELP.get(name))


def counter(name: str, help: Optional[str] = None, **labels) -> Counter:
    """Get-or-create the named counter (optional labels; `help` becomes
    the exposition's `# HELP` line — built-in igg_* names carry one
    already)."""
    return _get_metric(Counter, name, labels, help)


def gauge(name: str, help: Optional[str] = None, **labels) -> Gauge:
    return _get_metric(Gauge, name, labels, help)


def histogram(name: str, help: Optional[str] = None, **labels) -> Histogram:
    return _get_metric(Histogram, name, labels, help)


def snapshot() -> Dict[str, dict]:
    """The whole registry as `{exposition-key: {type, value|count/sum/
    min/max}}` — a plain JSON-serializable dict."""
    with _lock:
        metrics = list(_METRICS.values())
    return {m.key(): m.as_dict() for m in metrics}


def reset_metrics() -> None:
    """Clear the registry (``igg.finalize_global_grid`` leaves metrics
    alone — they are process-scoped, like the flight recorder; tests call
    this for isolation)."""
    with _lock:
        _METRICS.clear()
        _KIND_BY_NAME.clear()
        _HELP_BY_NAME.clear()


def metric_samples() -> List[dict]:
    """The registry as structured samples: one
    ``{name, labels, type, help, ...values}`` dict per metric instance
    (counters/gauges carry ``value``; histograms ``count/sum/min/max``).
    This is :func:`snapshot` with the labels kept structured instead of
    folded into the exposition key — what the `igg.statusd` multi-rank
    aggregation publishes and merges (a rank label can then be injected
    without re-parsing exposition keys)."""
    with _lock:
        metrics = list(_METRICS.values())
    out = []
    for m in metrics:
        out.append({"name": m.name, "labels": dict(m.labels),
                    "help": metric_help(m.name), **m.as_dict()})
    return out


def _prom_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def _prom_label_value(v: str) -> str:
    """Escape a label VALUE per the Prometheus text-format spec
    (backslash, double-quote, and newline must be escaped inside the
    quoted value) — a path-bearing or free-text label (e.g. a Windows
    run directory, a captured error line) must not emit an unparsable
    exposition."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_help_value(v: str) -> str:
    """Escape a `# HELP` text per the exposition spec (backslash and
    newline only — HELP text is not quoted)."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def prometheus_text() -> str:
    """The registry in the Prometheus text exposition format (histograms
    render as summaries: `_count`/`_sum`, plus `_min`/`_max` gauges).
    Metric names with a registered help string (`help=` at registration,
    or the built-in table) get a `# HELP` line ahead of their `# TYPE`."""
    with _lock:
        metrics = list(_METRICS.values())
    by_name: Dict[str, List[_Metric]] = {}
    for m in metrics:
        by_name.setdefault(m.name, []).append(m)
    out = io.StringIO()
    for name in sorted(by_name):
        group = by_name[name]
        pname = _prom_name(name)
        kind = group[0].kind
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "summary"}[kind]
        help_text = metric_help(name)
        if help_text:
            out.write(f"# HELP {pname} {_prom_help_value(help_text)}\n")
        out.write(f"# TYPE {pname} {ptype}\n")
        for m in sorted(group, key=lambda g: g.labels):
            lab = ("{" + ",".join(
                f'{_prom_name(k)}="{_prom_label_value(v)}"'
                for k, v in m.labels) + "}"
                   if m.labels else "")
            if kind == "histogram":
                out.write(f"{pname}_count{lab} {m.count}\n")
                out.write(f"{pname}_sum{lab} {m.sum}\n")
                if m.count:
                    out.write(f"{pname}_min{lab} {m.min}\n")
                    out.write(f"{pname}_max{lab} {m.max}\n")
            else:
                out.write(f"{pname}{lab} {m.value}\n")
    return out.getvalue()


# ---------------------------------------------------------------------------
# Trace spans
# ---------------------------------------------------------------------------

_device_annotation_ok = True   # flipped off permanently on first failure


def _device_annotation(name: str):
    """A `jax.profiler.TraceAnnotation` for mirroring a host span onto the
    device timeline — None when disabled (`IGG_TELEMETRY_DEVICE=0`) or
    unavailable (disabled permanently on first failure, so a broken
    profiler backend costs one try, not one per span)."""
    global _device_annotation_ok
    if not _device_annotation_ok:
        return None
    try:
        if not _env().flag("IGG_TELEMETRY_DEVICE", True):
            return None   # knob is off NOW — it may be turned back on
        import jax

        return jax.profiler.TraceAnnotation(name)
    except GridError:
        raise
    except Exception:
        _device_annotation_ok = False
        return None


@contextlib.contextmanager
def span(name: str, step: Optional[int] = None, **attrs):
    """Record the enclosed block as a named trace span: one `span` record
    on the bus (payload: name, dur_s, start timestamps, thread id, attrs)
    and a mirrored `jax.profiler.TraceAnnotation` on the device timeline.
    `IGG_TELEMETRY_SPANS=0` turns capture off (the block still runs)."""
    if not _env().flag("IGG_TELEMETRY_SPANS", True):
        yield
        return
    dev = _device_annotation(name)
    t0 = time.monotonic()
    w0 = time.time()
    if dev is not None:
        dev.__enter__()
    try:
        yield
    finally:
        if dev is not None:
            dev.__exit__(None, None, None)
        dur = time.monotonic() - t0
        emit("span", step=step, name=name, dur_s=dur, t0=t0, wall0=w0,
             tid=threading.get_ident(), **attrs)


def _chrome_events(records: Sequence[Record]) -> List[dict]:
    out = []
    for r in records:
        if r.kind != "span":
            continue
        p = r.payload
        out.append({
            "name": p.get("name", "span"), "cat": "igg", "ph": "X",
            "ts": p.get("wall0", r.wall) * 1e6,
            "dur": max(p.get("dur_s", 0.0), 0.0) * 1e6,
            "pid": r.process, "tid": p.get("tid", 0),
            "args": {k: v for k, v in p.items()
                     if k not in ("name", "dur_s", "t0", "wall0", "tid")},
        })
    return out


def export_chrome_trace(path, records: Optional[Sequence[Record]] = None
                        ) -> pathlib.Path:
    """Write the span records (default: the flight-recorder ring's) as a
    Chrome-trace/Perfetto JSON object (`{"traceEvents": [...]}` — opens in
    ui.perfetto.dev or chrome://tracing).  Timestamps are wall-clock
    microseconds, so traces from several processes overlay correctly."""
    recs = list(records) if records is not None else flight_recorder()
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    doc = {"traceEvents": _chrome_events(recs),
           "displayTimeUnit": "ms",
           "metadata": {"producer": "igg.telemetry",
                        "process": _process()}}
    path.write_text(json.dumps(doc, default=str))
    return path


# ---------------------------------------------------------------------------
# Sessions: per-run JSONL sinks + exports
# ---------------------------------------------------------------------------

class Telemetry:
    """One observability session rooted at a directory.  While attached
    (context manager, or the run loops' `telemetry=` knob) every bus
    record is appended to `events_r<rank>.jsonl`; metrics snapshots are
    exported periodically (`metrics_every` seconds — default
    `IGG_TELEMETRY_METRICS_EVERY`, 0 = at detach only) to
    `metrics_r<rank>.jsonl` + `metrics_r<rank>.prom`, the span trace is
    written to `trace_r<rank>.json` at detach, and the flight recorder is
    dumped to `flight_r<rank>.json` on faults.  Sessions nest/stack: the
    bus fans every record out to all attached sessions.

    Multihost: attach AFTER the JAX backend is up (the run loops do —
    they attach inside an initialized grid).  Rank tags come from
    `jax.process_index()`; a session attached before backend init on a
    SHARED directory would stamp every host's events file rank 0."""

    def __init__(self, dir, *, metrics_every: Optional[float] = None):
        self.dir = pathlib.Path(dir)
        self.metrics_every = (float(metrics_every)
                              if metrics_every is not None
                              else _env().number(
                                  "IGG_TELEMETRY_METRICS_EVERY", 0.0))
        self.attached = False
        self._events_fh = None
        # Bounded like the flight ring: the trace export keeps the LAST
        # N spans (a days-long run's full span history lives in the
        # events JSONL; the trace file is the recent-window view).
        self._spans: deque = deque(maxlen=4096)
        self._last_metrics = 0.0
        self._io_lock = threading.Lock()

    # -- file naming (rank-tagged for the multihost merge tool) ------------
    @property
    def events_path(self) -> pathlib.Path:
        return self.dir / f"events_r{_process()}.jsonl"

    @property
    def metrics_path(self) -> pathlib.Path:
        return self.dir / f"metrics_r{_process()}.jsonl"

    @property
    def prometheus_path(self) -> pathlib.Path:
        return self.dir / f"metrics_r{_process()}.prom"

    @property
    def trace_path(self) -> pathlib.Path:
        return self.dir / f"trace_r{_process()}.json"

    @property
    def flight_path(self) -> pathlib.Path:
        return self.dir / _flight_name()

    # -- lifecycle ---------------------------------------------------------
    def attach(self) -> "Telemetry":
        """Start sinking bus records into this session's directory
        (idempotent)."""
        with _lock:
            if self.attached:
                return self
            self.dir.mkdir(parents=True, exist_ok=True)
            self._events_fh = open(self.events_path, "a", buffering=1)
            self._last_metrics = time.monotonic()
            _SESSIONS.append(self)
            self.attached = True
        return self

    def detach(self) -> None:
        """Final exports (metrics snapshot + Prometheus file + Chrome
        trace) and stop sinking (idempotent)."""
        with _lock:
            if not self.attached:
                return
            self.attached = False
            if self in _SESSIONS:
                _SESSIONS.remove(self)
        self.export_metrics()
        try:
            export_chrome_trace(self.trace_path, self._spans)
        except OSError:
            pass
        with self._io_lock:
            if self._events_fh is not None:
                self._events_fh.close()
                self._events_fh = None

    def __enter__(self) -> "Telemetry":
        return self.attach()

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            # Still attached, so this session's flight_path is already an
            # auto-target — no explicit path (it would be written twice).
            dump_flight_recorder(f"{exc_type.__name__}: {exc}")
        self.detach()

    # -- sinks -------------------------------------------------------------
    def _write_record(self, rec: Record) -> None:
        if rec.kind == "span":
            with self._io_lock:
                self._spans.append(rec)
        try:
            line = json.dumps(rec.as_dict(), default=str)
        except (TypeError, ValueError):
            line = json.dumps({**rec.as_dict(), "payload": str(rec.payload)})
        try:
            with self._io_lock:
                if self._events_fh is not None:
                    self._events_fh.write(line + "\n")
        except OSError:
            pass   # a full/readonly sink must never kill the monitored run

    def maybe_export_metrics(self) -> bool:
        """Periodic-export check (the run loops call this at the watch
        cadence): exports when `metrics_every` seconds have elapsed since
        the last export.  Cheap when not due — one clock read."""
        if not self.metrics_every:
            return False
        now = time.monotonic()
        if now - self._last_metrics < self.metrics_every:
            return False
        self.export_metrics()
        return True

    def export_metrics(self) -> None:
        """Write one metrics snapshot line (JSONL) and rewrite the
        Prometheus exposition file."""
        self._last_metrics = time.monotonic()
        snap = {"t": time.monotonic(), "wall": time.time(),
                "process": _process(), "metrics": snapshot()}
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            with open(self.metrics_path, "a") as fh:
                fh.write(json.dumps(snap, default=str) + "\n")
            tmp = self.prometheus_path.with_name(
                self.prometheus_path.name + ".tmp")
            tmp.write_text(prometheus_text())
            os.replace(tmp, self.prometheus_path)
        except OSError:
            pass   # telemetry export must never kill the run


def as_session(telemetry) -> Optional[Telemetry]:
    """Coerce the run loops' `telemetry=` knob: None → a session under
    `IGG_TELEMETRY_DIR` when that is set (else no session); True → the env
    directory (GridError when unset); a str/Path → a session at that
    directory; a :class:`Telemetry` → itself; False → off even when the
    env knob is set."""
    if telemetry is False:
        return None
    if isinstance(telemetry, Telemetry):
        return telemetry
    if telemetry is None or telemetry is True:
        envdir = _env().text("IGG_TELEMETRY_DIR")
        if envdir:
            return Telemetry(envdir)
        if telemetry is True:
            raise GridError(
                "telemetry=True needs a directory: set IGG_TELEMETRY_DIR "
                "or pass telemetry=<dir> / a Telemetry session.")
        return None
    if isinstance(telemetry, (str, os.PathLike)):
        return Telemetry(telemetry)
    raise GridError(
        f"telemetry={telemetry!r}: expected None, False, True, a "
        f"directory path, or an igg.telemetry.Telemetry session.")


# ---------------------------------------------------------------------------
# The step-stats meter (piggybacks on the watchdog's async fetch channel)
# ---------------------------------------------------------------------------

class StepStats:
    """Per-window step-rate telemetry with ZERO additional host syncs.

    The resilient/ensemble watchdogs already fetch their probes
    asynchronously (`is_ready()` polling); this meter timestamps each
    healthy fetch on the host and derives the rate from consecutive
    fetches — the device is never asked anything the watchdog did not
    already ask.  A drain that fetches several queued probes back-to-back
    yields near-zero deltas; those windows are skipped (`_MIN_DT`), not
    extrapolated into nonsense rates.

    `perf` (a :func:`igg.perf.sample_context` dict) additionally feeds
    each window's measured ms/step into the perf ledger, attributed to
    the kernel tier(s) that served dispatches during the window
    (:func:`igg.perf.observe_window`) — host-side ladder bookkeeping on
    the same timestamps, so the zero-syncs contract is unchanged."""

    _MIN_DT = 1e-4

    def __init__(self, run: str, members: Optional[int] = None,
                 perf: Optional[dict] = None):
        self.run = run
        self.members = members
        self._anchor: Optional[Tuple[int, float]] = None
        self._sps = gauge("igg_steps_per_s", run=run)
        self._lag = gauge("igg_watchdog_fetch_lag_steps", run=run)
        # The live straggler signal (igg.comm): every rank exports its
        # window's per-step time (ms/step — windows of different lengths
        # compare directly), rank identity carried by the per-rank
        # metrics_r<rank>.prom file — a scraper diffing the exports across
        # ranks sees the worst-vs-median skew live; `python -m igg.comm
        # report` computes the same from merged streams (igg_rank_skew_ms).
        self._win = gauge("igg_rank_window_ms", run=run)
        self._msps = (gauge("igg_member_steps_per_s") if members else None)
        self._perf_ctx = perf
        self._perf_state: Optional[dict] = None
        if perf is not None:
            from . import perf as _perf

            self._perf_state = _perf.window_state()

    def fetched(self, probe_step: int, current_step: int,
                active_members: Optional[int] = None) -> None:
        """One healthy probe was fetched (host-side, post-`is_ready`)."""
        now = time.monotonic()
        lag = max(0, current_step - probe_step)
        self._lag.set(lag)
        anchor = self._anchor
        self._anchor = (probe_step, now)
        if anchor is None:
            return
        dsteps = probe_step - anchor[0]
        dt = now - anchor[1]
        if dsteps <= 0 or dt < self._MIN_DT:
            return
        sps = dsteps / dt
        self._sps.set(sps)
        self._win.set(1e3 / sps)
        payload = {"run": self.run, "steps_per_s": sps,
                   "ms_per_step": 1e3 / sps, "window_steps": dsteps,
                   "fetch_lag_steps": lag}
        if active_members is not None:
            msps = sps * active_members
            payload["members_active"] = active_members
            payload["member_steps_per_s"] = msps
            if self._msps is not None:
                self._msps.set(msps)
        emit("step_stats", step=probe_step, **payload)
        if self._perf_ctx is not None:
            from . import perf as _perf

            _perf.observe_window(self.run, 1e3 / sps, dsteps,
                                 self._perf_ctx, self._perf_state)


# ---------------------------------------------------------------------------
# Multihost merge tool
# ---------------------------------------------------------------------------

def merge_streams(inputs: Sequence, output=None) -> List[dict]:
    """Merge rank-tagged event JSONL files into one stream ordered by wall
    time (ties broken by process then monotonic t).  `inputs` are files or
    directories (directories contribute their `events_r*.jsonl`; a
    flight-recorder dump passed explicitly — either filename form, see
    :func:`flight_dumps` — contributes its `events` array);
    `output` is a path ('-' or None returns the records without
    writing).  Unparsable lines are skipped with a count in the trailing
    summary record rather than aborting the merge — a post-mortem must
    survive a half-written line from a killed process.  With records
    from >= 2 ranks, the summary also estimates per-rank wall-clock
    offsets (:func:`_rank_wall_offsets` — median pairwise delta on
    matching-step records) so cross-rank timelines are not misread
    through host clock drift."""
    files: List[pathlib.Path] = []
    for item in inputs:
        p = pathlib.Path(item)
        if p.is_dir():
            files.extend(sorted(p.glob("events_r*.jsonl")))
        else:
            files.append(p)
    if not files:
        raise GridError(f"telemetry merge: no event files found in "
                        f"{[str(i) for i in inputs]}.")
    records: List[dict] = []
    skipped = 0
    for f in files:
        try:
            text = f.read_text()
        except OSError as e:
            raise GridError(f"telemetry merge: cannot read {f}: {e}")
        if f.suffix == ".json":
            # A flight-recorder dump handed in explicitly (either
            # filename form — `flight_r<rank>.json` or the run-id'd
            # `flight_r<rank>.<id>.json`; :func:`flight_dumps` globs
            # them): its `events` array merges like any rank stream.
            try:
                doc = json.loads(text)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(doc, dict) and isinstance(doc.get("events"), list):
                records.extend(r for r in doc["events"]
                               if isinstance(r, dict))
            else:
                skipped += 1
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                skipped += 1
    records.sort(key=lambda r: (r.get("wall", 0.0), r.get("process", 0),
                                r.get("t", 0.0)))
    offsets, matched = _rank_wall_offsets(records)
    if skipped or offsets:
        payload = {"skipped_lines": skipped,
                   "files": [str(f) for f in files]}
        if offsets:
            payload["rank_wall_offsets"] = offsets
            payload["offset_matched_records"] = matched
        records.append({"kind": "merge_summary", "process": -1,
                        "wall": time.time(), "payload": payload})
    if output is not None and str(output) != "-":
        out = pathlib.Path(output)
        out.parent.mkdir(parents=True, exist_ok=True)
        with open(out, "w") as fh:
            for r in records:
                fh.write(json.dumps(r, default=str) + "\n")
    return records


def _rank_wall_offsets(records: Sequence[dict]
                       ) -> Tuple[Dict[str, float], int]:
    """Per-rank wall-clock offset estimates vs the lowest-ranked process
    (igg.comm, round 14): the MEDIAN pairwise wall delta over records
    that match on (kind, step) — events both ranks anchor to the same
    step (probe fetches, checkpoints, step stats) happen within one
    watch window of each other, so the median over many matches
    suppresses genuine per-window skew and leaves the host clock drift.
    First occurrence per (kind, step, process) only: a rolled-back
    replay re-emits the same steps, and its later copies are not
    simultaneous with the other rank's first pass.  Returns
    ``({rank: offset_seconds}, matched_record_count)`` — empty when
    fewer than two ranks share any step-anchored records.  Reported in
    the merge tool's ``merge_summary`` so cross-rank timelines are not
    misread through clock drift."""
    first: Dict[Tuple, Dict[int, float]] = {}
    for r in records:
        if not isinstance(r, dict) or r.get("step") is None:
            continue
        kind = r.get("kind")
        if not kind or kind == "merge_summary":
            continue
        p = int(r.get("process", 0))
        by_proc = first.setdefault((kind, r["step"]), {})
        if p not in by_proc:
            by_proc[p] = float(r.get("wall", 0.0) or 0.0)
    ranks = sorted({p for by in first.values() for p in by})
    if len(ranks) < 2:
        return {}, 0
    ref = ranks[0]
    offsets: Dict[str, float] = {}
    matched = 0
    for p in ranks[1:]:
        deltas = sorted(by[p] - by[ref] for by in first.values()
                        if ref in by and p in by)
        if not deltas:
            continue
        matched += len(deltas)
        offsets[str(p)] = deltas[len(deltas) // 2]
    return offsets, matched


def _records_from_dicts(dicts: Sequence[dict]) -> List[Record]:
    """Re-hydrate merged JSONL dicts as :class:`Record`s (for feeding the
    span exporter with cross-rank streams)."""
    out = []
    for r in dicts:
        if not isinstance(r, dict):
            continue
        out.append(Record(
            t=float(r.get("t", 0.0) or 0.0),
            wall=float(r.get("wall", 0.0) or 0.0),
            process=int(r.get("process", 0) or 0),
            kind=str(r.get("kind", "")), step=r.get("step"),
            payload=r.get("payload") if isinstance(r.get("payload"), dict)
            else {}))
    return out


def _main(argv: Sequence[str]) -> int:
    import sys

    usage = ("usage: python -m igg.telemetry merge [--trace <trace.json>] "
             "<out.jsonl|-> <events.jsonl|session-dir> [...]")
    argv = list(argv)
    if len(argv) < 1 or argv[0] != "merge":
        print(usage, file=sys.stderr)
        return 2
    rest = argv[1:]
    trace_out = None
    if "--trace" in rest:
        i = rest.index("--trace")
        if i + 1 >= len(rest):
            print(usage, file=sys.stderr)
            return 2
        trace_out = rest[i + 1]
        del rest[i:i + 2]
    if len(rest) < 2:
        print(usage, file=sys.stderr)
        return 2
    out, inputs = rest[0], rest[1:]
    records = merge_streams(inputs, out)
    if trace_out is not None:
        # One merged Chrome-trace over every rank's spans: the span
        # records of the wall-ordered merged stream, through the same
        # exporter the per-rank sessions use — multi-rank timelines then
        # open in Perfetto as a single overlaid view (timestamps are
        # wall-clock microseconds already).
        spans = [r for r in _records_from_dicts(records)
                 if r.kind == "span"]
        export_chrome_trace(trace_out, spans)
        print(f"wrote merged Chrome trace ({len(spans)} span(s)) -> "
              f"{trace_out}", file=sys.stderr)
    if out == "-":
        for r in records:
            print(json.dumps(r, default=str))
    else:
        print(f"merged {len(records)} records from {len(inputs)} input(s) "
              f"-> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":   # python -m igg.telemetry merge ...
    import sys

    sys.exit(_main(sys.argv[1:]))
