"""Profiling hooks.

The reference's observability is `tic()/toc()` only (SURVEY §5,
`/root/reference/src/tools.jl:228-234`); on TPU the idiomatic extra is an XLA
profiler trace viewable in TensorBoard/Perfetto (per-op device timelines,
collective overlap, HBM traffic).  Host-side spans recorded through
:mod:`igg.telemetry` are mirrored onto the same device timeline via
`jax.profiler.TraceAnnotation`, so a trace captured here lines up with the
unified event stream.
"""

from __future__ import annotations

import contextlib
import pathlib
import threading

from .shared import GridError

# Re-entrancy guard: `jax.profiler.start_trace` raises mid-flight when a
# trace is already active, which used to surface as an opaque runtime error
# AFTER the enclosing trace was silently broken.  One trace at a time,
# stated upfront.
_lock = threading.Lock()
_active_logdir = None


@contextlib.contextmanager
def trace(logdir: str = "/tmp/igg_trace"):
    """Capture a device trace of the enclosed block:

        with igg.profiling.trace("/tmp/trace"):
            for _ in range(10):
                T = step(T, Cp)

    Open the result with TensorBoard's profile plugin or ui.perfetto.dev.
    The log directory is created (parents included) if missing; nesting a
    second `trace()` inside an active one raises :class:`igg.GridError`
    immediately instead of corrupting the in-flight capture.  Entry and
    exit are recorded on the unified event bus (`trace_started` /
    `trace_stopped`, :mod:`igg.telemetry`).
    """
    import jax

    from . import telemetry as _telemetry

    global _active_logdir
    with _lock:
        if _active_logdir is not None:
            raise GridError(
                f"igg.profiling.trace: a trace is already active "
                f"(logdir {_active_logdir!r}) — traces do not nest; close "
                f"the enclosing trace first.")
        _active_logdir = str(logdir)
    try:
        # A missing parent used to crash start_trace deep inside the
        # profiler plugin; create the whole path upfront.
        pathlib.Path(logdir).mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(logdir)
    except BaseException:
        with _lock:
            _active_logdir = None
        raise
    _telemetry.emit("trace_started", logdir=str(logdir))
    try:
        yield logdir
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            with _lock:
                _active_logdir = None
            _telemetry.emit("trace_stopped", logdir=str(logdir))


def annotate(name: str):
    """Named region that shows up on the profiler timeline (wraps
    `jax.profiler.TraceAnnotation`).  :func:`igg.telemetry.span` builds on
    the same annotation and ALSO records the region on the host-side event
    bus — prefer it when you want both."""
    import jax

    return jax.profiler.TraceAnnotation(name)
