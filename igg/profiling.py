"""Profiling hooks.

The reference's observability is `tic()/toc()` only (SURVEY §5,
`/root/reference/src/tools.jl:228-234`); on TPU the idiomatic extra is an XLA
profiler trace viewable in TensorBoard/Perfetto (per-op device timelines,
collective overlap, HBM traffic).
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def trace(logdir: str = "/tmp/igg_trace"):
    """Capture a device trace of the enclosed block:

        with igg.profiling.trace("/tmp/trace"):
            for _ in range(10):
                T = step(T, Cp)

    Open the result with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the profiler timeline (wraps
    `jax.profiler.TraceAnnotation`)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
