"""igg.perf — performance observability: the persistent perf ledger,
live roofline / cost-model-drift gauges, and bench regression gating.

PR 7's :mod:`igg.telemetry` made *incidents* observable; this module does
the same for *performance*.  Three pieces, all flowing through the
telemetry bus (``perf_sample`` records in the flight recorder and every
attached session's JSONL sink, gauges in the metrics registry /
Prometheus exposition):

- **The perf ledger.**  Every measured dispatch becomes a sample keyed
  ``(family, tier, local_shape, dtype, dims, backend, device_kind)`` —
  the same signature axes the compiled-program cache keys on — with
  ms-per-step aggregates (best/mean/last/count, per-source counts).
  Samples arrive from three sources with zero hot-loop host syncs:

  1. *watchdog windows*: the run loops' :class:`igg.telemetry.StepStats`
     meter hands each window's measured ms/step to
     :func:`observe_window`, which attributes it to the kernel tier(s)
     that actually served dispatches inside that window
     (:func:`igg.degrade.active_records` stamps) — piggybacking entirely
     on the watchdog's existing async probe fetches;
  2. *verify-on-first-use*: after a fast tier passes its one-time
     numeric check, :mod:`igg.degrade` times one extra warm dispatch on
     scratch copies and records it (one sample per (tier, signature));
  3. *explicit calibration*: :func:`calibrate` slope-times a step (or a
     named model family's default step) ahead of time and records the
     result — the AOT path benchmarks and the future autotuner drive.

  The ledger persists to a **versioned JSON file**
  (``IGG_PERF_LEDGER``; format ``igg-perf-ledger-v1``) with
  merge-on-write atomic saves, rank-tagged on multi-controller runs,
  and is mergeable across processes/runs (``python -m igg.perf
  show|merge``).  :func:`best` / :func:`query` are the designed entry
  points for the ROADMAP-item-2 autotuner: an on-disk prior of measured
  per-(tier, shape, dtype, topology) timings.

- **Live gauges.**  Each recorded sample updates
  ``igg_achieved_gbps{family,tier}`` and ``igg_pct_hbm_peak`` from the
  family's analytic bytes/step accounting (the
  ``docs/stokes_roofline.md`` / ``pallas_sweep`` traffic models) and a
  per-device-kind HBM-peak table.  :func:`predict` registers the cost
  model's ``compute_s_per_step`` for a family
  (``benchmarks/cost_model_calibration.py`` feeds it); measured samples
  then maintain ``igg_cost_model_rel_error{family}`` and emit a
  ``cost_model_drift`` bus event when the relative error exceeds
  ``IGG_PERF_DRIFT_TOL``.

- **Regression gating.**  ``python -m igg.perf compare <baseline>
  <new> --tol X`` matches benchmark JSONL rows on (metric, config) AND
  the PR-7 provenance header — only rows with the same
  (backend, device_kind, smoke) are compared, so TPU evidence is never
  gated against CPU smoke — and exits nonzero on regressions beyond
  tolerance: a ``"pass": true`` contract row flipping false, a
  lower-is-better value (ms, %, seconds) growing past ``--tol``
  relative, a higher-is-better value (GB/s, steps/s, jobs/hour)
  shrinking past it, or a golden row missing entirely.
  ``benchmarks/run_all.py --compare`` and ``ci.sh`` enforce the
  committed CPU-smoke goldens under ``benchmarks/goldens/``.

Everything here is host-side bookkeeping: no device collectives, no
extra device→host synchronization (the zero-host-syncs sentinel in
``tests/test_telemetry.py`` runs with the ledger enabled).
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import _env
from . import shared
from . import telemetry as _telemetry
from .shared import GridError

__all__ = [
    "enabled", "ledger_path", "record", "query", "best", "predict",
    "calibrate", "observe_window", "window_state", "sample_context",
    "device_context", "bytes_per_step", "hbm_peak_gbps", "save", "load",
    "merge_ledgers", "reset", "invalidate", "forget_prediction",
    "compare_rows", "compare_paths", "register_family",
    "registered_families", "LEDGER_FORMAT",
]

LEDGER_FORMAT = "igg-perf-ledger-v1"

_lock = threading.RLock()
_LEDGER: Dict[Tuple, Dict] = {}          # key tuple -> aggregate entry
_PREDICTIONS: Dict[str, Dict] = {}       # family -> cost-model prediction
_DRIFT_EMITTED: set = set()              # (family, tier) drift events sent
# What this process has already contributed to each ledger FILE
# ({path: {key: {count, sum_ms, sources}}}): repeated saves to the same
# file must merge only the DELTA since the last save — re-merging the
# full in-memory ledger into a file that already holds its own earlier
# save would double-count every persisted sample.  load() credits a
# file's entries to its baseline for the same reason.
_PERSISTED: Dict[str, Dict[Tuple, Dict]] = {}
_last_save = 0.0
_atexit_registered = False


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """The master switch: ``IGG_PERF=0`` disables all ledger recording
    (queries and the CLI still work on whatever was loaded)."""
    return _env.flag("IGG_PERF", True)


def ledger_path() -> Optional[pathlib.Path]:
    """The configured on-disk ledger (``IGG_PERF_LEDGER``), rank-tagged on
    multi-controller runs so concurrent processes never fight over one
    file (``ledger.json`` → ``ledger_r3.json`` on rank 3; the rank files
    merge with ``python -m igg.perf merge``).  None when unset — the
    ledger then lives in memory only."""
    raw = _env.text("IGG_PERF_LEDGER")
    if not raw:
        return None
    p = pathlib.Path(raw)
    rank = _telemetry._process()
    if rank:
        p = p.with_name(f"{p.stem}_r{rank}{p.suffix or '.json'}")
    return p


# ---------------------------------------------------------------------------
# Roofline accounting: analytic bytes/step + per-device-kind HBM peaks
# ---------------------------------------------------------------------------

# Full-field HBM accesses per step for the per-step tiers (mosaic / xla),
# the ideal-fusion traffic models of benchmarks/pallas_sweep.py and
# docs/stokes_roofline.md (logical bytes; tile-padding excluded — see the
# roofline doc for the padded v5e numbers):
#   diffusion3d: read T + Cp, write T                      -> 3 accesses
#   stokes3d:    read P,Vx,Vy,Vz,Rho, write P,Vx,Vy,Vz     -> 9 accesses
#   hm3d:        read H,M, write H,M                       -> 4 accesses
#   wave2d:      read P,Vx,Vy, write P,Vx,Vy               -> 6 accesses
_FAMILY_ACCESSES = {"diffusion3d": 3, "stokes3d": 9, "hm3d": 4, "wave2d": 6}

# Round 17: the hard-coded family table above became a REGISTRATION HOOK
# so spec-defined families (igg.stencil) get roofline gauges, drift
# detection, and heal-loop re-calibration without editing this module.
# `register_family(name, accesses=..., steps=...)` supplies the analytic
# accesses count (the stencil analyzer derives it from the read-set) and
# an optional `steps(dtype) -> (state_fn, args)` builder consulted by
# :func:`calibrate`; the four built-ins stay in the tables as the
# fallback, registry entries win.
_FAMILY_REGISTRY: Dict[str, Dict] = {}


def register_family(name: str, *, accesses: Optional[int] = None,
                    steps=None) -> None:
    """Register (or update) a model family with the perf layer:
    `accesses` feeds :func:`bytes_per_step`'s roofline model, `steps`
    (a `(dtype) -> (state_fn, args)` builder on the live grid) makes
    :func:`calibrate`'s named-family convenience — and with it the heal
    loop's drift→recalibrate action — work for the family.  Idempotent;
    `igg.stencil.compile` calls it for every compiled spec."""
    with _lock:
        _FAMILY_REGISTRY[str(name)] = {
            "accesses": int(accesses) if accesses is not None else None,
            "steps": steps,
        }


def registered_families() -> Dict[str, Dict]:
    """The registered-family table (name -> {accesses, steps}); the
    built-in families live in the static fallback tables, not here."""
    with _lock:
        return dict(_FAMILY_REGISTRY)

# Peak HBM bandwidth per chip, GB/s (published per-chip figures; matched
# by substring against the lowercased jax `device_kind`).  The K-step
# trapezoid tiers read/write once per K steps, so the per-step model
# does not apply to them (bytes_per_step returns None there).
_HBM_PEAK_TABLE: Sequence[Tuple[str, float]] = (
    ("v6e", 1640.0), ("v6 lite", 1640.0),
    ("v5p", 2765.0), ("v5e", 819.0), ("v5 lite", 819.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
)


def bytes_per_step(family: str, tier: Optional[str], local_shape,
                   dtype) -> Optional[int]:
    """Analytic HBM traffic of ONE step of `family`'s per-step tiers on a
    `local_shape` block of `dtype` — logical bytes, the ideal-fusion
    model.  None when no model applies (unknown family, a K-step
    trapezoid tier whose traffic is amortized over K, or no shape)."""
    reg = _FAMILY_REGISTRY.get(family)
    acc = (reg["accesses"] if reg and reg.get("accesses") is not None
           else _FAMILY_ACCESSES.get(family))
    if acc is None or not local_shape:
        return None
    if tier and ("trapezoid" in tier or tier.endswith(".chunk")):
        # Resident K-step chunk tiers read/write HBM once per K steps,
        # so the per-step model does not apply.  The STREAMING `.banded`
        # tier is deliberately NOT excluded: its rolling window
        # re-streams every field once per iteration of the chunk (HBM
        # ping-pong), so its amortized per-step traffic matches the
        # ideal-fusion accesses model (docs/stokes_roofline.md).
        return None
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        return None
    cells = 1
    for s in local_shape:
        cells *= int(s)
    return acc * cells * itemsize


def hbm_peak_gbps(device_kind: Optional[str]) -> Optional[float]:
    """Published peak HBM bandwidth (GB/s) for a jax `device_kind`, or
    None when unknown (CPU hosts have no meaningful HBM peak)."""
    if not device_kind:
        return None
    dk = str(device_kind).lower()
    if "tpu" not in dk:
        return None
    for pat, val in _HBM_PEAK_TABLE:
        if pat in dk:
            return val
    return None


# ---------------------------------------------------------------------------
# Sample context (key axes read from live arrays — metadata only, no fetch)
# ---------------------------------------------------------------------------

def device_context() -> Dict:
    """`{backend, device_kind}` of the default device — the environment
    half of the ledger key (the same fields the benchmark provenance
    header stamps, so bench rows and ledger entries are joinable)."""
    import jax

    dev = jax.devices()[0]
    return {"backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", dev.platform)}


def sample_context(array=None) -> Dict:
    """Ledger-key context from a live grid array: its per-device block
    shape (shard metadata — never a device fetch), dtype, the grid's
    `dims`, and the device context.  With `array=None` only the
    grid/device axes are filled."""
    ctx = dict(device_context())
    ctx["dims"] = (tuple(shared.global_grid().dims)
                   if shared.grid_is_initialized() else None)
    if array is not None:
        shards = getattr(array, "addressable_shards", None)
        if shards:
            ctx["local_shape"] = tuple(shards[0].data.shape)
        else:
            ctx["local_shape"] = tuple(getattr(array, "shape", ()))
        ctx["dtype"] = str(getattr(array, "dtype", type(array).__name__))
    return ctx


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------

def _key(family, tier, local_shape, dtype, dims, backend, device_kind
         ) -> Tuple:
    return (str(family), str(tier),
            tuple(int(s) for s in (local_shape or ())),
            str(dtype),
            tuple(int(d) for d in dims) if dims else None,
            str(backend) if backend else None,
            str(device_kind) if device_kind else None)


def _key_str(k: Tuple) -> str:
    family, tier, shape, dtype, dims, backend, device_kind = k
    return "|".join([
        family, tier, "x".join(map(str, shape)) or "-", dtype,
        "x".join(map(str, dims)) if dims else "-",
        backend or "-", device_kind or "-"])


def _entry_key(e: Dict) -> Tuple:
    return _key(e["family"], e["tier"], e.get("local_shape") or (),
                e.get("dtype", "-"), e.get("dims"), e.get("backend"),
                e.get("device_kind"))


def record(family: str, tier: str, ms_per_step: float, *,
           local_shape=(), dtype="-", dims=None, backend=None,
           device_kind=None, source: str = "api",
           window_steps: Optional[int] = None) -> Optional[Dict]:
    """Record one measured sample into the ledger: update the keyed
    aggregates, refresh the roofline / cost-model gauges, emit a
    ``perf_sample`` bus record, and (throttled) autosave the on-disk
    ledger.  Pure host bookkeeping — no device work.  Returns the
    updated entry (a copy), or None when recording is disabled
    (``IGG_PERF=0``) or the sample is unusable (non-finite/non-positive
    ms)."""
    if not enabled():
        return None
    try:
        ms = float(ms_per_step)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(ms) or ms <= 0:
        return None
    k = _key(family, tier, local_shape, dtype, dims, backend, device_kind)
    now = time.time()
    with _lock:
        e = _LEDGER.get(k)
        if e is None:
            e = _LEDGER[k] = {
                "family": k[0], "tier": k[1], "local_shape": list(k[2]),
                "dtype": k[3], "dims": list(k[4]) if k[4] else None,
                "backend": k[5], "device_kind": k[6],
                "count": 0, "sum_ms": 0.0, "best_ms": ms, "last_ms": ms,
                "sources": {}, "updated_wall": now,
            }
        e["count"] += 1
        e["sum_ms"] += ms
        e["best_ms"] = min(e["best_ms"], ms)
        e["last_ms"] = ms
        e["mean_ms"] = e["sum_ms"] / e["count"]
        e["sources"][source] = e["sources"].get(source, 0) + 1
        e["updated_wall"] = now
        snapshot = dict(e)

    payload = {"family": k[0], "tier": k[1], "ms_per_step": ms,
               "local_shape": list(k[2]), "dtype": k[3],
               "dims": list(k[4]) if k[4] else None, "backend": k[5],
               "device_kind": k[6], "source": source}
    if window_steps is not None:
        payload["window_steps"] = int(window_steps)

    # Roofline gauges: achieved GB/s from the analytic traffic model,
    # percent of the device kind's HBM peak when one is known.
    nbytes = bytes_per_step(k[0], k[1], k[2], k[3])
    if nbytes:
        gbps = nbytes / (ms * 1e-3) / 1e9
        payload["achieved_gbps"] = gbps
        _telemetry.gauge("igg_achieved_gbps", family=k[0],
                         tier=k[1]).set(gbps)
        peak = hbm_peak_gbps(k[6])
        if peak:
            pct = 100.0 * gbps / peak
            payload["pct_hbm_peak"] = pct
            _telemetry.gauge("igg_pct_hbm_peak", family=k[0],
                             tier=k[1]).set(pct)

    # Cost-model drift: measured beside the registered prediction.
    pred = _PREDICTIONS.get(k[0])
    if pred is not None:
        rel = (pred["s_per_step"] * 1e3 - ms) / ms
        payload["predicted_s_per_step"] = pred["s_per_step"]
        payload["cost_model_rel_error"] = rel
        _telemetry.gauge("igg_cost_model_rel_error", family=k[0]).set(rel)
        tol = _env.number("IGG_PERF_DRIFT_TOL", 0.5)
        if abs(rel) > tol:
            with _lock:
                fresh = (k[0], k[1]) not in _DRIFT_EMITTED
                _DRIFT_EMITTED.add((k[0], k[1]))
            if fresh:
                _telemetry.emit(
                    "cost_model_drift", family=k[0], tier=k[1],
                    rel_error=rel, tol=tol, measured_ms=ms,
                    predicted_s_per_step=pred["s_per_step"],
                    prediction_source=pred.get("source"))

    _telemetry.emit("perf_sample", **payload)
    _maybe_autosave()
    return snapshot


def predict(family: str, compute_s_per_step: float, *,
            source: str = "cost_model", **extra) -> None:
    """Register the cost model's predicted seconds/step for a family
    (``benchmarks/cost_model_calibration.py`` calls this with the AOT
    ``compute_s_per_step``).  Measured samples recorded for the family —
    now or later — maintain the ``igg_cost_model_rel_error{family}``
    gauge and fire a ``cost_model_drift`` bus event (once per
    (family, tier)) past ``IGG_PERF_DRIFT_TOL``."""
    pred = {"s_per_step": float(compute_s_per_step), "source": source,
            **extra}
    with _lock:
        _PREDICTIONS[family] = pred
    _telemetry.emit("cost_model_prediction", family=family,
                    compute_s_per_step=pred["s_per_step"], source=source)
    # A measurement may already exist: surface the drift now, not at the
    # next (possibly never) sample.
    e = best(family)
    if e is not None:
        rel = (pred["s_per_step"] * 1e3 - e["best_ms"]) / e["best_ms"]
        _telemetry.gauge("igg_cost_model_rel_error", family=family).set(rel)


def forget_prediction(family: str) -> None:
    """Unregister `family`'s cost-model prediction (the
    :func:`igg.heal.recalibrate` action drops the stale registration
    FIRST, so the fresh samples it records cannot re-fire
    ``cost_model_drift`` against the very prediction being replaced)."""
    with _lock:
        _PREDICTIONS.pop(family, None)


def query(family: Optional[str] = None, *, tier: Optional[str] = None,
          local_shape=None, dtype=None, dims=None, backend=None,
          device_kind=None) -> List[Dict]:
    """Entries matching every given filter (None = wildcard), best-first.
    Shapes/dims compare as tuples, so lists and tuples both match."""
    def norm(x):
        return tuple(x) if x is not None else None

    want_shape, want_dims = norm(local_shape), norm(dims)
    out = []
    with _lock:
        entries = [dict(e) for e in _LEDGER.values()]
    for e in entries:
        if family is not None and e["family"] != family:
            continue
        if tier is not None and e["tier"] != tier:
            continue
        if want_shape is not None and tuple(e["local_shape"]) != want_shape:
            continue
        if dtype is not None and e["dtype"] != str(dtype):
            continue
        if want_dims is not None and norm(e["dims"]) != want_dims:
            continue
        if backend is not None and e["backend"] != backend:
            continue
        if device_kind is not None and e["device_kind"] != device_kind:
            continue
        out.append(e)
    # Deterministic order with tie-breaking (round 16 — the autotuner's
    # prior must be stable when two tiers measure equal-best): best_ms
    # first, ties broken toward the better-evidenced entry (higher
    # sample count), then the freshest (updated_wall), then tier name.
    out.sort(key=lambda e: (e["best_ms"], -e["count"],
                            -e.get("updated_wall", 0.0), e["tier"]))
    return out


def best(family: str, local_shape=None, **filters) -> Optional[Dict]:
    """The fastest recorded entry for `family` under the given filters —
    the autotuner's entry point: ``best("diffusion3d", (130, 130, 66))``
    answers "which tier served this shape fastest, and how fast"."""
    matches = query(family, local_shape=local_shape, **filters)
    return matches[0] if matches else None


def reset() -> None:
    """Clear the in-memory ledger, predictions, and drift-event memory
    (the on-disk file is untouched; tests call this for isolation)."""
    global _last_save
    with _lock:
        _LEDGER.clear()
        _PREDICTIONS.clear()
        _DRIFT_EMITTED.clear()
        _PERSISTED.clear()
        _FAMILY_REGISTRY.clear()
        _last_save = 0.0


def invalidate(family: str, tier: Optional[str] = None) -> int:
    """Drop every in-memory ledger entry for `family` (optionally one
    `tier`) and re-arm the family's once-per-(family, tier)
    ``cost_model_drift`` events — the :mod:`igg.heal` re-calibration
    loop's first step: a drifted calibration must stop serving
    ``query()/best()`` answers BEFORE fresh samples replace it.  The
    entries are also dropped from the per-file persisted baselines, so a
    later :func:`save` merges the replacement samples into the on-disk
    ledger as new deltas (the file keeps the old aggregates as history —
    merge-on-write is append-only by design).  The family's TUNING-CACHE
    entries are evicted too (:func:`igg.autotune.invalidate` — a ledger
    a drift verdict just emptied must not keep serving the winner it
    once picked; round 16).  Emits one ``perf_invalidated`` bus record;
    returns the number of ledger entries dropped."""
    with _lock:
        keys = [k for k in _LEDGER
                if k[0] == family and (tier is None or k[1] == tier)]
        for k in keys:
            del _LEDGER[k]
        for base in _PERSISTED.values():
            for k in [b for b in base
                      if b[0] == family and (tier is None or b[1] == tier)]:
                del base[k]
        for dk in [d for d in _DRIFT_EMITTED if d[0] == family
                   and (tier is None or d[1] == tier)]:
            _DRIFT_EMITTED.discard(dk)
    try:
        from . import autotune as _autotune

        tune_evicted = _autotune.invalidate(family, tier=tier)
    except Exception:   # pragma: no cover - advisory path
        tune_evicted = 0
    _telemetry.emit("perf_invalidated", family=family, tier=tier,
                    entries=len(keys), tune_evicted=tune_evicted)
    return len(keys)


# ---------------------------------------------------------------------------
# Watchdog-window attribution (zero additional host syncs)
# ---------------------------------------------------------------------------

def window_state() -> Dict:
    """Opaque per-run attribution state for :func:`observe_window`:
    remembers the ladder-dispatch stamp so a window is only attributed
    to families that dispatched DURING it (a tier some unrelated earlier
    factory warmed is never credited with this run's step rate)."""
    from . import degrade

    return {"stamp": degrade.dispatch_stamp()}


def observe_window(run: str, ms_per_step: float, window_steps: int,
                   ctx: Optional[Dict], state: Dict) -> List[Dict]:
    """One watchdog window's measured rate, attributed to the serving
    tier(s): every `(family, tier)` whose ladder dispatch stamp advanced
    since the previous window gets a ledger sample (source
    ``"watchdog"``).  Called by :class:`igg.telemetry.StepStats` on the
    SAME host timestamps it already takes for ``step_stats`` records —
    the attribution reads only host-side ladder state, so the zero
    additional device→host syncs contract of the step-stats meter is
    preserved (sentinel-asserted in ``tests/test_telemetry.py``)."""
    if ctx is None or not enabled():
        return []
    from . import degrade

    prev = state.get("stamp", -1)
    recs = degrade.active_records()
    state["stamp"] = degrade.dispatch_stamp()
    out = []
    for family, tier, stamp in recs:
        if stamp <= prev:
            continue
        e = record(family, tier, ms_per_step, source="watchdog",
                   window_steps=window_steps,
                   local_shape=ctx.get("local_shape", ()),
                   dtype=ctx.get("dtype", "-"), dims=ctx.get("dims"),
                   backend=ctx.get("backend"),
                   device_kind=ctx.get("device_kind"))
        if e is not None:
            out.append(e)
    return out


# ---------------------------------------------------------------------------
# Explicit calibration (the AOT path)
# ---------------------------------------------------------------------------

def _default_family_step(family: str, dtype):
    """(state_fn, args) for a named model family's default step on the
    live grid — the convenience behind ``calibrate("diffusion3d")``.
    `state_fn` maps args to same-structured outputs (the
    `igg.time_steps` contract); pass-through coefficients ride along.
    Registered families (:func:`register_family` — spec-defined physics
    among them) resolve through their registered builder first."""
    reg = _FAMILY_REGISTRY.get(family)
    if reg is not None and reg.get("steps") is not None:
        return reg["steps"](dtype)
    if family == "diffusion3d":
        from .models import diffusion3d as m

        T, Cp = m.init_fields(m.Params(), dtype=dtype)
        step = m.make_step(m.Params(), donate=False)
        return (lambda T, Cp: (step(T, Cp), Cp)), (T, Cp)
    if family == "hm3d":
        from .models import hm3d as m

        fields = m.init_fields(m.Params(), dtype=dtype)
        step = m.make_step(m.Params(), donate=False)
        return (lambda *fs: step(*fs)), tuple(fields)
    if family == "stokes3d":
        from .models import stokes3d as m

        fields = m.init_fields(m.Params(), dtype=dtype)
        it = m.make_iteration(m.Params(), donate=False)
        # The iteration returns (P, Vx, Vy, Vz); Rho rides along (the
        # model run()'s own wrapper shape).
        return (lambda P, Vx, Vy, Vz, Rho:
                it(P, Vx, Vy, Vz, Rho) + (Rho,)), tuple(fields)
    if family == "wave2d":
        from .models import wave2d as m

        fields = m.init_fields(m.Params(), dtype=dtype)
        step = m.make_step(m.Params(), donate=False)
        return (lambda P, Vx, Vy: step(P, Vx, Vy)), tuple(fields)
    raise GridError(
        f"igg.perf.calibrate: unknown family {family!r} (built-ins: "
        f"diffusion3d, hm3d, stokes3d, wave2d; registered: "
        f"{sorted(_FAMILY_REGISTRY) or 'none'}; pass a step callable + "
        f"args for anything else, or register via "
        f"igg.perf.register_family).")


def calibrate(model, args=None, *, family: Optional[str] = None,
              tier: Optional[str] = None, nt: int = 8, warmup: int = 1,
              dtype=np.float32, source: str = "calibrate") -> float:
    """Slope-time a step ahead of serving traffic and record the result.

    `model` is either a step callable (then `args` is its argument tuple
    and `family` is required) or a model-family name
    (``"diffusion3d"`` / ``"stokes3d"`` / ``"hm3d"`` — the family's
    default step is built on the live grid).  The measurement is
    `igg.time_steps` slope timing (two batch sizes, nt and 3·nt —
    constant dispatch latency cancels); the serving `tier` is read from
    :func:`igg.degrade.active` after the timed dispatches unless given.
    Returns the measured seconds per dispatch (and records ms into the
    ledger unless ``IGG_PERF=0``)."""
    import igg

    shared.check_initialized()
    if isinstance(model, str):
        family = family or model
        step_fn, args = _default_family_step(model, dtype)
    else:
        if family is None:
            raise GridError("igg.perf.calibrate: family= is required when "
                            "passing a step callable.")
        step_fn = model
        if args is None:
            raise GridError("igg.perf.calibrate: args= (the step's "
                            "argument tuple) is required when passing a "
                            "step callable.")
    if nt < 1:
        raise GridError("igg.perf.calibrate: nt must be >= 1.")
    args = tuple(args) if isinstance(args, (tuple, list)) else (args,)
    _, sec = igg.time_steps(step_fn, args, n1=nt, n2=3 * nt, warmup=warmup)
    from . import degrade

    served = tier or degrade.active().get(family, f"{family}.xla")
    ctx = sample_context(args[0] if args else None)
    record(family, served, sec * 1e3, source=source,
           local_shape=ctx.get("local_shape", ()),
           dtype=ctx.get("dtype", "-"), dims=ctx.get("dims"),
           backend=ctx.get("backend"), device_kind=ctx.get("device_kind"))
    return sec


# ---------------------------------------------------------------------------
# Persistence: versioned JSON, merge-on-write, cross-run merge
# ---------------------------------------------------------------------------

def _merge_entry(into: Dict, e: Dict) -> None:
    into["count"] += e["count"]
    into["sum_ms"] += e["sum_ms"]
    into["best_ms"] = min(into["best_ms"], e["best_ms"])
    if e.get("updated_wall", 0) >= into.get("updated_wall", 0):
        into["last_ms"] = e["last_ms"]
        into["updated_wall"] = e.get("updated_wall", 0)
    into["mean_ms"] = into["sum_ms"] / max(1, into["count"])
    for s, n in e.get("sources", {}).items():
        into["sources"][s] = into["sources"].get(s, 0) + n


def merge_ledgers(entries_lists: Sequence[Sequence[Dict]]) -> Dict[Tuple,
                                                                   Dict]:
    """Merge entry lists (same-key aggregates combine: counts/sums add,
    best_ms min, last_ms from the newest `updated_wall`)."""
    merged: Dict[Tuple, Dict] = {}
    for entries in entries_lists:
        for e in entries:
            k = _entry_key(e)
            have = merged.get(k)
            if have is None:
                merged[k] = json.loads(json.dumps(e))   # deep copy
            else:
                _merge_entry(have, e)
    return merged


def _read_ledger_file(path) -> List[Dict]:
    path = pathlib.Path(path)
    try:
        doc = json.loads(path.read_text())
    except OSError as e:
        raise GridError(f"igg.perf: cannot read ledger {path}: {e}")
    except json.JSONDecodeError as e:
        raise GridError(f"igg.perf: {path} is not valid JSON ({e}).")
    if not isinstance(doc, dict) or doc.get("format") != LEDGER_FORMAT:
        raise GridError(
            f"igg.perf: {path} is not an {LEDGER_FORMAT} ledger "
            f"(format={doc.get('format') if isinstance(doc, dict) else '?'!r}).")
    return list(doc.get("entries", {}).values())


def _baseline_snapshot(e: Dict) -> Dict:
    return {"count": e["count"], "sum_ms": e["sum_ms"],
            "sources": dict(e.get("sources", {}))}


def _delta_entry(e: Dict, base: Optional[Dict]) -> Optional[Dict]:
    """`e` minus what was already persisted (`base`) — the only part a
    save may merge into a file that holds the earlier save.  None when
    nothing new happened for this key."""
    if base is None:
        return dict(e)
    d_count = e["count"] - base["count"]
    if d_count <= 0:
        return None
    d = dict(e)
    d["count"] = d_count
    d["sum_ms"] = e["sum_ms"] - base["sum_ms"]
    d["mean_ms"] = d["sum_ms"] / d_count
    d["sources"] = {s: n - base["sources"].get(s, 0)
                    for s, n in e.get("sources", {}).items()
                    if n - base["sources"].get(s, 0) > 0}
    return d


def save(path=None) -> Optional[pathlib.Path]:
    """Persist the in-memory ledger: read whatever is on disk, merge in
    this process's DELTA since its last save to that file (never the
    full ledger — the file already holds the earlier saves; see
    `_PERSISTED`), and atomically replace the file (tmp + rename) — so
    concurrent runs lose nothing and repeated saves never double-count.
    `path` defaults to the ``IGG_PERF_LEDGER`` rank path; with neither,
    a no-op returning None."""
    global _last_save
    target = pathlib.Path(path) if path is not None else ledger_path()
    if target is None:
        return None
    pkey = str(target.resolve())   # non-strict: path need not exist yet
    on_disk: List[Dict] = []
    disk_ok = False
    if target.exists():
        try:
            on_disk = _read_ledger_file(target)
            disk_ok = True
        except GridError:
            on_disk = []   # a corrupt ledger is replaced, not fatal
    with _lock:
        _last_save = time.monotonic()
        base = _PERSISTED.get(pkey, {}) if disk_ok else {}
        mine = []
        for k, e in _LEDGER.items():
            d = _delta_entry(e, base.get(k))
            if d is not None:
                mine.append(d)
        new_base = {k: _baseline_snapshot(e) for k, e in _LEDGER.items()}
    merged = merge_ledgers([on_disk, mine])
    doc = {"format": LEDGER_FORMAT, "saved_wall": time.time(),
           "process": _telemetry._process(),
           "entries": {_key_str(k): e for k, e in sorted(merged.items())}}
    try:
        target.parent.mkdir(parents=True, exist_ok=True)
        tmp = target.with_name(target.name + ".tmp")
        tmp.write_text(json.dumps(doc, indent=1, sort_keys=True))
        os.replace(tmp, target)
    except OSError:
        return None   # a full/readonly disk must never kill the run
    with _lock:
        # Committed: everything now in memory is also in the file (a
        # disk that was missing/corrupt started from an empty baseline).
        _PERSISTED[pkey] = new_base
    return target


def load(path=None, *, replace: bool = False) -> int:
    """Load a ledger file into memory (merging with what is there;
    ``replace=True`` clears first).  `path` defaults to the
    ``IGG_PERF_LEDGER`` rank path.  Returns the number of entries now in
    memory.  Raises :class:`GridError` on a missing/invalid/
    wrong-format file."""
    target = pathlib.Path(path) if path is not None else ledger_path()
    if target is None:
        raise GridError("igg.perf.load: no path given and IGG_PERF_LEDGER "
                        "is unset.")
    entries = _read_ledger_file(target)
    with _lock:
        pkey = str(target.resolve())
        if replace:
            # Memory is redefined as exactly this file's contents: every
            # other path's baseline is stale, and this path's baseline IS
            # the loaded set.
            _LEDGER.clear()
            _PERSISTED.clear()
            _PERSISTED[pkey] = {_entry_key(e): _baseline_snapshot(e)
                                for e in entries}
        else:
            # The loaded amounts came FROM this file: credit them to its
            # persisted baseline, or the next save would merge them back
            # in on top of themselves (double-counting).
            base = _PERSISTED.setdefault(pkey, {})
            for e in entries:
                k = _entry_key(e)
                have = base.get(k)
                if have is None:
                    base[k] = _baseline_snapshot(e)
                else:
                    have["count"] += e["count"]
                    have["sum_ms"] += e["sum_ms"]
                    for s, n in e.get("sources", {}).items():
                        have["sources"][s] = have["sources"].get(s, 0) + n
        merged = merge_ledgers([[dict(e) for e in _LEDGER.values()],
                                entries])
        _LEDGER.clear()
        _LEDGER.update(merged)
        return len(_LEDGER)


def _maybe_autosave() -> None:
    """Throttled background persistence: at most one save per
    ``IGG_PERF_SAVE_EVERY`` seconds (default 60), plus one at process
    exit — so a long run's ledger survives a crash without paying a
    file write per sample."""
    global _atexit_registered
    if ledger_path() is None:
        return
    if not _atexit_registered:
        import atexit

        with _lock:
            if not _atexit_registered:
                _atexit_registered = True
                atexit.register(lambda: save())
    every = _env.number("IGG_PERF_SAVE_EVERY", 60.0)
    if time.monotonic() - _last_save >= every:
        save()


# ---------------------------------------------------------------------------
# Regression gating: benchmark-row comparison
# ---------------------------------------------------------------------------

def _load_rows(path) -> List[Dict]:
    """Benchmark JSONL rows from a file or a directory of ``*.jsonl``
    (``*.failed.jsonl`` postmortem salvage excluded); unparsable lines
    are skipped — a gate must survive a truncated artifact."""
    p = pathlib.Path(path)
    if p.is_dir():
        files = sorted(f for f in p.glob("*.jsonl")
                       if not f.name.endswith(".failed.jsonl"))
    else:
        files = [p]
    if not files:
        raise GridError(f"igg.perf compare: no .jsonl files under {p}.")
    rows = []
    for f in files:
        try:
            text = f.read_text()
        except OSError as e:
            raise GridError(f"igg.perf compare: cannot read {f}: {e}")
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and "metric" in row:
                rows.append(row)
    return rows


def _row_key(r: Dict) -> Tuple[str, str]:
    return (str(r.get("metric")),
            json.dumps(r.get("config"), sort_keys=True, default=str))


def _row_prov(r: Dict) -> Tuple:
    """The provenance axes rows must share to be comparable: backend,
    device_kind, smoke flag (PR-7 header; rows written before it carry
    None — backfill-tolerant, they only match each other)."""
    prov = r.get("provenance") or {}
    return (prov.get("backend"), prov.get("device_kind"), r.get("smoke"))


def _direction(unit: Optional[str]) -> str:
    """'lower' (ms, %, seconds — smaller is better), 'higher' (GB/s,
    steps/s, jobs/hour, efficiency/overlap fractions — bigger is
    better), or 'abs' (relative-error columns — closer to zero is
    better)."""
    u = (unit or "").lower()
    if "relative error" in u or "rel_error" in u:
        return "abs"
    for tok in ("gb/s", "gbps", "/s", "/sec", "/hour", "/hr", "flop",
                "fraction", "efficiency", "speedup"):
        if tok in u:
            return "higher"
    return "lower"


def compare_rows(baseline: Sequence[Dict], new: Sequence[Dict], *,
                 tol: float = 0.1, allow_missing: bool = False,
                 gate_pass_values: bool = False) -> Dict:
    """Compare two benchmark row sets (the regression gate).

    Rows pair on (metric, canonical config) and are only compared when
    their provenance (backend, device_kind, smoke) matches — so a CPU
    smoke golden can never gate TPU evidence or vice versa.  Verdicts:

    - a row whose golden ``"pass"`` is true and new ``"pass"`` is false
      is ALWAYS a regression (contract rows carry their own tolerance —
      their values are informational unless `gate_pass_values`);
    - value rows regress when they move past `tol` RELATIVE in the bad
      direction for their unit (`_direction`);
    - golden rows with no new counterpart are `missing` — regressions
      unless `allow_missing` (golden rows whose provenance matches no
      new row at all are `out_of_scope`, skipped: a different host);
    - new-only rows are informational.

    Returns ``{regressions, improvements, ok, missing, out_of_scope,
    new_only, lines}`` — `lines` is the human-readable report."""
    base_by_key: Dict[Tuple, Dict] = {}
    for r in baseline:
        base_by_key[_row_key(r)] = r      # last row per key wins
    new_by_key: Dict[Tuple, Dict] = {}
    for r in new:
        new_by_key[_row_key(r)] = r
    new_provs = {_row_prov(r) for r in new}

    regressions, improvements, ok = [], [], []
    missing, out_of_scope = [], []
    lines: List[str] = []

    def fin(row, field="value"):
        v = row.get(field)
        return float(v) if isinstance(v, (int, float)) else None

    for key, b in sorted(base_by_key.items()):
        n = new_by_key.get(key)
        label = f"{key[0]} config={key[1]}"
        if n is None:
            if _row_prov(b) not in new_provs:
                out_of_scope.append(key)
                lines.append(f"SKIP (provenance out of scope) {label}")
            else:
                missing.append(key)
                lines.append(f"MISSING {label}")
            continue
        if _row_prov(b) != _row_prov(n):
            out_of_scope.append(key)
            lines.append(f"SKIP (provenance mismatch "
                         f"{_row_prov(b)} vs {_row_prov(n)}) {label}")
            continue
        verdicts = []
        if isinstance(b.get("pass"), bool):
            if b["pass"] and not n.get("pass"):
                verdicts.append(("regression",
                                 'contract "pass": true -> false'))
            gate_value = gate_pass_values
        else:
            gate_value = True
        bv, nv = fin(b), fin(n)
        if gate_value and bv is not None and nv is not None:
            d = _direction(b.get("unit"))
            if d == "abs":
                drift = abs(nv) - abs(bv)
                if drift > tol:
                    verdicts.append(("regression",
                                     f"|error| {abs(bv):.4g} -> "
                                     f"{abs(nv):.4g} (+{drift:.4g} > "
                                     f"tol {tol:g})"))
            else:
                scale = abs(bv)
                rel = ((nv - bv) / scale if scale
                       else (math.inf if nv > bv else 0.0))
                bad = rel if d == "lower" else -rel
                if bad > tol:
                    arrow = f"{bv:.6g} -> {nv:.6g}"
                    verdicts.append(("regression",
                                     f"value {arrow} ({bad:+.1%} beyond "
                                     f"tol {tol:.0%}, {d}-is-better "
                                     f"unit {b.get('unit')!r})"))
                elif -bad > tol:
                    verdicts.append(("improvement",
                                     f"value {bv:.6g} -> {nv:.6g}"))
        regs = [v for v in verdicts if v[0] == "regression"]
        if regs:
            regressions.append((key, [v[1] for v in regs]))
            for _, why in regs:
                lines.append(f"REGRESSION {label}: {why}")
        elif any(v[0] == "improvement" for v in verdicts):
            improvements.append(key)
            lines.append(f"IMPROVED {label}: "
                         f"{[v[1] for v in verdicts if v[0] == 'improvement'][0]}")
        else:
            ok.append(key)
            lines.append(f"OK {label}")

    new_only = sorted(set(new_by_key) - set(base_by_key))
    failing = len(regressions) + (0 if allow_missing else len(missing))
    lines.append(
        f"compare: {len(ok) + len(improvements) + len(regressions)} "
        f"matched ({len(regressions)} regression(s), "
        f"{len(improvements)} improved), {len(missing)} missing"
        f"{' (allowed)' if allow_missing and missing else ''}, "
        f"{len(out_of_scope)} out-of-scope, {len(new_only)} new-only")
    return {"regressions": regressions, "improvements": improvements,
            "ok": ok, "missing": missing, "out_of_scope": out_of_scope,
            "new_only": new_only, "lines": lines,
            "failed": failing > 0}


def compare_paths(baseline, new, *, tol: float = 0.1,
                  allow_missing: bool = False,
                  gate_pass_values: bool = False) -> Dict:
    """:func:`compare_rows` over files/directories of benchmark JSONL."""
    return compare_rows(_load_rows(baseline), _load_rows(new), tol=tol,
                        allow_missing=allow_missing,
                        gate_pass_values=gate_pass_values)


# ---------------------------------------------------------------------------
# CLI: python -m igg.perf show|merge|compare
# ---------------------------------------------------------------------------

def _format_entries(entries: Sequence[Dict]) -> str:
    import io

    out = io.StringIO()
    header = (f"{'family':<12} {'tier':<24} {'local_shape':<16} "
              f"{'dtype':<9} {'dims':<8} {'backend':<7} "
              f"{'best_ms':>10} {'mean_ms':>10} {'n':>5}  sources")
    out.write(header + "\n")
    for e in sorted(entries, key=lambda e: (e["family"], e["best_ms"])):
        shape = "x".join(map(str, e.get("local_shape") or [])) or "-"
        dims = ("x".join(map(str, e["dims"])) if e.get("dims") else "-")
        srcs = ",".join(f"{s}:{n}"
                        for s, n in sorted(e.get("sources", {}).items()))
        out.write(f"{e['family']:<12} {e['tier']:<24} {shape:<16} "
                  f"{e['dtype']:<9} {dims:<8} {e.get('backend') or '-':<7} "
                  f"{e['best_ms']:>10.4f} {e.get('mean_ms', 0):>10.4f} "
                  f"{e['count']:>5}  {srcs}\n")
    return out.getvalue()


def _main(argv: Sequence[str]) -> int:
    import sys

    usage = (
        "usage: python -m igg.perf show [<ledger.json>] [--family F]\n"
        "           [--tier T]\n"
        "       python -m igg.perf tune [<cache.json>] [--family F]\n"
        "           [--ledger <ledger.json>]\n"
        "       python -m igg.perf merge <out.json> <ledger.json> [...]\n"
        "       python -m igg.perf compare <baseline> <new> [--tol X]\n"
        "           [--allow-missing] [--gate-pass-values]\n"
        "  show     print a ledger (default: $IGG_PERF_LEDGER) as a table,\n"
        "           optionally filtered to one family and/or tier (the\n"
        "           per-signature view the tuning work reads)\n"
        "  tune     print the autotuner's tuning cache (default:\n"
        "           $IGG_TUNE_CACHE) next to the ledger prior each winner\n"
        "           came from\n"
        "  merge    merge ledger files into one (aggregates combine)\n"
        "  compare  regression-gate benchmark JSONL rows/dirs; exit 1 on\n"
        "           regressions (or missing golden rows)")
    argv = list(argv)
    if not argv:
        print(usage, file=sys.stderr)
        return 2
    cmd, rest = argv[0], argv[1:]

    def take_flag(name):
        if name in rest:
            i = rest.index(name)
            val = rest[i + 1]
            del rest[i:i + 2]
            return val
        return None

    try:
        if cmd == "show":
            fam = take_flag("--family")
            tier_f = take_flag("--tier")
            path = rest[0] if rest else ledger_path()
            if path is None:
                print("igg.perf show: no ledger given and IGG_PERF_LEDGER "
                      "is unset.", file=sys.stderr)
                return 2
            entries = _read_ledger_file(path)
            if fam is not None:
                entries = [e for e in entries if e["family"] == fam]
            if tier_f is not None:
                entries = [e for e in entries if e["tier"] == tier_f]
            print(f"# {path} ({len(entries)} entr"
                  f"{'y' if len(entries) == 1 else 'ies'})")
            sys.stdout.write(_format_entries(entries))
            return 0
        if cmd == "tune":
            from . import autotune

            fam = take_flag("--family")
            ledger_arg = take_flag("--ledger")
            path = rest[0] if rest else autotune.cache_path()
            if path is None:
                print("igg.perf tune: no cache given and IGG_TUNE_CACHE "
                      "is unset.", file=sys.stderr)
                return 2
            entries = autotune._read_cache_file(path)
            if fam is not None:
                entries = [e for e in entries if e["family"] == fam]
            lpath = ledger_arg or ledger_path()
            led = []
            if lpath is not None and pathlib.Path(lpath).exists():
                led = _read_ledger_file(lpath)
            print(f"# {path} ({len(entries)} winner"
                  f"{'' if len(entries) == 1 else 's'})"
                  + (f" vs prior {lpath}" if led else " (no ledger prior)"))
            header = (f"{'family':<12} {'local_shape':<14} {'tier':<22} "
                      f"{'K':>3} {'bx':>3} {'band':>4} {'vmem':>5} "
                      f"{'ms':>9}  prior (ledger best)")
            print(header)
            for e in sorted(entries, key=lambda e: (e["family"],
                                                    str(e["local_shape"]))):
                shape = "x".join(map(str, e.get("local_shape") or [])) or "-"
                prior = [l for l in led
                         if l["family"] == e["family"]
                         and tuple(l.get("local_shape") or ())
                         == tuple(e.get("local_shape") or ())]
                prior.sort(key=lambda l: l["best_ms"])
                ptxt = (f"{prior[0]['tier']} @ {prior[0]['best_ms']:.4f} ms"
                        if prior else "-")
                print(f"{e['family']:<12} {shape:<14} "
                      f"{e.get('tier') or '-':<22} "
                      f"{e.get('K') or '-':>3} {e.get('bx') or '-':>3} "
                      f"{e.get('band') or '-':>4} "
                      f"{str(e.get('vmem_mb') or '-'):>5} "
                      f"{(e.get('ms') or 0):>9.4f}  {ptxt}")
            return 0
        if cmd == "merge":
            if len(rest) < 2:
                print(usage, file=sys.stderr)
                return 2
            out, inputs = rest[0], rest[1:]
            merged = merge_ledgers([_read_ledger_file(p) for p in inputs])
            doc = {"format": LEDGER_FORMAT, "saved_wall": time.time(),
                   "process": -1,
                   "entries": {_key_str(k): e
                               for k, e in sorted(merged.items())}}
            outp = pathlib.Path(out)
            outp.parent.mkdir(parents=True, exist_ok=True)
            outp.write_text(json.dumps(doc, indent=1, sort_keys=True))
            print(f"merged {len(merged)} entr"
                  f"{'y' if len(merged) == 1 else 'ies'} from "
                  f"{len(inputs)} ledger(s) -> {out}", file=sys.stderr)
            return 0
        if cmd == "compare":
            tol, allow_missing, gate_pass = 0.1, False, False
            if "--tol" in rest:
                i = rest.index("--tol")
                tol = float(rest[i + 1])
                del rest[i:i + 2]
            if "--allow-missing" in rest:
                allow_missing = True
                rest.remove("--allow-missing")
            if "--gate-pass-values" in rest:
                gate_pass = True
                rest.remove("--gate-pass-values")
            if len(rest) != 2:
                print(usage, file=sys.stderr)
                return 2
            rep = compare_paths(rest[0], rest[1], tol=tol,
                                allow_missing=allow_missing,
                                gate_pass_values=gate_pass)
            for line in rep["lines"]:
                print(line)
            return 1 if rep["failed"] else 0
    except (GridError, ValueError, IndexError) as e:
        print(f"igg.perf {cmd}: {e}", file=sys.stderr)
        return 2
    print(usage, file=sys.stderr)
    return 2


if __name__ == "__main__":   # python -m igg.perf ...
    import sys

    sys.exit(_main(sys.argv[1:]))
