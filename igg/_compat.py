"""Guarded compatibility grafts for older JAX releases.

igg targets the toolchain's current JAX surface (`jax.shard_map` with
`check_vma`, `ShapeDtypeStruct(..., vma=...)`); on a modern install every
graft below is a no-op (`hasattr` guards), so the production environment
never sees patched behavior.  On older releases (<= 0.4.x, where
`shard_map` still lives in `jax.experimental` and varying-manual-axes
checking is called `check_rep`) the grafts map the new names onto the old
implementations so the CPU-mesh test suite and the examples still run —
the repo's "stub or gate missing deps" policy applied to the JAX API
itself.  `ShapeDtypeStruct(vma=...)` needs no graft: every igg call site
already branches on whether the incoming aval carries a `vma`.
"""

from __future__ import annotations


def install() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, *args, **kwargs):
            # API-faithful pass-through shim: every old-API argument
            # (positional or keyword, incl. check_rep/auto) forwards
            # unchanged, so other in-process libraries feature-detecting
            # `jax.shard_map` see the experimental implementation's own
            # contract.  Only the new-API `check_vma` flag is translated:
            # check_rep (its old name) predates the vma machinery and is
            # stricter about primitives it has no rules for (pallas_call),
            # so it defaults off — new-JAX environments keep real
            # check_vma and never reach this shim.
            kwargs.pop("check_vma", None)
            kwargs.setdefault("check_rep", False)
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map

    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # pallas not shipped: nothing to graft
        return

    if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams
