"""igg — Implicit Global Grid, TPU-native.

A brand-new JAX/XLA framework with the capabilities of
`ImplicitGlobalGrid.jl` (reference at `/root/reference`): distributed
stencil-based simulations on regular staggered grids, where a solver written
for a single device's local `(nx, ny, nz)` array runs unchanged on an
implicitly-assembled global grid over a whole TPU slice.

Instead of a Cartesian MPI topology with CUDA-aware point-to-point halo
messages, the grid is a `jax.sharding.Mesh` whose axes are the grid
dimensions; halo updates are XLA collective-permutes over ICI fused with their
pack/unpack slices, and whole time steps compile to single SPMD programs whose
communication XLA overlaps with interior compute.

Public API (reference: the 13 exported symbols at
`/root/reference/src/ImplicitGlobalGrid.jl:10-22`):

    init_global_grid, finalize_global_grid, update_halo, gather,
    select_device, nx_g, ny_g, nz_g, x_g, y_g, z_g, tic, toc

plus TPU-native extensions: field constructors (`zeros`, `ones`, `full`),
coordinate fields (`x_g_field`, ..., `coord_fields`), whole-step SPMD
compilation (`sharded`, `update_halo_local`, `local_coords`),
`gather_interior`, checkpointing (`save_checkpoint`, `load_checkpoint`,
`latest_checkpoint`, `verify_checkpoint`), the resilient run loop
(`run_resilient` — device-side NaN watchdog, checkpoint ring with
rollback-and-retry, preemption handling; fault injectors in `igg.chaos`),
the verified tier-degradation ladder (`igg.degrade` — kernel
quarantine with compile-failure capture, numeric verify-on-first-use
against the pure-XLA composition truth, observable/resettable status),
the ensemble/fleet tier (`igg.run_ensemble` — M independent members
in one compiled program with per-member fault isolation and quarantine;
`igg.run_fleet` — a job queue drained onto whatever devices exist, with
retry/backoff, a persistent journal, and elastic resume), and the unified
observability subsystem (`igg.telemetry` — one timestamped, rank-tagged
event bus with a flight-recorder ring, a metrics registry with
Prometheus exposition, zero-sync device-side step stats, and Chrome-trace
spans; docs/observability.md), and the performance-observability layer
(`igg.perf` — a persistent per-(family, tier, shape, dtype, topology)
perf ledger feeding the future autotuner, live roofline and
cost-model-drift gauges, and the `python -m igg.perf compare` benchmark
regression gate), and the communication-observability layer (`igg.comm`
— the comm ledger + ICI roofline gauges, per-window step-time
decomposition with exposed-comm fraction and overlap efficiency,
per-rank skew, and the collective-stall heartbeat that turns hung
collectives into structured artifacts), and the self-healing control
plane (`igg.heal` — a policy engine subscribed to the event bus that
closes the detection→action loops: stall/straggler → elastic re-tile,
cost-model drift → re-calibration, lagging fleet job → repack, all
budget/hysteresis-governed and chaos-proven), and the live ops plane
(`igg.statusd` — an always-on HTTP endpoint serving `/metrics`,
`/healthz`, `/status`, and `/events` with live HBM gauges and
multi-rank aggregation, wired via the `serve=` knob on the run loops;
`python -m igg.top` renders it as a terminal dashboard), and the
numeric-integrity layer (`igg.integrity` — silent-data-corruption
defense: family-declared invariant probes and shadow re-execution
checks fused into the watchdog's single async fetch, per-rank device
attribution, deep-verified checkpoint rollback via
`verify_checkpoint(deep=True)`, and the heal loop's
fence-the-suspect-device re-tile — all chaos-provable with
`igg.chaos.silent_corruption`/`poison_checkpoint`).
"""

from ._compat import install as _compat_install

_compat_install()

from .shared import (
    AXIS_NAMES,
    NDIMS,
    NNEIGHBORS_PER_DIM,
    PROC_NULL,
    GlobalGrid,
    GridError,
    get_global_grid,
    grid_is_initialized,
)
from .init import init_global_grid
from .finalize import finalize_global_grid
from .halo import update_halo, update_halo_local
from .gather import gather, gather_interior
from .device import select_device
from .tools import (
    barrier,
    coord_fields,
    nx_g,
    ny_g,
    nz_g,
    tic,
    toc,
    x_g,
    x_g_field,
    y_g,
    y_g_field,
    z_g,
    z_g_field,
)
from .fields import (
    from_local_blocks,
    full,
    local_block,
    local_blocks,
    ones,
    spec_for,
    sharding_for,
    stacked_shape,
    zeros,
)
from .overlap import hide_communication
from .parallel import local_coords, sharded
from .checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
    save_checkpoint_sharded,
    verify_checkpoint,
    verify_checkpoint_distributed,
)
from .resilience import ResilienceError, RunResult, run_resilient
from .ensemble import EnsembleResult, run_ensemble
from .fleet import FleetResult, Job, JobOutcome, run_fleet
from .serve import ServeControl, ServeResult, serve_fleet
from .timing import time_steps
from . import autotune
from . import chaos
from . import comm
from . import degrade
from . import device
from . import ensemble
from . import fleet
from . import heal
from . import integrity
from . import perf
from . import profiling
from . import resilience
from . import serve
from . import statusd
from . import stencil
from . import telemetry
from . import tools
from . import vis
from .telemetry import Telemetry

__version__ = "0.1.0"

__all__ = [
    "AXIS_NAMES", "NDIMS", "NNEIGHBORS_PER_DIM", "PROC_NULL",
    "GlobalGrid", "GridError", "get_global_grid", "grid_is_initialized",
    "init_global_grid", "finalize_global_grid",
    "update_halo", "update_halo_local",
    "gather", "gather_interior",
    "select_device",
    "nx_g", "ny_g", "nz_g", "x_g", "y_g", "z_g",
    "x_g_field", "y_g_field", "z_g_field", "coord_fields",
    "tic", "toc", "barrier",
    "zeros", "ones", "full", "from_local_blocks", "local_blocks",
    "local_block", "spec_for", "sharding_for", "stacked_shape",
    "hide_communication", "local_coords", "sharded", "profiling",
    "save_checkpoint", "save_checkpoint_sharded", "load_checkpoint",
    "latest_checkpoint", "verify_checkpoint", "verify_checkpoint_distributed",
    "run_resilient", "RunResult", "ResilienceError", "resilience", "chaos",
    "degrade", "vis",
    "run_ensemble", "EnsembleResult", "ensemble",
    "run_fleet", "Job", "JobOutcome", "FleetResult", "fleet",
    "serve_fleet", "ServeControl", "ServeResult", "serve",
    "telemetry", "Telemetry", "perf", "comm", "heal", "integrity",
    "autotune",
    "statusd", "stencil", "time_steps", "__version__",
]
