"""Device selection.

Counterpart of `/root/reference/src/select_device.jl`.  The reference maps the
node-local MPI rank onto a CUDA device (`CUDA.device!(me_l)`); under JAX the
runtime already binds each process to its local TPU chips and the mesh handles
placement, so this is a thin parity shim that validates devices exist and
returns the id of this process's primary device.
"""

from __future__ import annotations

from .shared import GridError, check_initialized


def select_device() -> int:
    """Return the id of the device this process primarily drives.

    Raises if no accelerator (or CPU fallback) device is available, mirroring
    the reference's error when CUDA is not functional
    (`/root/reference/src/select_device.jl:18`).
    """
    import jax

    check_initialized()
    devices = jax.local_devices()
    if not devices:
        raise GridError("Cannot select a device: no JAX devices are available.")
    return devices[0].id
