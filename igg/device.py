"""Device selection.

Counterpart of `/root/reference/src/select_device.jl`.  The reference computes
the *node-local* rank of the calling process via
`MPI.Comm_split_type(COMM_TYPE_SHARED)` and binds it to the matching CUDA
device, erroring when a node hosts more ranks than GPUs
(`/root/reference/src/select_device.jl:13-27`).

The JAX analog differs in one structural way: the runtime already assigns each
controller process a *disjoint* set of local devices (`jax.local_devices()`),
so processes can never silently share a chip the way MPI ranks share a GPU.
What remains real work is (a) the node-local ordering of processes sharing a
physical host, (b) the host-level over-subscription check — more processes on
a host than the host has devices *in total* (the reference's exact error
condition) — and (c) binding the selected device as JAX's default device.
Host membership is established by allgathering a host fingerprint across
processes, the collective analog of `Comm_split_type(SHARED)`.

Like the reference's, :func:`select_device` is a *collective*: in a
multi-process run every process must call it (directly or via
``init_global_grid``).
"""

from __future__ import annotations

import hashlib
import socket
from typing import List, Sequence, Tuple

import numpy as np

from .shared import GridError, check_initialized


def _machine_id() -> str:
    """A machine-unique component beyond the hostname: containerized
    deployments routinely give distinct hosts identical hostnames, which
    would merge them into one 'node' and corrupt node-local ranks (or raise
    a spurious over-subscription error).  `/etc/machine-id` is stable across
    boots; `boot_id` distinguishes machines that lack it; hostname-only is
    the last resort."""
    for path in ("/etc/machine-id", "/proc/sys/kernel/random/boot_id"):
        try:
            with open(path) as f:
                v = f.read().strip()
            if v:
                return v
        except OSError:
            continue
    return ""


def _host_fingerprint() -> np.ndarray:
    """A stable per-host identifier, as two uint32s (transportable on meshes
    without x64 enabled).  `--xla_force_host_platform_device_count` test
    processes on one machine deliberately share a fingerprint — they model
    multiple ranks on one node, the exact case the reference's
    `Comm_split_type(SHARED)` exists for."""
    ident = f"{socket.gethostname()}|{_machine_id()}"
    digest = hashlib.sha1(ident.encode()).digest()
    lo = int.from_bytes(digest[0:4], "big")
    hi = int.from_bytes(digest[4:8], "big")
    return np.array([lo, hi], dtype=np.uint32)


def _same_host_processes() -> List[int]:
    """Process indices sharing this host, in `process_index` order (the
    reference's shared-memory communicator membership,
    `/root/reference/src/select_device.jl:15-17`).  Collective when
    `jax.process_count() > 1` (one allgather)."""
    import jax

    if jax.process_count() == 1:
        return [0]

    # (nprocs, 2): row p is process p's host fingerprint.
    all_fp = _allgather_fingerprints(_host_fingerprint())
    me = int(jax.process_index())
    return [p for p in range(all_fp.shape[0])
            if (all_fp[p] == all_fp[me]).all()]


def _allgather_fingerprints(mine: np.ndarray) -> np.ndarray:
    """`(nprocs, k)` table of every process's host fingerprint, on every
    process.  One compiled SPMD replication over the grid mesh — NOT
    `multihost_utils.process_allgather` of a host value, which some
    multi-controller backends (the multi-process CPU one included) do not
    implement.  Each device contributes its owning process's fingerprint;
    the replicated result is folded back per-process through the sharding's
    device→index map.  Requires the grid (callers run after
    `init_global_grid`, whose default mesh spans every process's devices).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .shared import AXIS_NAMES, global_grid, identity, replicating_jit

    grid = global_grid()
    ndev, k = grid.nprocs, int(mine.size)
    sh = NamedSharding(grid.mesh, PartitionSpec(tuple(AXIS_NAMES)))
    arr = jax.make_array_from_callback(
        (ndev, k), sh, lambda idx: mine[None, :].astype(np.uint32))
    rep = replicating_jit(
        identity, NamedSharding(grid.mesh, PartitionSpec()))(arr)
    rows = np.asarray(rep.addressable_shards[0].data)
    fp = np.zeros((int(jax.process_count()), k), dtype=np.uint32)
    for dev, idx in sh.devices_indices_map((ndev, k)).items():
        fp[dev.process_index] = rows[idx[0].start or 0]
    return fp


def node_local_rank() -> int:
    """Rank of this process among the processes running on the same host —
    the `me_l` the reference derives from `MPI.Comm_split_type`
    (`/root/reference/src/select_device.jl:15-17`).  Collective in
    multi-process runs."""
    import jax

    return _same_host_processes().index(int(jax.process_index()))


def _select(me_l: int, n_procs_on_host: int, n_local: int,
            n_host_devices: int) -> int:
    """Pure device-selection decision: which local device index to bind, or
    raise on over-subscription.  Split out for direct unit testing.

    Over-subscription is a *host-level* condition, exactly the reference's
    (`/root/reference/src/select_device.jl:18`): more processes on the host
    than the host has devices in total.  (A per-process `me_l < n_local`
    check would be wrong: in the standard one-device-per-process deployment,
    rank 1 on a 4-chip host legitimately has `me_l == 1` and one local
    device.)"""
    if n_local == 0:
        raise GridError("Cannot select a device: no JAX devices are "
                        "available to this process.")
    if n_procs_on_host > n_host_devices:
        raise GridError(
            f"Cannot select a device: this host runs {n_procs_on_host} "
            f"processes but has only {n_host_devices} device(s) in total "
            f"(the reference errors identically: "
            f"/root/reference/src/select_device.jl:18).")
    return me_l % n_local


def memory_stats(devices=None) -> List[dict]:
    """Per-device allocator statistics from the runtime, for the live
    HBM gauges of :mod:`igg.statusd`.

    Queries ``Device.memory_stats()`` on each of `devices` (default:
    this process's ``jax.local_devices()``) — a host-side allocator
    lookup, no device synchronization — and returns one entry per
    device that actually reports them::

        {"device": "tpu:0", "kind": "TPU v5p", "bytes_in_use": ...,
         "bytes_limit": ..., "peak_bytes_in_use": ...}

    Backends without allocator stats (the CPU backend among them) are
    HONESTLY OMITTED — an empty list, never an invented number (the
    `link_peak=None` precedent of :func:`igg.comm.link_peak_gbps`).
    Byte fields present in the runtime's dict but absent here simply
    were not reported."""
    import jax

    if devices is None:
        try:
            devices = jax.local_devices()
        except Exception:
            return []
    out: List[dict] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        entry = {"device": f"{d.platform}:{d.id}",
                 "kind": getattr(d, "device_kind", d.platform)}
        for k in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use",
                  "bytes_reserved", "largest_free_block_bytes"):
            v = stats.get(k)
            if v is not None:
                entry[k] = int(v)
        out.append(entry)
    return out


def select_device() -> int:
    """Bind this process to its node-local device and return the device id.

    Semantics mirror `/root/reference/src/select_device.jl:13-27`:
    node-local rank selects among this process's local devices; raises when
    the host runs more processes than it has devices, or when no devices are
    available at all (the reference's "CUDA is not functional" error, `:18`).
    Collective in multi-process runs (one allgather), like the reference's
    `Comm_split_type`.
    """
    import jax

    check_initialized()
    devices = jax.local_devices()

    if jax.process_count() == 1:
        if not devices:
            raise GridError("Cannot select a device: no JAX devices are "
                            "available to this process.")
        return devices[0].id

    same_host = _same_host_processes()
    me_l = same_host.index(int(jax.process_index()))
    host_procs = set(same_host)
    n_host_devices = sum(1 for d in jax.devices()
                         if d.process_index in host_procs)
    idx = _select(me_l, len(same_host), len(devices), n_host_devices)

    dev = devices[idx]
    jax.config.update("jax_default_device", dev)
    return dev.id
