"""Fleet scheduler — a queue of simulation jobs drained onto whatever
devices exist, with per-job retry, preemption persistence, and elastic
resume.

The "millions of users" tier of ROADMAP item 1: a *job* is a config — a
global domain, a member count, a step function — and the scheduler owns
everything a parameter-sweep driver would otherwise reinvent per launch:

- **Packing.**  Each job's Cartesian decomposition is planned against the
  devices that are actually present (`_plan_dims`: balanced factor
  triples of the device count, first one whose dims divide the job's
  global interior), so the same queue runs on a laptop CPU mesh, half a
  slice, or a full pod — and a RESUMED queue re-plans against the new
  capacity: the job's checkpoint ring re-tiles elastically through
  `igg.load_checkpoint(redistribute=True)` (the PR-4 path,
  `run_ensemble(resume=True)` rides it).
- **Per-job fault domain.**  Inside a job, member blowups are isolated by
  :func:`igg.run_ensemble` (per-member rollback/quarantine — a diverging
  member never kills the job, let alone the queue).  Around a job, a
  LAUNCHER fault (driver OOM, device grab race, transient filesystem
  error while building states) is retried with exponential backoff
  (`IGG_FLEET_RETRIES`/`IGG_FLEET_BACKOFF`); exhaustion marks the job
  `failed` and the queue drains on — one bad config cannot starve the
  fleet.
- **Preemption.**  SIGTERM (or `igg.resilience.request_preemption`)
  reaches the in-flight job's run loop, which writes its final generation
  on the way out; the scheduler records `preempted` in the queue journal
  and stops draining.  `run_fleet(..., resume=True)` re-admits every
  unfinished job: `done` jobs are skipped, `preempted`/`running` jobs
  resume from their rings (a `job_resumed` event), `queued` jobs start
  fresh — on whatever devices now exist.
- **The queue journal** (`{workdir}/journal.json`, format
  igg-fleet-journal-v1) is the scheduler's commit record: one atomic
  rewrite per state transition (`queued` → `running` → `done` | `failed`
  | `preempted`), carrying per-job attempts, steps done, member
  quarantines, and the dims the job last ran under.  A crash between
  transitions reads as `running`, which resume treats like `preempted`
  (resume from the ring — the ring's own commit protocol guarantees a
  loadable generation or none).

Chaos: :func:`igg.chaos.scheduler_fault` and
:func:`igg.chaos.job_preempt_at` inject both failure shapes
deterministically through the `_CHAOS_JOB_TAP` seam (consumed one-shot at
launch), so the retry/backoff and preempt/resume paths are proven on the
8-device CPU mesh (`tests/test_fleet.py`, `examples/fleet_run.py`).
Throughput headline: `benchmarks/fleet_throughput.py` (jobs/hour).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import pathlib
import signal
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import shared
from . import telemetry as _telemetry
from .shared import GridError, NDIMS
from .resilience import Event, ResilienceError, clear_preemption, \
    preemption_requested, preemption_requests, request_preemption

__all__ = ["Job", "JobOutcome", "FleetResult", "run_fleet", "plan_dims",
           "job_config_hash"]

_JOURNAL = "journal.json"
_JOURNAL_FORMAT = "igg-fleet-journal-v1"

# The scheduler-origin event kinds (everything else arriving at the
# fleet's emitter is a FORWARDED run_ensemble event — already on the
# telemetry bus from inside the run).
_SCHEDULER_KINDS = frozenset({
    "job_started", "job_done", "job_failed", "job_gave_up",
    "job_requeued", "job_preempted", "job_resumed", "heal_repack",
    "job_name_reused", "job_admitted", "job_shed", "job_rejected",
    "job_quarantined", "device_fenced",
})

# Chaos seam (igg.chaos.scheduler_fault / job_preempt_at): a dict
# {"fault": {job: {"times": n, "message": ...}},
#  "preempt": {job: {"step": k}}} consulted at job launch, entries
# consumed one-shot as they fire.
_CHAOS_JOB_TAP: Optional[dict] = None


def _fleet_retries_default() -> int:
    from . import _env

    return int(_env.integer("IGG_FLEET_RETRIES", 2))


def _fleet_backoff_default() -> float:
    from . import _env

    return float(_env.number("IGG_FLEET_BACKOFF", 0.5))


@dataclasses.dataclass
class Job:
    """One fleet job: a config plus a member count.

    - `name`: unique queue key (journal identity across resumes).
    - `step_fn`: the LOCAL member step (the :func:`igg.run_ensemble`
      contract) — rebuilt by the caller on every launch, so it can close
      over the freshly initialized grid.  When `make_step` is given it is
      called as `make_step(grid)` after grid init and its result serves
      instead (for steps that need grid-dependent constants).
    - `make_states(grid) -> [state dicts]`: builds the M member states on
      the live grid.  Must be decomposition-independent (global-coordinate
      initialization, the `igg.from_local_blocks` idiom) for elastic
      resume to be bit-exact.
    - `global_interior`: the de-duplicated global interior size per dim —
      the decomposition-invariant domain (`periodic: dims*(n-ol)`;
      `open: dims*(n-ol)+ol`).  The scheduler plans `dims` against the
      live devices and derives each local size from it.
    - `members`, `n_steps`, and the :func:`igg.run_ensemble` cadence knobs.
    """
    name: str
    global_interior: Tuple[int, int, int]
    members: int
    n_steps: int
    make_states: Callable = None
    step_fn: Callable = None
    make_step: Callable = None
    # Multi-tenant service identity (igg.serve): the owning tenant, the
    # scheduling priority (higher preempts lower), the submission wall
    # time, an optional queue-residency deadline, and the device-count
    # request the bin-packing admission honors (None: the scheduler's
    # default share).  Plain run_fleet drains ignore all but the journal
    # stamping, so batch queues are unchanged.
    tenant: str = "default"
    priority: int = 0
    submitted_at: Optional[float] = None
    deadline_s: Optional[float] = None
    n_devices: Optional[int] = None
    periods: Tuple[int, int, int] = (1, 1, 1)
    overlaps: Tuple[int, int, int] = (2, 2, 2)
    watch_every: int = 10
    checkpoint_every: int = 10
    ring: int = 3
    member_retries: Optional[int] = None
    steps_per_call: int = 1
    packing: str = "auto"
    chaos: object = None
    # Cost-model expectation for the igg.heal lagging-job loop: a job
    # whose measured member_steps_per_s falls below
    # `HealPolicy.throughput_tol` × this rate (sustained) is preempted at
    # the next generation and re-admitted at a different member packing.
    # None: the engine falls back to the job's own healthy baseline.
    expected_member_steps_per_s: Optional[float] = None


@dataclasses.dataclass
class JobOutcome:
    """Per-job record in a :class:`FleetResult`: terminal `status`
    ('done', 'failed', 'preempted', or 'queued' when the fleet stopped
    before reaching it), launcher `attempts` consumed, the job's
    :class:`igg.EnsembleResult` (None unless it ran to a result this
    drain), its event list, and the `dims` it ran under."""
    status: str
    attempts: int
    result: object = None
    events: List[Event] = dataclasses.field(default_factory=list)
    dims: Optional[Tuple[int, int, int]] = None
    error: Optional[str] = None


@dataclasses.dataclass
class FleetResult:
    jobs: Dict[str, JobOutcome]
    preempted: bool
    journal: pathlib.Path


# ---------------------------------------------------------------------------
# Decomposition planning
# ---------------------------------------------------------------------------

def _divisors(n: int) -> List[int]:
    out = set()
    d = 1
    while d * d <= n:
        if n % d == 0:
            out.add(d)
            out.add(n // d)
        d += 1
    return sorted(out)


@functools.lru_cache(maxsize=4096)
def _factor_triples(n: int) -> Tuple[Tuple[int, int, int], ...]:
    """All (dx, dy, dz) with dx*dy*dz == n, most balanced first (the
    `MPI_Dims_create` preference), deterministic order.  Divisor-based
    and memoized: the planner scans device counts N..1 per job launch,
    and an O(N) enumeration per count would make that scan quadratic at
    pod scale."""
    triples = []
    for dx in _divisors(n):
        for dy in _divisors(n // dx):
            triples.append((dx, dy, n // (dx * dy)))
    return tuple(sorted(triples,
                        key=lambda t: (max(t) - min(t), -t[0], -t[1])))


def _plan_score(dims, local, itemsize: int, hops):
    """Wire-bytes × link-hop score of one candidate mapping, plus the
    per-link traffic breakdown the `dims_planned` record carries."""
    elems = 1
    for n in local:
        elems *= int(n)
    nprocs = 1
    for d in dims:
        nprocs *= int(d)
    per_link = []
    total = 0.0
    for d in range(NDIMS):
        if dims[d] <= 1:
            continue
        b = 2 * (elems // int(local[d])) * int(itemsize) * nprocs
        h = float(hops[d]) if hops else 1.0
        per_link.append({"dim": "xyz"[d], "devices": int(dims[d]),
                         "wire_bytes_per_exchange": int(b),
                         "mean_link_hops": round(h, 3)})
        total += b * (h if h > 0 else 1.0)
    return total, per_link


def plan_dims(global_interior, n_devices: int, *, periods=(1, 1, 1),
              overlaps=(2, 2, 2), itemsize: int = 8,
              devices=None) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Plan a Cartesian decomposition of `global_interior` onto AT MOST
    `n_devices` devices: the largest device count with a factor triple
    whose dims divide the interior per dim and keep every local size a
    legal grid (`nx >= 2`, periodic dims >= `2*ol - 1`).  Returns
    `(dims, local)` — the `init_global_grid` arguments; raises `GridError`
    when not even one device fits.

    Balance stays the primary preference (the `MPI_Dims_create`
    contract the fleet/heal re-tile paths rely on), but EQUAL-BALANCE
    triples at the chosen device count are now tie-broken by predicted
    wire traffic instead of first-found enumeration order: total wire
    halo-plane bytes for the job's actual local shape
    (`igg.topology.plane_wire_bytes` — the `plane_bytes_by_mode` wire
    accounting at `itemsize` bytes/cell), each split dimension weighted
    by the mean physical ICI hop count of its mesh axis under the real
    `mesh_utils.create_device_mesh` placement (`igg.topology.link_hops`;
    every axis weighs 1 where the devices expose no physical coords —
    CPU meshes).  Score ties keep the original order, so isotropic
    interiors plan exactly as before.  The chosen mapping is logged as a
    ``dims_planned`` telemetry record carrying the predicted per-link
    traffic."""
    from . import telemetry as _telemetry
    from .topology import link_hops

    g = [int(v) for v in global_interior]
    per = [int(v) for v in periods]
    ol = [int(v) for v in overlaps]
    for nd in range(int(n_devices), 0, -1):
        legal = []
        for dims in _factor_triples(nd):
            local = []
            for d in range(NDIMS):
                span = g[d] if per[d] else g[d] - ol[d]
                if span % dims[d]:
                    local = None
                    break
                n = span // dims[d] + ol[d]
                if n < 2 or (per[d] and n < 2 * ol[d] - 1):
                    local = None
                    break
                local.append(n)
            if local is None:
                continue
            if local[1] == 1 and local[2] > 1:
                continue          # init_global_grid's ny/nz rule
            legal.append((tuple(dims), tuple(local)))
        if not legal:
            continue
        best = None
        for idx, (dims, local) in enumerate(legal):
            hops = (link_hops(dims, devices=devices)
                    if len(legal) > 1 else None)
            score, per_link = _plan_score(dims, local, itemsize, hops)
            # Primary key: balance (the MPI_Dims_create preference the
            # re-tile paths rely on); wire cost only breaks its ties.
            key = (max(dims) - min(dims), score, idx)
            if best is None or key < best[0]:
                best = (key, dims, local, per_link,
                        "physical" if hops else "uniform")
        (_, score, _), dims, local, per_link, hop_src = best
        _telemetry.emit("dims_planned", global_interior=list(g),
                        n_devices=int(n_devices), dims=list(dims),
                        local=list(local), itemsize=int(itemsize),
                        candidates=len(legal), hop_cost=hop_src,
                        predicted_wire_cost=round(float(score), 1),
                        per_link=per_link)
        return dims, local
    raise GridError(
        f"plan_dims: no decomposition of global interior {g} "
        f"(periods {per}, overlaps {ol}) fits onto <= {n_devices} "
        f"device(s).")


# ---------------------------------------------------------------------------
# The queue journal
# ---------------------------------------------------------------------------

def _read_journal(path: pathlib.Path) -> dict:
    try:
        j = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return {"format": _JOURNAL_FORMAT, "jobs": {}}
    if j.get("format") != _JOURNAL_FORMAT or not isinstance(
            j.get("jobs"), dict):
        return {"format": _JOURNAL_FORMAT, "jobs": {}}
    return j


def job_config_hash(job: "Job") -> str:
    """Identity stamp of a job's CONFIG (global_interior / members /
    n_steps / tenant), journaled with every record: resume matches a job
    against its prior record by this hash, so a NEW job reusing a finished
    job's name is a fresh job (`job_name_reused`), not a silent skip."""
    import hashlib

    key = json.dumps([list(int(v) for v in job.global_interior),
                      int(job.members), int(job.n_steps),
                      str(job.tenant)])
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def _write_journal(path: pathlib.Path, journal: dict) -> None:
    from .checkpoint import _write_atomic_text

    # durable=True: the journal is the queue's COMMIT RECORD — fsync the
    # tmp file before the atomic rename (and the directory after), so a
    # power cut mid-commit can never leave a torn journal that
    # resume=True misparses as "everything queued".
    _write_atomic_text(path, json.dumps(journal, indent=1, sort_keys=True),
                       durable=True)


def _journal_record(journal: dict, job: Job) -> dict:
    """The job's journal record, created queued if absent.  Existing
    records are read ABSENT-KEY-TOLERANTLY: a journal written before the
    service fields existed (tenant / priority / submitted_at / deadline_s
    / config_hash) resumes unchanged — missing keys are backfilled from
    the job without disturbing what the old drain recorded."""
    rec = journal["jobs"].setdefault(job.name, {
        "status": "queued", "attempts": 0, "steps_done": 0,
        "members": job.members, "quarantined": [], "dims": None})
    rec.setdefault("status", "queued")
    rec.setdefault("attempts", 0)
    rec.setdefault("steps_done", 0)
    rec.setdefault("quarantined", [])
    rec.setdefault("dims", None)
    rec.setdefault("tenant", job.tenant)
    rec.setdefault("priority", int(job.priority))
    rec.setdefault("submitted_at", job.submitted_at)
    rec.setdefault("deadline_s", job.deadline_s)
    rec.setdefault("config_hash", job_config_hash(job))
    return rec


def _reused_name(journal: dict, job: Job) -> bool:
    """True when `job` reuses the name of a journaled record whose config
    hash differs — a DIFFERENT job, not a resume target.  Records from
    pre-hash journals carry no hash and keep the old skip/resume
    semantics (there is nothing to compare)."""
    rec = journal["jobs"].get(job.name)
    if not isinstance(rec, dict):
        return False
    stamped = rec.get("config_hash")
    return stamped is not None and stamped != job_config_hash(job)


def _reset_for_reuse(journal: dict, jobdir: pathlib.Path, job: Job,
                     _emit) -> None:
    """Make a reused name a FRESH job: warn (`job_name_reused`), drop the
    stale record, and clear the prior job's checkpoint ring so elastic
    resume can never mix generations of two different configs."""
    import shutil

    old = journal["jobs"].pop(job.name, {}) or {}
    _emit("job_name_reused", 0, job=job.name, tenant=job.tenant,
          prior_status=old.get("status"),
          prior_config_hash=old.get("config_hash"),
          config_hash=job_config_hash(job))
    shutil.rmtree(jobdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# The scheduler
# ---------------------------------------------------------------------------

def _consume_tap(kind: str, job: str):
    """Pop/consume one chaos entry for `job` (one-shot semantics)."""
    global _CHAOS_JOB_TAP
    tap = _CHAOS_JOB_TAP
    if not tap or job not in tap.get(kind, {}):
        return None
    entry = tap[kind][job]
    if kind == "fault":
        entry["times"] -= 1
        if entry["times"] <= 0:
            tap[kind].pop(job)
    else:
        tap[kind].pop(job)
    if not any(tap.get(k) for k in tap):
        _CHAOS_JOB_TAP = None
    return entry


def run_fleet(jobs: Sequence[Job], workdir, *, devices=None,
              resume: bool = False, max_job_retries: Optional[int] = None,
              backoff: Optional[float] = None,
              install_sigterm: bool = True,
              on_event: Optional[Callable[[Event], None]] = None,
              telemetry=None, serve=None, heal=None) -> FleetResult:
    """Drain `jobs` in order onto the live devices (module docstring for
    the full contract).  The caller must NOT hold an initialized grid —
    the scheduler owns grid lifecycle per job.  `resume=True` reconciles
    against the journal under `workdir`: finished jobs are skipped,
    interrupted ones resume from their checkpoint rings (elastically, on
    whatever `devices` now exist).  Returns a :class:`FleetResult`;
    `on_event` receives every job-scoped event (detail carries `job`).
    `telemetry` attaches a unified observability session
    (:mod:`igg.telemetry` — the :func:`igg.run_resilient` contract) for
    the WHOLE drain: job lifecycle spans, a fleet queue-depth gauge,
    per-status job counters, and every job-scoped event on one
    rank-tagged JSONL stream.

    `serve` attaches the live ops endpoint (:mod:`igg.statusd` — the
    :func:`igg.run_resilient` contract: None = ``IGG_STATUSD_PORT``-
    driven, int port, True, shared server, or False) for the WHOLE
    drain; its `/status` additionally summarizes this drain's queue
    journal (per-status job counts).

    `heal` attaches the self-healing control plane (:mod:`igg.heal` —
    the :func:`igg.run_resilient` coercion: None = ``IGG_HEAL``-driven,
    True/policy/engine/False): a job whose measured
    ``member_steps_per_s`` falls below the policy's `throughput_tol` ×
    its `Job.expected_member_steps_per_s` (or its own healthy baseline)
    for `sustain` windows is preempted at the next generation (it writes
    its final ring generation — the PR-6 path) and re-admitted
    IMMEDIATELY at a different member packing (grid ↔ batch when
    admissible, else a halved device pool), resuming elastically from
    its ring — a `heal_repack` event per re-admission, budget/cool-down
    governed like every heal action."""
    import jax

    if shared.grid_is_initialized():
        raise GridError(
            "run_fleet: finalize the global grid first — the scheduler "
            "initializes one grid per job.")
    names = [j.name for j in jobs]
    if len(set(names)) != len(names):
        raise GridError(f"run_fleet: duplicate job names in {names}.")
    for j in jobs:
        if j.make_states is None or (j.step_fn is None
                                     and j.make_step is None):
            raise GridError(f"run_fleet: job {j.name!r} needs make_states "
                            f"and step_fn (or make_step).")
    if max_job_retries is None:
        max_job_retries = _fleet_retries_default()
    if backoff is None:
        backoff = _fleet_backoff_default()
    devs = list(devices) if devices is not None else list(jax.devices())

    workdir = pathlib.Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    jpath = workdir / _JOURNAL
    journal = _read_journal(jpath) if resume else {
        "format": _JOURNAL_FORMAT, "jobs": {}}
    outcomes: Dict[str, JobOutcome] = {}

    def _emit(kind, step, **detail) -> Event:
        ev = Event(kind, step, detail)
        # The unified bus (igg.telemetry): only the SCHEDULER's own kinds
        # are emitted here — nested run_ensemble events reach the bus from
        # inside the run (run="ensemble", same record), and re-emitting
        # the forwarded copy would double every incident in the stream.
        if kind in _SCHEDULER_KINDS:
            _telemetry.emit(kind, step=step, run="fleet", **detail)
        if on_event is not None:
            on_event(ev)
        return ev

    # Unified telemetry session for the whole drain.
    tel = _telemetry.as_session(telemetry)
    tel_owns = tel is not None and not tel.attached
    if tel_owns:
        tel.attach()
    _telemetry.emit("run_started", run="fleet", jobs=len(jobs),
                    resume=resume)
    # Self-healing control plane (igg.heal): the lagging-job → repack
    # loop — the engine watches each job's nested step_stats windows and
    # preempts a job measuring below its cost-model expectation; the
    # scheduler re-admits it at a different member packing below.
    from . import heal as _heal

    heal_eng = _heal.as_engine(heal, run="fleet")
    # Live ops endpoint (igg.statusd) for the whole drain; /status reads
    # this drain's journal for the per-status job counts.  Started AFTER
    # the heal= coercion above (a GridError there must not leak a bound
    # server), and a bind failure must not leak the drain-owned session
    # (the health tracker backfills run_started from the flight ring).
    from . import statusd as _statusd

    try:
        srv = _statusd.as_server(serve)
        srv_owns = srv is not None and not srv.started
        if srv_owns:
            srv.start()
    except BaseException:
        if tel_owns:
            tel.detach()
        raise
    if srv is not None:
        srv.watch_fleet(jpath)
    if heal_eng is not None:
        heal_eng.attach()
    m_queue = _telemetry.gauge("igg_fleet_queue_depth")

    def _queue_depth() -> int:
        """Jobs not yet terminal this drain ('done'/'failed' are terminal;
        'queued'/'running'/'preempted' still owe work)."""
        done = sum(1 for o in outcomes.values()
                   if o.status in ("done", "failed"))
        return len(jobs) - done

    def _jrec(job: Job) -> dict:
        return _journal_record(journal, job)

    def _transition(job: Job, **updates) -> None:
        _jrec(job).update(updates)
        journal["jobs"][job.name]["updated_at"] = time.time()
        _write_journal(jpath, journal)

    installed = False
    old_handler = None
    if install_sigterm:
        try:
            old_handler = signal.signal(signal.SIGTERM, request_preemption)
            installed = True
        except ValueError:
            pass

    fleet_preempted = False
    m_queue.set(_queue_depth())
    try:
        for job in jobs:
            if resume and _reused_name(journal, job):
                # Same name, different config hash: a NEW job, not the
                # journaled one — never skip it as finished (or resume it
                # from the other config's ring).
                _reset_for_reuse(journal, workdir / "jobs" / job.name,
                                 job, _emit)
                _write_journal(jpath, journal)
            rec = _jrec(job)
            if resume and rec["status"] == "done":
                outcomes[job.name] = JobOutcome(
                    status="done", attempts=rec["attempts"],
                    dims=tuple(rec["dims"]) if rec["dims"] else None)
                m_queue.set(_queue_depth())
                continue
            if fleet_preempted or preemption_requested():
                fleet_preempted = True
                outcomes[job.name] = JobOutcome(status="queued",
                                                attempts=rec["attempts"])
                break
            resume_job = resume and rec["status"] in ("preempted",
                                                      "running")
            with _telemetry.span("fleet.job", job=job.name,
                                 resume=resume_job):
                outcome = _run_job(job, workdir / "jobs" / job.name, devs,
                                   resume_job, max_job_retries, backoff,
                                   _emit, _transition, rec, tel, heal_eng)
            outcomes[job.name] = outcome
            _telemetry.counter("igg_fleet_jobs_total",
                               status=outcome.status).inc()
            m_queue.set(_queue_depth())
            # Stop draining on an in-run preemption, a preemption that
            # interrupted a launcher-fault backoff (the job went back to
            # 'queued'), or a SIGTERM that landed after the job's run
            # loop last checked (run_ensemble leaves the flag to its
            # owner — this scheduler — when install_sigterm=False).
            if outcome.status == "preempted" or preemption_requested():
                fleet_preempted = True
                break
        for job in jobs:
            if job.name not in outcomes:
                outcomes[job.name] = JobOutcome(
                    status="queued",
                    attempts=journal["jobs"].get(job.name,
                                                 {}).get("attempts", 0))
        _write_journal(jpath, journal)
        if fleet_preempted:
            _telemetry._auto_dump("fleet drain preempted")
    except BaseException as e:
        _telemetry._auto_dump(f"run_fleet: {type(e).__name__}: {e}")
        raise
    finally:
        if heal_eng is not None:
            heal_eng.detach()
        if installed:
            signal.signal(signal.SIGTERM, old_handler)
            # Owner-only clear (the igg.ensemble rule): with
            # install_sigterm=False a supervisor owns the wiring, and
            # clearing here would swallow a SIGTERM that landed after
            # this drain's last check.
            clear_preemption()
        _telemetry.emit("run_finished", run="fleet",
                        preempted=fleet_preempted)
        if srv_owns:
            srv.stop()
        if tel is not None:
            # Owned sessions export inside detach(); exporting here too
            # would write two identical back-to-back snapshots.
            if tel_owns:
                tel.detach()
            else:
                tel.export_metrics()

    return FleetResult(jobs=outcomes, preempted=fleet_preempted,
                       journal=jpath)


def _repack_choice(job: Job, served: str, devs) -> Tuple[str, list]:
    """A DIFFERENT member packing for a lagging job (the igg.heal repack
    loop): flip grid ↔ batch when the flip is admissible (batch needs the
    whole interior on one device and `members % n_devices == 0`), else
    keep the packing on a halved device pool — either way the members
    land on the devices differently, which is the point of re-admission."""
    if served != "batch":
        try:
            plan_dims(job.global_interior, 1, periods=job.periods,
                      overlaps=job.overlaps)
            fits_one = True
        except GridError:
            fits_one = False
        if fits_one and len(devs) > 1 and job.members % len(devs) == 0:
            return "batch", list(devs)
    else:
        return "grid", list(devs)
    return served, list(devs)[:max(1, len(devs) // 2)]


def _run_job(job: Job, jobdir: pathlib.Path, devs, resume_job: bool,
             max_job_retries: int, backoff: float, _emit, _transition,
             rec, tel, heal_eng=None) -> JobOutcome:
    """Launch one job with retry/exponential-backoff around LAUNCHER
    faults (grid init, decomposition planning, state build, compile) —
    a fault inside the run itself is the ensemble tier's problem."""
    import igg

    from .chaos import InjectedSchedulerFault
    from .ensemble import run_ensemble

    events: List[Event] = []

    def job_event(ev: Event) -> None:
        ev2 = Event(ev.kind, ev.step, {**ev.detail, "job": job.name})
        events.append(ev2)
        _emit(ev.kind, ev.step, **ev2.detail)

    attempt = rec["attempts"]   # journal-cumulative (all launches, ever)
    faults = 0                  # THIS drain's launcher faults: the budget
    #                             is per drain, so a job that was
    #                             preempted/resumed several times keeps
    #                             its full fault tolerance each time
    delay = backoff
    packing = job.packing       # rebindable: a heal repack re-admits the
    launch_devs = list(devs)    # job at a different packing/device pool
    expected_rate = job.expected_member_steps_per_s
    while True:
        attempt += 1
        _transition(job, status="running", attempts=attempt)
        try:
            fault = _consume_tap("fault", job.name)
            if fault is not None:
                raise InjectedSchedulerFault(
                    fault.get("message")
                    or f"injected launcher fault for job {job.name!r}")
            # Batch packing needs the degenerate single-device grid (the
            # member axis, not the domain, spans the devices); otherwise
            # pack the domain onto as many devices as divide it.
            cap = 1 if packing == "batch" else len(launch_devs)
            dims, local = plan_dims(job.global_interior, cap,
                                    periods=job.periods,
                                    overlaps=job.overlaps)
            ndev = int(np.prod(dims))
            igg.init_global_grid(
                *local, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                periodx=job.periods[0], periody=job.periods[1],
                periodz=job.periods[2], overlapx=job.overlaps[0],
                overlapy=job.overlaps[1], overlapz=job.overlaps[2],
                devices=launch_devs[:ndev], quiet=True)
            try:
                grid = igg.get_global_grid()
                step_fn = (job.make_step(grid) if job.make_step is not None
                           else job.step_fn)
                states = job.make_states(grid)
                chaos = job.chaos
                pre = _consume_tap("preempt", job.name)
                if pre is not None:
                    from .chaos import ChaosPlan

                    if chaos is None:
                        chaos = ChaosPlan(preempt_at=pre["step"])
                    else:
                        chaos.preempt_at = pre["step"]
                # Chaos throughput collapse (igg.chaos.throughput_collapse):
                # consumed one-shot at THIS launch — the rate limit on the
                # probe-readiness channel collapses measured member rates
                # for this launch only, so a heal re-admission runs clean.
                collapse = _consume_tap("collapse", job.name)
                slowdown = None
                if collapse is not None:
                    from .chaos import FetchDelay

                    slowdown = FetchDelay(collapse["delay_s"]).arm()
                job_event(Event("job_started", 0,
                                {"attempt": attempt, "dims": list(dims),
                                 "devices": ndev, "resume": resume_job,
                                 "packing": packing}))
                if heal_eng is not None:
                    heal_eng.watch_job(job.name, expected_rate)
                # The drain's session is passed THROUGH (already attached,
                # so the run neither re-attaches nor detaches it, and the
                # periodic metrics export runs at the watch cadence);
                # telemetry=False when the drain has none — the nested run
                # must not auto-attach a second session off
                # IGG_TELEMETRY_DIR onto the same files.
                try:
                    res = run_ensemble(
                        step_fn, states, job.n_steps, members=job.members,
                        watch_every=job.watch_every,
                        checkpoint_dir=jobdir,
                        checkpoint_every=job.checkpoint_every,
                        ring=job.ring,
                        member_retries=job.member_retries,
                        resume=resume_job,
                        steps_per_call=job.steps_per_call,
                        packing=packing, devices=launch_devs,
                        install_sigterm=False, on_event=job_event,
                        telemetry=tel if tel is not None else False,
                        # serve=False: the drain's endpoint covers every
                        # job — an env-driven nested server would try to
                        # bind the port the fleet's own server holds.
                        serve=False,
                        chaos=chaos)
                finally:
                    if slowdown is not None:
                        slowdown.disarm()
                    if heal_eng is not None:
                        heal_eng.unwatch_job()
                if resume_job and any(e.kind == "resume"
                                      for e in res.events):
                    job_event(Event("job_resumed",
                                    next(e.step for e in res.events
                                         if e.kind == "resume"),
                                    {"devices": ndev, "dims": list(dims)}))
            finally:
                igg.finalize_global_grid()
        except Exception as e:          # launcher fault: retry with backoff
            if igg.grid_is_initialized():
                igg.finalize_global_grid()
            if heal_eng is not None:
                # A repack planned for THIS job must not outlive its
                # failure: consume the plan and the engine's preemption
                # request, or the drain would misread the leaked flag as
                # an operator SIGTERM and stop the whole fleet (a real
                # SIGTERM racing the clear is re-raised, as above).
                rc = heal_eng.take_repack(job.name)
                if rc is not None:
                    clear_preemption()
                    if preemption_requests() > rc:
                        request_preemption()
            job_event(Event("job_failed", 0,
                            {"attempt": attempt,
                             "error": f"{type(e).__name__}: {e}"}))
            # The documented fault split: only LAUNCHER faults are
            # transient and worth a backoff retry.  The run's own terminal
            # verdicts are deterministic — an all-quarantined ensemble
            # (ResilienceError) or an invalid config (GridError) fails the
            # same way on every replay, and retrying would re-run the
            # whole job from scratch for nothing.
            faults += 1
            terminal = isinstance(e, (ResilienceError, GridError))
            if terminal or faults > max_job_retries:
                _transition(job, status="failed", attempts=attempt)
                job_event(Event("job_gave_up", 0, {"attempts": attempt,
                                                   "terminal": terminal}))
                return JobOutcome(status="failed", attempts=attempt,
                                  events=events,
                                  error=f"{type(e).__name__}: {e}")

            def _requeued():
                # A preemption landing around the backoff must not sleep
                # it out and relaunch (grid init + compile) just to stop:
                # hand the job back to the queue and let the drain stop.
                _transition(job, status="queued", attempts=attempt)
                job_event(Event("job_requeued", 0,
                                {"reason": "preempted during "
                                           "launcher-fault backoff"}))
                return JobOutcome(status="queued", attempts=attempt,
                                  events=events,
                                  error=f"{type(e).__name__}: {e}")

            if preemption_requested():
                return _requeued()
            time.sleep(delay)
            delay = min(delay * 2, 30.0)
            if preemption_requested():   # SIGTERM during the sleep
                return _requeued()
            continue

        repack_count = (heal_eng.take_repack(job.name)
                        if heal_eng is not None else None)
        if (repack_count is not None
                and preemption_requests() > repack_count):
            # An ADDITIONAL preemption request (an operator SIGTERM)
            # raced the heal action: honor it — the clear below would
            # swallow a real shutdown.  The job stands preempted in the
            # journal; a resume re-admits it (and may repack then).
            repack_count = None
        if res.preempted and repack_count is not None:
            # Loop 4 (igg.heal): the preemption was the heal engine's
            # doing — the job measured below its cost-model expectation
            # and wrote its final generation on the way out.  Re-admit it
            # IMMEDIATELY at a different member packing, resuming
            # elastically from the ring.  The engine's preemption request
            # is consumed here (owner-clear: run_ensemble ran with
            # install_sigterm=False, so the flag is this scheduler's).
            clear_preemption()
            if preemption_requests() > repack_count:
                # A SIGTERM slipped in between the guard above and the
                # clear: restore the flag — the drain must still stop.
                request_preemption()
            new_packing, new_devs = _repack_choice(job, res.packing,
                                                   launch_devs)
            if expected_rate is not None and len(new_devs) < len(
                    launch_devs):
                # A halved pool halves the deliverable rate: scale the
                # cost-model expectation, or the re-admitted job would
                # near-certainly re-signal lag against the stale one.
                expected_rate *= len(new_devs) / len(launch_devs)
            _transition(job, status="preempted", attempts=attempt,
                        steps_done=res.steps_done,
                        quarantined=res.quarantined, dims=list(dims))
            job_event(Event("heal_repack", res.steps_done,
                            {"from_packing": res.packing,
                             "packing": new_packing,
                             "from_devices": len(launch_devs),
                             "devices": len(new_devs),
                             "reason": "throughput_lag"}))
            heal_eng.record_done("repack", job=job.name,
                                 from_packing=res.packing,
                                 packing=new_packing)
            packing, launch_devs = new_packing, new_devs
            resume_job = True
            continue
        if not res.preempted and repack_count is not None:
            # The job finished before the engine's preemption request
            # landed: nothing to repack — consume the stale request so
            # the drain does not misread it as an operator SIGTERM
            # (a racing operator signal was already detected above and
            # left the flag standing).
            clear_preemption()
            if preemption_requests() > repack_count:
                request_preemption()   # a SIGTERM raced the clear: honor it
        status = "preempted" if res.preempted else "done"
        _transition(job, status=status, attempts=attempt,
                    steps_done=res.steps_done,
                    quarantined=res.quarantined, dims=list(dims))
        job_event(Event("job_preempted" if res.preempted else "job_done",
                        res.steps_done,
                        {"quarantined": res.quarantined,
                         "retries": {str(m): r
                                     for m, r in res.retries.items()}}))
        return JobOutcome(status=status, attempts=attempt, result=res,
                          events=events, dims=tuple(dims))
