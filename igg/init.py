"""Grid initialization.

TPU-native counterpart of `init_global_grid`
(`/root/reference/src/init_global_grid.jl:42-88`).  Instead of initializing
MPI and creating a Cartesian communicator of processes, it creates a
:class:`jax.sharding.Mesh` of TPU devices whose axes are the grid dimensions;
`reorder=1` maps mesh axes onto the physical ICI torus.  Argument names,
validation rules and the return tuple mirror the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from . import shared
from .shared import GlobalGrid, GridError, NDIMS
from .topology import create_mesh, dims_create


def _init_distributed_with_retry() -> int:
    """`jax.distributed.initialize()` with exponential backoff and a
    deadline — the coordinator process being slower to bind its port than
    the workers are to dial it is the standard multi-host launch flake, and
    a worker that gives up on the first refused connection kills the whole
    pod job.

    Knobs (environment): `IGG_DIST_INIT_TIMEOUT` — total seconds to keep
    retrying (default 300); `IGG_DIST_INIT_BACKOFF` — initial sleep between
    attempts (default 1s, doubling to a 30s cap).  On exhaustion raises
    `GridError` naming the coordinator address (from
    `JAX_COORDINATOR_ADDRESS`/`COORDINATOR_ADDRESS` when set) and the last
    underlying error.  Returns the number of attempts used (>= 1)."""
    import os
    import time

    import jax

    from . import _env

    timeout = _env.number("IGG_DIST_INIT_TIMEOUT", 300)
    delay = _env.number("IGG_DIST_INIT_BACKOFF", 1)
    deadline = time.monotonic() + timeout
    attempt = 0
    while True:
        attempt += 1
        try:
            jax.distributed.initialize()
            return attempt
        # Only connectivity-shaped errors are retried (the runtime surfaces
        # them as RuntimeError/XlaRuntimeError or OS-level socket errors);
        # a ValueError/TypeError from bad configuration can never succeed
        # on retry and propagates immediately.
        except (RuntimeError, ConnectionError, OSError, TimeoutError) as e:
            if "already initialized" in str(e).lower():
                # A second initialize can never succeed on retry; hiding
                # this one-line cause behind 300s of backoff and a
                # coordinator-unreachable diagnosis would be misleading.
                raise
            now = time.monotonic()
            if now >= deadline:
                coord = (os.environ.get("JAX_COORDINATOR_ADDRESS")
                         or os.environ.get("COORDINATOR_ADDRESS")
                         or "<auto-detected>")
                raise GridError(
                    f"jax.distributed.initialize() failed {attempt} time(s) "
                    f"over {timeout:g}s (IGG_DIST_INIT_TIMEOUT): coordinator "
                    f"{coord} never became reachable.  Last error: "
                    f"{type(e).__name__}: {e}") from e
            time.sleep(max(0.0, min(delay, deadline - now)))
            delay = min(delay * 2, 30.0)


def init_global_grid(nx: int, ny: int, nz: int, *,
                     dimx: int = 0, dimy: int = 0, dimz: int = 0,
                     periodx: int = 0, periody: int = 0, periodz: int = 0,
                     overlapx: int = 2, overlapy: int = 2, overlapz: int = 2,
                     disp: int = 1, reorder: int = 1,
                     devices: Optional[Sequence] = None,
                     init_distributed: bool = False,
                     select_device: bool = True,
                     quiet: bool = False):
    """Initialize a Cartesian grid of devices defining implicitly a global grid.

    Arguments mirror the reference (`/root/reference/src/init_global_grid.jl:42`):

    - ``nx, ny, nz``: number of elements of the *local* (per-device) grid.
    - ``dimx/y/z``: desired number of devices per dimension (0 = auto, chosen
      as balanced as possible, like ``MPI_Dims_create``).
    - ``periodx/y/z``: periodicity per dimension (0/1).
    - ``overlapx/y/z``: cells adjacent local grids overlap (default 2).
    - ``disp``/``reorder``: neighbor displacement / allow topology-aware device
      placement (ICI-torus alignment), the analogs of the ``MPI.Cart_shift`` /
      ``MPI.Cart_create`` arguments.
    - ``devices``: devices to build the grid from (default: ``jax.devices()``,
      i.e. every addressable device — the analog of ``MPI.COMM_WORLD``).
    - ``init_distributed``: initialize ``jax.distributed`` for multi-host runs
      (the analog of ``init_MPI=true``; default off because single-controller
      JAX needs no process bootstrap on one host).
    - ``select_device``: bind this process to its node-local device (the
      reference auto-selects at init when CUDA is enabled,
      `/root/reference/src/init_global_grid.jl:85`).  Only acts in
      multi-process runs — single-controller placement is fully described by
      the mesh; see :func:`igg.select_device`.

    Returns ``(me, dims, nprocs, coords, mesh)`` like the reference returns
    ``(me, dims, nprocs, coords, comm_cart)``
    (`/root/reference/src/init_global_grid.jl:87`); the mesh plays the role of
    the Cartesian communicator.
    """
    import jax

    if shared.grid_is_initialized():
        raise GridError("The global grid has already been initialized.")

    nxyz = np.array([nx, ny, nz], dtype=int)
    dims = np.array([dimx, dimy, dimz], dtype=int)
    periods = np.array([periodx, periody, periodz], dtype=int)
    overlaps = np.array([overlapx, overlapy, overlapz], dtype=int)

    # Argument validation (reference `/root/reference/src/init_global_grid.jl:62-66`).
    if nx == 1:
        raise GridError("Invalid arguments: nx can never be 1.")
    if ny == 1 and nz > 1:
        raise GridError("Invalid arguments: ny cannot be 1 if nz is greater than 1.")
    if np.any((nxyz == 1) & (dims > 1)):
        raise GridError(
            "Incoherent arguments: if nx, ny, or nz is 1, then the "
            "corresponding dimx, dimy or dimz must not be set (or set 0 or 1).")
    if np.any((nxyz < 2 * overlaps - 1) & (periods > 0)):
        raise GridError(
            "Incoherent arguments: if nx, ny, or nz is smaller than "
            "2*overlapx-1, 2*overlapy-1 or 2*overlapz-1, respectively, then "
            "the corresponding periodx, periody or periodz must not be set "
            "(or set 0).")
    # A dimension of size 1 forces a single device along it
    # (`/root/reference/src/init_global_grid.jl:66`).
    dims[(nxyz == 1) & (dims == 0)] = 1

    # `disp` is honored by the exchange (partners `disp` ranks away, the
    # `MPI.Cart_shift` semantics of `/root/reference/src/init_global_grid.jl:
    # 78-81`); negative displacements (role-swapped shifts) are not
    # meaningful for a halo update and are rejected eagerly.
    if disp < 1:
        raise GridError("Invalid arguments: disp must be a positive integer "
                        "(neighbor displacement of the Cartesian shift).")

    if init_distributed:
        # Retry-with-backoff: coordinator-not-yet-up is the standard
        # multi-host launch flake (IGG_DIST_INIT_TIMEOUT/_BACKOFF knobs).
        _init_distributed_with_retry()

    if devices is None:
        devices = jax.devices()
    nprocs_avail = len(devices)
    if np.all(dims > 0):
        nprocs = int(np.prod(dims))
    else:
        nprocs = nprocs_avail
    # The free dims are tie-broken by predicted wire traffic for THIS
    # local block (equal-balance permutations only — isotropic blocks
    # keep the MPI_Dims_create order exactly).
    dims = np.array(dims_create(nprocs, dims,
                                local_shape=(int(nx), int(ny), int(nz))),
                    dtype=int)

    mesh = create_mesh(tuple(dims), devices=devices, reorder=reorder)

    # Global grid size (`/root/reference/src/init_global_grid.jl:82`):
    # a periodic dimension has no outer boundary cells.
    nxyz_g = dims * (nxyz - overlaps) + overlaps * (periods == 0)

    me = int(jax.process_index())
    # Coordinates of this controller process in the grid.  Single-controller
    # (one process drives all devices): (0,0,0).  Per-device coordinates live
    # on the mesh and are queried with `igg.local_coords()` inside SPMD code.
    coords = (0, 0, 0)

    gg = GlobalGrid(
        nxyz_g=tuple(int(v) for v in nxyz_g),
        nxyz=(int(nx), int(ny), int(nz)),
        dims=tuple(int(v) for v in dims),
        overlaps=tuple(int(v) for v in overlaps),
        nprocs=int(nprocs),
        me=me,
        coords=coords,
        periods=tuple(int(v) for v in periods),
        disp=int(disp),
        reorder=int(reorder),
        mesh=mesh,
        quiet=bool(quiet),
        distributed=bool(init_distributed),
    )
    shared.set_global_grid(gg)

    # Auto device selection (the reference's `select_device=true` default
    # path, `/root/reference/src/init_global_grid.jl:85`): only meaningful —
    # and only collective-safe — when several controller processes must agree
    # on node-local device binding.
    if select_device and jax.process_count() > 1:
        from .device import select_device as _select_device
        _select_device()

    if not quiet and me == 0:
        print(f"Global grid: {nxyz_g[0]}x{nxyz_g[1]}x{nxyz_g[2]} "
              f"(nprocs: {nprocs}, dims: {dims[0]}x{dims[1]}x{dims[2]})")

    # Warm up the timing functions (`/root/reference/src/init_global_grid.jl:86,91-94`).
    # Skipped — rather than try/except-ed, which would also swallow real
    # timer failures — when the mesh holds devices the runtime cannot
    # execute on (AOT compile-only topologies, e.g.
    # `benchmarks/overlap_schedule.py` compiling an 8-chip SPMD program on
    # a 1-chip host); the timers warm up on first real use there.  In
    # multi-controller runs `jax.devices()` spans all hosts, so the
    # collective warm-up barrier still runs.
    if set(mesh.devices.flat) <= set(jax.devices()):
        from .tools import tic, toc
        tic()
        toc()

    return me, tuple(int(v) for v in dims), int(nprocs), coords, mesh
