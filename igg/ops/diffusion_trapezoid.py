"""K-step trapezoidal (halo-deep) diffusion kernel for exchanged meshes.

The mega-kernel (`diffusion_mega`) fuses the whole inner time loop into one
`pallas_call`, but only where every dimension self-wraps on one device.  On
the practical pod decompositions — `(N,1,1)` and `(N,M,1)` with the grid
split over the ring/torus — each step needs fresh halo planes from the
neighbors, so the per-step kernel re-pays the kernel-boundary HBM
round-trip and a collective per step
(`/root/reference/src/update_halo.jl`'s per-step exchange, likewise).

This module restores K-step fusion there with classic *trapezoidal temporal
blocking* over the exchanged dimension(s) — up to the full `(N,M,K)` 3-D
torus, the v5p BASELINE topology:

  1. Once per K-step chunk, each device receives the K rows beyond each end
     of its block along every exchanged dimension (ONE `ppermute` pair per
     dim moving K-deep slabs — 1/K of the per-step collective count at the
     same total bytes) and forms the extended buffer — a contiguous window
     of the global array.  The extensions are built dimension-sequentially:
     the y slabs are cut from the x-EXTENDED buffer and the z slabs from
     the x/y-extended buffer, so corner/edge regions arrive via the later
     neighbors' own earlier-dim extensions (the same sequential-exchange
     trick the halo engine uses for corner propagation,
     `/root/reference/src/update_halo.jl:36,130`).  z slabs ride the wire
     TRANSPOSED (z on the sublane axis) so nothing lane-padded
     materializes.
  2. ONE `pallas_call` advances K steps on the extended window (same
     VMEM-resident coefficient, HBM ping-pong, and hand double-buffered DMA
     as the mega-kernel; wrap dims keep their in-VMEM self-wrap aliases).
     Each step the outermost rows of every extended dimension lose
     validity — after K steps exactly the device's own block (interior AND
     halo rows) carries the values the per-step path would produce,
     bit-for-bit, because every row is updated by the identical stencil
     arithmetic the neighbor would apply.
  3. The final step's programs write only that central window to the
     output; the garbage shoulders are never materialized outside the
     ping-pong scratch.

Per-chunk overhead vs the ideal: the concat (one extended-buffer write) and
the redundant shoulder compute (`2K/S` per extended dim) — both amortized
by K.

Validity needs fresh data beyond each extended end.  Periodic rings
provide it by neighbor slabs (`periods[d]`, any `dims[d] >= 1` — on one
device a ring is the self-neighbor ppermute, handled by the in-kernel
wrap).  OPEN boundaries provide it by freezing: a no-write boundary row
(`/root/reference/test/test_update_halo.jl:727-732`) is genuinely local —
global-edge devices re-freeze their boundary plane from the chunk-entry
buffer every step (uniform SPMD shapes, `axis_index` edge flags), which
both preserves the frozen rows bit-for-bit and quarantines the
beyond-domain shoulder garbage, so the validity front never shrinks from
an open side.  The open modes (`_dim_modes`: "oext"/"frozen") run in BOTH
realizations (round 6 — the reference's examples default to non-periodic,
so this is its default boundary condition on the compiled tier):

  - the pure-XLA window path freezes the boundary plane AND the
    beyond-domain shoulder planes from the chunk-entry buffer
    (`_window_steps_xla`), pinned per-step-equivalent on open and mixed
    8-device meshes by `tests/test_trapezoid.py::test_open_*`;
  - the Mosaic chunk kernel re-freezes exactly the boundary plane per
    open side per step, from freeze planes held VMEM-resident for the
    whole chunk, gated by per-device `axis_index` edge flags in SMEM
    (the same no-write semantics the open mega-kernel modes realize,
    `diffusion_mega` "frozen").  Freezing the single boundary plane
    suffices for central-window equality with the window realization:
    influence from the shoulder planes can only reach the central window
    THROUGH the frozen plane, which never reads its neighbors — so the
    two realizations (and the per-step path) agree bit-for-bit on the
    block, and the evolving shoulder garbage is quarantined exactly as
    the window's explicit shoulder freeze quarantines it.

The compiled dispatcher admits the open modes with
`trapezoid_supported(..., allow_open=True)`; the dispatcher in
`fused_diffusion_steps` also runs one per-step kernel step BEFORE the
chunks, which consumes never-exchanged entry halos exactly like every
other path (bit-equivalence for ANY input).

In interpret mode (CPU meshes, the driver dryrun) the chunk runs as a
pure-XLA realization of the same window dynamics (`_window_steps_xla`) —
the chunked exchange, corner-carrying extensions, and shrinking validity
are exercised everywhere; only the manual-DMA kernel itself is TPU-only.

Round 16: the family-independent machinery — per-dim window modes, edge
flags, the slab-permute extension, the open-dim freeze masks, the chunk
driver, the VMEM budget authority — moved to the shared K-step chunk
engine (`igg.ops.chunk_engine`, `igg.ops._vmem`); this module keeps the
diffusion physics, the HBM-streaming ping-pong Mosaic kernel (unique to
blocks that exceed VMEM), and its admission accounting.  The historical
underscore names (`_dim_modes`, `_edge_flags`, `_extend_dim`, `_extend`)
remain importable as engine aliases.
"""

from __future__ import annotations

from functools import partial

from ._vmem import banded_vmem, chunk_budget, fit_banded
from .chunk_engine import (admit_banded_geometry, admit_chunk_common,
                           admit_send_slabs, central_window, dim_modes,
                           edge_flags, ext_shape, extend_dim_grouped,
                           extend_fields, field_ols, freeze_open_dim,
                           run_chunks, streaming_chunk_call, wrap_edges)
from .diffusion_pallas import _u_rows

# Engine aliases (the historical private names, still used by tests and
# benchmarks; the implementations live in `igg.ops.chunk_engine`).
_dim_modes = dim_modes
_edge_flags = edge_flags


def _extend_dim(T, K, ol, grid, d, mode: str = "ext"):
    """One field's `size + 2K` window along dim `d` — the single-field
    form of the engine's grouped slab extension (one ppermute pair of
    `(K+1)`-row slabs; see `chunk_engine.extend_dim_grouped`)."""
    return extend_dim_grouped([T], [ol], K, grid, d, mode)[0]


def _extend(T, K, grid, shape, modes):
    """Dimension-sequential extension of one field (x, then y OF the
    x-extended buffer, then z — the sequential-exchange corner trick);
    wrap/frozen dims are not extended."""
    ols = [tuple(grid.ol_of_local(d, shape) for d in range(3))]
    return extend_fields([T], ols, K, grid, modes)[0]


def trapezoid_supported(grid, shape, bx: int, n_inner: int, dtype,
                        force_y_ext=None, force_z_ext=None,
                        allow_open: bool = False):
    """Whether the K=bx trapezoidal chunk path applies: periodic rings
    along every dimension (self-wrap or extended), at least one full
    chunk, the K-slab sends must lie inside the block, and the extended
    coefficient plus working buffers must fit in VMEM (the interpret-mode
    XLA fallback obeys the same gates so both modes take the same
    route).  Returns an :class:`igg.degrade.Admission` (truthy/falsy)
    carrying the structured refusal reason.

    `allow_open=True` additionally admits open (non-periodic) dimensions
    — the "oext"/"frozen" modes of `_dim_modes`, realized by BOTH the
    Mosaic chunk kernel (per-device edge-freeze planes + `axis_index`
    flags) and the pure-XLA window path; the compiled dispatcher passes
    it, serving the reference-default boundary condition on the K-step
    tier.  The default stays False so direct callers opt in explicitly."""
    import numpy as np

    from ..degrade import Admission
    from .chunk_engine import admit_chunk_common

    common = admit_chunk_common(grid, bx, n_inner)
    if common is not None:
        return common
    modes = _dim_modes(grid, force_y_ext, force_z_ext)
    if not allow_open and any(m in ("oext", "frozen") for m in modes):
        return Admission.no(f"open (non-periodic) dimensions {modes} and "
                            f"the caller did not pass allow_open=True")
    y_ext = modes[1] in ("ext", "oext")
    z_ext = modes[2] in ("ext", "oext")
    S0, S1, S2 = shape
    K = bx
    olx = grid.ol_of_local(0, shape)
    if olx < 2 or S0 % bx != 0:
        return Admission.no(f"x extent {S0} (overlap {olx}) not chunkable "
                            f"at K={bx} (needs ol >= 2, S0 % K == 0)")
    if modes[0] != "frozen" and (S0 - olx - K < 0 or olx + K > S0):
        # x send slabs inside the block (no slabs in frozen mode)
        return Admission.no(f"K={K} x send slabs fall outside the local "
                            f"block (S0={S0}, ol={olx})")
    if modes[0] == "frozen" and S0 // bx < 2:
        # The kernel's edge programs fetch their own clamped segments;
        # with one program both edge branches would collide on one slot.
        return Admission.no(f"frozen-x block needs >= 2 band programs "
                            f"(S0={S0}, K={bx})")
    if S1 % 8 != 0:
        # Mosaic requires tile-aligned VMEM memref slices of the double-
        # buffered scratch; sublane extent must be 8-aligned (f32).
        return Admission.no(f"y extent {S1} not a multiple of 8 (Mosaic "
                            f"sublane tile)")
    if not z_ext and S2 % 128 != 0:
        # Ditto for the lane extent; in z-extended mode the kernel
        # right-pads the extended extent to a 128 multiple instead.
        return Admission.no(f"z extent {S2} not a multiple of 128 (Mosaic "
                            f"lane tile; z not extended)")
    S1e, S2e = S1, S2
    if y_ext:
        oly = grid.ol_of_local(1, shape)
        # 8-aligned K keeps the extended span and the caller's central-
        # window XLA slice on sublane-tile boundaries (S1 alignment is
        # gated unconditionally above); the y send slabs must lie inside
        # the block.
        if oly < 2 or K % 8 != 0:
            return Admission.no(f"y-extended chunk needs ol >= 2 and "
                                f"K % 8 == 0 (ol={oly}, K={K})")
        if S1 - oly - K < 0 or oly + K > S1:
            return Admission.no(f"K={K} y send slabs fall outside the "
                                f"local block (S1={S1}, ol={oly})")
        S1e = S1 + 2 * K
    if z_ext:
        olz = grid.ol_of_local(2, shape)
        # No S2 alignment requirement on the caller: the extension slabs
        # ride the wire TRANSPOSED (z on the sublane axis) so nothing
        # lane-padded materializes, and the compiled kernel right-pads the
        # extended lane extent to a 128 multiple (Mosaic requires aligned
        # VMEM lane slices); the K-offset central z slice is a relayout
        # pass amortized 1/K per step.
        if olz < 2:
            return Admission.no(f"z-extended chunk needs overlap >= 2 "
                                f"(ol={olz})")
        if S2 - olz - K < 0 or olz + K > S2:
            return Admission.no(f"K={K} z send slabs fall outside the "
                                f"local block (S2={S2}, ol={olz})")
        S2e = ((S2 + 2 * K + 127) // 128) * 128
    S0e = S0 + (2 * K if modes[0] != "frozen" else 0)
    itemsize = np.dtype(dtype).itemsize
    need = itemsize * (S0e * S1e * S2e            # A_ext resident
                       + 2 * (bx + 2) * S1e * S2e   # ext slabs (dbl-buffered)
                       + 2 * bx * S1e * S2e)        # out slabs (dbl-buffered)
    # Open dims keep their two freeze planes VMEM-resident for the chunk.
    for d, plane in ((0, S1e * S2e), (1, S0e * S2e), (2, S0e * S1e)):
        if modes[d] in ("oext", "frozen"):
            need += 2 * itemsize * plane
    if need > chunk_budget():
        return Admission.no(f"resident working set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def _kernel(*refs, K, bx, nbe, nbo, off, S0e, S1e, S2, modes, frz,
            rdx2, rdy2, rdz2):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Variadic unpacking (`frz` is the static tuple of (dim, lo, hi)
    # freeze-plane indices — empty on fully-periodic grids, whose program
    # carries no flags/planes/freeze scratch at all).
    nfr = 2 * len(frz)
    it = iter(refs)
    Text_hbm, A_hbm = next(it), next(it)
    flags = next(it) if frz else None          # SMEM (6,) i32 edge flags
    fr_hbm = [next(it) for _ in range(nfr)]    # squeezed freeze planes
    out_ref, buf0, buf1 = next(it), next(it), next(it)
    a_vmem, ext2, o2, esems, osems, asem = (next(it) for _ in range(6))
    fr_vmem = [next(it) for _ in range(nfr)]
    fsems = next(it) if frz else None

    k = pl.program_id(0)
    i = pl.program_id(1)
    scal = (rdx2, rdy2, rdz2)
    sl = i % 2

    # One-time: extended coefficient (and the chunk-invariant freeze
    # planes of the open dims) into VMEM.
    @pl.when((k == 0) & (i == 0))
    def _():
        dma = pltpu.make_async_copy(A_hbm, a_vmem, asem)
        dma.start()
        dma.wait()

    if frz:
        @pl.when((k == 0) & (i == 0))
        def _():
            cs = [pltpu.make_async_copy(fr_hbm[j], fr_vmem[j], fsems.at[j])
                  for j in range(nfr)]
            for c in cs:
                c.start()
            for c in cs:
                c.wait()

    # Out-write bookkeeping (identical scheme to diffusion_mega._kernel):
    # drain at each step boundary, else wait the DMA whose slot is reused.
    @pl.when((i == 0) & (k > 0))
    def _():
        pltpu.make_async_copy(o2.at[0], o2.at[0], osems.at[0]).wait()
        pltpu.make_async_copy(o2.at[1], o2.at[1], osems.at[1]).wait()

    @pl.when(i >= 2)
    def _():
        pltpu.make_async_copy(o2.at[sl], o2.at[sl], osems.at[sl]).wait()

    # Extended-slab fetches (rows [i*bx-1, i*bx+bx+1), CLAMPED at the
    # buffer ends — the clamped duplicate rows only feed shoulder rows that
    # are outside the validity trapezoid).  Edge programs fetch their own
    # segments synchronously; interior programs consume their
    # predecessor's prefetch and issue the next one.
    def sync_fetch(src):
        @pl.when(i == 0)
        def _():
            c0 = pltpu.make_async_copy(src.at[0:1], ext2.at[sl, 0:1],
                                       esems.at[sl])
            c1 = pltpu.make_async_copy(src.at[0:bx + 1],
                                       ext2.at[sl, 1:bx + 2],
                                       esems.at[1 - sl])
            c0.start(); c1.start(); c0.wait(); c1.wait()

        @pl.when(i == nbe - 1)
        def _():
            c0 = pltpu.make_async_copy(src.at[S0e - bx - 1:S0e],
                                       ext2.at[sl, 0:bx + 1], esems.at[sl])
            c1 = pltpu.make_async_copy(src.at[S0e - 1:S0e],
                                       ext2.at[sl, bx + 1:bx + 2],
                                       esems.at[1 - sl])
            c0.start(); c1.start(); c0.wait(); c1.wait()

    def prefetch_next(src):
        # Prefetch the NEXT program's slab — targets slabs 1..nbe-2 only
        # (edge programs fetch their own clamped segments synchronously).
        @pl.when((i >= 0) & (i <= nbe - 3))
        def _():
            pltpu.make_async_copy(
                src.at[pl.ds((i + 1) * bx - 1, bx + 2)],
                ext2.at[1 - sl], esems.at[1 - sl]).start()

    for cond, src in ((k == 0, Text_hbm),
                      ((k > 0) & (k % 2 == 1), buf0),
                      ((k > 0) & (k % 2 == 0), buf1)):
        @pl.when(cond)
        def _(src=src):
            sync_fetch(src)
            prefetch_next(src)

    @pl.when((i > 0) & (i < nbe - 1))
    def _():
        pltpu.make_async_copy(ext2.at[sl], ext2.at[sl], esems.at[sl]).wait()

    # Stencil update in x-row bands (identical scheme to the mega-kernel's
    # interior programs; every row of the extended buffer is "interior" —
    # shoulder rows compute garbage that the shrinking validity never reads
    # back into valid cells).
    ext = ext2.at[sl]
    o_vmem = o2.at[sl]
    c = ext[1:bx + 1]
    a = a_vmem[pl.ds(i * bx, bx)]
    if bx > 2:
        o_vmem[1:bx - 1, 1:-1, 1:-1] = _u_rows(
            c[0:bx - 2], c[1:bx - 1], c[2:bx], a[1:bx - 1], *scal)
    o_vmem[0:1, 1:-1, 1:-1] = _u_rows(ext[0:1], c[0:1], c[1:2],
                                      a[0:1], *scal)
    o_vmem[bx - 1:bx, 1:-1, 1:-1] = _u_rows(
        c[bx - 2:bx - 1], c[bx - 1:bx], ext[bx + 1:bx + 2],
        a[bx - 1:bx], *scal)
    if modes[1] == "wrap":
        # y self-wrap; in extended-y mode the edge rows are shoulder cells
        # whose (garbage) values the validity argument never reads back,
        # and in frozen-y mode the edge rows are owned by the freeze below.
        o_vmem[:, 0:1, 1:-1] = o_vmem[:, S1e - 2:S1e - 1, 1:-1]
        o_vmem[:, S1e - 1:S1e, 1:-1] = o_vmem[:, 1:2, 1:-1]
    if modes[2] == "wrap":
        # z self-wrap; ditto for extended-z shoulder lanes / frozen-z.
        o_vmem[:, :, 0:1] = o_vmem[:, :, S2 - 2:S2 - 1]
        o_vmem[:, :, S2 - 1:S2] = o_vmem[:, :, 1:2]

    # Open-boundary edge freeze (after the wrap writes — the freeze wins
    # the shared cells, like the per-step path's no-write planes): each
    # open dim's boundary plane is re-written from the chunk-entry values
    # on the devices whose `axis_index` edge flag is set ("frozen" dims
    # set both flags on every device — one device IS both edges).  x
    # planes belong to the single program owning that extended row; y/z
    # planes are written band-wise by every program.
    for j, (d, lo_i, hi_i) in enumerate(frz):
        vlo, vhi = fr_vmem[2 * j], fr_vmem[2 * j + 1]
        flo, fhi = flags[2 * d], flags[2 * d + 1]
        if d == 0:
            @pl.when((i == lo_i // bx) & (flo == 1))
            def _(vlo=vlo, r=lo_i % bx):
                o_vmem[r:r + 1] = vlo[...][None]

            @pl.when((i == hi_i // bx) & (fhi == 1))
            def _(vhi=vhi, r=hi_i % bx):
                o_vmem[r:r + 1] = vhi[...][None]
        elif d == 1:
            @pl.when(flo == 1)
            def _(vlo=vlo, p=lo_i):
                o_vmem[:, p:p + 1, :] = vlo[pl.ds(i * bx, bx)][:, None, :]

            @pl.when(fhi == 1)
            def _(vhi=vhi, p=hi_i):
                o_vmem[:, p:p + 1, :] = vhi[pl.ds(i * bx, bx)][:, None, :]
        else:
            @pl.when(flo == 1)
            def _(vlo=vlo, p=lo_i):
                o_vmem[:, :, p:p + 1] = vlo[pl.ds(i * bx, bx)][:, :, None]

            @pl.when(fhi == 1)
            def _(vhi=vhi, p=hi_i):
                o_vmem[:, :, p:p + 1] = vhi[pl.ds(i * bx, bx)][:, :, None]

    # Async write-back.  Final step: the central window goes to the real
    # output; shoulder programs park their slab in the (otherwise unused)
    # next ping-pong buffer so every program starts exactly one out-DMA and
    # the semaphore accounting stays statically balanced.
    # All puts are FULL slabs: every semaphore wait above assumes the
    # full-slab byte count, so a narrower (y-windowed) final DMA would
    # unbalance the accounting and hang the chip.  In y-extended mode the
    # output therefore carries the extended y span and the caller slices
    # the central window in XLA.
    central = (i >= off) & (i < off + nbo)

    def put(dst, at):
        pltpu.make_async_copy(o_vmem, dst.at[at], osems.at[sl]).start()

    @pl.when((k == K - 1) & central)
    def _():
        put(out_ref, pl.ds((i - off) * bx, bx))

    # Shoulder slabs park in the would-be ping-pong TARGET of this step
    # (buf0 for even k, buf1 for odd) — the other buffer is this step's
    # SOURCE, still being read by neighboring programs.
    @pl.when((k == K - 1) & ~central)
    def _():
        put(buf0 if (K - 1) % 2 == 0 else buf1, pl.ds(i * bx, bx))

    @pl.when((k < K - 1) & (k % 2 == 0))
    def _():
        put(buf0, pl.ds(i * bx, bx))

    @pl.when((k < K - 1) & (k % 2 == 1))
    def _():
        put(buf1, pl.ds(i * bx, bx))

    # Final drain: the last two out DMAs have no successor to wait them.
    @pl.when((k == K - 1) & (i == nbe - 1))
    def _():
        pltpu.make_async_copy(o2.at[1 - sl], o2.at[1 - sl],
                              osems.at[1 - sl]).wait()
        pltpu.make_async_copy(o2.at[sl], o2.at[sl], osems.at[sl]).wait()


def _window_steps_xla(Text, A_ext, *, K, modes, grid, rdx2, rdy2, rdz2):
    """Pure-XLA realization of the chunk kernel's per-step update (interior
    x rows; y/z wrap or extended) — the interpret-mode fallback so CPU
    meshes and the driver dryrun exercise the SAME chunked-exchange
    /shrinking-validity structure the TPU kernel runs (the kernel itself is
    manual-DMA and has no interpret mode).  This realization additionally
    carries the open-boundary modes (`_dim_modes`): after each step, open
    global-edge devices re-freeze their boundary slab from the chunk-entry
    buffer — the no-write halo semantics — which both preserves the
    reference's frozen boundary rows bit-for-bit and quarantines the
    garbage in the beyond-domain shoulder rows (a frozen row is never
    recomputed, so nothing beyond it is ever read by a valid row).  The
    wrap/freeze primitives are the engine's
    (`chunk_engine.wrap_edges`/`freeze_open_dim`)."""
    from jax import lax

    F = Text   # chunk-entry values: the freeze source for open edges

    def step(_, U):
        S1e, S2 = U.shape[1], U.shape[2]
        U = U.at[1:-1, 1:-1, 1:-1].set(
            _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1],
                    rdx2=rdx2, rdy2=rdy2, rdz2=rdz2))
        if modes[1] == "wrap":
            U = wrap_edges(U, 1, S1e, 2)
        if modes[2] == "wrap":
            U = wrap_edges(U, 2, S2, 2)
        for d in range(3):
            Sd = U.shape[d]
            if modes[d] in ("frozen", "oext"):
                lo = K if modes[d] == "oext" else 0
                hi = Sd - 1 - K if modes[d] == "oext" else Sd - 1
                U = freeze_open_dim(U, F, d, modes[d], lo, hi, grid)
        return U

    return lax.fori_loop(0, K, step, Text)


def _chunk_call(Text, A_ext, out_shape3, *, K, bx, modes, grid,
                rdx2, rdy2, rdz2, interpret=False):
    """Advance K steps on the extended buffer; returns the central
    `out_shape3` window."""
    import jax
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S0e, S1e, S2e = Text.shape
    S0, S1o, S2o = out_shape3
    if interpret:
        out = _window_steps_xla(Text, A_ext, K=K, modes=modes, grid=grid,
                                rdx2=rdx2, rdy2=rdy2, rdz2=rdz2)
        return central_window(out, out_shape3, K, modes)
    import jax.numpy as jnp

    extended = [m in ("ext", "oext") for m in modes]
    y_ext, z_ext = extended[1], extended[2]
    if z_ext and S2e % 128 != 0:
        # Mosaic requires 128-aligned VMEM lane slices; right-pad the
        # extended lane extent with zeros.  The garbage lanes lie beyond
        # the +K extension: their invalidity front reaches exactly lane
        # K+S2o after K steps, never entering the central window.
        S2p = ((S2e + 127) // 128) * 128
        pad = [(0, 0), (0, 0), (0, S2p - S2e)]
        Text = jnp.pad(Text, pad)
        A_ext = jnp.pad(A_ext, pad)
        S2e = S2p
    assert K == bx, "chunk depth is pinned to the block row count"
    nbe = S0e // bx
    nbo = S0 // bx
    off = 1 if modes[0] != "frozen" else 0   # = extension rows // bx

    # Open-dim freeze config: (dim, lo, hi) boundary-plane indices in the
    # (logical) extended buffer — `out + K` offsets for "oext", the buffer
    # ends for "frozen" — plus the squeezed chunk-entry freeze planes and
    # the per-device SMEM edge flags (frozen dims statically flag both
    # sides: one device IS both global edges, and no `axis_index` is
    # traced, so 1-device frozen grids still run under plain `jax.jit`).
    frz = tuple((d, (K if modes[d] == "oext" else 0),
                 out_shape3[d] + (K if modes[d] == "oext" else 0) - 1)
                for d in range(3) if modes[d] in ("oext", "frozen"))
    fr_planes = []
    flag_ops = []
    if frz:
        for d, lo, hi in frz:
            for idx in (lo, hi):
                fr_planes.append(jnp.squeeze(
                    lax.slice_in_dim(Text, idx, idx + 1, axis=d), d))
        flag_ops = [_edge_flags(modes, grid)]

    kern = partial(_kernel, K=K, bx=bx, nbe=nbe, nbo=nbo, off=off,
                   S0e=S0e, S1e=S1e, S2=S2e, modes=tuple(modes), frz=frz,
                   rdx2=rdx2, rdy2=rdy2, rdz2=rdz2)

    operands = [Text, A_ext, *flag_ops, *fr_planes]
    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(s):
        return (jax.ShapeDtypeStruct(s, Text.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(s, Text.dtype))

    fr_scratch = [pltpu.VMEM(p.shape, Text.dtype) for p in fr_planes]
    if frz:
        fr_scratch.append(pltpu.SemaphoreType.DMA((len(fr_planes),)))
    out, _, _ = pl.pallas_call(
        kern,
        grid=(K, nbe),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)]
        + [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(flag_ops)
        + [pl.BlockSpec(memory_space=pl.ANY)] * len(fr_planes),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_shape=[shp((S0, S1e, S2e)), shp(Text.shape), shp(Text.shape)],
        # Text is dead after the k=0 reads (the freeze planes are their
        # own buffers); buf1 (first written at k=1) reuses its buffer.
        input_output_aliases={0: 2},
        scratch_shapes=[
            pltpu.VMEM(Text.shape, Text.dtype),             # a_vmem
            pltpu.VMEM((2, bx + 2, S1e, S2e), Text.dtype),  # ext2
            pltpu.VMEM((2, bx, S1e, S2e), Text.dtype),      # o2
            pltpu.SemaphoreType.DMA((2,)),                  # esems
            pltpu.SemaphoreType.DMA((2,)),                  # osems
            pltpu.SemaphoreType.DMA,                        # asem
        ] + fr_scratch,                                     # fr_vmem, fsems
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary", "arbitrary")),
    )(*operands)
    if y_ext:
        # Central y window (tile-aligned K offset: a cheap slab slice).
        out = lax.slice_in_dim(out, K, K + S1o, axis=1)
    if z_ext:
        # Central z window (lane-dim slice, one relayout pass per chunk —
        # amortized 1/K per step).
        out = lax.slice_in_dim(out, K, K + S2o, axis=2)
    return out


def fused_diffusion_trapezoid_steps(T, A, *, n_inner: int, bx: int,
                                    grid, rdx2, rdy2, rdz2,
                                    force_y_ext=None, force_z_ext=None,
                                    interpret=False):
    """Advance `n_inner` steps in chunks of K=bx trapezoidal kernel calls
    (plus a per-step remainder handled by the caller; this function runs
    only the `n_inner // bx` full chunks and returns `(T, steps_done)`).
    `force_y_ext`/`force_z_ext` override the mesh-derived modes
    (benchmarking the `(N,M,K)` program shapes on a 1-device self-torus)."""
    K = bx
    shape = T.shape
    modes = _dim_modes(grid, force_y_ext, force_z_ext)
    A_ext = _extend(A, K, grid, shape, modes)   # loop-invariant

    def one(T):
        Text = _extend(T, K, grid, shape, modes)
        return (_chunk_call(Text, A_ext, shape, K=K, bx=bx, modes=modes,
                            grid=grid, rdx2=rdx2, rdy2=rdy2, rdz2=rdz2,
                            interpret=interpret),)

    T, done = run_chunks((T,), n_inner=n_inner, K=K, one_chunk=one)
    return T, done


# ---------------------------------------------------------------------------
# The STREAMING banded tier (diffusion3d.banded): rolling-window
# realization for the shapes the resident kernels' K-bound refuses
# ---------------------------------------------------------------------------

def _banded_update(Wt, Wa, *, bx, rdx2, rdy2, rdz2):
    """New band values (rows [a, a+bx), window row offset 1) from
    margin-1 windows of T and the coefficient — the per-step kernel's
    assembly: interior cells take `_u_rows`, y/z edge rows keep their
    old values (owned by the band-halo wrap/freeze).  Pure values: the
    engine's streaming kernel and the banded XLA realization share it."""
    import jax.numpy as jnp

    o = Wt[1:1 + bx]
    inner = _u_rows(Wt[0:bx], o, Wt[2:2 + bx], Wa[1:1 + bx],
                    rdx2, rdy2, rdz2)
    mid = jnp.concatenate([o[:, 1:-1, 0:1], inner, o[:, 1:-1, -1:]],
                          axis=2)
    return (jnp.concatenate([o[:, 0:1, :], mid, o[:, -1:, :]], axis=1),)


def diffusion_banded_supported(grid, shape, K: int, n_inner: int, dtype,
                               B: int = 8, interpret: bool = False):
    """Whether the STREAMING banded diffusion chunk tier applies at
    depth K / band B: the trapezoid tier's structural gates minus the
    resident K-bound — the rolling window (T plus the streamed
    coefficient, margin 1) is O(B), so this rung admits at the 256^3
    headline shape where `trapezoid_supported`'s resident accounting
    refuses.  Open dims freeze T's boundary planes (`freeze_fields =
    (0,)` — the coefficient is never written).  Returns an
    :class:`igg.degrade.Admission`."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if tuple(shape) != tuple(grid.nxyz):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz)}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = _dim_modes(grid)
    E = K
    shapes = [tuple(shape), tuple(shape)]
    ols = field_ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    geo = admit_banded_geometry(shapes, E, modes, B=B, extras=(1, 1),
                                interpret=interpret)
    if geo is not None:
        return geo
    exts = [ext_shape(s, E, modes) for s in shapes]
    need = banded_vmem(exts, B, (1, 1), 1, modes=modes,
                       freeze_fields=(0,))
    if need > chunk_budget():
        return Admission.no(f"banded window set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_diffusion_band(grid, shape, n_inner: int, dtype,
                       interpret: bool = False, kmax: int = 8,
                       bands=(8, 16)):
    """Largest admissible `(K, B)` for the banded tier
    (`_vmem.fit_banded`); None when none applies."""
    return fit_banded(
        lambda K, B: diffusion_banded_supported(grid, tuple(shape), K,
                                                n_inner, dtype, B=B,
                                                interpret=interpret),
        kmax, bands=bands)


def fused_diffusion_banded_steps(T, A, *, n_inner: int, K: int, B: int,
                                 grid, rdx2, rdy2, rdz2,
                                 interpret: bool = False):
    """Advance `n_inner // K` full K-step chunks through the STREAMING
    banded realization (`chunk_engine.streaming_chunk_call`: rolling
    VMEM window of band depth B, HBM ping-pong, the coefficient's
    extended buffer streamed per band instead of held resident);
    returns `(T, steps_done)`.  Same entry contract as
    :func:`fused_diffusion_trapezoid_steps` (the caller runs the warm-up
    step and the per-K remainder through the per-step path)."""
    modes = _dim_modes(grid)
    E = K
    shapes = [T.shape, T.shape]
    ols = field_ols(grid, shapes)
    A_ext = extend_fields([A], [ols[1]], E, grid, modes)[0]  # invariant

    def one(T):
        Text = extend_fields([T], ols[:1], E, grid, modes)
        return streaming_chunk_call(
            list(Text), [A_ext], K=K, B=B, modes=modes, grid=grid,
            ols=ols, shapes=shapes, E=E,
            band_update=partial(_banded_update, rdx2=rdx2, rdy2=rdy2,
                                rdz2=rdz2),
            extras=(1, 1), freeze_fields=(0,), interpret=interpret)

    T, done = run_chunks((T,), n_inner=n_inner, K=K, one_chunk=one)
    return T, done
