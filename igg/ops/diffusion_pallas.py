"""Fused Pallas diffusion step (single-device, fully-periodic grid).

One kernel performs what the XLA path expresses as ~10 separate HBM-bound
fusions (flux/Laplacian temporaries, interior dynamic-update-slice, six halo
plane updates): read T and Cp once, write T once.

Correctness model.  With overlap 2, a fully-periodic single-device grid, and
the reference's step structure (interior update, then halo exchange dimension
by dimension — `/root/reference/src/update_halo.jl:36`), the post-step array
satisfies `T_new[i,j,k] = U[m(i), m(j), m(k)]` where `U` is the interior
stencil update and `m` maps each halo index to its aliased interior index
(`m(0) = s-2`, `m(s-1) = 1`, identity otherwise), applied per dimension
independently — the sequential x→y→z exchange is exactly what makes the
per-dimension composition valid (corner/edge propagation,
`/root/reference/src/update_halo.jl:130`).  The kernel computes `U` for its
x-slab and assembles the y/z halo planes from `U` in VMEM; the two x halo
planes are copied by a tiny epilogue (they are whole-plane aliases of updated
interior planes).

Blocking: the grid runs over x-slabs of `bx` rows; each program reads its
slab, one periodic-neighbor plane on each side (single-plane BlockSpecs with
modular index maps — the in-kernel analog of the halo exchange), and the Cp
slab.  HBM traffic per step: `T * (1 + 2/bx) + Cp + T_out`.
"""

from __future__ import annotations

from functools import partial


def pallas_supported(grid, T) -> bool:
    """Whether the fused kernel applies: single device, fully periodic,
    overlap 2, 3-D unstaggered field, x divisible into slabs."""
    if grid.nprocs != 1 or any(p == 0 for p in grid.periods):
        return False
    if grid.overlaps != (2, 2, 2) or T.ndim != 3:
        return False
    if tuple(grid.local_shape_any(T)) != tuple(grid.nxyz):
        return False
    return T.shape[0] % 4 == 0 and T.shape[1] >= 8 and T.shape[2] >= 128


def _kernel(c_ref, p_ref, n_ref, cp_ref, o_ref, *, rdx2, rdy2, rdz2, dt_lam,
            bx):
    import jax.numpy as jnp

    # Extended slab: [prev plane; slab; next plane] — one temporary, sliced
    # for all three axes' neighbors.
    ext = jnp.concatenate([p_ref[:], c_ref[:], n_ref[:]], axis=0)
    ctr = ext[1:bx + 1, 1:-1, 1:-1]
    lap = ((ext[2:bx + 2, 1:-1, 1:-1] + ext[0:bx, 1:-1, 1:-1]) * rdx2
           + (ext[1:bx + 1, 2:, 1:-1] + ext[1:bx + 1, :-2, 1:-1]) * rdy2
           + (ext[1:bx + 1, 1:-1, 2:] + ext[1:bx + 1, 1:-1, :-2]) * rdz2
           - 2.0 * (rdx2 + rdy2 + rdz2) * ctr)
    U = ctr + dt_lam / cp_ref[:, 1:-1, 1:-1] * lap

    # Assemble the y then z halo planes from U (periodic aliases of updated
    # interior planes; order mirrors the reference's sequential dims).
    Uy = jnp.concatenate([U[:, -1:, :], U, U[:, :1, :]], axis=1)
    Uz = jnp.concatenate([Uy[:, :, -1:], Uy, Uy[:, :, :1]], axis=2)
    o_ref[:] = Uz


def fused_diffusion_step(T, Cp, *, dx, dy, dz, dt, lam, bx: int = 4,
                         interpret: bool = False):
    """One diffusion step `(T, Cp) -> T_new`, halo maintenance included.
    Must run under `jax.jit` (library call sites always do)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    S0, S1, S2 = T.shape
    if S0 % bx != 0:
        raise ValueError(f"x size {S0} not divisible by slab size {bx}")
    nb = S0 // bx

    # Plain Python floats: baked into the kernel as compile-time constants.
    kern = partial(_kernel, rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                   rdz2=1.0 / (dz * dz), dt_lam=float(dt * lam), bx=bx)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(T.shape, T.dtype),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, S1, S2), lambda i: ((i * bx - 1) % S0, 0, 0)),
            pl.BlockSpec((1, S1, S2), lambda i: ((i * bx + bx) % S0, 0, 0)),
            pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
        interpret=interpret,
        **kwargs,
    )(T, T, T, Cp)

    # x halo planes: whole-plane aliases of updated interior planes
    # (recv plane 0 <- plane s-2, plane s-1 <- plane 1;
    #  `/root/reference/src/update_halo.jl:386-405` with ol=2, self-wrap).
    out = out.at[0].set(out[S0 - 2])
    out = out.at[S0 - 1].set(out[1])
    return out
