"""Fused Pallas diffusion step (single-device, fully-periodic grid).

One kernel performs what the XLA path expresses as ~10 separate HBM-bound
fusions (flux/Laplacian temporaries, interior dynamic-update-slice, six halo
plane updates): read T and Cp once, write T once.

Correctness model.  With overlap 2, a fully-periodic single-device grid, and
the reference's step structure (interior update, then halo exchange dimension
by dimension — `/root/reference/src/update_halo.jl:36`), the post-step array
satisfies `T_new[i,j,k] = U[m(i), m(j), m(k)]` where `U` is the interior
stencil update and `m` maps each halo index to its aliased interior index
(`m(0) = s-2`, `m(s-1) = 1`, identity otherwise), applied per dimension
independently — the sequential x→y→z exchange is exactly what makes the
per-dimension composition valid (corner/edge propagation,
`/root/reference/src/update_halo.jl:130`).  The kernel computes `U` for its
x-slab and assembles the y/z halo planes from `U` in VMEM.  The two x halo
planes (`T_new[0] = U[s-2]·wrap`, `T_new[s-1] = U[1]·wrap`) are computed
*outside* the kernel from 3-plane slices (O(s²) work) and written into the
first/last programs' output blocks under `pl.when` — NOT patched in with a
`dynamic_update_slice` epilogue, which would make XLA materialize a full-array
copy per patched plane (the same conservative copy-insertion the halo engine
works around, see `igg/halo.py::assemble_planes`).

Blocking: the grid runs over x-slabs of `bx` rows; each program reads its
slab, one periodic-neighbor plane on each side (single-plane BlockSpecs with
modular index maps — the in-kernel analog of the halo exchange), and the Cp
slab.  HBM traffic per step: `T * (1 + 2/bx) + Cp + T_out`.
"""

from __future__ import annotations

from functools import partial


def pallas_supported(grid, T) -> bool:
    """Whether the fused kernel applies: single device, fully periodic,
    overlap 2, 3-D unstaggered field, x divisible into slabs."""
    if grid.nprocs != 1 or any(p == 0 for p in grid.periods):
        return False
    if grid.overlaps != (2, 2, 2) or T.ndim != 3:
        return False
    if tuple(grid.local_shape_any(T)) != tuple(grid.nxyz):
        return False
    return T.shape[0] % 4 == 0 and T.shape[1] >= 8 and T.shape[2] >= 128


def _wrap_yz(U):
    """Append the periodic y/z halo rows/columns of an interior-updated slab
    (aliases of updated interior planes; order mirrors the reference's
    sequential dims)."""
    import jax.numpy as jnp

    U = jnp.concatenate([U[:, -1:, :], U, U[:, :1, :]], axis=1)
    return jnp.concatenate([U[:, :, -1:], U, U[:, :, :1]], axis=2)


def _kernel(c_ref, p_ref, n_ref, cp_ref, first_ref, last_ref, o_ref, *,
            rdx2, rdy2, rdz2, dt_lam, bx, nb):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    # Extended slab: [prev plane; slab; next plane] — one temporary, sliced
    # for all three axes' neighbors.
    ext = jnp.concatenate([p_ref[:], c_ref[:], n_ref[:]], axis=0)
    ctr = ext[1:bx + 1, 1:-1, 1:-1]
    lap = ((ext[2:bx + 2, 1:-1, 1:-1] + ext[0:bx, 1:-1, 1:-1]) * rdx2
           + (ext[1:bx + 1, 2:, 1:-1] + ext[1:bx + 1, :-2, 1:-1]) * rdy2
           + (ext[1:bx + 1, 1:-1, 2:] + ext[1:bx + 1, 1:-1, :-2]) * rdz2
           - 2.0 * (rdx2 + rdy2 + rdz2) * ctr)
    U = ctr + dt_lam / cp_ref[:, 1:-1, 1:-1] * lap
    o_ref[:] = _wrap_yz(U)

    # x halo planes (whole-plane aliases of updated interior planes,
    # `/root/reference/src/update_halo.jl:386-405` with ol=2, self-wrap):
    # precomputed outside, written by the edge programs only.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[0:1] = first_ref[:]

    @pl.when(i == nb - 1)
    def _():
        o_ref[bx - 1:bx] = last_ref[:]


def _plane_update(Tm1, T0, Tp1, Cp0, *, rdx2, rdy2, rdz2, dt_lam):
    """Interior stencil update of one x-plane (`(S1, S2)` arrays), y/z halo
    wrap included — the O(s²) host-side computation of `U[1]` and `U[s-2]`."""
    ctr = T0[1:-1, 1:-1]
    lap = ((Tp1[1:-1, 1:-1] + Tm1[1:-1, 1:-1]) * rdx2
           + (T0[2:, 1:-1] + T0[:-2, 1:-1]) * rdy2
           + (T0[1:-1, 2:] + T0[1:-1, :-2]) * rdz2
           - 2.0 * (rdx2 + rdy2 + rdz2) * ctr)
    U = ctr + dt_lam / Cp0[1:-1, 1:-1] * lap
    return _wrap_yz(U[None])[0]


def fused_diffusion_step(T, Cp, *, dx, dy, dz, dt, lam, bx: int = 16,
                         interpret: bool = False):
    """One diffusion step `(T, Cp) -> T_new`, halo maintenance included.
    Must run under `jax.jit` (library call sites always do)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    S0, S1, S2 = T.shape
    if bx < 1 or (bx & (bx - 1)) != 0:
        raise ValueError(f"bx must be a positive power of two, got {bx}")
    while S0 % bx != 0:
        bx //= 2  # halving a power of two >= 1 always reaches a divisor (1)
    nb = S0 // bx

    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz), dt_lam=float(dt * lam))

    # T_new[0] = U[s-2] (y/z-wrapped), T_new[s-1] = U[1]: from 3-plane slices,
    # purely functional (no in-place patching of the kernel output).
    first = _plane_update(T[S0 - 3], T[S0 - 2], T[S0 - 1], Cp[S0 - 2], **scal)
    last = _plane_update(T[0], T[1], T[2], Cp[1], **scal)

    kern = partial(_kernel, bx=bx, nb=nb, **scal)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)
    plane = pl.BlockSpec((1, S1, S2), lambda i: (0, 0, 0))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(T.shape, T.dtype),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, S1, S2), lambda i: ((i * bx - 1) % S0, 0, 0)),
            pl.BlockSpec((1, S1, S2), lambda i: ((i * bx + bx) % S0, 0, 0)),
            pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
            plane,
            plane,
        ],
        out_specs=pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
        interpret=interpret,
        **kwargs,
    )(T, T, T, Cp, first[None], last[None])
