"""Fused Pallas diffusion step — mesh-capable (any dims / periodicity).

One kernel performs what the XLA path expresses as ~10 separate HBM-bound
fusions (flux/Laplacian temporaries, interior dynamic-update-slice, six halo
plane updates): read T and Cp once, write T once.

Structure (the TPU-native re-expression of the reference's device-kernel
layer, `/root/reference/src/update_halo.jl:439-486`, combined with
ParallelStencil's `@hide_communication` overlap trade,
`/root/reference/README.md:9`):

1. **Send planes from thin-slab recomputation** — the inner boundary planes
   `ol-1` / `s-ol` of the *updated* field
   (`/root/reference/src/update_halo.jl:386-394`) are produced by radius-1
   stencil applications on 3-plane slabs, O(s²) work independent of the main
   kernel (the :func:`igg.hide_communication` recipe).
2. **Dimension-sequential plane exchange** — `igg.halo.exchange_all_dims`,
   the same engine the XLA path uses: ppermute per mesh axis, corner/edge
   propagation by patching pending planes, open-boundary no-write via stale
   planes, self-wrap local copies when a periodic dimension has one device
   (`/root/reference/src/update_halo.jl:36,130,516-532`).
3. **Fused compute + assembly kernel** — each program reads its x-slab of T
   (plus one neighbor plane each side, modular index maps), computes the
   interior update, and writes the output block with the *received* halo
   planes assembled in dimension order (x plane first, y rows, then z
   columns winning the shared corners — the in-VMEM equivalent of
   `igg.halo.assemble_planes`).  HBM traffic per step:
   `T*(1 + 2/bx) + Cp + T_out` + O(s²) plane traffic.

**Slab carry (the multi-step fast path).**  Slicing 3-plane y/z slabs out of
the big array costs far more than their size on TPU — a minor-dim slice
still transfers whole (8,128) tiles, ~an eighth of the array for y and the
*entire* array for z.  :func:`fused_diffusion_steps` therefore carries the
four y/z boundary slabs of the field as separate compact arrays through the
time loop: the kernel emits them as extra outputs (copies of its assembled
output block's edge slabs, a few MB of dense writes), and the next
iteration's send planes are computed from the carried slabs without touching
the big array.  Cp's slabs are loop-invariant and sliced once.

**Compact minor-dim representation (round 3).**  Halo planes travel as
*squeezed* dense 2-D arrays (see `igg.halo`), and the carried z slabs are
stored **transposed** — `(S0, 3, S1)` with z on the sublane axis — because a
`(S0, S1, 3)` array is lane-padded to 128 on TPU (~42x its logical HBM
footprint, per-step I/O measured at ~40x logical size in round 2).  The
kernel emits the transposed slabs directly (an in-kernel lane extraction per
plane), and their send planes are produced by applying the axis-symmetric
stencil with swapped y/z coefficients, which yields the squeezed z plane
`(S0, S1)` with no further transposition.  No lane-padded array of any kind
touches HBM on this path.

Because the send planes are recomputed rather than sliced from the kernel
output, the exchange is data-independent of the main kernel; semantics match
:func:`igg.hide_communication` exactly (identical to the plain sequential
composition on periodic/interior ranks; at open-boundary edge ranks the
physically-meaningless halo cells keep pre-step values).  On a sharded mesh
this is the fused analog of running the XLA path with `overlap=True`.

**Path selection in** :func:`fused_diffusion_steps` (fastest applicable
wins): the K-step mega-kernel (`diffusion_mega`, 1-device grids, every
dim self-wrap or frozen, 0.24 ms/step at 256^3) > K-step trapezoidal
chunks (`diffusion_trapezoid`, exchanged rings/tori of ANY per-dim
periodicity — periodic dims self-wrapped or extended, open dims extended
with per-device edge freezing (the reference-default boundary condition)
— 0.29 ms/step on the `(N,1,1)` pod decomposition, 0.40 on `(N,M,1)`
with both dims extended; one K-deep slab ppermute pair per exchanged dim
per K steps) > the per-step kernel above (any mesh, 0.52 ms/step;
`benchmarks/results/pallas_sweep.jsonl`).
"""

from __future__ import annotations

from functools import partial


def pallas_supported(grid, T):
    """Whether the fused kernel applies: 3-D unstaggered f32-shaped field
    with overlap 2 in every dimension, local block large enough to slab
    (any device count and any periodicity — the exchange engine handles
    open boundaries and multi-device meshes).  Returns an
    :class:`igg.degrade.Admission` (truthy/falsy) carrying the structured
    refusal reason."""
    from ..degrade import Admission

    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if T.ndim != 3:
        return Admission.no(f"field rank {T.ndim} != 3")
    s = tuple(grid.local_shape_any(T))
    if s != tuple(grid.nxyz):
        return Admission.no(f"staggered local shape {s} != grid block "
                            f"{tuple(grid.nxyz)}")
    if s[0] % 4 != 0:
        return Admission.no(f"local x extent {s[0]} not a multiple of 4")
    if s[1] < 8 or s[2] < 128:
        return Admission.no(f"local block {s} too small to slab "
                            f"(needs y >= 8, z >= 128)")
    return Admission.yes()


def diffusion_compute(T, A, *, rdx2, rdy2, rdz2):
    """The pure stencil update on an arbitrary 3-D block: conservative
    7-point-Laplacian interior update, boundary planes keep their stale
    values (the reference's no-write semantics; physics of
    `/root/reference/docs/examples/diffusion3D_multigpu_CuArrays_novis.jl:41-48`,
    flux divergence re-associated — see `igg.models.diffusion3d.compute_step`).
    Shift-invariant and radius-1, so it applies equally to full local blocks
    and to the 3-plane slabs that produce send planes."""
    lap = ((T[2:, 1:-1, 1:-1] + T[:-2, 1:-1, 1:-1]) * rdx2
           + (T[1:-1, 2:, 1:-1] + T[1:-1, :-2, 1:-1]) * rdy2
           + (T[1:-1, 1:-1, 2:] + T[1:-1, 1:-1, :-2]) * rdz2
           - 2.0 * (rdx2 + rdy2 + rdz2) * T[1:-1, 1:-1, 1:-1])
    from .stencil import interior_add

    return interior_add(T, A[1:-1, 1:-1, 1:-1] * lap)


def _u_rows(Tm, T0, Tp, A0, rdx2, rdy2, rdz2):
    # (k,S1,S2) row bands; Tm/Tp are the x-neighbors of T0's rows; A0 is the
    # precomputed dt*lam/Cp coefficient band.
    ctr = T0[:, 1:-1, 1:-1]
    lap = ((Tp[:, 1:-1, 1:-1] + Tm[:, 1:-1, 1:-1]) * rdx2
           + (T0[:, 2:, 1:-1] + T0[:, :-2, 1:-1]) * rdy2
           + (T0[:, 1:-1, 2:] + T0[:, 1:-1, :-2]) * rdz2
           - 2.0 * (rdx2 + rdy2 + rdz2) * ctr)
    return ctr + A0[:, 1:-1, 1:-1] * lap


def _ref_taker(refs):
    """Positional consumer for variadic kernel refs: `take = _ref_taker(refs);
    a, b = take(2)` — shared by the fused kernels' argument unpacking."""
    state = {"pos": 0}

    def take(n):
        out = refs[state["pos"]:state["pos"] + n]
        state["pos"] += n
        return out

    return take


def _make_kernel(wrap_y: bool, wrap_z: bool, scal, bx: int, nb: int):
    """Kernel factory: one x-slab program with per-dimension halo modes.

    Assembly order realizes the reference's sequential-dimension semantics
    (`/root/reference/src/update_halo.jl:36,130`): x halo planes first, then
    y rows, then z columns — later dimensions own the shared corner/edge
    cells, exactly like `igg.halo.assemble_planes`.  Per dimension:

      - x: always plane inputs (they cross program boundaries) — received
        planes on multi-device x, the self-swapped send planes on a single
        periodic device;
      - y/z `wrap` mode (single periodic device along the dim — the
        reference's self-neighbor path,
        `/root/reference/src/update_halo.jl:516-532`): the halo is an
        in-VMEM alias of the updated inner plane.  No (S0,S1,1)-shaped
        z-plane arrays — whose minor-dim lane padding makes their HBM I/O
        cost ~40x their logical size — ever touch HBM, which is why 1-D/2-D
        decompositions `(N,1,1)`/`(N,M,1)` are the recommended meshes;
      - y/z `recv` mode: exchanged planes arrive as blocked inputs.

    No extended-slab concatenate: the update is written in three x-row
    bands whose outer rows take their x-neighbor from the single-plane
    `p`/`n` refs.

    Alias precision on periodic dims: wrap-mode halos are in-VMEM copies of
    their aliased interiors (bitwise equal); x halo planes are computed by
    XLA outside the kernel while their aliased interiors are computed by
    Mosaic inside, so `T_new[0] == T_new[S0-2]` holds to 1 ulp, not bitwise
    (measured max diff 1.5e-8 f32 on v5e; `tests/test_alias_invariant.py`).
    """
    from jax.experimental import pallas as pl

    def kernel(*refs):
        it = iter(refs)
        c_ref, p_ref, n_ref, a_ref = next(it), next(it), next(it), next(it)
        rxf_ref, rxl_ref = next(it), next(it)
        ryf_ref = ryl_ref = rzf_ref = rzl_ref = None
        if not wrap_y:
            ryf_ref, ryl_ref = next(it), next(it)
        if not wrap_z:
            rzf_ref, rzl_ref = next(it), next(it)
        o_ref = next(it)
        oy_lo_ref = oy_hi_ref = oz_lo_ref = oz_hi_ref = None
        if not wrap_y:
            oy_lo_ref, oy_hi_ref = next(it), next(it)
        if not wrap_z:
            oz_lo_ref, oz_hi_ref = next(it), next(it)

        import jax.numpy as jnp

        S1, S2 = c_ref.shape[1], c_ref.shape[2]
        c = c_ref[:]
        a = a_ref[:]
        if bx > 2:
            o_ref[1:bx - 1, 1:-1, 1:-1] = _u_rows(
                c[0:bx - 2], c[1:bx - 1], c[2:bx], a[1:bx - 1], *scal)
        o_ref[0:1, 1:-1, 1:-1] = _u_rows(p_ref[:], c[0:1], c[1:2],
                                         a[0:1], *scal)
        o_ref[bx - 1:bx, 1:-1, 1:-1] = _u_rows(
            c[bx - 2:bx - 1], c[bx - 1:bx], n_ref[:], a[bx - 1:bx], *scal)

        i = pl.program_id(0)

        # x halo planes (squeezed (S1,S2) inputs; interior region only —
        # their y/z edge cells are owned by the later y/z writes below).
        @pl.when(i == 0)
        def _():
            o_ref[0:1, 1:-1, 1:-1] = rxf_ref[1:-1, 1:-1][None]

        @pl.when(i == nb - 1)
        def _():
            o_ref[bx - 1:bx, 1:-1, 1:-1] = rxl_ref[1:-1, 1:-1][None]

        # y halo rows (full x extent; z edges overwritten below).
        if wrap_y:
            o_ref[:, 0:1, 1:-1] = o_ref[:, S1 - 2:S1 - 1, 1:-1]
            o_ref[:, S1 - 1:S1, 1:-1] = o_ref[:, 1:2, 1:-1]
        else:
            o_ref[:, 0:1, 1:-1] = jnp.expand_dims(ryf_ref[:, 1:-1], 1)
            o_ref[:, S1 - 1:S1, 1:-1] = jnp.expand_dims(ryl_ref[:, 1:-1], 1)
        # z halo columns (own all shared corners).  The squeezed (bx,S1)
        # plane is transposed onto the sublane axis in-register.
        if wrap_z:
            o_ref[:, :, 0:1] = o_ref[:, :, S2 - 2:S2 - 1]
            o_ref[:, :, S2 - 1:S2] = o_ref[:, :, 1:2]
        else:
            o_ref[:, :, 0:1] = jnp.expand_dims(rzf_ref[:], 2)
            o_ref[:, :, S2 - 1:S2] = jnp.expand_dims(rzl_ref[:], 2)

        # Boundary slabs of the assembled output for the recv-mode dims,
        # emitted compactly (consumed by the slab-carry loop); wrap dims
        # need no slabs.  z slabs are emitted TRANSPOSED (bx,3,S1) — the
        # natural (bx,S1,3) form would be lane-padded ~42x in HBM.
        if not wrap_y:
            oy_lo_ref[:] = o_ref[:, 0:3, :]
            oy_hi_ref[:] = o_ref[:, S1 - 3:S1, :]
        if not wrap_z:
            for j in range(3):
                oz_lo_ref[:, j, :] = o_ref[:, :, j]
                oz_hi_ref[:, j, :] = o_ref[:, :, S2 - 3 + j]

    return kernel


def _check_applicable(grid, s, bx):
    from ..halo import active_dims

    if bx < 2 or (bx & (bx - 1)) != 0:
        raise ValueError(f"bx must be a power of two >= 2, got {bx}")
    S0 = s[0]
    while S0 % bx != 0:
        bx //= 2  # halving reaches a divisor; S0 % 4 == 0 keeps bx >= 2
    if bx < 2:
        raise ValueError(f"x size {S0} not divisible into slabs of >= 2 rows")
    dims_active = active_dims(s, grid)
    if [d for d, _ in dims_active] != [0, 1, 2]:
        raise ValueError(
            f"fused kernel requires a halo in all three dimensions; active: "
            f"{dims_active}")
    return bx, dims_active


def _wrap_set(wrap_yz):
    """Dim indices handled by in-kernel wrap, for `exchange_all_dims`."""
    return {d for d, w in zip((1, 2), wrap_yz) if w}


def _wrap_dims(grid):
    """Per-dimension halo modes for y/z: `wrap` when the dim is periodic
    with a single device (the self-neighbor path handled in-VMEM).  x always
    goes through the plane exchange — its planes cross program boundaries
    anyway, and they are dense and cheap."""
    return tuple(grid.dims[d] == 1 and bool(grid.periods[d])
                 for d in (1, 2))


def _call_kernel(T, A, recv, scal, bx, interpret, wrap_yz):
    """pallas_call plumbing: returns `(out, *slabs)` where `slabs` are the
    boundary-slab outputs of the recv-mode dims only, in (y_lo, y_hi,
    z_lo, z_hi) order — wrap dims emit none.  The engine's keepdims recv
    planes are squeezed at this boundary (dense 2-D kernel operands; for
    wire-materialized planes the expand/squeeze pair cancels)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    s = T.shape
    S0, S1, S2 = s
    nb = S0 // bx
    wy, wz = wrap_yz
    recv = {d: (jnp.squeeze(a, d), jnp.squeeze(b, d))
            for d, (a, b) in recv.items()}
    rxf, rxl = recv[0]

    scal_t = (scal["rdx2"], scal["rdy2"], scal["rdz2"])
    kern = _make_kernel(wy, wz, scal_t, bx, nb)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)

    plane_x = pl.BlockSpec((S1, S2), lambda i: (0, 0))
    operands = [T, T, T, A, rxf, rxl]
    in_specs = [
        pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, S1, S2), lambda i: ((i * bx - 1) % S0, 0, 0)),
        pl.BlockSpec((1, S1, S2), lambda i: ((i * bx + bx) % S0, 0, 0)),
        pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0)),
        plane_x,
        plane_x,
    ]
    if not wy:
        operands += list(recv[1])
        in_specs += [pl.BlockSpec((bx, S2), lambda i: (i, 0))] * 2
    if not wz:
        operands += list(recv[2])
        in_specs += [pl.BlockSpec((bx, S1), lambda i: (i, 0))] * 2

    # Under shard_map with varying-mesh-axes checking, out_shapes must carry
    # which axes the results vary over: the union of the operands'.
    vmas = [getattr(getattr(x, "aval", None), "vma", None) for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(*dims):
        return (jax.ShapeDtypeStruct(dims, T.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(dims, T.dtype))

    out_shape = [shp(S0, S1, S2)]
    out_specs = [pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0))]
    if not wy:
        out_shape += [shp(S0, 3, S2)] * 2
        out_specs += [pl.BlockSpec((bx, 3, S2), lambda i: (i, 0, 0))] * 2
    if not wz:
        out_shape += [shp(S0, 3, S1)] * 2   # transposed z slabs
        out_specs += [pl.BlockSpec((bx, 3, S1), lambda i: (i, 0, 0))] * 2
    return pl.pallas_call(
        kern,
        out_shape=tuple(out_shape),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=interpret,
        **kwargs,
    )(*operands)


def _scal(dx, dy, dz):
    return dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))


def _self_wrap_all(grid) -> bool:
    """All dims periodic with a single device: the reference's single-process
    fully-periodic configuration, where every exchange is the self-neighbor
    path (`/root/reference/src/update_halo.jl:516-532`)."""
    return (tuple(grid.dims) == (1, 1, 1)
            and all(bool(p) for p in grid.periods))


def _single_device_modes(grid):
    """Per-dim mega-kernel halo modes for a 1-device grid ("wrap" periodic
    self-neighbor / "frozen" open no-write — `diffusion_mega` docstring),
    or None when any dimension is split across devices."""
    if tuple(grid.dims) != (1, 1, 1):
        return None
    return tuple("wrap" if grid.periods[d] else "frozen" for d in range(3))


def _sends_and_stale(T, a_slabs, slabs, scal, wrap_yz):
    """Squeezed send planes (updated inner planes `ol-1`/`s-ol`) from compact
    boundary slabs, plus stale (outermost) planes for open-boundary dims — no
    reads of the big array beyond its two cheap contiguous x-end slabs.
    Wrapped y/z dims need neither sends nor slabs.

    z slabs arrive TRANSPOSED (S0,3,S1): the stencil is axis-symmetric, so
    applying it with swapped y/z coefficients produces the transposed update,
    whose middle plane is exactly the squeezed z send plane (S0,S1)."""
    from jax import lax

    s = T.shape
    wy, wz = wrap_yz
    ys_lo, ys_hi, zt_lo, zt_hi = slabs
    ax_lo, ax_hi, ay_lo, ay_hi, azt_lo, azt_hi = a_slabs
    xs_lo = lax.slice_in_dim(T, 0, 3, axis=0)          # contiguous: cheap
    xs_hi = lax.slice_in_dim(T, s[0] - 3, s[0], axis=0)

    # Keepdims planes for the exchange engine (squeezed only on the wire /
    # at the kernel boundary — see `igg.halo`); the lazy expand/squeeze
    # pairs are metadata reshapes that cancel.
    import jax.numpy as jnp

    send = {
        (0, 0): diffusion_compute(xs_lo, ax_lo, **scal)[1:2],
        (0, 1): diffusion_compute(xs_hi, ax_hi, **scal)[1:2],
    }
    stale = {(0, 0): xs_lo[0:1], (0, 1): xs_hi[2:3]}
    if not wy:
        send[(1, 0)] = diffusion_compute(ys_lo, ay_lo, **scal)[:, 1:2, :]
        send[(1, 1)] = diffusion_compute(ys_hi, ay_hi, **scal)[:, 1:2, :]
        stale[(1, 0)] = ys_lo[:, 0:1, :]
        stale[(1, 1)] = ys_hi[:, 2:3, :]
    if not wz:
        swapped = dict(rdx2=scal["rdx2"], rdy2=scal["rdz2"],
                       rdz2=scal["rdy2"])
        send[(2, 0)] = jnp.expand_dims(
            diffusion_compute(zt_lo, azt_lo, **swapped)[:, 1, :], 2)
        send[(2, 1)] = jnp.expand_dims(
            diffusion_compute(zt_hi, azt_hi, **swapped)[:, 1, :], 2)
        stale[(2, 0)] = jnp.expand_dims(zt_lo[:, 0, :], 2)
        stale[(2, 1)] = jnp.expand_dims(zt_hi[:, 2, :], 2)
    return send, stale


def _boundary_slabs(A, wrap_yz):
    """The y/z 3-plane boundary slabs of a block for the recv-mode dims
    (one-time strided extraction; thereafter the kernel re-emits them
    compactly, z TRANSPOSED to (S0,3,S1) to stay dense); `None` placeholders
    for wrapped dims — the expensive minor-dim slices are skipped entirely
    there."""
    import jax.numpy as jnp
    from jax import lax

    s = A.shape
    wy, wz = wrap_yz
    ys = (None, None) if wy else (
        lax.slice_in_dim(A, 0, 3, axis=1),
        lax.slice_in_dim(A, s[1] - 3, s[1], axis=1))
    zs = (None, None) if wz else (
        jnp.swapaxes(lax.slice_in_dim(A, 0, 3, axis=2), 1, 2),
        jnp.swapaxes(lax.slice_in_dim(A, s[2] - 3, s[2], axis=2), 1, 2))
    return (*ys, *zs)


def _coef_slabs(A, wrap_yz):
    from jax import lax

    s = A.shape
    return (lax.slice_in_dim(A, 0, 3, axis=0),
            lax.slice_in_dim(A, s[0] - 3, s[0], axis=0),
            *_boundary_slabs(A, wrap_yz))


def fused_diffusion_step(T, Cp, *, dx, dy, dz, dt, lam, bx: int = 16,
                         interpret: bool = False):
    """One diffusion step `(T, Cp) -> T_new` on a per-device *local* block,
    halo maintenance included.  Call inside SPMD code (`igg.sharded` /
    shard_map) like :func:`igg.update_halo_local`; on a 1-device grid the
    exchange degenerates to local copies and the function also works under
    plain `jax.jit`.  For time loops use :func:`fused_diffusion_steps`,
    which avoids the per-step strided slab extraction this entry pays."""
    from ..halo import exchange_all_dims
    from .. import shared

    grid = shared.global_grid()
    bx, dims_active = _check_applicable(grid, T.shape, bx)
    scal = _scal(dx, dy, dz)
    A = float(dt * lam) / Cp   # loop-invariant coefficient (no in-loop divide)
    wrap_yz = _wrap_dims(grid)
    send, stale = _sends_and_stale(T, _coef_slabs(A, wrap_yz),
                                   _boundary_slabs(T, wrap_yz), scal,
                                   wrap_yz)
    recv = exchange_all_dims(T, send, dims_active, grid, stale=stale,
                             wrap=_wrap_set(wrap_yz))
    return _call_kernel(T, A, recv, scal, bx, interpret, wrap_yz)[0]


def fused_diffusion_steps(T, Cp, *, n_inner, dx, dy, dz, dt, lam,
                          bx: int = 16, interpret: bool = False):
    """`n_inner` fused diffusion steps with boundary-slab carry (see module
    docstring): the y/z slabs feeding each step's send planes are emitted by
    the previous step's kernel, so the steady-state HBM traffic per step is
    `T*(1 + 2/bx) + Cp + T_out` + a few MB of compact slab I/O.  Wrapped y/z
    dims (single periodic device) skip sends, slabs, and carry entirely.
    Call inside SPMD code; returns the advanced block."""
    from jax import lax

    from ..halo import exchange_all_dims
    from .. import shared

    grid = shared.global_grid()
    bx, dims_active = _check_applicable(grid, T.shape, bx)
    scal = _scal(dx, dy, dz)
    A = float(dt * lam) / Cp   # loop-invariant coefficient (no in-loop divide)
    wrap_yz = _wrap_dims(grid)

    modes = _single_device_modes(grid)
    if modes is not None:
        from .diffusion_mega import fused_diffusion_megasteps, mega_supported

        # Fastest: the whole inner loop as ONE pallas_call, coefficient
        # VMEM-resident when it fits and slab-streamed otherwise; open
        # dims run the frozen-edge mode (see `diffusion_mega` — this is
        # the reference's published 510^3 open-boundary headline path).
        if mega_supported(T.shape, bx, n_inner, interpret, dtype=T.dtype):
            return fused_diffusion_megasteps(T, A, n_inner=n_inner, bx=bx,
                                             **scal, modes=modes)

    # Exchanged meshes — (N,1,1)/(N,M,1)/(N,M,K) rings and tori with any
    # per-dim periodicity (periodic dims self-wrapped or extended, OPEN
    # dims extended with per-device edge freezing — the reference-default
    # boundary condition, round 6): K-step trapezoidal chunks, one K-deep
    # slab ppermute pair per exchanged dim per K steps, the loop fused
    # in-kernel (see `diffusion_trapezoid`).  One per-step kernel step
    # runs FIRST: it consumes (and replaces) whatever is in the entry
    # halo rows exactly like every other path, establishing the
    # exchange-fresh window state the trapezoid's validity argument
    # requires — so this path is bit-equivalent to the per-step path for
    # ANY input, including never-exchanged arrays.  Remainder steps fall
    # through to the per-step loop below.
    from .diffusion_trapezoid import (fused_diffusion_trapezoid_steps,
                                      trapezoid_supported)
    if trapezoid_supported(grid, T.shape, bx, n_inner - 1, T.dtype,
                           allow_open=True):
        T = fused_diffusion_step(T, Cp, dx=dx, dy=dy, dz=dz, dt=dt,
                                 lam=lam, bx=bx, interpret=interpret)
        n_inner -= 1
        T, done = fused_diffusion_trapezoid_steps(
            T, A, n_inner=n_inner, bx=bx, grid=grid, interpret=interpret,
            **scal)
        n_inner -= done
        if n_inner == 0:
            return T

    a_slabs = _coef_slabs(A, wrap_yz)  # loop-invariant: sliced once
    init_slabs = _boundary_slabs(T, wrap_yz)
    keep = [j for j, sl in enumerate(init_slabs) if sl is not None]

    def body(_, carry):
        T = carry[0]
        slabs = [None] * 4
        for pos, val in zip(keep, carry[1:]):
            slabs[pos] = val
        send, stale = _sends_and_stale(T, a_slabs, slabs, scal, wrap_yz)
        recv = exchange_all_dims(T, send, dims_active, grid, stale=stale,
                                 wrap=_wrap_set(wrap_yz))
        # _call_kernel returns (out, *slabs-in-keep-order)
        return _call_kernel(T, A, recv, scal, bx, interpret, wrap_yz)

    out = lax.fori_loop(0, n_inner, body,
                        (T, *(init_slabs[j] for j in keep)))
    return out[0]
