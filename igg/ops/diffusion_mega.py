"""K-step fused diffusion mega-kernel (single-device grids).

One `pallas_call` advances the ENTIRE inner time loop: grid `(K, nb)` with
sequential ("arbitrary") semantics, manual HBM<->VMEM DMA, and three
structural wins over one-kernel-per-step:

  1. **VMEM-resident coefficient** — `A = dt*lam/Cp` is DMA'd into a VMEM
     scratch once and read from on-chip memory for all K steps, removing a
     full-array HBM read per step (custom-call boundaries otherwise force
     every operand back to HBM each step).  When A does not fit (local
     blocks beyond ~300^3 f32 — the 512^3 headline case), the kernel
     STREAMS it instead: per-program A slabs ride the same double-buffered
     prefetch pipeline as the T slabs (round 5).  The streaming trade is
     +A bytes of HBM read per step — unavoidable at that size, and
     exactly what the per-step kernel pays too, while keeping wins 2/3.
  2. **HBM ping-pong** — T alternates between two HBM scratch buffers
     (extra ANY-space outputs); no XLA-level copy between steps.
  3. **Hand double-buffering** — each program consumes an extended x-slab
     prefetched by its predecessor and writes its output slab back
     asynchronously, with statically-balanced semaphore waits (every DMA
     start is paired with exactly one wait: slot reuse two programs later,
     plus a drain at each step boundary so the ping-pong source is fully
     written before it is read, plus a final drain).

Halo maintenance is per-dimension (round 5 generalized the original
all-self-wrap form to the open single-device modes, so the reference's
published headline workload — open boundaries — runs here too):

  - ``"wrap"`` (periodic single device, the reference's self-neighbor
    path `/root/reference/src/update_halo.jl:516-532`): y/z halos are
    VMEM aliases of the updated interior; the two x halo planes are
    computed by the first program of each step from 3-plane x-end slabs
    of the current source buffer.
  - ``"frozen"`` (open single device, the reference's no-write halo
    semantics `/root/reference/test/test_update_halo.jl:727-732`): halo
    rows are copied through from the step's SOURCE buffer — frozen rows
    never change, and the copy-through reproduces the per-step path's
    leave-them-alone behavior bit-for-bit at zero extra HBM traffic (the
    source rows are already in the fetched slabs).

Measured on TPU v5e at 256^3 f32 (K=100, bx=8): **0.237 ms/step**, audited
round 3 by three agreeing methods — dispatch-slope at K=100 (0.241), at
K=200 (0.239), and the pure device-side slope in K ((t_K200 - t_K100)/100 =
0.2366, immune to dispatch/readback artifacts).  Against the ACTUAL
per-step HBM traffic `T*(1+2/bx) + T_out + A/K` ≈ 151 MB that is 638 GB/s,
**78% of the chip's 819 GB/s HBM peak**.  The "~850 GB/s" figure sometimes
quoted is the *equivalent ideal-fusion throughput* (what a kernel touching
only `read T + Cp, write T` would need) — a speedup proxy, NOT a physical
bandwidth, and it exceeds peak precisely because the mega-kernel eliminates
the Cp read.  A round-2 record of 0.177 ms/step was a timing artifact of
small slope batches under the tunnel's readback jitter and is superseded.
Matches the per-step kernel path to 1 ulp.

Not available in interpret mode (manual TPU DMA/semaphores); callers fall
back to the per-step kernel.
"""

from __future__ import annotations

from functools import partial

# VMEM headroom for the resident coefficient + double buffers (the v5e has
# 128MB; leave slack for Mosaic's own allocations).
_VMEM_BUDGET = 110 * 1024 * 1024


def _working_vmem(shape, bx, itemsize, resident: bool) -> int:
    S0, S1, S2 = shape
    return itemsize * (
        (S0 * S1 * S2 if resident else (2 * bx + 2) * S1 * S2)  # A
        + 2 * (bx + 2) * S1 * S2   # ext slabs (double-buffered)
        + 2 * bx * S1 * S2         # out slabs (double-buffered)
        + 8 * S1 * S2)             # x-plane scratch


def resident_a_fits(shape, bx: int, dtype) -> bool:
    """Whether the coefficient array can stay VMEM-resident for the whole
    loop (the fastest mode; ~<=300^3 f32 locals)."""
    import numpy as np

    return (_working_vmem(shape, bx, np.dtype(dtype).itemsize, True)
            <= _VMEM_BUDGET)


def mega_supported(shape, bx: int, n_inner: int, interpret: bool,
                   dtype) -> bool:
    """Whether the K-step mega-kernel applies to a local block of `shape`:
    compiled mode only, at least two steps (with one step, the donated
    input buffer doubles as the output and the last program's wrapping
    fetch would read a row already overwritten), and the working buffers —
    sized at the ACTUAL element width, with the coefficient resident when
    it fits and streamed otherwise — must fit in VMEM (a hard-coded 4
    would under-estimate wider dtypes and fail at Mosaic compile time
    instead of falling back to the per-step kernel)."""
    import numpy as np

    if interpret or n_inner < 2:
        return False
    S0, S1, S2 = shape
    if S0 % bx != 0:  # nb = S0 // bx must cover every row
        return False
    if S0 < 2 * bx:  # the wrapping edge fetches assume >= 2 slabs per step
        return False
    if S2 % 128 != 0 or S1 % 8 != 0:
        # Mosaic requires tile-aligned VMEM memref slices: the double-
        # buffered scratch (2, ..., S1, S2) is sliced on its leading dim,
        # which needs the trailing (sublane, lane) extents tile-aligned.
        return False
    itemsize = np.dtype(dtype).itemsize
    return _working_vmem(shape, bx, itemsize, False) <= _VMEM_BUDGET


# Shared with the per-step kernel: the 1-ulp equality contract between the
# two paths (tests/test_mega_tpu.py) depends on literally the same stencil.
from .diffusion_pallas import _u_rows  # noqa: E402


def _kernel(T_hbm, A_hbm, out_ref, buf0, buf1, *scratch,
            K, bx, nb, S0, S1, S2, rdx2, rdy2, rdz2, resident, modes):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    it = iter(scratch)
    if resident:
        a_vmem = next(it)
        a2 = asems2 = axr = axsem = None
    else:
        a_vmem = None
        a2, asems2, axr, axsem = next(it), next(it), next(it), next(it)
    ext2, o2, xfl, esems, osems, xsems = (next(it) for _ in range(6))
    asem = next(it) if resident else None

    k = pl.program_id(0)
    i = pl.program_id(1)
    scal = (rdx2, rdy2, rdz2)
    sl = i % 2              # this program's ext/out slot

    if resident:
        # One-time: coefficient array into VMEM.
        @pl.when((k == 0) & (i == 0))
        def _():
            dma = pltpu.make_async_copy(A_hbm, a_vmem, asem)
            dma.start()
            dma.wait()
    else:
        # Streamed coefficient: per-program A slabs on the same
        # edge-sync/interior-prefetch pipeline as the T slabs below
        # (A is step-invariant but the 2-slot buffer forces a re-fetch
        # every step — the documented streaming trade).
        @pl.when((i == 0) | (i == nb - 1))
        def _():
            c = pltpu.make_async_copy(A_hbm.at[pl.ds(i * bx, bx)],
                                      a2.at[sl], asems2.at[sl])
            c.start()
            c.wait()

        # Prefetch the NEXT program's A slab — targets slabs 1..nb-2 only
        # (edge programs fetch their own synchronously above), the same
        # window convention as the ext-slab pipeline's `prefetch_next`.
        @pl.when((i >= 0) & (i <= nb - 3))
        def _():
            pltpu.make_async_copy(A_hbm.at[pl.ds((i + 1) * bx, bx)],
                                  a2.at[1 - sl], asems2.at[1 - sl]).start()

        @pl.when((i > 0) & (i < nb - 1))
        def _():
            pltpu.make_async_copy(a2.at[sl], a2.at[sl],
                                  asems2.at[sl]).wait()

        if modes[0] == "wrap":
            # The wrap-x halo planes need A rows S0-2 and 1 (fetched once:
            # A never changes).
            @pl.when((k == 0) & (i == 0))
            def _():
                c0 = pltpu.make_async_copy(A_hbm.at[S0 - 2:S0 - 1],
                                           axr.at[0:1], axsem.at[0])
                c1 = pltpu.make_async_copy(A_hbm.at[1:2], axr.at[1:2],
                                           axsem.at[1])
                c0.start(); c1.start(); c0.wait(); c1.wait()

    # Out-write bookkeeping: drain everything outstanding at each step
    # boundary (the ping-pong source must be fully written before any read
    # of step k), and otherwise wait the DMA whose slot this program reuses.
    @pl.when((i == 0) & (k > 0))
    def _():
        pltpu.make_async_copy(o2.at[0], o2.at[0], osems.at[0]).wait()
        pltpu.make_async_copy(o2.at[1], o2.at[1], osems.at[1]).wait()

    @pl.when(i >= 2)
    def _():
        pltpu.make_async_copy(o2.at[sl], o2.at[sl], osems.at[sl]).wait()

    # Extended-slab fetches (rows [i*bx-1, i*bx+bx+1) mod S0).  Edge
    # programs fetch their own wrapping segments synchronously; interior
    # programs consume the prefetch issued by their predecessor and issue
    # the next one.
    def sync_fetch(src):
        @pl.when(i == 0)
        def _():
            c0 = pltpu.make_async_copy(src.at[S0 - 1:S0],
                                       ext2.at[sl, 0:1], esems.at[sl])
            c1 = pltpu.make_async_copy(src.at[0:bx + 1],
                                       ext2.at[sl, 1:bx + 2],
                                       esems.at[1 - sl])
            c0.start(); c1.start(); c0.wait(); c1.wait()

        @pl.when(i == nb - 1)
        def _():
            c0 = pltpu.make_async_copy(src.at[S0 - bx - 1:S0],
                                       ext2.at[sl, 0:bx + 1], esems.at[sl])
            c1 = pltpu.make_async_copy(src.at[0:1],
                                       ext2.at[sl, bx + 1:bx + 2],
                                       esems.at[1 - sl])
            c0.start(); c1.start(); c0.wait(); c1.wait()

    def prefetch_next(src):
        # Prefetch the NEXT program's slab — targets slabs 1..nb-2 only
        # (edge programs fetch their own wrapping segments synchronously).
        @pl.when((i >= 0) & (i <= nb - 3))
        def _():
            pltpu.make_async_copy(
                src.at[pl.ds((i + 1) * bx - 1, bx + 2)],
                ext2.at[1 - sl], esems.at[1 - sl]).start()

    def fetch_xplanes(src):
        # Dedicated semaphores: these waits must not consume the prefetch
        # signal pending on esems for the next program.
        c0 = pltpu.make_async_copy(src.at[S0 - 3:S0], xfl.at[0:3],
                                   xsems.at[0])
        c1 = pltpu.make_async_copy(src.at[0:3], xfl.at[3:6], xsems.at[1])
        c0.start(); c1.start(); c0.wait(); c1.wait()

    for cond, src in ((k == 0, T_hbm),
                      ((k > 0) & (k % 2 == 1), buf0),
                      ((k > 0) & (k % 2 == 0), buf1)):
        @pl.when(cond)
        def _(src=src):
            sync_fetch(src)

            @pl.when(i == 0)
            def _():
                fetch_xplanes(src)
            prefetch_next(src)

    # Interior programs: wait for the prefetched slab.
    @pl.when((i > 0) & (i < nb - 1))
    def _():
        pltpu.make_async_copy(ext2.at[sl], ext2.at[sl], esems.at[sl]).wait()

    # x halo planes of this step, computed once per step.  Wrap mode:
    # T_new[0] = U[S0-2], T_new[S0-1] = U[1] stenciled from the x-end
    # slabs; frozen mode: the source edge rows pass through verbatim (a
    # fully-frozen row keeps every cell — even its wrap-dim halo cells
    # only ever copy values from within the same frozen row).  Edge cells
    # of computed planes follow the y/z modes, frozen edges sourced from
    # the plane's own center source row.
    @pl.when(i == 0)
    def _():
        def ywrap_col(col):
            # A frozen-z column with its wrap-y halo cells re-wrapped (the
            # engine's self-alias corner patch: edge row 0 <- inner row
            # S1-2, edge row S1-1 <- inner row 1).
            return jnp.concatenate([col[:, S1 - 2:S1 - 1, :],
                                    col[:, 1:S1 - 1, :],
                                    col[:, 1:2, :]], axis=1)

        def edge_yz(U, src):
            # U: (1, S1-2, S2-2) the new interior of an x-halo row; `src`:
            # (1, S1, S2) the source row the plane's frozen-dim edge cells
            # carry (the engine's corner patching delivers the x-SOURCE
            # row's values there: for a wrap-x plane the stencil center
            # row, for a frozen-x plane the row itself).  Wrap edges copy
            # from the new row's own interior; frozen-z corner cells under
            # wrap-y additionally re-wrap (the y self-alias patch runs
            # after the x patch on the pending z plane).
            if modes[1] == "wrap":
                U = jnp.concatenate([U[:, -1:, :], U, U[:, :1, :]], axis=1)
            else:
                U = jnp.concatenate([src[:, 0:1, 1:-1], U,
                                     src[:, S1 - 1:S1, 1:-1]], axis=1)
            if modes[2] == "wrap":
                return jnp.concatenate([U[:, :, -1:], U, U[:, :, :1]],
                                       axis=2)
            zlo = src[:, :, 0:1]
            zhi = src[:, :, S2 - 1:S2]
            if modes[1] == "wrap":
                zlo, zhi = ywrap_col(zlo), ywrap_col(zhi)
            return jnp.concatenate([zlo, U, zhi], axis=2)

        hi = xfl[0:3]
        lo = xfl[3:6]
        if modes[0] == "wrap":
            aS = a_vmem[S0 - 2:S0 - 1] if resident else axr[0:1]
            a1 = a_vmem[1:2] if resident else axr[1:2]
            xfl[6:7] = edge_yz(_u_rows(hi[0:1], hi[1:2], hi[2:3], aS,
                                       *scal), hi[1:2])
            xfl[7:8] = edge_yz(_u_rows(lo[0:1], lo[1:2], lo[2:3], a1,
                                       *scal), lo[1:2])
        else:
            # Frozen x: the source edge rows pass through, with their OWN
            # wrap-dim halo cells re-wrapped (the per-step path's wrap
            # writes copy within the frozen row; a no-op once the state is
            # exchange-fresh, but exact for any input).
            xfl[6:7] = edge_yz(lo[0:1, 1:-1, 1:-1], lo[0:1])
            xfl[7:8] = edge_yz(hi[2:3, 1:-1, 1:-1], hi[2:3])

    # Interior stencil update in x-row bands + per-mode y/z assembly
    # (identical scheme to diffusion_pallas._make_kernel).
    ext = ext2.at[sl]
    o_vmem = o2.at[sl]
    c = ext[1:bx + 1]
    a = a_vmem[pl.ds(i * bx, bx)] if resident else a2[sl]
    if bx > 2:
        o_vmem[1:bx - 1, 1:-1, 1:-1] = _u_rows(
            c[0:bx - 2], c[1:bx - 1], c[2:bx], a[1:bx - 1], *scal)
    o_vmem[0:1, 1:-1, 1:-1] = _u_rows(ext[0:1], c[0:1], c[1:2],
                                      a[0:1], *scal)
    o_vmem[bx - 1:bx, 1:-1, 1:-1] = _u_rows(
        c[bx - 2:bx - 1], c[bx - 1:bx], ext[bx + 1:bx + 2],
        a[bx - 1:bx], *scal)
    if modes[1] == "wrap":
        o_vmem[:, 0:1, 1:-1] = o_vmem[:, S1 - 2:S1 - 1, 1:-1]
        o_vmem[:, S1 - 1:S1, 1:-1] = o_vmem[:, 1:2, 1:-1]
    else:
        o_vmem[:, 0:1, 1:-1] = c[:, 0:1, 1:-1]
        o_vmem[:, S1 - 1:S1, 1:-1] = c[:, S1 - 1:S1, 1:-1]
    if modes[2] == "wrap":
        o_vmem[:, :, 0:1] = o_vmem[:, :, S2 - 2:S2 - 1]
        o_vmem[:, :, S2 - 1:S2] = o_vmem[:, :, 1:2]
    else:
        o_vmem[:, :, 0:1] = c[:, :, 0:1]
        o_vmem[:, :, S2 - 1:S2] = c[:, :, S2 - 1:S2]
        if modes[1] == "wrap":
            # Corner cells of the frozen z columns under wrap-y: the
            # engine's y self-alias patch rewraps the pending z plane's
            # y-edge rows (edge 0 <- inner S1-2, edge S1-1 <- inner 1).
            for zc in (slice(0, 1), slice(S2 - 1, S2)):
                o_vmem[:, 0:1, zc] = c[:, S1 - 2:S1 - 1, zc]
                o_vmem[:, S1 - 1:S1, zc] = c[:, 1:2, zc]

    @pl.when(i == 0)
    def _():
        o_vmem[0:1] = xfl[6:7]

    @pl.when(i == nb - 1)
    def _():
        o_vmem[bx - 1:bx] = xfl[7:8]

    # Async write-back to this step's destination.
    def put(dst):
        pltpu.make_async_copy(o_vmem, dst.at[pl.ds(i * bx, bx)],
                              osems.at[sl]).start()

    @pl.when(k == K - 1)
    def _():
        put(out_ref)

    @pl.when((k < K - 1) & (k % 2 == 0))
    def _():
        put(buf0)

    @pl.when((k < K - 1) & (k % 2 == 1))
    def _():
        put(buf1)

    # Final drain: the last two out DMAs have no successor to wait them.
    @pl.when((k == K - 1) & (i == nb - 1))
    def _():
        pltpu.make_async_copy(o2.at[1 - sl], o2.at[1 - sl],
                              osems.at[1 - sl]).wait()
        pltpu.make_async_copy(o2.at[sl], o2.at[sl], osems.at[sl]).wait()


def fused_diffusion_megasteps(T, A, *, n_inner: int, bx: int,
                              rdx2, rdy2, rdz2,
                              modes=("wrap", "wrap", "wrap"),
                              force_streamed: bool = False):
    """Advance `n_inner` single-device diffusion steps in ONE pallas_call.
    `A = dt*lam/Cp`; `modes` gives each dimension's halo mode ("wrap" for
    a periodic self-neighbor ring, "frozen" for an open boundary — module
    docstring).  The coefficient stays VMEM-resident when it fits and is
    slab-streamed otherwise (`force_streamed` pins streaming, for the
    equivalence tests).  The input T buffer is donated to the result (the
    k=0 reads all happen before any write lands in it)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = T.shape
    S0, S1, S2 = s
    nb = S0 // bx
    resident = (not force_streamed) and resident_a_fits(s, bx, T.dtype)
    kern = partial(_kernel, K=n_inner, bx=bx, nb=nb, S0=S0, S1=S1, S2=S2,
                   rdx2=rdx2, rdy2=rdy2, rdz2=rdz2, resident=resident,
                   modes=tuple(modes))

    vmas = [getattr(getattr(x, "aval", None), "vma", None) for x in (T, A)]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp():
        return (jax.ShapeDtypeStruct(s, T.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(s, T.dtype))

    if resident:
        a_scratch = [pltpu.VMEM(s, T.dtype)]              # a_vmem
    else:
        a_scratch = [
            pltpu.VMEM((2, bx, S1, S2), T.dtype),         # a2
            pltpu.SemaphoreType.DMA((2,)),                # asems2
            pltpu.VMEM((2, S1, S2), T.dtype),             # axr
            pltpu.SemaphoreType.DMA((2,)),                # axsem
        ]
    out, _, _ = pl.pallas_call(
        kern,
        grid=(n_inner, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_shape=[shp(), shp(), shp()],
        input_output_aliases={0: 0},
        scratch_shapes=a_scratch + [
            pltpu.VMEM((2, bx + 2, S1, S2), T.dtype),     # ext2
            pltpu.VMEM((2, bx, S1, S2), T.dtype),         # o2
            pltpu.VMEM((8, S1, S2), T.dtype),             # xfl
            pltpu.SemaphoreType.DMA((2,)),                # esems
            pltpu.SemaphoreType.DMA((2,)),                # osems
            pltpu.SemaphoreType.DMA((2,)),                # xsems
        ] + ([pltpu.SemaphoreType.DMA] if resident else []),  # asem
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary", "arbitrary")),
    )(T, A)
    return out
