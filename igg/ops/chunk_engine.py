"""The shared K-step chunk engine — one implementation of the trapezoidal
temporal-blocking machinery every model family instantiates.

Rounds 4-7 built the K-step chunk tiers twice: `diffusion_trapezoid`
(single-field, HBM-streaming ping-pong kernel) and `stokes_trapezoid`
(four-field staggered, VMEM-resident banded kernel) each carried their own
copy of the halo-extension slab permutes, the freeze-plane gating, the
window/margin analysis, the K-remainder handling, and the VMEM fitting.
This module is the extraction: the family-independent machinery lives here
ONCE, parameterized by a family's field set, per-row read margin, and
band-update core — and the missing speed rungs (`hm3d_trapezoid`, the
wave2d chunk tier) are generated from it rather than hand-written a third
and fourth time.

What the engine owns:

- **Per-dimension window modes** (:func:`dim_modes`) and the per-device
  SMEM edge-flag vector (:func:`edge_flags`) — moved verbatim from
  `diffusion_trapezoid` (which re-exports them for compatibility).
- **The grouped K-deep slab extension** (:func:`extend_dim_grouped`,
  :func:`extend_fields`): dimension-sequential `ppermute` pairs with
  per-field staggered overlaps, same-shaped slabs stacked onto one wire,
  z slabs transpose-carried, open-edge no-write restoration — the
  superset of `diffusion_trapezoid._extend_dim` (a one-field group) and
  `stokes_trapezoid._extend_dim_grouped` (moved here).
- **Window-realization building blocks**: the staggered periodic
  self-wrap (:func:`wrap_edges`) and the open-dim freeze masks
  (:func:`freeze_open_dim`) both pure-XLA realizations apply per
  iteration, plus the generic per-iteration window loop
  (:func:`window_chunk_xla`) the NEW families' interpret realizations run
  on (the existing families keep their proven iteration orderings — the
  oracle for this refactor is bit-exactness against the per-step
  composition, pinned by the unchanged `tests/test_trapezoid.py` /
  `tests/test_stokes_trapezoid.py` matrices).
- **Admission scaffolding** (:func:`admit_chunk_common`,
  :func:`admit_send_slabs`): the structural gates every chunk tier shares
  — full-chunk count, `disp == 1` permute tables, K-deep send slabs
  inside every extended dimension's block per staggered field — returning
  structured :class:`igg.degrade.Admission` refusals.  The VMEM half of
  admission goes through the single budget authority in
  `igg/ops/_vmem.py` (`chunk_budget`, `fit_chunk_K`).
- **The chunk driver** (:func:`run_chunks`): `n_inner // K` fused chunks
  inside one `lax.fori_loop`, the K-remainder left to the caller's
  per-step path.
- **The generic VMEM-resident banded Mosaic kernel**
  (:func:`resident_chunk_call`): the compiled realization of
  `stokes_trapezoid._kernel`, generalized to any (updated fields, const
  fields, per-field high margins, freeze set, band-update core) — all
  fields VMEM-resident for the whole chunk, grid `(K, nb)`, in-place
  x-row bands with one-row lag carry, chunk-entry freeze planes gated by
  SMEM `axis_index` edge flags.  `stokes_trapezoid` instantiates it with
  its proven config (the TPU-gated
  `test_stokes_trapezoid_matches_per_iteration` is the hardware oracle);
  `hm3d_trapezoid` instantiates it fresh.  In interpret mode every
  instantiation falls to its pure-XLA window realization, so CPU meshes
  and the driver dryrun exercise the same chunked-exchange structure.

Families keep for themselves exactly what is family physics: the
band-update arithmetic (`iteration_core` / `step_core` / the wave2d
leapfrog), the VMEM footprint model, and any family-specific kernel
realization (the diffusion HBM-streaming ping-pong kernel stays in
`diffusion_trapezoid` — its memory scheme is unique to blocks that exceed
VMEM).
"""

from __future__ import annotations

from functools import partial

from ._vmem import chunk_budget, fit_chunk_K  # noqa: F401  (re-exported)


# ---------------------------------------------------------------------------
# Per-dimension window modes + edge flags (moved from diffusion_trapezoid)
# ---------------------------------------------------------------------------

def dim_modes(grid, force_y_ext=None, force_z_ext=None):
    """Per-dimension window mode for the chunk evolution:

      - ``"ext"``    periodic ring, K-extended by ppermute slabs (x is
                     always extended when periodic — on one device the
                     self-neighbor slabs are local wrap values);
      - ``"wrap"``   periodic single device, y/z in-buffer self-wrap;
      - ``"oext"``   open with >1 devices: extended like "ext" but with
                     non-wrapping permutes, and the GLOBAL-edge devices
                     re-freeze their boundary slab every step (the
                     reference's no-write halo semantics,
                     `/root/reference/test/test_update_halo.jl:727-732` —
                     a frozen boundary row is genuinely local, so the
                     validity front never shrinks from that side);
      - ``"frozen"`` open single device: no extension, both edge rows
                     re-frozen every step on every device.

    All chunk realizations implement the four modes; open dims must be
    admitted explicitly (`allow_open=True` on the family gates — the
    compiled dispatchers pass it)."""
    modes = []
    for d in range(3):
        if grid.periods[d]:
            modes.append("ext" if (d == 0 or grid.dims[d] > 1) else "wrap")
        else:
            modes.append("oext" if grid.dims[d] > 1 else "frozen")
    # The force flags benchmark the (N,M,K) program shapes on a 1-device
    # self-torus; they only rewire PERIODIC dims (ext <-> wrap) — an open
    # dim keeps its open mode so the compiled-path gates still reject it
    # (forcing 'ext' onto an open boundary would silently wrap it).
    if force_y_ext is not None and grid.periods[1]:
        modes[1] = "ext" if force_y_ext else "wrap"
    if force_z_ext is not None and grid.periods[2]:
        modes[2] = "ext" if force_z_ext else "wrap"
    return tuple(modes)


def edge_flags(modes, grid):
    """Per-device SMEM edge-flag vector shared by the chunk kernels: two
    i32 flags per dim — "frozen" dims statically flag both sides (one
    device IS both global edges, and no `axis_index` is traced, so
    1-device frozen grids still run under plain `jax.jit`), "oext" dims
    flag the global-edge devices via `axis_index`, periodic dims carry
    zeros."""
    import jax.numpy as jnp
    from jax import lax

    from ..shared import AXIS_NAMES

    flag_vals = []
    for d in range(3):
        if modes[d] == "frozen":
            flag_vals += [1, 1]
        elif modes[d] == "oext":
            ai = lax.axis_index(AXIS_NAMES[d])
            flag_vals += [(ai == 0).astype(jnp.int32),
                          (ai == grid.dims[d] - 1).astype(jnp.int32)]
        else:
            flag_vals += [0, 0]
    return jnp.stack([jnp.asarray(v, jnp.int32) for v in flag_vals])


# ---------------------------------------------------------------------------
# Staggered-field helpers
# ---------------------------------------------------------------------------

def field_ols(grid, shapes):
    """Per-field per-dim staggered overlaps (`ol(dim, A)`,
    `/root/reference/src/shared.jl:81`)."""
    return [tuple(grid.ol_of_local(d, s) for d in range(len(s)))
            for s in shapes]


def ext_shape(s, E, modes):
    """A field's extended-window shape: +2E along every extended dim."""
    return tuple(s[d] + (2 * E if modes[d] in ("ext", "oext") else 0)
                 for d in range(len(s)))


def wrap_edges(v, axis, size, ol):
    """Per-field staggered periodic self-wrap of the outermost planes
    along `axis`: edge 0 <- inner `size-ol`, edge `size-1` <- inner
    `ol-1` (`/root/reference/src/update_halo.jl:516-532`)."""
    import jax.numpy as jnp
    from jax import lax

    idx = lax.broadcasted_iota(jnp.int32, v.shape, axis)
    v = jnp.where(idx == 0,
                  lax.slice_in_dim(v, size - ol, size - ol + 1, axis=axis),
                  v)
    return jnp.where(idx == size - 1,
                     lax.slice_in_dim(v, ol - 1, ol, axis=axis), v)


def freeze_open_dim(U, F, d, mode, lo, hi, grid):
    """Open-dim freeze mask of the window realizations: ``"frozen"``
    re-freezes exactly the boundary planes `lo`/`hi` from the chunk-entry
    buffer `F` on every device; ``"oext"`` re-freezes the whole shoulder+
    boundary band (`idx <= lo` / `idx >= hi`) on the global-edge devices
    only (`axis_index` gated) — the no-write halo semantics, which both
    preserves the frozen rows bit-for-bit and quarantines the
    beyond-domain shoulder garbage."""
    import jax.numpy as jnp
    from jax import lax

    from ..shared import AXIS_NAMES

    idx = lax.broadcasted_iota(jnp.int32, U.shape, d)
    if mode == "frozen":
        return jnp.where((idx == lo) | (idx == hi), F, U)
    ai = lax.axis_index(AXIS_NAMES[d])
    n = grid.dims[d]
    U = jnp.where((ai == 0) & (idx <= lo), F, U)
    return jnp.where((ai == n - 1) & (idx >= hi), F, U)


# ---------------------------------------------------------------------------
# The grouped K-deep slab extension (moved from stokes_trapezoid)
# ---------------------------------------------------------------------------

def extend_dim_grouped(arrs, ols, E, grid, d, mode="ext"):
    """The `size + 2E` contiguous global window along dim `d` for a GROUP
    of fields with per-field staggered overlaps: E extension rows beyond
    each end PLUS neighbor-fresh values for each block's own halo rows,
    all from one ppermute pair of `(E+1)`-row slabs per shape group —
    same-shaped slabs are stacked and ride ONE ppermute per direction
    (the halo engine's grouped plane wire); a single field goes alone
    (the `diffusion_trapezoid._extend_dim` case).  z slabs of 3-D fields
    ride the wire TRANSPOSED (z on the sublane axis) so nothing
    lane-padded materializes.

    Replacing the local halo rows with the neighbors' send-position rows
    makes the window exchange-fresh at chunk entry — the invariant the
    trapezoidal validity argument needs.  When the entry halos are
    already fresh (any state produced by `update_halo`, a model step, or
    a previous chunk) the replacement is a bit-exact no-op."""
    import jax.numpy as jnp
    from jax import lax

    from ..shared import AXIS_NAMES

    n = grid.dims[d]
    axis = AXIS_NAMES[d]
    open_edges = mode == "oext"
    tw = d == 2 and arrs[0].ndim == 3   # transpose-carried lane-dim slabs

    slabs = []
    for A, ol in zip(arrs, ols):
        S = A.shape[d]
        left = lax.slice_in_dim(A, S - ol - E, S - ol + 1, axis=d)
        right = lax.slice_in_dim(A, ol - 1, ol + E, axis=d)
        if tw:
            left, right = (jnp.swapaxes(x, 1, 2) for x in (left, right))
        slabs.append([left, right])

    if n > 1:
        if open_edges:
            to_right = [(i, i + 1) for i in range(n - 1)]
            to_left = [(i, i - 1) for i in range(1, n)]
        else:
            to_right = [(i, (i + 1) % n) for i in range(n)]
            to_left = [(i, (i - 1) % n) for i in range(n)]
        groups = {}
        for j, (left, right) in enumerate(slabs):
            groups.setdefault(tuple(left.shape), []).append(j)
        for members in groups.values():
            for side, table in ((0, to_right), (1, to_left)):
                if len(members) == 1:
                    j = members[0]
                    slabs[j][side] = lax.ppermute(slabs[j][side], axis,
                                                  table)
                else:
                    stacked = jnp.stack([slabs[j][side] for j in members])
                    stacked = lax.ppermute(stacked, axis, table)
                    for k, j in enumerate(members):
                        slabs[j][side] = stacked[k]

    out = []
    for A, ol, (left, right) in zip(arrs, ols, slabs):
        if tw:
            left, right = (jnp.swapaxes(x, 1, 2) for x in (left, right))
        S = A.shape[d]
        Text = jnp.concatenate(
            [left, lax.slice_in_dim(A, 1, S - 1, axis=d), right], axis=d)
        if open_edges:
            # Global-edge devices received zeros: rows [0, E) / [Se-E, Se)
            # lie beyond the domain (garbage the step-level freeze
            # quarantines), but ext row E / Se-1-E replaced the block's
            # own boundary rows — restore their no-write (stale) values
            # there.
            idx = lax.axis_index(axis)
            Se = S + 2 * E
            fixed_l = lax.dynamic_update_slice_in_dim(
                Text, lax.slice_in_dim(A, 0, 1, axis=d), E, axis=d)
            Text = jnp.where(idx == 0, fixed_l, Text)
            fixed_r = lax.dynamic_update_slice_in_dim(
                Text, lax.slice_in_dim(A, S - 1, S, axis=d), Se - 1 - E,
                axis=d)
            Text = jnp.where(idx == n - 1, fixed_r, Text)
        out.append(Text)
    return out


def extend_fields(arrs, ols, E, grid, modes):
    """Dimension-sequential extension of a list of fields: x first, then
    the y extension OF the x-extended buffers, then z of the x/y-extended
    — corner and edge regions arrive via the later neighbors' own
    earlier-dim extensions (the halo engine's sequential-exchange corner
    trick).  wrap/frozen dims are not extended."""
    out = list(arrs)
    for d in range(arrs[0].ndim):
        if modes[d] in ("ext", "oext"):
            out = extend_dim_grouped(out, [ol[d] for ol in ols], E, grid,
                                     d, modes[d])
    return out


# ---------------------------------------------------------------------------
# Admission scaffolding (shared structural gates)
# ---------------------------------------------------------------------------

def admit_chunk_common(grid, K, n_inner):
    """The gates every chunk tier shares: at least one full K-chunk and
    unit-displacement permute tables.  Returns a falsy Admission carrying
    the refusal, or None when the common gates pass (the family gate
    continues)."""
    from ..degrade import Admission

    if K < 2 or n_inner < K:
        return Admission.no(f"n_inner={n_inner} holds no full K={K} chunk "
                            f"(needs n_inner >= K >= 2)")
    if getattr(grid, "disp", 1) != 1:
        # The chunked slab exchange hardwires +-1 ppermute tables.
        return Admission.no(f"grid disp {grid.disp} != 1 (chunk slab "
                            f"exchange hardwires +-1 ppermute tables)")
    return None


def admit_send_slabs(shapes, ols, E, modes, *, grid=None, min_ol: int = 2):
    """E-deep send slabs must lie inside every extended dimension's block
    for every (staggered) field, with overlap >= `min_ol` — AND, when
    the `grid` is supplied, stay out of the sender's SHARED region:
    neighbor blocks duplicate `ol` base rows (the inter-block shift is
    `S - ol`), so the rightward slab `[S - ol - E, S - ol + 1)` consists
    of sender-OWNED rows only when `E <= S - 2*ol` per base dimension.
    A deeper slab ships rows the sender itself merely mirrors — on small
    blocks (e.g. an 8-row overlap-3 dimension, 2 owned rows) those are
    not the global rows the receiver's extension window claims, and the
    chunk serves quietly wrong values (the round-21 stokes `(2, 2, 2)`
    small-block incident).  Returns a falsy Admission or None."""
    from ..degrade import Admission

    nd = len(shapes[0])
    for d in range(nd):
        if modes[d] not in ("ext", "oext"):
            continue
        if grid is not None:
            nb, olb = grid.nxyz[d], grid.overlaps[d]
            if E > nb - 2 * olb:
                return Admission.no(
                    f"E={E} dim-{d} send slabs enter the sender's shared "
                    f"region (base extent {nb}, ol {olb}: needs "
                    f"E <= {nb - 2 * olb})")
        for s, ol in zip(shapes, ols):
            if ol[d] < min_ol:
                return Admission.no(
                    f"dim-{d} overlap {ol[d]} < {min_ol} (field shape {s})")
            if s[d] - ol[d] - E < 0 or ol[d] + E > s[d]:
                return Admission.no(
                    f"E={E} dim-{d} send slabs fall outside a field block "
                    f"(shape {s}, ol {ol[d]})")
    return None


def admit_sublane_extension(E, modes, *, tile: int = 8):
    """The sublane-tile-extension gate every banded/resident chunk kernel
    shares: a y-extension that is not a whole number of sublane tiles
    shifts every leading-dim row slice (and the central y window) off the
    Mosaic `(8, 128)` tile grid — configurations Mosaic refuses DEEP in
    lowering (a GridError crash, the round-17 hm3d `(2, 2, 2)` incident)
    rather than at admission.  One structured gate, one place: returns a
    falsy :class:`igg.degrade.Admission` carrying the reason, or None
    when the geometry is tileable."""
    from ..degrade import Admission

    if len(modes) > 1 and modes[1] in ("ext", "oext") and E % tile != 0:
        return Admission.no(f"y-extension E={E} not on sublane tiles "
                            f"(E % {tile} != 0)")
    return None


def admit_banded_geometry(shapes, E, modes, *, B, extras, lo=1,
                          interpret=False):
    """Structural gates of the streaming banded realization (shared by
    every family's `*_banded_supported`): sublane-tiled band depth, a
    band-divisible extended x span with at least two bands (the
    ping-pong out-write pipeline drains slot pairs), read margins inside
    one band, and — compiled mode only — 3-D fields plus the shared
    sublane-extension geometry (the pure-XLA banded realization has no
    tile grid, so interpret meshes skip the Mosaic-only gates).  Returns
    a falsy Admission or None."""
    from ..degrade import Admission

    nd = len(shapes[0])
    ext_shapes = [ext_shape(s, E, modes) for s in shapes]
    base = min(s[0] for s in ext_shapes)
    if B < 8 or B % 8 != 0:
        return Admission.no(f"band depth B={B} not on sublane tiles "
                            f"(needs B % 8 == 0, B >= 8)")
    if base % B != 0:
        return Admission.no(f"extended x span {base} not band-divisible "
                            f"by B={B}")
    if base // B < 2:
        return Admission.no(f"extended x span {base} holds fewer than 2 "
                            f"bands of B={B} (the streaming out-write "
                            f"pipeline ping-pongs two slots)")
    if max(extras) + lo > B:
        return Admission.no(f"read margins lo={lo}/extras={tuple(extras)} "
                            f"exceed one band of B={B}")
    if not interpret:
        if nd != 3:
            return Admission.no(f"compiled streaming kernel is 3-D only "
                                f"({nd}-D x-row bands are not "
                                f"sublane-tileable; interpret mode serves)")
        sub = admit_sublane_extension(E, modes)
        if sub is not None:
            return sub
    return None


# ---------------------------------------------------------------------------
# Generic window realization (the NEW families' pure-XLA chunk evolution)
# ---------------------------------------------------------------------------

def window_chunk_xla(fields, *, K, E, modes, grid, ols, shapes,
                     freeze_fields, core):
    """K iterations of a family's update on the extended windows:
    `core(*fields)` returns the updated full-window fields (the family's
    whole-block arithmetic — interior updates, stale edges); then per-dim
    halo handling IN DIMENSION ORDER (later dims win shared cells, the
    per-step exchange-assembly order): wrap dims re-apply the per-field
    staggered self-wrap, open dims re-freeze the freeze set's shoulder+
    boundary band from the chunk-entry buffers (`freeze_fields` may be a
    uniform sequence or a per-dim dict — :func:`normalize_freeze`).
    Returns the evolved extended windows (central slicing is the
    caller's — :func:`central_window`)."""
    from jax import lax

    entry = tuple(fields)
    nd = fields[0].ndim
    freeze = normalize_freeze(freeze_fields, nd)

    def step(_, S):
        S = list(core(*S))
        for d in range(nd):
            if modes[d] == "wrap":
                for f in range(len(S)):
                    S[f] = wrap_edges(S[f], d, S[f].shape[d], ols[f][d])
            elif modes[d] in ("oext", "frozen"):
                lo = E if modes[d] == "oext" else 0
                for f in freeze[d]:
                    hi = lo + shapes[f][d] - 1
                    S[f] = freeze_open_dim(S[f], entry[f], d, modes[d],
                                           lo, hi, grid)
        return tuple(S)

    return lax.fori_loop(0, K, step, entry)


def central_window(F, shape, E, modes):
    """Slice a field's central `shape` window out of its evolved extended
    buffer (extended dims only)."""
    from jax import lax

    for d in range(len(shape)):
        if modes[d] in ("ext", "oext"):
            F = lax.slice_in_dim(F, E, E + shape[d], axis=d)
    return F


def run_chunks(fields, *, n_inner, K, one_chunk):
    """`n_inner // K` full chunks inside one `lax.fori_loop`; the
    K-remainder is the caller's (served by its per-step path).  Returns
    `(*fields, steps_done)`."""
    from jax import lax

    chunks = n_inner // K
    out = lax.fori_loop(0, chunks, lambda _, S: tuple(one_chunk(*S)),
                        tuple(fields))
    return (*out, chunks * K)


# ---------------------------------------------------------------------------
# The generic WHOLE-WINDOW resident Mosaic kernel (compiled realization)
# ---------------------------------------------------------------------------

def normalize_freeze(freeze_fields, nd):
    """Per-dim freeze sets: a plain sequence applies to every dim (the
    stokes convention — velocities frozen on all open dims); a dict
    `{dim: (field indices)}` freezes per dim (a spec's face field is
    no-write only along its staggered dim — `igg.stencil.analyze`)."""
    if isinstance(freeze_fields, dict):
        return {d: tuple(freeze_fields.get(d, ())) for d in range(nd)}
    return {d: tuple(freeze_fields) for d in range(nd)}


def _whole_window_kernel(*refs, K, cfg, core, nfr):
    """Whole-window VMEM-resident chunk kernel (the wave2d scheme,
    generalized): grid `(K,)`, ALL extended fields loaded into VMEM
    scratch once, K coupled full-window steps evolved in place, written
    back once — `n(R+W)/K` HBM traffic per step.  Per iteration the
    per-dim halo handling runs in dimension order: wrap dims re-apply
    the staggered self-wrap; open dims re-freeze the per-dim freeze
    set's boundary PLANES from chunk-entry values, gated by the SMEM
    edge flags (the plane-only freeze — the shoulder garbage beyond is
    quarantined by the analyzer's boundary-validity recurrence, the
    Stokes "one frozen plane" rule)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    modes, ols, ext_shapes, E = (cfg["modes"], cfg["ols"],
                                 cfg["ext_shapes"], cfg["E"])
    shapes = cfg["shapes"]
    freeze = cfg["freeze"]
    n = len(ext_shapes)
    it = iter(refs)
    text_hbm = [next(it) for _ in range(n)]
    flags_ref = next(it) if nfr else None
    fr_hbm = [next(it) for _ in range(nfr)]
    outs = [next(it) for _ in range(n)]
    fv = [next(it) for _ in range(n)]
    fr_v = [next(it) for _ in range(nfr)]
    lsem = next(it)
    osem = next(it)
    fsem = next(it) if nfr else None

    k = pl.program_id(0)

    @pl.when(k == 0)
    def _():
        cs = [pltpu.make_async_copy(text_hbm[j], fv[j], lsem.at[j])
              for j in range(n)]
        for c in cs:
            c.start()
        for c in cs:
            c.wait()

    if nfr:
        @pl.when(k == 0)
        def _():
            cs = [pltpu.make_async_copy(fr_hbm[j], fr_v[j], fsem.at[j])
                  for j in range(nfr)]
            for c in cs:
                c.start()
            for c in cs:
                c.wait()

    fields = [fv[f][...] for f in range(n)]
    news = list(core(*fields))
    nd = fields[0].ndim
    flags = ([flags_ref[j] for j in range(6)] if nfr else [0] * 6)
    plane = {}
    j = 0
    for d in range(nd):
        if modes[d] not in ("oext", "frozen"):
            continue
        for f in freeze[d]:
            for side in (0, 1):
                plane[(f, d, side)] = fr_v[j][...]
                j += 1
    for d in range(nd):
        if modes[d] == "wrap":
            for f in range(n):
                news[f] = wrap_edges(news[f], d, ext_shapes[f][d],
                                     ols[f][d])
        elif modes[d] in ("oext", "frozen"):
            lo = E if modes[d] == "oext" else 0
            for f in freeze[d]:
                hi = lo + shapes[f][d] - 1
                idx = lax.broadcasted_iota(jnp.int32, news[f].shape, d)
                p0 = jnp.expand_dims(plane[(f, d, 0)], d)
                p1 = jnp.expand_dims(plane[(f, d, 1)], d)
                news[f] = jnp.where((idx == lo) & (flags[2 * d] == 1),
                                    p0, news[f])
                news[f] = jnp.where((idx == hi) & (flags[2 * d + 1] == 1),
                                    p1, news[f])
    for f in range(n):
        fv[f][...] = news[f]

    @pl.when(k == K - 1)
    def _():
        cs = [pltpu.make_async_copy(fv[f], outs[f], osem.at[f])
              for f in range(n)]
        for c in cs:
            c.start()
        for c in cs:
            c.wait()


def whole_window_chunk_call(exts, *, K, E, modes, grid, ols, shapes,
                            core, freeze_fields=(), window_fallback,
                            interpret=False):
    """Advance K coupled iterations on the extended buffers with the
    whole-window resident kernel; returns every field's central local
    block.  `core(*windows)` is the family's full-window arithmetic
    (the same callable the pure-XLA window realization evolves);
    `freeze_fields` the per-dim (or uniform) open-boundary no-write
    set (:func:`normalize_freeze`).  In interpret mode the chunk runs
    `window_fallback()` — the pure-XLA window realization — so CPU
    meshes exercise the same admission gates and chunked-exchange
    structure (the kernel's manual DMA is TPU-only)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nd = exts[0].ndim
    ext_shapes = [tuple(x.shape) for x in exts]
    freeze = normalize_freeze(freeze_fields, nd)

    def central(F, f):
        return central_window(F, shapes[f], E, modes)

    if interpret:
        out = window_fallback()
        return tuple(central(F, f) for f, F in enumerate(out))

    cfg = dict(modes=tuple(modes), ols=tuple(ols),
               ext_shapes=tuple(ext_shapes), E=E,
               shapes=tuple(shapes), freeze=freeze)

    # Open-dim entry freeze planes + per-device SMEM edge flags (the
    # resident_chunk_call pattern; "frozen" dims statically flag both
    # sides, so 1-device frozen grids run under plain jax.jit).
    fr_planes = []
    flag_ops = []
    any_open = any(modes[d] in ("oext", "frozen") for d in range(nd))
    if any_open:
        for d in range(nd):
            if modes[d] not in ("oext", "frozen"):
                continue
            lo = E if modes[d] == "oext" else 0
            for f in freeze[d]:
                hi = lo + shapes[f][d] - 1
                for idx in (lo, hi):
                    p = jnp.squeeze(
                        lax.slice_in_dim(exts[f], idx, idx + 1, axis=d), d)
                    fr_planes.append(p)
        # The kernel unpacks the SMEM flags operand iff freeze planes
        # exist (its refs iterator is keyed on nfr): an open-dim spec
        # whose per-dim freeze sets are all empty needs neither — the
        # per-iteration freeze loop has nothing to gate.
        if fr_planes:
            flag_ops = [edge_flags(tuple(modes) + ("wrap",) * (3 - nd),
                                   grid)]
    nfr = len(fr_planes)

    kern = partial(_whole_window_kernel, K=K, cfg=cfg, core=core, nfr=nfr)

    operands = [*exts, *flag_ops, *fr_planes]
    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in operands]
    vma = frozenset().union(*[v for v in vmas if v]) if any(vmas) else None

    def shp(a):
        return (jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(a.shape, a.dtype))

    out = pl.pallas_call(
        kern,
        grid=(K,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * len(exts)
        + [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(flag_ops)
        + [pl.BlockSpec(memory_space=pl.ANY)] * nfr,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * len(exts),
        out_shape=[shp(F) for F in exts],
        input_output_aliases={f: f for f in range(len(exts))},
        scratch_shapes=[pltpu.VMEM(F.shape, F.dtype) for F in exts]
        + [pltpu.VMEM(p.shape, p.dtype) for p in fr_planes]
        + [pltpu.SemaphoreType.DMA((len(exts),)),
           pltpu.SemaphoreType.DMA((len(exts),))]
        + ([pltpu.SemaphoreType.DMA((nfr,))] if nfr else []),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary",)),
    )(*operands)
    return tuple(central(F, f) for f, F in enumerate(out))


# ---------------------------------------------------------------------------
# The generic VMEM-resident banded Mosaic kernel (compiled realization)
# ---------------------------------------------------------------------------

def pad8(v: int) -> int:
    """Round up to the Mosaic sublane tile (f32) — the shared helper
    every chunk module's VMEM-footprint model uses, so the models can
    never drift from the kernels' actual padding."""
    return -(-v // 8) * 8


def pad128(v: int) -> int:
    """Round up to the Mosaic lane tile."""
    return -(-v // 128) * 128


_pad8, _pad128 = pad8, pad128


def band_halo(news, a, bx, flags, frx, fryz, cfg):
    """Per-band halo handling of the updated fields' new-band value
    arrays, in dimension order (later dims win shared cells, the
    per-step path's assembly order): x freeze rows (open dims,
    `freeze_fields` only), then y wrap/freeze, then z wrap/freeze
    (2-D fields stop at y).
    `flags` is the 6-vector of edge flags as VALUES (SMEM scalars in the
    kernel, python ints in the banded-scheme simulation);
    `frx[(f, side)]` are whole x freeze planes and `fryz[(f, d, side)]`
    the band-sliced y/z freeze rows of field f (logical trailing
    extents).  `cfg` carries modes/ols/ext_shapes/shapes/E and
    `freeze_fields` (which updated fields the open-dim no-write applies
    to — uniform sequence or per-dim dict, :func:`normalize_freeze`).
    Pure values — shared by the generic Mosaic kernels, the streaming
    banded kernel, the pure-XLA banded realization, and the
    banded-scheme simulation tests."""
    import jax.numpy as jnp
    from jax import lax

    modes, ols, ext_shapes, E = (cfg["modes"], cfg["ols"],
                                 cfg["ext_shapes"], cfg["E"])
    nd = news[0].ndim
    freeze = normalize_freeze(cfg.get("freeze_fields", (1, 2, 3)), nd)
    news = list(news)

    if modes[0] in ("oext", "frozen"):
        lo = E if modes[0] == "oext" else 0
        for f in freeze[0]:
            hi = lo + cfg["shapes"][f][0] - 1
            rows = lax.broadcasted_iota(jnp.int32, news[f].shape, 0) + a
            news[f] = jnp.where((rows == lo) & (flags[0] == 1),
                                frx[(f, 0)][None], news[f])
            news[f] = jnp.where((rows == hi) & (flags[1] == 1),
                                frx[(f, 1)][None], news[f])
    for d in range(1, nd):
        if modes[d] == "wrap":
            for f in range(len(news)):
                sd = ext_shapes[f][d]
                ol = ols[f][d]
                news[f] = wrap_edges(news[f], d, sd, ol)
        elif modes[d] in ("oext", "frozen"):
            lo = E if modes[d] == "oext" else 0
            for f in freeze[d]:
                hi = lo + cfg["shapes"][f][d] - 1
                idx = lax.broadcasted_iota(jnp.int32, news[f].shape, d)
                exp = (lambda P: jnp.expand_dims(P, d))
                news[f] = jnp.where((idx == lo) & (flags[2 * d] == 1),
                                    exp(fryz[(f, d, 0)]), news[f])
                news[f] = jnp.where((idx == hi) & (flags[2 * d + 1] == 1),
                                    exp(fryz[(f, d, 1)]), news[f])
    return tuple(news)


def _resident_kernel(*refs, K, bx, cfg, nfr, pads, band_update, extras):
    """The generic VMEM-resident in-place banded chunk kernel (the
    `stokes_trapezoid` scheme, parameterized): `n_up` updated fields plus
    `n_fields - n_up` const fields, all resident for the whole chunk
    (grid `(K, nb)`, "arbitrary" semantics), updated IN PLACE in x-row
    bands with a one-row lag buffer carrying each band's overwritten tail
    row to its successor.  HBM traffic per chunk is ONE read of the
    extended fields and ONE write of the updated ones — the 1/K
    amortization the rooflines demand."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shapes = cfg["shapes"]            # local (unextended) field shapes
    ext_shapes = cfg["ext_shapes"]    # logical extended shapes
    modes = cfg["modes"]
    n_fields = len(ext_shapes)
    n_up = cfg["n_up"]
    freeze = cfg.get("freeze_fields", ())

    it = iter(refs)
    text_hbm = [next(it) for _ in range(n_fields)]  # padded extended fields
    flags_ref = next(it) if nfr else None           # SMEM (6,) i32
    fr_hbm = [next(it) for _ in range(nfr)]         # padded freeze planes
    outs = [next(it) for _ in range(n_up)]          # aliased to text inputs
    fv = [next(it) for _ in range(n_fields)]        # resident field scratch
    lag = [next(it) for _ in range(n_up)]           # (2, 1, S1p, S2p)-ish
    fr_v = [next(it) for _ in range(nfr)]
    lsem = next(it)
    osem = next(it)
    fsem = next(it) if nfr else None

    k = pl.program_id(0)
    i = pl.program_id(1)
    a = i * bx
    sl = i % 2

    # One-time chunk-entry load: the padded extended fields (and the
    # freeze planes) HBM -> VMEM.  Synchronous — once per K iterations.
    @pl.when((k == 0) & (i == 0))
    def _():
        cs = [pltpu.make_async_copy(text_hbm[j], fv[j], lsem.at[j])
              for j in range(n_fields)]
        for c in cs:
            c.start()
        for c in cs:
            c.wait()

    if nfr:
        @pl.when((k == 0) & (i == 0))
        def _():
            cs = [pltpu.make_async_copy(fr_hbm[j], fr_v[j], fsem.at[j])
                  for j in range(nfr)]
            for c in cs:
                c.start()
            for c in cs:
                c.wait()

    # Band 0 has no predecessor: seed its low-margin lag slot with the
    # clamped duplicate of row 0 (the dup feeds only rows the validity
    # argument never reads back — shoulder garbage or frozen planes).
    @pl.when(i == 0)
    def _():
        for f in range(n_up):
            lag_w = lag[f].at[pl.ds(1, 1)]
            lag_w[:] = fv[f][pl.ds(0, 1)]

    # Save this band's tail row (about to be overwritten) for the next
    # band's low margin — VMEM-to-VMEM, one row per updated field,
    # slot-alternated (band i writes slot i%2, band i+1 reads it back as
    # 1-(i+1)%2; band 0 reads the seed above from the same expression).
    for f in range(n_up):
        lag_w = lag[f].at[pl.ds(sl, 1)]
        lag_w[:] = fv[f][pl.ds(a + bx - 1, 1)]

    # Margin-1 windows.  Low margin: row a-1 — band i-1 already overwrote
    # it, so every band reads its lag slot (const fields are never
    # overwritten: clamped margin read straight from the buffer).  High
    # margins clamp at the buffer end (top-band dups feed only
    # shoulder/frozen rows).
    nrows = [ext_shapes[f][0] for f in range(n_fields)]

    def window(f, extra):
        if f < n_up:
            m1 = lag[f][pl.ds(1 - sl, 1)]
        else:
            m1 = fv[f][pl.ds(jnp.maximum(a - 1, 0), 1)]
        parts = [m1, fv[f][pl.ds(a, bx)]]
        top = nrows[f] - 1
        for e in range(1, extra + 1):
            parts.append(fv[f][pl.ds(jnp.minimum(a + bx + e - 1, top), 1)])
        return jnp.concatenate(parts, axis=0)

    def logical(W, f):
        # Slice the tile-padded trailing extents back to the field's
        # logical extended shape (values; Mosaic masks the lanes).
        return W[:, :ext_shapes[f][1], :ext_shapes[f][2]]

    Ws = [logical(window(f, extras[f]), f) for f in range(n_fields)]

    news = band_update(*Ws, bx=bx)

    # Halo handling on the new band values (freeze planes band-sliced to
    # logical extents; SMEM flags read as scalars).
    flags = ([flags_ref[j] for j in range(6)] if nfr else [0] * 6)
    frx, fryz = {}, {}
    j = 0
    for d in range(3):
        if modes[d] not in ("oext", "frozen"):
            continue
        for f in freeze:
            pl_shape = [ext_shapes[f][x] for x in range(3) if x != d]
            for side in (0, 1):
                if d == 0:
                    frx[(f, side)] = fr_v[j][...][:pl_shape[0],
                                                  :pl_shape[1]]
                else:
                    fryz[(f, d, side)] = fr_v[j][pl.ds(a, bx)][
                        :, :pl_shape[1]]
                j += 1
    news = band_halo(news, a, bx, flags, frx, fryz, cfg)

    # In-place write, padded back with the old trailing columns.
    for f in range(n_up):
        new = news[f]
        pady, padz = pads[f]
        old = fv[f][pl.ds(a, bx)]
        if padz:
            new = jnp.concatenate([new, old[:, :new.shape[1], -padz:]],
                                  axis=2)
        if pady:
            new = jnp.concatenate([new, old[:, -pady:, :]], axis=1)
        fv[f][pl.ds(a, bx)] = new

    # Final iteration: band write-back to the (aliased) outputs.
    # Synchronous — once per chunk; rows outside the band grid (a
    # staggered field's top face) keep their aliased entry values,
    # exactly the frozen/no-write semantics they need.
    @pl.when(k == K - 1)
    def _():
        cs = [pltpu.make_async_copy(fv[f].at[pl.ds(a, bx)],
                                    outs[f].at[pl.ds(a, bx)], osem.at[f])
              for f in range(n_up)]
        for c in cs:
            c.start()
        for c in cs:
            c.wait()


def resident_chunk_call(exts, const_exts, *, K, bx, modes, grid, ols,
                        shapes, E, band_update, extras, freeze_fields,
                        window_fallback, interpret=False):
    """Advance K coupled iterations on the extended buffers with the
    generic VMEM-resident banded kernel; returns the updated fields'
    central local blocks.  `exts` are the updated fields' extended
    windows (aliased input->output), `const_exts` the loop-invariant
    ones; `extras[f]` is field f's high-margin row count (its read
    radius above the band); `freeze_fields` the updated-field indices the
    open-dim no-write semantics apply to; `band_update(*windows, bx=)`
    the family's pure-value band arithmetic.  In interpret mode the
    chunk runs `window_fallback()` — the family's pure-XLA window
    realization — so CPU meshes exercise the same admission gates and
    chunked-exchange structure (the kernel itself is manual-DMA,
    TPU-only)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_up = len(exts)
    ext_shapes = ([tuple(x.shape) for x in exts]
                  + [tuple(x.shape) for x in const_exts])

    def central(F, f):
        return central_window(F, shapes[f], E, modes)

    if interpret:
        out = window_fallback()
        return tuple(central(F, f) for f, F in enumerate(out[:n_up]))

    S0e = ext_shapes[0][0]
    nb = S0e // bx
    cfg = dict(modes=tuple(modes), ols=tuple(ols[:n_up]),
               ext_shapes=tuple(ext_shapes), E=E,
               shapes=tuple(shapes[:n_up]), n_up=n_up,
               freeze_fields=tuple(freeze_fields))

    # Tile-pad the staggered trailing extents so every leading-dim VMEM
    # slice in the kernel is tile-aligned; the pad columns carry garbage
    # the central slices never see.
    def padded(F):
        s = F.shape
        py = _pad8(s[1]) - s[1]
        pz = _pad128(s[2]) - s[2]
        if py or pz:
            F = jnp.pad(F, [(0, 0), (0, py), (0, pz)])
        return F

    fields_all = [padded(F) for F in list(exts) + list(const_exts)]
    pads = [(_pad8(s[1]) - s[1], _pad128(s[2]) - s[2])
            for s in ext_shapes[:n_up]]

    # Open-dim freeze planes (chunk-entry boundary planes of the frozen
    # fields) + per-device SMEM edge flags ("frozen" dims statically flag
    # both sides, so 1-device frozen grids run under plain jax.jit).
    fr_planes = []
    flag_ops = []
    any_open = any(m in ("oext", "frozen") for m in modes)
    if any_open:
        for d in range(3):
            if modes[d] not in ("oext", "frozen"):
                continue
            lo = E if modes[d] == "oext" else 0
            for f in freeze_fields:
                hi = lo + shapes[f][d] - 1
                for idx in (lo, hi):
                    p = jnp.squeeze(
                        lax.slice_in_dim(exts[f], idx, idx + 1, axis=d), d)
                    ps = p.shape
                    py = _pad8(ps[0]) - ps[0]
                    pz = _pad128(ps[1]) - ps[1]
                    if py or pz:
                        p = jnp.pad(p, [(0, py), (0, pz)])
                    fr_planes.append(p)
        flag_ops = [edge_flags(modes, grid)]
    nfr = len(fr_planes)

    kern = partial(_resident_kernel, K=K, bx=bx, cfg=cfg, nfr=nfr,
                   pads=pads, band_update=band_update, extras=extras)

    operands = [*fields_all, *flag_ops, *fr_planes]
    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(s):
        return (jax.ShapeDtypeStruct(s, exts[0].dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(s, exts[0].dtype))

    # Scratch order MUST mirror the kernel's unpack: field/lag VMEM,
    # freeze-plane VMEM, load semaphores, out semaphores, then the
    # freeze-plane semaphore LAST (present only when a dim is open).
    fr_scratch = [pltpu.VMEM(p.shape, p.dtype) for p in fr_planes]
    out = pl.pallas_call(
        kern,
        grid=(K, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * len(fields_all)
        + [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(flag_ops)
        + [pl.BlockSpec(memory_space=pl.ANY)] * nfr,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_up,
        out_shape=[shp(F.shape) for F in fields_all[:n_up]],
        # The entry buffers are dead after the (k==0, i==0) load; rows
        # the band grid never writes keep their entry values.
        input_output_aliases={f: f for f in range(n_up)},
        scratch_shapes=[pltpu.VMEM(F.shape, F.dtype) for F in fields_all]
        + [pltpu.VMEM((2, F.shape[1], F.shape[2]), F.dtype)
           for F in fields_all[:n_up]]
        + fr_scratch
        + [pltpu.SemaphoreType.DMA((len(fields_all),)),
           pltpu.SemaphoreType.DMA((n_up,))]
        + ([pltpu.SemaphoreType.DMA((nfr,))] if nfr else []),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary", "arbitrary")),
    )(*operands)
    out = [F[:, :ext_shapes[f][1], :ext_shapes[f][2]]
           for f, F in enumerate(out)]
    return tuple(central(F, f) for f, F in enumerate(out))


# ---------------------------------------------------------------------------
# The STREAMING banded chunk realization (HBM ping-pong, rolling VMEM window)
# ---------------------------------------------------------------------------
#
# The resident kernels above hold the full K-extended block in VMEM, so
# `fit_chunk_K` gates them off at exactly the headline shapes (the 160^3+
# refusal in `_vmem`).  The streaming realization below generalizes the
# diffusion ping-pong scheme (`diffusion_trapezoid._kernel`) to the
# engine's N-field/freeze-set/margin config: per iteration the x-row
# bands sweep the 2K-extended block through a rolling VMEM window of
# `lo + B + extras[f]` rows per field, writing B-row out slabs to an HBM
# ping-pong pair — the full extended block NEVER materializes in VMEM,
# so VMEM need is O(B * S1 * S2) instead of O(S0e * S1 * S2) and the
# tier admits wherever the band window fits.  HBM traffic per chunk is
# K reads + K writes of the extended fields (vs the per-step path's K
# reads + K writes of the fields PLUS K exchanges): only the exchange
# amortizes, which is what `igg.perf.bytes_per_step` models for the
# `.banded` tiers.
#
# Every band's window reads ALL-OLD values (the ping-pong source buffer
# holds the previous iteration), which is exactly the data the resident
# kernel's lag-slot scheme feeds its bands — so each family's proven
# `band_update` core transfers unchanged (lo margin 1), and derived
# cores come from :func:`band_core_from_window`.


def band_core_from_window(core, lo, n_up=None):
    """Derive a `band_update(*windows, bx=)` from a family's full-window
    `core(*fields)` (the same callable the window realizations evolve):
    apply the core to the small band windows (rows `[a-lo, a+bx+extras)`
    of each field — staggered-consistent shapes, so the shape-driven
    cores evaluate unchanged) and slice the central `bx` rows.  `lo`
    must be the per-iteration margin loss (`analysis.margin_after(1)`
    for spec families) so the central rows are at full validity
    distance from both window edges; `n_up` truncates cores that return
    const fields too."""
    def band_update(*Ws, bx):
        outs = core(*Ws)
        if n_up is not None:
            outs = outs[:n_up]
        return tuple(o[lo:lo + bx] for o in outs)

    return band_update


def banded_window_xla(fields, *, K, B, lo, modes, grid, ols, shapes, E,
                      band_update, extras, n_up, freeze_fields):
    """Pure-XLA realization of the streaming banded scheme: K iterations,
    each sweeping x-row bands over the previous iteration's buffers
    (ping-pong semantics — every band window reads all-OLD values) with
    clamped-duplicate margins at the buffer ends and the engine's
    per-band halo handling (:func:`band_halo`, the same callable the
    compiled kernels run).  Interpret/CPU meshes prove the banded data
    flow against the window-realization truth rung with this function;
    the contaminated shoulder rows the clamped margins produce differ
    from the window realization's but never reach the central window
    (the trapezoidal validity argument).  Returns the evolved extended
    buffers (updated fields first, const fields passed through);
    central slicing is the caller's."""
    import jax.numpy as jnp
    from jax import lax

    entry = tuple(fields)
    nd = fields[0].ndim
    n_fields = len(fields)
    ext_shapes = [tuple(F.shape) for F in fields]
    base = min(s[0] for s in ext_shapes)
    nb = base // B
    freeze = normalize_freeze(freeze_fields, nd)
    cfg = dict(modes=tuple(modes), ols=tuple(ols), E=E,
               ext_shapes=tuple(ext_shapes), shapes=tuple(shapes),
               freeze_fields=freeze_fields)

    any_open = any(modes[d] in ("oext", "frozen") for d in range(nd))
    flags = ([edge_flags(tuple(modes) + ("wrap",) * (3 - nd), grid)[j]
              for j in range(6)] if any_open else [0] * 6)

    # Chunk-entry freeze planes (whole planes; y/z ones band-sliced per
    # band below, the kernel's fr_vmem convention).
    frx, fryz_full = {}, {}
    for d in range(nd):
        if modes[d] not in ("oext", "frozen"):
            continue
        fr = E if modes[d] == "oext" else 0
        for f in freeze[d]:
            hi = fr + shapes[f][d] - 1
            for side, idx in ((0, fr), (1, hi)):
                p = jnp.squeeze(
                    lax.slice_in_dim(entry[f], idx, idx + 1, axis=d), d)
                if d == 0:
                    frx[(f, side)] = p
                else:
                    fryz_full[(f, d, side)] = p

    def one_iter(_, S):
        padded = []
        for f in range(n_fields):
            F = S[f]
            top = extras[f] - (ext_shapes[f][0] - base)
            parts = []
            if lo:
                parts.append(jnp.concatenate(
                    [lax.slice_in_dim(F, 0, 1, axis=0)] * lo, axis=0))
            parts.append(F)
            if top > 0:
                last = lax.slice_in_dim(F, ext_shapes[f][0] - 1,
                                        ext_shapes[f][0], axis=0)
                parts.append(jnp.concatenate([last] * top, axis=0))
            padded.append(jnp.concatenate(parts, axis=0)
                          if len(parts) > 1 else parts[0])

        def band(i, D):
            a = i * B
            Ws = [lax.dynamic_slice_in_dim(P, a, lo + B + extras[f],
                                           axis=0)
                  for f, P in enumerate(padded)]
            news = band_update(*Ws, bx=B)
            fryz = {key: lax.dynamic_slice_in_dim(p, a, B, axis=0)
                    for key, p in fryz_full.items()}
            news = band_halo(news, a, B, flags, frx, fryz, cfg)
            return tuple(
                lax.dynamic_update_slice_in_dim(D[f], news[f], a, axis=0)
                for f in range(n_up))

        # DST starts from the OLD buffers: rows the band grid never
        # writes (a staggered field's top face) keep their values,
        # exactly the compiled kernel's aliasing semantics.
        new_up = lax.fori_loop(0, nb, band, tuple(S[:n_up]))
        return (*new_up, *S[n_up:])

    return lax.fori_loop(0, K, one_iter, entry)


def _streaming_kernel(*refs, K, B, nb, lo, cfg, nfr, pads, band_update,
                      extras, stags):
    """The streaming banded chunk kernel: grid `(K, nb)`, HBM ping-pong
    (the `diffusion_trapezoid._kernel` scheme generalized).  Per program
    `(k, i)`: fetch each field's rolling window (rows
    `[i*B - lo, i*B + B + extras[f])`, clamped-duplicated at the buffer
    ends) from the iteration-k source — the input extended buffers at
    k=0, then the ping-pong pair by parity — compute the B-row band with
    the family's `band_update` + :func:`band_halo`, and write it to the
    iteration's destination (the other ping-pong buffer; the out buffers
    at k=K-1) through slot-alternated async DMA (drain both slots at
    each step boundary, wait the reused slot at i>=2, final drain —
    the diffusion out-write bookkeeping).  Const fields stream from
    their single HBM buffer every iteration (never resident: a 256^3
    coefficient would blow the budget the tier exists to escape)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ext_shapes = cfg["ext_shapes"]    # logical extended shapes
    modes = cfg["modes"]
    n_fields = len(ext_shapes)
    n_up = cfg["n_up"]
    freeze = normalize_freeze(cfg.get("freeze_fields", ()), 3)

    it = iter(refs)
    src_hbm = [next(it) for _ in range(n_fields)]   # padded extended fields
    flags_ref = next(it) if nfr else None           # SMEM (6,) i32
    fr_hbm = [next(it) for _ in range(nfr)]         # padded freeze planes
    outs, b0, b1 = [], [], []
    for _ in range(n_up):                           # (out, ping, pong) * n_up
        outs.append(next(it))
        b0.append(next(it))
        b1.append(next(it))
    wv = [next(it) for _ in range(n_fields)]        # rolling window scratch
    o2 = [next(it) for _ in range(n_up)]            # (2, B, S1p, S2p) slabs
    fr_v = [next(it) for _ in range(nfr)]
    wsem = next(it)
    osem = next(it)
    fsem = next(it) if nfr else None

    k = pl.program_id(0)
    i = pl.program_id(1)
    a = i * B
    sl = i % 2

    # One-time: freeze planes HBM -> VMEM; the ping buffer's staggered
    # tail rows seeded from the entry values (the pong buffer IS the
    # aliased input, so its tail is already correct).
    if nfr:
        @pl.when((k == 0) & (i == 0))
        def _():
            cs = [pltpu.make_async_copy(fr_hbm[j], fr_v[j], fsem.at[j])
                  for j in range(nfr)]
            for c in cs:
                c.start()
            for c in cs:
                c.wait()

    @pl.when((k == 0) & (i == 0))
    def _():
        for f in range(n_up):
            if stags[f]:
                c = pltpu.make_async_copy(
                    src_hbm[f].at[pl.ds(nb * B, stags[f])],
                    b0[f].at[pl.ds(nb * B, stags[f])], wsem.at[f])
                c.start()
                c.wait()

    # Out-write bookkeeping (the diffusion ping-pong scheme): drain both
    # slots at each step boundary, else wait the slot being reused.
    @pl.when((i == 0) & (k > 0))
    def _():
        for f in range(n_up):
            for s in (0, 1):
                pltpu.make_async_copy(o2[f].at[s], o2[f].at[s],
                                      osem.at[f, s]).wait()

    @pl.when(i >= 2)
    def _():
        for f in range(n_up):
            pltpu.make_async_copy(o2[f].at[sl], o2[f].at[sl],
                                  osem.at[f, sl]).wait()

    # Rolling-window fetches, synchronous (once per band per field; the
    # out-writes overlap the next band's fetch+compute).  Clamped
    # duplicate rows at the buffer ends feed only shoulder rows outside
    # the validity trapezoid.
    def fetch(f, src):
        w = wv[f]
        e = extras[f]
        stg = stags[f] if f < n_up else ext_shapes[f][0] - nb * B
        nrows = lo + B + e

        def copy(src_at, w_at):
            c = pltpu.make_async_copy(src_at, w_at, wsem.at[f])
            c.start()
            c.wait()

        @pl.when(i == 0)
        def _():
            for r in range(lo):
                copy(src.at[pl.ds(0, 1)], w.at[pl.ds(r, 1)])
            copy(src.at[pl.ds(0, B + e)], w.at[pl.ds(lo, B + e)])

        if nb > 2:
            @pl.when((i > 0) & (i < nb - 1))
            def _():
                copy(src.at[pl.ds(a - lo, nrows)], w.at[pl.ds(0, nrows)])

        @pl.when(i == nb - 1)
        def _():
            copy(src.at[pl.ds(a - lo, lo + B + stg)],
                 w.at[pl.ds(0, lo + B + stg)])
            for r in range(stg, e):
                copy(src.at[pl.ds(nb * B + stg - 1, 1)],
                     w.at[pl.ds(lo + B + r, 1)])

    for f in range(n_fields):
        if f < n_up:
            @pl.when(k == 0)
            def _(f=f):
                fetch(f, src_hbm[f])

            @pl.when((k > 0) & (k % 2 == 1))
            def _(f=f):
                fetch(f, b0[f])

            @pl.when((k > 0) & (k % 2 == 0))
            def _(f=f):
                fetch(f, b1[f])
        else:
            fetch(f, src_hbm[f])

    def logical(W, f):
        return W[:, :ext_shapes[f][1], :ext_shapes[f][2]]

    Ws = [logical(wv[f][...], f) for f in range(n_fields)]
    news = band_update(*Ws, bx=B)

    # Per-band halo handling (freeze planes band-sliced to logical
    # extents; SMEM flags read as scalars) — the resident kernel's exact
    # assembly.
    flags = ([flags_ref[j] for j in range(6)] if nfr else [0] * 6)
    frx, fryz = {}, {}
    j = 0
    for d in range(3):
        if modes[d] not in ("oext", "frozen"):
            continue
        for f in freeze[d]:
            pl_shape = [ext_shapes[f][x] for x in range(3) if x != d]
            for side in (0, 1):
                if d == 0:
                    frx[(f, side)] = fr_v[j][...][:pl_shape[0],
                                                  :pl_shape[1]]
                else:
                    fryz[(f, d, side)] = fr_v[j][pl.ds(a, B)][
                        :, :pl_shape[1]]
                j += 1
    news = band_halo(news, a, B, flags, frx, fryz, cfg)

    # Stage the band in this program's out slot, padded back with the
    # window's old trailing columns, and launch the async put to the
    # iteration's destination.
    for f in range(n_up):
        new = news[f]
        pady, padz = pads[f]
        old = wv[f][pl.ds(lo, B)]
        if padz:
            new = jnp.concatenate([new, old[:, :new.shape[1], -padz:]],
                                  axis=2)
        if pady:
            new = jnp.concatenate([new, old[:, -pady:, :]], axis=1)
        o2[f][pl.ds(sl, 1)] = new[None]

        def put(dst, f=f):
            pltpu.make_async_copy(o2[f].at[sl], dst.at[pl.ds(a, B)],
                                  osem.at[f, sl]).start()

        @pl.when(k == K - 1)
        def _(put=put, f=f):
            put(outs[f])

        @pl.when((k < K - 1) & (k % 2 == 0))
        def _(put=put, f=f):
            put(b0[f])

        @pl.when((k < K - 1) & (k % 2 == 1))
        def _(put=put, f=f):
            put(b1[f])

    # Final drain: the last two out DMAs have no successor to wait them.
    @pl.when((k == K - 1) & (i == nb - 1))
    def _():
        for f in range(n_up):
            pltpu.make_async_copy(o2[f].at[1 - sl], o2[f].at[1 - sl],
                                  osem.at[f, 1 - sl]).wait()
            pltpu.make_async_copy(o2[f].at[sl], o2[f].at[sl],
                                  osem.at[f, sl]).wait()


def streaming_chunk_call(exts, const_exts, *, K, B, modes, grid, ols,
                         shapes, E, band_update, extras, freeze_fields,
                         lo=1, interpret=False):
    """Advance K coupled iterations on the extended buffers with the
    STREAMING banded kernel — the chunk realization that never holds the
    K-extended block in VMEM; returns the updated fields' central local
    blocks.  Same contract as :func:`resident_chunk_call` (`exts`
    updated/aliased, `const_exts` loop-invariant, `extras[f]` the high
    read margin, `band_update(*windows, bx=)` the family band core) plus
    `lo`, the low read margin (1 for the hand band cores; the
    per-iteration margin loss for :func:`band_core_from_window` cores).
    In interpret mode the chunk runs :func:`banded_window_xla` — the
    pure-XLA realization of the SAME banded data flow — so CPU meshes
    prove the scheme itself against the window-realization truth rung,
    not just the admission gates."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_up = len(exts)
    fields = list(exts) + list(const_exts)
    ext_shapes = [tuple(x.shape) for x in fields]
    nd = exts[0].ndim

    def central(F, f):
        return central_window(F, shapes[f], E, modes)

    if interpret:
        out = banded_window_xla(
            fields, K=K, B=B, lo=lo, modes=modes, grid=grid, ols=ols,
            shapes=shapes, E=E, band_update=band_update, extras=extras,
            n_up=n_up, freeze_fields=freeze_fields)
        return tuple(central(F, f) for f, F in enumerate(out[:n_up]))

    base = min(s[0] for s in ext_shapes)
    nb = base // B
    stags = [ext_shapes[f][0] - base for f in range(n_up)]
    freeze = normalize_freeze(freeze_fields, nd)
    cfg = dict(modes=tuple(modes), ols=tuple(ols[:n_up]),
               ext_shapes=tuple(ext_shapes), E=E,
               shapes=tuple(shapes[:n_up]), n_up=n_up,
               freeze_fields=freeze_fields)

    def padded(F):
        s = F.shape
        py = _pad8(s[1]) - s[1]
        pz = _pad128(s[2]) - s[2]
        if py or pz:
            F = jnp.pad(F, [(0, 0), (0, py), (0, pz)])
        return F

    fields_all = [padded(F) for F in fields]
    pads = [(_pad8(s[1]) - s[1], _pad128(s[2]) - s[2])
            for s in ext_shapes[:n_up]]

    fr_planes = []
    flag_ops = []
    any_open = any(m in ("oext", "frozen") for m in modes)
    if any_open:
        for d in range(3):
            if modes[d] not in ("oext", "frozen"):
                continue
            fr = E if modes[d] == "oext" else 0
            for f in freeze[d]:
                hi = fr + shapes[f][d] - 1
                for idx in (fr, hi):
                    p = jnp.squeeze(
                        lax.slice_in_dim(exts[f], idx, idx + 1, axis=d), d)
                    ps = p.shape
                    py = _pad8(ps[0]) - ps[0]
                    pz = _pad128(ps[1]) - ps[1]
                    if py or pz:
                        p = jnp.pad(p, [(0, py), (0, pz)])
                    fr_planes.append(p)
        if fr_planes:
            flag_ops = [edge_flags(modes, grid)]
    nfr = len(fr_planes)

    kern = partial(_streaming_kernel, K=K, B=B, nb=nb, lo=lo, cfg=cfg,
                   nfr=nfr, pads=pads, band_update=band_update,
                   extras=extras, stags=stags)

    operands = [*fields_all, *flag_ops, *fr_planes]
    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(s):
        return (jax.ShapeDtypeStruct(s, exts[0].dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(s, exts[0].dtype))

    # Per updated field: out + ping + pong, all full padded extended
    # shape; the input buffer aliases the PONG slot (first written at
    # k=1 — dead after the k=0 reads), so unwritten tail rows keep their
    # entry values there for the odd-iteration fetches.
    out_shapes = []
    aliases = {}
    for f in range(n_up):
        out_shapes += [shp(fields_all[f].shape)] * 3
        aliases[f] = 3 * f + 2

    # Scratch order MUST mirror the kernel's unpack: rolling windows,
    # out slot pairs, freeze-plane VMEM, window semaphores, out
    # semaphores, then the freeze-plane semaphore LAST.
    win_scratch = [
        pltpu.VMEM((lo + B + extras[f], F.shape[1], F.shape[2]), F.dtype)
        for f, F in enumerate(fields_all)]
    out = pl.pallas_call(
        kern,
        grid=(K, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * len(fields_all)
        + [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(flag_ops)
        + [pl.BlockSpec(memory_space=pl.ANY)] * nfr,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (3 * n_up),
        out_shape=out_shapes,
        input_output_aliases=aliases,
        scratch_shapes=win_scratch
        + [pltpu.VMEM((2, B, F.shape[1], F.shape[2]), F.dtype)
           for F in fields_all[:n_up]]
        + [pltpu.VMEM(p.shape, p.dtype) for p in fr_planes]
        + [pltpu.SemaphoreType.DMA((len(fields_all),)),
           pltpu.SemaphoreType.DMA((n_up, 2))]
        + ([pltpu.SemaphoreType.DMA((nfr,))] if nfr else []),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary", "arbitrary")),
    )(*operands)
    evolved = [out[3 * f][:, :ext_shapes[f][1], :ext_shapes[f][2]]
               for f in range(n_up)]
    return tuple(central(F, f) for f, F in enumerate(evolved))
