"""Fused Pallas HM3D step (self-wrap single-device grids).

One `pallas_call` performs the full coupled hydro-mechanical step —
porosity-dependent (cubic) face permeabilities, Darcy fluxes, the effective
pressure update, the Gauss-Seidel-coupled porosity update, AND the grouped
halo update of both fields — reading Pe and phi once each and writing them
once each (the ideal 2+2 array traffic).  The XLA composition
(`hm3d.local_step`: `compute_step` + grouped `update_halo_local(Pe, phi)`)
pays ~10 HBM-bound fusion passes for the same step.

This extends the native-kernel tier (the reference's ">10x" claim for
custom kernels over array broadcasting, `/root/reference/README.md:161`)
to BASELINE config 4's model family; `diffusion_pallas`/`stokes_pallas`
cover configs 1-3 and 5.

Measured on v5e at 256^3 f32 (median-of-3, 100-step dispatches):
**0.66 ms/step vs 2.92 for the XLA composition — 4.5x** (the largest
native-tier gain of the three model kernels: the nonlinear per-step
`(phi/phi0)^n` permeabilities and two coupled interior updates cost the
XLA path many extra HBM passes that all fuse here), matching the XLA path
to float32 rounding; `benchmarks/results/overlap_study.jsonl`.

Structure (mirrors `stokes_pallas`, radius-1 two-field variant):
  - grid over x-slabs of `bx` rows; each program reads its slab of Pe and
    phi plus one margin row per side (single-row block refs, modular index
    maps — edge programs read wrapped rows whose results land only in halo
    rows overwritten by the halo phase);
  - the slab arithmetic is LITERALLY `hm3d.step_core` — one source of
    arithmetic truth with the XLA path;
  - x halo planes cross program boundaries: precomputed in XLA from the two
    3-row x-end windows (same `step_core`, contiguous dim-0 slices) and
    written by the edge programs; y/z halos are in-VMEM self-wrap aliases
    (overlap 2).

Requirements: single device, all dimensions periodic, overlap 2, equal
float dtypes.  Other configurations fall back to the XLA path.
"""

from __future__ import annotations

from functools import partial

# See stokes_pallas._VMEM_LIMIT: a tight scoped-vmem budget steers Mosaic
# toward better DMA/compute interleaving for slab kernels of this shape.
_VMEM_LIMIT = 32 * 1024 * 1024


def hm3d_pallas_supported(grid, Pe) -> bool:
    """Whether the fused step applies: self-wrap fully-periodic
    single-device grid with overlap 2, unstaggered local blocks large
    enough to slab."""
    if tuple(grid.dims) != (1, 1, 1) or not all(bool(p) for p in grid.periods):
        return False
    if grid.overlaps != (2, 2, 2) or Pe.ndim != 3:
        return False
    s = tuple(grid.local_shape_any(Pe))
    if s != tuple(grid.nxyz):
        return False
    return s[0] % 4 == 0 and s[0] >= 8 and s[1] >= 8 and s[2] >= 8


def _windows(Pe, phi, kw):
    """The updated x halo planes from the two 3-row x-end windows: send
    positions `s-ol = S0-2` (window rows [S0-3, S0)) and `ol-1 = 1`
    (rows [0, 3))."""
    from jax import lax

    from ..models.hm3d import step_core

    S0 = Pe.shape[0]

    def win(lo, hi):
        cut = lambda A: lax.slice_in_dim(A, lo, hi, axis=0)
        wPe, wphi = cut(Pe), cut(phi)
        dPe, dphi = step_core(wPe, wphi, **kw)
        # Full (S1,S2) planes: interior updated, y/z edge cells stale —
        # exactly the XLA path's send planes; the kernel's y/z wraps
        # overwrite the edges (sequential-dimension semantics).
        pe_pl = wPe[1].at[1:-1, 1:-1].add(dPe[0])
        phi_pl = wphi[1].at[1:-1, 1:-1].add(dphi[0])
        return pe_pl, phi_pl

    first = win(S0 - 3, S0)   # updated global row S0-2
    last = win(0, 3)          # updated global row 1
    return first, last


def _kernel(*refs, bx, nb, kw):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from ..models.hm3d import step_core

    it = iter(refs)
    m1, cPe, p1 = next(it), next(it), next(it)
    ePe = jnp.concatenate([m1[:], cPe[:], p1[:]], axis=0)
    m1, cphi, p1 = next(it), next(it), next(it)
    ephi = jnp.concatenate([m1[:], cphi[:], p1[:]], axis=0)
    pef, phif = next(it), next(it)      # first planes (row 0)
    pel, phil = next(it), next(it)      # last planes (row S0-1)
    oPe, ophi = next(it), next(it)

    dPe, dphi = step_core(ePe, ephi, **kw)

    # Out rows j <-> ext rows j+1; increments are on the ext interior
    # (offset 1), so out row j <-> increment row j.
    oPe[:] = ePe[1:1 + bx]
    oPe[:, 1:-1, 1:-1] = ePe[1:1 + bx, 1:-1, 1:-1] + dPe[0:bx]
    ophi[:] = ephi[1:1 + bx]
    ophi[:, 1:-1, 1:-1] = ephi[1:1 + bx, 1:-1, 1:-1] + dphi[0:bx]

    i = pl.program_id(0)

    # x halo planes first (dimension-sequential order: y/z own the shared
    # corner/edge cells via the wraps below).
    @pl.when(i == 0)
    def _():
        oPe[0:1] = pef[:][None]
        ophi[0:1] = phif[:][None]

    @pl.when(i == nb - 1)
    def _():
        oPe[bx - 1:bx] = pel[:][None]
        ophi[bx - 1:bx] = phil[:][None]

    # y then z self-wrap (overlap 2).
    for o_ref in (oPe, ophi):
        s1, s2 = o_ref.shape[1], o_ref.shape[2]
        o_ref[:, 0:1, :] = o_ref[:, s1 - 2:s1 - 1, :]
        o_ref[:, s1 - 1:s1, :] = o_ref[:, 1:2, :]
        o_ref[:, :, 0:1] = o_ref[:, :, s2 - 2:s2 - 1]
        o_ref[:, :, s2 - 1:s2] = o_ref[:, :, 1:2]


def fused_hm3d_step(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta,
                    bx: int = 8, interpret: bool = False):
    """One fused HM3D step `(Pe, phi) -> (Pe', phi')` with halo maintenance
    included, on a self-wrap grid (see module docstring).  Matches
    `hm3d.local_step(..., overlap=False)` to Mosaic-vs-XLA rounding."""
    import jax
    from jax.experimental import pallas as pl

    S0, S1, S2 = Pe.shape
    while S0 % bx != 0:
        bx //= 2
    if bx < 4:
        raise ValueError(f"x size {S0} not divisible into slabs of >= 4 rows")
    nb = S0 // bx
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)

    first, last = _windows(Pe, phi, kw)

    operands, in_specs = [], []
    for F in (Pe, phi):
        yz = F.shape[1:]
        for r in (-1, "c", bx):
            operands.append(F)
            if r == "c":
                in_specs.append(pl.BlockSpec((bx, *yz),
                                             lambda i: (i, 0, 0)))
            else:
                in_specs.append(pl.BlockSpec(
                    (1, *yz),
                    lambda i, rr=r: ((i * bx + rr) % S0, 0, 0)))
    for pln in (*first, *last):
        operands.append(pln)
        in_specs.append(pl.BlockSpec(pln.shape, lambda i: (0, 0)))

    vmas = [getattr(getattr(x, "aval", None), "vma", None) for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(F):
        return (jax.ShapeDtypeStruct(F.shape, F.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(F.shape, F.dtype))

    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT,
            dimension_semantics=("parallel",))

    return pl.pallas_call(
        partial(_kernel, bx=bx, nb=nb, kw=kw),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0))] * 2,
        out_shape=[shp(Pe), shp(phi)],
        interpret=interpret,
        **kwargs,
    )(*operands)
