"""Fused Pallas HM3D step — mesh-capable (any dims / periodicity).

One `pallas_call` performs the full coupled hydro-mechanical step —
porosity-dependent (cubic) face permeabilities, Darcy fluxes, the effective
pressure update, the Gauss-Seidel-coupled porosity update, AND the grouped
halo update of both fields — reading Pe and phi once each and writing them
once each (the ideal 2+2 array traffic).  The XLA composition
(`hm3d.local_step`: `compute_step` + grouped `update_halo_local(Pe, phi)`)
pays ~10 HBM-bound fusion passes for the same step.

This extends the native-kernel tier (the reference's ">10x" claim for
custom kernels over array broadcasting, `/root/reference/README.md:161`)
to BASELINE config 4's model family on *every* rank of a decomposed run —
the per-rank property of the reference's native tier — not just the
single-device configuration.

Measured on v5e at 256^3 f32 (median-of-3, 100-step dispatches, self-wrap
grid): **0.64 ms/step vs 2.92 for the XLA composition — 4.6x** — the
largest native-tier gain of the three model kernels: the nonlinear
per-step `(phi/phi0)^n` permeabilities and two coupled interior updates
cost the XLA path many extra HBM passes that all fuse here.  Matches the
XLA path to float32 rounding; `benchmarks/results/overlap_study.jsonl`.
On self-wrap grids the time loop goes further still: `fused_hm3d_steps`
routes it through the two-field K-step mega-kernel at **0.48 ms/step —
6.1x** (`igg/ops/hm3d_mega.py`).

Structure (the two-field radius-1 instance of the `diffusion_pallas`
recipe; see that module's docstring for the design rationale):

1. **Send planes from thin-slab recomputation** — the updated inner
   boundary planes `ol-1` / `s-ol` of both fields
   (`/root/reference/src/update_halo.jl:386-394`) are produced by applying
   `hm3d.compute_step` (radius-1 shift-invariant) to 3-plane slabs, O(s²)
   work data-independent of the main kernel.
2. **Dimension-sequential plane exchange** — `exchange_all_dims_grouped`:
   both fields' planes ride ONE ppermute per (dim, side) (they share plane
   shapes), with corner/edge propagation, open-boundary stale fallbacks,
   and self-wrap local copies (`/root/reference/src/update_halo.jl:36,130,
   516-532`).
3. **Fused compute + assembly kernel** — x-slab programs compute both
   interior updates from extended slabs (single-row modular margins; edge
   programs read wrapped rows whose results land only in overwritten halo
   rows) and assemble the received planes in dimension order: x planes
   first, y rows, then z columns winning the shared corners.

**Per-dimension halo modes** (from `diffusion_pallas._wrap_dims`): y/z dims
that are periodic with a single device are handled by in-VMEM self-wrap
aliases — no plane of theirs ever materializes; exchanged (or open
single-device) dims take received/stale planes as blocked kernel inputs.
x always goes through the plane exchange (its planes cross program
boundaries anyway; on a single periodic device the engine degenerates to
the swap of the send planes — the self-neighbor path).

**Slab carry** (`fused_hm3d_steps`): for recv-mode y/z dims the kernel
emits the 3-plane boundary slabs of its assembled outputs as compact extra
outputs (z TRANSPOSED to `(S0, 3, S1)` — the natural `(S0, S1, 3)` form is
lane-padded ~42x in HBM), and the next iteration's send planes are computed
from the carried slabs without touching the big arrays.  The z send planes
are produced by applying `compute_step` with swapped y/z spacings to the
transposed slabs (the stencil is axis-symmetric), yielding the squeezed z
plane directly.

Semantics match :func:`igg.hide_communication` exactly: identical to the
plain sequential composition on periodic/interior ranks; at open-boundary
edge ranks the physically-meaningless halo cells keep pre-step values.
"""

from __future__ import annotations

from .diffusion_pallas import _check_applicable, _wrap_dims, _wrap_set

# See stokes_pallas: a tight scoped-vmem budget steers Mosaic
# toward better DMA/compute interleaving for slab kernels of this shape.
from ._vmem import fit_bx, vmem_limit


def _vmem_need(bx: int, S1: int, S2: int, itemsize: int = 4) -> int:
    """First-order window footprint of the fused step at slab height
    `bx`: two fields x (bx-row center + 2 single-row sides) + two bx-row
    outputs + compact slab emissions, double-buffered; the 2.0x margin
    absorbs Mosaic scratch (same calibration as
    `stokes_pallas._vmem_need` — the fixed 32 MB budget OOM'd the
    512^3 per-step compile, caught round 5)."""
    rows = 4 * bx + 8
    return int(2 * rows * S1 * S2 * itemsize * 2.0)


def _vmem_limit(bx: int, S1: int, S2: int) -> int:
    return vmem_limit(_vmem_need(bx, S1, S2))


def _fit_bx(bx: int, S0: int, S1: int, S2: int,
            check_vmem: bool = True) -> int:
    # min_bx=2: `_check_applicable` accepts bx=2 slabs and the per-step
    # kernel ran them before the round-5 VMEM gating.
    return fit_bx(_vmem_need, bx, S0, S1, S2, min_bx=2,
                  check_vmem=check_vmem)


def hm3d_pallas_supported(grid, Pe, interpret: bool = False):
    """Whether the fused step applies: 3-D unstaggered overlap-2 grid (any
    device count and any periodicity — the exchange engine handles open
    boundaries and multi-device meshes), local blocks large enough to slab.
    A recv-mode z dimension (exchanged or open) additionally needs z >= 128:
    its compact slab emission is an in-kernel lane extraction.  Returns an
    :class:`igg.degrade.Admission` (truthy/falsy) carrying the structured
    refusal reason."""
    from ..degrade import Admission

    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if Pe.ndim != 3:
        return Admission.no(f"field rank {Pe.ndim} != 3")
    s = tuple(grid.local_shape_any(Pe))
    if s != tuple(grid.nxyz):
        return Admission.no(f"staggered local shape {s} != grid block "
                            f"{tuple(grid.nxyz)}")
    if not (s[0] % 4 == 0 and s[0] >= 8 and s[1] >= 8 and s[2] >= 8):
        return Admission.no(f"local block {s} too small to slab "
                            f"(needs x % 4 == 0, x >= 8, y >= 8, z >= 8)")
    _, wz = _wrap_dims(grid)
    if not (wz or s[2] >= 128):
        return Admission.no(f"recv-mode z extent {s[2]} < 128 (in-kernel "
                            f"lane extraction needs a full lane tile)")
    # Some slab height must fit the VMEM cap in compiled mode (512^3-class
    # y*z areas overflow the fixed budget — round 5).
    if _fit_bx(8, s[0], s[1], s[2], check_vmem=not interpret) < 2:
        return Admission.no(f"no slab height bx >= 2 fits the VMEM budget "
                            f"for local y*z area {s[1]}x{s[2]}")
    return Admission.yes()


def _updated(wPe, wphi, kw):
    """`compute_step` on a 3-plane window of both fields: full-shape outputs
    with the interior updated, edge cells stale — exactly the XLA path's
    pre-exchange state (the engine patches edge rows of pending planes)."""
    from ..models.hm3d import compute_step

    return compute_step(wPe, wphi, **kw)


def _sends_and_stale(Pe, phi, slabs, kw, wrap_yz):
    """Keepdims send planes (updated inner planes `ol-1`/`s-ol`) for BOTH
    fields from compact boundary slabs, plus stale (outermost) planes for
    open-boundary dims — no reads of the big arrays beyond their four cheap
    contiguous x-end slabs.  Wrapped y/z dims need neither.

    Returns `(sends, stales)` as two-element lists (Pe, phi) of
    `{(dim, side): plane}` dicts for `exchange_all_dims_grouped`.

    z slabs arrive TRANSPOSED `(S0, 3, S1)`: the stencil is axis-symmetric,
    so applying it with swapped y/z spacings produces the transposed update
    whose middle plane is the squeezed z send plane `(S0, S1)`."""
    import jax.numpy as jnp
    from jax import lax

    s = Pe.shape
    wy, wz = wrap_yz
    (pe_y_lo, pe_y_hi, phi_y_lo, phi_y_hi,
     pe_zt_lo, pe_zt_hi, phi_zt_lo, phi_zt_hi) = slabs

    def xcut(A, lo, hi):
        return lax.slice_in_dim(A, lo, hi, axis=0)

    sends = [{}, {}]
    stales = [{}, {}]
    up = _updated(xcut(Pe, 0, 3), xcut(phi, 0, 3), kw)
    for i in range(2):
        sends[i][(0, 0)] = up[i][1:2]
    up = _updated(xcut(Pe, s[0] - 3, s[0]), xcut(phi, s[0] - 3, s[0]), kw)
    for i in range(2):
        sends[i][(0, 1)] = up[i][1:2]
    stales[0][(0, 0)] = xcut(Pe, 0, 1)
    stales[0][(0, 1)] = xcut(Pe, s[0] - 1, s[0])
    stales[1][(0, 0)] = xcut(phi, 0, 1)
    stales[1][(0, 1)] = xcut(phi, s[0] - 1, s[0])

    if not wy:
        up = _updated(pe_y_lo, phi_y_lo, kw)
        for i in range(2):
            sends[i][(1, 0)] = up[i][:, 1:2, :]
        up = _updated(pe_y_hi, phi_y_hi, kw)
        for i in range(2):
            sends[i][(1, 1)] = up[i][:, 1:2, :]
        stales[0][(1, 0)] = pe_y_lo[:, 0:1, :]
        stales[0][(1, 1)] = pe_y_hi[:, 2:3, :]
        stales[1][(1, 0)] = phi_y_lo[:, 0:1, :]
        stales[1][(1, 1)] = phi_y_hi[:, 2:3, :]
    if not wz:
        swapped = dict(kw)
        swapped["dy"], swapped["dz"] = kw["dz"], kw["dy"]
        up = _updated(pe_zt_lo, phi_zt_lo, swapped)
        for i in range(2):
            sends[i][(2, 0)] = jnp.expand_dims(up[i][:, 1, :], 2)
        up = _updated(pe_zt_hi, phi_zt_hi, swapped)
        for i in range(2):
            sends[i][(2, 1)] = jnp.expand_dims(up[i][:, 1, :], 2)
        stales[0][(2, 0)] = jnp.expand_dims(pe_zt_lo[:, 0, :], 2)
        stales[0][(2, 1)] = jnp.expand_dims(pe_zt_hi[:, 2, :], 2)
        stales[1][(2, 0)] = jnp.expand_dims(phi_zt_lo[:, 0, :], 2)
        stales[1][(2, 1)] = jnp.expand_dims(phi_zt_hi[:, 2, :], 2)
    return sends, stales


def _boundary_slabs(Pe, phi, wrap_yz):
    """One-time strided extraction of both fields' y/z 3-plane boundary
    slabs for the recv-mode dims (thereafter the kernel re-emits them
    compactly, z TRANSPOSED); `None` placeholders for wrapped dims.  Order
    matches the kernel's slab outputs: y slabs of both fields, then z."""
    from .diffusion_pallas import _boundary_slabs as one

    pe = one(Pe, wrap_yz)    # (y_lo, y_hi, zt_lo, zt_hi)
    ph = one(phi, wrap_yz)
    return (pe[0], pe[1], ph[0], ph[1], pe[2], pe[3], ph[2], ph[3])


def _make_kernel(wrap_y: bool, wrap_z: bool, kw_core, bx: int, nb: int,
                 emit_slabs: bool):
    """Kernel factory: one x-slab program computing both coupled updates and
    assembling halos in dimension order (x planes first, then y rows, then z
    columns — later dimensions own the shared corner/edge cells, realizing
    `/root/reference/src/update_halo.jl:36,130`).  `emit_slabs` adds the
    compact boundary-slab outputs consumed by the slab-carry loop; the
    single-step entry skips them (no consumer)."""
    from jax.experimental import pallas as pl

    n_planes_y = 0 if wrap_y else 4
    n_planes_z = 0 if wrap_z else 4

    def kernel(*refs):
        import jax.numpy as jnp

        from ..models.hm3d import step_core
        from .diffusion_pallas import _ref_taker

        take = _ref_taker(refs)
        m1, cPe, p1 = take(3)
        ePe = jnp.concatenate([m1[:], cPe[:], p1[:]], axis=0)
        m1, cphi, p1 = take(3)
        ephi = jnp.concatenate([m1[:], cphi[:], p1[:]], axis=0)
        pef, phif, pel, phil = take(4)            # squeezed (S1,S2) x planes
        y_in = take(n_planes_y)                   # (pe_f, pe_l, phi_f, phi_l)
        z_in = take(n_planes_z)
        oPe, ophi = take(2)
        y_out = take(4 if emit_slabs and not wrap_y else 0)
        z_out = take(4 if emit_slabs and not wrap_z else 0)

        dPe, dphi = step_core(ePe, ephi, **kw_core)

        # Out rows j <-> ext rows j+1; increments are on the ext interior
        # (offset 1), so out row j <-> increment row j.
        oPe[:] = ePe[1:1 + bx]
        oPe[:, 1:-1, 1:-1] = ePe[1:1 + bx, 1:-1, 1:-1] + dPe[0:bx]
        ophi[:] = ephi[1:1 + bx]
        ophi[:, 1:-1, 1:-1] = ephi[1:1 + bx, 1:-1, 1:-1] + dphi[0:bx]

        i = pl.program_id(0)
        S1, S2 = oPe.shape[1], oPe.shape[2]

        # x halo planes (interior region only — their y/z edge cells are
        # owned by the later y/z writes).
        @pl.when(i == 0)
        def _():
            oPe[0:1, 1:-1, 1:-1] = pef[1:-1, 1:-1][None]
            ophi[0:1, 1:-1, 1:-1] = phif[1:-1, 1:-1][None]

        @pl.when(i == nb - 1)
        def _():
            oPe[bx - 1:bx, 1:-1, 1:-1] = pel[1:-1, 1:-1][None]
            ophi[bx - 1:bx, 1:-1, 1:-1] = phil[1:-1, 1:-1][None]

        # y halo rows (full x extent; z edges overwritten below).
        if wrap_y:
            for o in (oPe, ophi):
                o[:, 0:1, 1:-1] = o[:, S1 - 2:S1 - 1, 1:-1]
                o[:, S1 - 1:S1, 1:-1] = o[:, 1:2, 1:-1]
        else:
            for o, f, l in ((oPe, y_in[0], y_in[1]), (ophi, y_in[2], y_in[3])):
                o[:, 0:1, 1:-1] = jnp.expand_dims(f[:, 1:-1], 1)
                o[:, S1 - 1:S1, 1:-1] = jnp.expand_dims(l[:, 1:-1], 1)
        # z halo columns (own all shared corners).
        if wrap_z:
            for o in (oPe, ophi):
                o[:, :, 0:1] = o[:, :, S2 - 2:S2 - 1]
                o[:, :, S2 - 1:S2] = o[:, :, 1:2]
        else:
            for o, f, l in ((oPe, z_in[0], z_in[1]), (ophi, z_in[2], z_in[3])):
                o[:, :, 0:1] = jnp.expand_dims(f[:], 2)
                o[:, :, S2 - 1:S2] = jnp.expand_dims(l[:], 2)

        # Compact boundary slabs of the assembled outputs for the recv-mode
        # dims (consumed by the slab-carry loop); z TRANSPOSED (bx,3,S1).
        if y_out:
            y_out[0][:] = oPe[:, 0:3, :]
            y_out[1][:] = oPe[:, S1 - 3:S1, :]
            y_out[2][:] = ophi[:, 0:3, :]
            y_out[3][:] = ophi[:, S1 - 3:S1, :]
        if z_out:
            for j in range(3):
                z_out[0][:, j, :] = oPe[:, :, j]
                z_out[1][:, j, :] = oPe[:, :, S2 - 3 + j]
                z_out[2][:, j, :] = ophi[:, :, j]
                z_out[3][:, j, :] = ophi[:, :, S2 - 3 + j]

    return kernel


def _call_kernel(Pe, phi, recvs, kw_core, bx, interpret, wrap_yz,
                 emit_slabs: bool = True):
    """pallas_call plumbing: returns `(Pe', phi', *slabs)` where `slabs` are
    the recv-mode boundary-slab outputs in (y: pe_lo, pe_hi, phi_lo, phi_hi;
    z: same transposed) order — wrap dims emit none, and `emit_slabs=False`
    (the single-step entry) emits none at all."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    s = Pe.shape
    S0, S1, S2 = s
    nb = S0 // bx
    wy, wz = wrap_yz
    # Squeeze the engine's keepdims recv planes at the kernel boundary.
    rq = [{d: (jnp.squeeze(a, d), jnp.squeeze(b, d))
           for d, (a, b) in r.items()} for r in recvs]

    kern = _make_kernel(wy, wz, kw_core, bx, nb, emit_slabs)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=_vmem_limit(bx, S1, S2))

    operands, in_specs = [], []
    for F in (Pe, phi):
        for r in (-1, "c", bx):
            operands.append(F)
            if r == "c":
                in_specs.append(pl.BlockSpec((bx, S1, S2),
                                             lambda i: (i, 0, 0)))
            else:
                in_specs.append(pl.BlockSpec(
                    (1, S1, S2), lambda i, rr=r: ((i * bx + rr) % S0, 0, 0)))
    plane_x = pl.BlockSpec((S1, S2), lambda i: (0, 0))
    operands += [rq[0][0][0], rq[1][0][0], rq[0][0][1], rq[1][0][1]]
    in_specs += [plane_x] * 4
    if not wy:
        operands += [rq[0][1][0], rq[0][1][1], rq[1][1][0], rq[1][1][1]]
        in_specs += [pl.BlockSpec((bx, S2), lambda i: (i, 0))] * 4
    if not wz:
        operands += [rq[0][2][0], rq[0][2][1], rq[1][2][0], rq[1][2][1]]
        in_specs += [pl.BlockSpec((bx, S1), lambda i: (i, 0))] * 4

    vmas = [getattr(getattr(x, "aval", None), "vma", None) for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(*dims):
        return (jax.ShapeDtypeStruct(dims, Pe.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(dims, Pe.dtype))

    out_shape = [shp(S0, S1, S2)] * 2
    out_specs = [pl.BlockSpec((bx, S1, S2), lambda i: (i, 0, 0))] * 2
    if emit_slabs and not wy:
        out_shape += [shp(S0, 3, S2)] * 4
        out_specs += [pl.BlockSpec((bx, 3, S2), lambda i: (i, 0, 0))] * 4
    if emit_slabs and not wz:
        out_shape += [shp(S0, 3, S1)] * 4   # transposed z slabs
        out_specs += [pl.BlockSpec((bx, 3, S1), lambda i: (i, 0, 0))] * 4
    return pl.pallas_call(
        kern,
        out_shape=tuple(out_shape),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=interpret,
        **kwargs,
    )(*operands)


def _exchange(Pe, phi, slabs, kw, grid, dims_active, wrap_yz):
    from ..halo import exchange_all_dims_grouped

    sends, stales = _sends_and_stale(Pe, phi, slabs, kw, wrap_yz)
    wrap = _wrap_set(wrap_yz)
    return exchange_all_dims_grouped(
        [Pe.shape, phi.shape], sends, [dims_active] * 2, grid,
        stales=stales, wraps=[wrap] * 2, blocks=[Pe, phi])


def fused_hm3d_step(Pe, phi, *, dx, dy, dz, dt, phi0, npow, eta,
                    bx: int = 8, interpret: bool = False):
    """One fused HM3D step `(Pe, phi) -> (Pe', phi')` with halo maintenance
    included, on any mesh (see module docstring).  Call inside SPMD code
    (`igg.sharded` / shard_map); on a 1-device grid the exchange degenerates
    to local copies and the function also works under plain `jax.jit`.  For
    time loops use :func:`fused_hm3d_steps`, which avoids the per-step
    strided slab extraction this entry pays."""
    from .. import shared

    grid = shared.global_grid()
    bx, dims_active = _check_applicable(grid, Pe.shape, bx)
    bx = _fit_bx(bx, *Pe.shape, check_vmem=not interpret)
    if bx < 2:
        raise ValueError(
            f"no slab height divides x size {Pe.shape[0]}"
            + ("" if interpret else
               f" with windows fitting the VMEM budget at y*z area "
               f"{Pe.shape[1]}x{Pe.shape[2]}"))
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)
    wrap_yz = _wrap_dims(grid)
    slabs = _boundary_slabs(Pe, phi, wrap_yz)
    recvs = _exchange(Pe, phi, slabs, kw, grid, dims_active, wrap_yz)
    Pe2, phi2 = _call_kernel(Pe, phi, recvs, kw, bx, interpret, wrap_yz,
                             emit_slabs=False)
    return Pe2, phi2


def fused_hm3d_steps(Pe, phi, *, n_inner, dx, dy, dz, dt, phi0, npow, eta,
                     bx: int = 8, interpret: bool = False):
    """`n_inner` fused HM3D steps with boundary-slab carry (module
    docstring): the recv-mode y/z slabs feeding each step's send planes are
    emitted by the previous step's kernel, so the steady-state HBM traffic
    per step is the ideal 2 reads + 2 writes + compact slab I/O.  Wrapped
    y/z dims skip sends, slabs, and carry entirely."""
    from jax import lax

    from .. import shared
    from .diffusion_pallas import _self_wrap_all

    grid = shared.global_grid()
    bx, dims_active = _check_applicable(grid, Pe.shape, bx)
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)
    wrap_yz = _wrap_dims(grid)

    if _self_wrap_all(grid):
        from .hm3d_mega import fused_hm3d_megasteps, hm3d_mega_supported

        # Fastest: the whole inner loop as ONE pallas_call with manual DMA
        # and HBM ping-pong for both fields (see `hm3d_mega`).
        if hm3d_mega_supported(Pe.shape, bx, n_inner, interpret, Pe.dtype):
            return fused_hm3d_megasteps(Pe, phi, n_inner=n_inner, bx=bx,
                                        **kw)

    # Per-step loop path: the slab height must also fit the VMEM budget
    # (the mega branch above sizes its own buffers).
    bx = _fit_bx(bx, *Pe.shape, check_vmem=not interpret)
    if bx < 2:
        raise ValueError(
            f"no slab height divides x size {Pe.shape[0]}"
            + ("" if interpret else
               f" with windows fitting the VMEM budget at y*z area "
               f"{Pe.shape[1]}x{Pe.shape[2]}"))
    init_slabs = _boundary_slabs(Pe, phi, wrap_yz)
    keep = [j for j, sl in enumerate(init_slabs) if sl is not None]

    def body(_, carry):
        Pe, phi = carry[0], carry[1]
        slabs = [None] * 8
        for p, val in zip(keep, carry[2:]):
            slabs[p] = val
        recvs = _exchange(Pe, phi, slabs, kw, grid, dims_active, wrap_yz)
        # _call_kernel returns (Pe', phi', *slabs-in-keep-order)
        return _call_kernel(Pe, phi, recvs, kw, bx, interpret, wrap_yz)

    out = lax.fori_loop(0, n_inner, body,
                        (Pe, phi, *(init_slabs[j] for j in keep)))
    return out[0], out[1]
