"""Fused Pallas tiers for wave2d — the 2-D staggered leapfrog's missing
speed rungs (ROADMAP item 1: "wave2d has neither a Mosaic nor a chunk
tier"), both generated from the shared K-step chunk engine
(`igg.ops.chunk_engine`).

**Per-step Mosaic tier** (`fused_wave2d_step`): ONE `pallas_call`
computes the whole coupled leapfrog update — `Vx`/`Vy` from the pressure
gradient, then the pressure from the FRESH velocity divergence
(Gauss-Seidel flavor) — reading each field once and writing it once,
where the XLA composition pays a separate HBM-bound fusion per
sub-update; the grouped halo update then runs through the existing
exchange engine (`igg.update_halo_local`), so the step's semantics are
EXACTLY the sequential composition `wave2d.compute_step` +
`update_halo_local` on every mesh and boundary condition.  The kernel is
a single whole-block program (2-D fields are plane-sized, not
volume-sized — the VMEM gate in `wave2d_pallas_supported` does the
accounting), interpret-capable, so CPU meshes run the real kernel body.

**2-D chunk tier** (`fused_wave2d_chunk_steps`): K-step trapezoidal
temporal blocking over the exchanged mesh dims — both fields extended
`E = 2K` deep per split dim by the engine's grouped slab ppermutes (one
pair per dim for all three staggered fields), K steps evolved with NO
exchange (the coupled chain loses at most 2 rows of validity per side
per step: the pressure reads the fresh velocities which read the
pressure at +-1 — the same radius-2 contract as the Stokes chain, so
`2K` margins hold the front exactly), central blocks sliced out.
PERIODIC dims only: the per-step path updates the pressure's boundary
plane full-shape and the open-boundary no-write interplay differs per
field, so open meshes are refused with a structured Admission (the
per-step tiers serve them) rather than risking silently-wrong physics.
Two realizations: the engine's pure-XLA window loop (interpret mode —
the 8-device CPU mesh equivalence tests), and a whole-window
VMEM-resident Mosaic kernel (grid `(K,)`, all three extended fields in
VMEM scratch for the whole chunk, one HBM read + one write per chunk —
`3(R+W)/K` traffic per step; TPU-gated test in `tests/test_mega_tpu.py`,
verify-on-first-use guarding production dispatch).

Both tiers ride the `wave2d` degradation ladder
(`wave2d.make_step`: `wave2d.chunk` → `wave2d.mosaic` → `wave2d.xla`).
"""

from __future__ import annotations

from functools import partial

from ._vmem import banded_vmem, chunk_budget, fit_banded, fit_chunk_K
from .chunk_engine import (admit_banded_geometry, admit_chunk_common,
                           admit_send_slabs, band_core_from_window,
                           dim_modes, extend_fields, field_ols, run_chunks,
                           streaming_chunk_call, whole_window_chunk_call,
                           window_chunk_xla)


def _field_shapes(shape):
    """Local shapes of (P, Vx, Vy) from the unstaggered P shape."""
    S0, S1 = shape
    return [(S0, S1), (S0 + 1, S1), (S0, S1 + 1)]


def _compute(P, Vx, Vy, *, dx, dy, dt, rho, bulk):
    """The pure coupled leapfrog update (no halo exchange) —
    `wave2d.compute_step`, the single source of arithmetic truth shared
    with the XLA composition (`bulk` is the model's `K`, renamed here so
    the chunk depth keeps the trapezoid modules' `K` convention)."""
    from ..models.wave2d import compute_step

    return compute_step(P, Vx, Vy, dx=dx, dy=dy, dt=dt, rho=rho, K=bulk)


# ---------------------------------------------------------------------------
# Per-step Mosaic tier
# ---------------------------------------------------------------------------

def _whole_block_vmem(shapes, itemsize: int = 4) -> int:
    """The shared whole-block footprint model (round 17: moved next to
    the budget it gates, `igg.ops._vmem.whole_block_vmem`)."""
    from ._vmem import whole_block_vmem

    return whole_block_vmem(shapes, itemsize)


def wave2d_pallas_supported(grid, P, interpret: bool = False):
    """Whether the fused per-step kernel applies: 2-D decomposition
    (`dims[2] == 1`), overlap-2 grid, unstaggered 2-D pressure matching
    the grid block, and — in compiled mode — the three whole blocks
    fitting the VMEM budget.  Any periodicity: the halo half of the step
    is the existing exchange engine.  Returns an
    :class:`igg.degrade.Admission`."""
    from ..degrade import Admission

    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if getattr(P, "ndim", 0) != 2:
        return Admission.no(f"field rank {getattr(P, 'ndim', 0)} != 2")
    if grid.dims[2] != 1 or grid.nxyz[2] != 1:
        return Admission.no(f"grid is not a 2-D decomposition "
                            f"(dims={tuple(grid.dims)}, nz={grid.nxyz[2]})")
    s = tuple(grid.local_shape_any(P))
    if s != tuple(grid.nxyz[:2]):
        return Admission.no(f"local shape {s} != grid block "
                            f"{tuple(grid.nxyz[:2])}")
    if s[0] < 4 or s[1] < 4:
        return Admission.no(f"local block {s} too small (needs x >= 4, "
                            f"y >= 4)")
    if not interpret:
        need = _whole_block_vmem(_field_shapes(s))
        if need > chunk_budget():
            return Admission.no(f"whole-block working set {need} bytes "
                                f"exceeds the VMEM budget "
                                f"{chunk_budget()}")
    return Admission.yes()


def _step_kernel(p_ref, vx_ref, vy_ref, op_ref, ovx_ref, ovy_ref, *, scal):
    P, Vx, Vy = p_ref[...], vx_ref[...], vy_ref[...]
    Pn, Vxn, Vyn = _compute(P, Vx, Vy, **scal)
    op_ref[...] = Pn
    ovx_ref[...] = Vxn
    ovy_ref[...] = Vyn


def _call_step_kernel(P, Vx, Vy, scal, interpret):
    import jax
    from jax.experimental import pallas as pl

    operands = [P, Vx, Vy]
    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(a):
        return (jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(a.shape, a.dtype))

    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        from ._vmem import vmem_limit

        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit(
                _whole_block_vmem([a.shape for a in operands])))
    return pl.pallas_call(
        partial(_step_kernel, scal=scal),
        out_shape=tuple(shp(a) for a in operands),
        interpret=interpret,
        **kwargs,
    )(*operands)


def fused_wave2d_step(P, Vx, Vy, *, dx, dy, dt, rho, K,
                      interpret: bool = False):
    """One fused wave2d step `(P, Vx, Vy) -> (P', Vx', Vy')` — the whole
    coupled update in ONE kernel, then the grouped halo update through
    the exchange engine.  Semantics are exactly the sequential
    composition (`wave2d.local_step`) on every mesh and boundary
    condition.  Call inside SPMD code (`igg.sharded` / shard_map)."""
    from .. import halo

    scal = dict(dx=dx, dy=dy, dt=dt, rho=rho, bulk=K)
    Pn, Vxn, Vyn = _call_step_kernel(P, Vx, Vy, scal, interpret)
    return halo.update_halo_local(Pn, Vxn, Vyn)


def fused_wave2d_steps(P, Vx, Vy, *, n_inner, dx, dy, dt, rho, K,
                       interpret: bool = False):
    """`n_inner` fused steps in one `lax.fori_loop`."""
    from jax import lax

    return lax.fori_loop(
        0, n_inner,
        lambda _, S: tuple(fused_wave2d_step(*S, dx=dx, dy=dy, dt=dt,
                                             rho=rho, K=K,
                                             interpret=interpret)),
        (P, Vx, Vy))


# ---------------------------------------------------------------------------
# The 2-D chunk tier
# ---------------------------------------------------------------------------

def wave2d_chunk_supported(grid, shape, K: int, n_inner: int, dtype,
                           interpret: bool = False):
    """Whether the K-step wave2d chunk tier applies: the per-step
    kernel's prerequisites, PERIODIC dims only (open-boundary no-write
    interplay differs per field on this family — the per-step tiers
    serve open meshes), at least one full chunk, `E = 2K` send slabs
    inside every split dimension's block (per-field staggered ol), and
    the extended working set within the VMEM budget.  Returns an
    :class:`igg.degrade.Admission`."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if grid.dims[2] != 1 or grid.nxyz[2] != 1:
        return Admission.no(f"grid is not a 2-D decomposition "
                            f"(dims={tuple(grid.dims)}, nz={grid.nxyz[2]})")
    if tuple(shape) != tuple(grid.nxyz[:2]):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz[:2])}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = dim_modes(grid)[:2]
    if any(m in ("oext", "frozen") for m in modes):
        return Admission.no(
            f"open (non-periodic) dimensions {modes}: the wave2d chunk "
            f"tier serves periodic meshes only (the per-step tiers carry "
            f"open boundaries)")
    E = 2 * K
    shapes = _field_shapes(shape)
    ols = field_ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    exts = [tuple(s[d] + (2 * E if modes[d] == "ext" else 0)
                  for d in range(2)) for s in shapes]
    need = _whole_block_vmem(exts)
    if need > chunk_budget():
        return Admission.no(f"extended working set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_wave2d_K(grid, shape, n_inner: int, dtype,
                 interpret: bool = False, kmax: int = 8) -> int:
    """Largest admissible chunk depth K <= kmax (halving, >= 2;
    `_vmem.fit_chunk_K`); 0 when none applies."""
    return fit_chunk_K(
        lambda K: wave2d_chunk_supported(grid, tuple(shape), K, n_inner,
                                         dtype, interpret=interpret),
        kmax)


def _window_core(kw):
    def core(P, Vx, Vy):
        return _compute(P, Vx, Vy, **kw)

    return core


def _window_steps_xla(Pe, Vxe, Vye, *, Kc, E, modes, grid, kw, ols,
                      shapes):
    """Pure-XLA realization of the chunk evolution (interpret mode):
    the engine's generic window loop — periodic modes only, so the halo
    handling is pure staggered self-wrap on wrap dims."""
    return window_chunk_xla((Pe, Vxe, Vye), K=Kc, E=E, modes=modes,
                            grid=grid, ols=ols, shapes=shapes,
                            freeze_fields=(), core=_window_core(kw))


def _chunk_call(exts, *, Kc, modes, grid, kw, ols, shapes,
                interpret=False):
    """Advance Kc coupled steps on the extended buffers; returns the
    three central local blocks.  Round 17: the whole-window resident
    kernel moved into the chunk engine (`whole_window_chunk_call` — the
    same grid-`(Kc,)` scheme, generalized to N fields and open-dim
    freeze planes so `igg.stencil`'s generated chunk tiers instantiate
    it too); wave2d passes its proven periodic-only config."""
    E = 2 * Kc
    return whole_window_chunk_call(
        list(exts), K=Kc, E=E, modes=modes, grid=grid, ols=ols,
        shapes=shapes, core=_window_core(kw), freeze_fields=(),
        window_fallback=lambda: _window_steps_xla(
            *exts, Kc=Kc, E=E, modes=modes, grid=grid, kw=kw, ols=ols,
            shapes=shapes),
        interpret=interpret)


def fused_wave2d_chunk_steps(P, Vx, Vy, *, n_inner: int, K: int,
                             dx, dy, dt, rho, bulk,
                             interpret: bool = False):
    """Advance `n_inner // K` full K-step chunks (warm-up and remainder
    are the caller's, through the per-step tier); returns
    `(P, Vx, Vy, steps_done)`.

    Entry contract: OVERLAP-CONSISTENT, exchange-fresh state (the model
    init evolved by per-step iterations is; `Vx`'s x-overlap is 3, so
    `update_halo` alone cannot synchronize arbitrary interior
    duplicates — the Stokes chunk tier's contract).  Call inside SPMD
    code (`igg.sharded` / shard_map)."""
    from .. import shared

    grid = shared.global_grid()
    modes = dim_modes(grid)[:2]
    E = 2 * K
    shapes = _field_shapes(P.shape)
    ols = field_ols(grid, shapes)
    kw = dict(dx=dx, dy=dy, dt=dt, rho=rho, bulk=bulk)

    def one(P, Vx, Vy):
        exts = extend_fields([P, Vx, Vy], ols, E, grid, modes)
        return _chunk_call(exts, Kc=K, modes=modes, grid=grid, kw=kw,
                           ols=ols, shapes=shapes, interpret=interpret)

    *S, done = run_chunks((P, Vx, Vy), n_inner=n_inner, K=K, one_chunk=one)
    return (*S, done)


# ---------------------------------------------------------------------------
# The STREAMING banded tier (wave2d.banded)
# ---------------------------------------------------------------------------

# The coupled chain loses 2 rows of validity per side per iteration
# (pressure reads fresh velocities which read the pressure at +-1), so
# the band core's low margin is 2 and the per-field high margins are
# `2 + x-stagger`: (P, Vx, Vy) -> (2, 3, 2).
_BAND_LO = 2
_BAND_EXTRAS = (2, 3, 2)


def wave2d_banded_supported(grid, shape, K: int, n_inner: int, dtype,
                            B: int = 8, interpret: bool = False):
    """Whether the STREAMING banded wave2d chunk tier applies at depth
    K / band B: the chunk tier's structural gates (periodic dims only,
    2-D decomposition) minus the whole-window VMEM bound, plus the
    banded geometry.  The compiled streaming kernel is 3-D only, so this
    rung serves interpret meshes (the CPU contract rows); compiled TPU
    configurations get the structured `admit_banded_geometry` refusal.
    Returns an :class:`igg.degrade.Admission`."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if grid.dims[2] != 1 or grid.nxyz[2] != 1:
        return Admission.no(f"grid is not a 2-D decomposition "
                            f"(dims={tuple(grid.dims)}, nz={grid.nxyz[2]})")
    if tuple(shape) != tuple(grid.nxyz[:2]):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz[:2])}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = dim_modes(grid)[:2]
    if any(m in ("oext", "frozen") for m in modes):
        return Admission.no(
            f"open (non-periodic) dimensions {modes}: the wave2d chunk "
            f"tiers serve periodic meshes only (the per-step tiers carry "
            f"open boundaries)")
    E = 2 * K
    shapes = _field_shapes(shape)
    ols = field_ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    geo = admit_banded_geometry(shapes, E, modes, B=B,
                                extras=_BAND_EXTRAS, lo=_BAND_LO,
                                interpret=interpret)
    if geo is not None:
        return geo
    exts = [tuple(s[d] + (2 * E if modes[d] == "ext" else 0)
                  for d in range(2)) for s in shapes]
    need = banded_vmem(exts, B, _BAND_EXTRAS, 3, lo=_BAND_LO,
                       modes=modes, freeze_fields=())
    if need > chunk_budget():
        return Admission.no(f"banded window set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_wave2d_band(grid, shape, n_inner: int, dtype,
                    interpret: bool = False, kmax: int = 8,
                    bands=(8, 16)):
    """Largest admissible `(K, B)` for the banded tier
    (`_vmem.fit_banded`); None when none applies."""
    return fit_banded(
        lambda K, B: wave2d_banded_supported(grid, tuple(shape), K,
                                             n_inner, dtype, B=B,
                                             interpret=interpret),
        kmax, bands=bands)


def fused_wave2d_banded_steps(P, Vx, Vy, *, n_inner: int, K: int, B: int,
                              dx, dy, dt, rho, bulk,
                              interpret: bool = False):
    """Advance `n_inner // K` full K-step chunks through the STREAMING
    banded realization: the band core is derived from the coupled
    full-window update by :func:`chunk_engine.band_core_from_window`
    (margin loss 2 per iteration), swept over x-row bands with the
    engine's rolling window.  Same entry contract as
    :func:`fused_wave2d_chunk_steps`."""
    from .. import shared

    grid = shared.global_grid()
    modes = dim_modes(grid)[:2]
    E = 2 * K
    shapes = _field_shapes(P.shape)
    ols = field_ols(grid, shapes)
    kw = dict(dx=dx, dy=dy, dt=dt, rho=rho, bulk=bulk)
    band_update = band_core_from_window(_window_core(kw), _BAND_LO)

    def one(P, Vx, Vy):
        exts = extend_fields([P, Vx, Vy], ols, E, grid, modes)
        return streaming_chunk_call(
            list(exts), [], K=K, B=B, modes=modes, grid=grid, ols=ols,
            shapes=shapes, E=E, band_update=band_update,
            extras=_BAND_EXTRAS, freeze_fields=(), lo=_BAND_LO,
            interpret=interpret)

    *S, done = run_chunks((P, Vx, Vy), n_inner=n_inner, K=K, one_chunk=one)
    return (*S, done)
