"""One-pass in-place Pallas halo writer — deterministic assembly for
lane-dimension halos.

Why this kernel exists: on TPU, writing the two outer planes of the minor
(lane) dimension is tile-granular — the Mosaic DMA engine only moves
tile-aligned HBM windows (sublane slices in multiples of the sublane tile,
lane slices in multiples of 128; a single-plane HBM DMA fails to compile
with "Slice shape along dimension must be aligned to tiling").  Any update
that materializes a lane-dim halo therefore costs a read-modify-write of
every tile column containing the halo lanes; at a 256-lane local size that
is ALL columns, i.e. one full read+write pass of the block (~128 MB at
256^3 f32 — measured 203 us = 630 GB/s, the same rate a pure in-place
Pallas copy and the audited mega-kernel sustain on v5e).  This is the TPU
analog of the reference's maximally-strided dim-1 plane, which gets its own
custom kernel for the same reason (`/root/reference/src/update_halo.jl:
439-462`).

XLA can express the same one-pass update (masked-select chain or aligned
DUS), but its layout assignment is a compile lottery: the identical update
program measured anywhere from 171 us to 516 us across surrounding-code
variations at 256^3 f32 — sometimes inserting whole-array relayout copies
({2,0,1}/{1,0,2} layouts) around minor-dim plane extraction, and grouped
multi-field calls went superlinear (4 fields = 2.2x the cost of 4 x
1 field).  This kernel pins the strategy: ONE aliased in-place RMW pass,
patching every participating dimension in dimension order (later dims win
the shared corner cells — the reference's sequential-overwrite semantics,
`/root/reference/src/update_halo.jl:36,130`), with per-field cost exactly
one block pass (multi-field grouped calls scale linearly), and bf16 at half
the f32 cost (101 us) instead of 1.5x.

Per-dimension source modes:
  - ``("ext", first, last)`` — dense squeezed 2-D received planes (what
    `ppermute` delivers), or any XLA expression (e.g. lazy keepdims slices
    for the dim-0 self-wrap sources, squeezed — free for the major dim).
  - ``("wrap", ol)`` — single-device periodic self-wrap: halo rows are
    copied from the block's own inner send planes (`ol-1` / `s-ol`) INSIDE
    VMEM, so the lane/sublane planes never materialize in HBM at all (the
    pack-side relayout tax is zero).  Only valid for dims >= 1 (dim 0 wrap
    sources cross grid blocks; callers pass them as lazy "ext" slices).

Used by the halo engine whenever the lane dimension participates in the
update on TPU; the engine keeps XLA's aligned-DUS for sublane/major-only
halo sets (boundary-slab in-place writes, ~20 us at 256^3 — a full pass
would be a 10x regression there).  When the lane halo is EXCHANGED (z-split
meshes) and spans more than two tile columns, `_write_dim2` RMWs only the
two dirty columns instead of the full pass — `2*128/n2` of the block;
measured 205 us vs 403 at (256,256,512) f32, the win growing linearly in
`n2` (self-wrap z keeps the one-pass writer: its in-block sources live in
the other dirty column and cross-column side reads would erase the
saving).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

_VMEM_LIMIT = 100 * 1024 * 1024
# Element sizes the writers handle: 32-bit natively; bf16/f16 round-trip
# through f32 for the lane-dim plane expand (Mosaic: "Insertion of minor dim
# that is not a no-op only supported for 32-bit types"), which is exact.
# 64-bit non-complex dtypes (the reference's Julia-default Float64) run the
# SAME 32-bit kernels on a lane-paired uint32 bitcast view — `(n0,n1,n2)`
# f64 reinterpreted as `(n0,n1,2*n2)` u32 (a free metadata reshape, exact
# by construction): each f64 halo lane becomes a pair of u32 lanes, so the
# lane-dim writes split into word-wise single-lane writes and everything
# else is untouched geometry (see `_u64_view`/`_u64_specs`).  complex64
# (the other 8-byte dtype) has no paired view and takes the XLA fallback
# plans; complex128 (16 bytes) is outside `_EXPAND_OK` entirely.
#
# CAVEAT (round 4, pinned by on-chip attempts): current XLA:TPU cannot
# compile the view — its x64 rewriter lacks 64-bit `bitcast-convert`
# ("rewriting is not implemented: bitcast-convert u64[...]"), and native
# f64 pallas_call is rejected by Mosaic — so the engine routes hardware
# f64 to the deterministic aligned-DUS XLA plan instead
# (`igg.halo._writer_dims`); the u32 path stays fully tested through the
# interpret seam, ready for a toolchain that accepts either form.
_EXPAND_OK = (2, 4, 8)


def _is_u64(dtype) -> bool:
    import numpy as np

    return np.dtype(dtype).itemsize == 8 and np.dtype(dtype).kind != "c"


def _u64_view(A):
    """f64/i64 block `(n0, n1, n2)` -> u32 view `(n0, n1, 2*n2)` (bitcast +
    trailing-dims merge: metadata only)."""
    import jax
    import jax.numpy as jnp

    bits = jax.lax.bitcast_convert_type(A, jnp.uint32)
    return bits.reshape(A.shape[0], A.shape[1], A.shape[2] * 2)


def _u64_unview(B, dtype):
    import jax

    n0, n1, m = B.shape
    return jax.lax.bitcast_convert_type(B.reshape(n0, n1, m // 2, 2), dtype)


def _u64_specs(specs):
    """Transform writer specs to the u32 lane-paired view: dim-0/1 planes
    merge their trailing (lane) axis with the word axis; dim-2 entries
    become word-pair modes (`ext2`: four single-word planes; `wrap2`:
    doubled lane positions)."""
    import jax
    import jax.numpy as jnp

    def rows(p):
        bits = jax.lax.bitcast_convert_type(p, jnp.uint32)
        return bits.reshape(p.shape[0], p.shape[1] * 2)

    out = []
    for s in specs:
        d = s[0]
        if d < 2:
            out.append((d, s[1], rows(s[2]), rows(s[3])) if s[1] == "ext"
                       else s)
        elif s[1] == "ext":
            fb = jax.lax.bitcast_convert_type(s[2], jnp.uint32)
            lb = jax.lax.bitcast_convert_type(s[3], jnp.uint32)
            out.append((2, "ext2", fb[..., 0], fb[..., 1],
                        lb[..., 0], lb[..., 1]))
        else:
            out.append((2, "wrap2", s[2]))
    return out


def _pick_bx(n0: int, n1: int, n2: int, itemsize: int) -> int:
    """Largest power-of-two block row count <= 32 that divides n0 and keeps
    the double-buffered in+out blocks comfortably inside VMEM."""
    bx = 1
    while (n0 % (bx * 2) == 0 and bx * 2 <= 32
           and 4 * (bx * 2) * n1 * n2 * itemsize <= _VMEM_LIMIT // 2):
        bx *= 2
    return bx


def _dtype_ok(dtype, interpret: bool) -> bool:
    """Shared dtype eligibility: 16/32-bit anywhere; 64-bit non-complex
    only in interpret mode (the u32 lane-paired view is blocked on real
    hardware by the XLA:TPU x64 rewriter — see the module caveat; the
    itemsize-8 complex64 has no paired view at all)."""
    import numpy as np

    itemsize = np.dtype(dtype).itemsize
    if itemsize not in _EXPAND_OK:
        return False
    if itemsize == 8:
        return interpret and _is_u64(dtype)
    return True


def lane_dispatch(shape, dtype, dims, wraps) -> Tuple[bool, int]:
    """THE dirty-column-vs-one-pass dispatch decision for lane-active halo
    sets — returns `(use_col, bx)`: whether the two-dirty-column chain
    serves the set, and the x-block row count the lane-dim writer tiles
    with (picked against one 128-lane column on the dirty-column path,
    against the full block on the one-pass path).  Single source consumed
    by BOTH the runtime dispatcher (`write_lane_active`) and the engine
    gate (`ext_planes_supported`), so the gate provably prices the block
    shapes the writer will actually emit — previously the two sides
    duplicated these conditions and agreed only by accident (ADVICE r5
    item 2)."""
    import numpy as np

    n0, n1, n2 = shape
    col = lane_columns_writable(shape, dtype, dims, wraps)
    return col, _pick_bx(n0, n1, 128 if col else n2,
                         np.dtype(dtype).itemsize)


def ext_planes_supported(shape, dtype, ext_dims, dims=None,
                         wraps=frozenset()) -> bool:
    """Whether Mosaic accepts the writers' partial-grid BlockSpecs for the
    received (ext) planes of `ext_dims`: a plane array's own trailing dim
    must be 128-lane aligned when the writer tiles it with a partial
    `(bx, .)` block — dim-1 planes are `(n0, n2)` cut as `(bx, n2)` and
    dim-2 planes `(n0, n1)` cut as `(bx, n1)` ("last two dimensions of
    your block shape [must be] divisible by 8 and 128 respectively, or be
    equal to the full array dims").  Dim-0 planes are passed whole and are
    exempt, as is the whole field when `bx == n0` (full-block specs).
    Staggered fields (`n+1` extents) with exchanged sublane/lane dims fail
    this — caught by the round-5 v5p-64 AOT schedule study, where the
    Stokes overlap program crashed Mosaic lowering — and take the XLA
    plans instead.

    `dims`/`wraps` are the FULL spec dim list and wrap set the runtime
    dispatcher will see (they feed the shared :func:`lane_dispatch`, so
    the bx priced here is the bx the writer uses); `dims` defaults to
    `ext_dims` for callers without wrap-mode dims."""
    import numpy as np

    n0, n1, n2 = shape
    if not any(d in ext_dims for d in (1, 2)):
        return True
    itemsize = np.dtype(dtype).itemsize
    ts = _sublane_tile(itemsize)

    def bx_ok(bx):
        # Partial `(bx, .)` plane blocks put bx on the block's sublane dim
        # (staggered/odd n0 degrades bx to 1 — the Stokes Vx case); a
        # block equal to the full plane is always accepted.
        return bx == n0 or bx % ts == 0

    ok = True
    if 1 in ext_dims:
        ok = ok and n2 % 128 == 0 and bx_ok(_pick_bx(n0, n1, n2, itemsize))
    if 2 in ext_dims:
        # The exchanged-lane write runs `_write_dim2` when the dirty-column
        # conditions hold, the one-pass writer otherwise; the decision AND
        # the bx come from the same helper the dispatch consumes.
        _, bx2 = lane_dispatch(shape, dtype,
                               ext_dims if dims is None else dims, wraps)
        ok = ok and n1 % 128 == 0 and bx_ok(bx2)
    return ok


def halo_write_supported(shape, dtype, interpret: bool = False) -> bool:
    """The writer handles rank-3 blocks of >= 16-bit elements (16-bit lane
    expansion round-trips exactly through f32; 64-bit non-complex through
    the lane-paired u32 view, interpret mode only — see module caveat)."""
    if len(shape) != 3 or not _dtype_ok(dtype, interpret):
        return False
    n0, n1, n2 = shape
    return n0 >= 2 and n1 >= 2 and n2 >= 2


def _expand_minor(p, dtype):
    """`p[..., None]` that Mosaic accepts for 16-bit types."""
    import jax.numpy as jnp

    if jnp.dtype(dtype).itemsize >= 4:
        return p[..., None]
    return p.astype(jnp.float32)[..., None].astype(dtype)


def slab_write_supported(shape, dtype, dims, interpret: bool = False) -> bool:
    """Whether the per-dim slab writers cover a halo set (no lane dim):
    rank-3, dim-1 updates need tile-aligned rows with distinct first/last
    tiles; dtype eligibility as in :func:`halo_write_supported`."""
    import numpy as np

    if len(shape) != 3 or (len(shape) - 1) in dims:
        return False
    if not _dtype_ok(dtype, interpret):
        return False
    ts = _sublane_tile(np.dtype(dtype).itemsize)
    if 1 in dims and (shape[1] % ts != 0 or shape[1] < 2 * ts):
        return False
    return shape[0] >= 2


def _sublane_tile(itemsize: int) -> int:
    from ..halo import _SUBLANE  # single source of truth for tile heights

    return _SUBLANE.get(itemsize, 8)



def _inplace_call(kernel, A, *, grid, in_specs, out_spec, alias, args,
                  interpret):
    """Shared `pallas_call` wrapper for the in-place writers: aliases `A`
    (the last operand) to the output, preserves shard_map varying-manual
    axes (vma) on the out aval, and applies the VMEM limit in compiled
    mode."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    vma = getattr(getattr(A, "aval", None), "vma", None)
    out_shape = (jax.ShapeDtypeStruct(A.shape, A.dtype, vma=vma) if vma
                 else jax.ShapeDtypeStruct(A.shape, A.dtype))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=list(in_specs),
        out_specs=out_spec,
        out_shape=out_shape,
        input_output_aliases={alias: 0},
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
    )(*args, A)


def _write_dim0(A, first, last, *, interpret: bool):
    """In-place overwrite of the two outer dim-0 planes (untiled dim: the
    blocks ARE the planes; ~2 plane writes, no RMW)."""
    from jax.experimental import pallas as pl

    n0, n1, n2 = A.shape

    def kernel(pf_ref, pq_ref, a_ref, o_ref):
        j = pl.program_id(0)

        @pl.when(j == 0)
        def _():
            o_ref[...] = pf_ref[...][None, :, :]

        @pl.when(j == 1)
        def _():
            o_ref[...] = pq_ref[...][None, :, :]

    return _inplace_call(
        kernel, A, grid=(2,),
        in_specs=[pl.BlockSpec((n1, n2), lambda j: (0, 0)),
                  pl.BlockSpec((n1, n2), lambda j: (0, 0)),
                  pl.BlockSpec((1, n1, n2), lambda j: (j * (n0 - 1), 0, 0))],
        out_spec=pl.BlockSpec((1, n1, n2), lambda j: (j * (n0 - 1), 0, 0)),
        alias=2, args=(first, last), interpret=interpret)


def _write_dim1(A, spec, *, interpret: bool):
    """In-place RMW of the two outer dim-1 (sublane) planes: only the two
    boundary sublane-tile slabs are touched (~`2*ts/n1` of the block).
    `spec` is `("ext", first, last)` with dense `(n0, n2)` planes or
    `("wrap", ol)` (source rows fetched from their slabs by extra refs)."""
    import numpy as np
    from jax import lax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n0, n1, n2 = A.shape
    ts = _sublane_tile(np.dtype(A.dtype).itemsize)
    bx = _pick_bx(n0, n1, n2, np.dtype(A.dtype).itemsize)
    nb = n0 // bx
    njb = n1 // ts
    wrap = spec[0] == "wrap"
    ol = spec[1] if wrap else None

    def kernel(s0_ref, s1_ref, a_ref, o_ref):
        j = pl.program_id(1)
        t = a_ref[...]
        idx = lax.broadcasted_iota(jnp.int32, t.shape, 1)
        if wrap:
            pf = s0_ref[:, (n1 - ol) % ts, :]
            pq = s1_ref[:, (ol - 1) % ts, :]
        else:
            pf = s0_ref[...]
            pq = s1_ref[...]

        @pl.when(j == 0)
        def _():
            o_ref[...] = jnp.where(idx == 0, pf[:, None, :], t)

        @pl.when(j == 1)
        def _():
            o_ref[...] = jnp.where(idx == ts - 1, pq[:, None, :], t)

    if wrap:
        # The wrap source rows are pre-sliced (tile-aligned slabs) at the
        # XLA level into fresh small buffers: passing `A` itself as an extra
        # operand of its own aliased in-place update makes XLA insert a
        # defensive whole-array copy (measured 427 us instead of ~25 us for
        # the xy self-wrap update at 256^3 f32).
        base0 = ((n1 - ol) // ts) * ts
        base1 = ((ol - 1) // ts) * ts
        s0 = lax.slice_in_dim(A, base0, base0 + ts, axis=1)
        s1 = lax.slice_in_dim(A, base1, base1 + ts, axis=1)
        in_specs = [pl.BlockSpec((bx, ts, n2), lambda i, j: (i, 0, 0)),
                    pl.BlockSpec((bx, ts, n2), lambda i, j: (i, 0, 0))]
        args = (s0, s1)
        alias = 2
    else:
        in_specs = [pl.BlockSpec((bx, n2), lambda i, j: (i, 0)),
                    pl.BlockSpec((bx, n2), lambda i, j: (i, 0))]
        args = (spec[1], spec[2])
        alias = 2
    in_specs.append(
        pl.BlockSpec((bx, ts, n2), lambda i, j: (i, j * (njb - 1), 0)))

    return _inplace_call(
        kernel, A, grid=(nb, 2), in_specs=in_specs,
        out_spec=pl.BlockSpec((bx, ts, n2),
                              lambda i, j: (i, j * (njb - 1), 0)),
        alias=alias, args=args, interpret=interpret)


def _write_dim2(A, zspec, *, bx: int = None, interpret: bool):
    """In-place RMW of the two outer lane-dim planes touching ONLY the two
    dirty 128-lane tile columns (`2*128/n2` of the block, vs the one-pass
    writer's full RMW).  Received dense planes only — self-wrap sources
    live inside the dirty columns of the OTHER grid step and would need
    whole-column side reads that erase the saving, so wrap-mode z stays on
    the one-pass writer.  `zspec` is `(2, "ext", first, last)` or the
    u32 lane-paired `(2, "ext2", fe, fo, le, lo)` (two word lanes per
    64-bit halo lane)."""
    import numpy as np
    from jax import lax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n0, n1, n2 = A.shape
    if bx is None:  # standalone use; the engine passes lane_dispatch's bx
        bx = _pick_bx(n0, n1, 128, np.dtype(A.dtype).itemsize)
    ncols = n2 // 128
    paired = zspec[1] == "ext2"
    planes = zspec[2:6] if paired else zspec[2:4]

    def kernel(*refs):
        *plane_refs, a_ref, o_ref = refs
        j = pl.program_id(1)
        t = a_ref[...]
        idx = lax.broadcasted_iota(jnp.int32, t.shape, 2)
        if paired:
            lo_lanes, hi_lanes = ((0, 1), (126, 127))
        else:
            lo_lanes, hi_lanes = ((0,), (127,))
        nlo = len(lo_lanes)

        @pl.when(j == 0)
        def _():
            u = t
            for lane_i, ref in zip(lo_lanes, plane_refs[:nlo]):
                u = jnp.where(idx == lane_i,
                              _expand_minor(ref[...], t.dtype), u)
            o_ref[...] = u

        @pl.when(j == 1)
        def _():
            u = t
            for lane_i, ref in zip(hi_lanes, plane_refs[nlo:]):
                u = jnp.where(idx == lane_i,
                              _expand_minor(ref[...], t.dtype), u)
            o_ref[...] = u

    nplanes = len(planes)
    return _inplace_call(
        kernel, A, grid=(n0 // bx, 2),
        in_specs=[pl.BlockSpec((bx, n1), lambda i, j: (i, 0))] * nplanes
        + [pl.BlockSpec((bx, n1, 128),
                        lambda i, j: (i, 0, j * (ncols - 1)))],
        out_spec=pl.BlockSpec((bx, n1, 128),
                              lambda i, j: (i, 0, j * (ncols - 1))),
        alias=nplanes, args=tuple(planes), interpret=interpret)


def lane_columns_writable(shape, dtype, dims, wraps) -> bool:
    """Whether the dirty-column lane writer (+ slab writers for the other
    dims) beats the one-pass writer: the lane dim must be exchanged (not
    self-wrap), span >2 aligned tile columns, and the remaining dims must
    be slab-eligible (delegated to :func:`slab_write_supported` so the two
    gates cannot diverge)."""
    n2 = shape[-1]
    lane = len(shape) - 1
    if lane in wraps or n2 % 128 != 0 or n2 < 3 * 128:
        return False
    return slab_write_supported(shape, dtype,
                                [d for d in dims if d != lane])


def write_lane_active(A, specs, wraps, *, interpret: bool = False):
    """Assembly dispatch for lane-active halo sets: the dirty-column chain
    (slab writers for dims 0/1, then `_write_dim2` RMWing only the two
    dirty lane columns) when the lane halo is exchanged and spans >2 tile
    columns, the one-pass writer otherwise.  Shared by the halo engine and
    `assemble_field` (hide_communication).  64-bit fields run on the u32
    lane-paired view (module docstring)."""
    if _is_u64(A.dtype):
        B = _write_lane_active_raw(_u64_view(A), _u64_specs(specs), wraps,
                                   interpret=interpret)
        return _u64_unview(B, A.dtype)
    return _write_lane_active_raw(A, specs, wraps, interpret=interpret)


def _write_lane_active_raw(A, specs, wraps, *, interpret: bool = False):
    lane = A.ndim - 1
    zspec = [sp for sp in specs if sp[0] == lane]
    dims = [sp[0] for sp in specs]
    use_col, bx = lane_dispatch(A.shape, A.dtype, dims, wraps)
    if zspec and zspec[0][1] in ("ext", "ext2") and use_col:
        rest = [sp for sp in specs if sp[0] != lane]
        B = (_halo_write_slabs_raw(A, rest, interpret=interpret)
             if rest else A)
        return _write_dim2(B, zspec[0], bx=bx, interpret=interpret)
    return _halo_write_raw(A, specs, interpret=interpret)


def halo_write_slabs(A, specs: Sequence[Tuple], *, interpret: bool = False):
    """Non-lane halo assembly: chain per-dim in-place slab writers in
    dimension order (later dims win corners).  Touches only the dirty
    boundary slabs (~20-30 us at 256^3 vs a 200 us full pass), with cost
    strictly linear in the number of fields.  Dim-0 wrap sources must be
    passed as lazy "ext" slices (they cross grid blocks).  64-bit fields
    run on the u32 lane-paired view (module docstring)."""
    if _is_u64(A.dtype):
        B = _halo_write_slabs_raw(_u64_view(A), _u64_specs(specs),
                                  interpret=interpret)
        return _u64_unview(B, A.dtype)
    return _halo_write_slabs_raw(A, specs, interpret=interpret)


def _halo_write_slabs_raw(A, specs: Sequence[Tuple], *,
                          interpret: bool = False):
    for s in specs:
        d = s[0]
        if d == 0:
            if s[1] != "ext":
                raise ValueError("dim-0 wrap sources cross grid blocks; "
                                 "pass them as lazy 'ext' slices")
            A = _write_dim0(A, s[2], s[3], interpret=interpret)
        elif d == 1:
            A = _write_dim1(A, s[1:], interpret=interpret)
        else:
            raise ValueError("lane dim: use halo_write")
    return A


def halo_write(A, specs: Sequence[Tuple], *, interpret: bool = False):
    """Return `A` with its outer halo planes overwritten, in one in-place
    RMW pass (input buffer aliased to the output).

    `specs` is a list of `(dim, mode, ...)` entries in increasing dim order:
    `(d, "ext", first, last)` with dense 2-D planes (the squeezed plane
    shape of dim `d`), or `(d, "wrap", ol)` for `d >= 1`.  64-bit
    non-complex fields run on the u32 lane-paired view (module docstring).
    """
    if _is_u64(A.dtype):
        B = _halo_write_raw(_u64_view(A), _u64_specs(specs),
                            interpret=interpret)
        return _u64_unview(B, A.dtype)
    return _halo_write_raw(A, specs, interpret=interpret)


def _halo_write_raw(A, specs: Sequence[Tuple], *, interpret: bool = False):
    import numpy as np
    from jax import lax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n0, n1, n2 = A.shape
    bx = _pick_bx(n0, n1, n2, np.dtype(A.dtype).itemsize)
    nb = n0 // bx

    ext_planes: List = []
    for s in specs:
        if s[1] == "ext":
            ext_planes += [s[2], s[3]]
        elif s[1] == "ext2":
            ext_planes += list(s[2:6])
        elif s[0] == 0:
            raise ValueError("dim-0 wrap sources cross grid blocks; pass "
                             "them as lazy 'ext' slices")

    def kernel(*refs):
        *plane_refs, a_ref, o_ref = refs
        i = pl.program_id(0)
        t = a_ref[...]
        k = 0
        for s in specs:
            d = s[0]
            if s[1] == "ext":
                pf, pq = plane_refs[k][...], plane_refs[k + 1][...]
                k += 2
                if d == 0:
                    idx = lax.broadcasted_iota(jnp.int32, t.shape, 0) + i * bx
                    t = jnp.where(idx == 0, pf[None, :, :], t)
                    t = jnp.where(idx == n0 - 1, pq[None, :, :], t)
                elif d == 1:
                    idx = lax.broadcasted_iota(jnp.int32, t.shape, 1)
                    t = jnp.where(idx == 0, pf[:, None, :], t)
                    t = jnp.where(idx == n1 - 1, pq[:, None, :], t)
                else:
                    idx = lax.broadcasted_iota(jnp.int32, t.shape, 2)
                    t = jnp.where(idx == 0, _expand_minor(pf, t.dtype), t)
                    t = jnp.where(idx == n2 - 1, _expand_minor(pq, t.dtype),
                                  t)
            elif s[1] == "ext2":
                # u32 lane-paired view: each 64-bit halo lane is two
                # word lanes, written from four single-word planes.
                idx = lax.broadcasted_iota(jnp.int32, t.shape, 2)
                for lane_i, ref_j in ((0, k), (1, k + 1),
                                      (n2 - 2, k + 2), (n2 - 1, k + 3)):
                    t = jnp.where(idx == lane_i,
                                  _expand_minor(plane_refs[ref_j][...],
                                                t.dtype), t)
                k += 4
            elif s[1] == "wrap2":
                # u32 lane-paired self-wrap: 64-bit source lane n2-ol
                # (resp. ol-1) is word-lane pair 2*(n2-ol) (resp. 2ol-2).
                ol = s[2]
                idx = lax.broadcasted_iota(jnp.int32, t.shape, 2)
                for lane_i, src in ((0, n2 - 2 * ol), (1, n2 - 2 * ol + 1),
                                    (n2 - 2, 2 * ol - 2),
                                    (n2 - 1, 2 * ol - 1)):
                    t = jnp.where(idx == lane_i, t[:, :, src:src + 1], t)
            else:
                ol = s[2]
                if d == 1:
                    idx = lax.broadcasted_iota(jnp.int32, t.shape, 1)
                    t = jnp.where(idx == 0, t[:, n1 - ol:n1 - ol + 1, :], t)
                    t = jnp.where(idx == n1 - 1, t[:, ol - 1:ol, :], t)
                else:
                    idx = lax.broadcasted_iota(jnp.int32, t.shape, 2)
                    t = jnp.where(idx == 0, t[:, :, n2 - ol:n2 - ol + 1], t)
                    t = jnp.where(idx == n2 - 1, t[:, :, ol - 1:ol], t)
        o_ref[...] = t

    in_specs = []
    for s in specs:
        if s[1] not in ("ext", "ext2"):
            continue
        d = s[0]
        if d == 0:
            bs = pl.BlockSpec((n1, n2), lambda i: (0, 0))
        elif d == 1:
            bs = pl.BlockSpec((bx, n2), lambda i: (i, 0))
        else:
            bs = pl.BlockSpec((bx, n1), lambda i: (i, 0))
        in_specs += [bs] * (2 if s[1] == "ext" else 4)
    in_specs.append(pl.BlockSpec((bx, n1, n2), lambda i: (i, 0, 0)))

    return _inplace_call(
        kernel, A, grid=(nb,), in_specs=in_specs,
        out_spec=pl.BlockSpec((bx, n1, n2), lambda i: (i, 0, 0)),
        alias=len(ext_planes), args=tuple(ext_planes), interpret=interpret)
