"""K-step temporal-blocking (trapezoid chunk) tier for HM3D — the
two-field coupled instance of the shared K-step chunk engine
(`igg.ops.chunk_engine`), closing the "HM3D has no temporal-blocking
tier" gap (ROADMAP item 1).

The coupled hydro-mechanical update (`hm3d.step_core`) is radius-1 in
BOTH fields: `dPe` reads `Pe`/`phi` at +-1 (face permeabilities and
Darcy fluxes), and `dphi` reads the freshly-updated `Pe` at the SAME
cell (Gauss-Seidel coupling, no extra radius) — so the validity front
shrinks ONE row per extended side per step and the margin is `E = K`,
the diffusion trapezoid's geometry, not the Stokes `2K` one.

Chunk structure (all engine machinery):

  1. Once per K-step chunk, both fields are extended `E = K` deep along
     every exchanged dimension by ONE grouped `ppermute` pair per dim
     (the two fields share shapes and ride one wire —
     `chunk_engine.extend_fields`), dimension-sequentially so corners
     arrive via the later neighbors' earlier-dim extensions.
  2. K coupled steps run on the extended windows with NO exchange.
     Open dims re-freeze BOTH fields' boundary planes from the
     chunk-entry buffers (the per-step path's no-write semantics: the
     composition writes interior cells only, so open boundary planes
     never change) — `freeze_fields = (0, 1)`.
  3. The central local blocks are sliced out.

Two realizations of the same window dynamics:

  - **Pure-XLA window path** (`_window_steps_xla`, the engine's
    `window_chunk_xla`): interpret mode / CPU meshes / the driver
    dryrun — pinned per-step-equivalent on 8-device periodic, open, and
    mixed meshes by `tests/test_chunk_engine.py`.
  - **Mosaic chunk kernel**: the engine's generic VMEM-resident banded
    kernel (`chunk_engine.resident_chunk_call`) with this family's
    config — both fields resident for the whole chunk, in-place x-row
    bands with one-row lag carry, high margin 1 per field.  HBM traffic
    per chunk: ONE read + ONE write of both fields — `(2R+2W)/K` per
    step against the per-step fused kernel's `2R+2W`.  TPU-gated
    equivalence test in `tests/test_mega_tpu.py`; verify-on-first-use
    guards it in production dispatch (`igg.degrade`).

VMEM is the K-bound (both extended fields resident): ~24 MB at 128^3
f32 K=8, ~44 MB at 160^3 — `hm3d_trapezoid_supported` does the
accounting against the shared budget authority
(`igg.ops._vmem.chunk_budget`) and `fit_hm3d_K` (`_vmem.fit_chunk_K`)
picks the largest admissible K.

The compiled dispatcher (`hm3d.make_step`) runs one per-step fused
kernel FIRST (consuming the entry halos — the exchange-fresh window
contract), then `(n_inner - 1) // K` chunks, then the remainder through
the per-step kernel.
"""

from __future__ import annotations

from functools import partial

from ._vmem import banded_vmem, chunk_budget, fit_banded, fit_chunk_K
from .chunk_engine import (admit_banded_geometry, admit_chunk_common,
                           admit_send_slabs, admit_sublane_extension,
                           dim_modes, ext_shape, extend_fields, field_ols,
                           pad8 as _pad8, pad128 as _pad128,
                           resident_chunk_call, run_chunks,
                           streaming_chunk_call, window_chunk_xla)

_BX = 8          # x band height of the resident chunk kernel


def _vmem_need(shape, K, modes, itemsize: int = 4) -> int:
    """Modeled VMEM bytes of the resident chunk kernel at depth K: the
    two tile-padded K-extended fields, the lag rows, the open-dim freeze
    planes, and a 2x-margin band-temporary term for `step_core`'s
    permeability/flux chain (~12 band-row intermediates; the 2x absorbs
    Mosaic's own scratch — the `stokes_trapezoid._vmem_need` calibration
    style)."""
    E = K
    ext = ext_shape(shape, E, modes)
    a, b, c = ext
    row = _pad8(b) * _pad128(c) * itemsize
    need = 2 * a * row                         # both resident fields
    need += 2 * 2 * row                        # lag rows (2 slots x 2)
    for d in range(3):                         # freeze planes (2 fields)
        if modes[d] in ("oext", "frozen"):
            plane = (a, b, c)[:d] + (a, b, c)[d + 1:]
            need += (2 * 2 * _pad8(plane[0]) * _pad128(plane[1])
                     * itemsize)
    need += 2 * 12 * (_BX + 2) * row           # band temporaries, 2x margin
    return need


def hm3d_trapezoid_supported(grid, shape, K: int, n_inner: int, dtype,
                             interpret: bool = False,
                             allow_open: bool = True):
    """Whether the K-step HM3D chunk tier applies: overlap-2 grid (the
    per-step fused kernel's prerequisite — it runs the warm-up and
    remainder steps), at least one full chunk, K-deep send slabs inside
    every extended dimension's block, the resident kernel's band/tile
    geometry, and the resident working set within the VMEM budget.  Both
    realizations take the same gates (the trapezoid convention), so
    interpret meshes exercise the compiled tier's exact admission
    decisions.  Returns an :class:`igg.degrade.Admission`."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if tuple(shape) != tuple(grid.nxyz):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz)}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = dim_modes(grid)
    if not allow_open and any(m in ("oext", "frozen") for m in modes):
        return Admission.no(f"open (non-periodic) dimensions {modes} and "
                            f"the caller did not pass allow_open=True")
    E = K
    S0, S1, S2 = shape
    if S0 % _BX != 0 or S0 < 2 * _BX:
        return Admission.no(f"x extent {S0} not band-divisible "
                            f"(needs S0 % {_BX} == 0, S0 >= {2 * _BX})")
    if S1 % 8 != 0 or S2 % 128 != 0:
        return Admission.no(f"local y/z extents ({S1}, {S2}) not Mosaic "
                            f"tile-aligned (y % 8, z % 128)")
    if modes[0] != "frozen" and (2 * E) % _BX != 0:
        # S0e = S0 + 2E must stay band-divisible.
        return Admission.no(f"extended x span S0 + {2 * E} not "
                            f"band-divisible by {_BX}")
    sub = admit_sublane_extension(E, modes)
    if sub is not None:
        # Central y window slice offset on sublane tiles (the shared
        # engine gate — a structured refusal where Mosaic would crash
        # deep in lowering).
        return sub
    shapes = [tuple(shape), tuple(shape)]
    ols = field_ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    need = _vmem_need(shape, K, modes)
    if need > chunk_budget():
        return Admission.no(f"resident working set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_hm3d_K(grid, shape, n_inner: int, dtype,
               interpret: bool = False, kmax: int = 8) -> int:
    """Largest admissible chunk depth K <= kmax (halving, >= 2;
    `_vmem.fit_chunk_K`); 0 when none applies."""
    return fit_chunk_K(
        lambda K: hm3d_trapezoid_supported(grid, tuple(shape), K, n_inner,
                                           dtype, interpret=interpret),
        kmax)


# ---------------------------------------------------------------------------
# The family physics: full-window core + per-band value computation
# ---------------------------------------------------------------------------

def _core(kw):
    """The full-window coupled update: `hm3d.compute_step` (interior
    cells of both fields, stale edges) — the single source of arithmetic
    truth shared with the XLA composition and the per-step fused
    kernel."""
    def core(Pe, phi):
        from ..models.hm3d import compute_step

        return compute_step(Pe, phi, **kw)

    return core


def _band_update(Wpe, Wphi, *, bx, kw):
    """New band values (rows [a, a+bx), window row offset 1) from
    margin-1 windows of both fields — the `hm3d_mega`/`hm3d_pallas`
    assembly: interior cells take `step_core` increments, y/z edge rows
    keep their old values (owned by the band-halo wrap/freeze).  Pure
    values: shared by the engine's resident kernel and the banded-scheme
    simulation test."""
    import jax.numpy as jnp

    from ..models.hm3d import step_core

    dPe, dphi = step_core(Wpe, Wphi, **kw)
    outs = []
    for W, dF in ((Wpe, dPe), (Wphi, dphi)):
        o = W[1:1 + bx]
        inner = o[:, 1:-1, 1:-1] + dF[0:bx]
        mid = jnp.concatenate([o[:, 1:-1, 0:1], inner, o[:, 1:-1, -1:]],
                              axis=2)
        outs.append(jnp.concatenate([o[:, 0:1, :], mid, o[:, -1:, :]],
                                    axis=1))
    return tuple(outs)


def _window_steps_xla(Pee, phie, *, K, E, modes, grid, kw, ols, shapes):
    """Pure-XLA realization of the chunk evolution (interpret mode / CPU
    meshes): the engine's generic window loop with both fields frozen on
    open dims."""
    return window_chunk_xla((Pee, phie), K=K, E=E, modes=modes, grid=grid,
                            ols=ols, shapes=shapes, freeze_fields=(0, 1),
                            core=_core(kw))


def _chunk_call(exts, *, K, modes, grid, kw, ols, shapes, interpret=False):
    """Advance K coupled steps on the extended buffers; returns the two
    central local blocks (engine resident kernel / XLA window)."""
    E = K

    def window():
        return _window_steps_xla(*exts, K=K, E=E, modes=modes, grid=grid,
                                 kw=kw, ols=ols, shapes=shapes)

    return resident_chunk_call(
        list(exts), [], K=K, bx=_BX, modes=modes, grid=grid, ols=ols,
        shapes=shapes, E=E, band_update=partial(_band_update, kw=kw),
        extras=(1, 1), freeze_fields=(0, 1), window_fallback=window,
        interpret=interpret)


# ---------------------------------------------------------------------------
# Chunk driver
# ---------------------------------------------------------------------------

def fused_hm3d_trapezoid_steps(Pe, phi, *, n_inner: int, K: int,
                               dx, dy, dz, dt, phi0, npow, eta,
                               interpret: bool = False):
    """Advance `n_inner // K` full K-step chunks (the caller handles the
    warm-up step before and the per-K remainder after, through the
    per-step fused kernel); returns `(Pe, phi, steps_done)`.

    Entry contract: exchange-fresh halos (any state produced by
    `update_halo`, a model step, or a previous chunk).  Call inside SPMD
    code (`igg.sharded` / shard_map); fully-frozen 1-device grids also
    run under plain `jax.jit`."""
    from .. import shared

    grid = shared.global_grid()
    modes = dim_modes(grid)
    E = K
    shapes = [Pe.shape, phi.shape]
    ols = field_ols(grid, shapes)
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)

    def one(Pe, phi):
        exts = extend_fields([Pe, phi], ols, E, grid, modes)
        return _chunk_call(exts, K=K, modes=modes, grid=grid, kw=kw,
                           ols=ols, shapes=shapes, interpret=interpret)

    *S, done = run_chunks((Pe, phi), n_inner=n_inner, K=K, one_chunk=one)
    return (*S, done)


# ---------------------------------------------------------------------------
# The STREAMING banded tier (hm3d.banded): rolling-window realization for
# the shapes the resident kernel's K-bound refuses
# ---------------------------------------------------------------------------

def hm3d_banded_supported(grid, shape, K: int, n_inner: int, dtype,
                          B: int = 8, interpret: bool = False):
    """Whether the STREAMING banded HM3D chunk tier applies at depth K /
    band B: the resident tier's structural gates minus the K-bound —
    the rolling window's footprint is O(B), so this is the rung that
    admits at the headline shapes `fit_hm3d_K` refuses.  Returns an
    :class:`igg.degrade.Admission`."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if grid.overlaps != (2, 2, 2):
        return Admission.no(f"grid overlaps {grid.overlaps} != (2, 2, 2)")
    if tuple(shape) != tuple(grid.nxyz):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz)}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = dim_modes(grid)
    E = K
    shapes = [tuple(shape), tuple(shape)]
    ols = field_ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    geo = admit_banded_geometry(shapes, E, modes, B=B, extras=(1, 1),
                                interpret=interpret)
    if geo is not None:
        return geo
    exts = [ext_shape(s, E, modes) for s in shapes]
    need = banded_vmem(exts, B, (1, 1), 2, modes=modes,
                       freeze_fields=(0, 1))
    if need > chunk_budget():
        return Admission.no(f"banded window set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_hm3d_band(grid, shape, n_inner: int, dtype,
                  interpret: bool = False, kmax: int = 8,
                  bands=(8, 16)):
    """Largest admissible `(K, B)` for the banded tier
    (`_vmem.fit_banded`); None when none applies."""
    return fit_banded(
        lambda K, B: hm3d_banded_supported(grid, tuple(shape), K, n_inner,
                                           dtype, B=B, interpret=interpret),
        kmax, bands=bands)


def fused_hm3d_banded_steps(Pe, phi, *, n_inner: int, K: int, B: int,
                            dx, dy, dz, dt, phi0, npow, eta,
                            interpret: bool = False):
    """Advance `n_inner // K` full K-step chunks through the STREAMING
    banded realization (`chunk_engine.streaming_chunk_call` — same
    `_band_update` core and margins as the resident tier, rolling VMEM
    window of band depth B); returns `(Pe, phi, steps_done)`.  Same
    entry contract as :func:`fused_hm3d_trapezoid_steps`."""
    from .. import shared

    grid = shared.global_grid()
    modes = dim_modes(grid)
    E = K
    shapes = [Pe.shape, phi.shape]
    ols = field_ols(grid, shapes)
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=phi0, npow=npow, eta=eta)

    def one(Pe, phi):
        exts = extend_fields([Pe, phi], ols, E, grid, modes)
        return streaming_chunk_call(
            list(exts), [], K=K, B=B, modes=modes, grid=grid, ols=ols,
            shapes=shapes, E=E, band_update=partial(_band_update, kw=kw),
            extras=(1, 1), freeze_fields=(0, 1), interpret=interpret)

    *S, done = run_chunks((Pe, phi), n_inner=n_inner, K=K, one_chunk=one)
    return (*S, done)
