"""Hand-optimized TPU kernels for the hot ops (Pallas).

The reference's "native surface" is its CUDA pack/unpack kernels and SIMD
copies (`/root/reference/src/update_halo.jl:439-462,555-563`); on TPU the
equivalent layer is Pallas kernels that fuse the stencil update with halo
maintenance so each time step touches HBM exactly once per array.
"""

from .diffusion_pallas import (
    diffusion_compute,
    fused_diffusion_step,
    fused_diffusion_steps,
    pallas_supported,
)
from .stencil import interior_add
from .hm3d_pallas import (fused_hm3d_step, fused_hm3d_steps,
                          hm3d_pallas_supported)
from .stokes_pallas import fused_stokes_iteration, stokes_pallas_supported
from .stokes_trapezoid import (fit_stokes_K, fused_stokes_trapezoid_iters,
                               stokes_trapezoid_supported)

__all__ = ["diffusion_compute", "fit_stokes_K", "fused_diffusion_step",
           "fused_diffusion_steps", "fused_hm3d_step", "fused_hm3d_steps",
           "fused_stokes_iteration", "fused_stokes_trapezoid_iters",
           "hm3d_pallas_supported", "interior_add", "pallas_supported",
           "stokes_pallas_supported", "stokes_trapezoid_supported"]
