"""Hand-optimized TPU kernels for the hot ops (Pallas).

The reference's "native surface" is its CUDA pack/unpack kernels and SIMD
copies (`/root/reference/src/update_halo.jl:439-462,555-563`); on TPU the
equivalent layer is Pallas kernels that fuse the stencil update with halo
maintenance so each time step touches HBM exactly once per array.
"""

from .diffusion_pallas import (
    diffusion_compute,
    fused_diffusion_step,
    fused_diffusion_steps,
    pallas_supported,
)
from .stencil import interior_add
from .hm3d_pallas import (fused_hm3d_step, fused_hm3d_steps,
                          hm3d_pallas_supported)
from .stokes_pallas import fused_stokes_iteration, stokes_pallas_supported
from .stokes_trapezoid import (fit_stokes_K, fit_stokes_band,
                               fused_stokes_banded_iters,
                               fused_stokes_trapezoid_iters,
                               stokes_banded_supported,
                               stokes_trapezoid_supported)
from .hm3d_trapezoid import (fit_hm3d_K, fit_hm3d_band,
                             fused_hm3d_banded_steps,
                             fused_hm3d_trapezoid_steps,
                             hm3d_banded_supported,
                             hm3d_trapezoid_supported)
from .wave2d_pallas import (fit_wave2d_K, fit_wave2d_band,
                            fused_wave2d_banded_steps,
                            fused_wave2d_chunk_steps,
                            fused_wave2d_step, fused_wave2d_steps,
                            wave2d_banded_supported,
                            wave2d_chunk_supported, wave2d_pallas_supported)
from .diffusion_trapezoid import (diffusion_banded_supported,
                                  fit_diffusion_band,
                                  fused_diffusion_banded_steps)

__all__ = ["diffusion_banded_supported", "diffusion_compute",
           "fit_diffusion_band", "fit_hm3d_K", "fit_hm3d_band",
           "fit_stokes_K", "fit_stokes_band", "fit_wave2d_K",
           "fit_wave2d_band", "fused_diffusion_banded_steps",
           "fused_diffusion_step", "fused_diffusion_steps",
           "fused_hm3d_banded_steps", "fused_hm3d_step",
           "fused_hm3d_steps", "fused_hm3d_trapezoid_steps",
           "fused_stokes_banded_iters", "fused_stokes_iteration",
           "fused_stokes_trapezoid_iters", "fused_wave2d_banded_steps",
           "fused_wave2d_chunk_steps", "fused_wave2d_step",
           "fused_wave2d_steps", "hm3d_banded_supported",
           "hm3d_pallas_supported", "hm3d_trapezoid_supported",
           "interior_add", "pallas_supported", "stokes_banded_supported",
           "stokes_pallas_supported", "stokes_trapezoid_supported",
           "wave2d_banded_supported", "wave2d_chunk_supported",
           "wave2d_pallas_supported"]
