"""One-pass Pallas extraction of minor-dimension halo planes.

The TPU counterpart of the reference's custom pack kernels for its
worst-strided plane (`/root/reference/src/update_halo.jl:439-462`, thread
blocks re-shaped per dimension at `:341-353`): on TPU the worst case is the
sublane/lane (y/z) dimensions, where materializing a squeezed plane makes
XLA emit a separate relayout pass per plane over the source tiles (measured
491 us for the four y/z send planes of a 256^3 f32 block on v5e).  This
kernel streams the block through VMEM once and emits every requested plane
as a dense 2-D array (measured 92 us — the cost of one HBM read of the
block), including the in-kernel lane extraction for z planes.

Used by the halo engine when at least two minor-dim planes must be
materialized for a `ppermute` (z-split or y+z-split meshes); single planes
and untiled-dim (x) planes stay lazy XLA slices.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

# VMEM budget for the double-buffered input block.
_BLOCK_BYTES = 4 * 1024 * 1024


def pack_planes_supported(shape, dtype) -> bool:
    import numpy as np

    if len(shape) != 3:
        return False
    if np.dtype(dtype).itemsize > 4:
        # The in-kernel lane extraction is 32-bit territory in Mosaic;
        # 64-bit planes stay lazy XLA slices.
        return False
    s0, s1, s2 = shape
    return s0 >= 1 and s1 * s2 * 4 <= _BLOCK_BYTES


def _pick_bx(s0: int, s1: int, s2: int, itemsize: int) -> int:
    bx = 1
    while (s0 % (bx * 2) == 0
           and (bx * 2) * s1 * s2 * itemsize <= _BLOCK_BYTES):
        bx *= 2
    return bx


def pack_planes(A, reqs: Sequence[Tuple[int, int]]) -> List:
    """Extract the squeezed planes `[A[:, p, :] or A[:, :, p] for (d, p) in
    reqs]` (d in {1, 2}) in a single pass over `A`.  TPU compiled mode only —
    callers gate on platform and fall back to XLA slices elsewhere."""
    import jax
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s0, s1, s2 = A.shape
    itemsize = np.dtype(A.dtype).itemsize
    bx = _pick_bx(s0, s1, s2, itemsize)
    nb = s0 // bx
    reqs = list(reqs)

    def kernel(a_ref, *outs):
        for (d, p), o_ref in zip(reqs, outs):
            o_ref[:] = a_ref[:, p, :] if d == 1 else a_ref[:, :, p]

    vma = getattr(getattr(A, "aval", None), "vma", None)

    def shp(d):
        dims = (s0, s2) if d == 1 else (s0, s1)
        return (jax.ShapeDtypeStruct(dims, A.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(dims, A.dtype))

    out_specs = [
        pl.BlockSpec((bx, s2 if d == 1 else s1), lambda i: (i, 0))
        for d, _ in reqs
    ]
    return list(pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((bx, s1, s2), lambda i: (i, 0, 0))],
        out_specs=out_specs,
        out_shape=[shp(d) for d, _ in reqs],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
    )(A))
