"""K-step fused HM3D mega-kernel (self-wrap single-device grids).

The two-field instance of `diffusion_mega`: ONE `pallas_call` advances the
entire inner time loop of the coupled hydro-mechanical step — grid
`(K, nb)` with sequential semantics, manual HBM<->VMEM DMA, HBM ping-pong
for BOTH fields, and hand double-buffering.  Unlike the diffusion mega
there is no loop-invariant coefficient array to keep resident, so VMEM
holds only the double-buffered slabs (~20 MB at 256³) and the kernel
applies at ANY local x extent.

What it removes vs the per-step fused kernel (`hm3d_pallas`, 0.64 ms/step
at 256³): the per-step XLA glue between kernels — the x-end window
recomputation in XLA, the engine's (self-wrap) plane exchange, and the
kernel-boundary buffer round-trips.  Per-step HBM traffic becomes
`(Pe + phi)*(1 + 2/bx)` reads + `(Pe + phi)` writes.

Measured on v5e at 256³ f32 (slope-timed, K=100, bx=8 — the swept
optimum: bx 4/8/16/32 give 0.530/0.478/0.541/0.551): **0.478 ms/step**,
**6.1x the XLA composition** (2.93 ms) and 1.34x the per-step fused
kernel — ~632 GB/s on the actual ~302 MB/step traffic, at the chip's
sustained streaming rate; the residual vs the ideal 268 MB is the slab
margins, and the nonlinear `(phi/phi0)^n` VPU work overlaps under it.  Matches the
per-step fused kernel to float32 rounding
(`tests/test_mega_tpu.py::test_hm3d_mega_matches_per_step_kernel`).

Halo maintenance is the self-wrap scheme of the per-step kernel: y/z halos
are VMEM aliases of the updated interior; the two x halo planes of each
field are computed by the first program of each step from 3-plane x-end
slabs of the current source buffers
(`/root/reference/src/update_halo.jl:516-532`).

DMA/semaphore accounting mirrors `diffusion_mega._kernel` exactly, with
every per-field structure doubled: each DMA start is paired with exactly
one wait (slot reuse two programs later, a full drain at each step
boundary before the ping-pong source is read, and a final drain).

Not available in interpret mode (manual TPU DMA/semaphores); callers fall
back to the per-step kernel.
"""

from __future__ import annotations

from functools import partial

from .diffusion_mega import _VMEM_BUDGET


def hm3d_mega_supported(shape, bx: int, n_inner: int, interpret: bool,
                        dtype) -> bool:
    """Same gates as `diffusion_mega.mega_supported`, with the two-field
    VMEM accounting and no resident coefficient."""
    import numpy as np

    if interpret or n_inner < 2:
        return False
    S0, S1, S2 = shape
    if S0 % bx != 0 or S0 < 2 * bx:
        return False
    if S2 % 128 != 0 or S1 % 8 != 0:
        return False
    itemsize = np.dtype(dtype).itemsize
    need = itemsize * 2 * (2 * (bx + 2) * S1 * S2    # ext slabs x2 fields
                           + 2 * bx * S1 * S2        # out slabs x2 fields
                           + 8 * S1 * S2)            # x-plane scratch x2
    return need <= _VMEM_BUDGET


def _kernel(Pe_hbm, Phi_hbm, pe_out, phi_out, pb0, pb1, fb0, fb1,
            ext_pe, ext_phi, o_pe, o_phi, xfl_pe, xfl_phi,
            esems_pe, esems_phi, osems_pe, osems_phi, xsems,
            *, K, bx, nb, S0, S1, S2, kw_core):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..models.hm3d import step_core

    k = pl.program_id(0)
    i = pl.program_id(1)
    sl = i % 2

    # Out-write bookkeeping (per field): drain at each step boundary, else
    # wait the DMA whose slot this program reuses.
    @pl.when((i == 0) & (k > 0))
    def _():
        for o2, osems in ((o_pe, osems_pe), (o_phi, osems_phi)):
            pltpu.make_async_copy(o2.at[0], o2.at[0], osems.at[0]).wait()
            pltpu.make_async_copy(o2.at[1], o2.at[1], osems.at[1]).wait()

    @pl.when(i >= 2)
    def _():
        for o2, osems in ((o_pe, osems_pe), (o_phi, osems_phi)):
            pltpu.make_async_copy(o2.at[sl], o2.at[sl], osems.at[sl]).wait()

    # Extended-slab fetches (rows [i*bx-1, i*bx+bx+1) mod S0) for BOTH
    # fields; edge programs fetch their own wrapping segments synchronously,
    # interior programs consume their predecessor's prefetch and issue the
    # next one.
    def sync_fetch(src, ext2, esems):
        @pl.when(i == 0)
        def _():
            c0 = pltpu.make_async_copy(src.at[S0 - 1:S0],
                                       ext2.at[sl, 0:1], esems.at[sl])
            c1 = pltpu.make_async_copy(src.at[0:bx + 1],
                                       ext2.at[sl, 1:bx + 2],
                                       esems.at[1 - sl])
            c0.start(); c1.start(); c0.wait(); c1.wait()

        @pl.when(i == nb - 1)
        def _():
            c0 = pltpu.make_async_copy(src.at[S0 - bx - 1:S0],
                                       ext2.at[sl, 0:bx + 1], esems.at[sl])
            c1 = pltpu.make_async_copy(src.at[0:1],
                                       ext2.at[sl, bx + 1:bx + 2],
                                       esems.at[1 - sl])
            c0.start(); c1.start(); c0.wait(); c1.wait()

    def prefetch_next(src, ext2, esems):
        @pl.when((i + 1 >= 1) & (i + 1 <= nb - 2))
        def _():
            pltpu.make_async_copy(
                src.at[pl.ds((i + 1) * bx - 1, bx + 2)],
                ext2.at[1 - sl], esems.at[1 - sl]).start()

    def fetch_xplanes(src, xfl, xsem0, xsem1):
        c0 = pltpu.make_async_copy(src.at[S0 - 3:S0], xfl.at[0:3], xsem0)
        c1 = pltpu.make_async_copy(src.at[0:3], xfl.at[3:6], xsem1)
        c0.start(); c1.start(); c0.wait(); c1.wait()

    for cond, src_pe, src_phi in ((k == 0, Pe_hbm, Phi_hbm),
                                  ((k > 0) & (k % 2 == 1), pb0, fb0),
                                  ((k > 0) & (k % 2 == 0), pb1, fb1)):
        @pl.when(cond)
        def _(src_pe=src_pe, src_phi=src_phi):
            sync_fetch(src_pe, ext_pe, esems_pe)
            sync_fetch(src_phi, ext_phi, esems_phi)

            @pl.when(i == 0)
            def _():
                fetch_xplanes(src_pe, xfl_pe, xsems.at[0], xsems.at[1])
                fetch_xplanes(src_phi, xfl_phi, xsems.at[2], xsems.at[3])
            prefetch_next(src_pe, ext_pe, esems_pe)
            prefetch_next(src_phi, ext_phi, esems_phi)

    @pl.when((i > 0) & (i < nb - 1))
    def _():
        for ext2, esems in ((ext_pe, esems_pe), (ext_phi, esems_phi)):
            pltpu.make_async_copy(ext2.at[sl], ext2.at[sl],
                                  esems.at[sl]).wait()

    # x halo planes of this step for both fields (row 0 <- updated row
    # S0-2, row S0-1 <- updated row 1, wrapped in y/z), computed once per
    # step from the x-end slabs.
    @pl.when(i == 0)
    def _():
        def wrap_yz(U):
            U = jnp.concatenate([U[:, -1:, :], U, U[:, :1, :]], axis=1)
            return jnp.concatenate([U[:, :, -1:], U, U[:, :, :1]], axis=2)

        for key in (0, 1):   # 0: hi slab -> plane for row 0; 1: lo slab
            lo_, hi_ = (3, 6) if key else (0, 3)
            wpe = xfl_pe[lo_:hi_]
            wphi = xfl_phi[lo_:hi_]
            dPe, dphi = step_core(wpe, wphi, **kw_core)
            pe_pl = wpe[1:2, 1:-1, 1:-1] + dPe
            phi_pl = wphi[1:2, 1:-1, 1:-1] + dphi
            xfl_pe[6 + key:7 + key] = wrap_yz(pe_pl)
            xfl_phi[6 + key:7 + key] = wrap_yz(phi_pl)

    # Coupled stencil update on the extended slabs + y/z self-wrap assembly
    # (identical scheme to hm3d_pallas._make_kernel in wrap mode).
    ePe = ext_pe.at[sl][:]
    ephi = ext_phi.at[sl][:]
    ope = o_pe.at[sl]
    ophi = o_phi.at[sl]
    dPe, dphi = step_core(ePe, ephi, **kw_core)
    ope[:] = ePe[1:1 + bx]
    ope[:, 1:-1, 1:-1] = ePe[1:1 + bx, 1:-1, 1:-1] + dPe[0:bx]
    ophi[:] = ephi[1:1 + bx]
    ophi[:, 1:-1, 1:-1] = ephi[1:1 + bx, 1:-1, 1:-1] + dphi[0:bx]
    for o in (ope, ophi):
        o[:, 0:1, 1:-1] = o[:, S1 - 2:S1 - 1, 1:-1]
        o[:, S1 - 1:S1, 1:-1] = o[:, 1:2, 1:-1]
        o[:, :, 0:1] = o[:, :, S2 - 2:S2 - 1]
        o[:, :, S2 - 1:S2] = o[:, :, 1:2]

    @pl.when(i == 0)
    def _():
        ope[0:1] = xfl_pe[6:7]
        ophi[0:1] = xfl_phi[6:7]

    @pl.when(i == nb - 1)
    def _():
        ope[bx - 1:bx] = xfl_pe[7:8]
        ophi[bx - 1:bx] = xfl_phi[7:8]

    # Async write-back to this step's destinations.
    def put(o2, dst, osems):
        pltpu.make_async_copy(o2.at[sl], dst.at[pl.ds(i * bx, bx)],
                              osems.at[sl]).start()

    @pl.when(k == K - 1)
    def _():
        put(o_pe, pe_out, osems_pe)
        put(o_phi, phi_out, osems_phi)

    @pl.when((k < K - 1) & (k % 2 == 0))
    def _():
        put(o_pe, pb0, osems_pe)
        put(o_phi, fb0, osems_phi)

    @pl.when((k < K - 1) & (k % 2 == 1))
    def _():
        put(o_pe, pb1, osems_pe)
        put(o_phi, fb1, osems_phi)

    # Final drain: the last out DMAs of each field have no successor.
    @pl.when((k == K - 1) & (i == nb - 1))
    def _():
        for o2, osems in ((o_pe, osems_pe), (o_phi, osems_phi)):
            pltpu.make_async_copy(o2.at[1 - sl], o2.at[1 - sl],
                                  osems.at[1 - sl]).wait()
            pltpu.make_async_copy(o2.at[sl], o2.at[sl], osems.at[sl]).wait()


def fused_hm3d_megasteps(Pe, phi, *, n_inner: int, bx: int, **kw_core):
    """Advance `n_inner` self-wrap HM3D steps in ONE pallas_call.  The
    input buffers are donated to the results (the k=0 reads all happen
    before any write lands in them; `n_inner >= 2` gated in
    `hm3d_mega_supported`)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = Pe.shape
    S0, S1, S2 = s
    nb = S0 // bx
    kern = partial(_kernel, K=n_inner, bx=bx, nb=nb, S0=S0, S1=S1, S2=S2,
                   kw_core=kw_core)

    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in (Pe, phi)]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp():
        return (jax.ShapeDtypeStruct(s, Pe.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(s, Pe.dtype))

    pe_out, phi_out, *_ = pl.pallas_call(
        kern,
        grid=(n_inner, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 6,
        out_shape=[shp()] * 6,
        input_output_aliases={0: 0, 1: 1},
        scratch_shapes=[
            pltpu.VMEM((2, bx + 2, S1, S2), Pe.dtype),    # ext_pe
            pltpu.VMEM((2, bx + 2, S1, S2), Pe.dtype),    # ext_phi
            pltpu.VMEM((2, bx, S1, S2), Pe.dtype),        # o_pe
            pltpu.VMEM((2, bx, S1, S2), Pe.dtype),        # o_phi
            pltpu.VMEM((8, S1, S2), Pe.dtype),            # xfl_pe
            pltpu.VMEM((8, S1, S2), Pe.dtype),            # xfl_phi
            pltpu.SemaphoreType.DMA((2,)),                # esems_pe
            pltpu.SemaphoreType.DMA((2,)),                # esems_phi
            pltpu.SemaphoreType.DMA((2,)),                # osems_pe
            pltpu.SemaphoreType.DMA((2,)),                # osems_phi
            pltpu.SemaphoreType.DMA((4,)),                # xsems
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary", "arbitrary")),
    )(Pe, phi)
    return pe_out, phi_out
