"""Shared per-call scoped-VMEM budgeting for the fused model kernels.

The stokes/hm3d fused kernels keep a deliberately TIGHT vmem budget when
their working set allows (small budgets steer Mosaic to the best
DMA/compute interleave — see the sweep in `stokes_pallas.py`), but large
y*z window areas NEED more than the floor: round 5 found both kernels
OOM-ing at Mosaic compile on 256^3/512^3-class blocks under their fixed
32 MB budgets, with `use_pallas="auto"` users crashing instead of falling
back.  Each kernel supplies its own first-order window-footprint model
(`need_fn(bx, S1, S2)`); this module owns the shared floor/cap and the
slab-height fitting so the two cannot drift.

Round 16: this module is also the single budget authority for the K-step
CHUNK tiers — `CHUNK_VMEM_BUDGET` (the resident-working-set ceiling the
trapezoid gates used to copy from `diffusion_mega`) and
:func:`fit_chunk_K` (the fit-K-to-budget halving search both trapezoid
modules used to hand-roll).  The autotuner (`igg.autotune`) sweeps the
cap through :func:`set_cap_override`, so a tuned budget reaches every
kernel that consults :func:`vmem_limit` without per-kernel plumbing."""

from __future__ import annotations

from typing import Callable, Optional

VMEM_FLOOR = 32 * 1024 * 1024
VMEM_CAP = 110 * 1024 * 1024

# Resident-working-set ceiling for the K-step chunk kernels (the v5e/v5p
# have 128 MB of VMEM; leave slack for Mosaic's own allocations).  One
# constant, one place — the trapezoid modules and the chunk engine all
# read it from here.
CHUNK_VMEM_BUDGET = 110 * 1024 * 1024

# The autotuner's cap override (igg.autotune applies a tuned vmem budget
# here; None = the hand-derived default).  Read at trace/build time only —
# never from a hot loop.
_CAP_OVERRIDE: Optional[int] = None


def set_cap_override(cap_bytes: Optional[int]) -> None:
    """Install (or clear, with None) the autotuned per-call VMEM cap.
    Affects :func:`vmem_limit` and :func:`chunk_budget`; callers re-trace
    on the next factory build, so flipping it never invalidates a live
    compiled program mid-run."""
    global _CAP_OVERRIDE
    _CAP_OVERRIDE = int(cap_bytes) if cap_bytes else None


def vmem_cap() -> int:
    return _CAP_OVERRIDE if _CAP_OVERRIDE is not None else VMEM_CAP


def chunk_budget() -> int:
    """The chunk tiers' resident-working-set budget (override-aware)."""
    return (_CAP_OVERRIDE if _CAP_OVERRIDE is not None
            else CHUNK_VMEM_BUDGET)


def vmem_limit(need: int) -> int:
    """The per-call scoped-vmem budget for a modeled footprint."""
    return max(VMEM_FLOOR, min(vmem_cap(), need))


def fit_chunk_K(admissible: Callable[[int], object], kmax: int, *,
                min_k: int = 2) -> int:
    """Largest admissible chunk depth K <= kmax by halving (>= `min_k`);
    0 when none applies.  `admissible(K)` is the family's full admission
    gate (an :class:`igg.degrade.Admission` or bool) — the search walks
    kmax, kmax/2, ... so an even kmax keeps even K (the property the
    extended-span band-divisibility gates rely on).  This is the shared
    fit-K-to-budget computation both trapezoid modules used to carry
    privately (`stokes_trapezoid.fit_stokes_K`, the diffusion dispatch's
    fixed bx fallbacks)."""
    K = int(kmax)
    while K >= min_k:
        if admissible(K):
            return K
        K //= 2
    return 0


def whole_block_vmem(shapes, itemsize: int = 4) -> int:
    """Modeled VMEM footprint of a whole-block/whole-window kernel
    holding `shapes` in and out (trailing dims tile-padded to the
    Mosaic (8, 128) tile, 2x margin for Mosaic scratch) — the one
    footprint model the wave2d per-step/chunk gates and the
    `igg.stencil` generated tiers share, kept next to the budget it is
    compared against."""
    from .chunk_engine import pad8, pad128

    total = 0
    for s in shapes:
        padded = list(s)
        padded[-1] = pad128(s[-1])
        if len(s) >= 2:
            padded[-2] = pad8(s[-2])
        n = 1
        for v in padded:
            n *= int(v)
        total += n
    return int(2 * 2 * total * itemsize)


def banded_vmem(ext_shapes, B: int, extras, n_up: int, *, lo: int = 1,
                modes=None, freeze_fields=(), itemsize: int = 4) -> int:
    """Modeled VMEM footprint of the STREAMING banded chunk kernel
    (`chunk_engine.streaming_chunk_call`) at band depth B: the per-field
    rolling windows (`lo + B + extras[f]` tile-padded rows — NOT the
    full extended block, which is the whole point), the double-buffered
    out slot pairs of the updated fields, the open-dim freeze planes,
    and the resident models' 2x margin for band temporaries + Mosaic's
    own scratch.  Compared against :func:`chunk_budget` (override-aware,
    so `set_cap_override` sweeps reach the banded gates like every
    other kernel's)."""
    from .chunk_engine import normalize_freeze, pad8, pad128

    def row(s):
        return (pad8(s[1]) * pad128(s[2]) if len(s) == 3
                else pad128(s[1]))

    need = sum((lo + B + e) * row(s)
               for s, e in zip(ext_shapes, extras))
    need += sum(2 * B * row(s) for s in ext_shapes[:n_up])
    if modes is not None:
        nd = len(ext_shapes[0])
        freeze = normalize_freeze(freeze_fields, nd)
        for d in range(nd):
            if modes[d] in ("oext", "frozen"):
                for f in freeze[d]:
                    s = ext_shapes[f]
                    plane = s[:d] + s[d + 1:]
                    p = (pad8(plane[0]) * pad128(plane[1])
                         if len(plane) == 2 else pad128(plane[0]))
                    need += 2 * p
    return int(2 * need * itemsize)


def fit_banded(admissible, kmax: int, *, bands=(8, 16),
               min_k: int = 2):
    """Largest admissible `(K, B)` for a streaming banded tier:
    K by halving from kmax (deeper chunks amortize more exchange — the
    window footprint barely depends on K), bands in preference order;
    None when none applies.  `admissible(K, B)` is the family's full
    banded admission gate."""
    K = int(kmax)
    while K >= min_k:
        for B in bands:
            if admissible(K, B):
                return K, B
        K //= 2
    return None


def fit_bx(need_fn, bx: int, S0: int, S1: int, S2: int, *,
           min_bx: int, check_vmem: bool = True) -> int:
    """Largest slab height <= bx (halving, >= `min_bx`) that divides S0
    and — in compiled mode — whose modeled footprint fits the cap; 0 when
    none does.  `check_vmem=False` is the interpret-mode form: no Mosaic,
    no budget."""
    while bx >= min_bx:
        if S0 % bx == 0 and (not check_vmem
                             or need_fn(bx, S1, S2) <= vmem_cap()):
            return bx
        bx //= 2
    return 0
