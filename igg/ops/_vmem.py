"""Shared per-call scoped-VMEM budgeting for the fused model kernels.

The stokes/hm3d fused kernels keep a deliberately TIGHT vmem budget when
their working set allows (small budgets steer Mosaic to the best
DMA/compute interleave — see the sweep in `stokes_pallas.py`), but large
y*z window areas NEED more than the floor: round 5 found both kernels
OOM-ing at Mosaic compile on 256^3/512^3-class blocks under their fixed
32 MB budgets, with `use_pallas="auto"` users crashing instead of falling
back.  Each kernel supplies its own first-order window-footprint model
(`need_fn(bx, S1, S2)`); this module owns the shared floor/cap and the
slab-height fitting so the two cannot drift."""

from __future__ import annotations

VMEM_FLOOR = 32 * 1024 * 1024
VMEM_CAP = 110 * 1024 * 1024


def vmem_limit(need: int) -> int:
    """The per-call scoped-vmem budget for a modeled footprint."""
    return max(VMEM_FLOOR, min(VMEM_CAP, need))


def fit_bx(need_fn, bx: int, S0: int, S1: int, S2: int, *,
           min_bx: int, check_vmem: bool = True) -> int:
    """Largest slab height <= bx (halving, >= `min_bx`) that divides S0
    and — in compiled mode — whose modeled footprint fits the cap; 0 when
    none does.  `check_vmem=False` is the interpret-mode form: no Mosaic,
    no budget."""
    while bx >= min_bx:
        if S0 % bx == 0 and (not check_vmem
                             or need_fn(bx, S1, S2) <= VMEM_CAP):
            return bx
        bx //= 2
    return 0
