"""K-iteration temporal-blocking (trapezoid chunk) tier for the Stokes
pseudo-transient solver.

`docs/stokes_roofline.md` proves the per-iteration fused kernel
(`stokes_pallas`, 0.143 ms/iter at 128^3 f32) is jointly DMA- and
VPU-bound with a ~2.1x-over-composition ceiling, and names the only
escape: temporal blocking.  This module is that escape — the four-field,
staggered-shapes instance of the `diffusion_trapezoid` recipe:

  1. Once per K-iteration chunk, each device extends its block by
     `E = 2K` margin rows per extended dimension via ONE grouped
     `ppermute` pair per dimension per shape group (P and Vx share x-slab
     shapes and ride one permute, exactly like their shared plane shapes
     in `stokes_pallas`; Rho's loop-invariant extension is hoisted out of
     the chunk loop entirely).  The extensions are built
     dimension-sequentially — y slabs are cut from the x-extended buffer
     — so corner/edge regions arrive via the later neighbors' own
     earlier-dim extensions (the halo engine's sequential-exchange corner
     trick, `/root/reference/src/update_halo.jl:36,130`).
  2. K coupled iterations run on the extended windows with NO exchange:
     per iteration the windows lose (at most) 2 rows of validity per
     extended side — the pseudo-transient chain (pressure update read by
     the velocity updates, Gauss-Seidel flavor) reads at most one row
     below and one above per iteration, so the `2K` margin over-provisions
     the front by 2x (the contract `igg.hide_communication` uses for the
     same chain, radius=2).  After K iterations the device's own block
     (interior AND halo rows) carries per-iteration-path values: every
     block row is produced by the identical `iteration_core` arithmetic
     the neighbor would apply to the same global cells.
  3. Wrap dims (single periodic device) re-apply the per-field staggered
     self-wrap each iteration; OPEN dims re-freeze the three velocity
     fields' boundary planes each iteration from VMEM-held chunk-entry
     freeze planes gated by SMEM `axis_index` edge flags (the
     "frozen"/"oext" mechanism the diffusion chunk kernel proved).
     Pressure is NOT frozen: the per-iteration path's full-shape pressure
     update writes the boundary plane too (its no-write halo fallback IS
     the computed plane — `stokes_pallas._sends_and_stales`), and the
     boundary-adjacent expressions read nothing beyond the frozen
     velocity planes, so a single frozen velocity plane per open side
     quarantines the beyond-domain shoulder garbage (worked through in
     `docs/stokes_roofline.md`).

Two realizations of the same window dynamics:

  - **Pure-XLA window path** (`_window_iters_xla`) — interpret mode, CPU
    meshes, the driver dryrun: `iteration_core` + `interior_add` on the
    full extended window per iteration, shoulder-band freezing on open
    dims.  This is the realization the 8-device mesh equivalence tests
    pin against `stokes3d.local_iteration`.
  - **Mosaic chunk kernel** (`_kernel`) — compiled mode: all five fields
    VMEM-RESIDENT for the whole chunk (grid `(K, nb)`, "arbitrary"
    semantics), updated IN PLACE in x-row bands with a one-row lag
    buffer carrying each band's overwritten tail row to its successor
    (margin-1 windows, the per-iteration kernel's proven margins).  HBM
    traffic per chunk is ONE read of the five extended fields and ONE
    write of the four updated ones — `(5R+4W)/K` per iteration instead
    of the per-iteration kernel's `5R+4W`, the 1/K amortization the
    roofline demands.  Unlike the diffusion trapezoid (whose blocks
    exceed VMEM and stream through HBM ping-pong buffers), the Stokes
    working set at its VMEM-admissible sizes (~<=160^3 f32 locals) fits
    on chip, so the kernel needs no ping-pong: the only DMAs are the
    chunk-entry loads and the final-iteration band write-backs (the
    staggered Vy/Vz trailing dims ride tile-padded so every leading-dim
    VMEM slice stays aligned; the band compute slices the logical region
    back out as values).  `_band_update` — the shared per-band value
    computation — keeps `stokes3d.iteration_core` the single source of
    arithmetic truth, and is pinned against the window realization by
    the banded-scheme simulation in `tests/test_stokes_trapezoid.py`.

VMEM is the K-bound: the resident working set grows with `K` through the
`2K`-row extensions (plus the Vz lane padding the roofline documents), so
`stokes_trapezoid_supported` does the accounting and `fit_stokes_K` picks
the largest admissible K — at 128^3 f32 on an `(N,1,1)` mesh that is
K=8 (~70 MB modeled; K=16 would need the 2x-margin model past the
110 MB budget).  `docs/stokes_roofline.md` carries the full analysis.

The compiled dispatcher (`stokes3d.make_iteration`) runs one per-iteration
fused kernel FIRST — consuming (and replacing) the entry halos exactly
like every other path, establishing the exchange-fresh window state the
validity argument requires — then `n_inner // K` chunks, then the
remainder through the per-iteration kernel.
"""

from __future__ import annotations

from functools import partial

from .diffusion_mega import _VMEM_BUDGET
from .diffusion_trapezoid import _dim_modes

_BX = 8          # x band height of the chunk kernel (rows per program)


def _pad8(v: int) -> int:
    return -(-v // 8) * 8


def _pad128(v: int) -> int:
    return -(-v // 128) * 128


def _field_shapes(shape):
    """Local shapes of (P, Vx, Vy, Vz, Rho) from the unstaggered P shape."""
    S0, S1, S2 = shape
    return [(S0, S1, S2), (S0 + 1, S1, S2), (S0, S1 + 1, S2),
            (S0, S1, S2 + 1), (S0, S1, S2)]


def _ols(grid, shapes):
    """Per-field per-dim staggered overlaps (`ol(dim, A)`,
    `/root/reference/src/shared.jl:81`)."""
    return [tuple(grid.ol_of_local(d, s) for d in range(3)) for s in shapes]


def _ext_shape(s, E, modes):
    return tuple(s[d] + (2 * E if modes[d] in ("ext", "oext") else 0)
                 for d in range(3))


def _vmem_need(shape, K, modes, itemsize: int = 4) -> int:
    """Modeled VMEM bytes of the resident chunk kernel at iteration depth
    K: the five tile-padded extended fields (Vy sublane-padded, Vz
    lane-padded — the 2x physical footprint of its `(S,S,S+1)` shape the
    roofline documents), the per-field lag rows, the open-dim freeze
    planes, and a 2x-margin band-temporary term for `iteration_core`'s
    stress/residual chain (~16 band-row intermediates; the 2x absorbs
    Mosaic's own scratch, the same calibration style as
    `stokes_pallas._vmem_need`)."""
    E = 2 * K
    exts = [_ext_shape(s, E, modes) for s in _field_shapes(shape)]
    need = sum(a * _pad8(b) * _pad128(c) for a, b, c in exts) * itemsize
    row = _pad8(exts[2][1]) * _pad128(exts[3][2]) * itemsize
    need += 2 * 4 * row                       # lag rows (2 slots x 4 fields)
    for d in range(3):                        # freeze planes (3 velocities)
        if modes[d] in ("oext", "frozen"):
            for a, b, c in exts[1:4]:
                plane = (a, b, c)[:d] + (a, b, c)[d + 1:]
                need += 2 * _pad8(plane[0]) * _pad128(plane[1]) * itemsize
    need += 2 * 16 * (_BX + 3) * row          # band temporaries, 2x margin
    return need


def stokes_trapezoid_supported(grid, shape, K: int, n_inner: int, dtype,
                               interpret: bool = False):
    """Whether the K-iteration Stokes chunk tier applies: overlap-3 grid
    (the per-iteration kernel's prerequisite — it runs the warm-up and
    remainder iterations), at least one full chunk, `E = 2K` send slabs
    inside every extended dimension's block (per-field staggered ol), the
    kernel's band/tile-alignment geometry, and the resident working set
    within the VMEM budget.  Both realizations take the same gates so
    interpret meshes exercise the compiled tier's exact admission
    decisions (the `diffusion_trapezoid` convention).  Returns an
    :class:`igg.degrade.Admission` (truthy/falsy) carrying the structured
    refusal reason."""
    import numpy as np

    from ..degrade import Admission

    if K < 2 or n_inner < K:
        return Admission.no(f"n_inner={n_inner} holds no full K={K} chunk "
                            f"(needs n_inner >= K >= 2)")
    if grid.overlaps != (3, 3, 3):
        return Admission.no(f"grid overlaps {grid.overlaps} != (3, 3, 3)")
    if tuple(shape) != tuple(grid.nxyz):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz)}")
    if getattr(grid, "disp", 1) != 1:
        # The chunked slab exchange hardwires +-1 ppermute tables.
        return Admission.no(f"grid disp {grid.disp} != 1 (chunk slab "
                            f"exchange hardwires +-1 ppermute tables)")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = _dim_modes(grid)
    E = 2 * K
    S0, S1, S2 = shape
    if S0 % _BX != 0 or S0 < 2 * _BX:
        return Admission.no(f"x extent {S0} not band-divisible "
                            f"(needs S0 % {_BX} == 0, S0 >= {2 * _BX})")
    if S1 % 8 != 0 or S2 % 128 != 0:
        # Mosaic tile-aligned leading-dim VMEM slices (staggered trailing
        # extents are padded by the kernel; the base extents must align).
        return Admission.no(f"local y/z extents ({S1}, {S2}) not Mosaic "
                            f"tile-aligned (y % 8, z % 128)")
    if modes[0] != "frozen" and (2 * E) % _BX != 0:
        # S0e = S0 + 2E must stay band-divisible.
        return Admission.no(f"extended x span S0 + {2 * E} not "
                            f"band-divisible by {_BX}")
    if modes[1] in ("ext", "oext") and E % 8 != 0:
        # Central y window slice offset must stay on sublane tiles.
        return Admission.no(f"y-extension E={E} not on sublane tiles "
                            f"(E % 8 != 0)")
    shapes = _field_shapes(shape)
    ols = _ols(grid, shapes)
    for d in range(3):
        if modes[d] not in ("ext", "oext"):
            continue
        for s, ol in zip(shapes, ols):
            if s[d] - ol[d] - E < 0 or ol[d] + E > s[d]:
                # K-deep send slabs inside the block
                return Admission.no(
                    f"E={E} dim-{d} send slabs fall outside a field block "
                    f"(shape {s}, ol {ol[d]})")
    need = _vmem_need(shape, K, modes)
    if need > _VMEM_BUDGET:
        return Admission.no(f"resident working set {need} bytes exceeds "
                            f"the VMEM budget {_VMEM_BUDGET}")
    return Admission.yes()


def fit_stokes_K(grid, shape, n_inner: int, dtype,
                 interpret: bool = False, kmax: int = 8) -> int:
    """Largest admissible chunk depth K <= kmax (halving, >= 2); 0 when
    none applies.  Even K keeps `S0e = S0 + 4K` band-divisible on
    extended-x meshes."""
    K = kmax
    while K >= 2:
        if stokes_trapezoid_supported(grid, shape, K, n_inner, dtype,
                                      interpret=interpret):
            return K
        K //= 2
    return 0


# ---------------------------------------------------------------------------
# Extension: grouped K-deep slab ppermutes, dimension-sequential
# ---------------------------------------------------------------------------

def _extend_dim_grouped(arrs, ols, E, grid, d, mode):
    """`_extend_dim` of `diffusion_trapezoid`, generalized to a GROUP of
    fields with per-field staggered overlaps: same-shaped slabs are
    stacked and ride ONE ppermute per direction (P and Vx share x-slab
    shapes; Vy/Vz are staggered-shaped and go alone), the direct analog
    of the halo engine's grouped plane wire.  z slabs ride TRANSPOSED
    (z on the sublane axis) so nothing lane-padded materializes."""
    import jax.numpy as jnp
    from jax import lax

    from ..shared import AXIS_NAMES

    n = grid.dims[d]
    axis = AXIS_NAMES[d]
    open_edges = mode == "oext"
    tw = d == 2                      # transpose-carried lane-dim slabs

    slabs = []
    for A, ol in zip(arrs, ols):
        S = A.shape[d]
        left = lax.slice_in_dim(A, S - ol - E, S - ol + 1, axis=d)
        right = lax.slice_in_dim(A, ol - 1, ol + E, axis=d)
        if tw:
            left, right = (jnp.swapaxes(x, 1, 2) for x in (left, right))
        slabs.append([left, right])

    if n > 1:
        if open_edges:
            to_right = [(i, i + 1) for i in range(n - 1)]
            to_left = [(i, i - 1) for i in range(1, n)]
        else:
            to_right = [(i, (i + 1) % n) for i in range(n)]
            to_left = [(i, (i - 1) % n) for i in range(n)]
        groups = {}
        for j, (left, right) in enumerate(slabs):
            groups.setdefault(tuple(left.shape), []).append(j)
        for members in groups.values():
            for side, table in ((0, to_right), (1, to_left)):
                if len(members) == 1:
                    j = members[0]
                    slabs[j][side] = lax.ppermute(slabs[j][side], axis,
                                                  table)
                else:
                    stacked = jnp.stack([slabs[j][side] for j in members])
                    stacked = lax.ppermute(stacked, axis, table)
                    for k, j in enumerate(members):
                        slabs[j][side] = stacked[k]

    out = []
    for A, ol, (left, right) in zip(arrs, ols, slabs):
        if tw:
            left, right = (jnp.swapaxes(x, 1, 2) for x in (left, right))
        S = A.shape[d]
        Text = jnp.concatenate(
            [left, lax.slice_in_dim(A, 1, S - 1, axis=d), right], axis=d)
        if open_edges:
            # Global-edge devices received zeros; restore the block's own
            # no-write boundary rows at ext index E / Se-1-E (the
            # beyond-domain shoulder stays garbage the freeze quarantines).
            idx = lax.axis_index(axis)
            Se = S + 2 * E
            fixed_l = lax.dynamic_update_slice_in_dim(
                Text, lax.slice_in_dim(A, 0, 1, axis=d), E, axis=d)
            Text = jnp.where(idx == 0, fixed_l, Text)
            fixed_r = lax.dynamic_update_slice_in_dim(
                Text, lax.slice_in_dim(A, S - 1, S, axis=d), Se - 1 - E,
                axis=d)
            Text = jnp.where(idx == n - 1, fixed_r, Text)
        out.append(Text)
    return out


def _extend_fields(arrs, ols, E, grid, modes):
    """Dimension-sequential extension of a list of fields: x first, then
    the y extension OF the x-extended buffers, then z of the x/y-extended
    — the sequential-exchange corner trick.  wrap/frozen dims are not
    extended."""
    out = list(arrs)
    for d in range(3):
        if modes[d] in ("ext", "oext"):
            out = _extend_dim_grouped(out, [ol[d] for ol in ols], E, grid,
                                      d, modes[d])
    return out


# ---------------------------------------------------------------------------
# The shared per-band value computation (single source of arithmetic truth
# with the per-iteration paths: stokes3d.iteration_core)
# ---------------------------------------------------------------------------

def _band_update(Wp, Wvx, Wvy, Wvz, Wrho, *, bx, scal):
    """New band values (rows [a, a+bx), window row offset 1) from margin-1
    windows: P/Vy/Vz/Rho window rows [a-1, a+bx+1), the x-staggered Vx
    [a-1, a+bx+2) — the per-iteration kernel's proven minimal margins
    (`stokes_pallas._kernel`: out row j <-> ext row j+1 <-> increment
    row j).  Rho's margin rows are dummies (read row-locally).  Pure
    values: shared verbatim by the Mosaic kernel and the banded-scheme
    simulation test."""
    import jax.numpy as jnp

    from ..models.stokes3d import iteration_core

    Pn, dVx, dVy, dVz = iteration_core(Wp, Wvx, Wvy, Wvz, Wrho, **scal)
    outs = [Pn[1:1 + bx]]
    for W, dV in ((Wvx, dVx), (Wvy, dVy), (Wvz, dVz)):
        o = W[1:1 + bx]
        inner = o[:, 1:-1, 1:-1] + dV[0:bx]
        mid = jnp.concatenate([o[:, 1:-1, 0:1], inner, o[:, 1:-1, -1:]],
                              axis=2)
        outs.append(jnp.concatenate([o[:, 0:1, :], mid, o[:, -1:, :]],
                                    axis=1))
    return tuple(outs)


def _band_halo(news, a, bx, flags, frx, fryz, cfg):
    """Per-band halo handling of the four new-band value arrays, in
    dimension order (later dims win shared cells, the per-iteration
    path's assembly order): x freeze rows (open dims, velocities only),
    then y wrap/freeze, then z wrap/freeze.  `flags` is the 6-vector of
    edge flags as VALUES (SMEM scalars in the kernel, python ints in the
    simulation); `frx[(f, side)]` are whole x freeze planes and
    `fryz[(f, d, side)]` the band-sliced y/z freeze rows of velocity
    field f (logical trailing extents).  Pure values — shared by the
    Mosaic kernel and the banded-scheme simulation test."""
    import jax.numpy as jnp
    from jax import lax

    modes, ols, ext_shapes, E = (cfg["modes"], cfg["ols"],
                                 cfg["ext_shapes"], cfg["E"])
    news = list(news)

    if modes[0] in ("oext", "frozen"):
        lo = E if modes[0] == "oext" else 0
        for f in (1, 2, 3):
            hi = lo + cfg["shapes"][f][0] - 1
            rows = lax.broadcasted_iota(jnp.int32, news[f].shape, 0) + a
            news[f] = jnp.where((rows == lo) & (flags[0] == 1),
                                frx[(f, 0)][None], news[f])
            news[f] = jnp.where((rows == hi) & (flags[1] == 1),
                                frx[(f, 1)][None], news[f])
    for d in (1, 2):
        if modes[d] == "wrap":
            for f in range(4):
                sd = ext_shapes[f][d]
                ol = ols[f][d]
                news[f] = _wrap_edges(news[f], d, sd, ol)
        elif modes[d] in ("oext", "frozen"):
            lo = E if modes[d] == "oext" else 0
            for f in (1, 2, 3):
                hi = lo + cfg["shapes"][f][d] - 1
                idx = lax.broadcasted_iota(jnp.int32, news[f].shape, d)
                exp = (lambda P: jnp.expand_dims(P, d))
                news[f] = jnp.where((idx == lo) & (flags[2 * d] == 1),
                                    exp(fryz[(f, d, 0)]), news[f])
                news[f] = jnp.where((idx == hi) & (flags[2 * d + 1] == 1),
                                    exp(fryz[(f, d, 1)]), news[f])
    return tuple(news)


def _wrap_edges(v, axis, size, ol):
    """Per-field staggered periodic self-wrap of the outermost planes
    along `axis`: edge 0 <- inner `size-ol`, edge `size-1` <- inner
    `ol-1` (`/root/reference/src/update_halo.jl:516-532`)."""
    import jax.numpy as jnp
    from jax import lax

    idx = lax.broadcasted_iota(jnp.int32, v.shape, axis)
    v = jnp.where(idx == 0,
                  lax.slice_in_dim(v, size - ol, size - ol + 1, axis=axis),
                  v)
    return jnp.where(idx == size - 1,
                     lax.slice_in_dim(v, ol - 1, ol, axis=axis), v)


# ---------------------------------------------------------------------------
# Pure-XLA window realization (interpret mode / CPU meshes)
# ---------------------------------------------------------------------------

def _window_iters_xla(Pe, Vxe, Vye, Vze, Rhoe, *, K, E, modes, grid, scal,
                      ols, shapes):
    """K coupled iterations on the extended windows: full-window
    `iteration_core` + `interior_add`, then per-dim halo handling in
    dimension order — wrap dims self-wrap with per-field staggered ol;
    open dims re-freeze the VELOCITY shoulder+boundary band from the
    chunk-entry buffers on the global-edge devices (pressure is computed
    everywhere, its boundary value being the per-iteration path's
    computed no-write plane).  The freeze width differs from the Mosaic
    kernel (whole shoulder band vs exactly the boundary plane); the two
    agree on the central window because influence from the shoulder can
    only pass THROUGH the frozen boundary plane, which never reads it
    (the diffusion chunk kernel's quarantine argument, radius checked
    for the coupled chain in `docs/stokes_roofline.md`)."""
    import jax.numpy as jnp
    from jax import lax

    from ..shared import AXIS_NAMES
    from .stencil import interior_add

    entry = (Pe, Vxe, Vye, Vze)       # freeze source for open edges

    def step(_, S):
        P, Vx, Vy, Vz = S
        from ..models.stokes3d import iteration_core

        P, dVx, dVy, dVz = iteration_core(P, Vx, Vy, Vz, Rhoe, **scal)
        Vx = interior_add(Vx, dVx)
        Vy = interior_add(Vy, dVy)
        Vz = interior_add(Vz, dVz)
        fields = [P, Vx, Vy, Vz]
        for d in range(3):
            if modes[d] == "wrap":
                for f in range(4):
                    sd = fields[f].shape[d]
                    fields[f] = _wrap_edges(fields[f], d, sd, ols[f][d])
            elif modes[d] in ("oext", "frozen"):
                lo = E if modes[d] == "oext" else 0
                for f in (1, 2, 3):      # velocities only; P is computed
                    F0 = entry[f]
                    sd = shapes[f][d]
                    hi = lo + sd - 1
                    idx = lax.broadcasted_iota(jnp.int32, fields[f].shape,
                                               d)
                    if modes[d] == "frozen":
                        keep = (idx == lo) | (idx == hi)
                        fields[f] = jnp.where(keep, F0, fields[f])
                    else:
                        ai = lax.axis_index(AXIS_NAMES[d])
                        n = grid.dims[d]
                        fields[f] = jnp.where((ai == 0) & (idx <= lo), F0,
                                              fields[f])
                        fields[f] = jnp.where((ai == n - 1) & (idx >= hi),
                                              F0, fields[f])
        return tuple(fields)

    return lax.fori_loop(0, K, step, (Pe, Vxe, Vye, Vze))


# ---------------------------------------------------------------------------
# The Mosaic chunk kernel (compiled mode): VMEM-resident in-place bands
# ---------------------------------------------------------------------------

def _kernel(*refs, K, bx, scal, cfg, nfr, pads):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shapes = cfg["shapes"]            # local (unextended) field shapes
    ext_shapes = cfg["ext_shapes"]    # logical extended shapes
    modes = cfg["modes"]

    it = iter(refs)
    text_hbm = [next(it) for _ in range(5)]       # P,Vx,Vy,Vz,Rho (padded)
    flags_ref = next(it) if nfr else None         # SMEM (6,) i32
    fr_hbm = [next(it) for _ in range(nfr)]       # padded freeze planes
    outs = [next(it) for _ in range(4)]           # aliased to text inputs
    fv = [next(it) for _ in range(5)]             # resident field scratch
    lag = [next(it) for _ in range(4)]            # (2, 1, S1p, S2p)-ish
    fr_v = [next(it) for _ in range(nfr)]
    lsem = next(it)
    osem = next(it)
    fsem = next(it) if nfr else None

    k = pl.program_id(0)
    i = pl.program_id(1)
    a = i * bx
    sl = i % 2

    # One-time chunk-entry load: the five padded extended fields (and the
    # freeze planes) HBM -> VMEM.  Synchronous — once per K iterations.
    @pl.when((k == 0) & (i == 0))
    def _():
        cs = [pltpu.make_async_copy(text_hbm[j], fv[j], lsem.at[j])
              for j in range(5)]
        for c in cs:
            c.start()
        for c in cs:
            c.wait()

    if nfr:
        @pl.when((k == 0) & (i == 0))
        def _():
            cs = [pltpu.make_async_copy(fr_hbm[j], fr_v[j], fsem.at[j])
                  for j in range(nfr)]
            for c in cs:
                c.start()
            for c in cs:
                c.wait()

    # Band 0 has no predecessor: seed its low-margin lag slot with the
    # clamped duplicate of row 0 (the dup feeds only the band's outermost
    # V rows — shoulder garbage / frozen; the pressure rows never read
    # it, see the module docstring).
    @pl.when(i == 0)
    def _():
        for f in range(4):
            lag_w = lag[f].at[pl.ds(1, 1)]
            lag_w[:] = fv[f][pl.ds(0, 1)]

    # Save this band's tail row (about to be overwritten) for the next
    # band's low margin — VMEM-to-VMEM, one row per field, slot-alternated
    # (band i writes slot i%2, band i+1 reads it back as 1-(i+1)%2; band
    # 0 reads the seed above from the same uniform expression).
    for f in range(4):
        lag_w = lag[f].at[pl.ds(sl, 1)]
        lag_w[:] = fv[f][pl.ds(a + bx - 1, 1)]

    # Margin-1 windows.  Low margin: row a-1 — band i-1 already overwrote
    # it, so every band reads its lag slot.  High margins clamp at the
    # buffer end (top-band dups feed only shoulder/frozen V rows — the
    # pressure rows read real rows everywhere, module docstring).
    nrows = [ext_shapes[f][0] for f in range(5)]

    def window(f, extra):
        if f < 4:
            m1 = lag[f][pl.ds(1 - sl, 1)]
        else:
            m1 = fv[f][pl.ds(jnp.maximum(a - 1, 0), 1)]   # Rho: never
            # overwritten, clamped margin read straight from the buffer
        parts = [m1, fv[f][pl.ds(a, bx)]]
        top = nrows[f] - 1
        for e in range(1, extra + 1):
            parts.append(fv[f][pl.ds(jnp.minimum(a + bx + e - 1, top), 1)])
        return jnp.concatenate(parts, axis=0)

    def logical(W, f):
        # Slice the tile-padded trailing extents back to the field's
        # logical extended shape (values; Mosaic masks the lanes).
        return W[:, :ext_shapes[f][1], :ext_shapes[f][2]]

    Wp = logical(window(0, 1), 0)
    Wvx = logical(window(1, 2), 1)
    Wvy = logical(window(2, 1), 2)
    Wvz = logical(window(3, 1), 3)
    Wrho = logical(window(4, 1), 4)

    news = _band_update(Wp, Wvx, Wvy, Wvz, Wrho, bx=bx, scal=scal)

    # Halo handling on the new band values (freeze planes band-sliced to
    # logical extents; SMEM flags read as scalars).
    flags = ([flags_ref[j] for j in range(6)] if nfr else [0] * 6)
    frx, fryz = {}, {}
    j = 0
    for d in range(3):
        if modes[d] not in ("oext", "frozen"):
            continue
        for f in (1, 2, 3):
            pl_shape = [ext_shapes[f][x] for x in range(3) if x != d]
            for side in (0, 1):
                if d == 0:
                    frx[(f, side)] = fr_v[j][...][:pl_shape[0],
                                                  :pl_shape[1]]
                else:
                    fryz[(f, d, side)] = fr_v[j][pl.ds(a, bx)][
                        :, :pl_shape[1]]
                j += 1
    news = _band_halo(news, a, bx, flags, frx, fryz, cfg)

    # In-place write, padded back with the old trailing columns.
    for f in range(4):
        new = news[f]
        pady, padz = pads[f]
        old = fv[f][pl.ds(a, bx)]
        if padz:
            new = jnp.concatenate([new, old[:, :new.shape[1], -padz:]],
                                  axis=2)
        if pady:
            new = jnp.concatenate([new, old[:, -pady:, :]], axis=1)
        fv[f][pl.ds(a, bx)] = new

    # Final iteration: band write-back to the (aliased) outputs.
    # Synchronous — once per chunk; rows outside the band grid (Vx's top
    # face) keep their aliased entry values, exactly the frozen/no-write
    # semantics they need.
    @pl.when(k == K - 1)
    def _():
        cs = [pltpu.make_async_copy(fv[f].at[pl.ds(a, bx)],
                                    outs[f].at[pl.ds(a, bx)], osem.at[f])
              for f in range(4)]
        for c in cs:
            c.start()
        for c in cs:
            c.wait()


def _chunk_call(exts, Rho_ext, *, K, modes, grid, scal, ols, shapes,
                interpret=False):
    """Advance K coupled iterations on the extended buffers; returns the
    four central local blocks."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    E = 2 * K
    ext_shapes = [tuple(x.shape) for x in exts] + [tuple(Rho_ext.shape)]

    def central(F, f):
        for d in range(3):
            if modes[d] in ("ext", "oext"):
                F = lax.slice_in_dim(F, E, E + shapes[f][d], axis=d)
        return F

    if interpret:
        out = _window_iters_xla(*exts, Rho_ext, K=K, E=E, modes=modes,
                                grid=grid, scal=scal, ols=ols,
                                shapes=shapes)
        return tuple(central(F, f) for f, F in enumerate(out))

    S0e = ext_shapes[0][0]
    bx = _BX
    nb = S0e // bx
    cfg = dict(modes=tuple(modes), ols=tuple(ols[:4]),
               ext_shapes=tuple(ext_shapes), E=E,
               shapes=tuple(shapes[:4]))

    # Tile-pad the staggered trailing extents so every leading-dim VMEM
    # slice in the kernel is tile-aligned; the pad columns carry garbage
    # the central slices never see.
    def padded(F, f):
        s = F.shape
        py = _pad8(s[1]) - s[1]
        pz = _pad128(s[2]) - s[2]
        if py or pz:
            F = jnp.pad(F, [(0, 0), (0, py), (0, pz)])
        return F

    fields5 = [padded(F, f) for f, F in enumerate(list(exts) + [Rho_ext])]
    pads = [(_pad8(s[1]) - s[1], _pad128(s[2]) - s[2])
            for s in ext_shapes[:4]]

    # Open-dim freeze planes (chunk-entry boundary planes of the three
    # velocity fields) + per-device SMEM edge flags, as in the diffusion
    # chunk kernel ("frozen" dims statically flag both sides, so 1-device
    # frozen grids run under plain jax.jit).
    fr_planes = []
    flag_ops = []
    any_open = any(m in ("oext", "frozen") for m in modes)
    if any_open:
        for d in range(3):
            if modes[d] not in ("oext", "frozen"):
                continue
            lo = E if modes[d] == "oext" else 0
            for f in (1, 2, 3):
                hi = lo + shapes[f][d] - 1
                for idx in (lo, hi):
                    p = jnp.squeeze(
                        lax.slice_in_dim(exts[f], idx, idx + 1, axis=d), d)
                    ps = p.shape
                    py = _pad8(ps[0]) - ps[0]
                    pz = _pad128(ps[1]) - ps[1]
                    if py or pz:
                        p = jnp.pad(p, [(0, py), (0, pz)])
                    fr_planes.append(p)
        from .diffusion_trapezoid import _edge_flags

        flag_ops = [_edge_flags(modes, grid)]
    nfr = len(fr_planes)

    kern = partial(_kernel, K=K, bx=bx, scal=scal, cfg=cfg, nfr=nfr,
                   pads=pads)

    operands = [*fields5, *flag_ops, *fr_planes]
    vmas = [getattr(getattr(x, "aval", None), "vma", None)
            for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(s):
        return (jax.ShapeDtypeStruct(s, exts[0].dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(s, exts[0].dtype))

    # Scratch order MUST mirror the kernel's unpack: field/lag VMEM,
    # freeze-plane VMEM, load semaphores, out semaphores, then the
    # freeze-plane semaphore LAST (present only when a dim is open).
    fr_scratch = [pltpu.VMEM(p.shape, p.dtype) for p in fr_planes]
    out = pl.pallas_call(
        kern,
        grid=(K, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5
        + [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(flag_ops)
        + [pl.BlockSpec(memory_space=pl.ANY)] * nfr,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
        out_shape=[shp(F.shape) for F in fields5[:4]],
        # The entry buffers are dead after the (k==0, i==0) load; rows the
        # band grid never writes (Vx's top face) keep their entry values.
        input_output_aliases={0: 0, 1: 1, 2: 2, 3: 3},
        scratch_shapes=[pltpu.VMEM(F.shape, F.dtype) for F in fields5]
        + [pltpu.VMEM((2, F.shape[1], F.shape[2]), F.dtype)
           for F in fields5[:4]]
        + fr_scratch
        + [pltpu.SemaphoreType.DMA((5,)), pltpu.SemaphoreType.DMA((4,))]
        + ([pltpu.SemaphoreType.DMA((nfr,))] if nfr else []),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=128 * 1024 * 1024,
            dimension_semantics=("arbitrary", "arbitrary")),
    )(*operands)
    out = [F[:, :ext_shapes[f][1], :ext_shapes[f][2]]
           for f, F in enumerate(out)]
    return tuple(central(F, f) for f, F in enumerate(out))


# ---------------------------------------------------------------------------
# Chunk driver
# ---------------------------------------------------------------------------

def fused_stokes_trapezoid_iters(P, Vx, Vy, Vz, Rho, *, n_inner: int,
                                 K: int, dx, dy, dz, mu, dtP, dtV,
                                 interpret: bool = False):
    """Advance `n_inner // K` full K-iteration chunks (the caller handles
    the warm-up iteration before and the per-K remainder after, through
    the per-iteration fused kernel); returns
    `(P, Vx, Vy, Vz, iters_done)`.

    Entry contract — OVERLAP-CONSISTENT, exchange-fresh state: the
    duplicated overlap-region rows must be globally equal (every state
    reachable from a global-coordinates init — `init_fields`,
    `igg.coord_fields` — through per-iteration evolution is; an
    overlap-3 grid exchanges one plane per side, so `update_halo` alone
    cannot synchronize arbitrary interior duplicates, and the chunk
    windows read the NEIGHBOR's copy of those rows where the
    per-iteration path reads the local one).  The compiled dispatcher's
    warm-up iteration re-establishes halo freshness; consistency is an
    invariant of the model paths.  Call inside SPMD code (`igg.sharded`
    / shard_map); fully-frozen 1-device grids also run under plain
    `jax.jit`."""
    from jax import lax

    from .. import shared

    grid = shared.global_grid()
    modes = _dim_modes(grid)
    E = 2 * K
    shapes = _field_shapes(P.shape)
    ols = _ols(grid, shapes)
    scal = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
    chunks = n_inner // K

    # Rho never changes: its extension (one grouped ppermute set) is
    # hoisted out of the chunk loop entirely.
    Rho_ext = _extend_fields([Rho], [ols[4]], E, grid, modes)[0]

    def one(_, S):
        exts = _extend_fields(list(S), ols[:4], E, grid, modes)
        return _chunk_call(exts, Rho_ext, K=K, modes=modes, grid=grid,
                           scal=scal, ols=ols, shapes=shapes,
                           interpret=interpret)

    S = lax.fori_loop(0, chunks, one, (P, Vx, Vy, Vz))
    return (*S, chunks * K)
