"""K-iteration temporal-blocking (trapezoid chunk) tier for the Stokes
pseudo-transient solver.

`docs/stokes_roofline.md` proves the per-iteration fused kernel
(`stokes_pallas`, 0.143 ms/iter at 128^3 f32) is jointly DMA- and
VPU-bound with a ~2.1x-over-composition ceiling, and names the only
escape: temporal blocking.  This module is that escape — the four-field,
staggered-shapes instance of the shared K-step chunk engine
(`igg.ops.chunk_engine`):

  1. Once per K-iteration chunk, each device extends its block by
     `E = 2K` margin rows per extended dimension via ONE grouped
     `ppermute` pair per dimension per shape group (P and Vx share x-slab
     shapes and ride one permute, exactly like their shared plane shapes
     in `stokes_pallas`; Rho's loop-invariant extension is hoisted out of
     the chunk loop entirely) — `chunk_engine.extend_fields`.
  2. K coupled iterations run on the extended windows with NO exchange:
     per iteration the windows lose (at most) 2 rows of validity per
     extended side — the pseudo-transient chain (pressure update read by
     the velocity updates, Gauss-Seidel flavor) reads at most one row
     below and one above per iteration, so the `2K` margin over-provisions
     the front by 2x (the contract `igg.hide_communication` uses for the
     same chain, radius=2).  After K iterations the device's own block
     (interior AND halo rows) carries per-iteration-path values: every
     block row is produced by the identical `iteration_core` arithmetic
     the neighbor would apply to the same global cells.
  3. Wrap dims (single periodic device) re-apply the per-field staggered
     self-wrap each iteration; OPEN dims re-freeze the three velocity
     fields' boundary planes each iteration from VMEM-held chunk-entry
     freeze planes gated by SMEM `axis_index` edge flags (the
     "frozen"/"oext" mechanism the diffusion chunk kernel proved).
     Pressure is NOT frozen: the per-iteration path's full-shape pressure
     update writes the boundary plane too (its no-write halo fallback IS
     the computed plane — `stokes_pallas._sends_and_stales`), and the
     boundary-adjacent expressions read nothing beyond the frozen
     velocity planes, so a single frozen velocity plane per open side
     quarantines the beyond-domain shoulder garbage (worked through in
     `docs/stokes_roofline.md`).

Two realizations of the same window dynamics:

  - **Pure-XLA window path** (`_window_iters_xla`) — interpret mode, CPU
    meshes, the driver dryrun: `iteration_core` + `interior_add` on the
    full extended window per iteration through the engine's generic
    per-dim halo loop (`chunk_engine.window_chunk_xla`, velocities
    frozen on open dims).  This is the realization the 8-device mesh
    equivalence tests pin against `stokes3d.local_iteration`.
  - **Mosaic chunk kernel** — compiled mode: the engine's generic
    VMEM-resident banded kernel (`chunk_engine.resident_chunk_call`),
    instantiated with this family's config — five fields resident for
    the whole chunk (grid `(K, nb)`, "arbitrary" semantics), updated IN
    PLACE in x-row bands with a one-row lag buffer carrying each band's
    overwritten tail row to its successor (margin-1 windows, Vx's
    x-staggered high margin 2 — the per-iteration kernel's proven
    margins), velocities (fields 1-3) freeze-gated on open dims.  HBM
    traffic per chunk is ONE read of the five extended fields and ONE
    write of the four updated ones — `(5R+4W)/K` per iteration instead
    of the per-iteration kernel's `5R+4W`, the 1/K amortization the
    roofline demands.  `_band_update` — the shared per-band value
    computation — keeps `stokes3d.iteration_core` the single source of
    arithmetic truth, and is pinned against the window realization by
    the banded-scheme simulation in `tests/test_stokes_trapezoid.py`;
    the compiled instantiation is pinned on hardware by
    `tests/test_mega_tpu.py::test_stokes_trapezoid_matches_per_iteration`.

VMEM is the K-bound: the resident working set grows with `K` through the
`2K`-row extensions (plus the Vz lane padding the roofline documents), so
`stokes_trapezoid_supported` does the accounting against the shared
budget authority (`igg.ops._vmem.chunk_budget`) and `fit_stokes_K`
(`_vmem.fit_chunk_K`) picks the largest admissible K — at 128^3 f32 on
an `(N,1,1)` mesh that is K=8 (~70 MB modeled; K=16 would need the
2x-margin model past the 110 MB budget).  `docs/stokes_roofline.md`
carries the full analysis.

The compiled dispatcher (`stokes3d.make_iteration`) runs one per-iteration
fused kernel FIRST — consuming (and replacing) the entry halos exactly
like every other path, establishing the exchange-fresh window state the
validity argument requires — then `n_inner // K` chunks, then the
remainder through the per-iteration kernel.
"""

from __future__ import annotations

from functools import partial

from ._vmem import banded_vmem, chunk_budget, fit_banded, fit_chunk_K
from .chunk_engine import (admit_banded_geometry, admit_chunk_common,
                           admit_send_slabs, admit_sublane_extension,
                           band_halo,
                           dim_modes as _dim_modes, ext_shape as _ext_shape_e,
                           extend_dim_grouped, extend_fields, field_ols,
                           pad8 as _pad8, pad128 as _pad128,
                           resident_chunk_call, run_chunks,
                           streaming_chunk_call, window_chunk_xla,
                           wrap_edges as _wrap_edges)

_BX = 8          # x band height of the chunk kernel (rows per program)

# Engine aliases (historical private names, still used by tests/benchmarks).
_extend_dim_grouped = extend_dim_grouped
_extend_fields = extend_fields


def _field_shapes(shape):
    """Local shapes of (P, Vx, Vy, Vz, Rho) from the unstaggered P shape."""
    S0, S1, S2 = shape
    return [(S0, S1, S2), (S0 + 1, S1, S2), (S0, S1 + 1, S2),
            (S0, S1, S2 + 1), (S0, S1, S2)]


def _ols(grid, shapes):
    """Per-field per-dim staggered overlaps (`ol(dim, A)`,
    `/root/reference/src/shared.jl:81`)."""
    return field_ols(grid, shapes)


def _ext_shape(s, E, modes):
    return _ext_shape_e(s, E, modes)


def _vmem_need(shape, K, modes, itemsize: int = 4) -> int:
    """Modeled VMEM bytes of the resident chunk kernel at iteration depth
    K: the five tile-padded extended fields (Vy sublane-padded, Vz
    lane-padded — the 2x physical footprint of its `(S,S,S+1)` shape the
    roofline documents), the per-field lag rows, the open-dim freeze
    planes, and a 2x-margin band-temporary term for `iteration_core`'s
    stress/residual chain (~16 band-row intermediates; the 2x absorbs
    Mosaic's own scratch, the same calibration style as
    `stokes_pallas._vmem_need`)."""
    E = 2 * K
    exts = [_ext_shape(s, E, modes) for s in _field_shapes(shape)]
    need = sum(a * _pad8(b) * _pad128(c) for a, b, c in exts) * itemsize
    row = _pad8(exts[2][1]) * _pad128(exts[3][2]) * itemsize
    need += 2 * 4 * row                       # lag rows (2 slots x 4 fields)
    for d in range(3):                        # freeze planes (3 velocities)
        if modes[d] in ("oext", "frozen"):
            for a, b, c in exts[1:4]:
                plane = (a, b, c)[:d] + (a, b, c)[d + 1:]
                need += 2 * _pad8(plane[0]) * _pad128(plane[1]) * itemsize
    need += 2 * 16 * (_BX + 3) * row          # band temporaries, 2x margin
    return need


def stokes_trapezoid_supported(grid, shape, K: int, n_inner: int, dtype,
                               interpret: bool = False):
    """Whether the K-iteration Stokes chunk tier applies: overlap-3 grid
    (the per-iteration kernel's prerequisite — it runs the warm-up and
    remainder iterations), at least one full chunk, `E = 2K` send slabs
    inside every extended dimension's block (per-field staggered ol), the
    kernel's band/tile-alignment geometry, and the resident working set
    within the VMEM budget.  Both realizations take the same gates so
    interpret meshes exercise the compiled tier's exact admission
    decisions (the `diffusion_trapezoid` convention).  Returns an
    :class:`igg.degrade.Admission` (truthy/falsy) carrying the structured
    refusal reason."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if grid.overlaps != (3, 3, 3):
        return Admission.no(f"grid overlaps {grid.overlaps} != (3, 3, 3)")
    if tuple(shape) != tuple(grid.nxyz):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz)}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = _dim_modes(grid)
    E = 2 * K
    S0, S1, S2 = shape
    if S0 % _BX != 0 or S0 < 2 * _BX:
        return Admission.no(f"x extent {S0} not band-divisible "
                            f"(needs S0 % {_BX} == 0, S0 >= {2 * _BX})")
    if S1 % 8 != 0 or S2 % 128 != 0:
        # Mosaic tile-aligned leading-dim VMEM slices (staggered trailing
        # extents are padded by the kernel; the base extents must align).
        return Admission.no(f"local y/z extents ({S1}, {S2}) not Mosaic "
                            f"tile-aligned (y % 8, z % 128)")
    if modes[0] != "frozen" and (2 * E) % _BX != 0:
        # S0e = S0 + 2E must stay band-divisible.
        return Admission.no(f"extended x span S0 + {2 * E} not "
                            f"band-divisible by {_BX}")
    sub = admit_sublane_extension(E, modes)
    if sub is not None:
        # Central y window slice offset must stay on sublane tiles (the
        # shared engine gate — a structured refusal where Mosaic would
        # crash deep in lowering).
        return sub
    shapes = _field_shapes(shape)
    ols = _ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    need = _vmem_need(shape, K, modes)
    if need > chunk_budget():
        return Admission.no(f"resident working set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_stokes_K(grid, shape, n_inner: int, dtype,
                 interpret: bool = False, kmax: int = 8) -> int:
    """Largest admissible chunk depth K <= kmax (halving, >= 2;
    `_vmem.fit_chunk_K`); 0 when none applies.  Even K keeps
    `S0e = S0 + 4K` band-divisible on extended-x meshes."""
    return fit_chunk_K(
        lambda K: stokes_trapezoid_supported(grid, tuple(shape), K, n_inner,
                                             dtype, interpret=interpret),
        kmax)


# ---------------------------------------------------------------------------
# The shared per-band value computation (single source of arithmetic truth
# with the per-iteration paths: stokes3d.iteration_core)
# ---------------------------------------------------------------------------

def _band_update(Wp, Wvx, Wvy, Wvz, Wrho, *, bx, scal):
    """New band values (rows [a, a+bx), window row offset 1) from margin-1
    windows: P/Vy/Vz/Rho window rows [a-1, a+bx+1), the x-staggered Vx
    [a-1, a+bx+2) — the per-iteration kernel's proven minimal margins
    (`stokes_pallas._kernel`: out row j <-> ext row j+1 <-> increment
    row j).  Rho's margin rows are dummies (read row-locally).  Pure
    values: shared verbatim by the Mosaic kernel and the banded-scheme
    simulation test."""
    import jax.numpy as jnp

    from ..models.stokes3d import iteration_core

    Pn, dVx, dVy, dVz = iteration_core(Wp, Wvx, Wvy, Wvz, Wrho, **scal)
    outs = [Pn[1:1 + bx]]
    for W, dV in ((Wvx, dVx), (Wvy, dVy), (Wvz, dVz)):
        o = W[1:1 + bx]
        inner = o[:, 1:-1, 1:-1] + dV[0:bx]
        mid = jnp.concatenate([o[:, 1:-1, 0:1], inner, o[:, 1:-1, -1:]],
                              axis=2)
        outs.append(jnp.concatenate([o[:, 0:1, :], mid, o[:, -1:, :]],
                                    axis=1))
    return tuple(outs)


def _band_halo(news, a, bx, flags, frx, fryz, cfg):
    """Per-band halo handling of the four new-band value arrays — the
    engine's generic `chunk_engine.band_halo` with this family's freeze
    set (the three velocities).  Kept as the historical entry point for
    the banded-scheme simulation test."""
    cfg = dict(cfg)
    cfg.setdefault("freeze_fields", (1, 2, 3))
    return band_halo(news, a, bx, flags, frx, fryz, cfg)


# ---------------------------------------------------------------------------
# Pure-XLA window realization (interpret mode / CPU meshes)
# ---------------------------------------------------------------------------

def _window_iters_xla(Pe, Vxe, Vye, Vze, Rhoe, *, K, E, modes, grid, scal,
                      ols, shapes):
    """K coupled iterations on the extended windows: full-window
    `iteration_core` + `interior_add`, then the engine's per-dim halo
    handling in dimension order — wrap dims self-wrap with per-field
    staggered ol; open dims re-freeze the VELOCITY shoulder+boundary band
    from the chunk-entry buffers on the global-edge devices (pressure is
    computed everywhere, its boundary value being the per-iteration
    path's computed no-write plane).  The freeze width differs from the
    Mosaic kernel (whole shoulder band vs exactly the boundary plane);
    the two agree on the central window because influence from the
    shoulder can only pass THROUGH the frozen boundary plane, which
    never reads it (the diffusion chunk kernel's quarantine argument,
    radius checked for the coupled chain in `docs/stokes_roofline.md`)."""
    from .stencil import interior_add

    def core(P, Vx, Vy, Vz):
        from ..models.stokes3d import iteration_core

        P, dVx, dVy, dVz = iteration_core(P, Vx, Vy, Vz, Rhoe, **scal)
        return (P, interior_add(Vx, dVx), interior_add(Vy, dVy),
                interior_add(Vz, dVz))

    return window_chunk_xla((Pe, Vxe, Vye, Vze), K=K, E=E, modes=modes,
                            grid=grid, ols=ols, shapes=shapes,
                            freeze_fields=(1, 2, 3), core=core)


# ---------------------------------------------------------------------------
# The Mosaic chunk realization: the engine's generic resident banded kernel
# ---------------------------------------------------------------------------

def _chunk_call(exts, Rho_ext, *, K, modes, grid, scal, ols, shapes,
                interpret=False):
    """Advance K coupled iterations on the extended buffers; returns the
    four central local blocks.  Compiled mode runs the engine's generic
    VMEM-resident banded kernel with this family's config (4 updated
    fields + const Rho, Vx's x-staggered high margin 2, velocities
    frozen on open dims); interpret mode runs the pure-XLA window
    realization."""
    E = 2 * K

    def window():
        return _window_iters_xla(*exts, Rho_ext, K=K, E=E, modes=modes,
                                 grid=grid, scal=scal, ols=ols,
                                 shapes=shapes)

    return resident_chunk_call(
        list(exts), [Rho_ext], K=K, bx=_BX, modes=modes, grid=grid,
        ols=ols, shapes=shapes, E=E,
        band_update=partial(_band_update, scal=scal),
        extras=(1, 2, 1, 1, 1), freeze_fields=(1, 2, 3),
        window_fallback=window, interpret=interpret)


# ---------------------------------------------------------------------------
# Chunk driver
# ---------------------------------------------------------------------------

def fused_stokes_trapezoid_iters(P, Vx, Vy, Vz, Rho, *, n_inner: int,
                                 K: int, dx, dy, dz, mu, dtP, dtV,
                                 interpret: bool = False):
    """Advance `n_inner // K` full K-iteration chunks (the caller handles
    the warm-up iteration before and the per-K remainder after, through
    the per-iteration fused kernel); returns
    `(P, Vx, Vy, Vz, iters_done)`.

    Entry contract — OVERLAP-CONSISTENT, exchange-fresh state: the
    duplicated overlap-region rows must be globally equal (every state
    reachable from a global-coordinates init — `init_fields`,
    `igg.coord_fields` — through per-iteration evolution is; an
    overlap-3 grid exchanges one plane per side, so `update_halo` alone
    cannot synchronize arbitrary interior duplicates, and the chunk
    windows read the NEIGHBOR's copy of those rows where the
    per-iteration path reads the local one).  The compiled dispatcher's
    warm-up iteration re-establishes halo freshness; consistency is an
    invariant of the model paths.  Call inside SPMD code (`igg.sharded`
    / shard_map); fully-frozen 1-device grids also run under plain
    `jax.jit`."""
    from .. import shared

    grid = shared.global_grid()
    modes = _dim_modes(grid)
    E = 2 * K
    shapes = _field_shapes(P.shape)
    ols = _ols(grid, shapes)
    scal = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)

    # Rho never changes: its extension (one grouped ppermute set) is
    # hoisted out of the chunk loop entirely.
    Rho_ext = extend_fields([Rho], [ols[4]], E, grid, modes)[0]

    def one(P, Vx, Vy, Vz):
        exts = extend_fields([P, Vx, Vy, Vz], ols[:4], E, grid, modes)
        return _chunk_call(exts, Rho_ext, K=K, modes=modes, grid=grid,
                           scal=scal, ols=ols, shapes=shapes,
                           interpret=interpret)

    *S, done = run_chunks((P, Vx, Vy, Vz), n_inner=n_inner, K=K,
                          one_chunk=one)
    return (*S, done)


# ---------------------------------------------------------------------------
# The STREAMING banded tier (stokes3d.banded): rolling-window realization
# for the shapes the resident kernel's K-bound refuses
# ---------------------------------------------------------------------------

def stokes_banded_supported(grid, shape, K: int, n_inner: int, dtype,
                            B: int = 8, interpret: bool = False):
    """Whether the STREAMING banded Stokes chunk tier applies at depth
    K / band B: the resident tier's structural gates minus the K-bound
    — the rolling window (five staggered fields, Vx's high margin 2,
    const Rho streamed per band) is O(B), so this rung admits at the
    160^3+/256^3 shapes `fit_stokes_K` refuses.  Returns an
    :class:`igg.degrade.Admission`."""
    import numpy as np

    from ..degrade import Admission

    common = admit_chunk_common(grid, K, n_inner)
    if common is not None:
        return common
    if grid.overlaps != (3, 3, 3):
        return Admission.no(f"grid overlaps {grid.overlaps} != (3, 3, 3)")
    if tuple(shape) != tuple(grid.nxyz):
        return Admission.no(f"local shape {tuple(shape)} != grid block "
                            f"{tuple(grid.nxyz)}")
    if np.dtype(dtype) != np.float32:
        return Admission.no(f"dtype {np.dtype(dtype)} is not float32")
    modes = _dim_modes(grid)
    E = 2 * K
    shapes = _field_shapes(shape)
    ols = _ols(grid, shapes)
    slabs = admit_send_slabs(shapes, ols, E, modes, grid=grid)
    if slabs is not None:
        return slabs
    geo = admit_banded_geometry(shapes, E, modes, B=B,
                                extras=(1, 2, 1, 1, 1),
                                interpret=interpret)
    if geo is not None:
        return geo
    exts = [_ext_shape(s, E, modes) for s in shapes]
    need = banded_vmem(exts, B, (1, 2, 1, 1, 1), 4, modes=modes,
                      freeze_fields=(1, 2, 3))
    if need > chunk_budget():
        return Admission.no(f"banded window set {need} bytes exceeds "
                            f"the VMEM budget {chunk_budget()}")
    return Admission.yes()


def fit_stokes_band(grid, shape, n_inner: int, dtype,
                    interpret: bool = False, kmax: int = 8,
                    bands=(8, 16)):
    """Largest admissible `(K, B)` for the banded tier
    (`_vmem.fit_banded`); None when none applies."""
    return fit_banded(
        lambda K, B: stokes_banded_supported(grid, tuple(shape), K,
                                             n_inner, dtype, B=B,
                                             interpret=interpret),
        kmax, bands=bands)


def fused_stokes_banded_iters(P, Vx, Vy, Vz, Rho, *, n_inner: int,
                              K: int, B: int, dx, dy, dz, mu, dtP, dtV,
                              interpret: bool = False):
    """Advance `n_inner // K` full K-iteration chunks through the
    STREAMING banded realization (`chunk_engine.streaming_chunk_call` —
    same `_band_update` core and margins as the resident tier, rolling
    VMEM window of band depth B, Rho streamed from its hoisted extended
    buffer per band); returns `(P, Vx, Vy, Vz, iters_done)`.  Same
    entry contract as :func:`fused_stokes_trapezoid_iters`."""
    from .. import shared

    grid = shared.global_grid()
    modes = _dim_modes(grid)
    E = 2 * K
    shapes = _field_shapes(P.shape)
    ols = _ols(grid, shapes)
    scal = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)

    Rho_ext = extend_fields([Rho], [ols[4]], E, grid, modes)[0]

    def one(P, Vx, Vy, Vz):
        exts = extend_fields([P, Vx, Vy, Vz], ols[:4], E, grid, modes)
        return streaming_chunk_call(
            list(exts), [Rho_ext], K=K, B=B, modes=modes, grid=grid,
            ols=ols, shapes=shapes, E=E,
            band_update=partial(_band_update, scal=scal),
            extras=(1, 2, 1, 1, 1), freeze_fields=(1, 2, 3),
            interpret=interpret)

    *S, done = run_chunks((P, Vx, Vy, Vz), n_inner=n_inner, K=K,
                          one_chunk=one)
    return (*S, done)
