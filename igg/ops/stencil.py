"""Shared stencil-assembly utilities (plain XLA, model-agnostic)."""

from __future__ import annotations


def interior_add(A, delta, pad_width=1):
    """`A.at[interior].add(delta)` expressed as `A + zero-pad(delta)`:
    boundaries add exactly zero (the reference's no-write semantics) and
    the pad fuses into the producing pass — `.at[...].add` is a
    dynamic-update-slice that XLA turns into an extra full-array copy
    (measured: removing three of them made the Stokes iteration 4.2x
    faster on v5e).  `pad_width` follows `jnp.pad` (int or per-axis
    pairs, e.g. `((1,1),(0,0))` for a dim-0-staggered 2-D field)."""
    import jax.numpy as jnp

    return A + jnp.pad(delta, pad_width)
