"""Shared stencil-ASSEMBLY utilities (plain XLA, model-agnostic).

Naming note: this is `igg.ops.stencil` — low-level kernel/composition
assembly helpers the hand-written models AND the `igg.stencil` lowering
share.  The user-facing define-your-own-physics frontend is the PACKAGE
`igg.stencil` (`from igg import stencil`); nothing is re-exported
between the two, so the import direction is unambiguous — specs and
compilation from `igg.stencil`, assembly helpers from `igg.ops`
(`from igg.ops import interior_add`)."""

from __future__ import annotations


def interior_add(A, delta, pad_width=1):
    """`A.at[interior].add(delta)` expressed as `A + zero-pad(delta)`:
    boundaries add exactly zero (the reference's no-write semantics) and
    the pad fuses into the producing pass — `.at[...].add` is a
    dynamic-update-slice that XLA turns into an extra full-array copy
    (measured: removing three of them made the Stokes iteration 4.2x
    faster on v5e).  `pad_width` follows `jnp.pad` (int or per-axis
    pairs, e.g. `((1,1),(0,0))` for a dim-0-staggered 2-D field)."""
    import jax.numpy as jnp

    return A + jnp.pad(delta, pad_width)
