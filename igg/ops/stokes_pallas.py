"""Fused Pallas Stokes iteration — mesh-capable (any dims / periodicity).

One `pallas_call` performs a full pseudo-transient Stokes iteration —
pressure update, six stresses, three momentum residuals, velocity updates,
AND the grouped halo update of P/Vx/Vy/Vz — reading each field once and
writing each updated field once.  The XLA composition
(`stokes3d.local_iteration`: compute + `update_halo_local(P,Vx,Vy,Vz)`)
costs ~0.27 ms/iter at 128^3 on v5e, of which the 4-field halo phase alone
is ~0.26 ms measured in isolation (each field pays its own read+write
assembly pass, plus XLA's multi-field layout copies); the fused kernel's
traffic is the ideal 5 reads + 4 writes.

This is the TPU re-expression of the reference's native-kernel performance
tier (">10x faster" than the array-broadcast form,
`/root/reference/README.md:161`) for BASELINE config 5's Stokes solver, on
*every* rank of a decomposed run — the per-rank property of the
reference's native tier — not just the single-device configuration.

Measured on v5e at 128^3 f32 (median-of-3, 100-iteration dispatches,
self-wrap grid): **0.143 ms/iter** vs 0.224 for the XLA composition
(1.57x; round-5 artifact refresh — the round-5 ext-plane writer gate
also sped the composition itself up from 0.278);
matches the XLA path to ~1e-7 relative on the chip (identical
`iteration_core` arithmetic).  The DMA floor of this structure measured
with a no-op core is 0.108 ms (~790 GB/s on ~85 MB/iter of traffic,
including the 2x lane padding of Vz's (S,S,S+1) shape), so the remaining
gap to ideal is non-overlapped VPU time.  `docs/stokes_roofline.md`
carries the full traffic accounting: the structure is jointly DMA- and
VPU-bound and its ceiling is ~2.1x over the round-5 composition — no
per-iteration kernel of this solver reaches 3x at f32 128^3; only
temporal blocking or bf16 break the bound.

Structure (the radius-2 staggered four-field instance of the
`diffusion_pallas` recipe):

1. **Send planes from thin-window recomputation** — the updated inner
   boundary planes `ol-1` / `s-ol` of each exchanged field (per-field
   staggered `ol`, reference `/root/reference/src/shared.jl:81`) are
   produced by `compute_iteration` on 5-cell-row windows (the staggered
   field of the window's axis contributes 6 face rows), O(s²) work
   data-independent of the main kernel.  z windows are computed TRANSPOSED
   (axes 1<->2, Vy/Vz slots and dy/dz swapped, `buoy_axis=1` keeping the
   buoyancy on physical Vz), yielding the squeezed z planes directly — a
   `(S0,S1,5)` window would be lane-padded ~26x through the whole radius-2
   temporary chain.
2. **Dimension-sequential plane exchange** — `exchange_all_dims_grouped`
   over the four fields (P and Vx share plane shapes and ride one
   ppermute; Vy/Vz planes are staggered-shaped), with corner/edge
   propagation, open-boundary stale fallbacks, and self-wrap local copies
   (`/root/reference/src/update_halo.jl:36,130,516-532`).
3. **Fused compute + assembly kernel** — grid over x-slabs of `bx` rows;
   each program reads its slab plus 2 (3 for the x-staggered Vx) margin
   rows per side as single-row block refs with modular index maps — edge
   programs read wrapped rows whose results land only in halo rows that
   the halo phase overwrites.  The slab arithmetic is LITERALLY
   `stokes3d.iteration_core` — one source of truth with the XLA path.
   Received planes are assembled in dimension order: x planes by the edge
   programs, then y rows, then z columns winning the shared corners.
   Per-dimension halo modes as in `diffusion_pallas`: y/z dims periodic
   with a single device are in-VMEM self-wrap aliases (per-field staggered
   `ol`); exchanged or open dims take received/stale planes as blocked
   inputs.  Vx's extra global row `S0` lies outside the block grid; it is
   the x-side `s-1` halo row, assembled after the kernel from the received
   x plane with the y/z updates applied on top (one cheap dim-0 DUS).

Semantics match :func:`igg.hide_communication` exactly — which for the
slice-based `iteration_core` means identical to the plain sequential
composition *everywhere*, including the open-boundary planes that the
full-shape pressure update writes (the no-write fallback planes are
window-computed, see `_sends_and_stales`); decomposition invariance holds
on any mesh.

Requirements: overlap 3 (the radius-2 chain), unstaggered-pressure 3-D
local blocks large enough to slab, equal f32 dtypes; any device count and
periodicity.  Multi-device z decompositions pay a per-iteration strided
z-window extraction (~2 lane-tile passes); prefer `(N,1,1)`/`(N,M,1)`
meshes where z stays device-local, as with the diffusion kernel.
"""

from __future__ import annotations

from functools import partial

from .diffusion_pallas import _wrap_dims, _wrap_set

# Deliberately TIGHT when the working set allows: the scoped-vmem budget
# steers Mosaic's scheduling, and a small budget produces far better
# DMA/compute interleaving for this kernel.  Swept on v5e at 128^3
# (median-of-3, ms/iter): 20MB 0.138, 26MB 0.137, 32MB 0.136, 44MB 0.139,
# 56MB 0.157, 64MB 0.175, 100MB 0.224, 128MB 0.40.  At 128^3 the working
# set sits below 20MB and the floor budget applies; larger y*z areas
# (256^3-class) NEED more than the floor — the per-call limit grows with
# `_vmem_need` up to the hard cap (round 5: 256^3 OOM'd at Mosaic compile
# under the fixed 32MB budget; with the grown 82MB budget it runs
# bx=8 at 2.19 ms/iter vs 7.42 XLA — 3.4x — the shipped-and-measured
# configuration).
from ._vmem import fit_bx, vmem_limit


def _vmem_limit(bx: int, S1: int, S2: int) -> int:
    return vmem_limit(_vmem_need(bx, S1, S2))


def _vmem_need(bx: int, S1: int, S2: int, itemsize: int = 4) -> int:
    """VMEM bytes the fused iteration's windows demand at slab height
    `bx`: per input field (P,Vx,Vy,Vz) a bx-row center window plus 2-3
    single-row side windows, a bx-row Rho window, four bx-row outputs —
    all double-buffered — plus ~10 single-buffered plane windows.  The
    row count is `9*bx + 10` to first order; the 2.0x margin absorbs
    Mosaic's own scratch (calibrated against the observed 256^3 compile
    footprint: 69.3 MB demanded where the first-order model says 41)."""
    rows = 9 * bx + 10
    return int(2 * rows * S1 * S2 * itemsize * 2.0)


def _fit_bx(bx: int, S0: int, S1: int, S2: int,
            check_vmem: bool = True) -> int:
    return fit_bx(_vmem_need, bx, S0, S1, S2, min_bx=4,
                  check_vmem=check_vmem)


def stokes_pallas_supported(grid, P, interpret: bool = False):
    """Whether the fused iteration applies: overlap-3 grid (any device
    count and any periodicity — the exchange engine handles open boundaries
    and multi-device meshes), unstaggered-pressure local block large enough
    to slab, and some slab height whose windows fit VMEM (large y*z areas
    push the per-slab windows past the budget — caught by the round-5
    256^3 probe, where the unguarded kernel OOM'd at Mosaic compile).
    Returns an :class:`igg.degrade.Admission` (truthy/falsy) carrying the
    structured refusal reason."""
    from ..degrade import Admission

    if grid.overlaps != (3, 3, 3):
        return Admission.no(f"grid overlaps {grid.overlaps} != (3, 3, 3)")
    if P.ndim != 3:
        return Admission.no(f"pressure rank {P.ndim} != 3")
    s = tuple(grid.local_shape_any(P))
    if s != tuple(grid.nxyz):
        return Admission.no(f"staggered local shape {s} != grid block "
                            f"{tuple(grid.nxyz)}")
    if not (s[0] % 8 == 0 and s[0] >= 16 and s[1] >= 8 and s[2] >= 8):
        return Admission.no(f"local block {s} too small to slab "
                            f"(needs x % 8 == 0, x >= 16, y >= 8, z >= 8)")
    if _fit_bx(8, s[0], s[1], s[2], check_vmem=not interpret) < 4:
        return Admission.no(f"no slab height bx >= 4 fits the VMEM budget "
                            f"for local y*z area {s[1]}x{s[2]}")
    return Admission.yes()


def _win_x(P, Vx, Vy, Vz, Rho, scal, lo, hi):
    """`compute_iteration` on the contiguous x window of cell rows
    [lo, hi) (Vx contributes hi+1 face rows): valid updated cell rows are
    the window interior."""
    from jax import lax

    from ..models.stokes3d import compute_iteration

    cut = lambda A: lax.slice_in_dim(A, lo, hi, axis=0)
    cutx = lambda A: lax.slice_in_dim(A, lo, hi + 1, axis=0)
    return compute_iteration(cut(P), cutx(Vx), cut(Vy), cut(Vz), cut(Rho),
                             **scal)


def _win_y(P, Vx, Vy, Vz, Rho, scal, lo, hi):
    from jax import lax

    from ..models.stokes3d import compute_iteration

    cut = lambda A: lax.slice_in_dim(A, lo, hi, axis=1)
    cuty = lambda A: lax.slice_in_dim(A, lo, hi + 1, axis=1)
    return compute_iteration(cut(P), cut(Vx), cuty(Vy), cut(Vz), cut(Rho),
                             **scal)


def _win_z(P, Vx, Vy, Vz, Rho, scal, lo, hi):
    """TRANSPOSED z window: axes 1<->2, Vy/Vz slots and dy/dz swapped,
    buoyancy kept on physical Vz via `buoy_axis=1`.  Returns the updated
    transposed windows in PHYSICAL field order (P, Vx, Vy, Vz)."""
    import jax.numpy as jnp
    from jax import lax

    from ..models.stokes3d import compute_iteration

    cut = lambda A: jnp.swapaxes(lax.slice_in_dim(A, lo, hi, axis=2), 1, 2)
    cutz = lambda A: jnp.swapaxes(lax.slice_in_dim(A, lo, hi + 1, axis=2),
                                  1, 2)
    swapped = dict(scal)
    swapped["dy"], swapped["dz"] = scal["dz"], scal["dy"]
    Pt, Vxt, Vzt, Vyt = compute_iteration(
        cut(P), cut(Vx), cutz(Vz), cut(Vy), cut(Rho), **swapped,
        buoy_axis=1)
    return Pt, Vxt, Vyt, Vzt


def _sends_and_stales(P, Vx, Vy, Vz, Rho, scal, wrap_yz):
    """Keepdims send planes (updated inner planes `ol-1` / `s-ol`, staggered
    per field) and the open-boundary no-write fallback planes for the four
    exchanged fields, as parallel lists of `{(dim, side): plane}` dicts for
    `exchange_all_dims_grouped`.  Wrapped y/z dims need neither.

    The fallback planes are the *window-computed* outermost planes, NOT the
    pre-iteration ones: the full-shape pressure update writes its outermost
    planes too, and the plain composition (reference no-write semantics,
    `/root/reference/test/test_update_halo.jl:727-732`) keeps those computed
    values at an open boundary.  Window row values equal full-array row
    values because `iteration_core` is slice-based (see `igg.overlap`,
    same contract)."""
    import jax.numpy as jnp

    wy, wz = wrap_yz
    S0, S1, S2 = P.shape
    sends = [{}, {}, {}, {}]
    stales = [{}, {}, {}, {}]

    def put(side, d, planes, stale_planes):
        for i, pl_ in enumerate(planes):
            sends[i][(d, side)] = pl_
        for i, pl_ in enumerate(stale_planes):
            stales[i][(d, side)] = pl_

    # x: low window cells [0,5) -> updated row 2 (= ol-1) for P/Vy/Vz, row 3
    # for the x-staggered Vx (ol=4); high window cells [S0-5,S0) -> updated
    # row S0-3 (= s-ol) for every field.  Fallbacks: the windows' outermost
    # updated planes (local 0 low; local 4, or 5 for the staggered field,
    # high).
    Pw, Vxw, Vyw, Vzw = _win_x(P, Vx, Vy, Vz, Rho, scal, 0, 5)
    put(0, 0, (Pw[2:3], Vxw[3:4], Vyw[2:3], Vzw[2:3]),
        (Pw[0:1], Vxw[0:1], Vyw[0:1], Vzw[0:1]))
    Pw, Vxw, Vyw, Vzw = _win_x(P, Vx, Vy, Vz, Rho, scal, S0 - 5, S0)
    put(1, 0, (Pw[2:3], Vxw[2:3], Vyw[2:3], Vzw[2:3]),
        (Pw[4:5], Vxw[5:6], Vyw[4:5], Vzw[4:5]))

    if not wy:
        Pw, Vxw, Vyw, Vzw = _win_y(P, Vx, Vy, Vz, Rho, scal, 0, 5)
        put(0, 1, (Pw[:, 2:3], Vxw[:, 2:3], Vyw[:, 3:4], Vzw[:, 2:3]),
            (Pw[:, 0:1], Vxw[:, 0:1], Vyw[:, 0:1], Vzw[:, 0:1]))
        Pw, Vxw, Vyw, Vzw = _win_y(P, Vx, Vy, Vz, Rho, scal, S1 - 5, S1)
        put(1, 1, (Pw[:, 2:3], Vxw[:, 2:3], Vyw[:, 2:3], Vzw[:, 2:3]),
            (Pw[:, 4:5], Vxw[:, 4:5], Vyw[:, 5:6], Vzw[:, 4:5]))
    if not wz:
        ex = lambda W, j: jnp.expand_dims(W[:, j, :], 2)
        Pw, Vxw, Vyw, Vzw = _win_z(P, Vx, Vy, Vz, Rho, scal, 0, 5)
        put(0, 2, (ex(Pw, 2), ex(Vxw, 2), ex(Vyw, 2), ex(Vzw, 3)),
            (ex(Pw, 0), ex(Vxw, 0), ex(Vyw, 0), ex(Vzw, 0)))
        Pw, Vxw, Vyw, Vzw = _win_z(P, Vx, Vy, Vz, Rho, scal, S2 - 5, S2)
        put(1, 2, (ex(Pw, 2), ex(Vxw, 2), ex(Vyw, 2), ex(Vzw, 2)),
            (ex(Pw, 4), ex(Vxw, 4), ex(Vyw, 4), ex(Vzw, 5)))
    return sends, stales


def _kernel(*refs, bx, nb, shapes, scal, wrap_y, wrap_z):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from ..models.stokes3d import iteration_core
    from .diffusion_pallas import _ref_taker

    take = _ref_taker(refs)

    # Extended slabs: rows [a-1, a+bx+1) of each field (the x-staggered Vx
    # one row more).  Minimal margins — out rows that would read beyond them
    # are halo rows overwritten below.  Rho is read row-locally, so its
    # margin rows are dummies taken from the center block (values unused).
    m1, cP, p1 = take(3)
    eP = jnp.concatenate([m1[:], cP[:], p1[:]], axis=0)
    m1, cVx, p1, p2 = take(4)
    eVx = jnp.concatenate([m1[:], cVx[:], p1[:], p2[:]], axis=0)
    m1, cVy, p1 = take(3)
    eVy = jnp.concatenate([m1[:], cVy[:], p1[:]], axis=0)
    m1, cVz, p1 = take(3)
    eVz = jnp.concatenate([m1[:], cVz[:], p1[:]], axis=0)
    (cRho,) = take(1)
    r = cRho[:]
    eRho = jnp.concatenate([r[0:1], r, r[0:1]], axis=0)
    pf, vxf, vyf, vzf = take(4)        # x first planes (squeezed)
    pl_, vyl, vzl = take(3)            # x last planes (Vx's is post-kernel)
    y_in = take(0 if wrap_y else 8)    # (P f,l, Vx f,l, Vy f,l, Vz f,l)
    z_in = take(0 if wrap_z else 8)
    oP, oVx, oVy, oVz = take(4)

    Pn, dVx, dVy, dVz = iteration_core(eP, eVx, eVy, eVz, eRho, **scal)

    # Output rows j ↔ ext rows j+1; increments are on the ext interior
    # (offset 1), so out row j ↔ increment row j.
    oP[:] = Pn[1:1 + bx]
    for o_ref, ext, dV in ((oVx, eVx, dVx), (oVy, eVy, dVy), (oVz, eVz, dVz)):
        o_ref[:] = ext[1:1 + bx]
        o_ref[:, 1:-1, 1:-1] = (ext[1:1 + bx, 1:-1, 1:-1]
                                + dV[0:bx])

    i = pl.program_id(0)

    # x halo planes (dimension-sequential: x first, y/z own shared cells).
    @pl.when(i == 0)
    def _():
        oP[0:1] = pf[:][None]
        oVx[0:1] = vxf[:][None]
        oVy[0:1] = vyf[:][None]
        oVz[0:1] = vzf[:][None]

    @pl.when(i == nb - 1)
    def _():
        oP[bx - 1:bx] = pl_[:][None]
        oVy[bx - 1:bx] = vyl[:][None]
        oVz[bx - 1:bx] = vzl[:][None]
        # Vx's last halo row is global row S0, outside the block grid —
        # written by the caller after the kernel.

    # y halo rows (full x/z extent; z writes own the shared cells below).
    if wrap_y:
        for o_ref, (_, sy, sz), oly in ((oP, shapes[0], 3),
                                        (oVx, shapes[1], 3),
                                        (oVy, shapes[2], 4),
                                        (oVz, shapes[3], 3)):
            o_ref[:, 0:1, :] = o_ref[:, sy - oly:sy - oly + 1, :]
            o_ref[:, sy - 1:sy, :] = o_ref[:, oly - 1:oly, :]
    else:
        for o_ref, (_, sy, _), f, l in (
                (oP, shapes[0], y_in[0], y_in[1]),
                (oVx, shapes[1], y_in[2], y_in[3]),
                (oVy, shapes[2], y_in[4], y_in[5]),
                (oVz, shapes[3], y_in[6], y_in[7])):
            o_ref[:, 0:1, :] = jnp.expand_dims(f[:], 1)
            o_ref[:, sy - 1:sy, :] = jnp.expand_dims(l[:], 1)
    # z halo columns (own all shared corners).
    if wrap_z:
        for o_ref, (_, _, sz), olz in ((oP, shapes[0], 3),
                                       (oVx, shapes[1], 3),
                                       (oVy, shapes[2], 3),
                                       (oVz, shapes[3], 4)):
            o_ref[:, :, 0:1] = o_ref[:, :, sz - olz:sz - olz + 1]
            o_ref[:, :, sz - 1:sz] = o_ref[:, :, olz - 1:olz]
    else:
        for o_ref, (_, _, sz), f, l in (
                (oP, shapes[0], z_in[0], z_in[1]),
                (oVx, shapes[1], z_in[2], z_in[3]),
                (oVy, shapes[2], z_in[4], z_in[5]),
                (oVz, shapes[3], z_in[6], z_in[7])):
            o_ref[:, :, 0:1] = jnp.expand_dims(f[:], 2)
            o_ref[:, :, sz - 1:sz] = jnp.expand_dims(l[:], 2)


def fused_stokes_iteration(P, Vx, Vy, Vz, Rho, *, dx, dy, dz, mu, dtP, dtV,
                           bx: int = 8, interpret: bool = False):
    """One fused Stokes pseudo-transient iteration
    `(P, Vx, Vy, Vz, Rho) -> (P', Vx', Vy', Vz')` with halo maintenance
    included, on any mesh (see module docstring).  Call inside SPMD code
    (`igg.sharded` / shard_map); on a 1-device grid the exchange
    degenerates to local copies and the function also works under plain
    `jax.jit`.  Matches `stokes3d.local_iteration(..., overlap=True)` to
    Mosaic-vs-XLA rounding (overlap semantics are built in)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from .. import shared
    from ..halo import active_dims, exchange_all_dims_grouped

    grid = shared.global_grid()
    S0, S1, S2 = P.shape
    # Shrink the slab height until it divides S0 AND (compiled mode) its
    # windows fit the VMEM budget, which scales with S1*S2 (`_vmem_need`).
    bx = _fit_bx(bx, S0, S1, S2, check_vmem=not interpret)
    if bx < 4:
        raise ValueError(
            f"x size {S0} not divisible into slabs of >= 4 rows whose "
            f"windows fit VMEM at y*z area {S1}x{S2}")
    nb = S0 // bx
    scal = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
    shapes = [P.shape, Vx.shape, Vy.shape, Vz.shape, Rho.shape]
    wrap_yz = _wrap_dims(grid)
    wy, wz = wrap_yz
    wrap = _wrap_set(wrap_yz)

    fields = [P, Vx, Vy, Vz]
    sends, stales = _sends_and_stales(P, Vx, Vy, Vz, Rho, scal, wrap_yz)
    dims_actives = [active_dims(F.shape, grid) for F in fields]
    recvs = exchange_all_dims_grouped(
        [F.shape for F in fields], sends, dims_actives, grid,
        stales=stales, wraps=[wrap] * 4, blocks=fields)
    rq = [{d: (jnp.squeeze(a, d), jnp.squeeze(b, d))
           for d, (a, b) in r.items()} for r in recvs]

    operands, in_specs = [], []
    for F in (P, Vx, Vy, Vz, Rho):
        sx = F.shape[0]
        yz = F.shape[1:]
        if F is Rho:
            rows = ["c"]                    # row-local reads only
        elif F is Vx:
            rows = [-1, "c", bx, bx + 1]    # staggered: one extra top row
        else:
            rows = [-1, "c", bx]
        for r in rows:
            operands.append(F)
            if r == "c":
                in_specs.append(pl.BlockSpec((bx, *yz),
                                             lambda i: (i, 0, 0)))
            else:
                in_specs.append(pl.BlockSpec(
                    (1, *yz),
                    lambda i, rr=r, ss=sx: ((i * bx + rr) % ss, 0, 0)))
    # x planes: first of all four fields, last of P/Vy/Vz (Vx's handled
    # after the kernel).
    x_planes = [rq[0][0][0], rq[1][0][0], rq[2][0][0], rq[3][0][0],
                rq[0][0][1], rq[2][0][1], rq[3][0][1]]
    for pln in x_planes:
        operands.append(pln)
        in_specs.append(pl.BlockSpec(pln.shape, lambda i: (0, 0)))
    if not wy:
        for k in range(4):
            for side in (0, 1):
                pln = rq[k][1][side]        # squeezed (sx, S2)
                operands.append(pln)
                in_specs.append(pl.BlockSpec((bx, pln.shape[1]),
                                             lambda i: (i, 0)))
    if not wz:
        for k in range(4):
            for side in (0, 1):
                pln = rq[k][2][side]        # squeezed (sx, sy)
                operands.append(pln)
                in_specs.append(pl.BlockSpec((bx, pln.shape[1]),
                                             lambda i: (i, 0)))

    vmas = [getattr(getattr(x, "aval", None), "vma", None) for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(dims, dt):
        return (jax.ShapeDtypeStruct(dims, dt, vma=vma) if vma
                else jax.ShapeDtypeStruct(dims, dt))

    # Vx's out_shape is its full (S0+1) extent; the block grid covers rows
    # [0, S0) and the caller writes row S0 below.
    out_shape = [shp(F.shape, F.dtype) for F in (P, Vx, Vy, Vz)]
    out_specs = [pl.BlockSpec((bx, *s[1:]), lambda i: (i, 0, 0))
                 for s in (P.shape, Vx.shape, Vy.shape, Vz.shape)]

    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=_vmem_limit(bx, S1, S2),
            dimension_semantics=("parallel",))

    Pn, Vxn, Vyn, Vzn = pl.pallas_call(
        partial(_kernel, bx=bx, nb=nb, shapes=shapes[:4], scal=scal,
                wrap_y=wy, wrap_z=wz),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(*operands)

    # Vx's outside halo row (global S0): the x-side `s-1` halo row, from the
    # received x plane, with the later dimensions' updates applied on top —
    # the sequential-dimension semantics for a row outside the block grid.
    vx_out = rq[1][0][1]                   # (S1, S2)
    if wy:
        vx_out = _wrap_row(vx_out, 0, S1, 3)
    else:
        vx_out = vx_out.at[0, :].set(rq[1][1][0][S0, :])
        vx_out = vx_out.at[S1 - 1, :].set(rq[1][1][1][S0, :])
    if wz:
        vx_out = _wrap_row(vx_out, 1, S2, 3)
    else:
        vx_out = vx_out.at[:, 0].set(rq[1][2][0][S0, :])
        vx_out = vx_out.at[:, S2 - 1].set(rq[1][2][1][S0, :])
    from jax import lax

    Vxn = lax.dynamic_update_slice_in_dim(Vxn, vx_out[None], S0, axis=0)
    return Pn, Vxn, Vyn, Vzn


def _wrap_row(v, axis, size, ol):
    """Periodic self-wrap of the outermost rows of a plane along `axis`."""
    import jax.numpy as jnp
    from jax import lax

    idx = lax.broadcasted_iota(jnp.int32, v.shape, axis)
    v = jnp.where(idx == 0, lax.slice_in_dim(v, size - ol, size - ol + 1,
                                             axis=axis), v)
    return jnp.where(idx == size - 1,
                     lax.slice_in_dim(v, ol - 1, ol, axis=axis), v)
