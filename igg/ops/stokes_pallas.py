"""Fused Pallas Stokes iteration (self-wrap single-device grids).

One `pallas_call` performs a full pseudo-transient Stokes iteration —
pressure update, six stresses, three momentum residuals, velocity updates,
AND the grouped halo update of P/Vx/Vy/Vz — reading each field once and
writing each updated field once.  The XLA composition
(`stokes3d.local_iteration`: compute + `update_halo_local(P,Vx,Vy,Vz)`)
costs ~0.27 ms/iter at 128^3 on v5e, of which the 4-field halo phase alone
is ~0.26 ms measured in isolation (each field pays its own read+write
assembly pass, plus XLA's multi-field layout copies); the fused kernel's
traffic is the ideal 5 reads + 4 writes.

This is the TPU re-expression of the reference's native-kernel performance
tier (">10x faster" than the array-broadcast form,
`/root/reference/README.md:161`) for BASELINE config 5's Stokes solver.

Measured on v5e at 128^3 f32 (median-of-3, 100-iteration dispatches):
**0.136 ms/iter** vs 0.269 for the XLA composition with the round-3 halo
engine (2.0x) and 0.303 for round 2's (2.2x); matches the XLA path
BITWISE on the chip (identical `iteration_core` arithmetic).  The DMA
floor of this structure measured with a no-op core is 0.108 ms (~790 GB/s
on ~85 MB/iter of traffic, including the 2x lane padding of Vz's
(S,S,S+1) shape), so the remaining gap to ideal is non-overlapped VPU
time.

Structure (mirrors `diffusion_pallas`, radius-2 Gauss-Seidel variant):
  - grid over x-slabs of `bx` rows; each program reads its slab plus 2 (3
    for the x-staggered Vx) margin rows per side as single-row block refs
    with modular index maps — edge programs read wrapped rows whose results
    land only in halo rows that the halo phase overwrites;
  - the slab arithmetic is LITERALLY `stokes3d.iteration_core` — one source
    of truth with the XLA path, so the two agree to Mosaic-vs-XLA rounding;
  - x halo planes cross program boundaries, so they are precomputed in XLA
    from the two 5-row x-end windows (same `iteration_core`; contiguous
    dim-0 slices, ~2 MB of reads) and written by the edge programs; y/z
    halos are in-VMEM self-wrap aliases (each field's own staggered
    overlap `ol`, reference `/root/reference/src/shared.jl:81`);
  - Vx's extra global row `S0` lies outside the block grid; it is a halo
    row (`Vx[S0] = Vx[ol-1]`) written by one cheap dim-0 DUS after the
    kernel.

Requirements: single device, all dimensions periodic (the reference's
single-process fully-periodic configuration,
`/root/reference/src/update_halo.jl:516-532`), overlap 3 everywhere (the
radius-2 chain), float inputs of equal dtype.  Other configurations fall
back to the XLA path.
"""

from __future__ import annotations

from functools import partial

# Deliberately TIGHT: the scoped-vmem budget steers Mosaic's scheduling, and
# a small budget produces far better DMA/compute interleaving for this
# kernel.  Swept on v5e at 128^3 (median-of-3, ms/iter): 20MB 0.138,
# 26MB 0.137, 32MB 0.136, 44MB 0.139, 56MB 0.157, 64MB 0.175, 100MB 0.224,
# 128MB 0.40.  The kernel's own working set fits comfortably below 20MB.
_VMEM_LIMIT = 32 * 1024 * 1024


def stokes_pallas_supported(grid, P) -> bool:
    """Whether the fused iteration applies: self-wrap fully-periodic
    single-device grid with overlap 3, unstaggered-pressure local block
    large enough to slab."""
    if tuple(grid.dims) != (1, 1, 1) or not all(bool(p) for p in grid.periods):
        return False
    if grid.overlaps != (3, 3, 3) or P.ndim != 3:
        return False
    s = tuple(grid.local_shape_any(P))
    if s != tuple(grid.nxyz):
        return False
    return s[0] % 8 == 0 and s[0] >= 16 and s[1] >= 8 and s[2] >= 8


def _windows(P, Vx, Vy, Vz, Rho, scal):
    """The seven x-halo planes (and Vx's outside row) from the two 5-row
    x-end windows, via `compute_iteration` on contiguous dim-0 slices."""
    from jax import lax

    from ..models.stokes3d import compute_iteration

    S0 = P.shape[0]

    def win(lo, hi):
        cut = lambda A: lax.slice_in_dim(A, lo, hi, axis=0)
        cutx = lambda A: lax.slice_in_dim(A, lo, hi + 1, axis=0)
        return compute_iteration(cut(P), cutx(Vx), cut(Vy), cut(Vz),
                                 cut(Rho), **scal)

    Pw, Vxw, Vyw, Vzw = win(S0 - 5, S0)       # rows S0-5 .. S0-1 (cells)
    first = (Pw[2], Vxw[2], Vyw[2], Vzw[2])   # global row S0-3 = s-ol
    Pw, Vxw, Vyw, Vzw = win(0, 5)             # rows 0..4
    last = (Pw[2], Vyw[2], Vzw[2])            # global row ol-1 = 2
    vx_outside = Vxw[3]                       # Vx[S0] = Vx[ol_x-1] = Vx[3]
    return first, last, vx_outside


def _kernel(*refs, bx, nb, shapes, scal):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from ..models.stokes3d import iteration_core

    it = iter(refs)
    # Extended slabs: rows [a-1, a+bx+1) of each field (the x-staggered Vx
    # one row more).  Minimal margins — out rows that would read beyond them
    # are halo rows overwritten below.  Rho is read row-locally, so its
    # margin rows are dummies taken from the center block (values unused).
    m1, cP, p1 = next(it), next(it), next(it)
    eP = jnp.concatenate([m1[:], cP[:], p1[:]], axis=0)
    m1, cVx, p1, p2 = next(it), next(it), next(it), next(it)
    eVx = jnp.concatenate([m1[:], cVx[:], p1[:], p2[:]], axis=0)
    m1, cVy, p1 = next(it), next(it), next(it)
    eVy = jnp.concatenate([m1[:], cVy[:], p1[:]], axis=0)
    m1, cVz, p1 = next(it), next(it), next(it)
    eVz = jnp.concatenate([m1[:], cVz[:], p1[:]], axis=0)
    cRho = next(it)
    r = cRho[:]
    eRho = jnp.concatenate([r[0:1], r, r[0:1]], axis=0)
    pf, vxf, vyf, vzf = (next(it) for _ in range(4))   # first planes
    pl_, vyl, vzl = (next(it) for _ in range(3))       # last planes
    oP, oVx, oVy, oVz = (next(it) for _ in range(4))

    Pn, dVx, dVy, dVz = iteration_core(eP, eVx, eVy, eVz, eRho, **scal)

    # Output rows j ↔ ext rows j+1; increments are on the ext interior
    # (offset 1), so out row j ↔ increment row j.
    oP[:] = Pn[1:1 + bx]
    for o_ref, ext, dV in ((oVx, eVx, dVx), (oVy, eVy, dVy), (oVz, eVz, dVz)):
        o_ref[:] = ext[1:1 + bx]
        o_ref[:, 1:-1, 1:-1] = (ext[1:1 + bx, 1:-1, 1:-1]
                                + dV[0:bx])

    i = pl.program_id(0)

    # x halo planes (dimension-sequential: x first, y/z own shared cells).
    @pl.when(i == 0)
    def _():
        oP[0:1] = pf[:][None]
        oVx[0:1] = vxf[:][None]
        oVy[0:1] = vyf[:][None]
        oVz[0:1] = vzf[:][None]

    @pl.when(i == nb - 1)
    def _():
        oP[bx - 1:bx] = pl_[:][None]
        oVy[bx - 1:bx] = vyl[:][None]
        oVz[bx - 1:bx] = vzl[:][None]
        # Vx's last halo row is global row S0, outside the block grid —
        # written by the caller after the kernel.

    # y then z self-wrap (per-field staggered ol: 4 on the staggered axis).
    for o_ref, (_, sy, sz), oly, olz in (
            (oP, shapes[0], 3, 3), (oVx, shapes[1], 3, 3),
            (oVy, shapes[2], 4, 3), (oVz, shapes[3], 3, 4)):
        o_ref[:, 0:1, :] = o_ref[:, sy - oly:sy - oly + 1, :]
        o_ref[:, sy - 1:sy, :] = o_ref[:, oly - 1:oly, :]
        o_ref[:, :, 0:1] = o_ref[:, :, sz - olz:sz - olz + 1]
        o_ref[:, :, sz - 1:sz] = o_ref[:, :, olz - 1:olz]


def fused_stokes_iteration(P, Vx, Vy, Vz, Rho, *, dx, dy, dz, mu, dtP, dtV,
                           bx: int = 8, interpret: bool = False):
    """One fused Stokes pseudo-transient iteration
    `(P, Vx, Vy, Vz, Rho) -> (P', Vx', Vy', Vz')` with halo maintenance
    included, on a self-wrap grid (see module docstring).  Matches
    `stokes3d.local_iteration(..., overlap=False)` to Mosaic-vs-XLA
    rounding."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    S0, S1, S2 = P.shape
    while S0 % bx != 0:
        bx //= 2
    if bx < 4:
        raise ValueError(f"x size {S0} not divisible into slabs of >= 4 rows")
    nb = S0 // bx
    scal = dict(dx=dx, dy=dy, dz=dz, mu=mu, dtP=dtP, dtV=dtV)
    shapes = [P.shape, Vx.shape, Vy.shape, Vz.shape, Rho.shape]

    first, last, vx_outside = _windows(P, Vx, Vy, Vz, Rho, scal)

    operands, in_specs = [], []
    for F in (P, Vx, Vy, Vz, Rho):
        sx = F.shape[0]
        yz = F.shape[1:]
        if F is Rho:
            rows = ["c"]                    # row-local reads only
        elif F is Vx:
            rows = [-1, "c", bx, bx + 1]    # staggered: one extra top row
        else:
            rows = [-1, "c", bx]
        for r in rows:
            operands.append(F)
            if r == "c":
                in_specs.append(pl.BlockSpec((bx, *yz),
                                             lambda i: (i, 0, 0)))
            else:
                in_specs.append(pl.BlockSpec(
                    (1, *yz),
                    lambda i, rr=r, ss=sx: ((i * bx + rr) % ss, 0, 0)))
    for pln in (*first, *last):
        operands.append(pln)
        in_specs.append(pl.BlockSpec(pln.shape, lambda i: (0, 0)))

    vmas = [getattr(getattr(x, "aval", None), "vma", None) for x in operands]
    vma = frozenset().union(*[v for v in vmas if v])

    def shp(dims, dt):
        return (jax.ShapeDtypeStruct(dims, dt, vma=vma) if vma
                else jax.ShapeDtypeStruct(dims, dt))

    # Vx's out_shape is its full (S0+1) extent; the block grid covers rows
    # [0, S0) and the caller writes row S0 below.
    out_shape = [shp(F.shape, F.dtype) for F in (P, Vx, Vy, Vz)]
    out_specs = [pl.BlockSpec((bx, *s[1:]), lambda i: (i, 0, 0))
                 for s in (P.shape, Vx.shape, Vy.shape, Vz.shape)]

    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT,
            dimension_semantics=("parallel",))

    Pn, Vxn, Vyn, Vzn = pl.pallas_call(
        partial(_kernel, bx=bx, nb=nb, shapes=shapes[:4], scal=scal),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(*operands)

    # Vx's outside halo row (global S0): the sequential-dimension semantics
    # give it the updated row `ol-1` with the y/z self-wraps applied on top
    # (the later exchanges span the full x extent including this row).
    def wrap_row(v, axis, size, ol):
        idx = lax.broadcasted_iota(jnp.int32, v.shape, axis)
        v = jnp.where(idx == 0, lax.slice_in_dim(v, size - ol, size - ol + 1,
                                                 axis=axis), v)
        return jnp.where(idx == size - 1,
                         lax.slice_in_dim(v, ol - 1, ol, axis=axis), v)

    vx_outside = wrap_row(vx_outside, 0, S1, 3)   # y
    vx_outside = wrap_row(vx_outside, 1, S2, 3)   # z
    Vxn = lax.dynamic_update_slice_in_dim(Vxn, vx_outside[None], S0, axis=0)
    return Pn, Vxn, Vyn, Vzn
