"""K-iteration Stokes trapezoid chunk tier: the exchange/window machinery
on real multi-device CPU meshes (staggered shapes, periodic/open/mixed
dims), plus a pure-value simulation of the Mosaic kernel's banded
in-place scheme.

The chunk KERNEL is manual-DMA (TPU-only; equivalence pinned on hardware
by tests/test_mega_tpu.py::test_stokes_trapezoid_matches_per_iteration).
What runs here is everything around it — the grouped 2K-deep slab
ppermutes, the exchange-fresh window construction, the shrinking-validity
argument, and the velocity-freeze open-boundary semantics — realized in
pure XLA (`_window_iters_xla`) on 8-device CPU meshes and compared
against K applications of `stokes3d.local_iteration`; plus the banded
in-place + lag-row realization the kernel executes, simulated with the
kernel's own shared `_band_update`/`_band_halo` helpers and pinned
against the window realization.
"""

import numpy as np
import pytest

import igg
from igg.models import stokes3d


def _init(mesh, periods, local=(16, 16, 128)):
    igg.init_global_grid(local[0], local[1], local[2],
                         dimx=mesh[0], dimy=mesh[1], dimz=mesh[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2],
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    return igg.get_global_grid()


def _fresh_fields(params=None):
    """Nontrivial overlap-CONSISTENT fields with exchange-fresh halos —
    the chunk tier's entry contract (`fused_stokes_trapezoid_iters`
    docstring): the buoyancy init evolved by a few reference iterations,
    so the duplicated overlap-region rows are globally equal (per-index
    random fields are NOT — an overlap-3 grid exchanges one plane per
    side, so `update_halo` alone cannot synchronize the interior
    duplicates)."""
    params = params or stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
    P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
    it = stokes3d.make_iteration(params, donate=False, use_pallas=False,
                                 n_inner=3)
    P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
    return P, Vx, Vy, Vz, Rho


def _chunk_vs_per_iteration(mesh, periods, K=4, n_chunks=1, tol=2e-5):
    """One-or-more K-chunks of the window realization vs K*n_chunks
    applications of the plain sequential composition, from an
    exchange-fresh state."""
    from jax import lax

    from igg.ops.stokes_trapezoid import (_dim_modes,
                                          fused_stokes_trapezoid_iters,
                                          stokes_trapezoid_supported)

    grid = _init(mesh, periods)
    kw = stokes3d._pseudo_steps(stokes3d.Params(lx=4.0, ly=4.0, lz=4.0))
    n = K * n_chunks
    assert stokes_trapezoid_supported(grid, (16, 16, 128), K, n,
                                      np.float32, interpret=True)
    fields = _fresh_fields()
    Rho = fields[4]

    @igg.sharded
    def chunk(P, Vx, Vy, Vz, Rho):
        out = fused_stokes_trapezoid_iters(P, Vx, Vy, Vz, Rho, n_inner=n,
                                           K=K, **kw, interpret=True)
        return out[:4]

    @igg.sharded
    def per_it(P, Vx, Vy, Vz, Rho):
        return lax.fori_loop(
            0, n, lambda _, S: stokes3d.local_iteration(*S, Rho, **kw),
            (P, Vx, Vy, Vz))

    out = chunk(*fields)
    ref = per_it(*fields)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), ref, out):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < tol, (name, rel, mesh, periods)
    modes = _dim_modes(grid)
    igg.finalize_global_grid()
    return modes


def test_ring_periodic():
    """(8,1,1) fully periodic: x extended by self/neighbor slabs, y/z
    in-window self-wrap with per-field staggered ol."""
    assert _chunk_vs_per_iteration((8, 1, 1), (1, 1, 1)) == (
        "ext", "wrap", "wrap")


def test_ring_open():
    """(8,1,1) all open — the reference-default boundary condition:
    'oext' x (non-wrapping slab permutes + edge-device velocity freeze),
    frozen y/z."""
    assert _chunk_vs_per_iteration((8, 1, 1), (0, 0, 0)) == (
        "oext", "frozen", "frozen")


def test_torus_222_periodic():
    """(2,2,2) fully periodic 3-D torus: x/y/z all extended, corners via
    the later neighbors' earlier-dim extensions, staggered z slabs
    transpose-carried."""
    assert _chunk_vs_per_iteration((2, 2, 2), (1, 1, 1)) == (
        "ext", "ext", "ext")


def test_torus_222_open():
    """(2,2,2) all open: 'oext' on every dim — velocity shoulder freezing
    layered under later-dim extensions."""
    assert _chunk_vs_per_iteration((2, 2, 2), (0, 0, 0)) == (
        "oext", "oext", "oext")


def test_mixed_open_x_z():
    """Mixed (2,2,2): open x and z around a periodic extended y."""
    assert _chunk_vs_per_iteration((2, 2, 2), (0, 1, 0)) == (
        "oext", "ext", "oext")


def test_mesh_421_mixed_wrap():
    """(4,2,1): z wrapped in-window, x/y extended, open y."""
    assert _chunk_vs_per_iteration((4, 2, 1), (1, 0, 1)) == (
        "ext", "oext", "wrap")


def test_single_device_selfwrap_two_chunks():
    """(1,1,1) fully periodic (the benchmark's self-wrap grid): x rides
    self-neighbor slabs, y/z wrap; two chained chunks exercise
    chunk-exit halo invariants feeding the next chunk's extension."""
    assert _chunk_vs_per_iteration((1, 1, 1), (1, 1, 1),
                                   n_chunks=2) == ("ext", "wrap", "wrap")


def test_single_device_frozen():
    """(1,1,1) all open: every dim 'frozen' — no extension at all, the
    velocity boundary planes re-frozen every iteration."""
    assert _chunk_vs_per_iteration((1, 1, 1), (0, 0, 0)) == (
        "frozen", "frozen", "frozen")


# ---------------------------------------------------------------------------
# Model-path dispatch (make_iteration admission)
# ---------------------------------------------------------------------------

def _model_compare(grid_kw, n_inner, tol=2e-4, **mk_kw):
    """Chunk tier vs the per-iteration KERNEL path (the tight check —
    isolates exactly what the chunk tier adds), plus a coarse check
    against the XLA composition (the per-iteration kernel itself sits at
    ~1e-4 relative on the near-rest velocities of this state, so the
    XLA bound is loose by design — its tight bound is
    tests/test_stokes_pallas.py)."""
    fields = _fresh_fields()
    params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
    xla = stokes3d.make_iteration(params, donate=False, use_pallas=False,
                                  n_inner=n_inner)
    ref = stokes3d.make_iteration(params, donate=False, use_pallas=True,
                                  pallas_interpret=True, n_inner=n_inner,
                                  trapezoid=False)
    pal = stokes3d.make_iteration(params, donate=False, use_pallas=True,
                                  pallas_interpret=True, n_inner=n_inner,
                                  **mk_kw)
    x = xla(*fields)
    r = ref(*fields)
    o = pal(*fields)
    for name, a, b, c in zip(("P", "Vx", "Vy", "Vz"), r, o, x):
        a, b, c = (np.asarray(v, np.float64) for v in (a, b, c))
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < tol, (name, rel, grid_kw)
        rel_x = np.max(np.abs(c - b)) / (np.max(np.abs(c)) + 1e-30)
        assert rel_x < 1e-3, (name, rel_x, grid_kw)


def test_model_path_chunks_engage_ring():
    """make_iteration routes the (8,1,1) periodic mesh through the chunk
    tier (warm-up + one K=4 chunk) and must match the XLA composition."""
    from igg.ops.stokes_trapezoid import fit_stokes_K

    grid = _init((8, 1, 1), (1, 1, 1))
    assert fit_stokes_K(grid, (16, 16, 128), 4, np.float32,
                        interpret=True) == 4
    _model_compare({}, n_inner=5, trapezoid=True)
    igg.finalize_global_grid()


def test_model_path_chunks_with_remainder_open():
    """Open (8,1,1) mesh, n_inner=7 = warm-up + one K=4 chunk + 2
    remainder per-iteration kernels."""
    _init((8, 1, 1), (0, 0, 0))
    _model_compare({}, n_inner=7, trapezoid=True, K=4)
    igg.finalize_global_grid()


def test_model_auto_falls_back_when_unsupported():
    """trapezoid='auto' with too few iterations silently runs the
    per-iteration kernel (n_inner=2 < K+1 for every K)."""
    _init((8, 1, 1), (1, 1, 1))
    _model_compare({}, n_inner=2)
    igg.finalize_global_grid()


def test_model_trapezoid_true_raises_when_unsupported():
    """trapezoid=True is a real contract: requirement-string GridError
    when no K is admissible (here: n_inner too small for any chunk)."""
    _init((8, 1, 1), (1, 1, 1))
    params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
    fields = _fresh_fields()
    it = stokes3d.make_iteration(params, donate=False, use_pallas=True,
                                 pallas_interpret=True, n_inner=2,
                                 trapezoid=True)
    with pytest.raises(igg.GridError, match="chunk tier"):
        it(*fields)
    igg.finalize_global_grid()


def test_model_trapezoid_true_with_xla_path_raises():
    _init((8, 1, 1), (1, 1, 1))
    params = stokes3d.Params()
    with pytest.raises(igg.GridError, match="chunk tier"):
        stokes3d.make_iteration(params, use_pallas=False, trapezoid=True)
    igg.finalize_global_grid()


def test_gate_rejects():
    """Admission matrix of stokes_trapezoid_supported."""
    from igg.ops.stokes_trapezoid import stokes_trapezoid_supported

    grid = _init((8, 1, 1), (1, 1, 1))
    s = (16, 16, 128)
    ok = stokes_trapezoid_supported
    assert ok(grid, s, 4, 4, np.float32)
    assert not ok(grid, s, 4, 3, np.float32)      # no full chunk
    assert not ok(grid, s, 1, 8, np.float32)      # K < 2
    assert not ok(grid, s, 8, 8, np.float32)      # 2K send slabs too deep
    assert not ok(grid, s, 4, 4, np.float64)      # f32 only
    igg.finalize_global_grid()
    grid = igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                                periodx=1, periody=1, periodz=1,
                                quiet=True)  # overlap 2
    grid = igg.get_global_grid()
    assert not ok(grid, s, 4, 4, np.float32)
    igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# Banded in-place simulation of the Mosaic kernel's scheme
# ---------------------------------------------------------------------------

def _banded_sim(exts, Rho_ext, K, modes, grid, scal, ols, shapes):
    """Pure-value simulation of `stokes_trapezoid._kernel`'s execution:
    in-place x-row bands with the one-row lag carry and clamped margins,
    calling the kernel's own `_band_update`/`_band_halo` helpers — so the
    band indexing the TPU kernel executes is pinned on CPU against the
    window realization."""
    import jax.numpy as jnp

    from igg.ops.stokes_trapezoid import _BX, _band_halo, _band_update

    E = 2 * K
    bx = _BX
    fv = [np.array(x) for x in exts] + [np.array(Rho_ext)]
    ext_shapes = tuple(tuple(x.shape) for x in fv)
    cfg = dict(modes=tuple(modes), ols=tuple(ols[:4]),
               ext_shapes=ext_shapes, E=E, shapes=tuple(shapes[:4]))
    # Single-device simulation: frozen dims statically flag both sides.
    flags = [1 if modes[d] == "frozen" else 0 for d in range(3)
             for _ in (0, 1)]
    frx, fr_yz = {}, {}
    for d in range(3):
        if modes[d] not in ("oext", "frozen"):
            continue
        lo = E if modes[d] == "oext" else 0
        for f in (1, 2, 3):
            hi = lo + shapes[f][d] - 1
            for side, idx in ((0, lo), (1, hi)):
                plane = np.take(fv[f], idx, axis=d).copy()
                if d == 0:
                    frx[(f, side)] = jnp.asarray(plane)
                else:
                    fr_yz[(f, d, side)] = plane
    S0e = ext_shapes[0][0]
    nb = S0e // bx
    lag = [np.zeros((2,) + ext_shapes[f][1:], fv[f].dtype)
           for f in range(4)]
    for k in range(K):
        for i in range(nb):
            a = i * bx
            sl = i % 2
            for f in range(4):
                lag[f][sl] = fv[f][a + bx - 1]

            def window(f, extra):
                if f == 4:   # Rho: never overwritten, clamped direct read
                    m1 = fv[f][max(a - 1, 0)][None]
                else:
                    m1 = (fv[f][0:1] if i == 0
                          else lag[f][1 - sl][None])
                parts = [m1, fv[f][a:a + bx]]
                top = ext_shapes[f][0] - 1
                for e in range(1, extra + 1):
                    parts.append(fv[f][min(a + bx + e - 1, top)][None])
                return jnp.asarray(np.concatenate(parts, axis=0))

            news = _band_update(window(0, 1), window(1, 2), window(2, 1),
                                window(3, 1), window(4, 1), bx=bx,
                                scal=scal)
            fryz = {key: jnp.asarray(p[a:a + bx])
                    for key, p in fr_yz.items()}
            news = _band_halo(news, a, bx, flags, frx, fryz, cfg)
            for f in range(4):
                fv[f][a:a + bx] = np.asarray(news[f])
    out = []
    for f in range(4):
        F = fv[f]
        for d in range(3):
            if modes[d] in ("ext", "oext"):
                F = np.take(F, range(E, E + shapes[f][d]), axis=d)
        out.append(F)
    return out


@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0)],
                         ids=["selfwrap_ext_x", "frozen"])
def test_banded_scheme_matches_window(periods):
    """The kernel's banded in-place + lag-row scheme (shared
    `_band_update`/`_band_halo` helpers) must reproduce the window
    realization's central blocks on a 1-device grid — periodic
    (x self-extended, y/z wrap) and all-frozen."""
    from igg.ops.stokes_trapezoid import (_dim_modes, _extend_fields,
                                          _field_shapes, _ols,
                                          _window_iters_xla,
                                          stokes_trapezoid_supported)

    grid = _init((1, 1, 1), periods)
    K = 4
    E = 2 * K
    modes = _dim_modes(grid)
    kw = stokes3d._pseudo_steps(stokes3d.Params(lx=4.0, ly=4.0, lz=4.0))
    assert stokes_trapezoid_supported(grid, (16, 16, 128), K, K,
                                      np.float32)
    P, Vx, Vy, Vz, Rho = _fresh_fields()
    shapes = _field_shapes((16, 16, 128))
    ols = _ols(grid, shapes)
    exts = _extend_fields([P, Vx, Vy, Vz], ols[:4], E, grid, modes)
    Rho_ext = _extend_fields([Rho], [ols[4]], E, grid, modes)[0]

    win = _window_iters_xla(*exts, Rho_ext, K=K, E=E, modes=modes,
                            grid=grid, scal=kw, ols=ols, shapes=shapes)
    win_central = []
    for f, F in enumerate(win):
        F = np.asarray(F)
        for d in range(3):
            if modes[d] in ("ext", "oext"):
                F = np.take(F, range(E, E + shapes[f][d]), axis=d)
        win_central.append(F)

    band = _banded_sim(exts, Rho_ext, K, modes, grid, kw, ols, shapes)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), win_central, band):
        # Pure f32 reassociation between band-shaped and full-window
        # fusions; the values are identical expressions per element.
        scale = max(np.abs(a).max(), 1e-30)
        assert np.abs(a - b).max() <= 1e-5 * scale, name
    igg.finalize_global_grid()
