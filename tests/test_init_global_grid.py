"""Lifecycle + topology tests.

Ports the coverage of `/root/reference/test/test_init_global_grid.jl`:
init/finalize lifecycle, return values, full grid-state check, periodic
`nxyz_g` arithmetic, non-default overlaps, and the argument error cases.
"""

import numpy as np
import pytest

import igg
from igg.topology import dims_create


def test_initialization_and_return_values():
    me, dims, nprocs, coords, mesh = igg.init_global_grid(4, 4, 4, quiet=True)
    assert igg.grid_is_initialized()
    assert me == 0
    assert nprocs == 8
    assert tuple(sorted(dims, reverse=True)) == dims  # balanced, non-increasing
    assert int(np.prod(dims)) == 8
    assert mesh is igg.get_global_grid().mesh
    assert mesh.axis_names == igg.AXIS_NAMES
    assert tuple(mesh.devices.shape) == dims
    igg.finalize_global_grid()
    assert not igg.grid_is_initialized()


def test_grid_state_fields():
    igg.init_global_grid(5, 6, 7, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    g = igg.get_global_grid()
    assert g.nxyz == (5, 6, 7)
    assert g.dims == (2, 2, 2)
    assert g.overlaps == (2, 2, 2)
    assert g.periods == (1, 0, 0)
    assert g.nprocs == 8
    assert g.disp == 1 and g.reorder == 1
    # nxyz_g = dims*(nxyz-overlaps) + overlaps*(periods==0)
    # (`/root/reference/src/init_global_grid.jl:82`)
    assert g.nxyz_g == (2 * 3, 2 * 4 + 2, 2 * 5 + 2)
    assert igg.nx_g() == 6 and igg.ny_g() == 10 and igg.nz_g() == 12


def test_nonperiodic_vs_periodic_global_size():
    igg.init_global_grid(8, 8, 8, quiet=True)  # dims (2,2,2), all open
    assert (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (14, 14, 14)
    igg.finalize_global_grid()
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1, quiet=True)
    assert (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (12, 12, 12)


def test_non_default_overlaps():
    igg.init_global_grid(8, 8, 8, overlapx=3, overlapy=4, quiet=True)
    g = igg.get_global_grid()
    assert g.overlaps == (3, 4, 2)
    assert g.nxyz_g == (2 * 5 + 3, 2 * 4 + 4, 2 * 6 + 2)


def test_neighbors_and_ranks():
    igg.init_global_grid(4, 4, 4, periodx=1, quiet=True)  # dims (2,2,2)
    g = igg.get_global_grid()
    # x periodic: both neighbors exist everywhere and wrap.
    assert g.neighbors_of((0, 0, 0), 0) == (g.cart_rank((1, 0, 0)),
                                            g.cart_rank((1, 0, 0)))
    # y open: left edge has no left neighbor.
    assert g.neighbors_of((0, 0, 0), 1)[0] == igg.PROC_NULL
    assert g.neighbors_of((0, 1, 0), 1)[1] == igg.PROC_NULL
    # rank <-> coords round trip
    for r in range(g.nprocs):
        assert g.cart_rank(g.cart_coords(r)) == r


def test_dims_create():
    assert dims_create(8, (0, 0, 0)) == (2, 2, 2)
    assert dims_create(12, (0, 0, 0)) == (3, 2, 2)
    assert dims_create(16, (0, 0, 0)) == (4, 2, 2)
    assert dims_create(6, (0, 0, 1)) == (3, 2, 1)
    assert dims_create(8, (2, 0, 0)) == (2, 2, 2)
    assert dims_create(8, (8, 1, 1)) == (8, 1, 1)
    assert dims_create(7, (0, 1, 1)) == (7, 1, 1)
    with pytest.raises(igg.GridError):
        dims_create(8, (3, 0, 0))  # 3 does not divide 8


def test_error_cases():
    # (`/root/reference/src/init_global_grid.jl:43,62-66` /
    #  `/root/reference/test/test_init_global_grid.jl`)
    with pytest.raises(igg.GridError, match="nx can never be 1"):
        igg.init_global_grid(1, 4, 4, quiet=True)
    with pytest.raises(igg.GridError, match="ny cannot be 1"):
        igg.init_global_grid(4, 1, 4, quiet=True)
    with pytest.raises(igg.GridError, match="Incoherent arguments"):
        igg.init_global_grid(4, 4, 1, dimz=2, quiet=True)
    with pytest.raises(igg.GridError, match="Incoherent arguments"):
        igg.init_global_grid(4, 4, 2, periodz=1, quiet=True)  # nz < 2*ol-1
    igg.init_global_grid(4, 4, 4, quiet=True)
    with pytest.raises(igg.GridError, match="already been initialized"):
        igg.init_global_grid(4, 4, 4, quiet=True)


def test_nz1_forces_dimz_1():
    me, dims, nprocs, *_ = igg.init_global_grid(8, 8, 1, quiet=True)
    assert dims[2] == 1
    assert nprocs == 8


def test_check_initialized_guard():
    with pytest.raises(igg.GridError, match="init_global_grid"):
        igg.nx_g()
    with pytest.raises(igg.GridError, match="init_global_grid"):
        igg.tic()
