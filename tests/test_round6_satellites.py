"""Round-6 satellite coverage: the sequential-exchange pack fast path
(ADVICE r5 item 1), the shared lane-dispatch helper behind
`ext_planes_supported` (item 2), and the `igg.sharded` identity-keyed
cache-miss log (VERDICT r5 weak #5)."""

import logging

import numpy as np
import pytest

import igg


def _spy_pack(calls):
    """A `pack_planes` stand-in recording requests and returning the exact
    squeezed planes XLA slicing would produce, so the patched program's
    values match the unpatched oracle."""
    import jax.numpy as jnp
    from jax import lax

    def pack(A, reqs):
        calls.append(tuple(reqs))
        return [jnp.squeeze(lax.slice_in_dim(A, p, p + 1, axis=d), d)
                for d, p in reqs]

    return pack


def _seq_update(T, grid):
    from igg import halo

    da = halo.active_dims(T.shape, grid)
    dims = halo.moving_dims(da, grid)
    return halo.exchange_assemble_sequential(
        [T], [dims], grid, ["select"])[0]


def test_sequential_exchange_uses_pack_fast_path(monkeypatch):
    """On (virtually) TPU meshes, `exchange_assemble_sequential` must route
    eligible 32-bit minor-dim sends — including the open-boundary stale
    planes, which materialize for the wire's masked select — through the
    `pack_planes` one-pass extractor, and keep major-dim (x) planes lazy."""
    import jax.numpy as jnp

    from igg import halo
    from igg.ops import pack

    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=0, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    rng = np.random.default_rng(3)
    T0 = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls).astype(np.float32),
        (16, 16, 16))

    ref = np.asarray(igg.sharded(lambda T: _seq_update(T, grid))(T0))

    calls = []
    monkeypatch.setattr(halo, "_is_tpu", lambda g: True)
    monkeypatch.setattr(pack, "pack_planes", _spy_pack(calls))

    out = np.asarray(
        igg.sharded(lambda T: _seq_update(T, grid) + 0)(T0))

    # d=1 is open: 2 sends + 2 stales in one pass; d=2 periodic: 2 sends.
    # d=0 (major) never packs — its planes are free lazy slices.  Local
    # blocks are 16^3 (init sizes are per-device).
    assert tuple((1, p) for p in (1, 14, 0, 15)) in calls
    assert tuple((2, p) for p in (1, 14)) in calls
    assert not any(d == 0 for req in calls for d, _ in req)
    # The spy returns the genuine planes, so values must match the oracle.
    np.testing.assert_array_equal(out, ref)


def test_sequential_exchange_keeps_lazy_slices_for_pair_dtypes(monkeypatch):
    """Pair-emulated dtypes (f64 — the homogeneous-graph rule's domain)
    must NOT take the pack path (ADVICE r5: keep the sequential form where
    it was measured to win; pack is 32-bit-only in Mosaic)."""
    from igg import halo
    from igg.ops import pack

    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=0, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    T0 = igg.from_local_blocks(
        lambda coords, ls: np.full(ls, coords[1], np.float64), (16, 16, 16),
        dtype=np.float64)

    calls = []
    monkeypatch.setattr(halo, "_is_tpu", lambda g: True)
    monkeypatch.setattr(pack, "pack_planes", _spy_pack(calls))

    igg.sharded(lambda T: _seq_update(T, grid))(T0)
    assert calls == []


def test_ext_planes_gate_matches_lane_dispatch():
    """`ext_planes_supported` must price exactly the dispatch decision the
    runtime takes: the col-vs-one-pass verdict and the bx it aligns come
    from the shared `lane_dispatch` helper, across the block-shape matrix
    (ADVICE r5 item 2 — the gate and `write_lane_active` previously
    duplicated these conditions and agreed only by accident)."""
    import jax.numpy as jnp

    from igg.ops.halo_write import (_pick_bx, _sublane_tile,
                                    ext_planes_supported, lane_dispatch,
                                    lane_columns_writable)

    shapes = [(256, 256, 256), (256, 256, 512), (64, 64, 128),
              (65, 64, 128), (64, 64, 384), (32, 8, 128),
              (256, 256, 384), (33, 256, 384), (64, 257, 384),
              (64, 128, 129)]
    dtypes = [np.dtype(np.float32), np.dtype(jnp.bfloat16)]
    dim_sets = [[2], [1, 2], [0, 1, 2], [0, 2]]
    wrap_sets = [frozenset(), frozenset({1})]

    for shape in shapes:
        n0, n1, n2 = shape
        for dtype in dtypes:
            itemsize = dtype.itemsize
            ts = _sublane_tile(itemsize)
            for dims in dim_sets:
                for wraps in wrap_sets:
                    col, bx = lane_dispatch(shape, dtype, dims, wraps)
                    # The helper's verdict IS the runtime's: col comes from
                    # lane_columns_writable, bx from the block the writer
                    # tiles (one 128-lane column on the col path, the full
                    # block on the one-pass path).
                    assert col == lane_columns_writable(shape, dtype, dims,
                                                        wraps)
                    assert bx == _pick_bx(n0, n1, 128 if col else n2,
                                          itemsize)
                    # And the gate's lane-dim branch prices exactly that
                    # bx: recompute its verdict from the helper and compare.
                    ext_dims = [d for d in dims if d != 0
                                and d not in wraps]
                    expect = True
                    if any(d in ext_dims for d in (1, 2)):
                        if 1 in ext_dims:
                            expect = (expect and n2 % 128 == 0
                                      and (_pick_bx(n0, n1, n2, itemsize)
                                           in (n0,)
                                           or _pick_bx(n0, n1, n2,
                                                       itemsize) % ts == 0))
                        if 2 in ext_dims:
                            expect = (expect and n1 % 128 == 0
                                      and (bx == n0 or bx % ts == 0))
                    got = ext_planes_supported(shape, dtype, ext_dims,
                                               dims, wraps)
                    assert got == expect, (shape, dtype, dims, wraps)


def test_sharded_identity_cache_miss_logs(caplog):
    """A closure over unhashable captures is cache-keyed by object identity;
    the first compiled-cache miss must emit the debug-level retrace warning
    (once per function), and hashable-capture closures must stay silent
    (VERDICT r5 weak #5)."""
    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T = igg.zeros((16, 16, 16), dtype=np.float32)

    def make_unhashable_step(c):
        def step(T):
            return T + float(c[0])
        return step

    def make_hashable_step(k):
        def step(T):
            return T + k
        return step

    arr = np.asarray([1.5])  # numpy captures are unhashable
    with caplog.at_level(logging.DEBUG, logger="igg.parallel"):
        igg.sharded(make_unhashable_step(arr))(T)
    assert any("object identity" in r.message for r in caplog.records)

    caplog.clear()
    with caplog.at_level(logging.DEBUG, logger="igg.parallel"):
        igg.sharded(make_hashable_step(1.5))(T)
    assert not any("object identity" in r.message for r in caplog.records)
