"""Multi-controller (multi-host) runs over jax.distributed.

The reference's multi-node model is one MPI process per GPU
(`mpirun -np N`, `/root/reference/src/init_global_grid.jl:67-81`).  The TPU
build's analog is one controller process per host with
``jax.distributed.initialize``; the grid mesh then spans all hosts' devices
and the same shard_map/ppermute programs run over ICI+DCN.  This test spawns
two controller processes (4 virtual CPU devices each → one 8-device global
mesh), runs init → coordinate-filled field → update_halo → gather → barrier
→ finalize on both, and checks the gathered global array on the root process
is identical to a single-controller run of the same global grid.
"""

import os
import socket
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import igg

_WORKER = r"""
import os, sys
pid, nproc, port, outfile = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=nproc, process_id=pid)
import numpy as np, igg
me, dims, nprocs, coords, mesh = igg.init_global_grid(
    6, 6, 6, periodx=1, periodz=1, quiet=True)
assert nprocs == 8, nprocs
assert me == jax.process_index()
# Real node-local device selection (both workers run on this machine, so they
# model two ranks sharing one node: node-local ranks 0 and 1, each bound to
# its own local device).  Collective — both processes call it together.
assert igg.device.node_local_rank() == pid
dev_id = igg.select_device()
assert dev_id == jax.local_devices()[pid % 4].id, (dev_id, pid)
A = igg.zeros((6, 6, 6))
X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
A = A + X * 10000 + Y * 100 + Z
A = igg.update_halo(A)
out = igg.gather(A)
if me == 0:
    assert out is not None
    np.save(outfile, out)
else:
    assert out is None
# Non-default root (reference /root/reference/test/test_gather.jl:127-150):
# the result lands on rank 1, rank 0 gets None.
out1 = igg.gather(A, root=1)
if me == 1:
    assert out1 is not None and out1.shape == (12, 12, 12), out1.shape
else:
    assert out1 is None
# Checkpoint round-trip across controllers (shared filesystem; pins the
# docstring contract of igg/checkpoint.py: process-0 write + barrier +
# every-process read + cross-process device_put).
ck = outfile + ".ckpt.npz"
igg.save_checkpoint(ck, A=A)
B = igg.load_checkpoint(ck)["A"]
import jax.numpy as jnp
assert bool(jnp.all(B == A)), "multihost checkpoint roundtrip mismatch"
igg.tic(); igg.toc()
igg.finalize_global_grid()
"""

# Worker for the O(local) contract (round 9): during a SHARDED save and a
# root-biased gather, a non-root process must never materialize the global
# array — `process_allgather` (the old full-global-on-every-process
# fallback) is sentinel-blocked, and every device→host fetch is bounded by
# one local block (the VERDICT item-4 done-criterion).
_WORKER_OLOCAL = r"""
import os, sys
pid, nproc, port, outfile = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=nproc, process_id=pid)
import numpy as np, igg
import jax.numpy as jnp
me, dims, nprocs, coords, mesh = igg.init_global_grid(
    6, 6, 6, periodx=1, quiet=True)
A = igg.zeros((6, 6, 6))
X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
A = igg.update_halo(A + X * 10000 + Y * 100 + Z)

import jax.experimental.multihost_utils as mhu
def _allgather_sentinel(*a, **k):
    raise AssertionError("process_allgather used on an O(local) path")
real_allgather = mhu.process_allgather
real_get = jax.device_get
fetched = []
def _tracking_get(x):
    out = real_get(x)
    try:
        fetched.append(int(np.asarray(out).nbytes))
    except TypeError:
        pass
    return out
mhu.process_allgather = _allgather_sentinel
jax.device_get = _tracking_get
try:
    ck = outfile + ".sharded"
    igg.save_checkpoint_sharded(ck, A=A)
    B = igg.load_checkpoint(ck)["A"]
    assert bool(jnp.all(B == A)), "sharded multihost roundtrip mismatch"
    out = igg.gather(A)            # root-biased chunked path, no allgather
    if me == 0:
        assert out is not None and out.shape == (12, 12, 12)
        np.save(outfile, out)
    else:
        assert out is None
finally:
    mhu.process_allgather = real_allgather
    jax.device_get = real_get
# Bounded peak staging: no single fetch exceeded one (6,6,6) f64 block.
local_nbytes = 6 * 6 * 6 * 8
assert fetched, "sharded save fetched nothing?"
assert max(fetched) <= local_nbytes, (max(fetched), local_nbytes)
# Distributed verify: each process reads a round-robin shard subset; the
# verdict combine is one SPMD min-reduce over the mesh (no allgather of
# host values).
assert igg.verify_checkpoint_distributed(ck, check_finite=True)
igg.finalize_global_grid()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# The multi-process CPU runtime needs cross-process computation support in
# jaxlib (newer CPU backends ship Gloo collectives; some builds do not).
# When absent, EVERY cross-process program fails with this message — the
# subprocess tests then skip instead of reporting a library bug.
_NO_MULTIPROC = "Multiprocess computations aren't implemented"


def _spawn_workers(tmp_path, worker_src, out, nproc=2):
    """Launch `nproc` controller subprocesses of `worker_src`; returns
    their logs.  Skips (not fails) when the backend cannot run
    cross-process computations at all."""
    port = str(_free_port())
    worker = tmp_path / "worker.py"
    worker.write_text(worker_src)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the TPU plugin out
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(p), str(nproc), port, str(out)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for p in range(nproc)]
    try:
        logs = [p.communicate(timeout=240)[0].decode() for p in procs]
    except subprocess.TimeoutExpired:
        # Don't leave orphans holding the coordinator port; surface whatever
        # output the workers produced before hanging.
        partial = []
        for p in procs:
            p.kill()
            rest, _ = p.communicate()
            partial.append((rest or b"").decode())
        pytest.fail("multihost workers timed out; partial output:\n"
                    + "\n---\n".join(partial))
    if any(_NO_MULTIPROC in log for log in logs):
        pytest.skip("this jaxlib's CPU backend has no cross-process "
                    "computation support; run the multihost subprocess "
                    "tests on a backend with cross-process collectives")
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log}"
    return logs


@pytest.mark.slow
def test_two_controller_processes_match_single_controller(tmp_path):
    out = tmp_path / "gathered.npy"
    _spawn_workers(tmp_path, _WORKER, out)

    # Single-controller oracle on the same 8-device global grid.
    igg.init_global_grid(6, 6, 6, periodx=1, periodz=1, quiet=True)
    A = igg.zeros((6, 6, 6))
    X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
    A = igg.update_halo(A + X * 10000 + Y * 100 + Z)
    want = igg.gather(A)
    igg.finalize_global_grid()

    got = np.load(out)
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_sharded_save_and_gather_keep_nonroot_o_local(tmp_path):
    """Two controller processes: sharded checkpoint save/load/verify and a
    root-biased gather with `process_allgather` sentinel-blocked and every
    device→host fetch bounded by one local block (assertions live in the
    worker) — non-root processes never materialize the global array.  The
    root's gathered array still matches the single-controller oracle."""
    out = tmp_path / "gathered.npy"
    _spawn_workers(tmp_path, _WORKER_OLOCAL, out)

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    A = igg.zeros((6, 6, 6))
    X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
    A = igg.update_halo(A + X * 10000 + Y * 100 + Z)
    want = igg.gather(A)
    igg.finalize_global_grid()

    np.testing.assert_array_equal(np.load(out), want)
