"""Gather tests: ports `/root/reference/test/test_gather.jl` (1D/2D/3D
assembly vs the coordinate oracle, out-array validation, dtype flexibility).
The reference gathers whole local blocks in Cartesian order with overlap*=0
grids (`/root/reference/test/test_gather.jl:38,49,60`)."""

import numpy as np
import pytest

import igg

from helpers import encoded_block, encoded_field


class TestGather:
    def test_3d_assembly_matches_oracle(self):
        igg.init_global_grid(4, 4, 4, overlapx=0, overlapy=0, overlapz=0,
                             quiet=True)
        g = igg.get_global_grid()
        A = encoded_field((4, 4, 4))
        out = igg.gather(A)
        assert out.shape == (8, 8, 8)
        for r in range(g.nprocs):
            c = g.cart_coords(r)
            sl = tuple(slice(c[d] * 4, (c[d] + 1) * 4) for d in range(3))
            np.testing.assert_array_equal(out[sl], encoded_block(c, (4, 4, 4)))

    def test_2d_assembly(self):
        igg.init_global_grid(4, 4, 1, overlapx=0, overlapy=0, quiet=True)
        A = encoded_field((4, 4))
        out = igg.gather(A)
        g = igg.get_global_grid()
        assert out.shape == (4 * g.dims[0], 4 * g.dims[1])

    def test_out_array_form(self):
        igg.init_global_grid(4, 4, 4, overlapx=0, overlapy=0, overlapz=0,
                             quiet=True)
        A = encoded_field((4, 4, 4))
        out = np.zeros((8, 8, 8))
        assert igg.gather(A, out) is None
        np.testing.assert_array_equal(out, igg.gather(A))

    def test_out_array_size_validated(self):
        igg.init_global_grid(4, 4, 4, quiet=True)
        A = igg.zeros((4, 4, 4))
        bad = np.zeros((3, 3, 3))
        with pytest.raises(igg.GridError, match="nprocs"):
            igg.gather(A, bad)

    def test_dtype_flexibility(self):
        igg.init_global_grid(4, 4, 4, quiet=True)
        for dtype in (np.float32, np.float64, np.int16):
            A = igg.zeros((4, 4, 4), dtype=dtype)
            out = igg.gather(A)
            assert out.dtype == dtype

    def test_gather_interior_dedups_overlap(self):
        igg.init_global_grid(6, 6, 6, quiet=True)  # dims (2,2,2), ol 2, open
        T = igg.zeros((6, 6, 6))
        X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, T)
        F = X + 10 * Y + 100 * Z + 0 * T
        out = igg.gather_interior(F)
        assert out.shape == (igg.nx_g(), igg.ny_g(), igg.nz_g())
        # global coordinates are unique -> interior assembly is exactly the
        # coordinate lattice
        exp = (np.arange(10)[:, None, None] + 10 * np.arange(10)[None, :, None]
               + 100 * np.arange(10)[None, None, :]).astype(float)
        np.testing.assert_array_equal(out, exp)

    def test_gather_interior_periodic(self):
        igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                             quiet=True)
        T = igg.zeros((6, 6, 6))
        out = igg.gather_interior(T)
        assert out.shape == (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (8, 8, 8)

    def test_non_default_root_returns_none_off_root(self):
        """`/root/reference/test/test_gather.jl:127-150`: gather to a
        non-zero root returns the result only there; everyone else gets
        None.  This single-controller process is rank 0, so root=1 makes it
        a non-root participant."""
        igg.init_global_grid(4, 4, 4, overlapx=0, overlapy=0, overlapz=0,
                             quiet=True)
        A = encoded_field((4, 4, 4))
        assert igg.gather(A, root=1) is None
        assert igg.gather_interior(A, root=1) is None
        # A_global may not be supplied on a non-root process (reference
        # errors identically, `/root/reference/src/gather.jl:37`).
        with pytest.raises(igg.GridError, match="must be None"):
            igg.gather(A, np.zeros((8, 8, 8)), root=1)

    def test_chunked_fetch_matches_whole_fetch(self):
        """Large-array gathers stream device->host in largest-dim slabs;
        forcing a tiny chunk size must reproduce the one-shot fetch
        bit-for-bit."""
        import importlib

        gather_mod = importlib.import_module("igg.gather")

        igg.init_global_grid(6, 6, 6, overlapx=0, overlapy=0, overlapz=0,
                             quiet=True)
        A = encoded_field((6, 6, 6))
        whole = igg.gather(A)
        np.testing.assert_array_equal(
            gather_mod._fetch_global(A, chunk_bytes=1024).reshape(whole.shape),
            whole.reshape(whole.shape))

    def test_leading_singleton_streams_over_largest_dim(self, monkeypatch):
        """A `(1, ny, nz)`-shaped array above the chunk limit must STILL
        stream in bounded slabs (over its largest dim) instead of silently
        falling back to a whole-array second host buffer — the old
        leading-dim-only streaming skipped any array with `shape[0] <= 1`.
        """
        import importlib

        import jax

        gather_mod = importlib.import_module("igg.gather")

        igg.init_global_grid(4, 4, 4, quiet=True)   # any live grid
        A = jax.numpy.arange(1 * 64 * 32, dtype=jax.numpy.float64).reshape(
            1, 64, 32)                              # 16 KiB
        limit = 2048

        fetched = []
        real_get = jax.device_get

        def tracking_get(x):
            out = real_get(x)
            fetched.append(int(np.asarray(out).nbytes))
            return out

        monkeypatch.setattr(jax, "device_get", tracking_get)
        out = gather_mod._slabbed_get(A, limit)
        monkeypatch.undo()

        np.testing.assert_array_equal(out, np.asarray(A))
        # Streamed: several bounded fetches, never a whole-array one.
        assert len(fetched) > 1
        assert max(fetched) <= limit

    def test_stream_axis_picks_largest_dim(self):
        from igg.gather import _stream_axis

        assert _stream_axis((1, 64, 32)) == 1
        assert _stream_axis((8, 4, 4)) == 0
        assert _stream_axis((4, 4, 16)) == 2
        assert _stream_axis((1, 1, 1)) is None     # nothing to stream over
        assert _stream_axis(()) is None
        assert _stream_axis((5,)) == 0


class TestRank4:
    """Rank-4 component-stacked fields through gather/gather_interior
    (trailing dims unsharded — rank-generic like GGArray{T,N})."""

    def test_gather_and_interior(self):
        igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)   # (2,2,2)
        A = encoded_field((6, 6, 6, 3))
        g = igg.gather(A)
        assert g.shape == (12, 12, 12, 3)
        np.testing.assert_array_equal(g, np.asarray(A))
        gi = igg.gather_interior(A)
        # x periodic: 2*(6-2)=8 unique; y/z open: 2*(6-2)+2=10; C kept.
        assert gi.shape == (8, 10, 10, 3)
        # every component plane must match the rank-3 gather of the same
        # encoding offset by 1000*c
        base = igg.gather_interior(A[..., 0].copy())
        for c in range(3):
            np.testing.assert_array_equal(gi[..., c], base + 1000.0 * c)
        igg.finalize_global_grid()
