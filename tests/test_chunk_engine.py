"""The shared K-step chunk engine and the two NEW speed rungs it
generates: the HM3D trapezoid tier and the wave2d Mosaic/chunk tiers.

The existing diffusion/Stokes matrices (tests/test_trapezoid.py,
tests/test_stokes_trapezoid.py) pin the engine refactor bit-exact; this
file covers what is new — the hm3d chunk tier against its pure-XLA
composition truth on 8-device periodic/open/mixed interpret meshes, the
wave2d per-step Mosaic tier (interpret-capable, so the REAL kernel body
runs here) and 2-D chunk tier against the XLA composition, the
structured Admission verdicts on both ladders, and the `_vmem` budget
authority (fit_chunk_K + cap override) the engine dispatches through.
The compiled Mosaic realizations are TPU-only and pinned on hardware by
tests/test_mega_tpu.py.
"""

import numpy as np
import pytest

import igg
from igg.ops import _vmem


# ---------------------------------------------------------------------------
# _vmem: the single budget authority (satellite)
# ---------------------------------------------------------------------------

def test_fit_chunk_k_halving():
    # Walks kmax, kmax/2, ...; returns the first admissible; 0 when none.
    assert _vmem.fit_chunk_K(lambda K: K <= 5, 8) == 4
    assert _vmem.fit_chunk_K(lambda K: K == 8, 8) == 8
    assert _vmem.fit_chunk_K(lambda K: False, 8) == 0
    assert _vmem.fit_chunk_K(lambda K: True, 8, min_k=4) == 8
    assert _vmem.fit_chunk_K(lambda K: K < 4, 8, min_k=4) == 0
    # Admission objects work as predicates (truthy/falsy).
    from igg.degrade import Admission

    assert _vmem.fit_chunk_K(
        lambda K: Admission.yes() if K <= 4 else Admission.no("big"),
        16) == 4


def test_vmem_cap_override_round_trip():
    base_cap = _vmem.vmem_cap()
    base_budget = _vmem.chunk_budget()
    try:
        _vmem.set_cap_override(64 * 1024 * 1024)
        assert _vmem.vmem_cap() == 64 * 1024 * 1024
        assert _vmem.chunk_budget() == 64 * 1024 * 1024
        assert _vmem.vmem_limit(2 ** 30) == 64 * 1024 * 1024
    finally:
        _vmem.set_cap_override(None)
    assert _vmem.vmem_cap() == base_cap
    assert _vmem.chunk_budget() == base_budget


# ---------------------------------------------------------------------------
# HM3D trapezoid tier (generated from the engine)
# ---------------------------------------------------------------------------

def _hm3d_compare(mesh, periods, K, tol=2e-5):
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=mesh[0], dimy=mesh[1],
                         dimz=mesh[2], periodx=periods[0],
                         periody=periods[1], periodz=periods[2],
                         quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    n_inner = K + 1          # warm-up + one full chunk
    ref = hm3d.make_step(p, donate=False, n_inner=n_inner,
                         use_pallas=False)
    trap = hm3d.make_step(p, donate=False, n_inner=n_inner,
                          use_pallas=True, pallas_interpret=True,
                          trapezoid=True, K=K)
    r = ref(Pe, phi)
    t = trap(Pe, phi)
    assert igg.degrade.active().get("hm3d") == "hm3d.trapezoid"
    for name, a, b in zip(("Pe", "phi"), r, t):
        a, b = (np.asarray(v, np.float64) for v in (a, b))
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, (name, rel, mesh, periods)
    igg.finalize_global_grid()


def test_hm3d_chunk_ring_periodic():
    """(8,1,1) fully periodic: x extended by self/neighbor slabs, y/z
    in-window self-wrap."""
    _hm3d_compare((8, 1, 1), (1, 1, 1), K=4)


def test_hm3d_chunk_ring_open():
    """(8,1,1) all open — the reference-default boundary condition:
    'oext' x with BOTH fields' boundary planes frozen, frozen y/z."""
    _hm3d_compare((8, 1, 1), (0, 0, 0), K=4)


def test_hm3d_chunk_torus_mixed():
    """(2,2,2) mixed: open x/z around periodic extended y (K=8 — the
    y-extension sublane-tile gate)."""
    _hm3d_compare((2, 2, 2), (0, 1, 0), K=8)


def test_hm3d_chunk_single_device_frozen():
    """(1,1,1) all open: every dim 'frozen' — both fields' boundary
    planes re-frozen every step."""
    _hm3d_compare((1, 1, 1), (0, 0, 0), K=4)


def test_hm3d_chunk_with_remainder():
    """n_inner = warm-up + one K=4 chunk + 2 per-step remainder steps."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    ref = hm3d.make_step(p, donate=False, n_inner=7, use_pallas=False)
    trap = hm3d.make_step(p, donate=False, n_inner=7, use_pallas=True,
                          pallas_interpret=True, trapezoid=True, K=4)
    r = ref(Pe, phi)
    t = trap(Pe, phi)
    for name, a, b in zip(("Pe", "phi"), r, t):
        a, b = (np.asarray(v, np.float64) for v in (a, b))
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-5, (name, rel)


def test_hm3d_chunk_admission_matrix():
    """Structured Admission verdicts of the hm3d chunk gate."""
    from igg.ops.hm3d_trapezoid import (fit_hm3d_K,
                                        hm3d_trapezoid_supported)

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    s = (16, 16, 128)
    ok = hm3d_trapezoid_supported
    assert ok(grid, s, 4, 4, np.float32)
    adm = ok(grid, s, 4, 3, np.float32)          # no full chunk
    assert not adm and "chunk" in adm.reason
    adm = ok(grid, s, 1, 8, np.float32)          # K < 2
    assert not adm
    adm = ok(grid, s, 4, 4, np.float64)          # f32 only
    assert not adm and "float32" in adm.reason
    adm = ok(grid, s, 16, 16, np.float32)        # send slabs too deep
    assert not adm
    assert fit_hm3d_K(grid, s, 8, np.float32) == 8
    assert fit_hm3d_K(grid, s, 3, np.float32) == 0
    igg.finalize_global_grid()
    # overlap-3 grid: the per-step kernel's overlap-2 prerequisite fails
    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    grid = igg.get_global_grid()
    adm = ok(grid, s, 4, 4, np.float32)
    assert not adm and "overlaps" in adm.reason


def test_hm3d_trapezoid_true_raises_when_unsupported():
    """trapezoid=True is a real contract: requirement-string GridError
    when no K is admissible."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    step = hm3d.make_step(p, donate=False, n_inner=2, use_pallas=True,
                          pallas_interpret=True, trapezoid=True)
    with pytest.raises(igg.GridError, match="chunk tier"):
        step(Pe, phi)


# ---------------------------------------------------------------------------
# wave2d Mosaic per-step tier
# ---------------------------------------------------------------------------

def _wave_fields(p, dtype=np.float32, pre_steps=0):
    from igg.models import wave2d

    fields = wave2d.init_fields(p, dtype=dtype)
    if pre_steps:
        pre = wave2d.make_step(p, donate=False, n_inner=pre_steps,
                               use_pallas=False)
        fields = pre(*fields)
    return fields


@pytest.mark.parametrize("periods", [(1, 1), (0, 0)],
                         ids=["periodic", "open"])
def test_wave2d_mosaic_matches_xla(periods):
    """The fused per-step kernel (real kernel body, interpret mode) on
    the (4,2,1) 8-device mesh — periodic AND open (the tier's halo half
    is the exchange engine, so every boundary condition is served)."""
    from igg.models import wave2d

    igg.init_global_grid(8, 8, 1, periodx=periods[0], periody=periods[1],
                         quiet=True)
    p = wave2d.Params()
    fields = _wave_fields(p)
    ref = wave2d.make_step(p, donate=False, n_inner=5, use_pallas=False)
    pal = wave2d.make_step(p, donate=False, n_inner=5, use_pallas=True,
                           pallas_interpret=True, chunk=False)
    r = ref(*fields)
    o = pal(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.mosaic"
    for name, a, b in zip(("P", "Vx", "Vy"), r, o):
        a, b = (np.asarray(v, np.float64) for v in (a, b))
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 1e-5, (name, rel, periods)


def test_wave2d_xla_rung_serves_f64():
    """The fast tiers are f32-only: the f64 configuration (the historical
    test setup) rides the truth rung."""
    from igg.models import wave2d

    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    p = wave2d.Params()
    fields = _wave_fields(p, dtype=np.float64)
    step = wave2d.make_step(p, donate=False, use_pallas=True,
                            pallas_interpret=True)
    with pytest.raises(igg.GridError):
        step(*fields)     # use_pallas=True on f64 is a real refusal
    auto = wave2d.make_step(p, donate=False, use_pallas="auto",
                            pallas_interpret=True)
    auto(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.xla"


# ---------------------------------------------------------------------------
# wave2d 2-D chunk tier
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh,local", [((4, 2, 1), (16, 16)),
                                        ((1, 1, 1), (16, 16))],
                         ids=["mesh42", "selfwrap"])
def test_wave2d_chunk_matches_xla(mesh, local):
    """One warm-up + one K=4 chunk on periodic meshes, from an
    overlap-consistent model-evolved state, against the composition."""
    from igg.models import wave2d

    igg.init_global_grid(local[0], local[1], 1, dimx=mesh[0],
                         dimy=mesh[1], dimz=mesh[2],
                         periodx=1, periody=1, quiet=True)
    p = wave2d.Params()
    fields = _wave_fields(p, pre_steps=3)
    ref = wave2d.make_step(p, donate=False, n_inner=5, use_pallas=False)
    chk = wave2d.make_step(p, donate=False, n_inner=5, use_pallas=True,
                           pallas_interpret=True, chunk=True, K=4)
    r = ref(*fields)
    c = chk(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.chunk"
    for name, a, b in zip(("P", "Vx", "Vy"), r, c):
        a, b = (np.asarray(v, np.float64) for v in (a, b))
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-5, (name, rel, mesh)


def test_wave2d_chunk_refuses_open_with_structured_reason():
    """Open meshes are refused with a structured Admission naming the
    periodic-only contract (the per-step tiers serve them) — and the
    auto ladder falls to the mosaic rung there instead."""
    from igg.models import wave2d
    from igg.ops.wave2d_pallas import wave2d_chunk_supported

    igg.init_global_grid(16, 16, 1, quiet=True)   # all open
    grid = igg.get_global_grid()
    adm = wave2d_chunk_supported(grid, (16, 16), 4, 8, np.float32)
    assert not adm and "periodic" in adm.reason
    p = wave2d.Params()
    fields = _wave_fields(p)
    step = wave2d.make_step(p, donate=False, n_inner=5, use_pallas=True,
                            pallas_interpret=True, chunk="auto")
    step(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.mosaic"


def test_wave2d_chunk_admission_matrix():
    from igg.ops.wave2d_pallas import fit_wave2d_K, wave2d_chunk_supported

    igg.init_global_grid(16, 16, 1, periodx=1, periody=1, quiet=True)
    grid = igg.get_global_grid()
    s = (16, 16)
    ok = wave2d_chunk_supported
    assert ok(grid, s, 4, 4, np.float32)
    assert not ok(grid, s, 4, 3, np.float32)      # no full chunk
    assert not ok(grid, s, 1, 8, np.float32)      # K < 2
    assert not ok(grid, s, 8, 8, np.float32)      # 2K slabs too deep
    assert not ok(grid, s, 4, 4, np.float64)      # f32 only
    assert fit_wave2d_K(grid, s, 8, np.float32) == 4
    igg.finalize_global_grid()


def test_wave2d_chunk_true_raises_when_unsupported():
    from igg.models import wave2d

    igg.init_global_grid(16, 16, 1, periodx=1, periody=1, quiet=True)
    p = wave2d.Params()
    fields = _wave_fields(p)
    step = wave2d.make_step(p, donate=False, n_inner=2, use_pallas=True,
                            pallas_interpret=True, chunk=True)
    with pytest.raises(igg.GridError, match="chunk tier"):
        step(*fields)


# ---------------------------------------------------------------------------
# Verify-on-first-use guards the generated tiers (the miscompile story)
# ---------------------------------------------------------------------------

def test_corrupt_hm3d_chunk_tier_never_serves():
    """A chaos-corrupted hm3d.trapezoid output must be caught by
    verify-on-first-use and quarantined — the generated-tier safety
    contract: a miscompiled generated tier can never serve wrong
    physics."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    igg.degrade.reset()
    try:
        with igg.chaos.kernel_corrupt("hm3d.trapezoid", magnitude=1e3):
            step = hm3d.make_step(p, donate=False, n_inner=5,
                                  use_pallas=True, pallas_interpret=True,
                                  trapezoid="auto", verify="first_use")
            step(Pe, phi)
        q = igg.degrade.status()
        assert "hm3d.trapezoid" in q
        assert q["hm3d.trapezoid"].reason == "verify_mismatch"
        # Dispatch fell to the next healthy rung.
        assert igg.degrade.active().get("hm3d") in ("hm3d.mosaic",
                                                    "hm3d.xla")
    finally:
        igg.degrade.reset()


def test_use_pallas_false_pins_xla_past_the_chunk_tiers():
    """use_pallas=False must reach the truth rung even where the chunk
    tier would be admissible — the chunk tiers ride the fused kernels,
    so an explicit XLA pin outranks them (hm3d, wave2d, and stokes all
    share the gate)."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    step = hm3d.make_step(p, donate=False, n_inner=5, use_pallas=False,
                          pallas_interpret=True, trapezoid="auto")
    step(Pe, phi)
    assert igg.degrade.active().get("hm3d") == "hm3d.xla"
    igg.finalize_global_grid()

    from igg.models import wave2d

    igg.init_global_grid(16, 16, 1, periodx=1, periody=1, quiet=True)
    wp = wave2d.Params()
    fields = wave2d.init_fields(wp, dtype=np.float32)
    wstep = wave2d.make_step(wp, donate=False, n_inner=5,
                             use_pallas=False, pallas_interpret=True,
                             chunk="auto")
    wstep(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.xla"


# ---------------------------------------------------------------------------
# Streaming banded tier (round 18): the rung below the resident chunk
# tiers — K iterations over the 2K-extended block swept in x-row bands
# through a rolling VMEM window with HBM ping-pong, so admission needs
# only the band working set, not the whole block resident.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh,periods,K",
                         [((8, 1, 1), (1, 1, 1), 4),
                          ((8, 1, 1), (0, 0, 0), 4),
                          ((2, 2, 2), (0, 1, 0), 8)],
                         ids=["ring_periodic", "ring_open", "torus_mixed"])
def test_hm3d_banded_matches_xla(mesh, periods, K):
    """banded=True pins hm3d.banded past the (admissible) resident
    trapezoid tier; output matches the XLA composition on periodic,
    open, and mixed 8-device interpret meshes."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=mesh[0], dimy=mesh[1],
                         dimz=mesh[2], periodx=periods[0],
                         periody=periods[1], periodz=periods[2],
                         quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    n_inner = K + 1
    ref = hm3d.make_step(p, donate=False, n_inner=n_inner,
                         use_pallas=False)
    band = hm3d.make_step(p, donate=False, n_inner=n_inner,
                          pallas_interpret=True, banded=True, K=K, band=8)
    r = ref(Pe, phi)
    b = band(Pe, phi)
    assert igg.degrade.active().get("hm3d") == "hm3d.banded"
    for name, a, c in zip(("Pe", "phi"), r, b):
        a, c = (np.asarray(v, np.float64) for v in (a, c))
        rel = np.abs(a - c).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-5, (name, rel, mesh, periods)
    igg.finalize_global_grid()


def test_stokes_banded_matches_xla_staggered():
    """The staggered-shape family (Vx/Vy/Vz each extend one cell along
    their own axis) through the banded rung on the 8-device overlap-3
    ring."""
    from igg.models import stokes3d

    igg.init_global_grid(16, 16, 128, dimx=8, periodx=1, periody=1,
                         periodz=1, overlapx=3, overlapy=3, overlapz=3,
                         quiet=True)
    p = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
    P, Vx, Vy, Vz, Rho = stokes3d.init_fields(p, dtype=np.float32)
    ref = stokes3d.make_iteration(p, donate=False, n_inner=5,
                                  use_pallas=False)
    band = stokes3d.make_iteration(p, donate=False, n_inner=5,
                                   pallas_interpret=True, banded=True,
                                   K=4, band=8)
    r = ref(P, Vx, Vy, Vz, Rho)
    b = band(P, Vx, Vy, Vz, Rho)
    assert igg.degrade.active().get("stokes3d") == "stokes3d.banded"
    for name, a, c in zip(("P", "Vx", "Vy", "Vz"), r, b):
        a, c = (np.asarray(v, np.float64) for v in (a, c))
        rel = np.abs(a - c).max() / (np.abs(a).max() + 1e-30)
        # 5e-4, not the 2e-5 standard: pure f32 reassociation amplified
        # by the pseudo-transient Gauss-Seidel chain — the same compare
        # in f64 agrees to <=1.5e-13 (banded-vs-window order effect).
        assert rel < 5e-4, (name, rel)
    igg.finalize_global_grid()


def test_wave2d_banded_matches_xla():
    from igg.models import wave2d

    igg.init_global_grid(16, 16, 1, dimx=4, dimy=2, periodx=1, periody=1,
                         quiet=True)
    p = wave2d.Params()
    fields = _wave_fields(p, pre_steps=3)
    ref = wave2d.make_step(p, donate=False, n_inner=5, use_pallas=False)
    band = wave2d.make_step(p, donate=False, n_inner=5,
                            pallas_interpret=True, banded=True, K=4,
                            band=8)
    r = ref(*fields)
    b = band(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.banded"
    for name, a, c in zip(("P", "Vx", "Vy"), r, b):
        a, c = (np.asarray(v, np.float64) for v in (a, c))
        rel = np.abs(a - c).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-5, (name, rel)


def test_diffusion_banded_matches_xla():
    from igg.models import diffusion3d

    igg.init_global_grid(16, 16, 128, dimx=8, periodx=1, periody=1,
                         periodz=1, quiet=True)
    p = diffusion3d.Params(lx=4.0, ly=4.0, lz=4.0)
    T, Cp = diffusion3d.init_fields(p, dtype=np.float32)
    ref = diffusion3d.make_multi_step(5, p, donate=False,
                                      use_pallas=False)
    band = diffusion3d.make_multi_step(5, p, donate=False,
                                       pallas_interpret=True, banded=True,
                                       K=4, band=8)
    r = ref(T, Cp)
    b = band(T, Cp)
    assert igg.degrade.active().get("diffusion3d") == "diffusion3d.banded"
    a, c = (np.asarray(v, np.float64) for v in (r, b))
    rel = np.abs(a - c).max() / (np.abs(a).max() + 1e-30)
    assert rel < 2e-5, rel
    igg.finalize_global_grid()


def test_spec_banded_matches_xla():
    """The spec-lowered ladder serves `<name>.banded` too — a tier the
    frontend generates with zero family-specific banded code."""
    from igg import stencil
    from igg.models import wave2d

    igg.init_global_grid(16, 16, 1, dimx=4, dimy=2, periodx=1, periody=1,
                         quiet=True)
    p = wave2d.Params()
    spec = stencil.wave2d_spec()
    cf = stencil.wave2d_coeffs(p)
    fields = _wave_fields(p, pre_steps=3)
    ref = stencil.compile(spec, coeffs=cf, donate=False, n_inner=5,
                          use_pallas=False)
    band = stencil.compile(spec, coeffs=cf, donate=False, n_inner=5,
                           pallas_interpret=True, banded=True, K=4,
                           band=8)
    r = ref(*fields)
    b = band(*fields)
    assert igg.degrade.active().get(spec.name) == spec.name + ".banded"
    for name, a, c in zip(("P", "Vx", "Vy"), r, b):
        a, c = (np.asarray(v, np.float64) for v in (a, c))
        rel = np.abs(a - c).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-5, (name, rel)


def test_banded_admits_where_resident_fit_refuses_256cubed():
    """The tentpole's admission claim at the headline shape: 256^3 f32
    single-device, where the resident window's working set (202 MB)
    exceeds the VMEM budget so `fit_chunk_K` returns 0 — the banded
    rung's rolling window still fits and admits (K=4, B=8).  Pure host
    arithmetic; nothing is allocated."""
    from igg.ops.hm3d_trapezoid import (fit_hm3d_K, fit_hm3d_band,
                                        hm3d_banded_supported)
    from igg.ops.stokes_trapezoid import (fit_stokes_K, fit_stokes_band,
                                          stokes_banded_supported)

    s = (256, 256, 256)
    igg.init_global_grid(*s, dimx=1, dimy=1, dimz=1, periodx=1,
                         periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    assert fit_hm3d_K(grid, s, 8, np.float32, interpret=True) == 0
    adm = hm3d_banded_supported(grid, s, 4, 4, np.float32, B=8,
                                interpret=True)
    assert adm, adm.reason
    assert fit_hm3d_band(grid, s, 4, np.float32, interpret=True) == (4, 8)
    igg.finalize_global_grid()

    igg.init_global_grid(*s, dimx=1, dimy=1, dimz=1, periodx=1,
                         periody=1, periodz=1, overlapx=3, overlapy=3,
                         overlapz=3, quiet=True)
    grid = igg.get_global_grid()
    assert fit_stokes_K(grid, s, 8, np.float32, interpret=True) == 0
    adm = stokes_banded_supported(grid, s, 4, 4, np.float32, B=8,
                                  interpret=True)
    assert adm, adm.reason
    assert fit_stokes_band(grid, s, 4, np.float32,
                           interpret=True) == (4, 8)
    igg.finalize_global_grid()


def test_banded_serves_on_auto_ladder_when_resident_refused():
    """The auto ladder falls THROUGH the resident chunk tier to the
    banded rung when the VMEM budget refuses the resident window (the
    2 MB cap keeps the resident fit at 0 while the band fit still
    admits) — the serving half of the admission claim, provable at
    test shapes."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=8, periodx=1, periody=1,
                         periodz=1, quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    step = hm3d.make_step(p, donate=False, n_inner=5,
                          pallas_interpret=True)
    try:
        _vmem.set_cap_override(2 * 1024 * 1024)
        step(Pe, phi)
        assert igg.degrade.active().get("hm3d") == "hm3d.banded"
    finally:
        _vmem.set_cap_override(None)


def test_banded_true_raises_when_unsupported():
    """banded=True is a real contract: requirement-string GridError when
    no (K, B) is admissible (n_inner=2 leaves no room for a chunk)."""
    from igg.models import hm3d

    igg.init_global_grid(16, 16, 128, dimx=8, periodx=1, periody=1,
                         periodz=1, quiet=True)
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    step = hm3d.make_step(p, donate=False, n_inner=2,
                          pallas_interpret=True, banded=True)
    with pytest.raises(igg.GridError, match="banded"):
        step(Pe, phi)
    with pytest.raises(igg.GridError, match="banded"):
        hm3d.make_step(p, donate=False, n_inner=5, use_pallas=False,
                       banded=True)


def test_resolve_band_rules():
    """The shared (K, B) resolution: explicit pins hard-refuse, cached
    values fall back to the fit."""
    from igg.models._dispatch import resolve_band

    sup = lambda K, B: K == 4 and B == 8
    fit = lambda bands: (4, 8) if 8 in bands else None
    # Explicit admissible pair serves; inadmissible explicit pair is a
    # hard refusal (None), NOT a silent fallback.
    assert resolve_band(4, 8, False, sup, fit) == (4, 8)
    assert resolve_band(8, 8, False, sup, fit) is None
    assert resolve_band(4, 16, False, sup, fit) is None
    # Cache-sourced values fall back to the auto-fit instead.
    assert resolve_band(8, 8, True, sup, fit) == (4, 8)
    assert resolve_band(None, 16, True, sup, fit) == (4, 8)
    # No K: fit over the band space.
    assert resolve_band(None, None, False, sup, fit) == (4, 8)


def test_explicit_chunk_true_outranks_cached_xla_winner(tmp_path,
                                                        monkeypatch):
    """A cached '<family>.xla' winner must not turn an explicit
    trapezoid=True request into a spurious GridError."""
    from igg import autotune
    from igg.models import hm3d

    monkeypatch.setenv("IGG_TUNE_CACHE", str(tmp_path / "tune.json"))
    autotune.reset()
    try:
        igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                             periodx=1, periody=1, periodz=1, quiet=True)
        autotune.record_winner("hm3d", {"tier": "hm3d.xla", "K": None,
                                        "bx": None, "vmem_mb": None,
                                        "ms": 1.0})
        p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
        Pe, phi = hm3d.init_fields(p, dtype=np.float32)
        step = hm3d.make_step(p, donate=False, n_inner=5,
                              pallas_interpret=True, trapezoid=True,
                              tune="auto")
        step(Pe, phi)
        assert igg.degrade.active().get("hm3d") == "hm3d.trapezoid"
    finally:
        autotune.reset()
